"""Prefix-aware routing + the shared KV prefix tier: prefix identity
and matching, the template wire codec (every cache layout round-trips
through a real socket pair bit-identically; adversarial blobs are
request-scoped), the engine admission fast path (token-identical to
prefix-blind full prefill in every mode; a shipped template warms a
replica with ZERO prefix forwards), router placement (residency
preference, idle-slot tiebreak, ring degradation), the PREFIX wire
ops, and the deterministic bench-arm pins.

The two-REAL-process warm-ship acceptance pin lives at the bottom
(fixture: tests/fixtures/prefix_replica_fixture.py x2 — router + two
replicas, one warmed by a template ship).

Compile frugality: one tiny f32 config for everything except the
per-layout codec cases (single prefills, not serve loops).
"""

import json
import logging
import os
import socket
import subprocess
import sys
import threading
import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import transformer as T
from tony_tpu.models.decode import generate
from tony_tpu.models.serve import (ContinuousBatcher,
                                   SpeculativeContinuousBatcher)
from tony_tpu.runtime import metrics as M
from tony_tpu.serving import kvship
from tony_tpu.serving import protocol as P
from tony_tpu.serving.client import StreamingClient
from tony_tpu.serving.prefix import fingerprint, match_prefix
from tony_tpu.serving.router import ServingRouter
from tony_tpu.serving.server import ServingServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)          # for `import bench` (repo-root script)
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")

CFG = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.PRNGKey(0), CFG)


def _reference(params, prompt, max_new):
    out = generate(params, jnp.asarray(prompt, jnp.int32)[None], CFG,
                   max_new_tokens=max_new, rng=jax.random.PRNGKey(0),
                   temperature=0.0)
    return [int(t) for t in np.asarray(out.tokens[0, len(prompt):])]


def _prefix_and_suffixes(seed, prefix_len, suffix_lens, vocab=None):
    rs = np.random.RandomState(seed)
    v = vocab or CFG.vocab_size
    prefix = [int(t) for t in rs.randint(0, v, size=prefix_len)]
    return prefix, [[int(t) for t in rs.randint(0, v, size=n)]
                    for n in suffix_lens]


def _wait_resident(host, pid, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pid in host.resident_prefixes():
            return True
        time.sleep(0.02)
    return False


# ---------------------------------------------------------------------------
# Identity + matching (jax-free)
# ---------------------------------------------------------------------------
class TestPrefixIdentity:
    def test_fingerprint_is_content_derived(self):
        assert fingerprint([1, 2, 3]) == fingerprint([1, 2, 3])
        assert fingerprint([1, 2, 3]) != fingerprint([1, 2, 4])
        assert fingerprint([1, 2]) != fingerprint([1, 2, 0])
        assert len(fingerprint(list(range(100)))) == 16

    def test_match_prefix_longest_proper_boundary(self):
        catalog = {"a": [1, 2], "b": [1, 2, 3], "c": [9]}
        # longest wins
        assert match_prefix([1, 2, 3, 4], catalog) == "b"
        # a prompt that IS a catalog entry leaves no suffix: only the
        # shorter entry is a PROPER prefix
        assert match_prefix([1, 2, 3], catalog) == "a"
        assert match_prefix([1, 2], catalog) is None  # only improper
        assert match_prefix([2, 1, 3], catalog) is None
        assert match_prefix([], {}) is None


# ---------------------------------------------------------------------------
# Template codec: every layout round-trips through a real socket pair
# ---------------------------------------------------------------------------
class TestTemplateCodec:
    LAYOUTS = {
        "f32": dict(),
        "bf16": dict(dtype=jnp.bfloat16),
        "int8": dict(kv_cache_dtype="int8"),
        "window": dict(attn_window=8),
    }

    def _ship_blob(self, blob):
        """One real socket hop: sendall on one end, drain the other."""
        a, b = socket.socketpair()
        got = bytearray()

        def _drain():
            while len(got) < len(blob):
                chunk = b.recv(65536)
                if not chunk:
                    return
                got.extend(chunk)

        t = threading.Thread(target=_drain)
        t.start()
        try:
            a.sendall(blob)
            t.join(timeout=30)
        finally:
            a.close()
            b.close()
        return bytes(got)

    @pytest.mark.parametrize("layout", sorted(LAYOUTS))
    def test_socket_round_trip_installs_bit_identical(self, layout):
        """install on A -> pack -> REAL socket -> unpack -> install on
        B: B's resident template buffers are bit-identical to A's, for
        every template-capable cache layout (f32, bf16, int8+scales,
        sliding-window), and B ran ZERO prefill forwards to get there.
        int8 templates stay in STORAGE dtype on the wire (int8 values +
        f32 scales, like KV row shipments)."""
        cfg = CFG.scaled(**self.LAYOUTS[layout])
        p = T.init_params(jax.random.PRNGKey(0), cfg)
        prefix = [3, 1, 4, 1, 5, 9, 2, 6]
        src = ContinuousBatcher(p, cfg, batch=1, max_len=32)
        assert src.install_prefix("sys", prefix)
        blob = src.export_prefix_blob("sys")

        meta, bufs = kvship.unpack_template(self._ship_blob(blob))
        dst = ContinuousBatcher(p, cfg, batch=1, max_len=32)
        assert dst.install_prefix_template(meta, bufs) == "sys"
        assert dst.prefill_forward_tokens == 0

        a = src._prefix_store["sys"].template
        b = dst._prefix_store["sys"].template
        assert set(a) == set(b)
        for name in a:
            na, nb = np.asarray(a[name]), np.asarray(b[name])
            assert na.dtype == nb.dtype, name
            assert na.tobytes() == nb.tobytes(), name
        if layout == "int8":
            assert any(np.asarray(v).dtype == np.int8
                       for v in bufs.values())

    def test_truncated_and_mistagged_blobs_are_protocol_errors(self,
                                                               params):
        src = ContinuousBatcher(params, CFG, batch=1, max_len=32)
        src.install_prefix("sys", [1, 2, 3, 4])
        blob = src.export_prefix_blob("sys")
        for cut in (1, 10, len(blob) // 2, len(blob) - 1):
            with pytest.raises(P.ProtocolError):
                kvship.unpack_template(blob[:cut])
        # a KV ROW shipment routed onto the template lane is refused by
        # its kind tag, not silently misread
        key = np.zeros(2, np.uint32)
        row_blob = kvship.pack_shipment(
            kvship.pack_kv_meta(1, 4, 3, key, rng_off=0),
            {"k": np.zeros((2, 1, 3, 1, 4), np.float32)})
        with pytest.raises(P.ProtocolError, match="not a prefix"):
            kvship.unpack_template(row_blob)

    def test_wrong_vocab_and_wrong_layers_rejected_at_install(self,
                                                              params):
        """A template from a differently-shaped model is a
        request-scoped ValueError at install — never garbage K/V
        discovered mid-serve, never engine death."""
        batcher = ContinuousBatcher(params, CFG, batch=1, max_len=32)
        src = ContinuousBatcher(params, CFG, batch=1, max_len=32)
        src.install_prefix("sys", [1, 2, 3])
        meta, bufs = kvship.unpack_template(src.export_prefix_blob("sys"))

        wrong_vocab = dict(meta, vocab=CFG.vocab_size + 1)
        with pytest.raises(ValueError, match="vocab"):
            batcher.install_prefix_template(wrong_vocab, bufs)

        lcfg = CFG.scaled(n_layers=1)
        lsrc = ContinuousBatcher(
            T.init_params(jax.random.PRNGKey(0), lcfg), lcfg,
            batch=1, max_len=32)
        lsrc.install_prefix("sys", [1, 2, 3])
        lmeta, lbufs = kvship.unpack_template(
            lsrc.export_prefix_blob("sys"))
        with pytest.raises(ValueError, match="layer"):
            batcher.install_prefix_template(lmeta, lbufs)

        # the batcher is unharmed either way: nothing resident, serving
        # works
        assert batcher.resident_prefixes() == []
        assert batcher.serve([[5, 6, 7]], 3) == [
            _reference(params, [5, 6, 7], 3)]

    def test_garbage_on_the_live_lane_costs_only_itself(self, params):
        """The REAL install path: garbage and a wrong-vocab template
        shipped onto a running server's prefix lane are dropped by the
        install thread — the replica keeps serving and stays unwarmed,
        and a subsequent GOOD ship still lands."""
        from tony_tpu.channels.channel import ChannelSender

        server = ServingServer(
            ContinuousBatcher(params, CFG, batch=1, max_len=32),
            registry=M.MetricsRegistry())
        try:
            server.start()
            target = f"127.0.0.1:{server.prefix_port}"
            s = ChannelSender(target, "prefix", window=2,
                              registry=M.MetricsRegistry())
            try:
                s.send_bytes(b"not a template at all", sync=True,
                             timeout=20)
                wrong = kvship.pack_template(
                    "sys", [1, 2, 3],
                    {"k": np.zeros((2, 1, 3, 1, 4), np.float32)},
                    vocab=CFG.vocab_size + 7)
                s.send_bytes(wrong, sync=True, timeout=20)
            finally:
                s.close(drain=False)
            time.sleep(0.3)
            assert server.resident_prefixes() == []

            src = ContinuousBatcher(params, CFG, batch=1, max_len=32)
            src.install_prefix("sys", [1, 2, 3, 4])
            s2 = ChannelSender(target, "prefix", window=2,
                               registry=M.MetricsRegistry())
            try:
                s2.send_bytes(src.export_prefix_blob("sys"), sync=True,
                              timeout=20)
            finally:
                s2.close(drain=False)
            assert _wait_resident(server, "sys"), \
                "good ship did not land after garbage"
            with StreamingClient("127.0.0.1", server.port) as c:
                out, reason = c.result(c.submit([5, 6, 7], 3))
            assert reason in ("eos", "budget")
            assert out == _reference(params, [5, 6, 7], 3)
        finally:
            server.kill()


# ---------------------------------------------------------------------------
# Engine admission fast path: token-identical, fewer forward tokens
# ---------------------------------------------------------------------------
class TestEngineFastPath:
    def _serve(self, params, prompts, budgets, install=None, **kw):
        b = ContinuousBatcher(params, CFG, batch=2, max_len=64, chunk=3,
                              **kw)
        if install is not None:
            assert b.install_prefix(fingerprint(install), install)
        outs = b.serve(prompts, budgets)
        return outs, b

    @pytest.mark.parametrize("mode", ["greedy", "sampled"])
    def test_token_identity_vs_prefix_blind(self, params, mode):
        """Prefix-hit admissions (auto-matched — no id anywhere) are
        token-identical to prefix-blind full prefill, greedy AND
        sampled, across a mixed workload (hits + a non-matching
        prompt)."""
        kw = (dict(temperature=0.9, top_k=12, top_p=0.95, seed=11)
              if mode == "sampled" else {})
        prefix, suffixes = _prefix_and_suffixes(3, 17, (4, 2, 6, 3))
        prompts = [prefix + s for s in suffixes]
        prompts.insert(2, [7] * 9)          # prefix-blind bystander
        budgets = [5, 7, 4, 6, 5]
        blind, _ = self._serve(params, prompts, budgets, **kw)
        aware, b = self._serve(params, prompts, budgets, install=prefix,
                               **kw)
        assert aware == blind
        assert b.prefix_admits == 4
        assert b.prefix_copied_tokens == 4 * len(prefix)
        # install cost (one prefill) + suffixes + the bystander — never
        # the hits' prefix positions
        assert b.prefill_forward_tokens == (
            len(prefix) + sum(len(s) for s in suffixes) + 9)

    def test_speculative_token_identity(self, params):
        prefix, suffixes = _prefix_and_suffixes(5, 11, (3, 5, 2))
        prompts = [prefix + s for s in suffixes]
        budgets = [6, 4, 7]

        def run(install):
            b = SpeculativeContinuousBatcher(
                params, CFG, params, CFG, batch=2, max_len=64,
                num_speculative=3, chunk=2)
            if install:
                assert b.install_prefix("sys", prefix)
            return b.serve(prompts, budgets), b

        blind, _ = run(False)
        aware, b = run(True)
        assert aware == blind
        assert b.prefix_admits == 3
        # the draft template was computed at install (entry hook), so
        # draft-side admission never re-prefilled the prefix either
        assert b._prefix_store["sys"].draft_template is not None

    def test_shipped_template_serves_with_zero_prefix_forwards(self,
                                                               params):
        """The warm replica's whole point: install from a SHIPPED
        template, serve a prefix-heavy workload, and the lifetime
        forward-token count is suffixes only."""
        prefix, suffixes = _prefix_and_suffixes(9, 21, (3, 4, 2, 5))
        src = ContinuousBatcher(params, CFG, batch=2, max_len=64)
        src.install_prefix("sys", prefix)
        meta, bufs = kvship.unpack_template(src.export_prefix_blob("sys"))

        warm = ContinuousBatcher(params, CFG, batch=2, max_len=64,
                                 chunk=3)
        warm.install_prefix_template(meta, bufs)
        prompts = [prefix + s for s in suffixes]
        blind = ContinuousBatcher(params, CFG, batch=2, max_len=64,
                                  chunk=3).serve(prompts, 5)
        assert warm.serve(prompts, 5) == blind
        assert warm.prefill_forward_tokens == sum(
            len(s) for s in suffixes)
        assert warm.prefix_admits == len(suffixes)

    def test_explicit_id_and_wrong_id_are_both_safe(self, params):
        """submit(prefix_id=) takes the named entry when the prompt
        really continues it; a wrong/unknown id falls back (tokenized
        match, then full prefill) — outputs identical in every case."""
        from tony_tpu.models.serve import ServeEngine

        prefix, (sfx,) = _prefix_and_suffixes(13, 9, (4,))
        prompt = prefix + sfx
        ref = _reference(params, prompt, 5)

        for pid in ("sys", "no-such-prefix", None):
            b = ContinuousBatcher(params, CFG, batch=1, max_len=64,
                                  chunk=3)
            assert b.install_prefix("sys", prefix)
            outs = {}
            eng = ServeEngine(
                b, on_delta=lambda r, t: outs.setdefault(r, []).extend(t),
                on_retired=lambda r, reason, n, final:
                    outs.setdefault(r, []).extend(final),
                registry=M.MetricsRegistry())
            eng.submit("r1", prompt, 5, prefix_id=pid)
            th = threading.Thread(target=eng.run)
            th.start()
            eng.drain()
            th.join(timeout=60)
            assert outs["r1"] == ref, pid
            assert b.prefix_admits == 1, pid    # matched under any id

    def test_prompt_equal_to_prefix_is_not_a_hit(self, params):
        """A prompt that IS the prefix leaves no suffix to run — it
        must full-prefill (proper-prefix contract), same tokens."""
        prefix, _ = _prefix_and_suffixes(15, 12, ())
        b = ContinuousBatcher(params, CFG, batch=1, max_len=64, chunk=3)
        assert b.install_prefix("sys", prefix)
        assert b.serve([prefix], 4) == [_reference(params, prefix, 4)]
        assert b.prefix_admits == 0

    def test_install_validation(self, params):
        b = ContinuousBatcher(params, CFG, batch=1, max_len=16)
        with pytest.raises(ValueError, match="non-empty"):
            b.install_prefix("x", [])
        with pytest.raises(ValueError, match="no room"):
            b.install_prefix("x", list(range(15)))
        legacy = ContinuousBatcher(params, CFG, batch=1, max_len=32,
                                   shared_prefix=[1, 2, 3])
        with pytest.raises(ValueError, match="shared_prefix"):
            legacy.install_prefix("x", [4, 5])
        hit_overflow = ContinuousBatcher(params, CFG, batch=1,
                                         max_len=16)
        assert hit_overflow.install_prefix("s", list(range(10)))
        from tony_tpu.models.serve import ServeEngine
        eng = ServeEngine(hit_overflow, on_delta=lambda r, t: None,
                          on_retired=lambda r, reason, n, final: None,
                          registry=M.MetricsRegistry())
        with pytest.raises(ValueError, match="exceeds"):
            eng.submit("r", list(range(10)) + [1, 2], 8)


# ---------------------------------------------------------------------------
# Ring caches degrade prefix-blind (warning, never an error)
# ---------------------------------------------------------------------------
class TestRingDegrade:
    RING = dict(attn_window=8, kv_cache_capacity=8)

    def test_batcher_degrades_with_one_warning(self, caplog):
        cfg = CFG.scaled(**self.RING)
        p = T.init_params(jax.random.PRNGKey(0), cfg)
        b = ContinuousBatcher(p, cfg, batch=1, max_len=32)
        with caplog.at_level(logging.WARNING, "tony_tpu.models.serve"):
            assert b.install_prefix("sys", [1, 2, 3]) is False
            assert b.install_prefix("sys2", [4, 5]) is False
        warns = [r for r in caplog.records
                 if "ring" in r.getMessage()]
        assert len(warns) == 1                  # once, not per install
        assert b.resident_prefixes() == []
        # prefix-id admissions still serve, prefix-blind
        ref = generate(p, jnp.asarray([5, 6, 7], jnp.int32)[None], cfg,
                       max_new_tokens=3, rng=jax.random.PRNGKey(0),
                       temperature=0.0)
        assert b.serve([[5, 6, 7]], 3) == [
            [int(t) for t in np.asarray(ref.tokens[0, 3:])]]

    def test_router_places_on_ring_replicas_prefix_blind(self, caplog):
        """A ring replica advertises `ring`; the router warns ONCE,
        keeps placing on it (miss-counted), and the session serves."""
        cfg = CFG.scaled(**self.RING)
        p = T.init_params(jax.random.PRNGKey(0), cfg)
        server = ServingServer(
            ContinuousBatcher(p, cfg, batch=2, max_len=32),
            registry=M.MetricsRegistry())
        reg = M.MetricsRegistry()
        router = None
        try:
            addr = f"127.0.0.1:{server.start()}"
            router = ServingRouter([addr], registry=reg,
                                   health_interval_s=0.2)
            prefix = [1, 2, 3, 4]
            router.register_prefix(prefix, prefix_id="sys")
            with caplog.at_level(logging.WARNING,
                                 "tony_tpu.serving.router"):
                router.start()
            assert sum("ring" in r.getMessage()
                       for r in caplog.records) == 1
            with StreamingClient("127.0.0.1", router.port) as c:
                out, reason = c.result(c.submit(prefix + [9, 9], 3))
            assert reason in ("eos", "budget") and len(out) == 3
            assert reg.counter(
                "tony_router_prefix_misses_total").value == 1
            assert reg.counter(
                "tony_router_prefix_hits_total").value == 0
        finally:
            if router is not None:
                router.stop()
            server.kill()


# ---------------------------------------------------------------------------
# Router placement: residency preference + idle-slot tiebreak
# ---------------------------------------------------------------------------
def _fake_link(load, idle, assigned=0, prefixes=(), alive=True,
               role="engine", addr="x"):
    return types.SimpleNamespace(
        alive=alive, role=role, reported_load=load, idle_slots=idle,
        assigned=assigned, prefixes=set(prefixes), addr=addr,
        draining=False, weights_version=None)


class TestRouterPlacement:
    def _router(self):
        # never started: placement is exercised directly on fake links
        return ServingRouter(["127.0.0.1:1"],
                             registry=M.MetricsRegistry())

    def test_idle_slot_tiebreak_ordering_pinned(self):
        """At EQUAL queue depths the link with more idle decode slots
        wins; load still dominates idle; assigned breaks the final
        tie. First-seen no longer wins."""
        r = self._router()
        busy = _fake_link(load=1, idle=4, addr="busy")
        few_idle = _fake_link(load=0, idle=1, addr="few")
        many_idle = _fake_link(load=0, idle=3, addr="many")
        r._links = [busy, few_idle, many_idle]
        assert r._pick_link() is many_idle
        # load dominates: a lower-load link beats a higher-idle one
        busy.reported_load = 0
        busy.idle_slots = 9
        assert r._pick_link() is busy
        # full tie -> fewest router-side assignments
        r._links = [_fake_link(0, 2, assigned=3, addr="a"),
                    _fake_link(0, 2, assigned=1, addr="b")]
        assert r._pick_link().addr == "b"

    def test_residency_restricts_then_falls_back(self):
        """prefer_prefix narrows the pool to resident replicas even
        when a non-resident one is less loaded; with NO resident
        replica the full pool serves (cold fleet never errors)."""
        r = self._router()
        cold = _fake_link(load=0, idle=4, addr="cold")
        warm = _fake_link(load=2, idle=1, prefixes={"sys"}, addr="warm")
        r._links = [cold, warm]
        assert r._pick_link(prefer_prefix="sys") is warm
        assert r._pick_link(prefer_prefix="nope") is cold
        assert r._pick_link() is cold

    def test_exclude_accepts_a_set_and_draining_fences(self):
        """``exclude`` is a SET (a migration storm / multi-replica
        failure excludes several links at once); an exhausted pool
        returns None; a draining link never takes a placement until
        undrained."""
        r = self._router()
        a = _fake_link(0, 4, addr="a")
        b = _fake_link(0, 3, addr="b")
        c = _fake_link(0, 2, addr="c")
        r._links = [a, b, c]
        assert r._pick_link(exclude=(a, b)) is c
        assert r._pick_link(exclude=[a]) in (b, c)
        assert r._pick_link(exclude=(a, b, c)) is None
        b.draining = True
        assert r._pick_link(exclude=(a,)) is c
        b.draining = False
        assert r._pick_link(exclude=(a, c)) is b

    def test_prefer_version_restricts_then_falls_back(self):
        """A version-pinned session stays on its weights generation
        while ANY same-version replica survives — even a busier one;
        with the generation gone, continuity beats pinning and the
        full pool serves."""
        r = self._router()
        v1 = _fake_link(load=2, idle=1, addr="v1")
        v1.weights_version = "v1"
        v2 = _fake_link(load=0, idle=4, addr="v2")
        v2.weights_version = "v2"
        r._links = [v1, v2]
        assert r._pick_link(prefer_version="v1") is v1
        assert r._pick_link(prefer_version="v2") is v2
        assert r._pick_link(prefer_version="v3") is v2
        assert r._pick_link() is v2

    def test_sessions_land_on_the_resident_replica(self, params):
        """In-process fleet: A resident, B cold — every prefix session
        places on A (hits counted, residency gauge = 1) while a
        non-prefix session still balances by load."""
        servers = [ServingServer(
            ContinuousBatcher(params, CFG, batch=2, max_len=64,
                              chunk=3),
            registry=M.MetricsRegistry()) for _ in range(2)]
        reg = M.MetricsRegistry()
        router = None
        prefix, suffixes = _prefix_and_suffixes(21, 13, (3, 4, 2))
        try:
            addrs = [f"127.0.0.1:{s.start()}" for s in servers]
            assert servers[0].install_prefix(prefix,
                                             prefix_id="sys") == "sys"
            router = ServingRouter(addrs, registry=reg,
                                   health_interval_s=0.2)
            router.register_prefix(prefix, prefix_id="sys")
            router.start()
            with StreamingClient("127.0.0.1", router.port) as c:
                rids = [c.submit(prefix + s, 4) for s in suffixes]
                for r in rids:
                    out, reason = c.result(r, timeout=120)
                    assert reason in ("eos", "budget") and len(out) == 4
            assert reg.counter(
                "tony_router_prefix_hits_total").value == len(suffixes)
            assert reg.counter(
                "tony_router_prefix_misses_total").value == 0
            assert reg.gauge("tony_router_prefix_resident_replicas",
                             prefix="sys").value == 1
            st = router.stats()
            assert st["replicas"][addrs[0]]["prefixes"] == ["sys"]
            assert st["replicas"][addrs[1]]["prefixes"] == []
            # every prefix session went to the resident replica
            with StreamingClient("127.0.0.1", servers[0].port) as ca:
                assert ca.stats()["prefix_admits"] == len(suffixes)
        finally:
            if router is not None:
                router.stop()
            for s in servers:
                s.kill()


# ---------------------------------------------------------------------------
# PREFIX wire ops + the in-process warm-ship composition
# ---------------------------------------------------------------------------
class TestPrefixOps:
    def test_install_publish_list_and_bad_ops(self, params):
        """The full wire surface against real servers: install on A
        over PREFIX frames, publish A->B over B's template lane, list
        shows residency on both; bad ops are request-scoped (the
        connection keeps working)."""
        servers = [ServingServer(
            ContinuousBatcher(params, CFG, batch=1, max_len=32),
            registry=M.MetricsRegistry()) for _ in range(2)]
        try:
            for s in servers:
                s.start()
            with StreamingClient("127.0.0.1", servers[0].port) as ca, \
                    StreamingClient("127.0.0.1", servers[1].port) as cb:
                lane_b = cb.hello.get("prefix_port")
                assert lane_b == servers[1].prefix_port
                assert ca.hello.get("prefixes") == []

                res = ca.prefix_op("install", tokens=[1, 2, 3, 4],
                                   id="sys")
                assert res["ok"] and res["id"] == "sys"
                assert res["resident"] == ["sys"]

                # request-scoped failures, same connection
                assert not ca.prefix_op("install", tokens=[])["ok"]
                assert not ca.prefix_op("install",
                                        tokens=["nan"])["ok"]
                assert not ca.prefix_op("publish", id="ghost",
                                        target="127.0.0.1:1")["ok"]
                assert not ca.prefix_op("frobnicate")["ok"]

                res = ca.prefix_op("publish", id="sys",
                                   target=f"127.0.0.1:{lane_b}")
                assert res["ok"] and res["bytes"] > 0
                assert _wait_resident(servers[1], "sys")
                assert cb.prefix_op("list")["resident"] == ["sys"]
                # B's STATS now advertises it (router residency source)
                assert cb.stats()["prefixes"] == ["sys"]
        finally:
            for s in servers:
                s.kill()

    def test_router_register_and_list_ops(self, params):
        server = ServingServer(
            ContinuousBatcher(params, CFG, batch=1, max_len=32),
            registry=M.MetricsRegistry())
        router = None
        try:
            addr = f"127.0.0.1:{server.start()}"
            router = ServingRouter([addr],
                                   registry=M.MetricsRegistry(),
                                   health_interval_s=0.2)
            router.start()
            with StreamingClient("127.0.0.1", router.port) as c:
                res = c.prefix_op("register", tokens=[1, 2, 3])
                assert res["ok"]
                pid = res["id"]
                assert pid == fingerprint([1, 2, 3])
                listed = c.prefix_op("list")
                assert listed["catalog"] == [pid]
                assert addr in listed["resident"]
                assert not c.prefix_op("register", tokens=[])["ok"]
                assert not c.prefix_op("install", tokens=[1])["ok"]
                # the connection survived every failure
                assert c.prefix_op("list")["ok"]
        finally:
            if router is not None:
                router.stop()
            server.kill()

    def test_metrics_plane_sees_installs_and_ships(self, params):
        rega, regb = M.MetricsRegistry(), M.MetricsRegistry()
        a = ServingServer(ContinuousBatcher(params, CFG, batch=1,
                                            max_len=32), registry=rega)
        b = ServingServer(ContinuousBatcher(params, CFG, batch=1,
                                            max_len=32), registry=regb)
        try:
            a.start()
            b.start()
            pid = a.install_prefix([1, 2, 3, 4], prefix_id="sys")
            n = a.publish_prefix(pid, f"127.0.0.1:{b.prefix_port}")
            assert _wait_resident(b, "sys")
            assert rega.counter("tony_prefix_ships_total").value == 1
            assert rega.counter(
                "tony_prefix_ship_bytes_total").value == n
            assert regb.counter(
                "tony_prefix_installs_total").value == 1
        finally:
            a.kill()
            b.kill()


# ---------------------------------------------------------------------------
# Disaggregation composes: the prefill tier takes the fast path
# ---------------------------------------------------------------------------
class TestDisaggComposition:
    def test_prefill_tier_prefix_hits_are_token_identical(self, params):
        """A prefill tier with a resident prefix runs suffix-only waves
        (forward-token counters prove it) and the disaggregated outputs
        stay token-identical to the colocated reference."""
        from tony_tpu.serving.disagg import DecodeServer, PrefillServer

        prefix, suffixes = _prefix_and_suffixes(31, 15, (3, 5, 2, 4))
        prompts = [prefix + s for s in suffixes]
        ref = ContinuousBatcher(params, CFG, batch=2, max_len=64,
                                chunk=3, seed=0).serve(prompts, 5)

        regp = M.MetricsRegistry()
        pre = PrefillServer(params, CFG, max_len=64, max_batch=2,
                            seed=0, registry=regp)
        dec = DecodeServer(ContinuousBatcher(params, CFG, batch=2,
                                             max_len=64, chunk=3,
                                             seed=0),
                           registry=M.MetricsRegistry())
        router = None
        try:
            pre.start()
            dec.start()
            assert pre.install_prefix(prefix, prefix_id="sys") == "sys"
            router = ServingRouter(
                [f"127.0.0.1:{pre.port}"],
                decode_replicas=[f"127.0.0.1:{dec.port}"],
                registry=M.MetricsRegistry(), health_interval_s=0.2)
            router.register_prefix(prefix, prefix_id="sys")
            router.start()
            with StreamingClient("127.0.0.1", router.port) as c:
                rids = [c.submit(p, 5) for p in prompts]
                outs = [c.result(r, timeout=120)[0] for r in rids]
            assert outs == ref
            assert regp.counter(
                "tony_prefill_forward_tokens_total").value == sum(
                    len(s) for s in suffixes)
            assert regp.counter(
                "tony_prefill_prefix_tokens_total").value == len(
                    prefix) * len(suffixes)
        finally:
            if router is not None:
                router.stop()
            pre.stop()
            dec.stop()


# ---------------------------------------------------------------------------
# Bench-arm pins (deterministic tier-1 + latency-realistic @slow)
# ---------------------------------------------------------------------------
class TestPrefixBenchArm:
    def test_ttft_and_flops_pins(self):
        """The tentpole acceptance, deterministically: at 8x reuse of
        one shared prefix across a 2-replica fleet (one computed the
        prefix, one warmed in ONE template ship — zero prefix forwards
        on it, asserted inside the arm), prefix-aware placement wins
        TTFT >= 2x, cuts prefill forward tokens >= 2x, places every
        prefix session on a resident replica, and stays
        token-identical to the prefix-blind fleet (asserted inside
        the arm)."""
        import bench

        res = bench._prefix_arm()
        assert res["serving_prefix_ttft_vs_blind"] >= 2.0, res
        assert res["serving_prefix_forward_vs_blind"] >= 2.0, res
        assert res["serving_prefix_hit_rate"] == 1.0, res
        assert res["serving_prefix_ship_bytes"] > 0, res
        assert res["serving_prefix_forward_tokens_aware"] > 0, res


@pytest.mark.slow
class TestPrefixBenchRealistic:
    def test_ttft_contrast_survives_wan_latency(self):
        """Latency-realistic variant: the client path rides a
        LatencyProxy WAN hop. The TTFT win comes from admission
        compute, not the link — the contrast must hold."""
        import bench

        res = bench._prefix_arm(one_way_s=0.02)
        assert res["serving_prefix_ttft_vs_blind"] >= 1.5, res


# ---------------------------------------------------------------------------
# Two REAL processes: warm-ship + token-identity acceptance pin
# ---------------------------------------------------------------------------
@pytest.mark.e2e
def test_warm_ship_token_identity_across_real_processes(tmp_path,
                                                        params):
    """Router + two real replica processes, replica B warmed by ONE
    template ship from replica A: prefix-aware serving is
    token-identical to the same fleet serving prefix-blind, greedy AND
    sampled, every placement is a hit, and B's engines ran ZERO prefix
    forwards (stats-pinned: forward tokens == suffix tokens of its
    admissions). Everything that could diverge — params init, template
    pack/unpack, the channel lane, residency advertisement, placement
    — crosses real process boundaries here."""
    port_files = [tmp_path / "replica-a.json", tmp_path / "replica-b.json"]
    done = tmp_path / "done"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable,
         os.path.join(FIXTURES, "prefix_replica_fixture.py"),
         "--port_file", str(pf), "--done_file", str(done)],
        env=env, cwd=str(tmp_path)) for pf in port_files]
    routers = []
    prefix, suffixes = _prefix_and_suffixes(41, 19, (4, 2, 5, 3, 4, 2))
    prompts = [prefix + s for s in suffixes]
    try:
        deadline = time.time() + 180
        while time.time() < deadline and not all(
                pf.exists() for pf in port_files):
            assert all(p.poll() is None for p in procs), \
                "a replica process died before binding"
            time.sleep(0.2)
        assert all(pf.exists() for pf in port_files), \
            "replica port files never appeared"
        pa, pb = [json.loads(pf.read_text()) for pf in port_files]

        # warm B's "aware" servers from A's over the template lane —
        # the only prefix compute in the whole fleet is A's two
        # installs (one per mode)
        for mode in ("greedy", "sampled"):
            with StreamingClient(
                    "127.0.0.1", pa[f"aware_{mode}"]["port"]) as ca:
                res = ca.prefix_op("install", tokens=prefix, id="sys",
                                   timeout=180)
                assert res["ok"], res
                res = ca.prefix_op(
                    "publish", id="sys",
                    target="127.0.0.1:"
                           f"{pb[f'aware_{mode}']['prefix_port']}",
                    timeout=180)
                assert res["ok"], res
            with StreamingClient(
                    "127.0.0.1", pb[f"aware_{mode}"]["port"]) as cb:
                deadline = time.time() + 60
                while time.time() < deadline:
                    if cb.prefix_op("list")["resident"] == ["sys"]:
                        break
                    time.sleep(0.1)
                assert cb.prefix_op("list")["resident"] == ["sys"], \
                    f"{mode}: template ship never landed on B"

        def run_fleet(pass_name, mode, aware):
            reg = M.MetricsRegistry()
            router = ServingRouter(
                [f"127.0.0.1:{pa[f'{pass_name}_{mode}']['port']}",
                 f"127.0.0.1:{pb[f'{pass_name}_{mode}']['port']}"],
                registry=reg, health_interval_s=5.0)
            routers.append(router)
            if aware:
                router.register_prefix(prefix, prefix_id="sys")
            router.start()
            with StreamingClient("127.0.0.1", router.port) as c:
                rids = [c.submit(p, 5) for p in prompts]
                outs = [c.result(r, timeout=180)[0] for r in rids]
            # gauges reflect LIVE links — read before stop tears them
            resident = reg.gauge("tony_router_prefix_resident_replicas",
                                 prefix="sys").value if aware else 0
            router.stop()
            return outs, reg, resident

        for mode in ("greedy", "sampled"):
            blind, _, _ = run_fleet("blind", mode, aware=False)
            aware, reg, resident = run_fleet("aware", mode, aware=True)
            assert aware == blind, mode
            if mode == "greedy":
                assert blind == [_reference(params, p, 5)
                                 for p in prompts]
            assert reg.counter(
                "tony_router_prefix_hits_total").value == len(prompts), \
                mode
            assert reg.counter(
                "tony_router_prefix_misses_total").value == 0, mode
            assert resident == 2, mode
            # the warmed replica ran ZERO prefix forwards, ever: its
            # lifetime forward tokens are exactly its admissions'
            # suffixes
            with StreamingClient(
                    "127.0.0.1", pb[f"aware_{mode}"]["port"]) as cb:
                st = cb.stats()
            assert st["prefix_admits"] > 0, \
                f"{mode}: warmed replica B never got a session"
            assert st["prefix_tokens"] == len(prefix) * \
                st["prefix_admits"], mode
            # suffixes are <= 5 tokens, the prefix is 19: even ONE
            # prefix forward on B would blow this bound
            assert st["prefill_tokens"] <= max(
                len(s) for s in suffixes) * st["prefix_admits"], mode
    finally:
        done.write_text("done")
        for router in routers:
            try:
                router.stop()
            except Exception:
                pass
        for p in procs:
            try:
                p.wait(timeout=90)
            except subprocess.TimeoutExpired:
                p.kill()
    assert all(p.returncode == 0 for p in procs), \
        [p.returncode for p in procs]
