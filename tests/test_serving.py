"""Streaming serving data plane: open-loop engine semantics, TONYS1
protocol codec + robustness, server/client end-to-end, router
placement + failover, and the streamed-vs-request/response bench pins.

Compile frugality: everything here shares ONE tiny config and a small
set of (batch, max_len, chunk) shapes, so the module pays a handful of
compiled serving programs, not one per test.
"""

import os
import queue as queue_mod
import socket
import struct
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import transformer as T
from tony_tpu.models.decode import generate
from tony_tpu.models.serve import ContinuousBatcher, ServeEngine
from tony_tpu.runtime import metrics as M
from tony_tpu.serving import protocol as P
from tony_tpu.serving.client import ServingConnectionError, StreamingClient
from tony_tpu.serving.netem import LatencyProxy
from tony_tpu.serving.router import ServingRouter
from tony_tpu.serving.server import ServingServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)          # for `import bench` (repo-root script)

CFG = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.PRNGKey(0), CFG)


def _reference(params, prompt, max_new):
    out = generate(params, jnp.asarray(prompt, jnp.int32)[None], CFG,
                   max_new_tokens=max_new, rng=jax.random.PRNGKey(0),
                   temperature=0.0)
    return [int(t) for t in np.asarray(out.tokens[0, len(prompt):])]


def _prompts(seed, sizes):
    rng = np.random.RandomState(seed)
    return [[int(t) for t in rng.randint(0, CFG.vocab_size, size=n)]
            for n in sizes]


def _batcher(params, **kw):
    kw.setdefault("batch", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("chunk", 3)
    return ContinuousBatcher(params, CFG, **kw)


class _EngineHarness:
    """ServeEngine on a background thread with recorded deltas/retires.
    A request's final eos/budget delta arrives via on_retired (the
    atomic-final contract), so both callbacks feed ``got``."""

    def __init__(self, batcher, registry=None):
        self.got: dict = {}
        self.retired: dict = {}

        def on_retired(rid, reason, n, final):
            self.got.setdefault(rid, []).extend(final)
            self.retired.setdefault(rid, (reason, n))

        self.engine = ServeEngine(
            batcher,
            on_delta=lambda rid, t: self.got.setdefault(rid, []).extend(t),
            on_retired=on_retired, registry=registry)
        self.thread = threading.Thread(target=self.engine.run, daemon=True)
        self.thread.start()

    def finish(self, timeout=120):
        self.engine.drain()
        self.thread.join(timeout=timeout)
        assert not self.thread.is_alive(), "engine did not drain"


class TestProtocol:
    def test_frame_round_trip(self):
        a, b = socket.socketpair()
        try:
            P.send_frame(a, P.ADMIT, 7, P.pack_json({"x": 1}))
            P.send_frame(a, P.TOKENS, 9, P.pack_tokens([3, 1, 4, 1, 5]))
            ftype, rid, payload = P.recv_frame(b)
            assert (ftype, rid) == (P.ADMIT, 7)
            assert P.unpack_json(payload) == {"x": 1}
            ftype, rid, payload = P.recv_frame(b)
            assert (ftype, rid) == (P.TOKENS, 9)
            assert P.unpack_tokens(payload) == [3, 1, 4, 1, 5]
            a.close()
            assert P.recv_frame(b) is None      # clean EOF
        finally:
            b.close()

    def test_implausible_length_rejected(self):
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack("<I", P.MAX_FRAME_BYTES + 1))
            with pytest.raises(P.ProtocolError, match="implausible"):
                P.recv_frame(b)
        finally:
            a.close()
            b.close()

    def test_truncated_frame_rejected(self):
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack("<I", 100) + b"\x01short")
            a.close()
            with pytest.raises(P.ProtocolError, match="truncated"):
                P.recv_frame(b)
        finally:
            b.close()

    def test_large_payload_zero_copy_path_round_trips(self):
        """Payloads >= LARGE_PAYLOAD_BYTES ship as header-then-payload
        writes (memoryview accepted, no concatenated copy); the wire is
        byte-identical — recv_frame sees one ordinary frame."""
        a, b = socket.socketpair()
        try:
            blob = bytes(range(256)) * (P.LARGE_PAYLOAD_BYTES // 256 + 1)
            assert len(blob) >= P.LARGE_PAYLOAD_BYTES
            got = {}
            t = threading.Thread(
                target=lambda: got.update(frame=P.recv_frame(b)))
            t.start()        # concurrent reader: blob exceeds socket buf
            P.send_frame(a, P.TOKENS, 5, memoryview(blob))
            t.join(timeout=10)
            ftype, rid, payload = got["frame"]
            assert (ftype, rid) == (P.TOKENS, 5)
            assert payload == blob
        finally:
            a.close()
            b.close()

    def test_memoryview_payload_small_frame(self):
        assert P.encode_frame(P.TOKENS, 3, memoryview(b"abc")) \
            == P.encode_frame(P.TOKENS, 3, b"abc")

    def test_non_byte_memoryview_uses_nbytes(self):
        """A float32 view's len() counts ELEMENTS; the frame length must
        be its byte size or the receiver desyncs."""
        arr = np.arange(6, dtype=np.float32).reshape(2, 3)
        frame = P.encode_frame(P.TOKENS, 1, memoryview(arr))
        assert frame == P.encode_frame(P.TOKENS, 1, arr.tobytes())
        a, b = socket.socketpair()
        try:
            P.send_frame(a, P.TOKENS, 1, memoryview(arr))
            ftype, rid, payload = P.recv_frame(b)
            assert (ftype, rid) == (P.TOKENS, 1)
            assert payload == arr.tobytes()
        finally:
            a.close()
            b.close()

    def test_frame_header_size_guard(self):
        with pytest.raises(P.ProtocolError, match="too large"):
            P.frame_header(P.TOKENS, 1, P.MAX_FRAME_BYTES)

    def test_recv_exact_short_read_contract(self):
        """recv_into rewrite keeps the contract: None on clean EOF at a
        boundary, ProtocolError on EOF mid-read."""
        a, b = socket.socketpair()
        a.close()
        assert P.recv_exact(b, 4) is None
        b.close()
        a, b = socket.socketpair()
        try:
            a.sendall(b"\x01\x02")
            a.close()
            with pytest.raises(P.ProtocolError, match="truncated"):
                P.recv_exact(b, 4)
        finally:
            b.close()

    def test_tokens_payload_must_be_u32s(self):
        with pytest.raises(P.ProtocolError, match="u32"):
            P.unpack_tokens(b"\x01\x02\x03")

    def test_parse_admit_validation(self):
        ok = P.pack_json({"prompt": [1, 2], "max_new_tokens": 4})
        assert P.parse_admit(ok) == ([1, 2], 4, True)
        for bad in ({"prompt": "nope", "max_new_tokens": 4},
                    {"prompt": [1, "x"], "max_new_tokens": 4},
                    {"prompt": [1], "max_new_tokens": "4"},
                    {"prompt": [1], "max_new_tokens": 4, "stream": 1},
                    {"prompt": [True], "max_new_tokens": 4}):
            with pytest.raises(P.ProtocolError):
                P.parse_admit(P.pack_json(bad))
        with pytest.raises(P.ProtocolError, match="JSON"):
            P.parse_admit(b"\xff{")


class TestOpenLoopEngine:
    def test_incremental_submission_matches_closed_batch(self, params):
        """Requests submitted WHILE the engine runs (some after earlier
        ones already streamed deltas) produce exactly the closed-batch
        serve() outputs — per-request streams make admission timing
        invisible."""
        prompts = _prompts(0, (5, 3, 7, 4))
        closed = _batcher(params).serve(prompts, 6)
        h = _EngineHarness(_batcher(params))
        h.engine.submit(0, prompts[0], 6)
        h.engine.submit(1, prompts[1], 6)
        # wait for a first delta before submitting the rest: the live
        # queue is genuinely live, not a pre-drained FIFO
        t0 = time.time()
        while not h.got and time.time() - t0 < 60:
            time.sleep(0.005)
        assert h.got, "no deltas streamed"
        h.engine.submit(2, prompts[2], 6)
        h.engine.submit(3, prompts[3], 6)
        h.finish()
        for i in range(4):
            assert h.got[i] == closed[i], i
            assert h.retired[i] == ("budget", 6)

    def test_deltas_stream_before_retirement(self, params):
        """A long request's tokens arrive across multiple deltas (one
        per consumed chunk), not as one lump at retirement — with the
        LAST delta riding the retirement callback (the atomic-final
        contract)."""
        prompts = _prompts(1, (4,))
        b = _batcher(params, batch=1, chunk=2)
        deltas = []
        eng = ServeEngine(
            b, on_delta=lambda rid, t: deltas.append(list(t)),
            on_retired=lambda rid, r, n, final: deltas.append(list(final)))
        eng.submit(0, prompts[0], 10)
        eng.drain()
        eng.run()
        assert len(deltas) >= 4, deltas       # 10 tokens / 2-step chunks
        assert all(d for d in deltas[:-1])    # live deltas are nonempty
        assert deltas[-1], "final delta must ride the retirement"
        assert [t for d in deltas for t in d] == _reference(
            params, prompts[0], 10)

    def test_cancel_waiting_and_inflight(self, params):
        """Cancelling a WAITING request retires it with zero tokens;
        cancelling an ADMITTED one frees its slot so queued work
        completes; double-cancel and cancel-after-retire are no-ops."""
        prompts = _prompts(2, (5, 4, 6, 3))
        h = _EngineHarness(_batcher(params, batch=1, chunk=2,
                                    max_len=64))
        h.engine.submit("run", prompts[0], 4)
        h.engine.submit("doomed", prompts[1], 59)           # long
        h.engine.submit("waiting", prompts[2], 4)
        h.engine.submit("last", prompts[3], 4)
        h.engine.cancel("waiting")                # still queued
        t0 = time.time()
        while "doomed" not in h.got and time.time() - t0 < 60:
            time.sleep(0.005)                     # admitted + streaming
        h.engine.cancel("doomed")
        h.engine.cancel("doomed")                 # idempotent
        h.finish()
        assert h.retired["waiting"] == ("cancelled", 0)
        assert h.got.get("waiting", []) == []     # zero tokens streamed
        assert h.retired["doomed"][0] == "cancelled"
        assert len(h.got["doomed"]) < 59          # stopped early
        ref = _reference(params, prompts[1], 59)
        assert h.got["doomed"] == ref[:len(h.got["doomed"])]
        assert h.got["run"] == _reference(params, prompts[0], 4)
        assert h.got["last"] == _reference(params, prompts[3], 4)
        h.engine.cancel("last")                   # after retirement: no-op
        assert h.retired["last"] == ("budget", 4)

    def test_queue_depth_gauge_exact(self, params):
        """The qdepth gauge tracks the live wait queue through submit,
        admission, and cancel."""
        reg = M.MetricsRegistry()
        b = _batcher(params, batch=1, chunk=2)
        eng = ServeEngine(b, registry=reg)
        g = reg.gauge("tony_serve_queue_depth")
        prompts = _prompts(3, (4, 4, 4))
        eng.submit(0, prompts[0], 4)
        eng.submit(1, prompts[1], 4)
        eng.submit(2, prompts[2], 4)
        assert g.value == 3                       # nothing admitted yet
        eng.cancel(1)
        assert g.value == 2
        eng.drain()
        eng.run()
        assert g.value == 0

    def test_stop_aborts_outstanding(self, params):
        prompts = _prompts(4, (4, 4))
        h = _EngineHarness(_batcher(params, batch=1, chunk=2,
                                    max_len=64))
        h.engine.submit(0, prompts[0], 40)
        h.engine.submit(1, prompts[1], 8)
        t0 = time.time()
        while 0 not in h.got and time.time() - t0 < 60:
            time.sleep(0.005)
        h.engine.stop()
        h.thread.join(timeout=60)
        assert not h.thread.is_alive()
        assert h.retired[0][0] == "stopped"
        assert h.retired[1][0] == "stopped"
        with pytest.raises(RuntimeError, match="draining"):
            h.engine.submit(2, prompts[0], 4)

    def test_failed_validation_leaves_no_phantom_queue_depth(self,
                                                             params):
        """A mid-list invalid request fails the whole serve() up front
        AND unwinds the earlier submits — the queue-depth gauge must
        not report phantom waiters from an engine that never ran."""
        reg = M.MetricsRegistry()
        saved = M.set_default(reg)
        try:
            b = _batcher(params, batch=1)
            with pytest.raises(ValueError, match="request 1"):
                b.serve([[1, 2], [1] * 40], 8)
            assert reg.gauge("tony_serve_queue_depth").value == 0
            # and the batcher is still serviceable
            assert b.serve([[1, 2]], 4)
        finally:
            M.set_default(saved)

    def test_second_engine_on_live_batcher_rejected(self, params):
        """Constructing an engine over a batcher another engine is
        driving must fail BEFORE touching the batcher's rng/counter
        state — a silent reset would corrupt the live run's streams."""
        b = _batcher(params, batch=1, chunk=2)
        h = _EngineHarness(b)
        t0 = time.time()
        while not getattr(b, "_engine_running", False) \
                and time.time() - t0 < 30:
            time.sleep(0.005)
        with pytest.raises(RuntimeError, match="live engine"):
            ServeEngine(b)
        with pytest.raises(RuntimeError, match="live engine"):
            b.serve([[1, 2]], 4)
        h.finish()
        assert b.serve([[1, 2]], 4)         # reusable once drained

    def test_submit_validation(self, params):
        eng = ServeEngine(_batcher(params, batch=1))
        with pytest.raises(ValueError, match="empty prompt"):
            eng.submit(0, [], 4)
        with pytest.raises(ValueError, match="positive"):
            eng.submit(0, [1, 2], 0)
        with pytest.raises(ValueError, match="exceeds max_len"):
            eng.submit(0, [1] * 30, 8)
        eng.submit(0, [1, 2], 4)
        with pytest.raises(ValueError, match="already active"):
            eng.submit(0, [1, 2], 4)
        eng.stop()
        eng.run()                                 # drains the abort


class TestServingServerE2E:
    def test_streamed_tokens_match_reference(self, params):
        prompts = _prompts(0, (5, 3, 7, 4))
        reg = M.MetricsRegistry()
        srv = ServingServer(_batcher(params), registry=reg)
        port = srv.start()
        try:
            with StreamingClient("127.0.0.1", port) as c:
                assert c.hello["slots"] == 2
                rids = [c.submit(p, 6) for p in prompts]
                for i, rid in enumerate(rids):
                    toks, reason = c.result(rid)
                    assert toks == _reference(params, prompts[i], 6), i
                    assert reason == "budget"
            # latency histograms populated at the delta-emission point
            assert reg.histogram("tony_serve_ttft_seconds").count >= 4
            assert reg.histogram("tony_serve_intertoken_seconds").count > 0
        finally:
            srv.stop(drain=True)

    def test_poll_mode_and_stats(self, params):
        prompts = _prompts(5, (4, 4))
        srv = ServingServer(_batcher(params), registry=M.MetricsRegistry())
        port = srv.start()
        try:
            with StreamingClient("127.0.0.1", port) as c:
                rid = c.submit(prompts[0], 6, stream=False)
                got, polls = [], 0
                while True:
                    toks, reason = c.poll(rid)
                    polls += 1
                    got.extend(toks)
                    if reason is not None:
                        break
                assert got == _reference(params, prompts[0], 6)
                assert reason == "budget"
                assert polls >= 2                 # chunked, not one lump
                st = c.stats()
                assert st["slots"] == 2
                assert st["queue_depth"] == 0
        finally:
            srv.stop(drain=True)

    def test_cancel_over_the_wire(self, params):
        prompts = _prompts(6, (4, 4))
        srv = ServingServer(_batcher(params, batch=1, chunk=2),
                            registry=M.MetricsRegistry())
        port = srv.start()
        try:
            with StreamingClient("127.0.0.1", port) as c:
                rid = c.submit(prompts[0], 25)
                ev = c.next_event(rid, timeout=60)
                assert ev[0] == "tokens"
                c.cancel(rid)
                c.cancel(rid)                     # idempotent on the wire
                toks = list(ev[1])
                while True:
                    ev = c.next_event(rid, timeout=60)
                    if ev[0] == "retired":
                        assert ev[1] == "cancelled"
                        break
                    assert ev[0] == "tokens"
                    toks.extend(ev[1])
                # a cancelled stream is a PREFIX of the full answer
                ref = _reference(params, prompts[0], 25)
                assert toks == ref[:len(toks)]
                assert len(toks) < 25
                # the freed slot serves the next request completely
                rid2 = c.submit(prompts[1], 6)
                toks2, reason = c.result(rid2)
                assert toks2 == _reference(params, prompts[1], 6)
        finally:
            srv.stop(drain=True)

    def test_graceful_drain(self, params):
        """stop(drain=True) finishes in-flight requests — the client
        still receives every token and the RETIRED frame."""
        prompts = _prompts(7, (4,))
        srv = ServingServer(_batcher(params, batch=1, chunk=2),
                            registry=M.MetricsRegistry())
        port = srv.start()
        c = StreamingClient("127.0.0.1", port)
        try:
            rid = c.submit(prompts[0], 12)
            ev = c.next_event(rid, timeout=60)
            assert ev[0] == "tokens"
            stopper = threading.Thread(target=srv.stop,
                                       kwargs={"drain": True})
            stopper.start()
            toks = list(ev[1])
            while True:
                ev = c.next_event(rid, timeout=60)
                if ev[0] == "retired":
                    break
                toks.extend(ev[1])
            assert toks == _reference(params, prompts[0], 12)
            stopper.join(timeout=60)
            assert not stopper.is_alive()
        finally:
            c.close()


class TestProtocolRobustness:
    """Satellite contract: malformed/truncated frames never kill the
    server; disconnects free slots; errors are scoped correctly."""

    @pytest.fixture()
    def server(self, params):
        srv = ServingServer(_batcher(params), registry=M.MetricsRegistry())
        srv.start()
        yield srv
        srv.stop()

    def _assert_still_serving(self, params, port):
        prompts = _prompts(9, (4,))
        with StreamingClient("127.0.0.1", port) as c:
            toks, reason = c.result(c.submit(prompts[0], 5))
            assert toks == _reference(params, prompts[0], 5)

    def test_garbage_magic_closed(self, params, server):
        s = socket.create_connection(("127.0.0.1", server.port))
        s.sendall(b"GET / HTTP/1.1\r\n\r\n")
        assert s.recv(4096) == b""                # server closed it
        s.close()
        self._assert_still_serving(params, server.port)

    def test_implausible_frame_is_connection_scoped(self, params, server):
        s = socket.create_connection(("127.0.0.1", server.port))
        s.sendall(P.MAGIC)
        assert P.recv_frame(s)[0] == P.HELLO
        s.sendall(struct.pack("<I", P.MAX_FRAME_BYTES + 5))
        frame = P.recv_frame(s)                   # ERROR rid=0, then EOF
        assert frame is not None and frame[0] == P.ERROR and frame[1] == 0
        assert "implausible" in P.unpack_json(frame[2])["message"]
        assert P.recv_frame(s) is None
        s.close()
        self._assert_still_serving(params, server.port)

    def test_truncated_frame_never_kills_server(self, params, server):
        s = socket.create_connection(("127.0.0.1", server.port))
        s.sendall(P.MAGIC)
        assert P.recv_frame(s)[0] == P.HELLO
        s.sendall(struct.pack("<I", 64) + b"\x01partial")
        s.close()                                 # die mid-frame
        self._assert_still_serving(params, server.port)

    def test_unknown_frame_type_is_connection_scoped(self, params,
                                                     server):
        s = socket.create_connection(("127.0.0.1", server.port))
        s.sendall(P.MAGIC)
        assert P.recv_frame(s)[0] == P.HELLO
        P.send_frame(s, 250, 1)
        frame = P.recv_frame(s)
        assert frame[0] == P.ERROR and frame[1] == 0
        assert P.recv_frame(s) is None
        s.close()
        self._assert_still_serving(params, server.port)

    def test_malformed_admit_payload_is_connection_scoped(self, params,
                                                          server):
        s = socket.create_connection(("127.0.0.1", server.port))
        s.sendall(P.MAGIC)
        assert P.recv_frame(s)[0] == P.HELLO
        P.send_frame(s, P.ADMIT, 1, b"\xff\xfenot json")
        frame = P.recv_frame(s)
        assert frame[0] == P.ERROR and frame[1] == 0
        s.close()
        self._assert_still_serving(params, server.port)

    def test_unservable_admit_is_request_scoped(self, params, server):
        """A too-long prompt costs an ERROR for that rid only — the
        connection keeps working."""
        with StreamingClient("127.0.0.1", server.port) as c:
            rid = c.submit([1] * 40, 8)           # exceeds max_len 32
            ev = c.next_event(rid, timeout=60)
            assert ev[0] == "error" and "exceeds max_len" in ev[1]
            prompts = _prompts(10, (4,))
            toks, _ = c.result(c.submit(prompts[0], 5))
            assert toks == _reference(params, prompts[0], 5)

    def test_duplicate_rid_is_request_scoped(self, params, server):
        """A duplicate ADMIT rid earns an ERROR for that rid while the
        original stream keeps delivering — and the reply is sent after
        the session lock is dropped (TL001), so a slow duplicate-sender
        can never stall admission for everyone else."""
        with StreamingClient("127.0.0.1", server.port) as c:
            prompt = _prompts(14, (4,))[0]
            c.submit(prompt, 6, rid=777)
            c.submit(prompt, 6, rid=777)          # duplicate, same rid
            saw_error, saw_retired = False, False
            deadline = time.time() + 60
            while not (saw_error and saw_retired) and time.time() < deadline:
                ev = c.next_event(777, timeout=60)
                if ev[0] == "error":
                    assert "already active" in ev[1]
                    saw_error = True
                elif ev[0] == "retired":
                    saw_retired = True            # original stream intact
            assert saw_error and saw_retired
            # connection-scoped state is clean: fresh rids still serve
            toks, _ = c.result(c.submit(prompt, 5))
            assert toks == _reference(params, prompt, 5)

    def test_disconnect_mid_stream_frees_slots(self, params, server):
        """A client that vanishes mid-stream must not leak its cache
        slots: with batch=2 fully occupied by the vanished client, a
        NEW client's requests still complete."""
        c1 = StreamingClient("127.0.0.1", server.port)
        r1 = c1.submit(_prompts(11, (4,))[0], 25)
        r2 = c1.submit(_prompts(12, (4,))[0], 25)
        assert c1.next_event(r1, timeout=60)[0] == "tokens"
        c1.close()                                # both slots were busy
        self._assert_still_serving(params, server.port)
        # engine-side: the cancelled occupants were swept
        t0 = time.time()
        while time.time() - t0 < 30:
            st = server.engine.stats()
            if st["active"] == 0 and st["queue_depth"] == 0:
                break
            time.sleep(0.01)
        assert st["active"] == 0, st


class TestRouter:
    def _replicas(self, params, n=2, **kw):
        servers = [ServingServer(_batcher(params, **kw),
                                 registry=M.MetricsRegistry())
                   for _ in range(n)]
        ports = [s.start() for s in servers]
        return servers, [f"127.0.0.1:{p}" for p in ports]

    def test_sessions_spread_by_queue_depth(self, params):
        """Enough concurrent sessions land on BOTH replicas (placement
        by reported queue depth + local assignment), and every output
        matches the solo reference."""
        servers, addrs = self._replicas(params)
        router = ServingRouter(addrs, registry=M.MetricsRegistry())
        rport = router.start()
        prompts = _prompts(13, (5, 3, 7, 4, 6, 3))
        try:
            with StreamingClient("127.0.0.1", rport) as c:
                assert c.hello["router"] is True
                rids = [c.submit(p, 6) for p in prompts]
                outs = [c.result(r) for r in rids]
            for i, (toks, reason) in enumerate(outs):
                assert toks == _reference(params, prompts[i], 6), i
            placed = router.stats()["replicas"]
            placed_counts = [servers[i].engine.b.steps_executed
                             for i in range(2)]
            assert all(s > 0 for s in placed_counts), (
                f"placement did not spread: {placed}")
        finally:
            router.stop()
            for s in servers:
                s.stop()

    def test_placement_prefers_less_loaded_replica(self, params):
        """With replica A pre-loaded (its queue depth reported via
        STATS), new router sessions land on B."""
        servers, addrs = self._replicas(params, chunk=2)
        router = ServingRouter(addrs, health_interval_s=0.1,
                               registry=M.MetricsRegistry())
        rport = router.start()
        try:
            # saturate replica A directly: 2 slots busy + 2 queued
            host_a, port_a = addrs[0].rsplit(":", 1)
            ca = StreamingClient(host_a, int(port_a))
            fillers = [ca.submit(p, 28)
                       for p in _prompts(14, (3, 3, 3, 3))]
            # let a health/stats cycle observe the load
            deadline = time.time() + 10
            while time.time() < deadline:
                load = router.stats()["replicas"][addrs[0]]
                if load["reported_load"] >= 3:
                    break
                time.sleep(0.02)
            assert load["reported_load"] >= 3, load
            with StreamingClient("127.0.0.1", rport) as c:
                prompts = _prompts(15, (4, 4))
                rids = [c.submit(p, 4) for p in prompts]
                for i, r in enumerate(rids):
                    toks, _ = c.result(r)
                    assert toks == _reference(params, prompts[i], 4)
            placed = router.stats()["replicas"]
            b_sessions = servers[1].engine.b.steps_executed
            assert b_sessions > 0, placed         # B actually served
            for f in fillers:
                ca.cancel(f)
            ca.close()
        finally:
            router.stop()
            for s in servers:
                s.stop()

    def test_replica_loss_drains_to_survivor_no_dup_no_drop(self, params):
        """THE router acceptance pin: kill a replica mid-stream; every
        session it carried completes on the survivor with exactly the
        solo-reference token sequence — the streamed prefix is trimmed
        into the re-admission, so nothing duplicates and nothing
        drops."""
        class SlowFetch(ContinuousBatcher):
            def _fetch(self, handle):
                time.sleep(0.05)          # keep streams mid-flight
                return super()._fetch(handle)

        servers = [ServingServer(SlowFetch(params, CFG, batch=2,
                                           max_len=64, chunk=2),
                                 registry=M.MetricsRegistry())
                   for _ in range(2)]
        addrs = [f"127.0.0.1:{s.start()}" for s in servers]
        reg = M.MetricsRegistry()
        router = ServingRouter(addrs, health_interval_s=0.2, registry=reg)
        rport = router.start()
        prompts = _prompts(16, (5, 5, 5, 5))
        budget = 24
        got = {}
        try:
            with StreamingClient("127.0.0.1", rport) as c:
                rids = [c.submit(p, budget) for p in prompts]
                got = {r: [] for r in rids}
                started = set()
                deadline = time.time() + 60
                while len(started) < len(rids) and time.time() < deadline:
                    for i, r in enumerate(rids):
                        if r in started:
                            continue
                        try:
                            ev = c.next_event(r, timeout=0.05)
                        except queue_mod.Empty:
                            continue
                        assert ev[0] == "tokens", ev
                        got[r].extend(ev[1])
                        started.add(r)
                assert len(started) == len(rids), "streams never started"
                pre = router.stats()["replicas"]
                assert all(v["assigned"] > 0 for v in pre.values()), pre
                servers[0].kill()                 # replica loss
                for i, r in enumerate(rids):
                    while True:
                        ev = c.next_event(r, timeout=60)
                        if ev[0] == "tokens":
                            got[r].extend(ev[1])
                        elif ev[0] == "retired":
                            break
                        else:
                            raise AssertionError(ev)
                for i, r in enumerate(rids):
                    assert got[r] == _reference(params, prompts[i],
                                                budget), i
            assert reg.counter("tony_router_failovers_total").value >= 1
            assert reg.gauge("tony_router_replica_up",
                             replica=addrs[0]).value == 0
            assert reg.gauge("tony_router_replica_up",
                             replica=addrs[1]).value == 1
        finally:
            router.stop()
            for s in servers:
                s.stop()


class TestFleetOperations:
    """Planned drain + rolling upgrade on REAL serving replicas: the
    live-operability acceptance pins. Migration re-prefills on a
    survivor with the streamed prefix folded in and the session's rng
    stream/offset pinned, so the full token sequence — greedy AND
    sampled — must equal the solo reference exactly."""

    def _slow_servers(self, params, n=2, weights_version=None,
                      fetch_s=0.05, **kw):
        class SlowFetch(ContinuousBatcher):
            def _fetch(self, handle):
                time.sleep(fetch_s)       # keep streams mid-flight
                return super()._fetch(handle)

        kw.setdefault("batch", 2)
        kw.setdefault("max_len", 64)
        kw.setdefault("chunk", 2)
        servers = [ServingServer(SlowFetch(params, CFG, **kw),
                                 registry=M.MetricsRegistry(),
                                 weights_version=weights_version)
                   for _ in range(n)]
        return servers, [f"127.0.0.1:{s.start()}" for s in servers]

    def _start_streams(self, c, prompts, budget):
        """Submit and block until every stream has produced at least
        one token (so a drain migrates genuinely mid-flight)."""
        rids = [c.submit(p, budget) for p in prompts]
        got = {r: [] for r in rids}
        started = set()
        deadline = time.time() + 60
        while len(started) < len(rids) and time.time() < deadline:
            for r in rids:
                if r in started:
                    continue
                try:
                    ev = c.next_event(r, timeout=0.05)
                except queue_mod.Empty:
                    continue
                assert ev[0] == "tokens", ev
                got[r].extend(ev[1])
                started.add(r)
        assert len(started) == len(rids), "streams never started"
        return rids, got

    def _collect(self, c, rids, got):
        for r in rids:
            while True:
                ev = c.next_event(r, timeout=60)
                if ev[0] == "tokens":
                    got[r].extend(ev[1])
                elif ev[0] == "retired":
                    break
                else:
                    raise AssertionError(ev)

    def test_planned_drain_zero_dup_drop_greedy(self, params):
        """Drain a replica carrying live greedy streams: every session
        completes with exactly the solo-reference tokens, the drained
        replica ends fenced and empty, and the migration counters
        move."""
        # batch=4: the survivor has idle slots, so migrations ACK
        # while the old placement still streams (the interesting path)
        servers, addrs = self._slow_servers(params, batch=4)
        reg = M.MetricsRegistry()
        router = ServingRouter(addrs, health_interval_s=0.2,
                               registry=reg)
        rport = router.start()
        prompts = _prompts(31, (5, 5, 5, 5))
        budget = 24
        try:
            with StreamingClient("127.0.0.1", rport) as c:
                rids, got = self._start_streams(c, prompts, budget)
                pre = router.stats()["replicas"]
                assert all(v["assigned"] > 0 for v in pre.values()), pre
                victim = max(pre, key=lambda a: pre[a]["assigned"])
                res = c.drain_replica(victim)
                assert res.get("drained"), res
                assert res["migrated"] >= 1, res
                self._collect(c, rids, got)
                for i, r in enumerate(rids):
                    assert got[r] == _reference(params, prompts[i],
                                                budget), i
                post = router.stats()["replicas"]
                assert post[victim]["draining"]
                assert post[victim]["assigned"] == 0
            # every drain-initiated migration either ACKs (counted) or
            # the old placement legitimately finishes first — at least
            # one must take the ACK path with idle survivor slots
            migs = reg.counter("tony_router_migrations_total").value
            assert 1 <= migs <= res["migrated"], (migs, res)
            assert reg.counter("tony_router_drains_total").value == 1
            # drain is planned, not failover
            assert reg.counter("tony_router_failovers_total").value == 0
        finally:
            router.stop()
            for s in servers:
                s.stop()

    def test_planned_drain_zero_dup_drop_sampled(self, params):
        """The sampled twin: per-session rng stream + offset pinning
        makes the migrated continuation bit-identical to the
        uninterrupted sampled run."""
        kw = dict(batch=2, max_len=64, chunk=2, seed=7,
                  temperature=0.8, top_k=20, top_p=0.9)
        prompts = _prompts(32, (5, 4, 6, 5))
        budget = 20
        ref = ContinuousBatcher(params, CFG, **kw).serve(prompts, budget)
        servers, addrs = self._slow_servers(params, **kw)
        router = ServingRouter(addrs, health_interval_s=0.2,
                               registry=M.MetricsRegistry())
        rport = router.start()
        try:
            with StreamingClient("127.0.0.1", rport) as c:
                rids, got = self._start_streams(c, prompts, budget)
                pre = router.stats()["replicas"]
                victim = max(pre, key=lambda a: pre[a]["assigned"])
                res = c.drain_replica(victim)
                assert res.get("drained"), res
                self._collect(c, rids, got)
                for i, r in enumerate(rids):
                    assert got[r] == ref[i], \
                        f"stream {i}: sampled dup/drop across migration"
        finally:
            router.stop()
            for s in servers:
                s.stop()

    def test_rolling_upgrade_mid_stream_continuity(self, params):
        """Upgrade a 2-replica fleet mid-stream: stand up the v2 tier,
        drain and retire v1 replica by replica. Every in-flight
        session keeps exact token continuity, the fleet ends all-v2,
        and a fresh session lands on the new tier."""
        from tony_tpu.serving.fleet import CapacityProvider, \
            FleetController

        old_servers, old_addrs = self._slow_servers(
            params, weights_version="v1")
        new_servers, new_addrs = self._slow_servers(
            params, weights_version="v2")
        by_addr = dict(zip(old_addrs + new_addrs,
                           old_servers + new_servers))

        class StopProvider(CapacityProvider):
            released = []

            def grow(self, n):
                raise AssertionError("upgrade must not grow")

            def release(self, addrs):
                for a in addrs:
                    self.released.append(a)
                    by_addr[a].stop()

        reg = M.MetricsRegistry()
        router = ServingRouter(old_addrs, health_interval_s=0.2,
                               registry=reg)
        rport = router.start()
        prompts = _prompts(33, (5, 5, 4, 6))
        budget = 24
        try:
            ctrl = FleetController(router, StopProvider(), registry=reg)
            with StreamingClient("127.0.0.1", rport) as c:
                rids, got = self._start_streams(c, prompts, budget)
                results = ctrl.rolling_upgrade(new_addrs)
                assert set(results) == set(old_addrs)
                assert all(r.get("drained") for r in results.values()), \
                    results
                self._collect(c, rids, got)
                for i, r in enumerate(rids):
                    assert got[r] == _reference(params, prompts[i],
                                                budget), i
                post = router.stats()["replicas"]
                assert set(post) == set(new_addrs), post
                assert all(v["weights_version"] == "v2"
                           for v in post.values()), post
                assert sorted(StopProvider.released) == sorted(old_addrs)
                # a fresh session serves on the upgraded tier
                p = _prompts(34, (5,))[0]
                rid = c.submit(p, 6)
                toks, reason = c.result(rid)
                assert toks == _reference(params, p, 6)
            assert reg.counter("tony_fleet_upgrades_total").value == 1
        finally:
            router.stop()
            for s in old_servers + new_servers:
                s.stop()


class TestStreamingBenchArm:
    def test_stream_vs_request_response_pins(self):
        """The tentpole acceptance, deterministically: at a 50 ms
        injected round trip the streamed wall sits within 1.15x of the
        zero-delay wall (the round trip is paid once) while the
        request/response tunnel pays it per chunk + per admission —
        stream-vs-rr >= 2. The plug keeps the streamed sync schedule
        identical across runs (asserted)."""
        import bench

        res = bench._streaming_arm()
        assert res["serving_stream_syncs"] == \
            res["serving_stream_syncs_nodelay"], res
        assert res["serving_stream_vs_nodelay"] <= 1.15, res
        assert res["serving_stream_vs_rr_wall"] >= 2.0, res
        # rr degraded by >= exchanges x RT over ITS compute floor
        floor = (res["serving_stream_wall_nodelay_s"]
                 - 0.0)                           # same chunk schedule
        degraded = res["serving_rr_wall_s"] - floor
        assert degraded >= (0.8 * res["serving_rr_round_trips"]
                            * res["serving_stream_round_trip_s"]), res
        assert res["serving_stream_ttft_s"] > 0, res


@pytest.mark.slow
class TestStreamingBenchRealistic:
    def test_realistic_compute_still_streams_past_rr(self):
        """No injected fetch floor — real (tiny-model) chunk compute
        only, so the 50 ms round trip dominates: streaming must beat
        the per-chunk tunnel by well over 2x."""
        import bench

        res = bench._streaming_arm(fetch_floor_s=0.0, budget=96)
        assert res["serving_stream_vs_rr_wall"] >= 2.0, res
