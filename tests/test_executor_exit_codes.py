"""Executor exit-code semantics: lost-coordinator is distinct from user
failure (VERDICT r1 weak #6 — the reference folds both into -1,
TaskExecutor.java:264-268, losing the triage signal)."""

import os
import subprocess
import sys
import threading
import time

import pytest

from tony_tpu import constants
from tony_tpu.rpc.server import ApplicationRpcServer
from tony_tpu.rpc.service import (ApplicationRpc, ApplicationStatus, TaskUrl,
                                  WorkerSpecResponse)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class OneWorkerImpl(ApplicationRpc):
    """Single-worker gang: barrier releases on first registration."""

    def __init__(self):
        self.heartbeats = []
        self.lock = threading.Lock()

    def get_task_urls(self):
        return []

    def get_cluster_spec(self, task_id):
        return '{"worker": ["h0:1"]}'

    def register_worker_spec(self, worker, spec):
        return WorkerSpecResponse(
            spec='{"worker": ["h0:1"]}', coordinator_address="h0:9999",
            process_id=0, num_processes=1, mesh_spec='{"axes": {"dp": 1}}')

    def register_tensorboard_url(self, url):
        return url

    def register_execution_result(self, exit_code, job_name, job_index,
                                  session_id):
        return "RECEIVED"

    def finish_application(self):
        return "SUCCEEDED"

    def task_executor_heartbeat(self, task_id):
        with self.lock:
            self.heartbeats.append(task_id)

    def get_application_status(self):
        return ApplicationStatus(status="RUNNING", session_id=0)


@pytest.mark.e2e
def test_lost_coordinator_exits_distinct_code(tmp_path):
    """A REAL executor process whose coordinator vanishes mid-run must exit
    with EXIT_LOST_COORDINATOR, not a generic failure code."""
    impl = OneWorkerImpl()
    srv = ApplicationRpcServer(impl)
    srv.start()
    conf = tmp_path / "tony-final.xml"
    conf.write_text("")      # kv format: empty + overrides via file
    (tmp_path / "conf.kv").write_text(
        "tony.task.heartbeat-interval-ms=100\n"
        # a short re-attach window: the test is about the EXIT CODE once
        # the window expires, not about riding out a 30s (default) outage
        "tony.coordinator.reattach-timeout-ms=1500\n")
    env = dict(os.environ)
    env.update({
        "JOB_NAME": "worker", "TASK_INDEX": "0", "TASK_NUM": "1",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    proc = subprocess.Popen(
        [sys.executable, "-m", "tony_tpu.cluster.executor",
         "--am_address", f"localhost:{srv.port}",
         "--conf_file", str(tmp_path / "conf.kv"),
         "--task_command", "sleep 60"],
        env=env, cwd=tmp_path,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not impl.heartbeats:
            time.sleep(0.1)
        assert impl.heartbeats, "executor never heartbeat"
        srv.stop(0)          # coordinator vanishes
        out, _ = proc.communicate(timeout=120)
        assert proc.returncode == constants.EXIT_LOST_COORDINATOR, \
            (proc.returncode, out.decode()[-2000:])
        assert b"lost the coordinator" in out
    finally:
        if proc.poll() is None:
            proc.kill()


def test_session_failure_message_distinguishes_lost_coordinator():
    """Session triage: exit 75 is reported as a coordinator-contact loss
    (infra), other codes as user failure — the message lands in
    final-status.json and the history UI."""
    from tony_tpu.cluster.session import Session
    from tony_tpu.conf.config import TonyConfig

    s = Session(TonyConfig({"tony.worker.instances": "2"}))
    s.register_task_spec("worker:0", "h0:1")
    s.on_task_completed("worker", 0, constants.EXIT_LOST_COORDINATOR)
    assert "lost contact with the coordinator" in s.failure_message
    s2 = Session(TonyConfig({"tony.worker.instances": "1"}))
    s2.register_task_spec("worker:0", "h0:1")
    s2.on_task_completed("worker", 0, 1)
    assert "failed with exit code 1" in s2.failure_message
