"""Unit suite for the coordinator's write-ahead session journal.

Pins the recovery contract: torn FINAL records recover by truncation,
corrupt INTERIOR records fail loudly with the byte offset, and folding
is deterministic — the same journal always rebuilds the same state.
"""

import subprocess
import sys

import pytest

from tony_tpu.cluster import journal as jr


def _write_basic(job_dir) -> str:
    j = jr.Journal(str(job_dir))
    j.append("coordinator_start", app_id="app-1", attempt=0)
    j.append("rpc_bound", port=12345)
    j.append("launch", task_id="worker:0", allocation_id=0, pid=111)
    j.append("launch", task_id="worker:1", allocation_id=1, pid=222)
    j.append("task_registered", task_id="worker:0", spec="h0:9000",
             channel_port=0)
    j.append("task_registered", task_id="worker:1", spec="h1:9001",
             channel_port=7070)
    j.close()
    return j.path


def test_round_trip_fold(tmp_path):
    path = _write_basic(tmp_path)
    state = jr.fold(jr.replay(path))
    assert state.incarnation == 1
    assert state.app_id == "app-1"
    assert state.rpc_port == 12345
    assert state.session_id == 0
    t0 = state.tasks["worker:0"]
    assert (t0.spec, t0.pid, t0.allocation_id) == ("h0:9000", 111, 0)
    assert state.tasks["worker:1"].channel_port == 7070
    assert {t.task_id for t in state.live_tasks()} == {"worker:0",
                                                       "worker:1"}


def test_completion_and_restart_fold(tmp_path):
    j = jr.Journal(str(tmp_path))
    j.append("coordinator_start", app_id="a")
    j.append("launch", task_id="worker:0", allocation_id=0, pid=10)
    j.append("task_registered", task_id="worker:0", spec="h0:1")
    j.append("completion", task_id="worker:0", exit_code=9)
    j.append("task_restart", task_id="worker:0")
    j.append("launch", task_id="worker:0", allocation_id=1, pid=20)
    j.close()
    t = jr.fold(jr.replay(j.path)).tasks["worker:0"]
    # the restarted generation is launched but not yet registered
    assert not t.completed and not t.registered
    assert t.restarts == 1
    assert (t.pid, t.allocation_id) == (20, 1)


def test_elastic_and_session_reset_fold(tmp_path):
    j = jr.Journal(str(tmp_path))
    j.append("coordinator_start", app_id="a")
    j.append("task_registered", task_id="worker:0", spec="h0:1")
    j.append("task_registered", task_id="worker:1", spec="h1:1")
    j.append("elastic_shrink", lost=["worker:1"], epoch=1)
    j.append("regrow_armed", task_ids=["worker:1"])
    state = jr.fold(jr.replay(j.path))
    assert state.cluster_epoch == 1
    assert state.tasks["worker:1"].detached
    assert state.regrow_pending == {"worker:1"}
    assert [t.task_id for t in state.live_tasks()] == ["worker:0"]
    j.append("task_registered", task_id="worker:1", spec="h2:1")
    j.append("regrow_activated", epoch=2, task_ids=["worker:1"])
    state = jr.fold(jr.replay(j.path))
    assert state.cluster_epoch == 2
    assert not state.tasks["worker:1"].detached
    assert state.regrow_pending == set()
    # a whole-job retry wipes per-task state but keeps the incarnation
    j.append("session_reset", session_id=1)
    j.close()
    state = jr.fold(jr.replay(j.path))
    assert state.session_id == 1
    assert state.tasks == {}
    assert state.incarnation == 1


def test_watermark_and_unknown_kinds(tmp_path):
    j = jr.Journal(str(tmp_path))
    j.append("coordinator_start", app_id="a")
    j.append("watermark", name="checkpoint_step", value=40)
    j.append("watermark", name="checkpoint_step", value=60)
    j.append("from_the_future", some_field=1)     # must be skipped
    j.close()
    state = jr.fold(jr.replay(j.path))
    assert state.watermarks == {"checkpoint_step": 60}


def test_incarnation_counts_coordinator_starts(tmp_path):
    j = jr.Journal(str(tmp_path))
    j.append("coordinator_start", app_id="a")
    j.append("coordinator_start", app_id="a")
    j.append("coordinator_start", app_id="a")
    j.close()
    assert jr.fold(jr.replay(j.path)).incarnation == 3


def test_torn_final_record_recovers_by_truncation(tmp_path):
    path = _write_basic(tmp_path)
    full = jr.replay(path)
    with open(path, "r+b") as f:
        f.seek(0, 2)
        f.truncate(f.tell() - 7)      # tear the final record mid-line
    assert jr.replay(path) == full[:-1]
    # truncate_torn physically drops the tear; the file is clean after
    jr.replay(path, truncate_torn=True)
    records, torn_offset, _ = jr.scan(path)
    assert torn_offset is None
    assert records == full[:-1]


def test_torn_final_append_in_progress(tmp_path):
    """A crash can also land mid-append of a NEW record: valid file +
    partial trailing line with no newline."""
    path = _write_basic(tmp_path)
    full = jr.replay(path)
    with open(path, "ab") as f:
        f.write(b"deadbeef {\"k\":\"launch\",\"task")
    assert jr.replay(path) == full


def test_corrupt_interior_record_fails_with_offset(tmp_path):
    path = _write_basic(tmp_path)
    with open(path, "rb") as f:
        data = f.read()
    # flip one payload byte of the THIRD record
    offsets = [i + 1 for i, b in enumerate(data) if b == ord("\n")]
    victim = offsets[1]      # start of record 3
    corrupted = bytearray(data)
    corrupted[victim + 12] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(corrupted))
    with pytest.raises(jr.JournalCorruptError) as e:
        jr.replay(path)
    assert e.value.offset == victim
    assert "checksum mismatch" in str(e.value)


def test_replay_is_deterministic(tmp_path):
    path = _write_basic(tmp_path)
    a = jr.fold(jr.replay(path))
    b = jr.fold(jr.replay(path))
    assert a == b
    # byte-stability: identical records encode identically
    rec = {"k": "launch", "task_id": "worker:0", "pid": 1}
    assert jr.encode_record(rec) == jr.encode_record(dict(reversed(
        list(rec.items()))))


def test_append_survives_unwritable_dir(tmp_path):
    j = jr.Journal(str(tmp_path / "does-not-exist"))
    j.append("coordinator_start", app_id="a")     # must not raise
    j.append("rpc_bound", port=1)
    j.close()


def _fsck(job_dir):
    return subprocess.run(
        [sys.executable, "-m", "tony_tpu.cluster.journal",
         "--verify", str(job_dir)],
        capture_output=True, text=True)


def test_fsck_clean(tmp_path):
    _write_basic(tmp_path)
    res = _fsck(tmp_path)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "OK: 6 record(s), incarnation 1" in res.stdout
    assert "task worker:0: running pid=111" in res.stdout


def test_fsck_torn_tail_is_clean_but_reported(tmp_path):
    path = _write_basic(tmp_path)
    with open(path, "r+b") as f:
        f.seek(0, 2)
        f.truncate(f.tell() - 5)
    res = _fsck(tmp_path)
    assert res.returncode == 0
    assert "torn final record at byte offset" in res.stdout


def test_fsck_corrupt_interior_points_at_offset(tmp_path):
    path = _write_basic(tmp_path)
    with open(path, "rb") as f:
        data = f.read()
    offsets = [i + 1 for i, b in enumerate(data) if b == ord("\n")]
    victim = offsets[0]
    corrupted = bytearray(data)
    corrupted[victim + 12] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(corrupted))
    res = _fsck(tmp_path)
    assert res.returncode == 2
    assert f"byte offset {victim}" in res.stdout


def test_fsck_missing_file(tmp_path):
    assert _fsck(tmp_path).returncode == 1
