"""Multi-tenant cluster daemon tests: scheduler policy units, the
SimCluster 1000-job chaos suite, the daemon wire plane, journal
recovery (including the SIGKILL-mid-grant e2e), the history server's
cluster dashboard, and the bench-arm pin.

The chaos pins live INSIDE the harness (tiling episodes, per-grant
invariant, fence-resume assertion) — the tests here drive 1000-job
traces through them and additionally pin the report-level properties:
every job terminal, queue-wait p99 bounded, determinism by seed.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tony_tpu.cluster import daemon as D
from tony_tpu.cluster import journal as journal_mod
from tony_tpu.cluster import scheduler as S
from tony_tpu.cluster.simcluster import SimCluster, generate_trace
from tony_tpu.conf import keys as K
from tony_tpu.conf.config import TonyConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


# ---------------------------------------------------------------------------
# SlicePool
# ---------------------------------------------------------------------------
def _pool(n, digest=""):
    p = S.SlicePool()
    for i in range(n):
        p.add(f"s{i}", digest=digest)
    return p


def test_pool_acquire_is_all_or_nothing():
    p = _pool(3)
    assert p.acquire("a", 2) is not None
    # 1 slice free, 2 wanted: nothing must be touched
    assert p.acquire("b", 2) is None
    assert p.free_count() == 1
    ids, warm = p.acquire("b", 1)
    assert len(ids) == 1 and warm == 0


def test_pool_prefers_digest_matching_slices():
    p = S.SlicePool()
    p.add("cold-1")
    p.add("warm-1", digest="d1")
    p.add("cold-2")
    p.add("warm-2", digest="d1")
    ids, warm = p.acquire("job", 2, digest="d1")
    assert sorted(ids) == ["warm-1", "warm-2"]
    assert warm == 2
    assert p.warm_hits == 2 and p.cold_grants == 0


def test_pool_release_retags_digest_and_idle():
    p = _pool(1)
    p.acquire("a", 1)
    p.release("s0", digest="dd", now=5.0)
    slot = p.get("s0")
    assert slot.digest == "dd" and slot.job_id == "" \
        and slot.idle_since == 5.0
    # empty digest on release keeps the old warm tag
    p.acquire("b", 1)
    p.release("s0", digest="", now=9.0)
    assert p.get("s0").digest == "dd"


def test_pool_reap_idle_skips_busy_slices():
    p = _pool(3)
    p.acquire("a", 1)  # s0 busy (stalest-first order is deterministic)
    busy = [s.slice_id for s in p.slices() if s.job_id][0]
    reaped = p.reap_idle(now=100.0, idle_s=50.0)
    assert busy not in reaped
    assert p.size() == 1 and p.get(busy) is not None


def test_pool_remove_busy_and_duplicate_add_raise():
    p = _pool(1)
    with pytest.raises(S.SchedulerError):
        p.add("s0")
    p.acquire("a", 1)
    with pytest.raises(S.SchedulerError):
        p.remove("s0")


# ---------------------------------------------------------------------------
# ClusterScheduler policy
# ---------------------------------------------------------------------------
def _sched(n_slices, **kw):
    return S.ClusterScheduler(_pool(n_slices), **kw)


def _job(jid, slices=1, user="u", priority=0, digest="", elastic=False):
    return S.Job(job_id=jid, user=user, slices=slices, priority=priority,
                 digest=digest, elastic=elastic)


def test_priority_then_fifo_ordering():
    sched = _sched(1)
    sched.submit(_job("low-old"), 0.0)
    sched.submit(_job("low-new"), 1.0)
    sched.submit(_job("high", priority=2), 2.0)
    order = []
    now = 3.0
    while len(order) < 3:
        grants, _ = sched.tick(now)
        for g in grants:
            order.append(g.job.job_id)
            sched.complete(g.job.job_id, now)
        now += 1.0
    assert order == ["high", "low-old", "low-new"]


def test_gang_grant_is_atomic_and_head_of_line_blocks():
    sched = _sched(4)
    sched.submit(_job("big", slices=3), 0.0)
    sched.submit(_job("small", slices=1), 0.0)
    grants, _ = sched.tick(1.0)
    assert {g.job.job_id for g in grants} == {"big", "small"}
    # big-2 (3 slices, 1 free) now blocks; small-2 behind it must NOT
    # leak the free slice away from the reserving head
    sched.submit(_job("big-2", slices=3), 2.0)
    sched.submit(_job("small-2", slices=1), 2.0)
    grants, _ = sched.tick(3.0)
    assert grants == []
    sched.complete("big", 4.0)
    grants, _ = sched.tick(5.0)
    assert [g.job.job_id for g in grants] == ["big-2"]
    sched.complete("small", 6.0)
    grants, _ = sched.tick(7.0)
    assert [g.job.job_id for g in grants] == ["small-2"]


def test_quota_blocked_user_is_skipped_not_blocking():
    sched = _sched(4, user_quota=2)
    sched.submit(_job("a1", slices=2, user="alice"), 0.0)
    sched.submit(_job("a2", slices=2, user="alice"), 0.0)
    sched.submit(_job("b1", slices=2, user="bob"), 0.0)
    grants, _ = sched.tick(1.0)
    assert [g.job.job_id for g in grants] == ["a1", "b1"]
    sched.complete("a1", 2.0)
    grants, _ = sched.tick(3.0)
    assert [g.job.job_id for g in grants] == ["a2"]


def test_warm_affinity_on_back_to_back_grants():
    sched = _sched(4)
    sched.submit(_job("first", slices=2, digest="dd"), 0.0)
    grants, _ = sched.tick(1.0)
    freed = grants[0].slice_ids
    assert grants[0].warm_hits == 0
    sched.complete("first", 2.0)
    sched.submit(_job("second", slices=2, digest="dd"), 3.0)
    grants, _ = sched.tick(4.0)
    assert grants[0].warm_hits == 2
    assert sorted(grants[0].slice_ids) == sorted(freed)


def test_preemption_victims_lowest_priority_youngest_first():
    sched = _sched(4)
    sched.submit(_job("old-low", slices=2, priority=0, elastic=True), 0.0)
    sched.submit(_job("new-low", slices=2, priority=0, elastic=True), 1.0)
    sched.tick(2.0)
    sched.submit(_job("urgent", slices=2, priority=5), 3.0)
    _, shrinks = sched.tick(4.0)
    # one victim covers the whole shortfall; youngest-first within the
    # lowest priority level
    assert [s.job.job_id for s in shrinks] == ["new-low"]
    assert shrinks[0].requeue is True
    assert len(shrinks[0].release_ids) == 2
    # a fence already in flight is never double-issued
    _, again = sched.tick(5.0)
    assert again == []
    # fence commits -> slices return warm-tagged, victim requeues with
    # its resume step, and the urgent job takes the freed slices
    sched.preemption_complete("new-low", 6.0, fence_step=17)
    victim = sched.jobs["new-low"]
    assert victim.state == S.QUEUED and victim.resume_step == 17
    grants, _ = sched.tick(7.0)
    assert [g.job.job_id for g in grants] == ["urgent"]
    sched.check_invariant()


def test_partial_shrink_keeps_elastic_floor():
    sched = _sched(4)
    sched.submit(_job("wide", slices=4, elastic=True), 0.0)
    sched.tick(1.0)
    sched.submit(_job("head", slices=2, priority=1), 2.0)
    _, shrinks = sched.tick(3.0)
    assert len(shrinks) == 1 and shrinks[0].requeue is False
    assert len(shrinks[0].release_ids) == 2
    sched.preemption_complete("wide", 4.0, fence_step=9)
    wide = sched.jobs["wide"]
    assert wide.state == S.RUNNING and len(wide.granted) == 2
    assert wide.resume_step == 9
    grants, _ = sched.tick(5.0)
    assert [g.job.job_id for g in grants] == ["head"]


def test_non_elastic_and_equal_priority_jobs_are_never_victims():
    sched = _sched(2)
    sched.submit(_job("rigid", slices=2, priority=0, elastic=False), 0.0)
    sched.tick(1.0)
    sched.submit(_job("urgent", slices=2, priority=5), 2.0)
    _, shrinks = sched.tick(3.0)
    assert shrinks == []
    assert sched.jobs["rigid"].state == S.RUNNING


def test_submit_rejections():
    sched = _sched(2, queue_limit=2)
    sched.submit(_job("a", slices=2), 0.0)
    with pytest.raises(S.SchedulerError):
        sched.submit(_job("a"), 0.0)          # duplicate id
    with pytest.raises(S.SchedulerError):
        sched.submit(_job("huge", slices=3), 0.0)  # can never fit
    sched.submit(_job("b"), 0.0)
    with pytest.raises(S.QueueFullError):
        sched.submit(_job("c"), 0.0)


def test_check_invariant_catches_double_grant():
    sched = _sched(2)
    sched.submit(_job("a"), 0.0)
    sched.submit(_job("b"), 0.0)
    sched.tick(1.0)
    # corrupt the books: both jobs claim the same slice
    sched.jobs["b"].granted = list(sched.jobs["a"].granted)
    with pytest.raises(S.DoubleGrantError):
        sched.check_invariant()


# ---------------------------------------------------------------------------
# fold_daemon (journal replay)
# ---------------------------------------------------------------------------
def test_fold_daemon_rejects_grant_of_busy_slice():
    records = [
        {"k": "slice_added", "slice_id": "s0", "t": 0.0},
        {"k": "job_submitted", "job_id": "a", "slices": 1, "seq": 0,
         "t": 1.0},
        {"k": "job_submitted", "job_id": "b", "slices": 1, "seq": 1,
         "t": 1.0},
        {"k": "job_granted", "job_id": "a", "slice_ids": ["s0"], "t": 2.0},
        {"k": "job_granted", "job_id": "b", "slice_ids": ["s0"], "t": 3.0},
    ]
    with pytest.raises(journal_mod.JournalCorruptError):
        D.fold_daemon(records)


def test_fold_daemon_replays_preemption_to_requeue():
    records = [
        {"k": "daemon_start", "t": 0.0, "incarnation": 1},
        {"k": "slice_added", "slice_id": "s0", "t": 0.0},
        {"k": "job_submitted", "job_id": "a", "slices": 1, "seq": 0,
         "digest": "dd", "elastic": True, "t": 1.0},
        {"k": "job_granted", "job_id": "a", "slice_ids": ["s0"], "t": 2.0},
        {"k": "shrink_requested", "job_id": "a", "release_ids": ["s0"],
         "requeue": True, "t": 3.0},
        {"k": "job_preempted", "job_id": "a", "fence_step": 42, "t": 4.0},
    ]
    state = D.fold_daemon(records)
    job = state["jobs"]["a"]
    assert job.state == S.QUEUED and job.resume_step == 42
    assert job.granted == [] and state["pool"].free_count() == 1
    assert state["pool"].get("s0").digest == "dd"   # released warm
    assert state["preemptions"] == 1


# ---------------------------------------------------------------------------
# SimCluster: the 1000-job chaos suite
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_simcluster_1000_jobs_with_preemption_chaos():
    """1000-job seeded trace + seeded preemption chaos through the real
    scheduler.  The harness asserts at every event: no double grant
    (check_invariant), zero committed steps lost or re-done (episode
    tiling + fence-resume equality).  Here we pin the report: every
    job reaches a terminal state, preemption/requeue/warm paths all
    actually fired, and queue-wait p99 stays bounded."""
    trace = generate_trace(seed=7, n_jobs=1000, pool_size=8)
    sc = SimCluster(pool_size=8, chaos_seed=11, cold_bringup_s=2.0,
                    warm_adopt_s=0.05)
    report = sc.run(trace)
    assert report.failed_to_finish == []
    assert report.completed == len(sc.runs)       # trace + chaos probes
    assert report.completed >= 1000
    assert report.preemptions > 20                # chaos really bit
    assert report.requeues > 0                    # full shrink-to-zero path
    total = report.warm_hits + report.cold_grants
    assert report.warm_hits > total // 4          # affinity really works
    assert report.wait_quantile(0.99) < 60.0      # virtual seconds
    assert report.wait_quantile(0.5) <= report.wait_quantile(0.99)


@pytest.mark.chaos
def test_simcluster_is_deterministic_by_seed():
    def run():
        sc = SimCluster(pool_size=6, chaos_seed=3)
        return sc.run(generate_trace(seed=5, n_jobs=300, pool_size=6))
    a, b = run(), run()
    assert (a.completed, a.preemptions, a.requeues, a.warm_hits,
            a.virtual_makespan_s) == \
           (b.completed, b.preemptions, b.requeues, b.warm_hits,
            b.virtual_makespan_s)
    assert a.queue_waits == b.queue_waits


@pytest.mark.chaos
def test_simcluster_user_quota_and_fairness():
    """With a per-user slice cap nobody monopolizes the pool: the run
    still drains fully and every user's p99 wait stays bounded (no
    user starves behind another's backlog)."""
    trace = generate_trace(seed=9, n_jobs=400, pool_size=8, users=4)
    sc = SimCluster(pool_size=8, user_quota=4, chaos_seed=2)
    report = sc.run(trace)
    assert report.failed_to_finish == []
    assert len(report.per_user_waits) >= 4
    for user, waits in report.per_user_waits.items():
        waits = sorted(waits)
        p99 = waits[min(len(waits) - 1, int(0.99 * len(waits)))]
        assert p99 < 120.0, f"user {user} starved: p99={p99}"


# ---------------------------------------------------------------------------
# Daemon: in-process wire plane
# ---------------------------------------------------------------------------
def _daemon(tmp_path, n_slices=2, **kw):
    kw.setdefault("runner", D.OracleRunner())
    kw.setdefault("tick_interval_s", 0.005)
    d = D.ClusterDaemon(str(tmp_path / "home"), slices=n_slices, **kw)
    d.start()
    return d


def _wait(predicate, timeout_s=15.0, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


def test_daemon_wire_submit_status_list_stats_cancel(tmp_path):
    d = _daemon(tmp_path)
    try:
        with D.DaemonClient("127.0.0.1", d.port) as c:
            assert c.hello["daemon_id"] == "cluster-daemon"
            assert c.hello["incarnation"] == 1
            a = c.submit(user="alice", slices=2, digest="dd",
                         payload={"duration_steps": 30})["job_id"]
            b = c.submit(user="bob", slices=1,
                         payload={"duration_steps": 30})["job_id"]
            _wait(lambda: c.status(a)["state"] == S.COMPLETED,
                  msg="job a completion")
            _wait(lambda: c.status(b)["state"] == S.COMPLETED,
                  msg="job b completion")
            jobs = c.list_jobs()
            assert [j["job_id"] for j in jobs] == [a, b]
            st = c.stats()
            assert st["pool_free"] == 2 and st["incarnation"] == 1
            # cancel a queued job
            q = c.submit(user="eve", slices=2, job_id="will-cancel",
                         payload={"duration_steps": 10 ** 6})["job_id"]
            _wait(lambda: c.status(q)["state"] in (S.RUNNING, S.COMPLETED),
                  msg="grant")
            assert c.cancel(q)["state"] in (S.CANCELLED, S.RUNNING)
            _wait(lambda: c.status(q)["state"] == S.CANCELLED,
                  msg="cancellation")
    finally:
        d.stop()


def test_daemon_wire_request_scoped_errors(tmp_path):
    d = _daemon(tmp_path)
    try:
        with D.DaemonClient.from_home(d.home_dir) as c:
            with pytest.raises(D.DaemonError, match="unknown job"):
                c.status("nope")
            c.submit(job_id="dup", payload={"duration_steps": 10 ** 6})
            with pytest.raises(D.DaemonError, match="duplicate"):
                c.submit(job_id="dup")
            with pytest.raises(D.DaemonError, match="wants 99"):
                c.submit(slices=99)
            with pytest.raises(D.DaemonError, match="unknown op"):
                c._op(op="frobnicate")
            # the connection survives every request-scoped failure
            assert c.stats()["pool_size"] == 2
    finally:
        d.stop()


def test_daemon_queue_limit_rejects_submission(tmp_path):
    conf = TonyConfig({K.DAEMON_QUEUE_LIMIT_KEY: "1"})
    d = _daemon(tmp_path, conf=conf)
    try:
        with D.DaemonClient("127.0.0.1", d.port) as c:
            a = c.submit(slices=2,
                         payload={"duration_steps": 10 ** 6})["job_id"]
            _wait(lambda: c.status(a)["state"] == S.RUNNING, msg="grant")
            c.submit(slices=2, payload={"duration_steps": 10 ** 6})
            with pytest.raises(D.DaemonError, match="queue is full"):
                c.submit(slices=2)
    finally:
        d.stop()


def test_daemon_preemption_loses_zero_committed_steps(tmp_path):
    """Wall-clock preemption through the daemon loop: the oracle runner
    itself asserts the victim resumes from exactly its fence step."""
    conf = TonyConfig({K.DAEMON_PREEMPTION_GRACE_MS_KEY: "50"})
    d = _daemon(tmp_path, conf=conf)
    try:
        with D.DaemonClient("127.0.0.1", d.port) as c:
            victim = c.submit(user="low", slices=2, elastic=True,
                              payload={"duration_steps": 500,
                                       "steps_per_s": 100})["job_id"]
            _wait(lambda: c.status(victim)["state"] == S.RUNNING,
                  msg="victim grant")
            urgent = c.submit(user="vip", slices=2, priority=5,
                              payload={"duration_steps": 20,
                                       "steps_per_s": 1000})["job_id"]
            _wait(lambda: c.status(urgent)["state"] == S.COMPLETED,
                  msg="urgent completion")
            v = c.status(victim)
            assert v["preemptions"] == 1
            _wait(lambda: c.status(victim)["state"] == S.COMPLETED,
                  timeout_s=30.0, msg="victim completion")
            # the fence step survived the requeue round-trip
            assert c.status(victim)["resume_step"] > 0
            assert d.registry.counter(
                "tony_sched_preemptions_total").value >= 1
    finally:
        d.stop()


def test_daemon_reaps_idle_slices(tmp_path):
    conf = TonyConfig({K.DAEMON_POOL_IDLE_REAP_MS_KEY: "50"})
    reaped = []
    d = _daemon(tmp_path, conf=conf, on_slice_reaped=reaped.append)
    try:
        _wait(lambda: len(reaped) == 2, msg="idle reap")
        assert d.pool.size() == 0
        replayed = journal_mod.replay(
            D.daemon_journal_path(d.home_dir))
        assert sum(1 for r in replayed if r["k"] == "slice_reaped") == 2
    finally:
        d.stop()


# ---------------------------------------------------------------------------
# Daemon: SIGKILL-mid-grant recovery e2e
# ---------------------------------------------------------------------------
def _spawn_daemon(home, *extra):
    proc = subprocess.Popen(
        [PY, "-m", "tony_tpu.cluster.daemon", "--home", str(home),
         "--slices", "2", *extra],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=REPO)
    line = proc.stdout.readline()
    return proc, json.loads(line)


@pytest.mark.e2e
@pytest.mark.recovery
def test_daemon_sigkill_mid_grant_recovers_from_journal(tmp_path):
    """SIGKILL the daemon while a gang is granted and two jobs queue
    behind it; the restarted daemon must replay the journal into the
    exact same pool/grant/queue — same slice ids, same queue order,
    zero re-provisioned slices — and then drain the queue to
    completion."""
    home = tmp_path / "home"
    proc, hello = _spawn_daemon(home)
    try:
        assert hello["incarnation"] == 1 and not hello["recovered"]
        with D.DaemonClient.from_home(str(home)) as c:
            a = c.submit(user="alice", slices=2, digest="dd",
                         payload={"duration_steps": 600,
                                  "steps_per_s": 100})["job_id"]
            b = c.submit(user="bob", slices=1,
                         payload={"duration_steps": 40,
                                  "steps_per_s": 100})["job_id"]
            cc = c.submit(user="bob", slices=1,
                          payload={"duration_steps": 40,
                                   "steps_per_s": 100})["job_id"]
            _wait(lambda: c.status(a)["state"] == S.RUNNING,
                  msg="grant before kill")
            granted_before = c.status(a)["granted"]
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        journal_before = journal_mod.replay(
            D.daemon_journal_path(str(home)))
        # restart on the same home dir: journal replay, not bootstrap
        proc2, hello2 = _spawn_daemon(home)
        try:
            assert hello2["incarnation"] == 2 and hello2["recovered"]
            with D.DaemonClient.from_home(str(home)) as c:
                snap = {j["job_id"]: j for j in c.list_jobs()}
                assert snap[a]["state"] == S.RUNNING
                assert snap[a]["granted"] == granted_before
                assert snap[b]["state"] == S.QUEUED
                assert snap[cc]["state"] == S.QUEUED
                for jid in (a, b, cc):
                    _wait(lambda j=jid: c.status(j)["state"] == S.COMPLETED,
                          timeout_s=60.0, msg=f"{jid} completion")
                # b (older seq) was granted before cc
                assert (c.status(b)["submitted_at"]
                        < c.status(cc)["submitted_at"])
        finally:
            proc2.send_signal(signal.SIGTERM)
            proc2.wait(timeout=10)
        journal_after = journal_mod.replay(
            D.daemon_journal_path(str(home)))
        added = [r for r in journal_after if r["k"] == "slice_added"]
        assert len(added) == 2                # ZERO re-provisioned slices
        assert len(journal_after) > len(journal_before)
        starts = [r for r in journal_after if r["k"] == "daemon_start"]
        assert len(starts) == 2
        # grants after recovery reuse pooled slice ids only
        pool_ids = {r["slice_id"] for r in added}
        for r in journal_after:
            if r["k"] == "job_granted":
                assert set(r["slice_ids"]) <= pool_ids
    finally:
        if proc.poll() is None:
            proc.kill()


# ---------------------------------------------------------------------------
# History server: cluster dashboard from jhist alone
# ---------------------------------------------------------------------------
def test_history_server_cluster_dashboard_replays_jhist(tmp_path):
    from tony_tpu.history import HistoryServer

    hist = tmp_path / "hist" / "intermediate"
    os.makedirs(hist)
    d = _daemon(tmp_path, history_dir=str(hist))
    try:
        with D.DaemonClient("127.0.0.1", d.port) as c:
            a = c.submit(user="alice", slices=2, digest="dd",
                         payload={"duration_steps": 20})["job_id"]
            _wait(lambda: c.status(a)["state"] == S.COMPLETED,
                  msg="job a")
            b = c.submit(user="alice", slices=2, digest="dd",
                         payload={"duration_steps": 20})["job_id"]
            _wait(lambda: c.status(b)["state"] == S.COMPLETED,
                  msg="job b")
    finally:
        d.stop()      # daemon is GONE; the dashboard replays jhist only
    conf = TonyConfig({
        K.HISTORY_LOCATION_KEY: str(tmp_path / "hist"),
        K.HISTORY_INTERMEDIATE_KEY: str(hist),
        K.HISTORY_FINISHED_KEY: str(tmp_path / "hist" / "finished"),
    })
    server = HistoryServer(conf, port=0)
    state = server.cluster_state()
    assert [x["app_id"] for x in state["daemons"]] == ["cluster-daemon-i1"]
    assert state["states"].get(S.COMPLETED) == 2
    by_id = {j["job_id"]: j for j in state["jobs"]}
    assert by_id[a]["user"] == "alice" and by_id[a]["slices"] == 2
    assert by_id[a]["warm"] is False        # first grant was cold
    assert by_id[b]["warm"] is True         # back-to-back digest match
    assert by_id[b]["warm_hits"] == 2
    # HTTP routes render the same fold
    import urllib.request
    server.start()
    try:
        with urllib.request.urlopen(
                f"http://localhost:{server.port}/cluster", timeout=10) as r:
            page = r.read().decode("utf-8")
        assert a in page and "warm" in page
        with urllib.request.urlopen(
                f"http://localhost:{server.port}/api/cluster",
                timeout=10) as r:
            api = json.loads(r.read().decode("utf-8"))
        assert api["states"] == state["states"]
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# Backend: release-to-pool (never a teardown)
# ---------------------------------------------------------------------------
def test_backend_release_gang_returns_name_and_digest():
    import threading

    from tony_tpu.backend.tpu import TpuSliceBackend

    conf = TonyConfig({
        "tony.scheduler.backend": "tpu", "tony.tpu.project": "p",
        "tony.tpu.zone": "z", "tony.tpu.accelerator-type": "v5litepod",
        "tony.worker.instances": "1", "tony.worker.tpus": "8",
        "tony.worker.tpu.topology": "2x2",
    })
    b = TpuSliceBackend(conf, app_id="app1", dry_run=True)
    b._gangs[("worker", 0)] = {"name": b._slice_name("worker", 0),
                               "ready": threading.Event()}
    b._stage_digest = "sha256-ff"
    name, digest = b.release_gang("worker", 0)
    assert name == b._slice_name("worker", 0)
    assert digest == "sha256-ff"
    assert b._gangs == {}          # stop() will NOT tear the slice down
    assert b.release_all() == []


# ---------------------------------------------------------------------------
# Bench arm pin
# ---------------------------------------------------------------------------
def test_sched_bench_arm_pins_warm_turnover_ratio():
    """bench._sched_arm drives identical 3-job workloads through a real
    daemon with and without digest affinity.  Pin: warm turnover beats
    cold by >= 2x (measured ~5x), and the queue-wait p99 read off
    tony_sched_queue_wait_seconds is sane."""
    sys.path.insert(0, REPO)
    import bench
    res = bench._sched_arm()
    assert res["sched_warm_turnover_vs_cold"] >= 2
    assert res["sched_warm_turnover_s"] > 0
    assert res["sched_cold_turnover_s"] > res["sched_warm_turnover_s"]
    assert res["sched_warm_hits"] >= 4      # jobs 2..3 x 2 slices, warm arm
    assert 0 <= res["sched_queue_wait_p99_s"] < 30
