"""TPU slice backend: command construction + gating (the launch-command unit
tests, mirroring the reference's TestTonyClient.java:23-31 /
TestTonyApplicationMaster.java:13-34 style)."""

import pytest

from tony_tpu.backend.base import LaunchSpec
from tony_tpu.backend.tpu import (TpuProvisioningError, TpuSliceBackend,
                                  slice_name)
from tony_tpu.conf.config import TonyConfig


def tpu_conf(**extra):
    base = {
        "tony.scheduler.backend": "tpu",
        "tony.tpu.project": "my-proj",
        "tony.tpu.zone": "us-central2-b",
        "tony.tpu.accelerator-type": "v5litepod",
        "tony.worker.instances": "2",
        "tony.worker.tpus": "8",
        "tony.worker.tpu.topology": "4x4",
    }
    base.update(extra)
    return TonyConfig(base)


def test_requires_config_when_live():
    with pytest.raises(TpuProvisioningError):
        TpuSliceBackend(TonyConfig({"tony.scheduler.backend": "tpu"}),
                        dry_run=False)


def test_create_command_shape():
    b = TpuSliceBackend(tpu_conf(), app_id="application_1_abc", dry_run=True)
    cmd = b.create_slice_command("worker", "4x4")
    assert cmd[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "create",
                       "tony-application-1-abc-worker"]
    assert "--accelerator-type=v5litepod-16" in cmd  # 4x4 topology → 16 chips
    assert "--project=my-proj" in cmd and "--zone=us-central2-b" in cmd


def test_preemptible_flag():
    b = TpuSliceBackend(tpu_conf(**{"tony.tpu.preemptible": "true"}),
                        app_id="a", dry_run=True)
    assert "--preemptible" in b.create_slice_command("worker", "2x2")


def test_ssh_and_delete_commands():
    b = TpuSliceBackend(tpu_conf(), app_id="app1", dry_run=True)
    ssh = b.ssh_command("worker", 2, "echo hi")
    assert "--worker=2" in ssh and "--command=echo hi" in ssh
    assert slice_name("app1", "worker") in ssh
    delete = b.delete_slice_command("worker")
    assert "delete" in delete and "--async" in delete


def test_dry_run_gang_provisions_once():
    b = TpuSliceBackend(tpu_conf(), app_id="app1", dry_run=True)
    for i in range(4):
        b.launch_task(LaunchSpec(task_id=f"worker:{i}", command="run",
                                 env={}, log_dir="/tmp", tpu_topology="4x4"))
    # one slice (gang) for all 4 hosts of the job type
    assert list(b._gangs) == [("worker", 0)]
    assert b.poll_completed() == []
    b.stop()


def test_multi_slice_gangs():
    """tony.worker.slices=2: two gangs, each its own TPU VM; task index i →
    slice i // hosts_per_slice, ssh --worker = i % hosts_per_slice."""
    conf = tpu_conf(**{"tony.worker.instances": "4",
                       "tony.worker.slices": "2"})
    b = TpuSliceBackend(conf, app_id="app1", dry_run=True)
    assert b._gang_of("worker:0") == ("worker", 0, 0)
    assert b._gang_of("worker:1") == ("worker", 0, 1)
    assert b._gang_of("worker:2") == ("worker", 1, 0)
    assert b._gang_of("worker:3") == ("worker", 1, 1)
    for i in range(4):
        b.launch_task(LaunchSpec(task_id=f"worker:{i}", command="run",
                                 env={}, log_dir="/tmp", tpu_topology="4x4"))
    assert sorted(b._gangs) == [("worker", 0), ("worker", 1)]
    assert b._gangs[("worker", 0)]["name"] == "tony-app1-worker-s0"
    assert b._gangs[("worker", 1)]["name"] == "tony-app1-worker-s1"
    # per-gang commands address the right VM and in-slice host
    ssh = b.ssh_command("worker", 1, "echo hi", slice_idx=1)
    assert "tony-app1-worker-s1" in " ".join(ssh) and "--worker=1" in ssh
    b.stop()


def test_single_slice_names_unsuffixed():
    assert slice_name("a", "worker", 0, 1) == "tony-a-worker"
    assert slice_name("a", "worker", 1, 2) == "tony-a-worker-s1"


def test_relaunch_after_preemption_reprovisions():
    """Regression: a retried session must get a FRESH slice — the old one's
    cached PREEMPTED state was instantly re-failing every relaunched task,
    and stale _reported entries swallowed the new generation's exits."""
    b = TpuSliceBackend(tpu_conf(), app_id="app1", dry_run=True)
    spec = LaunchSpec(task_id="worker:0", command="run", env={},
                      log_dir="/tmp", tpu_topology="4x4")
    b.launch_task(spec)
    # simulate the slice being preempted and the task observed as dead
    b._state_cache[("worker", 0)] = "PREEMPTED"
    b._state_ts[("worker", 0)] = float("inf")   # keep the cache "fresh"
    b._reported.add("worker:0")
    old_slice = b._gangs[("worker", 0)]["name"]
    b.launch_task(spec)                      # session retry relaunch
    assert "worker:0" not in b._reported
    assert b._state_cache.get(("worker", 0)) != "PREEMPTED"
    assert b._gangs[("worker", 0)]["name"] == old_slice  # same name, freshly provisioned
    assert b.poll_completed() == []          # no instant preempted re-fail
    b.stop()


def test_delete_command_wait_mode():
    b = TpuSliceBackend(tpu_conf(), app_id="app1", dry_run=True)
    assert "--async" not in b.delete_slice_command("worker", wait=True)


def test_slice_name_sanitized_and_bounded():
    n = slice_name("application_1785325254085_2d827d" * 3, "worker")
    assert "_" not in n and len(n) <= 61


def test_node_label_attached_to_slice(tmp_path):
    from tony_tpu.conf.config import TonyConfig
    from tony_tpu.backend.tpu import TpuSliceBackend
    conf = TonyConfig({"tony.tpu.project": "p", "tony.tpu.zone": "z",
                       "tony.tpu.accelerator-type": "v5litepod",
                       "tony.application.node-label": "batch-pool"})
    b = TpuSliceBackend(conf, app_id="app1", dry_run=True)
    cmd = b.create_slice_command("worker", "2x4")
    assert "--labels=tony-node-label=batch-pool" in cmd


def test_stage_commands_scp_mode():
    """Default transport: tarball over scp to every worker, strict unpack."""
    b = TpuSliceBackend(tpu_conf(), app_id="app1", dry_run=True)
    cmds = b.stage_commands("worker", "/jobs/app1")
    assert len(cmds) == 2
    scp, unpack = cmds
    assert scp[4] == "scp" and scp[5] == "/jobs/app1/.tony-stage.tgz"
    assert scp[6].endswith(":/tmp/tony-stage.tgz")
    assert "--worker=all" in scp
    unpack_cmd = unpack[-1]
    assert unpack_cmd.startswith("--command=")
    assert "tar -xzf /tmp/tony-stage.tgz -C ~/tony-job" in unpack_cmd
    assert "mkdir -p ~/tony-job" in unpack_cmd


def test_stage_commands_gs_pull_mode():
    """When the client staged to gs://, hosts pull with gsutil rsync."""
    conf = tpu_conf()
    conf.set("tony.staging.remote-job-dir", "gs://bkt/staging/app1")
    b = TpuSliceBackend(conf, app_id="app1", dry_run=True)
    cmds = b.stage_commands("worker", "/spool/app1")
    assert len(cmds) == 1
    (pull,) = cmds
    assert "--worker=all" in pull
    assert "gsutil -m rsync -r gs://bkt/staging/app1 ~/tony-job" in pull[-1]


def test_launch_command_runs_in_remote_job_dir(caplog):
    """The remote command must cd into the staged job dir (strictly) and
    lead PYTHONPATH with the staged framework copy."""
    import logging
    b = TpuSliceBackend(tpu_conf(), app_id="app1", dry_run=True)
    spec = LaunchSpec(task_id="worker:0", command="python3 -m x",
                      env={"JOB_NAME": "worker"}, log_dir="/tmp",
                      cwd="", tpu_topology="2x4")
    with caplog.at_level(logging.INFO, logger="tony_tpu.backend.tpu"):
        b.launch_task(spec)
    launches = [r.getMessage() for r in caplog.records
                if "--command=" in r.getMessage()
                and "cd ~/tony-job" in r.getMessage()]
    assert launches, [r.getMessage() for r in caplog.records]
    assert "cd ~/tony-job &&" in launches[-1]
    assert "export PYTHONPATH=~/tony-job/.tony-framework" in launches[-1]


def test_stage_commands_ship_tls_cert_not_key(tmp_path):
    """With TLS on, the PUBLIC cert is scp'd to hosts; the private key
    must never appear in the staging plan (it stays with the
    coordinator)."""
    from tony_tpu.rpc.tls import generate_self_signed
    job_dir = str(tmp_path)
    generate_self_signed(job_dir)
    b = TpuSliceBackend(tpu_conf(), app_id="app1", dry_run=True)
    cmds = b.stage_commands("worker", job_dir)
    flat = " ".join(" ".join(c) for c in cmds)
    assert ".tony-tls.crt" in flat
    assert ".tony-tls.key" not in flat


def test_launch_exports_tls_cert_path(caplog):
    """The remote launch wrapper must export TONY_TLS_CERT from the
    staged cert — and the coordinator-LOCAL path in spec.env must NOT
    ride the command as a K=V prefix (it would override the export with
    a path that does not exist on the slice host)."""
    import logging
    b = TpuSliceBackend(tpu_conf(), app_id="app1", dry_run=True)
    spec = LaunchSpec(task_id="worker:0", command="python3 -m x",
                      env={"JOB_NAME": "worker",
                           "TONY_TLS_CERT": "/submit/host/.tony-tls.crt"},
                      log_dir="/tmp", cwd="", tpu_topology="2x4")
    with caplog.at_level(logging.INFO, logger="tony_tpu.backend.tpu"):
        b.launch_task(spec)
    launches = [r.getMessage() for r in caplog.records
                if "--command=" in r.getMessage()]
    assert launches
    assert ("[ -f ~/tony-job/.tony-tls.crt ] && "
            "export TONY_TLS_CERT=~/tony-job/.tony-tls.crt" in launches[-1])
    assert "/submit/host/.tony-tls.crt" not in launches[-1]
