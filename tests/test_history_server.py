"""History server tests (mirrors the reference's Play controller tests in
tony-history-server/test/controllers/): index listing, intermediate→finished
migration, per-job events/config pages, JSON API, caching, retention."""

import json
import os
import time
import urllib.error
import urllib.request
from html.parser import HTMLParser

import pytest

from tony_tpu.conf import keys as K
from tony_tpu.conf.config import TonyConfig
from tony_tpu.events.events import EventHandler, history_file_name
from tony_tpu.history import (HistoryDirs, HistoryServer, TTLCache,
                              migrate_finished, purge_expired)
from tony_tpu.history.server import config_file_name


def _write_job(intermediate: str, app_id: str, status: str = "SUCCEEDED",
               user: str = "alice", with_config: bool = True) -> str:
    """Write a complete jhist (+ config) via the real EventHandler."""
    handler = EventHandler(intermediate, app_id, user)
    handler.start()
    handler.emit("APPLICATION_INITED", app_id=app_id, num_tasks=2,
                 host="localhost")
    handler.emit("APPLICATION_FINISHED", app_id=app_id,
                 failed=status != "SUCCEEDED")
    path = handler.stop(status)
    if with_config:
        conf = TonyConfig({"tony.worker.instances": "2",
                           "tony.application.name": app_id})
        conf.write_xml(os.path.join(intermediate, config_file_name(app_id)))
    return path


@pytest.fixture
def dirs(tmp_path):
    d = HistoryDirs(str(tmp_path / "hist"),
                    str(tmp_path / "hist" / "intermediate"),
                    str(tmp_path / "hist" / "finished"))
    d.ensure()
    return d


@pytest.fixture
def server(dirs):
    conf = TonyConfig({
        K.HISTORY_LOCATION_KEY: dirs.location,
        K.HISTORY_INTERMEDIATE_KEY: dirs.intermediate,
        K.HISTORY_FINISHED_KEY: dirs.finished,
    })
    s = HistoryServer(conf, port=0)
    s.start()
    yield s
    s.stop()


def _get(server, path):
    with urllib.request.urlopen(
            f"http://localhost:{server.port}{path}", timeout=10) as r:
        return r.status, r.read().decode("utf-8")


def test_migration_moves_finished_to_dated_dirs(dirs):
    """Reference: JobsMetadataPageController.java:49-72 moves completed jobs
    intermediate → finished/yyyy/mm/dd; in-progress jobs stay."""
    _write_job(dirs.intermediate, "application_1_0001")
    # an in-progress job (no completed ts, .inprogress suffix) must NOT move
    inprog = os.path.join(
        dirs.intermediate,
        history_file_name("application_1_0002", int(time.time() * 1000),
                          "bob", in_progress=True))
    with open(inprog, "w", encoding="utf-8"):
        pass
    moved = migrate_finished(dirs)
    assert len(moved) == 1
    # dated layout finished/yyyy/mm/dd/<name>
    rel = os.path.relpath(moved[0], dirs.finished)
    parts = rel.split(os.sep)
    assert len(parts) == 4 and all(p.isdigit() for p in parts[:3])
    # config moved alongside
    assert os.path.exists(os.path.join(
        os.path.dirname(moved[0]), config_file_name("application_1_0001")))
    assert os.path.exists(inprog)
    assert not os.path.exists(os.path.join(
        dirs.intermediate, os.path.basename(moved[0])))


def test_index_lists_jobs_and_migrates(server, dirs):
    _write_job(dirs.intermediate, "application_2_0001")
    _write_job(dirs.intermediate, "application_2_0002", status="FAILED",
               user="bob")
    status, body = _get(server, "/")
    assert status == 200
    assert "application_2_0001" in body and "application_2_0002" in body
    assert "FAILED" in body and "SUCCEEDED" in body
    # index load migrated them out of intermediate
    assert not any(n.endswith(".jhist")
                   for n in os.listdir(dirs.intermediate))


def test_events_page_and_api(server, dirs):
    _write_job(dirs.intermediate, "application_3_0001")
    status, body = _get(server, "/jobs/application_3_0001")
    assert status == 200
    assert "APPLICATION_INITED" in body and "APPLICATION_FINISHED" in body
    status, body = _get(server, "/api/jobs/application_3_0001/events")
    events = json.loads(body)
    assert [e["event_type"] for e in events] == [
        "APPLICATION_INITED", "APPLICATION_FINISHED"]
    assert events[0]["payload"]["num_tasks"] == 2


def test_config_page_and_api(server, dirs):
    _write_job(dirs.intermediate, "application_4_0001")
    status, body = _get(server, "/config/application_4_0001")
    assert status == 200 and "tony.worker.instances" in body
    status, body = _get(server, "/api/jobs/application_4_0001/config")
    assert json.loads(body)["tony.worker.instances"] == "2"


class _PageParser(HTMLParser):
    """Structural HTML reader for the three pages: tables as row-lists of
    cell texts, plus every link's (href, text) — the BrowserTest analog
    (reference: tony-history-server/test/controllers), so markup
    regressions fail the suite instead of passing substring checks."""

    def __init__(self):
        super().__init__()
        self.tables: list[list[list[str]]] = []
        self.links: list[tuple[str, str]] = []
        self._row: list[str] | None = None
        self._cell: list[str] | None = None
        self._href: str | None = None
        self._link_text: list[str] = []

    def handle_starttag(self, tag, attrs):
        if tag == "table":
            self.tables.append([])
        elif tag == "tr" and self.tables:
            self._row = []
            self.tables[-1].append(self._row)
        elif tag in ("td", "th") and self._row is not None:
            self._cell = []
        elif tag == "a":
            self._href = dict(attrs).get("href", "")
            self._link_text = []

    def handle_endtag(self, tag):
        if tag in ("td", "th") and self._cell is not None:
            self._row.append("".join(self._cell).strip())
            self._cell = None
        elif tag == "tr":
            self._row = None
        elif tag == "a" and self._href is not None:
            self.links.append((self._href, "".join(self._link_text)))
            self._href = None

    def handle_data(self, data):
        if self._cell is not None:
            self._cell.append(data)
        if self._href is not None:
            self._link_text.append(data)


def _parse(body: str) -> _PageParser:
    p = _PageParser()
    p.feed(body)
    return p


def test_index_page_structure(server, dirs):
    """The job index renders a real table: one row per job with the
    declared columns, the app id as a link to its events page, and a
    config link — not just the strings somewhere in the markup."""
    _write_job(dirs.intermediate, "application_9_0001")
    _write_job(dirs.intermediate, "application_9_0002", status="FAILED",
               user="bob")
    _, body = _get(server, "/")
    page = _parse(body)
    assert len(page.tables) == 1
    header, *rows = page.tables[0]
    assert header == ["Job", "User", "Started (UTC)", "Completed (UTC)",
                      "Status", "Uptime", ""]
    assert len(rows) == 2
    by_id = {r[0]: r for r in rows}
    assert set(by_id) == {"application_9_0001", "application_9_0002"}
    assert by_id["application_9_0001"][1] == "alice"
    assert by_id["application_9_0001"][4] == "SUCCEEDED"
    assert by_id["application_9_0002"][1] == "bob"
    assert by_id["application_9_0002"][4] == "FAILED"
    # every row's cells populated (timestamps render, uptime non-empty)
    for r in rows:
        assert all(c for c in r[:6]), r
    assert ("/jobs/application_9_0001",
            "application_9_0001") in page.links
    assert ("/config/application_9_0002", "config") in page.links


def test_events_page_structure(server, dirs):
    """The event timeline is a table ordered by timestamp with the
    declared columns and a back-link to the index."""
    _write_job(dirs.intermediate, "application_9_0003")
    _, body = _get(server, "/jobs/application_9_0003")
    page = _parse(body)
    assert len(page.tables) == 1
    header, *rows = page.tables[0]
    assert header == ["Time (UTC)", "Event", "Payload"]
    assert [r[1] for r in rows] == ["APPLICATION_INITED",
                                    "APPLICATION_FINISHED"]
    # timeline ordered by the rendered timestamps
    times = [r[0] for r in rows]
    assert times == sorted(times) and all(times)
    assert ("/", "← all jobs") in page.links


def test_config_page_structure(server, dirs):
    """The config table renders key/value CELLS (sorted by key), not
    merely the substrings."""
    _write_job(dirs.intermediate, "application_9_0004")
    _, body = _get(server, "/config/application_9_0004")
    page = _parse(body)
    assert len(page.tables) == 1
    header, *rows = page.tables[0]
    assert header == ["Key", "Value"]
    as_dict = {k: v for k, v in rows}
    assert as_dict["tony.worker.instances"] == "2"
    assert as_dict["tony.application.name"] == "application_9_0004"
    keys = [k for k, _ in rows]
    assert keys == sorted(keys)


def test_unknown_job_404(server):
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(server, "/jobs/no_such_app")
    assert exc.value.code == 404


def test_api_jobs_json(server, dirs):
    _write_job(dirs.intermediate, "application_5_0001")
    status, body = _get(server, "/api/jobs")
    jobs = json.loads(body)
    assert len(jobs) == 1
    assert jobs[0]["app_id"] == "application_5_0001"
    assert jobs[0]["status"] == "SUCCEEDED"
    assert jobs[0]["user"] == "alice"


def test_retention_purges_old_finished(dirs):
    """Files completed before the retention window are deleted."""
    old_ms = int((time.time() - 7200) * 1000)
    name = history_file_name("application_6_0001", old_ms - 1000, "alice",
                             completed_ms=old_ms, status="SUCCEEDED")
    dest = os.path.join(dirs.finished, "2020", "01", "01")
    os.makedirs(dest)
    with open(os.path.join(dest, name), "w", encoding="utf-8"):
        pass
    assert purge_expired(dirs, retention_s=3600) == 1
    assert not os.path.exists(os.path.join(dest, name))
    # fresh file survives
    fresh = history_file_name("application_6_0002",
                              int(time.time() * 1000) - 1000, "alice",
                              completed_ms=int(time.time() * 1000),
                              status="SUCCEEDED")
    with open(os.path.join(dest, fresh), "w", encoding="utf-8"):
        pass
    assert purge_expired(dirs, retention_s=3600) == 0
    assert os.path.exists(os.path.join(dest, fresh))


def test_ttl_cache_memoises_and_expires():
    calls = []
    cache = TTLCache(ttl_s=0.2)
    assert cache.get_or_load("k", lambda: calls.append(1) or "v") == "v"
    assert cache.get_or_load("k", lambda: calls.append(1) or "v2") == "v"
    assert len(calls) == 1
    time.sleep(0.25)
    assert cache.get_or_load("k", lambda: calls.append(1) or "v2") == "v2"
    assert len(calls) == 2


def test_stale_inprogress_does_not_shadow_completed(server, dirs):
    """A crashed coordinator attempt leaves <app>.jhist.inprogress; once the
    retry writes a completed jhist, the completed record must win and the
    ghost file must be cleaned up."""
    app = "application_7_0001"
    stale = os.path.join(
        dirs.intermediate,
        history_file_name(app, int(time.time() * 1000) - 5000, "alice",
                          in_progress=True))
    with open(stale, "w", encoding="utf-8"):
        pass
    _write_job(dirs.intermediate, app)
    _, body = _get(server, "/api/jobs")
    jobs = [j for j in json.loads(body) if j["app_id"] == app]
    assert len(jobs) == 1
    assert jobs[0]["status"] == "SUCCEEDED"
    assert not os.path.exists(stale)
    # events page serves the completed run
    _, body = _get(server, f"/api/jobs/{app}/events")
    assert [e["event_type"] for e in json.loads(body)] == [
        "APPLICATION_INITED", "APPLICATION_FINISHED"]


def test_relative_history_conf_frozen_absolute(tmp_path, monkeypatch):
    """Client must absolutize ALL history dirs (location, intermediate,
    finished) before freezing the config."""
    from tony_tpu.client.client import TonyClient
    monkeypatch.chdir(tmp_path)
    conf = TonyConfig({"tony.staging.dir": str(tmp_path / "staging"),
                       "tony.history.intermediate": "my-hist/inter"})
    client = TonyClient(conf, "true")
    client.stage()
    assert conf.get(K.HISTORY_INTERMEDIATE_KEY) == str(
        tmp_path / "my-hist" / "inter")
    assert os.path.isabs(conf.get(K.HISTORY_LOCATION_KEY))
    assert os.path.isabs(conf.get(K.HISTORY_FINISHED_KEY))


def test_concurrent_index_loads_race_free(dirs):
    """Concurrent scans must not 500 when both observe the same pre-migration
    snapshot (reference behavior: moves happen inside request handling)."""
    import threading
    conf = TonyConfig({K.HISTORY_LOCATION_KEY: dirs.location,
                       K.HISTORY_INTERMEDIATE_KEY: dirs.intermediate,
                       K.HISTORY_FINISHED_KEY: dirs.finished})
    s = HistoryServer(conf, port=0)
    for i in range(20):
        _write_job(dirs.intermediate, f"application_8_{i:04d}",
                   with_config=False)
    errs = []

    def scan():
        try:
            # bypass the TTL cache so both threads really scan
            s._scan_jobs()
        except Exception as e:  # noqa: BLE001 - recording any failure
            errs.append(e)

    threads = [threading.Thread(target=scan) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(s.list_jobs()) == 20


def test_index_shows_uptime_column(tmp_path):
    """The index surfaces the tracked-uptime fraction from the final event."""
    import json as _json
    import time as _time
    from tony_tpu.conf.config import TonyConfig
    from tony_tpu.events import events as ev
    from tony_tpu.history.server import HistoryServer

    hist = tmp_path / "hist"
    handler = ev.EventHandler(str(hist / "intermediate"), "application_9_0001",
                              "alice")
    handler.start()
    handler.emit(ev.APPLICATION_INITED, app_id="application_9_0001",
                 num_tasks=1, host="h")
    handler.emit(ev.APPLICATION_FINISHED, app_id="application_9_0001",
                 status="SUCCEEDED", failed_tasks=[],
                 metrics={"tracked_uptime_fraction": 0.957,
                          "task_uptime_s": {"worker:0": 3.2},
                          "session_wall_s": 3.4, "tracked_window_s": 3.2})
    handler.stop("SUCCEEDED")
    conf = TonyConfig({"tony.history.location": str(hist)})
    server = HistoryServer(conf, port=0)
    page = server._render_index()
    assert "<th>Uptime</th>" in page
    assert "95.7%" in page


# ---------------------------------------------------------------------------
# Metrics plane: /metrics (live Prometheus) + /api/jobs/<id>/metrics (replay)
# ---------------------------------------------------------------------------

def _snapshot_wire(tokens=100, rss=64 << 20):
    from tony_tpu.runtime import metrics as M
    reg = M.MetricsRegistry()
    reg.counter("tony_serve_tokens_total", help="useful generated tokens"
                ).inc(tokens)
    reg.gauge("tony_process_rss_bytes", help="resident set size").set(rss)
    reg.histogram("tony_train_step_seconds", help="step wall",
                  buckets=(0.1, 1.0)).observe(0.5)
    return reg.to_wire()


def _wait_for(predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return False


def _check_exposition(text):
    """Prometheus text-format sanity: every TYPE appears once, every
    sample line is `name{labels} value` with a numeric value, and no
    series repeats."""
    types, series = {}, []
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert name not in types, f"duplicate TYPE for {name}"
            assert kind in ("counter", "gauge", "histogram")
            types[name] = kind
        elif line.startswith("# HELP ") or not line.strip():
            continue
        else:
            series.append(line)
            float(line.rpartition(" ")[2])
    keys = [s.rpartition(" ")[0] for s in series]
    assert len(set(keys)) == len(keys), "duplicate series"
    return types, series


def test_metrics_route_live_then_replay(server, dirs):
    """A RUNNING job's heartbeat-shipped snapshots are served live on
    /metrics (from the flushed .inprogress jhist) and, once the job
    finishes, /api/jobs/<id>/metrics reconstructs the same series purely
    from the METRICS_SNAPSHOT events."""
    from tony_tpu.events import events as ev
    app = "application_m_0001"
    handler = EventHandler(dirs.intermediate, app, "alice")
    handler.start()
    handler.emit(ev.APPLICATION_INITED, app_id=app, num_tasks=1, host="h")
    wire_w0 = _snapshot_wire(tokens=100)
    wire_am = _snapshot_wire(tokens=0, rss=32 << 20)
    handler.emit(ev.METRICS_SNAPSHOT, tasks={"worker:0": wire_w0},
                 session_id=0)
    final_tasks = {"worker:0": _snapshot_wire(tokens=250),
                   "am:0": wire_am}
    handler.emit(ev.METRICS_SNAPSHOT, tasks=final_tasks, session_id=0)
    # the async writer flushes per event — wait until all three landed
    inprog = handler._inprogress_path
    assert _wait_for(lambda: os.path.exists(inprog) and
                     open(inprog).read().count("METRICS_SNAPSHOT") == 2)

    status, text = _get(server, "/metrics")
    assert status == 200
    types, series = _check_exposition(text)
    assert types["tony_serve_tokens_total"] == "counter"
    assert types["tony_train_step_seconds"] == "histogram"
    assert (f'tony_serve_tokens_total{{job="{app}",task="worker:0"}} 250'
            in text)
    assert f'tony_process_rss_bytes{{job="{app}",task="am:0"}}' in text
    assert f'tony_train_step_seconds_bucket{{job="{app}",le="+Inf"' in text
    assert 'tony_history_jobs{state="running"} 1' in text

    # finish the job; replay must reconstruct the SAME series from jhist
    handler.stop("SUCCEEDED")
    server.metadata_cache.invalidate_all()
    server.events_cache.invalidate_all()
    status, body = _get(server, f"/api/jobs/{app}/metrics")
    assert status == 200
    m = json.loads(body)
    assert m["snapshot_count"] == 2
    assert m["tasks"] == final_tasks          # latest snapshot, bit-exact
    assert m["snapshots"][0]["tasks"] == {"worker:0": wire_w0}
    # a finished job no longer exports live series
    _, text = _get(server, "/metrics")
    assert "tony_serve_tokens_total" not in text
    assert 'tony_history_jobs{state="finished"} 1' in text


def test_job_page_renders_metrics_section(server, dirs):
    from tony_tpu.events import events as ev
    app = "application_m_0002"
    handler = EventHandler(dirs.intermediate, app, "alice")
    handler.start()
    handler.emit(ev.APPLICATION_INITED, app_id=app, num_tasks=1, host="h")
    handler.emit(ev.METRICS_SNAPSHOT,
                 tasks={"worker:0": _snapshot_wire(tokens=42)},
                 session_id=0)
    handler.emit(ev.APPLICATION_FINISHED, app_id=app, status="SUCCEEDED")
    handler.stop("SUCCEEDED")
    _, body = _get(server, f"/jobs/{app}")
    page = _parse(body)
    # events table (snapshot rows excluded from the timeline) + metrics
    assert len(page.tables) == 2
    event_rows = [r[1] for r in page.tables[0][1:]]
    assert "METRICS_SNAPSHOT" not in event_rows
    header, *rows = page.tables[1]
    assert header == ["Task", "Metric", "Labels", "Value"]
    by_metric = {(r[0], r[1]): r[3] for r in rows}
    assert by_metric[("worker:0", "tony_serve_tokens_total")] == "42"


def test_job_metrics_replay_capped(server, dirs):
    """The JSON replay truncates to the newest MAX_METRICS_SNAPSHOTS
    while snapshot_count reports the untruncated total and `tasks` stays
    the LATEST snapshot."""
    from tony_tpu.events import events as ev
    app = "application_m_0005"
    handler = EventHandler(dirs.intermediate, app, "alice")
    handler.start()
    for i in range(5):
        handler.emit(ev.METRICS_SNAPSHOT,
                     tasks={"worker:0": _snapshot_wire(tokens=i)},
                     session_id=0)
    handler.stop("SUCCEEDED")
    server.MAX_METRICS_SNAPSHOTS = 3
    try:
        _, body = _get(server, f"/api/jobs/{app}/metrics")
    finally:
        del server.MAX_METRICS_SNAPSHOTS     # restore class default
    m = json.loads(body)
    assert m["snapshot_count"] == 5
    assert len(m["snapshots"]) == 3
    counters = {n: v for n, _, v in m["tasks"]["worker:0"]["c"]}
    assert counters["tony_serve_tokens_total"] == 4     # the latest
    # the kept window is the NEWEST three, oldest-first
    kept = [dict((n, v) for n, _, v in s["tasks"]["worker:0"]["c"])
            ["tony_serve_tokens_total"] for s in m["snapshots"]]
    assert kept == [2, 3, 4]


def test_job_metrics_api_no_snapshots_and_404(server, dirs):
    _write_job(dirs.intermediate, "application_m_0003")
    status, body = _get(server, "/api/jobs/application_m_0003/metrics")
    assert status == 200
    m = json.loads(body)
    assert m["snapshot_count"] == 0 and m["tasks"] == {}
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(server, "/api/jobs/no_such_app/metrics")
    assert exc.value.code == 404


def test_metrics_route_skips_malformed_snapshots(server, dirs):
    """A corrupted snapshot in the event stream must not 500 /metrics —
    the bad task is skipped, good tasks still render."""
    from tony_tpu.events import events as ev
    app = "application_m_0004"
    handler = EventHandler(dirs.intermediate, app, "bob")
    handler.start()
    handler.emit(ev.METRICS_SNAPSHOT,
                 tasks={"worker:0": {"c": "corrupt"},
                        "worker:1": _snapshot_wire(tokens=9)},
                 session_id=0)
    inprog = handler._inprogress_path
    assert _wait_for(lambda: os.path.exists(inprog) and
                     "METRICS_SNAPSHOT" in open(inprog).read())
    status, text = _get(server, "/metrics")
    assert status == 200
    _check_exposition(text)
    assert 'task="worker:1"' in text and 'task="worker:0"' not in text
    handler.stop("FAILED")


def test_bearer_token_auth(dirs, tmp_path):
    """With a token configured, every route except /healthz needs
    `Authorization: Bearer <token>`; wrong/missing tokens get 401."""
    conf = TonyConfig({
        K.HISTORY_LOCATION_KEY: dirs.location,
        K.HISTORY_INTERMEDIATE_KEY: dirs.intermediate,
        K.HISTORY_FINISHED_KEY: dirs.finished,
        K.HISTORY_SERVER_TOKEN_KEY: "s3cret",
    })
    s = HistoryServer(conf, port=0)
    s.start()
    try:
        def status(path, token=None):
            req = urllib.request.Request(
                f"http://localhost:{s.port}{path}")
            if token:
                req.add_header("Authorization", f"Bearer {token}")
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    return r.status
            except urllib.error.HTTPError as e:
                return e.code
        assert status("/") == 401
        assert status("/api/jobs") == 401
        assert status("/metrics") == 401          # scrapes need the token
        assert status("/api/jobs", token="wrong") == 401
        assert status("/healthz") == 200          # probes stay open
        assert status("/", token="s3cret") == 200
        assert status("/api/jobs", token="s3cret") == 200
    finally:
        s.stop()


def test_token_file_and_bind_default(dirs, tmp_path):
    tf = tmp_path / "token"
    tf.write_text("from-file\n")
    conf = TonyConfig({
        K.HISTORY_LOCATION_KEY: dirs.location,
        K.HISTORY_INTERMEDIATE_KEY: dirs.intermediate,
        K.HISTORY_FINISHED_KEY: dirs.finished,
        K.HISTORY_SERVER_TOKEN_FILE_KEY: str(tf),
    })
    s = HistoryServer(conf, port=0)
    assert s.token == "from-file"       # file wins, whitespace stripped
    assert s.bind == "127.0.0.1"        # loopback unless configured
    with pytest.raises(ValueError, match="empty"):
        empty = tmp_path / "empty"
        empty.write_text("")
        HistoryServer(TonyConfig({
            K.HISTORY_LOCATION_KEY: dirs.location,
            K.HISTORY_INTERMEDIATE_KEY: dirs.intermediate,
            K.HISTORY_FINISHED_KEY: dirs.finished,
            K.HISTORY_SERVER_TOKEN_FILE_KEY: str(empty),
        }), port=0)


def test_https_serves_and_rejects_plaintext(dirs, tmp_path):
    """tony.history.server.tls-cert/key → HTTPS (the reference's
    tony.https.* keystore analog): https with the pinned cert works,
    plain-http requests fail the handshake."""
    import ssl
    from tony_tpu.rpc.tls import generate_self_signed
    key, cert = generate_self_signed(str(tmp_path))
    conf = TonyConfig({
        K.HISTORY_LOCATION_KEY: dirs.location,
        K.HISTORY_INTERMEDIATE_KEY: dirs.intermediate,
        K.HISTORY_FINISHED_KEY: dirs.finished,
        K.HISTORY_SERVER_TLS_CERT_KEY: cert,
        K.HISTORY_SERVER_TLS_KEY_KEY: key,
    })
    s = HistoryServer(conf, port=0)
    s.start()
    try:
        ctx = ssl.create_default_context(cafile=cert)
        ctx.check_hostname = False     # per-job cert names tony-coordinator
        with urllib.request.urlopen(
                f"https://localhost:{s.port}/healthz", timeout=10,
                context=ctx) as r:
            assert r.status == 200
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://localhost:{s.port}/healthz", timeout=5)
    finally:
        s.stop()


def test_https_requires_both_cert_and_key(dirs, tmp_path):
    from tony_tpu.rpc.tls import generate_self_signed
    _, cert = generate_self_signed(str(tmp_path))
    conf = TonyConfig({
        K.HISTORY_LOCATION_KEY: dirs.location,
        K.HISTORY_INTERMEDIATE_KEY: dirs.intermediate,
        K.HISTORY_FINISHED_KEY: dirs.finished,
        K.HISTORY_SERVER_TLS_CERT_KEY: cert,
    })
    s = HistoryServer(conf, port=0)
    with pytest.raises(ValueError, match="BOTH"):
        s.start()


def test_malformed_jhist_tail_logs_and_does_not_500(dirs, server, caplog):
    """One corrupt log must not 500 the whole index (the uptime column
    degrades to "-") — and TL005 behaviorally: the swallow leaves
    evidence in the server log instead of hiding the corrupt file."""
    import logging

    path = _write_job(dirs.intermediate, "application_7_0001")
    # corrupt the tail: a FINISHED-looking line that is not valid JSON
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"event_type": "APPLICATION_FINISHED" oops\n')
    with caplog.at_level(logging.WARNING, logger="tony_tpu.history.server"):
        status, body = _get(server, "/")
    assert status == 200
    assert "application_7_0001" in body
    assert any("unreadable jhist tail" in r.message
               for r in caplog.records), caplog.records
