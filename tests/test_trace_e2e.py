"""End-to-end distributed tracing plane: real local-backend jobs whose
spans cross processes, ride heartbeats into TRACE_SPAN jhist events, and
export as Chrome-trace JSON — plus the flight recorder's postmortem
artifacts (the acceptance path of the tracing issue)."""

import json
import glob
import os
import sys
import urllib.request

import pytest

from tony_tpu.client.client import TonyClient
from tony_tpu.conf.config import TonyConfig
from tony_tpu.events import events as ev
from tony_tpu.history.server import HistoryServer
from tony_tpu.runtime import tracing

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


def _make_client(tmp_path, command, confs=None, shell_env=None):
    base = {
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.location": str(tmp_path / "tony-history"),
        "tony.application.timeout": "150000",
        "tony.task.heartbeat-interval-ms": "100",
        "tony.metrics.snapshot-interval-ms": "200",
    }
    base.update(confs or {})
    return TonyClient(TonyConfig(base), command, shell_env=shell_env)


def _job_spans(hist_dir):
    """Every span from every TRACE_SPAN event across the job's jhist,
    annotated with the emitting task."""
    spans = []
    for path in ev.find_job_files(hist_dir):
        for e in ev.parse_events(path):
            if e.event_type != ev.TRACE_SPAN:
                continue
            for s in e.payload.get("spans", []):
                tracing.validate_span(s)
                spans.append({**s, "_task": e.payload.get("task")})
    return spans


def _http_json(port, path):
    with urllib.request.urlopen(
            f"http://localhost:{port}{path}", timeout=10) as r:
        assert r.status == 200
        return json.loads(r.read().decode("utf-8"))


@pytest.mark.e2e
def test_serving_request_trace_crosses_processes(tmp_path):
    """A streaming serving request traced end to end across two real
    processes: the jax-free client (driver task) roots the trace, its
    context rides the ADMIT frame, and the engine task's TTFT
    decomposition (engine.queued -> engine.first_token within
    engine.request) lands under the SAME 128-bit trace id — exported as
    valid Chrome trace JSON by the history server."""
    hist = str(tmp_path / "tony-history")
    engine = os.path.join(FIXTURES, "serve_engine_fixture.py")
    driver = os.path.join(FIXTURES, "stream_client_fixture.py")
    client = _make_client(
        tmp_path, "echo unused-job-wide-command",
        {"tony.engine.instances": "1",
         "tony.driver.instances": "1",
         "tony.engine.program": f"{PY} {engine}",
         "tony.driver.program": f"{PY} {driver}"},
        shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
                   "XLA_FLAGS": ""})
    assert client.run() == 0

    spans = _job_spans(hist)
    by_tid = {}
    for s in spans:
        by_tid.setdefault(s["tid"], []).append(s)
    roots = [s for s in spans if s["n"] == "client.request"]
    assert roots, f"no client.request span in {sorted({s['n'] for s in spans})}"
    trace = by_tid[roots[0]["tid"]]
    names = {s["n"] for s in trace}
    # the TTFT decomposition, one trace id, >= 2 processes
    assert {"client.request", "client.ttft", "engine.request",
            "engine.queued", "engine.first_token"} <= names, names
    procs = {s["proc"] for s in trace}
    assert len(procs) >= 2, procs
    assert any(p.startswith("driver:0") for p in procs), procs
    assert any(p.startswith("engine:0") for p in procs), procs
    # parent links: engine.request is a child of the client's span
    by_sid = {s["sid"]: s for s in trace}
    eng_req = next(s for s in trace if s["n"] == "engine.request")
    assert by_sid[eng_req["pid"]]["n"] == "client.request"

    # export: GET /api/jobs/<id>/trace is Chrome-trace JSON carrying
    # the same cross-process request
    server = HistoryServer(TonyConfig({"tony.history.location": hist}),
                           port=0)
    server.start()
    try:
        chrome = _http_json(server.port,
                            f"/api/jobs/{client.app_id}/trace")
        events = chrome["traceEvents"]
        assert events and chrome.get("displayTimeUnit") == "ms"
        xs = [e for e in events if e["ph"] == "X"]
        for e in xs:
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float))
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        tid0 = roots[0]["tid"]
        exported = [e for e in xs if e["args"].get("trace_id") == tid0]
        assert {"client.request", "engine.first_token"} <= {
            e["name"] for e in exported}
        # process metadata names both processes
        meta = {e["args"]["name"] for e in events
                if e["ph"] == "M" and e["name"] == "process_name"}
        assert any(m.startswith("driver:0") for m in meta), meta
        assert any(m.startswith("engine:0") for m in meta), meta
    finally:
        server.stop()


@pytest.mark.e2e
def test_pipeline_step_spans_share_one_trace_id(tmp_path):
    """A 2-gang cross-slice pipeline job (per-gang PROGRAMS over real
    DCN channels): each step's per-stage microbatch spans — recorded in
    SEPARATE processes — share one deterministic trace id derived from
    the job trace + step ordinal, tagged with the channel seq, with no
    extra channel frames."""
    steps, m = 2, 2
    hist = str(tmp_path / "tony-history")
    trainer = os.path.join(REPO, "examples", "lm", "train_pipeline.py")
    out = tmp_path / "pipe"
    prog = (f"{PY} {trainer} --steps {steps} --microbatches {m} "
            f"--mb_rows 2 --dim 4 --lr 0.1 --out {out}")
    client = _make_client(
        tmp_path, f"{PY} -c 'raise SystemExit(7)'",     # must be unused
        {"tony.stage0.instances": "1",
         "tony.stage1.instances": "1",
         "tony.pipeline.stages": "stage0,stage1",
         "tony.stage0.program": prog,
         "tony.stage1.program": prog},
        shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
                   "XLA_FLAGS": ""})
    assert client.run() == 0

    spans = _job_spans(hist)
    stage_spans = [s for s in spans if s["n"] == "pipeline.stage"]
    assert stage_spans, sorted({s["n"] for s in spans})
    by_tid = {}
    for s in stage_spans:
        by_tid.setdefault(s["tid"], set()).add(s["proc"])
    # at least one step's stage spans arrived from BOTH stage processes
    # under one trace id
    both = [tid for tid, procs in by_tid.items()
            if any(p.startswith("stage0:0") for p in procs)
            and any(p.startswith("stage1:0") for p in procs)]
    assert both, by_tid
    tid = both[0]
    mbs = [s for s in spans
           if s["tid"] == tid and s["n"] in ("pipeline.forward",
                                             "pipeline.backward")]
    assert {s["proc"].split("/")[0] for s in mbs} >= {"stage0:0",
                                                     "stage1:0"}
    # microbatch journeys reconstruct off the channel seq: stage 0's
    # forward SEND seq matches stage 1's forward RECV seq per mb
    f0 = {s["a"]["mb"]: s["a"].get("seq") for s in mbs
          if s["n"] == "pipeline.forward" and s["a"]["stage"] == 0}
    f1 = {s["a"]["mb"]: s["a"].get("seq") for s in mbs
          if s["n"] == "pipeline.forward" and s["a"]["stage"] == 1}
    assert f0 and f0 == f1, (f0, f1)
    # every stage span parents onto the shared deterministic step root
    root_sid = tracing.deterministic_span_id(f"{tid}:root")
    assert all(s["pid"] == root_sid for s in stage_spans
               if s["tid"] == tid)


@pytest.mark.e2e
def test_abnormal_exit_leaves_flight_dump_and_jhist_tail(tmp_path):
    """An abnormal child exit dumps the executor's flight ring to the
    job dir (a parseable postmortem whose final entries record the
    incident) and ships the tail on the final beat — the incident's
    TASK_FINISHED event carries it."""
    hist = str(tmp_path / "tony-history")
    client = _make_client(
        tmp_path, f"{PY} {os.path.join(FIXTURES, 'exit_1.py')}",
        {"tony.worker.instances": "1"})
    assert client.run() == 1

    dumps = glob.glob(os.path.join(client.job_dir, "flight-*.json"))
    assert dumps, os.listdir(client.job_dir)
    executor_dumps = [d for d in dumps if "worker-0" in d]
    assert executor_dumps, dumps
    doc = json.load(open(executor_dumps[0]))
    assert doc["reason"].startswith("child_exit")
    kinds = [e["kind"] for e in doc["events"]]
    # the FINAL entries record the incident itself
    assert kinds[-1] == "flight_dump" and "child_exit" in kinds, kinds

    finished = [e for path in ev.find_job_files(hist)
                for e in ev.parse_events(path)
                if e.event_type == ev.TASK_FINISHED
                and e.payload.get("task") == "worker:0"]
    assert finished, "no TASK_FINISHED for worker:0"
    tail = finished[0].payload.get("flight")
    assert tail is not None, finished[0].payload
    assert tail["reason"].startswith("child_exit")
    assert any(e["kind"] == "child_exit" and e.get("code") == 1
               for e in tail["events"]), tail
    # the jhist event references the on-disk dump
    assert tail["dump"] in executor_dumps, (tail["dump"], executor_dumps)
