"""tonylint coverage: every checker firing + non-firing on inline
fixtures, suppression semantics, the wire-manifest gate, and the
self-check that keeps ``tony_tpu/`` itself clean.

The self-check IS the CI wiring (satellite: tier-1 runs this file, so
``python -m pytest -m lint`` and the plain tier-1 sweep both gate on
``python -m tony_tpu.devtools.lint tony_tpu/`` staying at zero
non-baselined findings)."""

import json
import os
import textwrap

import pytest

from tony_tpu.devtools import lint

pytestmark = pytest.mark.lint


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------
def _findings(tmp_path, src, checker=None, name="fixture.py"):
    """Run the per-file checkers over one inline snippet."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(src), encoding="utf-8")
    mod = lint.load_module(str(p))
    assert mod is not None
    out = lint.run_per_file_checkers(mod)
    if checker is not None:
        out = [f for f in out if f.checker == checker]
    return out


# ---------------------------------------------------------------------------
# TL001 blocking-while-locked
# ---------------------------------------------------------------------------
def test_tl001_fires_on_socket_send_under_lock(tmp_path):
    out = _findings(tmp_path, """
        class S:
            def reply(self, conn):
                with self._lock:
                    conn.send(b"x")
    """, "TL001")
    assert len(out) == 1
    assert "conn.send" in out[0].message
    assert out[0].symbol == "S.reply"


def test_tl001_fires_on_sleep_subprocess_join_and_recv_bytes(tmp_path):
    out = _findings(tmp_path, """
        import subprocess, time
        class S:
            def a(self):
                with self._lock:
                    time.sleep(1)
            def b(self):
                with self._cv:
                    subprocess.run(["true"])
            def c(self, t):
                with self._mutex:
                    t.join()
            def d(self, ch):
                with self._send_lock:
                    ch.recv_bytes()
    """, "TL001")
    assert len(out) == 4


def test_tl001_quiet_outside_lock_and_on_nonblocking_work(tmp_path):
    out = _findings(tmp_path, """
        import time
        class S:
            def ok(self, conn):
                with self._lock:
                    self.n += 1
                    parts = ", ".join(self.names)     # str.join
                    path = os.path.join("a", "b")     # os.path.join
                conn.send(b"x")
                time.sleep(0)
    """, "TL001")
    assert out == []


def test_tl001_quiet_on_cv_wait_on_the_held_condition(tmp_path):
    # Condition.wait RELEASES the condition — the one legal block
    out = _findings(tmp_path, """
        class S:
            def take(self):
                with self._cv:
                    while not self.q:
                        self._cv.wait(0.5)
            def bad(self, other):
                with self._cv:
                    other.wait()
    """, "TL001")
    assert len(out) == 1 and out[0].symbol == "S.bad"


def test_tl001_ignores_nested_function_bodies(tmp_path):
    # a closure defined under the lock runs later, off-lock
    out = _findings(tmp_path, """
        class S:
            def spawn(self):
                with self._lock:
                    def later():
                        self.sock.recv(4)
                    self.cb = later
    """, "TL001")
    assert out == []


# ---------------------------------------------------------------------------
# TL002 guarded-by lock discipline
# ---------------------------------------------------------------------------
def test_tl002_fires_on_unlocked_access_of_guarded_attr(tmp_path):
    out = _findings(tmp_path, """
        import threading
        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._table = {}  # guarded-by: _lock
            def bad(self, k):
                return self._table.get(k)
    """, "TL002")
    assert len(out) == 1
    assert out[0].symbol == "S._table"
    assert "_lock" in out[0].message


def test_tl002_quiet_under_the_right_lock_and_without_annotation(tmp_path):
    out = _findings(tmp_path, """
        import threading
        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._table = {}  # guarded-by: _lock
                self._free = 0    # unannotated: no discipline claimed
            def ok(self, k):
                with self._lock:
                    return self._table.get(k)
            def also_ok(self):
                self._free += 1
    """, "TL002")
    assert out == []


def test_tl002_real_tree_has_live_annotations():
    # the annotation is exercised in the shipped tree, not just fixtures
    mod = lint.load_module(os.path.join(
        lint.REPO_ROOT, "tony_tpu", "cluster", "liveness.py"))
    assert lint._guarded_decls(
        [n for n in mod.tree.body
         if getattr(n, "name", "") == "HeartbeatMonitor"][0], mod.lines)
    assert lint.check_lock_discipline(mod) == []


# ---------------------------------------------------------------------------
# TL003 thread hygiene
# ---------------------------------------------------------------------------
def test_tl003_fires_on_unnamed_and_unjoined_threads(tmp_path):
    out = _findings(tmp_path, """
        import threading
        def bad():
            threading.Thread(target=print, daemon=True).start()   # unnamed
            t = threading.Thread(target=print, name="tony-x")     # unjoined
            t.start()
    """, "TL003")
    assert len(out) == 2
    assert any("not 'tony-'-prefixed" in f.message for f in out)
    assert any("neither daemon" in f.message for f in out)


def test_tl003_quiet_on_named_daemon_and_named_joined(tmp_path):
    out = _findings(tmp_path, """
        import threading
        def ok():
            threading.Thread(target=print, name="tony-a",
                             daemon=True).start()
            t = threading.Thread(target=print, name=f"tony-b{1}")
            t.start()
            t.join()
            threads = [threading.Thread(target=print, name="tony-c")
                       for _ in range(3)]
            for t2 in threads:
                t2.start()
            for t2 in threads:
                t2.join()
    """, "TL003")
    assert out == []


# ---------------------------------------------------------------------------
# TL004 fd hygiene
# ---------------------------------------------------------------------------
def test_tl004_fires_on_leaked_open_and_socket(tmp_path):
    out = _findings(tmp_path, """
        import socket
        def leak(path):
            f = open(path)
            s = socket.socket()
            return f.read()
    """, "TL004")
    assert {f.symbol for f in out} == {"leak:s"}  # f escapes via read()? no:
    # open() result used via f.read() is still a leak; socket unused is too


def test_tl004_open_leak_fires(tmp_path):
    out = _findings(tmp_path, """
        def leak(path):
            f = open(path)
            data = f.read
            return None
    """, "TL004")
    assert [f.symbol for f in out] == ["leak:f"]


def test_tl004_quiet_on_with_close_finally_and_escape(tmp_path):
    out = _findings(tmp_path, """
        import socket
        def ok(path):
            with open(path) as f:
                return f.read()
        def ok2(path):
            f = open(path)
            try:
                return f.read()
            finally:
                f.close()
        def ok3():
            s = socket.socket()
            return s                       # ownership handed to caller
        def ok4(self):
            s = socket.socket()
            self.sock = s                  # lifetime owned by self
        def ok5(registry):
            s = socket.socket()
            registry.adopt(s)              # ownership transferred
    """, "TL004")
    assert out == []


# ---------------------------------------------------------------------------
# TL005 broad except
# ---------------------------------------------------------------------------
def test_tl005_fires_on_silent_broad_except(tmp_path):
    out = _findings(tmp_path, """
        def a():
            try:
                work()
            except Exception:
                pass
        def b():
            try:
                work()
            except:
                return None
    """, "TL005")
    assert len(out) == 2


def test_tl005_quiet_when_raising_logging_or_flight_recording(tmp_path):
    out = _findings(tmp_path, """
        def a():
            try:
                work()
            except Exception:
                raise
        def b():
            try:
                work()
            except Exception:
                log.exception("boom")
        def c():
            try:
                work()
            except Exception as e:
                get_flight().record("err", error=str(e))
        def d():
            try:
                work()
            except ValueError:
                pass                       # narrow: fine
    """, "TL005")
    assert out == []


# ---------------------------------------------------------------------------
# TL006 proto additivity + wire manifest
# ---------------------------------------------------------------------------
_PROTO_V1 = """\
syntax = "proto3";
message Ping {
  string task_id = 1;
  string metrics = 2;
}
message Pong {
  string token = 1;
}
"""


def _proto_root(tmp_path, proto_text):
    root = tmp_path / "repo"
    d = root / "tony_tpu" / "rpc" / "proto"
    d.mkdir(parents=True)
    (d / "tony.proto").write_text(proto_text, encoding="utf-8")
    return str(root)


def test_tl006_parse_and_manifest_roundtrip(tmp_path):
    root = _proto_root(tmp_path, _PROTO_V1)
    proto = lint.parse_proto(os.path.join(root, lint.PROTO_FILE))
    assert proto == {"Ping": {"task_id": 1, "metrics": 2},
                     "Pong": {"token": 1}}
    mpath = os.path.join(root, lint.WIRE_MANIFEST)
    lint.write_wire_manifest(mpath, proto, None)
    assert lint.load_wire_manifest(mpath) == proto
    assert lint.check_proto_additivity(root) == []


def test_tl006_added_field_passes_renumber_and_reuse_fail(tmp_path):
    root = _proto_root(tmp_path, _PROTO_V1)
    ppath = os.path.join(root, lint.PROTO_FILE)
    mpath = os.path.join(root, lint.WIRE_MANIFEST)
    lint.write_wire_manifest(mpath, lint.parse_proto(ppath), None)

    # adding a field is the legal evolution
    add = _PROTO_V1.replace("string metrics = 2;",
                            "string metrics = 2;\n  string spans = 3;")
    (tmp_path / "repo/tony_tpu/rpc/proto/tony.proto").write_text(add)
    assert lint.check_proto_additivity(root) == []
    # ... and --update-wire-manifest folds it in
    lint.write_wire_manifest(mpath, lint.parse_proto(ppath),
                             lint.load_wire_manifest(mpath))
    assert lint.load_wire_manifest(mpath)["Ping"]["spans"] == 3

    # renumbering a released field fails
    renum = _PROTO_V1.replace("string metrics = 2;",
                              "string metrics = 7;")
    (tmp_path / "repo/tony_tpu/rpc/proto/tony.proto").write_text(renum)
    bad = lint.check_proto_additivity(root)
    assert len(bad) == 1 and "renumbered" in bad[0].message
    assert bad[0].symbol == "Ping.metrics"

    # deleting a field and reusing its number fails
    reuse = _PROTO_V1.replace("string metrics = 2;",
                              "string other = 2;")
    (tmp_path / "repo/tony_tpu/rpc/proto/tony.proto").write_text(reuse)
    bad = lint.check_proto_additivity(root)
    assert len(bad) == 1 and "reused" in bad[0].message
    # removing WITHOUT reuse is fine (the number just stays reserved)
    gone = _PROTO_V1.replace("  string metrics = 2;\n", "")
    (tmp_path / "repo/tony_tpu/rpc/proto/tony.proto").write_text(gone)
    assert lint.check_proto_additivity(root) == []


def test_tl006_manifest_retains_removed_fields(tmp_path):
    root = _proto_root(tmp_path, _PROTO_V1)
    ppath = os.path.join(root, lint.PROTO_FILE)
    mpath = os.path.join(root, lint.WIRE_MANIFEST)
    lint.write_wire_manifest(mpath, lint.parse_proto(ppath), None)
    gone = _PROTO_V1.replace("  string metrics = 2;\n", "")
    (tmp_path / "repo/tony_tpu/rpc/proto/tony.proto").write_text(gone)
    lint.write_wire_manifest(mpath, lint.parse_proto(ppath),
                             lint.load_wire_manifest(mpath))
    # the removed field's number stays reserved in the manifest...
    assert lint.load_wire_manifest(mpath)["Ping"]["metrics"] == 2
    # ... so a later reuse of number 2 still fails
    reuse = gone.replace("string task_id = 1;",
                         "string task_id = 1;\n  string other = 2;")
    (tmp_path / "repo/tony_tpu/rpc/proto/tony.proto").write_text(reuse)
    bad = lint.check_proto_additivity(root)
    assert len(bad) == 1 and "reused" in bad[0].message


def test_tl006_committed_manifest_matches_live_proto():
    # the shipped tree: manifest exists, is current, and gates cleanly
    manifest = lint.load_wire_manifest(
        os.path.join(lint.REPO_ROOT, lint.WIRE_MANIFEST))
    proto = lint.parse_proto(
        os.path.join(lint.REPO_ROOT, lint.PROTO_FILE))
    assert manifest is not None
    assert manifest == proto        # nothing removed/renumbered yet
    assert "HeartbeatRequest" in manifest
    assert manifest["HeartbeatRequest"]["goodput"] == 6
    assert lint.check_proto_additivity(lint.REPO_ROOT) == []


# ---------------------------------------------------------------------------
# TL007 frame exhaustiveness
# ---------------------------------------------------------------------------
def _frame_root(tmp_path, dispatch_src):
    root = tmp_path / "repo"
    (root / "tony_tpu" / "serving").mkdir(parents=True)
    (root / "tony_tpu" / "channels").mkdir(parents=True)
    (root / "tony_tpu" / "serving" / "protocol.py").write_text(
        textwrap.dedent("""
            ADMIT = 1
            CANCEL = 2
            FRAME_NAMES = {ADMIT: "ADMIT", CANCEL: "CANCEL"}
        """), encoding="utf-8")
    (root / "tony_tpu" / "channels" / "channel.py").write_text(
        "CH_HELLO = 1\nCH_ACK = 3\n", encoding="utf-8")
    dp = root / "tony_tpu" / "serving" / "server.py"
    dp.write_text(textwrap.dedent(dispatch_src), encoding="utf-8")
    mods = [lint.load_module(str(p)) for p in (
        root / "tony_tpu" / "serving" / "protocol.py",
        root / "tony_tpu" / "channels" / "channel.py", dp)]
    return str(root), mods


def test_tl007_fires_on_undispatched_constant(tmp_path):
    root, mods = _frame_root(tmp_path, """
        from .protocol import ADMIT
        from ..channels.channel import CH_HELLO, CH_ACK
        def handle(ftype, op):
            if ftype == ADMIT:
                pass
            if op == CH_HELLO or op == CH_ACK:
                pass
    """)
    out = lint.check_frame_exhaustiveness(root, mods)
    assert [f.symbol for f in out] == ["CANCEL"]
    assert "no dispatch arm" in out[0].message


def test_tl007_quiet_when_all_constants_dispatch(tmp_path):
    root, mods = _frame_root(tmp_path, """
        from .protocol import ADMIT, CANCEL
        from ..channels.channel import CH_HELLO, CH_ACK
        HANDLERS = {CH_ACK: print}
        def handle(ftype, op):
            if ftype in (ADMIT, CANCEL):
                pass
            if op == CH_HELLO:
                pass
    """)
    assert lint.check_frame_exhaustiveness(root, mods) == []


def test_tl007_real_tree_dispatches_every_frame():
    assert lint.check_frame_exhaustiveness(lint.REPO_ROOT) == []


# ---------------------------------------------------------------------------
# TL008 observability bijections
# ---------------------------------------------------------------------------
def _obs_root(tmp_path, code, metrics_doc):
    root = tmp_path / "repo"
    (root / "tony_tpu" / "events").mkdir(parents=True)
    (root / "docs").mkdir()
    (root / "tony_tpu" / "m.py").write_text(textwrap.dedent(code),
                                            encoding="utf-8")
    (root / "tony_tpu" / "events" / "events.py").write_text(
        'APPLICATION_INITED = "APPLICATION_INITED"\n', encoding="utf-8")
    (root / "docs" / "observability.md").write_text(metrics_doc,
                                                    encoding="utf-8")
    return str(root)


def test_tl008_fires_on_undocumented_and_stale_series(tmp_path):
    root = _obs_root(
        tmp_path,
        'reg.counter("tony_real_total")\nreg.counter("tony_hidden_total")\n',
        "| `tony_real_total` | `tony_ghost_total` |\n"
        "`APPLICATION_INITED`\n")
    out = lint.check_observability(root, facets=("metrics",))
    msgs = {f.symbol: f.message for f in out}
    assert "series missing from docs/observability.md: tony_hidden_total" \
        in msgs["tony_hidden_total"]
    assert "not registered" in msgs["tony_ghost_total"]
    assert len(out) == 2


def test_tl008_fires_on_undocumented_event_type(tmp_path):
    root = _obs_root(tmp_path, 'x = "tony_real_total"\n',
                     "`tony_real_total` docs without the event row\n")
    out = lint.check_observability(root, facets=("events",))
    assert [f.symbol for f in out] == ["APPLICATION_INITED"]
    assert "event types missing from docs/observability.md" \
        in out[0].message


def test_tl008_dynamic_prefix_and_suffix_series_pass(tmp_path):
    root = _obs_root(
        tmp_path,
        'PFX = "tony_serve_phase"\n'
        'reg.counter(f"{prefix}_seconds_total")\n'
        'reg.counter(f"tony_startup_{phase}_seconds")\n',
        "| `tony_serve_phase` `tony_serve_phase_seconds_total` "
        "`tony_serve_phase_*` `tony_startup_` |\n"
        "`APPLICATION_INITED`\n")
    assert lint.check_observability(root, facets=("metrics",)) == []


def test_tl008_real_tree_is_bijective():
    assert lint.check_observability(lint.REPO_ROOT) == []


# ---------------------------------------------------------------------------
# baseline / suppression semantics
# ---------------------------------------------------------------------------
def test_baseline_suppresses_by_symbol_not_line(tmp_path):
    src = """
        def a():
            try:
                work()
            except Exception:
                pass
    """
    out = _findings(tmp_path, src, "TL005")
    assert len(out) == 1
    sup = [{"checker": "TL005", "path": out[0].path, "symbol": "a"}]
    left, n_sup, stale = lint.apply_baseline(out, sup)
    assert left == [] and n_sup == 1 and stale == []
    # the entry keys on the symbol: a DIFFERENT function is not covered
    other = [{"checker": "TL005", "path": out[0].path, "symbol": "zz"}]
    left, n_sup, stale = lint.apply_baseline(out, other)
    assert len(left) == 1 and n_sup == 0 and len(stale) == 1


def test_shipped_baseline_small_current_and_ratcheting():
    """The introduction baseline stays SMALL and every entry still
    matches a live finding — a fixed finding must drop its entry, and
    new code must never grow the list (the ratchet)."""
    sups = lint.load_baseline(
        os.path.join(lint.REPO_ROOT, lint.DEFAULT_BASELINE))
    assert 0 < len(sups) <= 20, (
        "the baseline only ratchets down from its introduction size; "
        "fix new findings instead of baselining them")
    all_findings = lint.run([os.path.join(lint.REPO_ROOT, "tony_tpu")])
    _, n_sup, stale = lint.apply_baseline(all_findings, sups)
    assert stale == [], f"stale baseline entries (delete them): {stale}"
    assert n_sup >= len(sups)


def test_self_check_zero_unbaselined_findings(capsys):
    """THE gate: `python -m tony_tpu.devtools.lint tony_tpu/` exits 0 on
    the shipped tree."""
    rc = lint.main([os.path.join(lint.REPO_ROOT, "tony_tpu")])
    out = capsys.readouterr()
    assert rc == 0, f"tonylint found regressions:\n{out.out}{out.err}"


def test_new_unbaselined_finding_fails_the_gate(tmp_path, capsys):
    """A synthetic new finding (not in the baseline) must exit non-zero
    even WITH the shipped baseline loaded."""
    bad = tmp_path / "new_code.py"
    bad.write_text(textwrap.dedent("""
        def swallow():
            try:
                work()
            except Exception:
                pass
    """), encoding="utf-8")
    rc = lint.main([str(bad)])
    out = capsys.readouterr()
    assert rc == 1
    assert "TL005" in out.out and "new_code.py" in out.out
    assert "fix:" in out.out                      # findings carry a hint


def test_findings_render_path_line_checker_and_hint(tmp_path):
    out = _findings(tmp_path, """
        def a():
            try:
                work()
            except Exception:
                pass
    """, "TL005")
    text = out[0].render()
    assert text.startswith(f"{out[0].path}:{out[0].line}: TL005 [a] ")
    assert "(fix: " in text
