"""Inter-gang tensor channels + cross-slice 1F1B (tier-1).

The acceptance suite for the MPMD pipeline data path:

- transport semantics: typed TENSOR frames, bounded send windows
  (backpressure, never unbounded buffering), reconnect-with-seq-resume
  (no duplicated/dropped microbatch), channel-scoped failure (garbage
  costs one connection, the hub keeps serving);
- the coordinator-owned channel registry (stage wiring, rank pairing,
  config validation);
- THE numerical pin: cross-slice 1F1B loss/grads bit-identical to the
  in-slice ``pipeline_value_and_grad`` schedule on the same
  params/microbatches — moving a model across slices never changes what
  it learns;
- the bench pin: overlapped 1F1B >= 1.5x serialized stage execution
  under injected DCN latency, channel walls/queue depths visible on the
  metrics plane.
"""

import json
import socket
import struct
import threading
import time

import numpy as np
import pytest

from tony_tpu.channels import (ACT_CHANNEL, ChannelError, ChannelHub,
                               ChannelSender, act_channel,
                               build_channel_specs, decode_tensor,
                               encode_tensor, grad_channel,
                               open_local_pipeline)
from tony_tpu.channels.channel import CH_HELLO, CH_MAGIC, CH_TENSOR
from tony_tpu.runtime.metrics import MetricsRegistry
from tony_tpu.serving.protocol import (ProtocolError, pack_json,
                                       recv_frame, send_frame)


def _mk_hub(capacity=8):
    reg = MetricsRegistry()
    hub = ChannelHub(capacity=capacity, registry=reg)
    port = hub.start()
    return hub, port, reg


def _mk_sender(port, name="t", *, window=8, reg=None, **kw):
    return ChannelSender(f"127.0.0.1:{port}", name,
                         window=window, registry=reg or MetricsRegistry(),
                         **kw)


class TestTensorCodec:
    def test_round_trip_dtypes_and_shapes(self):
        for arr in (np.arange(12, dtype=np.float32).reshape(3, 4),
                    np.array(3.5, dtype=np.float64),
                    np.zeros((0, 5), dtype=np.int32),
                    np.random.RandomState(0).randn(2, 3, 4)
                    .astype(np.float16)):
            head, raw = encode_tensor(arr)
            out = decode_tensor(head + raw)
            assert out.dtype == arr.dtype and out.shape == arr.shape
            assert np.array_equal(out, arr, equal_nan=True)

    def test_non_contiguous_input(self):
        arr = np.arange(24, dtype=np.float32).reshape(4, 6)[:, ::2]
        head, raw = encode_tensor(arr)
        assert np.array_equal(decode_tensor(head + raw), arr)

    @pytest.mark.parametrize("payload", [
        b"",                                     # shorter than prefix
        b"\x05\x00\x00\x00ab",                   # header len > frame
        b"\x02\x00\x00\x00{}",                   # header not dtype/shape
    ])
    def test_malformed_payloads_raise_protocol_error(self, payload):
        with pytest.raises(ProtocolError):
            decode_tensor(payload)

    def test_size_mismatch_raises(self):
        head, raw = encode_tensor(np.zeros(4, np.float32))
        with pytest.raises(ProtocolError):
            decode_tensor(head + raw[:-1])


class TestWireCodec:
    """The compressed encodings (bf16, int8+per-tensor-scale) and their
    kind-tag discipline: a compressed frame can never silently decode on
    a raw channel, nor a raw frame on a codec channel."""

    def _arr(self, scale=3.0):
        return (np.random.RandomState(3).randn(16, 8)
                .astype(np.float32) * scale)

    def test_int8_round_trip_close(self):
        a = self._arr()
        head, raw = encode_tensor(a, "int8")
        out = decode_tensor(head + raw, "int8")
        assert out.dtype == a.dtype and out.shape == a.shape
        # per-tensor scale: worst-case error is half a quantization step
        step = np.abs(a).max() / 127
        assert np.max(np.abs(out - a)) <= step
        # the wire carries ~1/4 the bytes (scale prefix + int8 values)
        assert len(raw) == 4 + a.size

    def test_bf16_round_trip(self):
        import ml_dtypes
        a = self._arr()
        head, raw = encode_tensor(a, "bf16")
        assert len(raw) == a.size * 2
        out = decode_tensor(head + raw, "bf16")
        assert out.dtype == np.float32
        assert np.array_equal(out, a.astype(ml_dtypes.bfloat16)
                              .astype(np.float32))

    def test_bf16_input_under_int8(self):
        import ml_dtypes
        a = self._arr().astype(ml_dtypes.bfloat16)
        head, raw = encode_tensor(a, "int8")
        out = decode_tensor(head + raw, "int8")
        assert out.dtype == a.dtype and out.shape == a.shape

    def test_non_compressible_dtype_passes_through(self):
        for codec in ("int8", "bf16"):
            a = np.arange(10, dtype=np.int32)
            head, raw = encode_tensor(a, codec)
            assert json.loads(head[4:].decode())["wire"] == "raw"
            assert np.array_equal(decode_tensor(head + raw, codec), a)

    def test_zero_and_empty_tensors(self):
        for a in (np.zeros((4, 4), np.float32),     # amax 0: scale 1.0
                  np.zeros((0, 3), np.float32),
                  np.float32(2.5).reshape(())):
            for codec in ("int8", "bf16"):
                head, raw = encode_tensor(a, codec)
                out = decode_tensor(head + raw, codec)
                assert out.shape == a.shape and out.dtype == a.dtype

    def test_unknown_codec_rejected(self):
        with pytest.raises(ValueError, match="unknown channel codec"):
            encode_tensor(np.zeros(2, np.float32), "gzip")

    # -- kind-tag discipline ------------------------------------------
    def test_compressed_frame_on_raw_channel_rejected(self):
        head, raw = encode_tensor(self._arr(), "int8")
        with pytest.raises(ProtocolError, match="raw channel"):
            decode_tensor(head + raw)

    def test_raw_frame_on_codec_channel_rejected(self):
        head, raw = encode_tensor(self._arr())
        with pytest.raises(ProtocolError, match="codec"):
            decode_tensor(head + raw, "int8")

    def test_cross_codec_frame_rejected(self):
        head, raw = encode_tensor(self._arr(), "bf16")
        with pytest.raises(ProtocolError):
            decode_tensor(head + raw, "int8")

    def _craft(self, header: dict, payload: bytes) -> bytes:
        head = json.dumps(header).encode()
        return struct.pack("<I", len(head)) + head + payload

    def test_truncated_scale_rejected(self):
        # int8 payload shorter than its 4-byte scale prefix
        frame = self._craft({"codec": "int8", "wire": "int8",
                             "dtype": "float32", "shape": [4]}, b"\x01\x02")
        with pytest.raises(ProtocolError):
            decode_tensor(frame, "int8")

    def test_non_finite_scale_rejected(self):
        payload = struct.pack("<f", float("nan")) + bytes(4)
        frame = self._craft({"codec": "int8", "wire": "int8",
                             "dtype": "float32", "shape": [4]}, payload)
        with pytest.raises(ProtocolError):
            decode_tensor(frame, "int8")

    def test_wrong_dtype_header_rejected(self):
        payload = struct.pack("<f", 1.0) + bytes(4)
        frame = self._craft({"codec": "int8", "wire": "int8",
                             "dtype": "float99", "shape": [4]}, payload)
        with pytest.raises(ProtocolError):
            decode_tensor(frame, "int8")

    def test_unknown_wire_kind_rejected(self):
        frame = self._craft({"codec": "int8", "wire": "zstd",
                             "dtype": "float32", "shape": [4]}, bytes(4))
        with pytest.raises(ProtocolError):
            decode_tensor(frame, "int8")

    def test_compressed_wire_for_raw_only_dtype_rejected(self):
        # int8 wire kind claiming to carry an int32 tensor: compressible
        # dtypes only
        payload = struct.pack("<f", 1.0) + bytes(4)
        frame = self._craft({"codec": "int8", "wire": "int8",
                             "dtype": "int32", "shape": [4]}, payload)
        with pytest.raises(ProtocolError):
            decode_tensor(frame, "int8")


class TestChannelTransport:
    def test_ordered_delivery(self):
        hub, port, reg = _mk_hub()
        sender = _mk_sender(port, reg=reg)
        recv = hub.receiver("t")
        try:
            sent = [np.full((2, 2), i, np.float32) for i in range(20)]
            got: list = []
            consumer = threading.Thread(
                target=lambda: got.extend(recv.recv(timeout=30)
                                          for _ in range(20)))
            consumer.start()       # window < 20: consume concurrently
            for a in sent:
                sender.send(a, timeout=30)
            consumer.join(timeout=30)
            assert len(got) == 20
            for a, b in zip(sent, got):
                assert np.array_equal(a, b)
        finally:
            sender.close()
            hub.stop()

    def test_bounded_window_blocks_instead_of_buffering(self):
        """With the consumer stalled, the sender admits at most
        window + receiver-capacity frames and then BLOCKS — host memory
        never absorbs an unbounded backlog."""
        hub, port, reg = _mk_hub(capacity=1)
        sender = _mk_sender(port, window=2, reg=reg)
        recv = hub.receiver("t")
        done = []

        def producer():
            for i in range(8):
                sender.send(np.full((4,), i, np.float32), timeout=30)
                done.append(i)

        t = threading.Thread(target=producer, daemon=True)
        try:
            t.start()
            time.sleep(1.0)
            # nobody consumed: 2 in the window + 1 parked in the hub
            # queue can clear; the producer must be parked well short
            # of 8
            assert len(done) <= 4, done
            assert t.is_alive()
            got = [recv.recv(timeout=10) for _ in range(8)]
            t.join(timeout=10)
            assert not t.is_alive() and len(done) == 8
            assert [int(a[0]) for a in got] == list(range(8))
        finally:
            sender.close(drain=False)
            hub.stop()

    def test_reconnect_resumes_at_receiver_seq(self):
        """Severing the socket mid-stream (hub keeps its state) loses
        nothing: the sender reconnects, learns the receiver's resume
        point, and the consumer sees every microbatch exactly once."""
        hub, port, reg = _mk_hub()
        sender = _mk_sender(port, reg=reg)
        recv = hub.receiver("t")
        got = []

        def consumer():
            for _ in range(30):
                got.append(int(recv.recv(timeout=30)[0]))

        t = threading.Thread(target=consumer, daemon=True)
        try:
            t.start()
            for i in range(30):
                sender.send(np.full((3,), i, np.float32), timeout=30)
                if i in (7, 19):
                    hub.disconnect_all()       # transient DCN blip
            sender.drain(timeout=30)
            t.join(timeout=30)
            assert got == list(range(30)), got
            assert reg.counter("tony_channel_reconnects_total",
                               channel="t").value >= 1
        finally:
            sender.close(drain=False)
            hub.stop()

    def test_sync_send_waits_for_ack(self):
        hub, port, reg = _mk_hub()
        sender = _mk_sender(port, reg=reg)
        recv = hub.receiver("t")
        try:
            sender.send(np.zeros(2, np.float32), sync=True, timeout=10)
            assert sender.unacked() == 0
            assert np.array_equal(recv.recv(timeout=5),
                                  np.zeros(2, np.float32))
        finally:
            sender.close()
            hub.stop()

    def test_unreachable_peer_raises_after_budget(self):
        with socket.socket() as s:       # reserve a port nobody serves
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        sender = ChannelSender(f"127.0.0.1:{port}", "t", window=2,
                               max_retries=2, backoff_s=0.01,
                               registry=MetricsRegistry())
        with pytest.raises(ChannelError):
            sender.send(np.zeros(1, np.float32), timeout=5)
        sender.close(drain=False)


class TestChannelFailureScoping:
    def _raw_conn(self, port):
        sock = socket.create_connection(("127.0.0.1", port), timeout=5)
        sock.sendall(CH_MAGIC)
        send_frame(sock, CH_HELLO, 0, pack_json({"v": 1, "channel": "g"}))
        fr = recv_frame(sock)
        assert fr is not None and fr[0] == CH_HELLO
        return sock

    def test_garbage_tensor_frame_is_channel_scoped(self):
        """A connection feeding undecodable TENSOR payloads dies alone:
        the hub keeps serving its OTHER channel, and the garbage
        channel's state survives for a clean resume."""
        hub, port, reg = _mk_hub()
        sender = _mk_sender(port, name="good", reg=reg)
        good = hub.receiver("good")
        try:
            bad = self._raw_conn(port)
            send_frame(bad, CH_TENSOR, 0, b"\xff\xff\xff\xffjunk")
            # the hub answers with CH_ERROR (or just closes) — either
            # way the connection ends...
            assert recv_frame(bad) is None or True
            bad.close()
            # ...and the good channel keeps flowing
            sender.send(np.ones(4, np.float32))
            assert np.array_equal(good.recv(timeout=10),
                                  np.ones(4, np.float32))
            # a well-behaved peer then resumes channel "g" at seq 0
            again = self._raw_conn(port)
            again.close()
        finally:
            sender.close()
            hub.stop()

    def test_truncated_frame_mid_stream(self):
        """A peer dying mid-frame (length prefix promised more bytes)
        costs only that connection."""
        hub, port, reg = _mk_hub()
        sender = _mk_sender(port, name="good", reg=reg)
        good = hub.receiver("good")
        try:
            bad = self._raw_conn(port)
            bad.sendall(b"\xf0\x00\x00\x00")      # 240-byte frame promised
            bad.sendall(b"\x02partial")            # ...never delivered
            bad.close()
            sender.send(np.full(2, 7, np.float32))
            assert np.array_equal(good.recv(timeout=10),
                                  np.full(2, 7, np.float32))
        finally:
            sender.close()
            hub.stop()

    def test_stray_peer_wrong_magic(self):
        hub, port, reg = _mk_hub()
        try:
            sock = socket.create_connection(("127.0.0.1", port), timeout=5)
            sock.sendall(b"GET / HTTP/1.1\r\n\r\n")
            sock.settimeout(2)
            try:
                data = sock.recv(64)
            except ConnectionResetError:
                data = b""     # RST instead of FIN: still a rejection
            assert data == b""                     # closed at byte 0
            sock.close()
        finally:
            hub.stop()

    def test_seq_gap_closes_connection_state_survives(self):
        hub, port, reg = _mk_hub()
        recv = hub.receiver("g")
        try:
            bad = self._raw_conn(port)
            head, raw = encode_tensor(np.ones(2, np.float32))
            send_frame(bad, CH_TENSOR, 5, head + raw)   # expected seq 0
            # connection-scoped error; nothing was enqueued
            deadline = time.monotonic() + 5
            while recv.qsize() == 0 and time.monotonic() < deadline:
                fr = None
                try:
                    fr = recv_frame(bad)
                except (ProtocolError, OSError):
                    break
                if fr is None:
                    break
            assert recv.qsize() == 0
            bad.close()
            # a correct sender still starts cleanly at seq 0
            sender = _mk_sender(port, name="g", reg=reg)
            sender.send(np.full(2, 3, np.float32))
            assert np.array_equal(recv.recv(timeout=10),
                                  np.full(2, 3, np.float32))
            sender.close()
        finally:
            hub.stop()


class TestCodecTransport:
    """Codec negotiation at the channel handshake + channel-scoped
    failure when the wire and the negotiated codec disagree."""

    def test_int8_end_to_end(self):
        hub, port, reg = _mk_hub()
        sender = _mk_sender(port, reg=reg, codec="int8")
        recv = hub.receiver("t", codec="int8")
        try:
            a = np.random.RandomState(1).randn(32, 16).astype(np.float32)
            sender.send(a, sync=True, timeout=10)
            out = recv.recv(timeout=10)
            assert out.dtype == a.dtype and out.shape == a.shape
            assert np.max(np.abs(out - a)) <= np.abs(a).max() / 127
            # logical counters see decoded bytes; the codec-only wire
            # counter sees the encoded frame (~1/4 the payload)
            logical = reg.counter("tony_channel_bytes_total",
                                  channel="t", direction="send").value
            encoded = reg.counter("tony_channel_compressed_bytes_total",
                                  channel="t", direction="send").value
            assert logical == a.nbytes
            assert 0 < encoded < logical / 1.9
            assert reg.counter("tony_channel_compressed_bytes_total",
                               channel="t", direction="recv").value \
                == encoded
        finally:
            sender.close()
            hub.stop()

    def test_codec_mismatch_fails_at_handshake(self):
        """A sender dialing with the wrong codec is refused PERMANENTLY
        (CH_ERROR, no retry burn) and channel-scoped: the same hub's
        healthy channel keeps flowing, and a matching sender succeeds
        on the refused channel afterwards."""
        hub, port, reg = _mk_hub()
        good_recv = hub.receiver("good")
        good = _mk_sender(port, name="good", reg=reg)
        recv = hub.receiver("t", codec="int8")
        t0 = time.monotonic()
        bad = _mk_sender(port, name="t", reg=reg)       # raw vs int8
        try:
            with pytest.raises(ChannelError, match="refused"):
                bad.send(np.zeros(4, np.float32), timeout=30)
            assert time.monotonic() - t0 < 10    # permanent, not retried
            bad.close(drain=False)
            # reverse direction: codec sender against a raw lane
            raw_recv = hub.receiver("r")
            bad2 = _mk_sender(port, name="r", reg=reg, codec="bf16")
            with pytest.raises(ChannelError, match="refused"):
                bad2.send(np.zeros(4, np.float32), timeout=30)
            bad2.close(drain=False)
            # the healthy channel never noticed
            good.send(np.ones(3, np.float32), sync=True, timeout=10)
            assert np.array_equal(good_recv.recv(timeout=10),
                                  np.ones(3, np.float32))
            # a MATCHING sender owns the refused lane cleanly
            ok = _mk_sender(port, name="t", reg=reg, codec="int8")
            ok.send(np.full(2, 5, np.float32), sync=True, timeout=10)
            assert np.allclose(recv.recv(timeout=10),
                               np.full(2, 5, np.float32), atol=0.05)
            ok.close()
        finally:
            good.close()
            hub.stop()

    def test_first_sender_declares_codec_for_late_receiver(self):
        """Negotiation is first-declarer-wins: a sender HELLO carrying a
        codec binds the lane before the local receiver exists; a
        receiver then asking for a DIFFERENT codec is the config bug."""
        hub, port, reg = _mk_hub()
        sender = _mk_sender(port, reg=reg, codec="int8")
        try:
            sender.send(np.ones(4, np.float32), sync=True, timeout=10)
            with pytest.raises(ValueError, match="codec"):
                hub.receiver("t", codec="bf16")
            recv = hub.receiver("t", codec="int8")
            assert np.allclose(recv.recv(timeout=10),
                               np.ones(4, np.float32), atol=0.05)
        finally:
            sender.close()
            hub.stop()

    def test_mistagged_wire_frame_is_channel_scoped(self):
        """A connection that NEGOTIATES int8 but then ships a raw-tagged
        frame dies alone (kind-tag mismatch -> ProtocolError), state
        survives for a clean resume — the garbage-frame discipline,
        codec edition."""
        hub, port, reg = _mk_hub()
        recv = hub.receiver("g", codec="int8")
        other_recv = hub.receiver("other", codec="int8")
        other = _mk_sender(port, name="other", reg=reg, codec="int8")
        try:
            sock = socket.create_connection(("127.0.0.1", port), timeout=5)
            sock.sendall(CH_MAGIC)
            send_frame(sock, CH_HELLO, 0,
                       pack_json({"v": 1, "channel": "g",
                                  "codec": "int8"}))
            fr = recv_frame(sock)
            assert fr is not None and fr[0] == CH_HELLO
            head, raw = encode_tensor(np.ones(4, np.float32))  # raw tag!
            send_frame(sock, CH_TENSOR, 0, head + raw)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:     # wait for the close
                try:
                    if recv_frame(sock) is None:
                        break
                except (ProtocolError, OSError):
                    break
            sock.close()
            assert recv.qsize() == 0               # nothing was enqueued
            # sibling codec channel on the same hub keeps flowing
            other.send(np.full(3, 2.0, np.float32), sync=True, timeout=10)
            assert np.allclose(other_recv.recv(timeout=10),
                               np.full(3, 2.0, np.float32), atol=0.05)
            # ...and the poisoned lane resumes at seq 0 for a clean peer
            ok = _mk_sender(port, name="g", reg=reg, codec="int8")
            ok.send(np.full(2, 3.0, np.float32), sync=True, timeout=10)
            assert np.allclose(recv.recv(timeout=10),
                               np.full(2, 3.0, np.float32), atol=0.05)
            ok.close()
        finally:
            other.close()
            hub.stop()

    def test_resend_window_holds_encoded_buffer(self):
        """The satellite pin: the sender's resend window retains the
        POST-encode payload — under int8 the parked host memory is ~1/4
        of the raw copies a pre-codec window would hold."""

        def window_bytes_for(codec):
            # a hub that handshakes but never acks: every frame parks in
            # the sender's window deterministically
            srv = socket.socket()
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)
            stop = threading.Event()

            def fake_hub():
                conn, _ = srv.accept()
                with conn:
                    conn.settimeout(10)
                    assert conn.recv(len(CH_MAGIC)) == CH_MAGIC
                    fr = recv_frame(conn)
                    assert fr is not None and fr[0] == CH_HELLO
                    send_frame(conn, CH_HELLO, 0,
                               pack_json({"v": 1, "resume": 0}))
                    stop.wait(20)

            t = threading.Thread(target=fake_hub, daemon=True)
            t.start()
            sender = ChannelSender(
                f"127.0.0.1:{srv.getsockname()[1]}", "t", window=4,
                codec=codec, registry=MetricsRegistry())
            try:
                a = np.random.RandomState(0).randn(64, 64) \
                    .astype(np.float32)
                for _ in range(4):
                    sender.send(a, timeout=20)
                assert sender.unacked() == 4
                return sender.window_bytes()
            finally:
                stop.set()
                sender.close(drain=False)
                srv.close()

        raw_bytes = window_bytes_for("none")
        int8_bytes = window_bytes_for("int8")
        assert raw_bytes / int8_bytes >= 1.9, (raw_bytes, int8_bytes)


class TestChannelRegistry:
    def test_two_stage_wiring(self):
        tasks = {
            "stage0": [("stage0:0", "hostA", 1001)],
            "stage1": [("stage1:0", "hostB", 2001)],
        }
        specs = build_channel_specs(["stage0", "stage1"],
                                    lambda jt: tasks[jt])
        assert specs["stage0:0"] == {
            "stage": 0, "num_stages": 2, "rank": 0, "ranks": 1,
            "prev": "", "next": "hostB:2001"}
        assert specs["stage1:0"] == {
            "stage": 1, "num_stages": 2, "rank": 0, "ranks": 1,
            "prev": "hostA:1001", "next": ""}

    def test_rank_pairing_multi_host_stages(self):
        tasks = {
            "a": [("a:0", "h0", 10), ("a:1", "h1", 11)],
            "b": [("b:0", "h2", 20), ("b:1", "h3", 21)],
            "c": [("c:0", "h4", 30), ("c:1", "h5", 31)],
        }
        specs = build_channel_specs(["a", "b", "c"], lambda jt: tasks[jt])
        assert specs["b:1"]["prev"] == "h1:11"
        assert specs["b:1"]["next"] == "h5:31"
        assert specs["b:1"]["stage"] == 1 and specs["b:1"]["rank"] == 1
        assert specs["c:0"]["next"] == ""

    def test_session_channel_spec_rides_barrier_release(self):
        from tony_tpu.cluster.session import Session
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({"tony.stage0.instances": "1",
                           "tony.stage1.instances": "1",
                           "tony.pipeline.stages": "stage0,stage1"})
        s = Session(conf)
        assert s.register_task_spec("stage0:0", "hA:5000", 6000) is None
        assert s.channel_spec_for("stage0:0") == ""      # barrier held
        payload = s.register_task_spec("stage1:0", "hB:5001", 6001)
        assert payload is not None
        import json
        spec0 = json.loads(s.channel_spec_for("stage0:0"))
        spec1 = json.loads(s.channel_spec_for("stage1:0"))
        assert spec0["next"] == "hB:6001" and spec0["stage"] == 0
        assert spec1["prev"] == "hA:6000" and spec1["stage"] == 1

    def test_non_pipeline_job_has_no_channel_spec(self):
        from tony_tpu.cluster.session import Session
        from tony_tpu.conf.config import TonyConfig
        s = Session(TonyConfig({"tony.worker.instances": "1"}))
        s.register_task_spec("worker:0", "h:1", 9999)
        assert s.channel_spec_for("worker:0") == ""

    def test_config_rejects_unknown_stage_type(self):
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({"tony.stage0.instances": "1",
                           "tony.pipeline.stages": "stage0,stage9"})
        with pytest.raises(ValueError, match="stage9"):
            conf.task_requests()

    def test_config_rejects_mismatched_stage_hosts(self):
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({"tony.stage0.instances": "2",
                           "tony.stage1.instances": "1",
                           "tony.pipeline.stages": "stage0,stage1"})
        with pytest.raises(ValueError, match="mismatched host counts"):
            conf.task_requests()

    def test_config_rejects_single_stage(self):
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({"tony.stage0.instances": "1",
                           "tony.pipeline.stages": "stage0"})
        with pytest.raises(ValueError, match="at least 2"):
            conf.task_requests()

    def test_program_key_parsed_into_request(self):
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({"tony.stage0.instances": "1",
                           "tony.stage1.instances": "1",
                           "tony.pipeline.stages": "stage0,stage1",
                           "tony.stage0.program": "python s0.py",
                           "tony.stage1.program": "python s1.py"})
        reqs = conf.task_requests()
        assert reqs["stage0"].program == "python s0.py"
        assert reqs["stage1"].program == "python s1.py"

    def test_interleave_closes_the_ring(self):
        """With interleave > 1 every chunk boundary crosses gangs, so
        the boundary stages need neighbors too: stage 0's prev wraps to
        the last stage and vice versa, and the spec carries the
        interleave + codec for the trainers."""
        tasks = {
            "stage0": [("stage0:0", "hostA", 1001)],
            "stage1": [("stage1:0", "hostB", 2001)],
        }
        specs = build_channel_specs(["stage0", "stage1"],
                                    lambda jt: tasks[jt],
                                    interleave=2, compression="int8")
        assert specs["stage0:0"]["prev"] == "hostB:2001"      # ring wrap
        assert specs["stage0:0"]["next"] == "hostB:2001"
        assert specs["stage1:0"]["prev"] == "hostA:1001"
        assert specs["stage1:0"]["next"] == "hostA:1001"      # ring wrap
        for spec in specs.values():
            assert spec["interleave"] == 2
            assert spec["compression"] == "int8"

    def test_default_spec_carries_no_new_fields(self):
        """interleave=1 / compression="none" keep the spec byte-
        compatible with pre-codec coordinators (additive fields only)."""
        tasks = {"a": [("a:0", "h0", 10)], "b": [("b:0", "h1", 11)]}
        specs = build_channel_specs(["a", "b"], lambda jt: tasks[jt])
        for spec in specs.values():
            assert "interleave" not in spec
            assert "compression" not in spec

    def test_chunk_lane_names(self):
        assert act_channel(0) == ACT_CHANNEL
        assert act_channel(1) == f"{ACT_CHANNEL}.1"
        assert grad_channel(0) != grad_channel(1)

    def test_stage_env_parses_interleave_and_codec(self):
        from tony_tpu.channels import stage_env
        env = {"TONY_PIPELINE_STAGE": "1",
               "TONY_PIPELINE_NUM_STAGES": "2",
               "TONY_CHANNEL_PREV": "h0:1", "TONY_CHANNEL_NEXT": "h0:2",
               "TONY_PIPELINE_INTERLEAVE": "2",
               "TONY_CHANNEL_COMPRESSION": "int8"}
        parsed = stage_env(env)
        assert parsed["interleave"] == 2
        assert parsed["compression"] == "int8"
        env.pop("TONY_PIPELINE_INTERLEAVE")
        env.pop("TONY_CHANNEL_COMPRESSION")
        parsed = stage_env(env)
        assert parsed["interleave"] == 1
        assert parsed["compression"] == "none"

    def test_config_rejects_unknown_compression(self):
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({"tony.stage0.instances": "1",
                           "tony.stage1.instances": "1",
                           "tony.pipeline.stages": "stage0,stage1",
                           "tony.channel.compression": "gzip"})
        with pytest.raises(ValueError, match="gzip"):
            conf.task_requests()

    def test_config_rejects_nonpositive_interleave(self):
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({"tony.stage0.instances": "1",
                           "tony.stage1.instances": "1",
                           "tony.pipeline.stages": "stage0,stage1",
                           "tony.pipeline.interleave": "0"})
        with pytest.raises(ValueError, match="interleave"):
            conf.task_requests()

    def test_session_spec_carries_interleave_and_codec(self):
        import json as _json

        from tony_tpu.cluster.session import Session
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({"tony.stage0.instances": "1",
                           "tony.stage1.instances": "1",
                           "tony.pipeline.stages": "stage0,stage1",
                           "tony.pipeline.interleave": "2",
                           "tony.channel.compression": "bf16"})
        s = Session(conf)
        s.register_task_spec("stage0:0", "hA:5000", 6000)
        s.register_task_spec("stage1:0", "hB:5001", 6001)
        spec0 = _json.loads(s.channel_spec_for("stage0:0"))
        assert spec0["interleave"] == 2
        assert spec0["compression"] == "bf16"
        assert spec0["prev"] == "hB:6001"        # ring wrap at stage 0


# ---------------------------------------------------------------------------
# THE numerical pin: cross-slice == in-slice, bit for bit
# ---------------------------------------------------------------------------
class TestCrossSliceBitIdentity:
    # bit-identity pins: the conftest guard forbids quantized codecs here
    pytestmark = pytest.mark.exact
    DIM, MB, M = 8, 4, 4

    def _model(self):
        import jax.numpy as jnp

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        def loss_head(hp, out, tgt):
            return jnp.mean((out @ hp["wo"] - tgt) ** 2)
        rs = np.random.RandomState(0)
        stacked = {
            "w": rs.randn(2, self.DIM, self.DIM).astype(np.float32) * 0.3,
            "b": rs.randn(2, self.DIM).astype(np.float32) * 0.1,
        }
        head = {"wo": rs.randn(self.DIM, self.DIM).astype(np.float32) * 0.2}
        x = rs.randn(self.M * self.MB, self.DIM).astype(np.float32)
        tgt = rs.randn(self.M * self.MB, self.DIM).astype(np.float32)
        return stage_fn, loss_head, stacked, head, x, tgt

    def _run_cross_slice(self, stage_fn, loss_head, stacked, head, x, tgt,
                         lookahead=0, sync=False):
        import jax
        import jax.numpy as jnp

        from tony_tpu.parallel.pipeline import CrossSlicePipeline
        reg = MetricsRegistry()
        links = open_local_pipeline(2, registry=reg)
        xs = jnp.asarray(x).reshape(self.M, self.MB, self.DIM)
        tgts = jnp.asarray(tgt).reshape(self.M, self.MB, self.DIM)
        out = {}

        def run(stage):
            params = jax.tree.map(lambda v: jnp.asarray(v[stage]), stacked)
            pipe = CrossSlicePipeline(
                stage_fn, links[stage],
                loss_head=loss_head if stage == 1 else None,
                lookahead=lookahead, sync_transport=sync)
            out[stage] = pipe.value_and_grad(
                params, num_microbatches=self.M,
                microbatches=xs if stage == 0 else None,
                head_params=head if stage == 1 else None,
                head_batches=tgts if stage == 1 else None)

        try:
            threads = [threading.Thread(target=run, args=(s,))
                       for s in (0, 1)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert 0 in out and 1 in out, "stage thread did not finish"
        finally:
            for link in links:
                link.close()
        return out

    def test_loss_and_grads_bit_identical_to_in_slice(self):
        import jax
        from jax.sharding import Mesh

        from tony_tpu.parallel.pipeline import pipeline_value_and_grad
        stage_fn, loss_head, stacked, head, x, tgt = self._model()
        mesh = Mesh(np.array(jax.devices()[:2]), ("pp",))
        import jax.numpy as jnp
        loss_ref, g_ref, hg_ref, dx_ref = pipeline_value_and_grad(
            stage_fn, jax.tree.map(jnp.asarray, stacked), jnp.asarray(x),
            jax.tree.map(jnp.asarray, head), jnp.asarray(tgt), mesh,
            loss_head=loss_head, num_microbatches=self.M)

        out = self._run_cross_slice(stage_fn, loss_head, stacked, head,
                                    x, tgt)
        loss_x = out[1][0]
        assert np.array_equal(np.asarray(loss_ref), np.asarray(loss_x)), \
            (float(loss_ref), float(loss_x))
        for stage in (0, 1):
            for k in ("w", "b"):
                a = np.asarray(g_ref[k][stage])
                b = np.asarray(out[stage][1][k])
                assert np.array_equal(a, b), (stage, k)
        assert np.array_equal(np.asarray(hg_ref["wo"]),
                              np.asarray(out[1][2]["wo"]))
        dx = np.asarray(out[0][3]).reshape(np.asarray(dx_ref).shape)
        assert np.array_equal(np.asarray(dx_ref), dx)

    def test_lookahead_and_sync_do_not_change_math(self):
        """The latency-tolerance knob (extra in-flight microbatches) and
        the serialized-transport mode reshuffle WALLS only — backward
        accumulation order is fixed, so results stay bit-identical."""
        stage_fn, loss_head, stacked, head, x, tgt = self._model()
        base = self._run_cross_slice(stage_fn, loss_head, stacked, head,
                                     x, tgt)
        ahead = self._run_cross_slice(stage_fn, loss_head, stacked, head,
                                      x, tgt, lookahead=3)
        synced = self._run_cross_slice(stage_fn, loss_head, stacked, head,
                                       x, tgt, sync=True)
        import jax
        for other in (ahead, synced):
            assert np.array_equal(np.asarray(base[1][0]),
                                  np.asarray(other[1][0]))
            for stage in (0, 1):
                for a, b in zip(jax.tree.leaves(base[stage][1]),
                                jax.tree.leaves(other[stage][1])):
                    assert np.array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Shared trainer harness: N-step cross-slice training at any
# (stages, interleave, codec), and the in-slice reference — the
# loss-curve-equivalence pins for BOTH compression and interleave run
# through these.
# ---------------------------------------------------------------------------
_H_DIM, _H_MB, _H_M, _H_LR = 8, 4, 4, 0.1


def _h_block(g: int):
    rs = np.random.RandomState(100 + g)
    return {"w": rs.randn(_H_DIM, _H_DIM).astype(np.float32) * 0.3,
            "b": rs.randn(_H_DIM).astype(np.float32) * 0.1}


def _h_head():
    rs = np.random.RandomState(999)
    return {"wo": rs.randn(_H_DIM, _H_DIM).astype(np.float32) * 0.2}


def _h_batch(step: int):
    rs = np.random.RandomState(5000 + step)
    return (rs.randn(_H_M, _H_MB, _H_DIM).astype(np.float32),
            rs.randn(_H_M, _H_MB, _H_DIM).astype(np.float32))


def _h_model():
    import jax.numpy as jnp

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def loss_head(hp, out, tgt):
        return jnp.mean((out @ hp["wo"] - tgt) ** 2)
    return stage_fn, loss_head


def _train_cross_slice(steps: int, *, num_stages: int = 2,
                       interleave: int = 1, compression: str = "none"):
    """Train the V = S*v block model over real loopback channels for
    ``steps`` SGD steps. Returns (losses, params-by-virtual-stage,
    head_params) with everything as host arrays."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.parallel.pipeline import CrossSlicePipeline
    stage_fn, loss_head = _h_model()
    S, v = num_stages, interleave
    V = S * v
    reg = MetricsRegistry()
    links = open_local_pipeline(S, interleave=v, compression=compression,
                                registry=reg)
    out: dict = {}
    failures: list = []

    def run_gang(s: int) -> None:
        try:
            pipe = CrossSlicePipeline(
                stage_fn, links[s],
                loss_head=loss_head if s == S - 1 else None, registry=reg)
            if v == 1:
                params = jax.tree.map(jnp.asarray, _h_block(s))
            else:
                params = [jax.tree.map(jnp.asarray, _h_block(j * S + s))
                          for j in range(v)]
            head = jax.tree.map(jnp.asarray, _h_head()) \
                if s == S - 1 else None
            losses = []
            for step in range(steps):
                x, tgt = _h_batch(step)
                loss, grads, hgrads, _ = pipe.value_and_grad(
                    params, num_microbatches=_H_M,
                    microbatches=jnp.asarray(x) if s == 0 else None,
                    head_params=head,
                    head_batches=jnp.asarray(tgt) if s == S - 1 else None)
                params = jax.tree.map(lambda p, g: p - _H_LR * g,
                                      params, grads)
                if s == S - 1:
                    head = jax.tree.map(lambda p, g: p - _H_LR * g,
                                        head, hgrads)
                    losses.append(np.asarray(loss))
            out[s] = (params, head, losses)
        except BaseException as exc:
            failures.append(exc)

    try:
        threads = [threading.Thread(target=run_gang, args=(s,))
                   for s in range(S)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        if failures:
            raise failures[0]
        assert len(out) == S, "gang thread did not finish"
    finally:
        for link in links:
            link.close()
    by_virtual = {}
    for s in range(S):
        params = out[s][0]
        chunks = [params] if v == 1 else params
        for j, chunk in enumerate(chunks):
            by_virtual[j * S + s] = jax.tree.map(np.asarray, chunk)
    losses = np.asarray(out[S - 1][2], np.float32).reshape(steps)
    head = jax.tree.map(np.asarray, out[S - 1][1])
    return losses, by_virtual, head


def _train_in_slice(steps: int, num_virtual: int):
    """The reference: the SAME V-block model trained with the in-slice
    1F1B schedule (one device per virtual stage on the pp mesh)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from tony_tpu.parallel.pipeline import pipeline_value_and_grad
    stage_fn, loss_head = _h_model()
    V = num_virtual
    mesh = Mesh(np.array(jax.devices()[:V]), ("pp",))
    stacked = jax.tree.map(lambda *a: jnp.stack(a),
                           *[_h_block(g) for g in range(V)])
    head = jax.tree.map(jnp.asarray, _h_head())
    losses = []
    for step in range(steps):
        x, tgt = _h_batch(step)
        loss, g_sp, g_hp, _ = pipeline_value_and_grad(
            stage_fn, stacked, jnp.asarray(x.reshape(-1, _H_DIM)), head,
            jnp.asarray(tgt.reshape(-1, _H_DIM)), mesh,
            loss_head=loss_head, num_microbatches=_H_M)
        stacked = jax.tree.map(lambda p, g: p - _H_LR * g, stacked, g_sp)
        head = jax.tree.map(lambda p, g: p - _H_LR * g, head, g_hp)
        losses.append(np.asarray(loss))
    by_virtual = {g: jax.tree.map(lambda a: np.asarray(a[g]), stacked)
                  for g in range(V)}
    return (np.asarray(losses, np.float32).reshape(steps), by_virtual,
            jax.tree.map(np.asarray, head))


class TestInterleavedBitIdentity:
    """Interleaved 1F1B (v virtual stages per gang) must not change the
    math: with compression off, chunk j of gang s is bit-identical to
    virtual stage j*S+s of the in-slice V-stage schedule — across a
    multi-step TRAINING RUN, not just one step."""
    pytestmark = pytest.mark.exact
    STEPS = 3

    def _pin(self, got, ref):
        losses, by_virtual, head = got
        ref_losses, ref_virtual, ref_head = ref
        assert np.array_equal(losses, ref_losses), (losses, ref_losses)
        for g, chunk in ref_virtual.items():
            for k in chunk:
                assert np.array_equal(by_virtual[g][k], chunk[k]), (g, k)
        assert np.array_equal(head["wo"], ref_head["wo"])

    def test_v1_training_bit_identical_to_in_slice(self):
        self._pin(_train_cross_slice(self.STEPS),
                  _train_in_slice(self.STEPS, 2))

    def test_v2_training_bit_identical_to_in_slice_4deep(self):
        self._pin(_train_cross_slice(self.STEPS, interleave=2),
                  _train_in_slice(self.STEPS, 4))


class TestLossCurveEquivalence:
    """The quantized channels change bytes, not learning: N-step loss
    curves under int8/bf16 wire codecs stay within a pinned tolerance of
    the f32 curve (which itself is bit-identical to in-slice — pinned
    above), and training still converges."""
    STEPS = 4

    @pytest.fixture(scope="class")
    def f32_curve(self):
        return _train_cross_slice(self.STEPS)[0]

    def _pin_curve(self, losses, f32_losses):
        assert losses.shape == f32_losses.shape
        # per-tensor int8 adds ~0.8% relative error per hop; the curve
        # must track f32 within 10% relative and keep descending
        np.testing.assert_allclose(losses, f32_losses, rtol=0.1,
                                   atol=5e-3)
        assert losses[-1] < losses[0]

    def test_int8_curve_tracks_f32(self, f32_curve):
        losses, _, _ = _train_cross_slice(self.STEPS, compression="int8")
        assert not np.array_equal(losses, f32_curve)   # it IS quantized
        self._pin_curve(losses, f32_curve)

    def test_bf16_curve_tracks_f32(self, f32_curve):
        losses, _, _ = _train_cross_slice(self.STEPS, compression="bf16")
        self._pin_curve(losses, f32_curve)

    def test_interleave_plus_int8_curve_tracks_f32(self):
        # the composed mode: v=2 AND quantized lanes vs v=2 f32
        f32_il = _train_cross_slice(self.STEPS, interleave=2)[0]
        q_il = _train_cross_slice(self.STEPS, interleave=2,
                                  compression="int8")[0]
        self._pin_curve(q_il, f32_il)


class TestExactnessGuard:
    """The CI tripwire: inside ``exact``-marked tests the conftest
    fixture arms channels.forbid_codecs, so building any quantized
    channel endpoint fails at the construction site."""

    @pytest.mark.exact
    def test_exact_marker_forbids_codec_channels(self):
        hub, port, reg = _mk_hub()
        try:
            with pytest.raises(RuntimeError, match="bit-exactness"):
                _mk_sender(port, reg=reg, codec="int8")
            with pytest.raises(RuntimeError, match="bit-exactness"):
                hub.receiver("t", codec="bf16")
            # raw channels stay usable inside exactness pins
            sender = _mk_sender(port, reg=reg)
            recv = hub.receiver("t")
            sender.send(np.ones(2, np.float32), sync=True, timeout=10)
            assert np.array_equal(recv.recv(timeout=10),
                                  np.ones(2, np.float32))
            sender.close()
        finally:
            hub.stop()

    def test_codecs_allowed_outside_exact_tests(self):
        hub, port, reg = _mk_hub()
        try:
            sender = _mk_sender(port, reg=reg, codec="int8")
            hub.receiver("t", codec="int8")
            sender.close(drain=False)
        finally:
            hub.stop()


# ---------------------------------------------------------------------------
# Bench pins
# ---------------------------------------------------------------------------
class TestPipelineBench:
    def test_overlap_vs_serialized_tier1(self):
        """The tentpole ratio, deterministically: overlapped 1F1B must
        beat serialized stage execution >= 1.5x under injected DCN
        latency (the arm itself also asserts channel walls + queue
        depths are visible on the metrics plane)."""
        import bench
        res = bench._pipeline_arm()
        assert res["pipeline_overlap_vs_serialized_wall"] >= 1.5, res
        assert 0.0 <= res["pipeline_bubble_fraction"] < 1.0, res

    def test_dcn_bytes_and_interleave_tier1(self):
        """The DCN-bytes tentpole pins, deterministically: int8 cuts
        pipeline bytes-on-wire >= 1.9x, and the interleaved (v=2)
        placement beats the flat one under 50 ms one-way DCN latency
        with fixed compute floors — both on the end-to-end wall
        (measured ~1.03-1.07x at M=24; fill drag included) and, with
        real margin, on the steady-state per-microbatch rate (the
        two-point marginal wall, fill cancelled; measured ~1.13x and
        load-stable because host jitter inflates both placements
        together)."""
        import bench
        res = bench._pipeline_dcn_arm()
        assert res["pipeline_bytes_on_wire_vs_raw"] >= 1.9, res
        assert res["pipeline_interleaved_vs_flat_wall"] > 1.0, res
        assert res["pipeline_interleaved_vs_flat_steady_rate"] >= 1.05, res

    @pytest.mark.slow
    def test_overlap_latency_realistic(self):
        """Latency-realistic variant: a WAN-ish 80 ms round trip and no
        compute floors beyond the tiny jitted blocks — the overlap win
        grows with the latency/compute ratio."""
        import bench
        res = bench._pipeline_arm(one_way_s=0.04, fwd_floor_s=0.002,
                                  bwd_floor_s=0.004, num_microbatches=12,
                                  window=16, lookahead=8)
        assert res["pipeline_overlap_vs_serialized_wall"] >= 2.0, res
