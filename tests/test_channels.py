"""Inter-gang tensor channels + cross-slice 1F1B (tier-1).

The acceptance suite for the MPMD pipeline data path:

- transport semantics: typed TENSOR frames, bounded send windows
  (backpressure, never unbounded buffering), reconnect-with-seq-resume
  (no duplicated/dropped microbatch), channel-scoped failure (garbage
  costs one connection, the hub keeps serving);
- the coordinator-owned channel registry (stage wiring, rank pairing,
  config validation);
- THE numerical pin: cross-slice 1F1B loss/grads bit-identical to the
  in-slice ``pipeline_value_and_grad`` schedule on the same
  params/microbatches — moving a model across slices never changes what
  it learns;
- the bench pin: overlapped 1F1B >= 1.5x serialized stage execution
  under injected DCN latency, channel walls/queue depths visible on the
  metrics plane.
"""

import socket
import threading
import time

import numpy as np
import pytest

from tony_tpu.channels import (ACT_CHANNEL, ChannelError, ChannelHub,
                               ChannelSender, build_channel_specs,
                               decode_tensor, encode_tensor,
                               open_local_pipeline)
from tony_tpu.channels.channel import CH_HELLO, CH_MAGIC, CH_TENSOR
from tony_tpu.runtime.metrics import MetricsRegistry
from tony_tpu.serving.protocol import (ProtocolError, pack_json,
                                       recv_frame, send_frame)


def _mk_hub(capacity=8):
    reg = MetricsRegistry()
    hub = ChannelHub(capacity=capacity, registry=reg)
    port = hub.start()
    return hub, port, reg


def _mk_sender(port, name="t", *, window=8, reg=None, **kw):
    return ChannelSender(f"127.0.0.1:{port}", name,
                         window=window, registry=reg or MetricsRegistry(),
                         **kw)


class TestTensorCodec:
    def test_round_trip_dtypes_and_shapes(self):
        for arr in (np.arange(12, dtype=np.float32).reshape(3, 4),
                    np.array(3.5, dtype=np.float64),
                    np.zeros((0, 5), dtype=np.int32),
                    np.random.RandomState(0).randn(2, 3, 4)
                    .astype(np.float16)):
            head, raw = encode_tensor(arr)
            out = decode_tensor(head + raw)
            assert out.dtype == arr.dtype and out.shape == arr.shape
            assert np.array_equal(out, arr, equal_nan=True)

    def test_non_contiguous_input(self):
        arr = np.arange(24, dtype=np.float32).reshape(4, 6)[:, ::2]
        head, raw = encode_tensor(arr)
        assert np.array_equal(decode_tensor(head + raw), arr)

    @pytest.mark.parametrize("payload", [
        b"",                                     # shorter than prefix
        b"\x05\x00\x00\x00ab",                   # header len > frame
        b"\x02\x00\x00\x00{}",                   # header not dtype/shape
    ])
    def test_malformed_payloads_raise_protocol_error(self, payload):
        with pytest.raises(ProtocolError):
            decode_tensor(payload)

    def test_size_mismatch_raises(self):
        head, raw = encode_tensor(np.zeros(4, np.float32))
        with pytest.raises(ProtocolError):
            decode_tensor(head + raw[:-1])


class TestChannelTransport:
    def test_ordered_delivery(self):
        hub, port, reg = _mk_hub()
        sender = _mk_sender(port, reg=reg)
        recv = hub.receiver("t")
        try:
            sent = [np.full((2, 2), i, np.float32) for i in range(20)]
            got: list = []
            consumer = threading.Thread(
                target=lambda: got.extend(recv.recv(timeout=30)
                                          for _ in range(20)))
            consumer.start()       # window < 20: consume concurrently
            for a in sent:
                sender.send(a, timeout=30)
            consumer.join(timeout=30)
            assert len(got) == 20
            for a, b in zip(sent, got):
                assert np.array_equal(a, b)
        finally:
            sender.close()
            hub.stop()

    def test_bounded_window_blocks_instead_of_buffering(self):
        """With the consumer stalled, the sender admits at most
        window + receiver-capacity frames and then BLOCKS — host memory
        never absorbs an unbounded backlog."""
        hub, port, reg = _mk_hub(capacity=1)
        sender = _mk_sender(port, window=2, reg=reg)
        recv = hub.receiver("t")
        done = []

        def producer():
            for i in range(8):
                sender.send(np.full((4,), i, np.float32), timeout=30)
                done.append(i)

        t = threading.Thread(target=producer, daemon=True)
        try:
            t.start()
            time.sleep(1.0)
            # nobody consumed: 2 in the window + 1 parked in the hub
            # queue can clear; the producer must be parked well short
            # of 8
            assert len(done) <= 4, done
            assert t.is_alive()
            got = [recv.recv(timeout=10) for _ in range(8)]
            t.join(timeout=10)
            assert not t.is_alive() and len(done) == 8
            assert [int(a[0]) for a in got] == list(range(8))
        finally:
            sender.close(drain=False)
            hub.stop()

    def test_reconnect_resumes_at_receiver_seq(self):
        """Severing the socket mid-stream (hub keeps its state) loses
        nothing: the sender reconnects, learns the receiver's resume
        point, and the consumer sees every microbatch exactly once."""
        hub, port, reg = _mk_hub()
        sender = _mk_sender(port, reg=reg)
        recv = hub.receiver("t")
        got = []

        def consumer():
            for _ in range(30):
                got.append(int(recv.recv(timeout=30)[0]))

        t = threading.Thread(target=consumer, daemon=True)
        try:
            t.start()
            for i in range(30):
                sender.send(np.full((3,), i, np.float32), timeout=30)
                if i in (7, 19):
                    hub.disconnect_all()       # transient DCN blip
            sender.drain(timeout=30)
            t.join(timeout=30)
            assert got == list(range(30)), got
            assert reg.counter("tony_channel_reconnects_total",
                               channel="t").value >= 1
        finally:
            sender.close(drain=False)
            hub.stop()

    def test_sync_send_waits_for_ack(self):
        hub, port, reg = _mk_hub()
        sender = _mk_sender(port, reg=reg)
        recv = hub.receiver("t")
        try:
            sender.send(np.zeros(2, np.float32), sync=True, timeout=10)
            assert sender.unacked() == 0
            assert np.array_equal(recv.recv(timeout=5),
                                  np.zeros(2, np.float32))
        finally:
            sender.close()
            hub.stop()

    def test_unreachable_peer_raises_after_budget(self):
        with socket.socket() as s:       # reserve a port nobody serves
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        sender = ChannelSender(f"127.0.0.1:{port}", "t", window=2,
                               max_retries=2, backoff_s=0.01,
                               registry=MetricsRegistry())
        with pytest.raises(ChannelError):
            sender.send(np.zeros(1, np.float32), timeout=5)
        sender.close(drain=False)


class TestChannelFailureScoping:
    def _raw_conn(self, port):
        sock = socket.create_connection(("127.0.0.1", port), timeout=5)
        sock.sendall(CH_MAGIC)
        send_frame(sock, CH_HELLO, 0, pack_json({"v": 1, "channel": "g"}))
        fr = recv_frame(sock)
        assert fr is not None and fr[0] == CH_HELLO
        return sock

    def test_garbage_tensor_frame_is_channel_scoped(self):
        """A connection feeding undecodable TENSOR payloads dies alone:
        the hub keeps serving its OTHER channel, and the garbage
        channel's state survives for a clean resume."""
        hub, port, reg = _mk_hub()
        sender = _mk_sender(port, name="good", reg=reg)
        good = hub.receiver("good")
        try:
            bad = self._raw_conn(port)
            send_frame(bad, CH_TENSOR, 0, b"\xff\xff\xff\xffjunk")
            # the hub answers with CH_ERROR (or just closes) — either
            # way the connection ends...
            assert recv_frame(bad) is None or True
            bad.close()
            # ...and the good channel keeps flowing
            sender.send(np.ones(4, np.float32))
            assert np.array_equal(good.recv(timeout=10),
                                  np.ones(4, np.float32))
            # a well-behaved peer then resumes channel "g" at seq 0
            again = self._raw_conn(port)
            again.close()
        finally:
            sender.close()
            hub.stop()

    def test_truncated_frame_mid_stream(self):
        """A peer dying mid-frame (length prefix promised more bytes)
        costs only that connection."""
        hub, port, reg = _mk_hub()
        sender = _mk_sender(port, name="good", reg=reg)
        good = hub.receiver("good")
        try:
            bad = self._raw_conn(port)
            bad.sendall(b"\xf0\x00\x00\x00")      # 240-byte frame promised
            bad.sendall(b"\x02partial")            # ...never delivered
            bad.close()
            sender.send(np.full(2, 7, np.float32))
            assert np.array_equal(good.recv(timeout=10),
                                  np.full(2, 7, np.float32))
        finally:
            sender.close()
            hub.stop()

    def test_stray_peer_wrong_magic(self):
        hub, port, reg = _mk_hub()
        try:
            sock = socket.create_connection(("127.0.0.1", port), timeout=5)
            sock.sendall(b"GET / HTTP/1.1\r\n\r\n")
            sock.settimeout(2)
            try:
                data = sock.recv(64)
            except ConnectionResetError:
                data = b""     # RST instead of FIN: still a rejection
            assert data == b""                     # closed at byte 0
            sock.close()
        finally:
            hub.stop()

    def test_seq_gap_closes_connection_state_survives(self):
        hub, port, reg = _mk_hub()
        recv = hub.receiver("g")
        try:
            bad = self._raw_conn(port)
            head, raw = encode_tensor(np.ones(2, np.float32))
            send_frame(bad, CH_TENSOR, 5, head + raw)   # expected seq 0
            # connection-scoped error; nothing was enqueued
            deadline = time.monotonic() + 5
            while recv.qsize() == 0 and time.monotonic() < deadline:
                fr = None
                try:
                    fr = recv_frame(bad)
                except (ProtocolError, OSError):
                    break
                if fr is None:
                    break
            assert recv.qsize() == 0
            bad.close()
            # a correct sender still starts cleanly at seq 0
            sender = _mk_sender(port, name="g", reg=reg)
            sender.send(np.full(2, 3, np.float32))
            assert np.array_equal(recv.recv(timeout=10),
                                  np.full(2, 3, np.float32))
            sender.close()
        finally:
            hub.stop()


class TestChannelRegistry:
    def test_two_stage_wiring(self):
        tasks = {
            "stage0": [("stage0:0", "hostA", 1001)],
            "stage1": [("stage1:0", "hostB", 2001)],
        }
        specs = build_channel_specs(["stage0", "stage1"],
                                    lambda jt: tasks[jt])
        assert specs["stage0:0"] == {
            "stage": 0, "num_stages": 2, "rank": 0, "ranks": 1,
            "prev": "", "next": "hostB:2001"}
        assert specs["stage1:0"] == {
            "stage": 1, "num_stages": 2, "rank": 0, "ranks": 1,
            "prev": "hostA:1001", "next": ""}

    def test_rank_pairing_multi_host_stages(self):
        tasks = {
            "a": [("a:0", "h0", 10), ("a:1", "h1", 11)],
            "b": [("b:0", "h2", 20), ("b:1", "h3", 21)],
            "c": [("c:0", "h4", 30), ("c:1", "h5", 31)],
        }
        specs = build_channel_specs(["a", "b", "c"], lambda jt: tasks[jt])
        assert specs["b:1"]["prev"] == "h1:11"
        assert specs["b:1"]["next"] == "h5:31"
        assert specs["b:1"]["stage"] == 1 and specs["b:1"]["rank"] == 1
        assert specs["c:0"]["next"] == ""

    def test_session_channel_spec_rides_barrier_release(self):
        from tony_tpu.cluster.session import Session
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({"tony.stage0.instances": "1",
                           "tony.stage1.instances": "1",
                           "tony.pipeline.stages": "stage0,stage1"})
        s = Session(conf)
        assert s.register_task_spec("stage0:0", "hA:5000", 6000) is None
        assert s.channel_spec_for("stage0:0") == ""      # barrier held
        payload = s.register_task_spec("stage1:0", "hB:5001", 6001)
        assert payload is not None
        import json
        spec0 = json.loads(s.channel_spec_for("stage0:0"))
        spec1 = json.loads(s.channel_spec_for("stage1:0"))
        assert spec0["next"] == "hB:6001" and spec0["stage"] == 0
        assert spec1["prev"] == "hA:6000" and spec1["stage"] == 1

    def test_non_pipeline_job_has_no_channel_spec(self):
        from tony_tpu.cluster.session import Session
        from tony_tpu.conf.config import TonyConfig
        s = Session(TonyConfig({"tony.worker.instances": "1"}))
        s.register_task_spec("worker:0", "h:1", 9999)
        assert s.channel_spec_for("worker:0") == ""

    def test_config_rejects_unknown_stage_type(self):
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({"tony.stage0.instances": "1",
                           "tony.pipeline.stages": "stage0,stage9"})
        with pytest.raises(ValueError, match="stage9"):
            conf.task_requests()

    def test_config_rejects_mismatched_stage_hosts(self):
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({"tony.stage0.instances": "2",
                           "tony.stage1.instances": "1",
                           "tony.pipeline.stages": "stage0,stage1"})
        with pytest.raises(ValueError, match="mismatched host counts"):
            conf.task_requests()

    def test_config_rejects_single_stage(self):
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({"tony.stage0.instances": "1",
                           "tony.pipeline.stages": "stage0"})
        with pytest.raises(ValueError, match="at least 2"):
            conf.task_requests()

    def test_program_key_parsed_into_request(self):
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({"tony.stage0.instances": "1",
                           "tony.stage1.instances": "1",
                           "tony.pipeline.stages": "stage0,stage1",
                           "tony.stage0.program": "python s0.py",
                           "tony.stage1.program": "python s1.py"})
        reqs = conf.task_requests()
        assert reqs["stage0"].program == "python s0.py"
        assert reqs["stage1"].program == "python s1.py"


# ---------------------------------------------------------------------------
# THE numerical pin: cross-slice == in-slice, bit for bit
# ---------------------------------------------------------------------------
class TestCrossSliceBitIdentity:
    DIM, MB, M = 8, 4, 4

    def _model(self):
        import jax.numpy as jnp

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        def loss_head(hp, out, tgt):
            return jnp.mean((out @ hp["wo"] - tgt) ** 2)
        rs = np.random.RandomState(0)
        stacked = {
            "w": rs.randn(2, self.DIM, self.DIM).astype(np.float32) * 0.3,
            "b": rs.randn(2, self.DIM).astype(np.float32) * 0.1,
        }
        head = {"wo": rs.randn(self.DIM, self.DIM).astype(np.float32) * 0.2}
        x = rs.randn(self.M * self.MB, self.DIM).astype(np.float32)
        tgt = rs.randn(self.M * self.MB, self.DIM).astype(np.float32)
        return stage_fn, loss_head, stacked, head, x, tgt

    def _run_cross_slice(self, stage_fn, loss_head, stacked, head, x, tgt,
                         lookahead=0, sync=False):
        import jax
        import jax.numpy as jnp

        from tony_tpu.parallel.pipeline import CrossSlicePipeline
        reg = MetricsRegistry()
        links = open_local_pipeline(2, registry=reg)
        xs = jnp.asarray(x).reshape(self.M, self.MB, self.DIM)
        tgts = jnp.asarray(tgt).reshape(self.M, self.MB, self.DIM)
        out = {}

        def run(stage):
            params = jax.tree.map(lambda v: jnp.asarray(v[stage]), stacked)
            pipe = CrossSlicePipeline(
                stage_fn, links[stage],
                loss_head=loss_head if stage == 1 else None,
                lookahead=lookahead, sync_transport=sync)
            out[stage] = pipe.value_and_grad(
                params, num_microbatches=self.M,
                microbatches=xs if stage == 0 else None,
                head_params=head if stage == 1 else None,
                head_batches=tgts if stage == 1 else None)

        try:
            threads = [threading.Thread(target=run, args=(s,))
                       for s in (0, 1)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert 0 in out and 1 in out, "stage thread did not finish"
        finally:
            for link in links:
                link.close()
        return out

    def test_loss_and_grads_bit_identical_to_in_slice(self):
        import jax
        from jax.sharding import Mesh

        from tony_tpu.parallel.pipeline import pipeline_value_and_grad
        stage_fn, loss_head, stacked, head, x, tgt = self._model()
        mesh = Mesh(np.array(jax.devices()[:2]), ("pp",))
        import jax.numpy as jnp
        loss_ref, g_ref, hg_ref, dx_ref = pipeline_value_and_grad(
            stage_fn, jax.tree.map(jnp.asarray, stacked), jnp.asarray(x),
            jax.tree.map(jnp.asarray, head), jnp.asarray(tgt), mesh,
            loss_head=loss_head, num_microbatches=self.M)

        out = self._run_cross_slice(stage_fn, loss_head, stacked, head,
                                    x, tgt)
        loss_x = out[1][0]
        assert np.array_equal(np.asarray(loss_ref), np.asarray(loss_x)), \
            (float(loss_ref), float(loss_x))
        for stage in (0, 1):
            for k in ("w", "b"):
                a = np.asarray(g_ref[k][stage])
                b = np.asarray(out[stage][1][k])
                assert np.array_equal(a, b), (stage, k)
        assert np.array_equal(np.asarray(hg_ref["wo"]),
                              np.asarray(out[1][2]["wo"]))
        dx = np.asarray(out[0][3]).reshape(np.asarray(dx_ref).shape)
        assert np.array_equal(np.asarray(dx_ref), dx)

    def test_lookahead_and_sync_do_not_change_math(self):
        """The latency-tolerance knob (extra in-flight microbatches) and
        the serialized-transport mode reshuffle WALLS only — backward
        accumulation order is fixed, so results stay bit-identical."""
        stage_fn, loss_head, stacked, head, x, tgt = self._model()
        base = self._run_cross_slice(stage_fn, loss_head, stacked, head,
                                     x, tgt)
        ahead = self._run_cross_slice(stage_fn, loss_head, stacked, head,
                                      x, tgt, lookahead=3)
        synced = self._run_cross_slice(stage_fn, loss_head, stacked, head,
                                       x, tgt, sync=True)
        import jax
        for other in (ahead, synced):
            assert np.array_equal(np.asarray(base[1][0]),
                                  np.asarray(other[1][0]))
            for stage in (0, 1):
                for a, b in zip(jax.tree.leaves(base[stage][1]),
                                jax.tree.leaves(other[stage][1])):
                    assert np.array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Bench pins
# ---------------------------------------------------------------------------
class TestPipelineBench:
    def test_overlap_vs_serialized_tier1(self):
        """The tentpole ratio, deterministically: overlapped 1F1B must
        beat serialized stage execution >= 1.5x under injected DCN
        latency (the arm itself also asserts channel walls + queue
        depths are visible on the metrics plane)."""
        import bench
        res = bench._pipeline_arm()
        assert res["pipeline_overlap_vs_serialized_wall"] >= 1.5, res
        assert 0.0 <= res["pipeline_bubble_fraction"] < 1.0, res

    @pytest.mark.slow
    def test_overlap_latency_realistic(self):
        """Latency-realistic variant: a WAN-ish 80 ms round trip and no
        compute floors beyond the tiny jitted blocks — the overlap win
        grows with the latency/compute ratio."""
        import bench
        res = bench._pipeline_arm(one_way_s=0.04, fwd_floor_s=0.002,
                                  bwd_floor_s=0.004, num_microbatches=12,
                                  window=16, lookahead=8)
        assert res["pipeline_overlap_vs_serialized_wall"] >= 2.0, res
