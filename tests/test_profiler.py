"""Profiler-hook tests: env plumbing, trace capture, step-bounded tracing."""

import glob
import os

import jax
import jax.numpy as jnp
import pytest

from tony_tpu import constants
from tony_tpu.runtime import profiler


def test_profile_dir_off_by_default(monkeypatch):
    monkeypatch.delenv(constants.TONY_PROFILE_DIR, raising=False)
    assert profiler.profile_dir() is None


def test_profile_dir_per_task(monkeypatch):
    monkeypatch.setenv(constants.TONY_PROFILE_DIR, "/tmp/traces")
    monkeypatch.setenv(constants.JOB_NAME, "worker")
    monkeypatch.setenv(constants.TASK_INDEX, "3")
    assert profiler.profile_dir() == "/tmp/traces/worker-3"


def test_maybe_start_disabled(monkeypatch):
    monkeypatch.delenv(constants.TONY_PROFILE_ENABLED, raising=False)
    assert profiler.maybe_start() is False


class TestMaybeStartReportsLiveness:
    """maybe_start() must return whether the profiler server is actually
    LIVE — not merely that profiling was requested (the old behavior
    returned True with no TB_PORT and even when start_server raised)."""

    @pytest.fixture(autouse=True)
    def _fresh_latch(self):
        profiler._reset_server_state_for_tests()
        yield
        profiler._reset_server_state_for_tests()

    def test_no_tb_port_returns_false(self, monkeypatch):
        monkeypatch.setenv(constants.TONY_PROFILE_ENABLED, "true")
        monkeypatch.delenv(constants.TB_PORT, raising=False)
        assert profiler.maybe_start() is False
        monkeypatch.setenv(constants.TB_PORT, "0")
        assert profiler.maybe_start() is False
        monkeypatch.setenv(constants.TB_PORT, "")     # exported but empty
        assert profiler.maybe_start() is False

    def test_server_start_failure_returns_false(self, monkeypatch):
        monkeypatch.setenv(constants.TONY_PROFILE_ENABLED, "true")
        monkeypatch.setenv(constants.TB_PORT, "12345")
        monkeypatch.setattr(
            jax.profiler, "start_server",
            lambda port: (_ for _ in ()).throw(RuntimeError("boom")))
        assert profiler.maybe_start() is False
        assert profiler._server_started is False      # retryable next call

    def test_server_start_success_returns_true_and_latches(
            self, monkeypatch):
        started = []
        monkeypatch.setenv(constants.TONY_PROFILE_ENABLED, "true")
        monkeypatch.setenv(constants.TB_PORT, "12345")
        monkeypatch.setattr(jax.profiler, "start_server", started.append)
        assert profiler.maybe_start() is True
        assert profiler.maybe_start() is True         # idempotent
        assert started == [12345]                     # started exactly once


def test_trace_writes_capture(tmp_path, monkeypatch):
    logdir = str(tmp_path / "trace")
    with profiler.trace(logdir):
        jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
    # xprof capture lands under plugins/profile/<run>/
    assert glob.glob(os.path.join(logdir, "plugins", "profile", "*", "*"))


def test_trace_noop_when_unconfigured(monkeypatch):
    monkeypatch.delenv(constants.TONY_PROFILE_DIR, raising=False)
    with profiler.trace():          # must not raise or start anything
        jnp.ones(4).block_until_ready()


def test_step_tracer_bounded_capture(tmp_path):
    logdir = str(tmp_path / "steps")
    tracer = profiler.StepTracer(start=2, stop=4, logdir=logdir)
    x = jnp.ones((32, 32))
    for step in range(6):
        tracer.step(step)
        x = (x @ x).block_until_ready()
    tracer.close()
    assert not tracer._active
    assert glob.glob(os.path.join(logdir, "plugins", "profile", "*", "*"))


def test_step_tracer_noop_without_dir(monkeypatch):
    monkeypatch.delenv(constants.TONY_PROFILE_DIR, raising=False)
    tracer = profiler.StepTracer(start=0, stop=2)
    for step in range(3):
        tracer.step(step)
    tracer.close()


def test_executor_exports_profile_env(monkeypatch):
    """Conf keys → executor env (without running a real executor)."""
    from tony_tpu.conf import keys as K
    from tony_tpu.conf.config import TonyConfig

    conf = TonyConfig({K.TASK_PROFILE_ENABLED_KEY: "true",
                       K.TASK_PROFILE_DIR_KEY: "/tmp/prof"})
    assert conf.get_bool(K.TASK_PROFILE_ENABLED_KEY) is True
    # The executor's framework_env reads these two keys; defaults stay off.
    assert TonyConfig().get_bool(K.TASK_PROFILE_ENABLED_KEY) is False
