"""Storage abstraction tests: local + gs:// (via a fake gsutil on a tmpdir).

The reference reaches all durable bytes through Hadoop's FileSystem
(TonyClient.java staging, util/HdfsUtils.java, events/EventHandler.java);
the TPU rebuild's seam is tony_tpu.storage. The GCS implementation is
exercised against tests/fake_gsutil.py — the same real-CLI-contract trick
as the reference's MiniDFS."""

import os
import subprocess
import sys

import pytest

from tony_tpu.storage import (GcsStorage, LocalStorage, StorageError,
                              is_remote, register_storage, sbasename,
                              scheme_of, sdirname, sjoin, storage_for)

FAKE_GSUTIL = os.path.join(os.path.dirname(__file__), "fake_gsutil.py")


def make_fake_gsutil(tmp_path, monkeypatch) -> str:
    """Write a gsutil shim mapping gs:// to tmp_path/gcs; returns its path."""
    monkeypatch.setenv("FAKE_GCS_ROOT", str(tmp_path / "gcs"))
    (tmp_path / "gcs").mkdir(exist_ok=True)
    gsutil = tmp_path / "gsutil"
    # -S skips site/sitecustomize: the dev image's sitecustomize drags in
    # the TPU platform on EVERY interpreter start, which would dominate
    # each fake call (fake_gsutil.py uses only the stdlib)
    gsutil.write_text(
        f"#!/bin/bash\nexec {sys.executable} -S {FAKE_GSUTIL} \"$@\"\n")
    gsutil.chmod(0o755)
    return str(gsutil)


# ---------------------------------------------------------------------------
def test_uri_helpers():
    assert scheme_of("gs://b/x") == "gs"
    assert scheme_of("/local/path") == ""
    assert is_remote("gs://b") and not is_remote("relative/path")
    assert sjoin("gs://b/x", "y", "z") == "gs://b/x/y/z"
    assert sjoin("gs://b/x/", "/y/") == "gs://b/x/y"
    assert sjoin("/a", "b") == os.path.join("/a", "b")
    assert sdirname("gs://b/x/y") == "gs://b/x"
    assert sbasename("gs://b/x/y.jhist") == "y.jhist"
    assert sdirname("/a/b/c") == "/a/b"


def test_storage_for_unknown_scheme_errors():
    with pytest.raises(StorageError, match="s3"):
        storage_for("s3://bucket/x")


def test_storage_for_registry_override(tmp_path):
    fake = LocalStorage()
    register_storage("gs", fake)
    try:
        assert storage_for("gs://b/x") is fake
    finally:
        register_storage("gs", None)
    assert isinstance(storage_for("gs://b/x"), GcsStorage)
    register_storage("gs", None)


# ---------------------------------------------------------------------------
@pytest.fixture(params=["local", "gcs"])
def store_and_root(request, tmp_path, monkeypatch):
    """The SAME contract suite runs over both implementations."""
    if request.param == "local":
        yield LocalStorage(), str(tmp_path / "data")
    else:
        gsutil = make_fake_gsutil(tmp_path, monkeypatch)
        yield GcsStorage(gsutil=gsutil), "gs://bucket/data"


class TestStorageContract:
    def test_write_read_exists(self, store_and_root):
        store, root = store_and_root
        path = sjoin(root, "a", "f.txt")
        assert not store.exists(path)
        store.write_bytes(path, b"hello")
        assert store.exists(path)
        assert store.read_bytes(path) == b"hello"

    def test_read_tail(self, store_and_root):
        store, root = store_and_root
        path = sjoin(root, "t.log")
        store.write_bytes(path, b"0123456789")
        assert store.read_tail(path, 4) == b"6789"
        assert store.read_tail(path, 100) == b"0123456789"

    def test_listdir_and_isdir(self, store_and_root):
        store, root = store_and_root
        store.write_bytes(sjoin(root, "d", "x.txt"), b"1")
        store.write_bytes(sjoin(root, "d", "sub", "y.txt"), b"2")
        assert store.isdir(sjoin(root, "d"))
        assert not store.isdir(sjoin(root, "nope"))
        assert store.listdir(sjoin(root, "d")) == ["sub", "x.txt"]

    def test_walk_files(self, store_and_root):
        store, root = store_and_root
        store.write_bytes(sjoin(root, "w", "a.txt"), b"1")
        store.write_bytes(sjoin(root, "w", "s", "b.txt"), b"2")
        found = {sjoin(d, f) for d, files in
                 store.walk_files(sjoin(root, "w")) for f in files}
        assert found == {sjoin(root, "w", "a.txt"),
                         sjoin(root, "w", "s", "b.txt")}

    def test_move(self, store_and_root):
        store, root = store_and_root
        src, dst = sjoin(root, "m", "a"), sjoin(root, "m", "b")
        store.write_bytes(src, b"x")
        store.move(src, dst)
        assert not store.exists(src)
        assert store.read_bytes(dst) == b"x"

    def test_remove(self, store_and_root):
        store, root = store_and_root
        p = sjoin(root, "r.txt")
        store.write_bytes(p, b"x")
        store.remove(p)
        assert not store.exists(p)

    def test_open_append_is_live_readable(self, store_and_root):
        """EventHandler contract: each flush makes bytes visible to a
        concurrent reader (the history server tails .inprogress files)."""
        store, root = store_and_root
        p = sjoin(root, "events.jhist.inprogress")
        f = store.open_append(p)
        f.write("line1\n")
        f.flush()
        assert store.read_bytes(p) == b"line1\n"
        f.write("line2\n")
        f.flush()
        assert store.read_bytes(p) == b"line1\nline2\n"
        f.close()

    def test_put_get_single_file(self, store_and_root, tmp_path):
        store, root = store_and_root
        local = tmp_path / "up.bin"
        local.write_bytes(b"payload")
        remote = sjoin(root, "up.bin")
        store.put(str(local), remote)
        assert store.read_bytes(remote) == b"payload"
        back = tmp_path / "down" / "up.bin"
        store.get(remote, str(back))
        assert back.read_bytes() == b"payload"

    def test_put_tree_get_tree(self, store_and_root, tmp_path):
        store, root = store_and_root
        src = tmp_path / "tree"
        (src / "sub").mkdir(parents=True)
        (src / "f1.txt").write_text("one")
        (src / "sub" / "f2.txt").write_text("two")
        remote = sjoin(root, "staged")
        store.put_tree(str(src), remote)
        assert store.read_bytes(sjoin(remote, "f1.txt")) == b"one"
        assert store.read_bytes(sjoin(remote, "sub", "f2.txt")) == b"two"
        dl = tmp_path / "dl"
        store.get_tree(remote, str(dl))
        assert (dl / "f1.txt").read_text() == "one"
        assert (dl / "sub" / "f2.txt").read_text() == "two"


# ---------------------------------------------------------------------------
@pytest.fixture
def gcs(tmp_path, monkeypatch):
    """gs:// end-to-end: register a fake-gsutil-backed GcsStorage."""
    gsutil = make_fake_gsutil(tmp_path, monkeypatch)
    register_storage("gs", GcsStorage(gsutil=gsutil))
    yield gsutil
    register_storage("gs", None)


class TestEventsOnGcs:
    def test_event_lifecycle_on_gcs(self, gcs):
        """EventHandler writes .inprogress to gs://, stop() renames to the
        final jhist name; find_job_files + parse_events read it back."""
        from tony_tpu.events.events import (EventHandler, find_job_files,
                                            parse_events)
        h = EventHandler("gs://bucket/history/intermediate", "app_1", "me")
        h.start()
        h.emit("APPLICATION_INITED", app_id="app_1", num_tasks=2)
        h.emit("APPLICATION_FINISHED", app_id="app_1", status="SUCCEEDED")
        final = h.stop("SUCCEEDED")
        assert final.startswith("gs://bucket/history/intermediate/")
        assert final.endswith("-SUCCEEDED.jhist")
        files = find_job_files("gs://bucket/history")
        assert files == [final]
        evs = parse_events(final)
        assert [e.event_type for e in evs] == ["APPLICATION_INITED",
                                               "APPLICATION_FINISHED"]

    def test_history_server_over_gcs(self, gcs, tmp_path):
        """Index + config + uptime render from a gs:// history tree, and
        finished jobs migrate intermediate -> finished/yyyy/mm/dd."""
        import urllib.request
        from tony_tpu.conf.config import TonyConfig
        from tony_tpu.events.events import EventHandler, config_file_name
        from tony_tpu.history.server import HistoryServer
        from tony_tpu.storage import storage_for

        h = EventHandler("gs://bucket/hist/intermediate", "app_7", "alice")
        h.start()
        h.emit("APPLICATION_INITED", app_id="app_7", num_tasks=1)
        h.emit("APPLICATION_FINISHED", app_id="app_7", status="SUCCEEDED",
               metrics={"tracked_uptime_fraction": 0.925})
        h.stop("SUCCEEDED")
        cfg = TonyConfig({"tony.worker.instances": "1"})
        local_cfg = tmp_path / "cfg.xml"
        cfg.write_xml(str(local_cfg))
        storage_for("gs://x").put(
            str(local_cfg),
            "gs://bucket/hist/intermediate/" + config_file_name("app_7"))

        srv = HistoryServer(
            TonyConfig({"tony.history.location": "gs://bucket/hist"}),
            port=0)
        port = srv.start()
        try:
            index = urllib.request.urlopen(
                f"http://localhost:{port}/", timeout=10).read().decode()
            assert "app_7" in index and "92.5%" in index
            config = urllib.request.urlopen(
                f"http://localhost:{port}/config/app_7",
                timeout=10).read().decode()
            assert "tony.worker.instances" in config
        finally:
            srv.stop()
        # completed jhist migrated out of intermediate into finished/y/m/d
        store = storage_for("gs://bucket/hist")
        assert store.listdir("gs://bucket/hist/intermediate") == []
        migrated = [p for _, fs in store.walk_files("gs://bucket/hist/finished")
                    for p in fs]
        assert any(p.endswith("-SUCCEEDED.jhist") for p in migrated)


class TestClientRemoteStaging:
    def test_stage_to_gcs_pushes_job_dir(self, gcs, tmp_path):
        """A gs:// staging root spools locally then uploads the whole job
        dir (the reference's HDFS .tony/<appId> upload,
        TonyClient.java:163-185), freezing the remote dir into the conf."""
        from tony_tpu.client.client import TonyClient
        from tony_tpu.conf import keys as K
        from tony_tpu.conf.config import TonyConfig
        from tony_tpu.storage import storage_for

        src = tmp_path / "proj"
        src.mkdir()
        (src / "train.py").write_text("print('hi')\n")
        conf = TonyConfig({
            "tony.staging.dir": "gs://bucket/staging",
            "tony.worker.instances": "1",
            "tony.application.security.enabled": "true",
        })
        client = TonyClient(conf, "python train.py", src_dir=str(src))
        client.stage()
        assert client.remote_job_dir == f"gs://bucket/staging/{client.app_id}"
        # local spool exists (coordinator runs off it for local backends)
        assert os.path.exists(
            os.path.join(client.job_dir, "tony-final.xml"))
        # remote side has the full job dir
        store = storage_for(client.remote_job_dir)
        assert store.exists(sjoin(client.remote_job_dir, "tony-final.xml"))
        assert store.exists(
            sjoin(client.remote_job_dir, "proj", "train.py"))
        # the frozen conf records the remote job dir for slice-host pulls
        frozen = store.read_bytes(
            sjoin(client.remote_job_dir, "tony-final.xml")).decode()
        assert K.REMOTE_JOB_DIR_KEY in frozen
        assert client.remote_job_dir in frozen
        # the per-job auth secret rides env only — NEVER the bucket — but
        # is still written locally for out-of-band tooling (tony kill)
        assert not store.exists(sjoin(client.remote_job_dir, ".tony-secret"))
        assert os.path.exists(os.path.join(client.job_dir, ".tony-secret"))


class TestRangedReads:
    """read_range / size / open_read — the data feed's storage primitives
    (reference: HdfsAvroFileSplitReader.java:201 fs.open + positioned
    reads; ctors :301-317 take a FileSystem)."""

    def test_contract_both_substrates(self, store_and_root):
        store, root = store_and_root
        path = sjoin(root, "blob.bin")
        payload = bytes(range(256)) * 40                # 10240 bytes
        store.write_bytes(path, payload)
        assert store.size(path) == len(payload)
        assert store.read_range(path, 0, 16) == payload[:16]
        assert store.read_range(path, 1000, 24) == payload[1000:1024]
        # short read at EOF, empty past EOF, zero-length
        assert store.read_range(path, len(payload) - 5, 100) == payload[-5:]
        assert store.read_range(path, len(payload) + 10, 4) == b""
        assert store.read_range(path, 3, 0) == b""

    def test_open_read_is_seekable_stream(self, store_and_root):
        store, root = store_and_root
        path = sjoin(root, "stream.bin")
        payload = b"".join(f"line-{i:05d}\n".encode() for i in range(2000))
        store.write_bytes(path, payload)
        with store.open_read(path) as f:
            assert f.read(10) == payload[:10]
            f.seek(0, os.SEEK_END)
            assert f.tell() == len(payload)
            f.seek(len(payload) // 2)
            rest = f.read()
            assert rest == payload[len(payload) // 2:]
            f.seek(11)                       # second line start
            assert f.readline() == b"line-00001\n"

    def test_parallel_prefetch_overlap_and_bytes(self, tmp_path,
                                                 monkeypatch):
        """Sequential gs:// scans keep ``prefetch_depth`` ranged fetches
        in flight (the DataFetcher-overlap property,
        HdfsAvroFileSplitReader.java:176 — here against subprocess-per-
        chunk gsutil). Asserted from the fake's per-call [start, end]
        timestamps — >= 3 cat fetches genuinely concurrent at depth 4,
        none at depth 1 — which holds under arbitrary CI load where a
        wall-clock ratio would flake. Bytes must be identical."""
        gsutil = make_fake_gsutil(tmp_path, monkeypatch)
        store = GcsStorage(gsutil=gsutil)
        store.READ_CHUNK = 4096                      # 16 chunks
        payload = os.urandom(16 * 4096)
        store.write_bytes("gs://bucket/big.bin", payload)
        monkeypatch.setenv("FAKE_GSUTIL_LATENCY_S", "0.15")
        time_log = tmp_path / "times.log"
        monkeypatch.setenv("FAKE_GSUTIL_TIME_LOG", str(time_log))

        def scan(depth):
            store.prefetch_depth = depth
            time_log.write_text("")
            chunks = []
            # production read pattern: the record decoders pull small
            # reads that the BufferedReader refills one READ_CHUNK at a
            # time (f.read() whole-file would batch the serial baseline
            # into DEFAULT_BUFFER_SIZE raw reads instead)
            with store.open_read("gs://bucket/big.bin") as f:
                while True:
                    piece = f.read(2048)
                    if not piece:
                        break
                    chunks.append(piece)
            spans = [(float(a), float(b)) for verb, a, b in
                     (l.split() for l in time_log.read_text().splitlines())
                     if verb == "cat"]
            # max number of fetches simultaneously in flight
            events = ([(s, 1) for s, _ in spans]
                      + [(e, -1) for _, e in spans])
            live = peak = 0
            for _, d in sorted(events):
                live += d
                peak = max(peak, live)
            return b"".join(chunks), peak

        data_serial, peak_serial = scan(1)
        data_par, peak_par = scan(4)
        assert data_serial == payload and data_par == payload
        assert peak_serial == 1, peak_serial
        assert peak_par >= 3, peak_par

    def test_prefetch_probe_reads_stay_small(self, tmp_path, monkeypatch):
        """A small-buffer header probe must NOT pull prefetch windows —
        asserted by CALL COUNT (the fake's auth log records every
        invocation), not wall time, so CI load can't flake it."""
        gsutil = make_fake_gsutil(tmp_path, monkeypatch)
        store = GcsStorage(gsutil=gsutil)
        store.READ_CHUNK = 4096
        store.write_bytes("gs://bucket/probe.bin", os.urandom(16 * 4096))
        call_log = tmp_path / "calls.log"
        monkeypatch.setenv("FAKE_GSUTIL_AUTH_LOG", str(call_log))
        with store.open_read("gs://bucket/probe.bin", buffer_size=64) as f:
            head = f.read(64)
        assert len(head) == 64
        calls = call_log.read_text().splitlines()
        # size() (du) + exactly one small ranged read (cat); a leaked
        # prefetch window would add depth-1 more cat calls
        assert len([c for c in calls if c.startswith("cat")]) == 1, calls
        assert len(calls) <= 2, calls

    def test_multi_identity_token_map(self, tmp_path, monkeypatch):
        """A JSON {bucket: token} credential blob (tony.gcs.service-account
        with bucket=sa pairs — the list-valued tony.other.namenodes
        analog) selects the token by each call's target bucket; an
        unmapped bucket raises instead of leaking ambient credentials."""
        import json

        gsutil = make_fake_gsutil(tmp_path, monkeypatch)
        auth_log = tmp_path / "auth.log"
        monkeypatch.setenv("FAKE_GSUTIL_AUTH_LOG", str(auth_log))
        blob = json.dumps({"bkt-a": "tok-a", "bkt-b": "tok-b"})
        st = GcsStorage(gsutil=gsutil, token=blob)
        st.write_bytes("gs://bkt-a/x", b"1")
        st.write_bytes("gs://bkt-b/y", b"2")
        assert st.read_bytes("gs://bkt-a/x") == b"1"
        calls = [l.split() for l in auth_log.read_text().splitlines()]
        assert calls
        for verb, target, tok in calls:
            if target.startswith("gs://bkt-a"):
                assert tok == "tok-a", (verb, target, tok)
            elif target.startswith("gs://bkt-b"):
                assert tok == "tok-b", (verb, target, tok)
        with pytest.raises(StorageError, match="no GCS identity"):
            st.write_bytes("gs://unlisted/z", b"3")
        # a cross-bucket op spanning two identities cannot run as one
        # gsutil call under a single token — it must fail loudly
        with pytest.raises(StorageError, match="DIFFERENT identities"):
            st.move("gs://bkt-a/x", "gs://bkt-b/moved")
        # '*' maps the default identity
        st2 = GcsStorage(gsutil=gsutil,
                         token=json.dumps({"*": "tok-any"}))
        st2.write_bytes("gs://whatever/z", b"3")
        assert auth_log.read_text().splitlines()[-1].endswith("tok-any")
        # same default identity on both sides: cross-bucket ops fine
        st2.move("gs://whatever/z", "gs://other/z")

    def test_mint_credential_parses_pairs(self, monkeypatch):
        """bucket=sa parsing: one mint per DISTINCT account, bad entries
        rejected at submit time."""
        from tony_tpu.client.client import _mint_gcs_credential
        import json

        minted = []
        monkeypatch.setattr("tony_tpu.client.client._mint_gcs_token",
                            lambda sa: minted.append(sa) or f"tok:{sa}")
        blob = _mint_gcs_credential(
            "bkt-a=sa1@x.iam, bkt-b=sa2@x.iam, gs://bkt-c/=sa1@x.iam")
        assert json.loads(blob) == {"bkt-a": "tok:sa1@x.iam",
                                    "bkt-b": "tok:sa2@x.iam",
                                    "bkt-c": "tok:sa1@x.iam"}
        assert minted == ["sa1@x.iam", "sa2@x.iam"]   # deduped
        assert _mint_gcs_credential("solo@x.iam") == "tok:solo@x.iam"
        with pytest.raises(ValueError, match="bucket=service-account"):
            _mint_gcs_credential("=sa@x.iam")

    def test_sopen_ssize_dispatch(self, tmp_path, monkeypatch):
        from tony_tpu.storage import register_storage, sopen, ssize

        gsutil = make_fake_gsutil(tmp_path, monkeypatch)
        register_storage("gs", GcsStorage(gsutil=gsutil))
        try:
            GcsStorage(gsutil=gsutil).write_bytes("gs://bucket/x.bin",
                                                  b"remote-bytes")
            local = tmp_path / "x.bin"
            local.write_bytes(b"local-bytes")
            assert ssize(str(local)) == 11
            assert ssize("gs://bucket/x.bin") == 12
            with sopen(str(local)) as f:
                assert f.read() == b"local-bytes"
            with sopen("gs://bucket/x.bin") as f:
                assert f.read() == b"remote-bytes"
        finally:
            register_storage("gs", None)
