"""Driver-entry regression tests.

Round-1 postmortem: the driver's multichip check failed because
``dryrun_multichip`` asserted on ``len(jax.devices())`` instead of
bootstrapping a virtual mesh (MULTICHIP_r01.json ``ok: false``). These
tests pin the self-bootstrap behavior: from a process that can only see
one device, the dryrun must still pass by re-execing onto a forced
n-device CPU backend — the reference's run-anywhere fake-cluster
property (tony-mini MiniCluster.java:44-60).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import __graft_entry__ as graft  # noqa: E402


def test_virtual_mesh_env_forces_cpu_and_device_count(monkeypatch):
    monkeypatch.setenv(
        "XLA_FLAGS",
        "--xla_dump_to=/tmp/x --xla_force_host_platform_device_count=8")
    env = graft._virtual_mesh_env(16)
    assert env["JAX_PLATFORMS"] == "cpu"
    # stale forced count replaced, unrelated flags kept
    assert "--xla_force_host_platform_device_count=16" in env["XLA_FLAGS"]
    assert "device_count=8" not in env["XLA_FLAGS"]
    assert "--xla_dump_to=/tmp/x" in env["XLA_FLAGS"]
    assert "axon_site" not in env.get("PYTHONPATH", "")


@pytest.mark.e2e
@pytest.mark.slow
def test_dryrun_bootstraps_when_devices_insufficient():
    """Caller pinned to ONE device must still pass dryrun_multichip(4)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    proc = subprocess.run(
        [sys.executable, "-c",
         "import jax; assert len(jax.devices()) == 1, jax.devices(); "
         "import __graft_entry__ as g; g.dryrun_multichip(4); "
         "print('BOOTSTRAP_OK')"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr
    assert "BOOTSTRAP_OK" in proc.stdout


@pytest.mark.slow
def test_dryrun_is_self_verifying_against_broken_collective(monkeypatch):
    """A deliberately wrong shard_map body (a ring that never rotates —
    each chunk attends only to its local K/V, the canonical missing-
    collective bug GSPMD can't catch because the result is finite and
    well-shaped) must FAIL the dryrun's sharded-vs-unsharded comparison,
    not sail through a finiteness check."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    import importlib

    R = importlib.import_module("tony_tpu.parallel.ring_attention")

    def corrupted(q, k, v, axis_name="cp", causal=True, scale=None):
        # local-only attention: the ppermute hops are "forgotten"
        return R._single_chunk(q, k, v, causal=causal, scale=scale)

    monkeypatch.setattr(R, "ring_attention_local", corrupted)
    with pytest.raises(AssertionError, match="loss|grad norm"):
        graft._dryrun_body(8)


@pytest.mark.slow
def test_dryrun_self_verification_passes_in_process():
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    graft._dryrun_body(8)
