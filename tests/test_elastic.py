"""Elastic preemption-tolerant training (tier-1).

The acceptance suite for the degraded-resume loop: a REAL job (client →
coordinator → 2 worker processes → jax.distributed over the gang
barrier) loses one gang mid-train to an injected preemption and KEEPS
RUNNING — the survivor checkpoint-syncs, re-handshakes over a bumped
cluster-spec epoch, restores from the latest completed async checkpoint
and resumes, with the loss curve pinned step-continuous against an
uninterrupted single-process run (the elastic_epochs source makes global
batches world-size invariant, so the losses match to float noise). A
second e2e regrows the lost gang and pins continuity across BOTH
transitions. The stop-the-world session-rerun path stays pinned for
non-preemption failures and for losses the eligibility gate rejects
(the chief's gang)."""

import json
import os
import re
import subprocess
import sys
import time

import numpy as np
import pytest

from tony_tpu.backend.base import LaunchSpec
from tony_tpu.client.client import TonyClient
from tony_tpu.conf.config import TonyConfig
from tony_tpu.events.events import find_job_files, parse_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")
PY = sys.executable
TRAINER = os.path.join(FIXTURES, "elastic_trainer.py")

#: observed cross-world-size drift is 0 (bit-identical); the tolerance
#: only absorbs float-print rounding
LOSS_TOL = 1e-4


def _parse_losses(text: str) -> dict[int, list[float]]:
    out: dict[int, list[float]] = {}
    for m in re.finditer(r"^step (\d+) loss ([\d.]+)$", text, re.M):
        out.setdefault(int(m.group(1)), []).append(float(m.group(2)))
    return out


@pytest.fixture(scope="module")
def elastic_data(tmp_path_factory):
    d = tmp_path_factory.mktemp("elastic-data")
    rows = np.random.RandomState(0).randint(
        0, 1024, size=(64, 5)).astype(np.int32)
    path = d / "data.bin"
    rows.tofile(path)
    return str(path)


@pytest.fixture(scope="module")
def baseline_losses(elastic_data, tmp_path_factory):
    """Uninterrupted single-process run: THE loss curve. Elastic runs at
    any world size / any kill schedule must reproduce it exactly."""
    ck = tmp_path_factory.mktemp("elastic-baseline") / "ck"
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
                "PYTHONPATH": REPO})
    res = subprocess.run(
        [PY, TRAINER, "--steps", "16", "--ckpt_dir", str(ck),
         "--ckpt_every", "2", "--data", elastic_data,
         "--global_batch", "8"],
        env=env, capture_output=True, text=True, timeout=180)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    losses = _parse_losses(res.stdout)
    assert sorted(losses) == list(range(16)), sorted(losses)
    return {k: v[0] for k, v in losses.items()}


def _trainer_cmd(steps, ck, data, marker, touch_at, touch_index=1):
    return (f"{PY} {TRAINER} --steps {steps} --ckpt_dir {ck} "
            f"--ckpt_every 2 --data {data} --global_batch 8 "
            f"--step_wait 0.25 --touch {marker} --touch_at {touch_at} "
            f"--touch_index {touch_index}")


def _make_client(tmp_path, cmd, confs, shell_env):
    base = {
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.location": str(tmp_path / "hist"),
        "tony.application.timeout": "150000",
    }
    base.update(confs)
    env = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": "", "PYTHONPATH": REPO,
           "TONY_RESYNC_KILL_GRACE_S": "3"}
    env.update(shell_env)
    return TonyClient(TonyConfig(base), cmd, shell_env=env)


def _job_events(client):
    files = find_job_files(client.conf.get("tony.history.location"))
    assert len(files) == 1, files
    return list(parse_events(files[0]))


def _logged_losses(client) -> dict[int, list[float]]:
    merged: dict[int, list[float]] = {}
    log_dir = os.path.join(client.job_dir, "logs")
    for name in sorted(os.listdir(log_dir)):
        if name.startswith("worker-") and name.endswith(".stdout"):
            for step, vals in _parse_losses(
                    open(os.path.join(log_dir, name)).read()).items():
                merged.setdefault(step, []).extend(vals)
    return merged


def _assert_continuous(client, baseline, last_step):
    """Every loss any worker EVER printed — before the kill, replayed
    after the restore, post-regrow — must equal the uninterrupted run's
    loss at that global step."""
    losses = _logged_losses(client)
    assert max(losses) == last_step, sorted(losses)
    for step, vals in losses.items():
        for v in vals:
            assert abs(v - baseline[step]) <= LOSS_TOL, (
                f"step {step}: got {v}, uninterrupted run had "
                f"{baseline[step]}")


@pytest.mark.e2e
class TestElasticE2E:
    def test_shrink_survives_gang_loss_with_loss_continuity(
            self, tmp_path, elastic_data, baseline_losses):
        """Kill gang worker:1 (slice 1) at step 6; the session must NOT
        reset — worker:0 re-handshakes over the shrunk world, restores
        from the latest completed checkpoint, and finishes all 12 steps
        with the loss curve pinned to the uninterrupted run."""
        marker = tmp_path / "kill.marker"
        client = _make_client(
            tmp_path,
            _trainer_cmd(12, tmp_path / "ck", elastic_data, marker, 6),
            {"tony.worker.instances": "2", "tony.worker.slices": "2",
             "tony.application.mesh": "dp=-1",
             "tony.elastic.enabled": "true",
             "tony.elastic.regrow": "false"},
            {"TEST_PREEMPT_TASKS": f"worker:1@{marker}"})
        assert client.run() == 0
        _assert_continuous(client, baseline_losses, last_step=11)
        # the survivor demonstrably ran the shrunk world
        w0 = open(os.path.join(client.job_dir, "logs",
                               "worker-0.stdout")).read()
        assert "procs=1" in w0 and "procs=2" in w0
        types = [e.event_type for e in _job_events(client)]
        assert "ELASTIC_SHRINK" in types
        assert "ELASTIC_RESUMED" in types
        assert "SESSION_RESET" not in types
        ev = {e.event_type: e.payload for e in _job_events(client)}
        assert ev["ELASTIC_SHRINK"]["lost"] == ["worker:1"]
        assert ev["ELASTIC_SHRINK"]["epoch"] == 1
        assert ev["ELASTIC_RESUMED"]["recovery_wall_s"] > 0
        finished = [e.payload for e in _job_events(client)
                    if e.event_type == "TASK_FINISHED"
                    and e.payload["task"] == "worker:1"]
        assert finished[0]["preempted"] and finished[0]["detached"]

    def test_regrow_expands_back_and_keeps_training(
            self, tmp_path, elastic_data, baseline_losses):
        """Same kill, regrow on: the survivor first trains ALONE (epoch 1,
        procs=1), then the relaunched gang folds back in (epoch 2,
        procs=2) and both run to step 16 — loss curve continuous across
        BOTH elastic transitions."""
        marker = tmp_path / "kill.marker"
        client = _make_client(
            tmp_path,
            _trainer_cmd(16, tmp_path / "ck", elastic_data, marker, 5),
            {"tony.worker.instances": "2", "tony.worker.slices": "2",
             "tony.application.mesh": "dp=-1",
             "tony.elastic.enabled": "true",
             "tony.elastic.regrow": "true",
             # long enough that the survivor demonstrably trains alone
             # before the replacement lands
             "tony.elastic.regrow-backoff-ms": "6000"},
            {"TEST_PREEMPT_TASKS": f"worker:1@{marker}"})
        assert client.run() == 0
        _assert_continuous(client, baseline_losses, last_step=15)
        types = [e.event_type for e in _job_events(client)]
        assert "ELASTIC_SHRINK" in types
        assert "ELASTIC_REGROW" in types
        assert "SESSION_RESET" not in types
        regrow = [e.payload for e in _job_events(client)
                  if e.event_type == "ELASTIC_REGROW"][0]
        assert regrow["regrown"] == ["worker:1"] and regrow["active"] == 2
        w0 = open(os.path.join(client.job_dir, "logs",
                               "worker-0.stdout")).read()
        assert "procs=1" in w0          # the degraded interlude happened
        # the regrown gang really trained again after its loss
        w1 = open(os.path.join(client.job_dir, "logs",
                               "worker-1.stdout")).read()
        assert "step 15" in w1 and "done:" in w1

    def test_user_failure_keeps_stop_the_world(self, tmp_path):
        """Elastic ON, but a plain user failure (exit 1, not preemption):
        the session-rerun path must fire exactly as before — elastic only
        absorbs infrastructure loss."""
        client = _make_client(
            tmp_path,
            f"{PY} {os.path.join(FIXTURES, 'fail_once.py')}",
            {"tony.worker.instances": "2",
             "tony.elastic.enabled": "true",
             "tony.am.retry-count": "1"},
            {})
        assert client.run() == 0
        types = [e.event_type for e in _job_events(client)]
        assert "SESSION_RESET" in types
        assert "ELASTIC_SHRINK" not in types

    def test_chief_gang_loss_falls_back_to_session_rerun(
            self, tmp_path, elastic_data):
        """The chief's gang is never detachable: killing it routes to the
        stop-the-world preemption budget, which re-runs the session (and
        the rerun resumes from the shared checkpoint dir)."""
        marker = tmp_path / "kill.marker"
        client = _make_client(
            tmp_path,
            _trainer_cmd(10, tmp_path / "ck", elastic_data, marker, 4,
                         touch_index=0),
            {"tony.worker.instances": "2", "tony.worker.slices": "2",
             "tony.application.mesh": "dp=-1",
             "tony.elastic.enabled": "true",
             "tony.am.retry-count": "0"},      # preemption budget only
            {"TEST_PREEMPT_TASKS": f"worker:0@{marker}"})
        assert client.run() == 0
        types = [e.event_type for e in _job_events(client)]
        assert "SESSION_RESET" in types
        assert "ELASTIC_SHRINK" not in types

    def test_preempt_tasks_hook_drives_preemption_budget(self, tmp_path):
        """The new kill-gang hook composes with the EXISTING stop-the-world
        machinery when elastic is off: an immediate (marker-less) clause
        preempts the task once and the job recovers from the preemption
        budget without consuming a user retry."""
        client = _make_client(
            tmp_path,
            f"{PY} {os.path.join(FIXTURES, 'sleep_briefly.py')} 3",
            {"tony.worker.instances": "1",
             "tony.am.retry-count": "0"},
            {"TEST_PREEMPT_TASKS": "worker:0"})
        assert client.run() == 0
        types = [e.event_type for e in _job_events(client)]
        assert "SESSION_RESET" in types


# ---------------------------------------------------------------------------
# elastic_epochs: world-size-invariant data positions (no cluster)
# ---------------------------------------------------------------------------
class TestElasticEpochs:
    DIM = 3

    def _data(self, tmp_path, rows=40):
        arr = np.arange(rows * (self.DIM + 1),
                        dtype=np.int32).reshape(rows, self.DIM + 1)
        path = tmp_path / "rows.bin"
        arr.tofile(path)
        return str(path), arr

    def _take(self, path, steps, *, pid, pcount, start_step=0):
        from tony_tpu.io.prefetch import elastic_epochs
        it, per_epoch = elastic_epochs(
            [path], 8, np.int32, (self.DIM + 1,), shuffle=True, seed=3,
            start_step=start_step, process_index=pid,
            process_count=pcount)
        out = [next(it) for _ in range(steps)]
        return out, per_epoch

    def test_global_batches_world_size_invariant(self, tmp_path):
        path, _ = self._data(tmp_path)
        canon, per_epoch = self._take(path, 10, pid=0, pcount=1)
        assert per_epoch == 5            # 40 rows / global batch 8
        for pcount in (2, 4):
            slices = [self._take(path, 10, pid=p, pcount=pcount)[0]
                      for p in range(pcount)]
            for step in range(10):
                got = np.concatenate([s[step] for s in slices])
                np.testing.assert_array_equal(got, canon[step])

    def test_mid_epoch_shrink_no_duplicates_no_gaps(self, tmp_path):
        """Shrink N=2 → N-1 mid-epoch: 2 processes feed steps 0..2, the
        kill lands at step 3 with the checkpoint at step 2, and the
        survivor resumes at start_step=2 alone. The union of batches fed
        across all survivors IS the deterministic canonical stream —
        every global step's batch fed exactly by its canonical rows,
        none skipped, none double-fed (the replayed step 2 is the SAME
        batch, re-fed to recompute the same update)."""
        path, arr = self._data(tmp_path)
        canon, per_epoch = self._take(path, 5, pid=0, pcount=1)
        pre = [self._take(path, 3, pid=p, pcount=2)[0] for p in range(2)]
        post, _ = self._take(path, 3, pid=0, pcount=1, start_step=2)
        fed = {}
        for step in range(3):            # the 2-process prefix
            fed[step] = np.concatenate([pre[0][step], pre[1][step]])
        for i, batch in enumerate(post):  # the survivor, from the ckpt
            fed[2 + i] = batch
        assert sorted(fed) == list(range(5))      # no gaps
        for step in range(5):                     # no foreign/dup rows
            np.testing.assert_array_equal(fed[step], canon[step])
        # one full epoch's coverage is exactly the file's rows
        rows = np.concatenate([fed[s] for s in range(5)])
        assert sorted(map(tuple, rows)) == sorted(map(tuple, arr))

    def test_start_step_skips_into_later_epochs(self, tmp_path):
        path, _ = self._data(tmp_path)
        canon, _ = self._take(path, 13, pid=0, pcount=1)
        tail, _ = self._take(path, 2, pid=0, pcount=1, start_step=11)
        np.testing.assert_array_equal(tail[0], canon[11])
        np.testing.assert_array_equal(tail[1], canon[12])

    def test_indivisible_global_batch_rejected(self, tmp_path):
        from tony_tpu.io.prefetch import elastic_epochs
        path, _ = self._data(tmp_path)
        with pytest.raises(ValueError, match="divide"):
            elastic_epochs([path], 8, np.int32, (self.DIM + 1,),
                           process_index=0, process_count=3)

    def test_too_small_data_rejected(self, tmp_path):
        from tony_tpu.io.prefetch import elastic_epochs
        path, _ = self._data(tmp_path, rows=4)
        with pytest.raises(ValueError, match="global batch"):
            elastic_epochs([path], 8, np.int32, (self.DIM + 1,),
                           process_index=0, process_count=1)


# ---------------------------------------------------------------------------
# Session elastic state machine (no processes)
# ---------------------------------------------------------------------------
class TestSessionElastic:
    def _session(self):
        from tony_tpu.cluster.session import Session
        return Session(TonyConfig({"tony.worker.instances": "4",
                                   "tony.worker.slices": "2",
                                   "tony.application.mesh": "dp=-1"}))

    def test_shrink_holds_barrier_and_shrinks_payload(self):
        s = self._session()
        for i in range(4):
            payload = s.register_task_spec(f"worker:{i}", f"h{i}:1")
        assert payload["num_processes"] == 4
        assert payload["cluster_epoch"] == 0
        assert s.gang_task_ids("worker:3") == ["worker:2", "worker:3"]
        for tid in s.gang_task_ids("worker:2"):
            s.detach_for_preemption(tid)
        assert s.begin_elastic_resync() == 1
        assert not s.barrier_released()
        assert s.register_task_spec("worker:0", "h0:1") is None
        payload = s.register_task_spec("worker:1", "h1:1")
        assert payload["num_processes"] == 2
        assert payload["cluster_epoch"] == 1
        spec = json.loads(payload["cluster_spec"])
        assert spec["worker"] == ["h0:1", "h1:1"]
        mesh = json.loads(payload["mesh_spec"])
        assert mesh["slice_spec"]["worker"] == {
            "slices": 1, "hosts_per_slice": 2, "active_slices": [0]}
        # detached tasks are not a job verdict
        assert s.update_session_status().value == "RUNNING"

    def test_regrow_round_trip(self):
        s = self._session()
        for i in range(4):
            s.register_task_spec(f"worker:{i}", f"h{i}:1")
        for tid in ("worker:2", "worker:3"):
            s.detach_for_preemption(tid)
        s.begin_elastic_resync()
        s.register_task_spec("worker:0", "h0:1")
        s.register_task_spec("worker:1", "h1:1")
        armed = s.arm_regrow(["worker:2", "worker:3"])
        assert [t.task_id for t in armed] == ["worker:2", "worker:3"]
        assert not s.regrow_ready()
        # a replacement's registration never releases the degraded barrier
        assert s.register_task_spec("worker:2", "h2:2") is None
        assert s.barrier_released()      # survivors unaffected
        s.register_task_spec("worker:3", "h3:2")
        assert s.regrow_ready()
        assert s.activate_regrow() == 2
        assert not s.barrier_released()  # survivors must resync
        s.register_task_spec("worker:0", "h0:1")
        payload = s.register_task_spec("worker:1", "h1:1")
        assert payload["num_processes"] == 4
        assert payload["cluster_epoch"] == 2
        mesh = json.loads(payload["mesh_spec"])
        assert mesh["slice_spec"]["worker"] == {
            "slices": 2, "hosts_per_slice": 2}
        assert [t.process_id for t in s.all_tasks()] == [0, 1, 2, 3]
        assert s.all_tasks()[2].regrows == 1

    def test_abort_regrow_unarms(self):
        s = self._session()
        for i in range(4):
            s.register_task_spec(f"worker:{i}", f"h{i}:1")
        for tid in ("worker:2", "worker:3"):
            s.detach_for_preemption(tid)
        s.begin_elastic_resync()
        s.arm_regrow(["worker:2", "worker:3"])
        s.register_task_spec("worker:2", "h2:2")
        s.abort_regrow("worker:2", exit_code=9)
        assert not s.regrow_ready()      # half-dead regrow cannot gate
        assert s.regrow_pending_ids() == {"worker:3"}
        t = s.get_task_by_id("worker:2")
        assert t.detached and t.exit_code == 9


# ---------------------------------------------------------------------------
# Coordinator routing: liveness expiry and failure triage (no processes)
# ---------------------------------------------------------------------------
class TestCoordinatorElasticRouting:
    def _coordinator(self, tmp_path, extra=None):
        from tony_tpu.cluster.coordinator import Coordinator
        conf = {"tony.worker.instances": "2", "tony.worker.slices": "2",
                "tony.elastic.enabled": "true",
                "tony.elastic.regrow": "false",
                "tony.elastic.quiesce-ms": "0",
                "tony.history.location": str(tmp_path / "hist")}
        conf.update(extra or {})
        return Coordinator(TonyConfig(conf), "app_route",
                           str(tmp_path / "job"))

    def test_liveness_expiry_absorbed_as_gang_loss(self, tmp_path):
        """A tracked task going silent with elastic ON detaches its gang
        instead of failing the job (the 'liveness reports a gang lost'
        entry point of the tentpole)."""
        from tony_tpu.cluster.session import SessionStatus
        co = self._coordinator(tmp_path)
        try:
            co.session.register_task_spec("worker:0", "h0:1")
            co.session.register_task_spec("worker:1", "h1:1")
            co._on_task_dead("worker:1")
            assert not co.task_missed_hb.is_set()
            time.sleep(0.01)
            co._elastic_tick()
            t = co.session.get_task_by_id("worker:1")
            assert t.detached and t.completed
            assert co.session.cluster_epoch == 1
            assert co.session.status is SessionStatus.RUNNING
            assert co.elastic_budget_left == 2      # one shrink consumed
        finally:
            co.rpc_server.stop(0)

    def test_liveness_expiry_without_elastic_fails_job(self, tmp_path):
        co = self._coordinator(tmp_path,
                               {"tony.elastic.enabled": "false"})
        try:
            co.session.register_task_spec("worker:0", "h0:1")
            co.session.register_task_spec("worker:1", "h1:1")
            co._on_task_dead("worker:1")
            assert co.task_missed_hb.is_set()
        finally:
            co.rpc_server.stop(0)

    def test_collateral_failure_charged_to_incident(self, tmp_path):
        """An abnormal exit landing in the same quiesce window as a
        preemption is collateral: the shrink detaches the preempted gang,
        and the collateral task (whose gang = itself here) rides the same
        incident instead of failing the session."""
        from tony_tpu.cluster.session import SessionStatus
        co = self._coordinator(
            tmp_path, {"tony.worker.instances": "3",
                       "tony.worker.slices": "3",
                       "tony.elastic.quiesce-ms": "200"})
        try:
            for i in range(3):
                co.session.register_task_spec(f"worker:{i}", f"h{i}:1")
            co.record_completion("worker", 1, 0, preempted=True)
            # worker:2 crashes on the dead gang's collectives (exit 1,
            # NOT preempted) inside the window
            co.record_completion("worker", 2, 1)
            time.sleep(0.25)
            co._elastic_tick()
            assert co.session.get_task_by_id("worker:1").detached
            assert co.session.get_task_by_id("worker:2").detached
            assert co.session.status is SessionStatus.RUNNING
        finally:
            co.rpc_server.stop(0)

    def test_pipeline_stage_gang_loss_falls_back_to_stop_the_world(
            self, tmp_path):
        """A pipeline STAGE gang is not a shrinkable data-parallel
        replica — it holds layers. Losing one with elastic ON must route
        through the stop-the-world preemption retry path (session
        preempted, NOTHING detached, no shrink epoch), never a shrink."""
        from tony_tpu.cluster.session import SessionStatus
        co = self._coordinator(
            tmp_path, {"tony.worker.instances": "0",
                       "tony.worker.slices": "1",
                       "tony.stage0.instances": "1",
                       "tony.stage1.instances": "1",
                       "tony.pipeline.stages": "stage0,stage1"})
        try:
            co.session.register_task_spec("stage0:0", "h0:1", 7000)
            co.session.register_task_spec("stage1:0", "h1:1", 7001)
            co.record_completion("stage0", 0, 143, preempted=True)
            assert co.session.status is SessionStatus.RUNNING  # quiescing
            time.sleep(0.01)
            co._elastic_tick()
            t = co.session.get_task_by_id("stage0:0")
            assert not t.detached and t.completed
            assert co.session.cluster_epoch == 0       # no shrink cut
            assert co._session_preempted               # retry-budget path
            assert co.session.status is SessionStatus.FAILED
        finally:
            co.rpc_server.stop(0)

    def test_pure_user_failure_replays_through_normal_path(self, tmp_path):
        """No preemption in the window → the held failure replays as the
        ordinary user failure it was: session FAILED, nothing detached."""
        from tony_tpu.cluster.session import SessionStatus
        co = self._coordinator(tmp_path,
                               {"tony.elastic.quiesce-ms": "0"})
        try:
            co.session.register_task_spec("worker:0", "h0:1")
            co.session.register_task_spec("worker:1", "h1:1")
            co.record_completion("worker", 1, 1)      # plain exit 1
            assert co.session.status is SessionStatus.RUNNING  # held
            time.sleep(0.01)
            co._elastic_tick()
            assert co.session.status is SessionStatus.FAILED
            assert not co.session.get_task_by_id("worker:1").detached
        finally:
            co.rpc_server.stop(0)


# ---------------------------------------------------------------------------
# Heartbeat epoch piggyback (wire level)
# ---------------------------------------------------------------------------
class TestEpochPiggyback:
    def _serve(self, impl):
        from tony_tpu.rpc.client import ApplicationRpcClient
        from tony_tpu.rpc.server import ApplicationRpcServer
        srv = ApplicationRpcServer(impl)
        srv.start()
        return srv, ApplicationRpcClient(f"localhost:{srv.port}")

    def test_epoch_rides_heartbeat_ack(self):
        from tony_tpu.rpc.service import HeartbeatAck
        from tests.test_rpc import FakeImpl

        class Impl(FakeImpl):
            def task_executor_heartbeat(self, task_id, metrics=""):
                super().task_executor_heartbeat(task_id, metrics)
                return HeartbeatAck(gcs_token="tok", cluster_epoch=7)

        srv, client = self._serve(Impl())
        try:
            ack = client.task_executor_heartbeat("worker:0")
            assert ack.gcs_token == "tok" and ack.cluster_epoch == 7
        finally:
            client.close()
            srv.stop(0)

    def test_pre_elastic_impl_maps_to_epoch_zero(self):
        """An impl returning a bare token string (the pre-elastic shape)
        still serves; clients see epoch 0 — never a spurious resync."""
        from tests.test_rpc import FakeImpl

        class Impl(FakeImpl):
            def task_executor_heartbeat(self, task_id, metrics=""):
                super().task_executor_heartbeat(task_id, metrics)
                return "bare-token"

        srv, client = self._serve(Impl())
        try:
            ack = client.task_executor_heartbeat("worker:0")
            assert ack.gcs_token == "bare-token"
            assert ack.cluster_epoch == 0
        finally:
            client.close()
            srv.stop(0)


# ---------------------------------------------------------------------------
# Bench arm: deterministic tier-1 variant (jax-free fake trainer)
# ---------------------------------------------------------------------------
@pytest.mark.e2e
def test_elastic_bench_arm_deterministic():
    """bench._elastic_arm drives the SAME injected kill through the
    elastic and stop-the-world paths and emits recovery wall + replay
    counts. Pins: the elastic run genuinely shrank and recovered, its
    replays never exceed the stop-the-world run's by more than one
    checkpoint interval per worker (both strategies lose at most
    ckpt_every steps per affected worker), and the headline keys exist
    for BENCH json."""
    sys.path.insert(0, REPO)
    import bench
    res = bench._elastic_arm()
    assert res["elastic_recovery_wall_s"] > 0
    assert res["elastic_steps_replayed"] <= \
        res["restart_steps_replayed"] + 2 * 2
    assert res["elastic_goodput_vs_restart"] > 0
    assert res["elastic_wall_s"] > 0 and res["restart_wall_s"] > 0


# ---------------------------------------------------------------------------
# TPU backend: deterministic preemption + reprovision-on-regrow (fake gcloud)
# ---------------------------------------------------------------------------
@pytest.mark.e2e
def test_tpu_backend_fake_preempt_and_regrow_reprovisions(
        tmp_path, monkeypatch):
    """FAKE_PREEMPT_<GANG> drives the backend's preemption detection
    deterministically: the marked slice reports its tasks preempted, and
    a subsequent launch of the same task (the elastic regrow) deletes the
    dead slice and provisions a fresh one, while the untouched gang keeps
    its slice (adopt semantics)."""
    from tony_tpu.backend.tpu import TpuSliceBackend, slice_name

    fleet = tmp_path / "fleet"
    fleet.mkdir()
    bindir = tmp_path / "bin"
    bindir.mkdir()
    gcloud = bindir / "gcloud"
    gcloud.write_text(f"#!/bin/bash\nexec {PY} "
                      f"{os.path.join(REPO, 'tests', 'fake_gcloud.py')} "
                      f"\"$@\"\n")
    gcloud.chmod(0o755)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_GCLOUD_ROOT", str(fleet))
    monkeypatch.setenv("FAKE_NUM_WORKERS", "1")

    job_dir = tmp_path / "job"
    log_dir = job_dir / "logs"
    log_dir.mkdir(parents=True)
    (job_dir / "tony-final.xml").write_text("<configuration/>\n")
    conf = TonyConfig({
        "tony.scheduler.backend": "tpu",
        "tony.tpu.project": "p", "tony.tpu.zone": "z",
        "tony.tpu.accelerator-type": "v5litepod",
        "tony.tpu.state-refresh-ms": "100",
        "tony.worker.instances": "2", "tony.worker.slices": "2",
    })
    backend = TpuSliceBackend(conf, app_id="app_1_2")
    victim = slice_name("app_1_2", "worker", 1, 2)
    monkeypatch.setenv(
        "FAKE_PREEMPT_" + "".join(
            c if c.isalnum() else "_" for c in victim).upper(), "1")
    try:
        for i in range(2):
            backend.launch_task(LaunchSpec(
                task_id=f"worker:{i}", command="sleep 30", env={},
                log_dir=str(log_dir), cwd=str(job_dir),
                tpu_topology="2x4"))
        deadline = time.monotonic() + 30
        events = []
        while time.monotonic() < deadline and not events:
            events = [e for e in backend.poll_completed() if e.preempted]
            time.sleep(0.05)
        assert [e.task_id for e in events] == ["worker:1"]
        creates_before = sum(
            1 for c in open(fleet / "calls.log")
            if c.split()[3:4] == ["create"])
        # regrow: relaunching the lost task must delete + re-create ITS
        # slice only
        backend.launch_task(LaunchSpec(
            task_id="worker:1", command="true", env={},
            log_dir=str(log_dir), cwd=str(job_dir), tpu_topology="2x4"))
        lines = [c.split() for c in open(fleet / "calls.log")]
        creates = [c[4] for c in lines if c[3] == "create"]
        deletes = [c[4] for c in lines if c[3] == "delete"]
        assert len(creates) == creates_before + 1
        assert creates[-1] == victim and victim in deletes
    finally:
        backend.stop()
