"""Checkpoint/resume tests: orbax-backed manager, sharding round-trip,
retry-aware bootstrap."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tony_tpu.models.checkpoint import CheckpointManager, attempt_number
from tony_tpu.parallel.mesh import make_mesh


def _state(value=1.0):
    return {"params": {"w": jnp.full((8, 4), value), "b": jnp.zeros((4,))},
            "step": jnp.zeros((), jnp.int32)}


class TestCheckpointManager:
    def test_save_restore_roundtrip(self, tmp_path):
        with CheckpointManager(str(tmp_path / "ckpt")) as mgr:
            state = _state(3.0)
            assert mgr.save(0, state)
            mgr.wait_until_finished()
            restored = mgr.restore(template=_state(0.0))
        np.testing.assert_array_equal(restored["params"]["w"],
                                      state["params"]["w"])

    def test_latest_step_and_retention(self, tmp_path):
        with CheckpointManager(str(tmp_path / "c"), max_to_keep=2) as mgr:
            for s in range(4):
                mgr.save(s, _state(float(s)))
            mgr.wait_until_finished()
            assert mgr.latest_step() == 3
            restored = mgr.restore(template=_state())
            np.testing.assert_array_equal(restored["params"]["w"][0, 0], 3.0)

    def test_save_interval_skips(self, tmp_path):
        with CheckpointManager(str(tmp_path / "c"),
                               save_interval_steps=5) as mgr:
            assert mgr.save(0, _state())
            assert not mgr.save(1, _state())   # below interval
            assert mgr.save(1, _state(), force=True)

    def test_restore_or_init_fresh(self, tmp_path):
        with CheckpointManager(str(tmp_path / "c")) as mgr:
            state = mgr.restore_or_init(lambda: _state(7.0))
        np.testing.assert_array_equal(state["params"]["w"][0, 0], 7.0)

    def test_restore_or_init_resumes(self, tmp_path):
        with CheckpointManager(str(tmp_path / "c")) as mgr:
            mgr.save(2, _state(9.0))
            mgr.wait_until_finished()
            state = mgr.restore_or_init(lambda: _state(0.0))
            np.testing.assert_array_equal(state["params"]["w"][0, 0], 9.0)
            assert mgr.latest_step() == 2

    def test_restore_missing_raises(self, tmp_path):
        with CheckpointManager(str(tmp_path / "c")) as mgr:
            with pytest.raises(FileNotFoundError):
                mgr.restore(template=_state())

    def test_sharded_roundtrip_preserves_layout(self, tmp_path):
        """Arrays saved from a mesh restore onto the same sharding — the
        slice-preemption resume path."""
        mesh = make_mesh({"dp": 2, "tp": 4})
        sharding = NamedSharding(mesh, P("dp", "tp"))
        w = jax.device_put(jnp.arange(32, dtype=jnp.float32).reshape(8, 4),
                           sharding)
        state = {"w": w}
        with CheckpointManager(str(tmp_path / "c")) as mgr:
            mgr.save(0, state)
            mgr.wait_until_finished()
            restored = mgr.restore(template=state)
        assert restored["w"].sharding.is_equivalent_to(sharding, ndim=2)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(w))


class TestRestoreVsInFlightSaveFence:
    """restore/latest_step must never observe a partially-written async
    save: both fence on wait_until_finished BEFORE consulting the step
    index (the elastic degraded-resume path restores immediately after a
    kill that may have interrupted a save mid-commit)."""

    class _Tracking:
        """Proxy over the real orbax manager recording call order."""

        def __init__(self, real, calls):
            self.__dict__["_real"] = real
            self.__dict__["calls"] = calls

        def __getattr__(self, name):
            if name in ("wait_until_finished", "latest_step", "restore"):
                def wrapped(*a, **k):
                    self.calls.append(name)
                    return getattr(self._real, name)(*a, **k)
                return wrapped
            return getattr(self._real, name)

    def test_latest_step_fences_first(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "c"))
        calls = []
        mgr._mgr = self._Tracking(mgr._mgr, calls)
        mgr.save(3, _state(5.0))         # async — commit in flight
        assert mgr.latest_step() == 3    # fenced: never a partial view
        assert "wait_until_finished" in calls
        assert calls.index("wait_until_finished") \
            < calls.index("latest_step")
        mgr.close()

    def test_restore_during_in_flight_save_sees_committed_state(
            self, tmp_path):
        """Save → IMMEDIATE restore with no explicit wait, repeatedly:
        the fence makes every restore read the just-accepted save's
        committed bytes, never an older step or a torn directory."""
        with CheckpointManager(str(tmp_path / "c"), max_to_keep=2) as mgr:
            for s in range(4):
                mgr.save(s, _state(float(s)))
                restored = mgr.restore(template=_state(0.0))
                np.testing.assert_array_equal(
                    restored["params"]["w"][0, 0], float(s))
                assert mgr.latest_step() == s

    def test_restore_or_init_fences(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "c"))
        calls = []
        mgr._mgr = self._Tracking(mgr._mgr, calls)
        mgr.save(1, _state(2.0))
        state = mgr.restore_or_init(lambda: _state(0.0))
        np.testing.assert_array_equal(state["params"]["w"][0, 0], 2.0)
        assert calls.index("wait_until_finished") \
            < calls.index("latest_step")
        mgr.close()


def test_attempt_number_env(monkeypatch):
    from tony_tpu import constants
    assert attempt_number() == 0
    monkeypatch.setenv(constants.ATTEMPT_NUMBER, "2")
    assert attempt_number() == 2
