"""Control-plane RPC tests: in-process server + retrying client.

Covers the seven-method protocol end to end over real gRPC, plus the client's
retry-until-coordinator-up behavior (the reference relies on Hadoop RetryProxy
for the same race, ApplicationRpcClient.java:80-104)."""

import threading
import time

import pytest

from tony_tpu.rpc.client import ApplicationRpcClient, RpcRetryError
from tony_tpu.rpc.server import ApplicationRpcServer, find_free_port
from tony_tpu.rpc.service import (ApplicationRpc, ApplicationStatus, TaskUrl,
                                  WorkerSpecResponse)


class FakeImpl(ApplicationRpc):
    """Scriptable ApplicationRpc with a 2-task gang barrier."""

    def __init__(self, expected=2):
        self.expected = expected
        self.registered = {}
        self.heartbeats = []
        self.heartbeat_snapshots = []   # the piggybacked metrics strings
        self.results = []
        self.tb_url = None
        self.finished = False
        self.lock = threading.Lock()

    def get_task_urls(self):
        return [TaskUrl("worker", "0", "http://w0/logs")]

    def get_cluster_spec(self, task_id):
        with self.lock:
            if len(self.registered) < self.expected:
                return ""
            return '{"worker": ["h0:1", "h1:1"]}'

    def register_worker_spec(self, worker, spec):
        with self.lock:
            self.registered[worker] = spec
            if len(self.registered) < self.expected:
                return WorkerSpecResponse()
            return WorkerSpecResponse(
                spec='{"worker": ["h0:1", "h1:1"]}',
                coordinator_address="h0:9999",
                process_id=sorted(self.registered).index(worker),
                num_processes=self.expected, mesh_spec='{"axes": {"dp": 2}}')

    def register_tensorboard_url(self, spec):
        self.tb_url = spec
        return spec

    def register_execution_result(self, exit_code, job_name, job_index, session_id):
        self.results.append((exit_code, job_name, job_index, session_id))
        return "RECEIVED"

    def finish_application(self):
        self.finished = True
        return "SUCCEEDED"

    def task_executor_heartbeat(self, task_id, metrics=""):
        self.heartbeats.append(task_id)
        self.heartbeat_snapshots.append(metrics)

    def get_application_status(self):
        return ApplicationStatus(
            status="SUCCEEDED" if self.finished else "RUNNING", session_id=0)


@pytest.fixture
def server():
    impl = FakeImpl()
    srv = ApplicationRpcServer(impl)
    srv.start()
    yield impl, srv
    srv.stop(0)


def test_all_seven_methods(server):
    impl, srv = server
    client = ApplicationRpcClient(f"localhost:{srv.port}")

    # gang barrier: first registration held back
    r0 = client.register_worker_spec("worker:0", "h0:1")
    assert not r0.released
    assert client.get_cluster_spec("worker:0") == ""
    r1 = client.register_worker_spec("worker:1", "h1:1")
    assert r1.released and r1.num_processes == 2
    assert r1.coordinator_address == "h0:9999"
    # re-register after release returns the full spec + stable ids
    r0b = client.register_worker_spec("worker:0", "h0:1")
    assert r0b.released and r0b.process_id == 0
    assert "worker" in client.get_cluster_spec("worker:0")

    urls = client.get_task_urls()
    assert urls == [TaskUrl("worker", "0", "http://w0/logs")]
    assert client.register_tensorboard_url("http://tb") == "http://tb"
    assert client.register_execution_result(0, "worker", "0", "0") == "RECEIVED"
    client.task_executor_heartbeat("worker:0")
    client.task_executor_heartbeat("worker:1")
    assert impl.heartbeats == ["worker:0", "worker:1"]
    assert client.get_application_status().status == "RUNNING"
    assert client.finish_application() == "SUCCEEDED"
    assert impl.finished
    st = client.get_application_status()
    assert st.finished and st.status == "SUCCEEDED"
    client.close()


def test_client_retries_until_server_up():
    port = find_free_port((20000, 30000))
    client = ApplicationRpcClient(f"localhost:{port}", max_retries=50,
                                  base_backoff_s=0.05)
    impl = FakeImpl(expected=1)

    def start_late():
        time.sleep(0.5)
        srv = ApplicationRpcServer(impl, port=port)
        srv.start()
        start_late.srv = srv

    t = threading.Thread(target=start_late)
    t.start()
    resp = client.register_worker_spec("worker:0", "h:1")  # issued before server exists
    t.join()
    assert resp.released
    start_late.srv.stop(0)
    client.close()


def test_client_retry_budget_exhausted():
    port = find_free_port((20000, 30000))
    client = ApplicationRpcClient(f"localhost:{port}", max_retries=3,
                                  base_backoff_s=0.01)
    with pytest.raises(RpcRetryError):
        client.get_task_urls()
    client.close()


def test_singleton_per_address(server):
    _, srv = server
    a = ApplicationRpcClient.get_instance(f"localhost:{srv.port}")
    b = ApplicationRpcClient.get_instance(f"localhost:{srv.port}")
    assert a is b
    a.close()


# ---------------------------------------------------------------------------
# Heartbeat metrics piggyback (the TaskMonitor/MetricsRpc analog riding
# the existing beat)
# ---------------------------------------------------------------------------

class TestHeartbeatMetricsPiggyback:
    def test_old_style_heartbeat_still_accepted(self, server):
        """An executor sending NO snapshot (the pre-metrics client call
        shape AND a raw wire message without the field) must keep
        working end to end through rpc/server.py + rpc/client.py."""
        impl, srv = server
        client = ApplicationRpcClient(f"localhost:{srv.port}")
        client.task_executor_heartbeat("worker:0")          # old call shape
        assert impl.heartbeats == ["worker:0"]
        assert impl.heartbeat_snapshots == [""]
        client.close()

    def test_wire_message_without_metrics_field(self, server):
        """A HeartbeatRequest serialized WITHOUT the metrics field (what
        an old binary puts on the wire) deserializes server-side with
        the proto3 default and is handled normally."""
        import grpc
        from tony_tpu.rpc import tony_pb2 as pb
        from tony_tpu.rpc.server import SERVICE_NAME
        impl, srv = server
        # serialize only field 1, exactly like the old message definition
        raw = pb.HeartbeatRequest(task_id="worker:1").SerializeToString()
        assert b"metrics" not in raw
        channel = grpc.insecure_channel(f"localhost:{srv.port}")
        stub = channel.unary_unary(
            f"/{SERVICE_NAME}/TaskExecutorHeartbeat",
            request_serializer=lambda m: m,
            response_deserializer=pb.HeartbeatResponse.FromString)
        stub(raw, timeout=10.0)
        channel.close()
        assert impl.heartbeats == ["worker:1"]
        assert impl.heartbeat_snapshots == [""]

    def test_snapshot_round_trips_bit_exact(self, server):
        """The piggybacked registry snapshot must arrive byte-identical
        and decode back to the same wire dict."""
        from tony_tpu.runtime import metrics as M
        impl, srv = server
        reg = M.MetricsRegistry()
        reg.counter("tony_serve_tokens_total",
                    help="useful generated tokens").inc(123)
        reg.gauge("tony_process_rss_bytes", help="rss").set(4096.5)
        h = reg.histogram("tony_train_step_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(2.0)
        payload = reg.to_wire_json()
        client = ApplicationRpcClient(f"localhost:{srv.port}")
        client.task_executor_heartbeat("worker:0", payload)
        client.close()
        assert impl.heartbeat_snapshots == [payload]        # bit-exact
        decoded = M.from_wire_json(impl.heartbeat_snapshots[0])
        assert decoded == reg.to_wire()
        # and the decoded snapshot re-encodes to the identical string
        import json
        assert json.dumps(decoded, separators=(",", ":")) == payload

    def test_malformed_snapshot_never_kills_coordinator_handler(
            self, tmp_path, monkeypatch):
        """Garbage metrics on the heartbeat must neither raise out of the
        coordinator's handler nor poison a previously-good snapshot."""
        monkeypatch.chdir(tmp_path)
        from tony_tpu.cluster.coordinator import Coordinator, CoordinatorRpc
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({
            "tony.worker.instances": "1",
            "tony.history.location": str(tmp_path / "hist")})
        co = Coordinator(conf, "application_rpc_metrics", str(tmp_path))
        try:
            rpc = CoordinatorRpc(co)
            good = ('{"c":[["tony_serve_tokens_total",{},7]],"g":[],'
                    '"h":[],"m":{}}')
            rpc.task_executor_heartbeat("worker:0", good)
            assert co.metrics_table.tasks() == ["worker:0"]
            for garbage in ("NOT JSON", "[]", '{"c": "nope"}',
                            '{"c": [["x", {}, "str"]]}',
                            '{"h": [["x", {}, {"b": [], "n": []}]]}',
                            "\x00\xff"):
                rpc.task_executor_heartbeat("worker:0", garbage)   # no raise
            # the last GOOD snapshot survives the garbage
            assert co.metrics_table.get("worker:0")["c"] == [
                ["tony_serve_tokens_total", {}, 7]]
            assert co.metrics_table.rejected == 6
        finally:
            co.rpc_server.stop(0)


# ---------------------------------------------------------------------------
# Heartbeat trace piggyback (spans + clock fields, strictly additive —
# the same wire-evolution precedent as the metrics piggyback above)
# ---------------------------------------------------------------------------

class TraceFakeImpl(FakeImpl):
    """New-style impl: accepts the trace piggyback."""

    def __init__(self, expected=2):
        super().__init__(expected)
        self.heartbeat_spans = []
        self.heartbeat_clocks = []

    def task_executor_heartbeat(self, task_id, metrics="", spans="",
                                client_time=0.0, client_rtt=0.0):
        self.heartbeats.append(task_id)
        self.heartbeat_snapshots.append(metrics)
        self.heartbeat_spans.append(spans)
        self.heartbeat_clocks.append((client_time, client_rtt))


class TestHeartbeatTracePiggyback:
    def test_old_wire_message_defaults_to_no_spans(self):
        """A HeartbeatRequest serialized WITHOUT the trace fields (an
        old binary's wire bytes) reaches a new impl as ""/0 — a plain
        beat, accepted end to end."""
        import grpc
        from tony_tpu.rpc import tony_pb2 as pb
        from tony_tpu.rpc.server import SERVICE_NAME
        impl = TraceFakeImpl(expected=1)
        srv = ApplicationRpcServer(impl)
        srv.start()
        try:
            # proto3 omits unset fields entirely, so serializing only
            # task_id+metrics IS the old binary's wire shape; sanity:
            # it reparses with the trace fields at their defaults
            raw = pb.HeartbeatRequest(task_id="worker:0",
                                      metrics="{}").SerializeToString()
            reparsed = pb.HeartbeatRequest.FromString(raw)
            assert reparsed.spans == "" and reparsed.client_unix_time == 0.0
            channel = grpc.insecure_channel(f"localhost:{srv.port}")
            stub = channel.unary_unary(
                f"/{SERVICE_NAME}/TaskExecutorHeartbeat",
                request_serializer=lambda m: m,
                response_deserializer=pb.HeartbeatResponse.FromString)
            stub(raw, timeout=10.0)
            channel.close()
            assert impl.heartbeat_spans == [""]
            assert impl.heartbeat_clocks == [(0.0, 0.0)]
        finally:
            srv.stop(0)

    def test_old_impl_still_served_piggyback_dropped(self, server):
        """An impl with the pre-trace signature (metrics-only, the
        FakeImpl above) keeps working against a NEW client sending
        spans + clock fields — the server detects the signature and
        drops the piggyback instead of TypeError-ing every beat."""
        impl, srv = server
        client = ApplicationRpcClient(f"localhost:{srv.port}")
        ack = client.task_executor_heartbeat(
            "worker:0", "", spans='{"s":[]}', client_rtt=0.25)
        assert ack is not None
        assert impl.heartbeats == ["worker:0"]
        client.close()

    def test_span_batch_and_clock_round_trip(self):
        """A span batch arrives byte-identical; the request stamps the
        sender's wall clock at send and carries the caller's RTT."""
        import time as _time

        from tony_tpu.runtime import tracing as T
        impl = TraceFakeImpl(expected=1)
        srv = ApplicationRpcServer(impl)
        srv.start()
        try:
            tr = T.Tracer(proc="w:0", sample_rate=1.0)
            with tr.span("unit.work", k="v"):
                pass
            batch = T.encode_batch(tr.drain())
            client = ApplicationRpcClient(f"localhost:{srv.port}")
            before = _time.time()
            client.task_executor_heartbeat("worker:0", "", spans=batch,
                                           client_rtt=0.125)
            after = _time.time()
            client.close()
            assert impl.heartbeat_spans == [batch]          # bit-exact
            decoded = T.parse_batch_json(impl.heartbeat_spans[0])
            assert decoded["s"][0]["n"] == "unit.work"
            stamped, rtt = impl.heartbeat_clocks[0]
            assert before <= stamped <= after
            assert abs(rtt - 0.125) < 1e-9
        finally:
            srv.stop(0)


# ---------------------------------------------------------------------------
# Retry policy: per-call deadlines, and non-idempotent calls never
# retried on DEADLINE_EXCEEDED (the coordinator may have processed them)
# ---------------------------------------------------------------------------

class TestRetryPolicy:
    def test_nonidempotent_not_retried_on_deadline(self):
        """register_execution_result past its deadline raises instead of
        retrying — a second send could double-record the exit."""
        import grpc
        from tony_tpu.rpc import tony_pb2 as pb

        class SlowResult(FakeImpl):
            def __init__(self):
                super().__init__(expected=1)
                self.result_calls = 0

            def register_execution_result(self, *a):
                self.result_calls += 1
                time.sleep(0.6)
                return "RECEIVED"

        impl = SlowResult()
        srv = ApplicationRpcServer(impl)
        srv.start()
        try:
            client = ApplicationRpcClient(f"localhost:{srv.port}",
                                          max_retries=5,
                                          base_backoff_s=0.01)
            with pytest.raises(grpc.RpcError) as ei:
                client._call(client._register_result,
                             pb.RegisterExecutionResultRequest(
                                 exit_code=0, job_name="worker",
                                 job_index="0", session_id="0"),
                             idempotent=False, deadline_s=0.3)
            assert ei.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
            time.sleep(0.8)               # let any straggler attempts land
            assert impl.result_calls == 1, "non-idempotent call was retried"
            client.close()
        finally:
            srv.stop(0)

    def test_idempotent_deadline_is_retried(self):
        """An idempotent read that times out once succeeds on the retry
        (the wedged-then-recovered coordinator shape)."""
        from tony_tpu.rpc import tony_pb2 as pb

        class SlowOnce(FakeImpl):
            def __init__(self):
                super().__init__(expected=1)
                self.spec_calls = 0

            def get_cluster_spec(self, task_id):
                self.spec_calls += 1
                if self.spec_calls == 1:
                    time.sleep(0.5)
                return '{"worker": ["h0:1"]}'

        impl = SlowOnce()
        srv = ApplicationRpcServer(impl)
        srv.start()
        try:
            client = ApplicationRpcClient(f"localhost:{srv.port}",
                                          max_retries=5,
                                          base_backoff_s=0.01)
            resp = client._call(client._get_cluster_spec,
                                pb.GetClusterSpecRequest(task_id="worker:0"),
                                idempotent=True, deadline_s=0.3)
            assert "worker" in resp.cluster_spec
            assert impl.spec_calls >= 2
            client.close()
        finally:
            srv.stop(0)

    def test_hot_path_reads_pass_tight_deadline(self, server, monkeypatch):
        """The barrier poll and the client monitor's status read run with
        a 3s per-attempt deadline — a wedged coordinator surfaces as a
        quick retryable timeout, not a 10s stall per attempt."""
        impl, srv = server
        client = ApplicationRpcClient(f"localhost:{srv.port}")
        seen = {}
        orig = client._call

        def spy(stub, request, **kw):
            seen[stub] = kw
            return orig(stub, request, **kw)

        monkeypatch.setattr(client, "_call", spy)
        client.get_cluster_spec("worker:0")
        client.get_application_status()
        assert seen[client._get_cluster_spec]["deadline_s"] == 3.0
        assert seen[client._get_status]["deadline_s"] == 3.0
        client.close()

    def test_reconnect_evicts_cached_instance(self, server):
        """reconnect() hands back a FRESH client (new channel) and
        installs it as the cached instance — the stale-channel escape
        hatch the executor's re-attach probe uses after a coordinator
        restart on the same address."""
        _, srv = server
        addr = f"localhost:{srv.port}"
        a = ApplicationRpcClient.get_instance(addr)
        b = ApplicationRpcClient.reconnect(addr)
        assert b is not a
        assert ApplicationRpcClient.get_instance(addr) is b
        assert b.get_task_urls()          # the fresh channel really dials
        b.close()


# ---------------------------------------------------------------------------
# Coordinator incarnation (crash-recovery re-attach signal) on the wire
# ---------------------------------------------------------------------------

class IncarnationImpl(FakeImpl):
    """Restarted-coordinator shape: serves incarnation 2 on both the
    heartbeat ack and the registration response."""

    def task_executor_heartbeat(self, task_id, metrics="", spans="",
                                client_time=0.0, client_rtt=0.0):
        from tony_tpu.rpc.service import HeartbeatAck
        self.heartbeats.append(task_id)
        return HeartbeatAck(gcs_token="tok", cluster_epoch=3, incarnation=2)

    def register_worker_spec(self, worker, spec):
        r = super().register_worker_spec(worker, spec)
        from dataclasses import replace
        return replace(r, incarnation=2)


class TestIncarnationWire:
    def test_round_trips_on_heartbeat_and_registration(self):
        impl = IncarnationImpl(expected=1)
        srv = ApplicationRpcServer(impl)
        srv.start()
        try:
            client = ApplicationRpcClient(f"localhost:{srv.port}")
            ack = client.task_executor_heartbeat("worker:0")
            assert ack.incarnation == 2
            assert ack.cluster_epoch == 3
            r = client.register_worker_spec("worker:0", "h0:1")
            assert r.incarnation == 2
            client.close()
        finally:
            srv.stop(0)

    def test_old_server_defaults_to_untracked(self, server):
        """A pre-recovery impl (FakeImpl returns a bare ack) maps to
        incarnation 0 = "not tracked" — new executors must not mistake
        it for a restart."""
        impl, srv = server
        client = ApplicationRpcClient(f"localhost:{srv.port}")
        ack = client.task_executor_heartbeat("worker:0")
        assert ack.incarnation == 0
        r = client.register_worker_spec("worker:0", "h0:1")
        assert r.incarnation == 0
        client.close()


# ---------------------------------------------------------------------------
# Control-plane auth (ClientToAMToken analog)
# ---------------------------------------------------------------------------

class TestRpcAuth:
    def _server(self, secret):
        impl = FakeImpl(expected=1)
        server = ApplicationRpcServer(impl, secret=secret)
        server.start()
        return impl, server

    def test_valid_token_accepted(self):
        impl, server = self._server("s3cret")
        try:
            client = ApplicationRpcClient(f"localhost:{server.port}",
                                          secret="s3cret", max_retries=3)
            urls = client.get_task_urls()
            assert urls and urls[0].name == "worker"
            client.close()
        finally:
            server.stop()

    def test_missing_token_rejected(self):
        import grpc
        impl, server = self._server("s3cret")
        try:
            client = ApplicationRpcClient(f"localhost:{server.port}",
                                          secret=None, max_retries=3)
            with pytest.raises(grpc.RpcError) as ei:
                client.get_task_urls()
            assert ei.value.code() == grpc.StatusCode.UNAUTHENTICATED
            client.close()
        finally:
            server.stop()

    def test_wrong_token_rejected(self):
        import grpc
        impl, server = self._server("s3cret")
        try:
            client = ApplicationRpcClient(f"localhost:{server.port}",
                                          secret="wrong", max_retries=3)
            with pytest.raises(grpc.RpcError) as ei:
                client.task_executor_heartbeat("worker:0")
            assert ei.value.code() == grpc.StatusCode.UNAUTHENTICATED
            client.close()
        finally:
            server.stop()

    def test_no_secret_server_accepts_all(self):
        impl, server = self._server(None)
        try:
            client = ApplicationRpcClient(f"localhost:{server.port}",
                                          secret="anything", max_retries=3)
            assert client.get_task_urls()
            client.close()
        finally:
            server.stop()

    def test_secret_env_fallback(self, monkeypatch):
        from tony_tpu import constants
        impl, server = self._server("envtoken")
        try:
            monkeypatch.setenv(constants.TONY_SECRET, "envtoken")
            client = ApplicationRpcClient(f"localhost:{server.port}",
                                          max_retries=3)
            assert client.get_task_urls()
            client.close()
        finally:
            server.stop()


class TestRpcTls:
    """Per-job TLS (rpc/tls.py): coordinator serves over TLS, clients pin
    to the job cert; plaintext and wrong-cert clients are rejected."""

    @pytest.fixture(scope="class")
    def certs(self, tmp_path_factory):
        from tony_tpu.rpc.tls import generate_self_signed
        d = tmp_path_factory.mktemp("tls")
        key, cert = generate_self_signed(str(d))
        return key, cert

    def test_key_file_is_private(self, certs):
        import os
        key, cert = certs
        assert (os.stat(key).st_mode & 0o777) == 0o600

    def test_tls_roundtrip_with_auth(self, certs):
        key, cert = certs
        impl = FakeImpl(expected=1)
        server = ApplicationRpcServer(impl, secret="s3cret",
                                      tls=(key, cert))
        server.start()
        try:
            c = ApplicationRpcClient(f"localhost:{server.port}",
                                     secret="s3cret", tls_cert=cert,
                                     max_retries=3, base_backoff_s=0.05)
            r = c.register_worker_spec("worker:0", "h0:1")
            assert r.num_processes == 1
            assert c.get_application_status().status == "RUNNING"
            c.close()
        finally:
            server.stop()

    def test_plaintext_client_rejected(self, certs):
        key, cert = certs
        server = ApplicationRpcServer(FakeImpl(), tls=(key, cert))
        server.start()
        try:
            c = ApplicationRpcClient(f"localhost:{server.port}",
                                     max_retries=2, base_backoff_s=0.05)
            with pytest.raises(Exception):   # handshake failure → retries
                c.get_application_status()   # exhausted → RpcRetryError
            c.close()
        finally:
            server.stop()

    def test_wrong_cert_rejected(self, certs, tmp_path):
        from tony_tpu.rpc.tls import generate_self_signed
        key, cert = certs
        _, other_cert = generate_self_signed(str(tmp_path))
        server = ApplicationRpcServer(FakeImpl(), tls=(key, cert))
        server.start()
        try:
            c = ApplicationRpcClient(f"localhost:{server.port}",
                                     tls_cert=other_cert,
                                     max_retries=2, base_backoff_s=0.05)
            with pytest.raises(Exception):
                c.get_application_status()
            c.close()
        finally:
            server.stop()

    def test_env_cert_pickup(self, certs, monkeypatch):
        """Executors get the cert path via TONY_TLS_CERT — the client must
        use it without explicit plumbing (like TONY_SECRET)."""
        from tony_tpu import constants
        key, cert = certs
        monkeypatch.setenv(constants.TONY_TLS_CERT, cert)
        server = ApplicationRpcServer(FakeImpl(expected=1), tls=(key, cert))
        server.start()
        try:
            c = ApplicationRpcClient(f"localhost:{server.port}",
                                     max_retries=3, base_backoff_s=0.05)
            assert c.get_application_status().status == "RUNNING"
            c.close()
        finally:
            server.stop()
