"""Continuous batching: slot reuse, per-request exactness, eos handling,
pipelined-vs-sequential equivalence, bucketed/batched admission, and the
closed-batch-over-open-loop-engine equivalence pin."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import transformer as T
from tony_tpu.models.decode import generate
from tony_tpu.models.serve import (ContinuousBatcher, ServeEngine,
                                   SpeculativeContinuousBatcher)

CFG = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.PRNGKey(0), CFG)


def _reference(params, prompt, max_new):
    out = generate(params, jnp.asarray(prompt, jnp.int32)[None], CFG,
                   max_new_tokens=max_new, rng=jax.random.PRNGKey(0),
                   temperature=0.0)
    return [int(t) for t in np.asarray(out.tokens[0, len(prompt):])]


class TestContinuousBatching:
    def test_token_identical_with_slot_reuse(self, params):
        """6 requests of mixed lengths through 3 slots: every request's
        output equals its solo greedy generate — including requests
        admitted into a REUSED slot whose cache still holds the previous
        occupant's stale K/V beyond the frontier."""
        rng = np.random.RandomState(0)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 3, 7, 4, 6, 3)]
        batcher = ContinuousBatcher(params, CFG, batch=3, max_len=32,
                                    chunk=4)
        outs = batcher.serve(prompts, max_new_tokens=6)
        for i, p in enumerate(prompts):
            assert outs[i] == _reference(params, p, 6), f"request {i}"

    def test_quantized_cache_token_identical_to_quant_generate(self,
                                                               params):
        """int8 KV serving: the batcher with a quantized cache equals
        per-request generate under the SAME quantized config (quant-to-
        quant is deterministic — per-row math is batch-independent on
        CPU; quant-to-float agreement is approximate by design). Slot
        reuse included."""
        qcfg = CFG.scaled(kv_cache_dtype="int8")
        rng = np.random.RandomState(7)
        prompts = [list(rng.randint(0, qcfg.vocab_size, size=n))
                   for n in (5, 3, 6, 4)]
        batcher = ContinuousBatcher(params, qcfg, batch=2, max_len=32,
                                    chunk=4)
        outs = batcher.serve(prompts, max_new_tokens=6)
        for i, p in enumerate(prompts):
            want = generate(params, jnp.asarray(p, jnp.int32)[None],
                            qcfg, max_new_tokens=6,
                            rng=jax.random.PRNGKey(0), temperature=0.0)
            assert outs[i] == [int(t) for t in
                               np.asarray(want.tokens[0, len(p):])], \
                f"request {i}"

    def test_single_slot_serializes_correctly(self, params):
        """batch=1 degenerates to sequential serving — same outputs."""
        rng = np.random.RandomState(1)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (4, 6)]
        batcher = ContinuousBatcher(params, CFG, batch=1, max_len=32,
                                    chunk=3)
        outs = batcher.serve(prompts, max_new_tokens=5)
        for i, p in enumerate(prompts):
            assert outs[i] == _reference(params, p, 5)

    def test_eos_stops_a_row_early(self, params):
        """A request whose greedy chain hits eos stops there (eos token
        included), freeing the slot; others run to their budget."""
        rng = np.random.RandomState(2)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 4)]
        ref0 = _reference(params, prompts[0], 6)
        eos = ref0[2]            # third generated token of request 0
        batcher = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                                    eos_id=eos, chunk=2)
        outs = batcher.serve(prompts, max_new_tokens=6)
        assert outs[0] == ref0[:3]          # stopped AT the eos token
        ref1 = _reference(params, prompts[1], 6)
        cut = (ref1.index(eos) + 1) if eos in ref1 else 6
        assert outs[1] == ref1[:cut]

    def test_prompt_too_long_rejected(self, params):
        batcher = ContinuousBatcher(params, CFG, batch=1, max_len=16)
        with pytest.raises(ValueError, match="exceeds max_len"):
            batcher.serve([[1] * 14], max_new_tokens=8)

    def test_per_request_budgets(self, params):
        """Mixed generation budgets (the case continuous batching exists
        for): each request stops at ITS budget and slots recycle."""
        rng = np.random.RandomState(3)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=4))
                   for _ in range(4)]
        budgets = [2, 7, 3, 5]
        batcher = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                                    chunk=3)
        outs = batcher.serve(prompts, budgets)
        for i, (p, b) in enumerate(zip(prompts, budgets)):
            assert outs[i] == _reference(params, p, b), f"request {i}"
        assert batcher.steps_executed >= max(budgets)

    def test_idle_slots_do_not_march(self, params, monkeypatch):
        """Queue drained with a straggler still running: freed slots are
        reset EVERY chunk (not just once), so an idle slot's garbage
        frontier cannot walk toward the cache end. Asserted on the
        retire masks themselves (a final-state length check is vacuous
        — serve()'s last iteration resets all rows anyway)."""
        import tony_tpu.models.serve as S
        masks = []
        orig = S.retire_rows

        def spy(cache, mask):
            masks.append(np.asarray(mask))
            return orig(cache, mask)

        monkeypatch.setattr(S, "retire_rows", spy)
        rng = np.random.RandomState(4)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=4))
                   for _ in range(3)]
        batcher = ContinuousBatcher(params, CFG, batch=3, max_len=32,
                                    chunk=2)
        outs = batcher.serve(prompts, [2, 2, 12])
        for i, (p, b) in enumerate(zip(prompts, [2, 2, 12])):
            assert outs[i] == _reference(params, p, b)
        # rows 0 and 1 free after ~1 chunk; the straggler needs ~6 — the
        # idle rows must be re-reset on EVERY subsequent chunk
        both_idle = [m for m in masks if m[0] and m[1]]
        assert len(both_idle) >= 3, [list(m) for m in masks]

    def test_invalid_request_rejected_before_serving(self, params):
        """A bad request ANYWHERE in the list fails up front — no partial
        serve that would discard completed outputs mid-flight."""
        batcher = ContinuousBatcher(params, CFG, batch=1, max_len=16)
        with pytest.raises(ValueError, match="request 1"):
            batcher.serve([[1, 2], [1] * 14], max_new_tokens=8)
        with pytest.raises(ValueError, match="must be positive"):
            batcher.serve([[1, 2]], max_new_tokens=0)
        with pytest.raises(ValueError, match="empty prompt"):
            batcher.serve([[1, 2], []], max_new_tokens=4)


class TestSharedPrefix:
    """Shared-prefix caching: the prefix prefills once into a K/V
    template; admission copies it and runs only the request's suffix."""

    def _refs(self, params, prefix, suffixes, budgets):
        out = []
        for sfx, b in zip(suffixes, budgets):
            full = jnp.asarray(prefix + sfx, jnp.int32)[None]
            g = generate(params, full, CFG, max_new_tokens=b,
                         rng=jax.random.PRNGKey(0), temperature=0.0)
            out.append([int(t) for t in
                        np.asarray(g.tokens[0, full.shape[1]:])])
        return out

    def test_greedy_prefix_serving_token_identical(self, params):
        """Serving suffixes against a shared prefix equals per-request
        greedy decode of prefix+suffix — including slot reuse, where a
        new occupant's template copy overwrites the previous request's
        K/V."""
        rs = np.random.RandomState(7)
        prefix = [int(t) for t in rs.randint(0, CFG.vocab_size, size=9)]
        suffixes = [list(rs.randint(0, CFG.vocab_size,
                                    size=rs.randint(2, 6)))
                    for _ in range(5)]
        budgets = [int(b) for b in rs.randint(4, 9, size=5)]
        batcher = ContinuousBatcher(params, CFG, batch=2, max_len=48,
                                    chunk=3, shared_prefix=prefix)
        outs = batcher.serve(suffixes, budgets)
        assert outs == self._refs(params, prefix, suffixes, budgets)

    def test_speculative_prefix_serving_token_identical(self, params):
        """The speculative batcher's prefix admission fills BOTH models'
        caches from their own templates; greedy rounds stay token-exact."""
        draft = T.init_params(jax.random.PRNGKey(99), CFG)
        rs = np.random.RandomState(8)
        prefix = [int(t) for t in rs.randint(0, CFG.vocab_size, size=7)]
        suffixes = [list(rs.randint(0, CFG.vocab_size, size=3))
                    for _ in range(4)]
        budgets = [5, 7, 4, 6]
        batcher = SpeculativeContinuousBatcher(
            params, CFG, draft, CFG, batch=2, max_len=48,
            num_speculative=3, chunk=2, shared_prefix=prefix)
        outs = batcher.serve(suffixes, budgets)
        assert outs == self._refs(params, prefix, suffixes, budgets)

    def test_prefix_budget_validation(self, params):
        batcher = ContinuousBatcher(params, CFG, batch=1, max_len=16,
                                    shared_prefix=[1, 2, 3, 4])
        with pytest.raises(ValueError, match="shared prefix 4"):
            batcher.serve([[5] * 6], max_new_tokens=8)
        with pytest.raises(ValueError, match="non-empty"):
            ContinuousBatcher(params, CFG, batch=1, max_len=16,
                              shared_prefix=[])


class TestSampledServing:
    """temperature/top_k/top_p on the continuous batcher: valid tokens,
    seed-reproducible workloads, seed-sensitive outputs."""

    def test_sampled_serve_reproducible_by_seed(self, params):
        rng = np.random.RandomState(5)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=4))
                   for _ in range(5)]

        def run(seed):
            b = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                                  chunk=3, temperature=0.8, top_k=50,
                                  top_p=0.9, seed=seed)
            return b.serve(prompts, max_new_tokens=6)

        outs = run(0)
        for o in outs:
            assert len(o) == 6
            assert all(0 <= t < CFG.vocab_size for t in o)
        assert outs == run(0)          # same seed, same workload
        assert outs != run(1)          # overwhelmingly likely

    def test_greedy_default_unchanged_by_seed(self, params):
        rng = np.random.RandomState(6)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=4))
                   for _ in range(3)]
        a = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                              chunk=3, seed=0).serve(prompts, 5)
        b = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                              chunk=3, seed=7).serve(prompts, 5)
        assert a == b
        for i, p in enumerate(prompts):
            assert a[i] == _reference(params, p, 5)


class TestSpeculativeContinuousBatching:
    """Continuous batching composed with speculative decoding: every
    slot runs draft-propose/target-verify rounds at its own frontier
    and commits its own acceptance; slot reuse/retirement identical to
    the greedy batcher."""

    def test_token_identical_with_slot_reuse(self, params):
        """7 mixed-length requests with mixed budgets through 3 slots,
        self-draft and rejecting draft: every request equals its solo
        greedy generate, and the self-draft (full acceptance) finishes
        in strictly fewer speculative rounds."""
        draft = T.init_params(jax.random.PRNGKey(99), CFG)
        rng = np.random.RandomState(3)
        prompts = [list(rng.randint(0, CFG.vocab_size,
                                    size=rng.randint(3, 9)))
                   for _ in range(7)]
        budgets = [int(b) for b in rng.randint(4, 14, size=7)]
        rounds = {}
        for d, name in ((params, "self"), (draft, "rej")):
            batcher = SpeculativeContinuousBatcher(
                params, CFG, d, CFG, batch=3, max_len=64,
                num_speculative=3, chunk=2)
            outs = batcher.serve(prompts, budgets)
            for i, (p, b) in enumerate(zip(prompts, budgets)):
                assert outs[i] == _reference(params, p, b), (name, i)
            rounds[name] = batcher.rounds_executed
        assert rounds["self"] < rounds["rej"]

    def test_eos_frees_slot_early(self, params):
        """A request hitting eos mid-speculative-chunk stops there (eos
        included, surplus committed tokens discarded) and its slot is
        recycled."""
        rng = np.random.RandomState(2)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 4, 6)]
        ref0 = _reference(params, prompts[0], 8)
        eos = ref0[2]
        batcher = SpeculativeContinuousBatcher(
            params, CFG, params, CFG, batch=2, max_len=64,
            num_speculative=4, eos_id=eos, chunk=2)
        outs = batcher.serve(prompts, max_new_tokens=8)
        assert outs[0] == ref0[:3]
        for i in (1, 2):
            ref = _reference(params, prompts[i], 8)
            cut = (ref.index(eos) + 1) if eos in ref else 8
            assert outs[i] == ref[:cut]

    def test_bad_num_speculative_rejected(self, params):
        with pytest.raises(ValueError, match="num_speculative"):
            SpeculativeContinuousBatcher(params, CFG, params, CFG,
                                         batch=2, max_len=32,
                                         num_speculative=0)

    def test_vocab_mismatch_rejected(self, params):
        """A draft with a different vocabulary is silent corruption in
        greedy mode and a shape error in sampled mode — rejected up
        front, at the batcher AND at the generate-path entry points."""
        from tony_tpu.models.decode import (speculative_generate,
                                            speculative_generate_device)

        bad_cfg = CFG.scaled(vocab_size=CFG.vocab_size // 2)
        bad = T.init_params(jax.random.PRNGKey(1), bad_cfg)
        with pytest.raises(ValueError, match="vocab"):
            SpeculativeContinuousBatcher(params, CFG, bad, bad_cfg,
                                         batch=2, max_len=32)
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        with pytest.raises(ValueError, match="vocab"):
            speculative_generate_device(params, bad, prompt, CFG, bad_cfg,
                                        max_new_tokens=4,
                                        num_speculative=2)
        with pytest.raises(ValueError, match="vocab"):
            speculative_generate(params, bad, prompt, CFG, bad_cfg,
                                 max_new_tokens=4, num_speculative=2)

    @pytest.mark.slow
    def test_sampled_speculative_serving_matches_target_distribution(self):
        """Sampled speculative serving (rejection-sampling rounds inside
        the continuous batcher): each served request's tokens are
        distributed as direct target sampling, for a MISMATCHED draft —
        measured on the 2-token joint over many served requests, with a
        draft-only baseline proving the tolerance discriminates. Also
        pins seed-reproducibility of a whole served workload."""
        from tony_tpu.models.decode import generate as gen

        cfg = T.TransformerConfig(vocab_size=11, d_model=24, n_layers=2,
                                  n_heads=2, d_ff=48, max_seq=1024,
                                  dtype=jnp.float32,
                                  logits_dtype=jnp.float32, remat=False)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        draft = T.init_params(jax.random.PRNGKey(99), cfg)
        prompt = [3, 7, 1, 5]
        n_req, n = 192, 2

        def joint_serve(seed):
            b = SpeculativeContinuousBatcher(
                params, cfg, draft, cfg, batch=48, max_len=32,
                num_speculative=3, chunk=1, temperature=1.1, top_k=6,
                seed=seed)
            outs = b.serve([prompt] * n_req, n)
            c = np.zeros((cfg.vocab_size, cfg.vocab_size))
            for o in outs:
                c[o[0], o[1]] += 1
            return c

        counts = sum(joint_serve(s) for s in range(8))
        spec_p = counts / counts.sum()

        pm = jnp.asarray([prompt], jnp.int32).repeat(n_req, 0)

        def joint_gen(model, seed0):
            c = np.zeros((cfg.vocab_size, cfg.vocab_size))
            for i in range(8):
                a = np.asarray(gen(model, pm, cfg, max_new_tokens=n,
                                   rng=jax.random.PRNGKey(seed0 + i),
                                   temperature=1.1,
                                   top_k=6).tokens[:, -n:])
                for r in a:
                    c[r[0], r[1]] += 1
            return c / c.sum()

        ref_p = joint_gen(params, 40)
        ref2_p = joint_gen(params, 400)      # independent same-dist run
        draft_p = joint_gen(draft, 80)
        tv_spec = 0.5 * np.abs(spec_p - ref_p).sum()
        tv_noise = 0.5 * np.abs(ref2_p - ref_p).sum()
        tv_draft = 0.5 * np.abs(draft_p - ref_p).sum()
        # self-calibrated: within ~2x of same-distribution sampling
        # noise at this sample count (and far under the draft's gap)
        assert tv_spec < max(0.1, 2.0 * tv_noise), (tv_spec, tv_noise)
        assert tv_draft > 0.3, tv_draft

        # whole-workload reproducibility by seed
        b1 = SpeculativeContinuousBatcher(
            params, cfg, draft, cfg, batch=3, max_len=32,
            num_speculative=3, chunk=2, temperature=1.1, top_k=6, seed=7)
        o1 = b1.serve([prompt] * 5, 6)
        b2 = SpeculativeContinuousBatcher(
            params, cfg, draft, cfg, batch=3, max_len=32,
            num_speculative=3, chunk=2, temperature=1.1, top_k=6, seed=7)
        assert o1 == b2.serve([prompt] * 5, 6)

    def test_spec_sampled_pipelined_equals_sequential(self, params):
        """Sampled speculative serving: per-request round-key streams
        make the pipelined loop's shifted admissions invisible — both
        loops produce identical sampled streams."""
        draft = T.init_params(jax.random.PRNGKey(99), CFG)
        rng = np.random.RandomState(11)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (4, 6, 3)]
        budgets = [5, 3, 4]

        def run(pipeline):
            b = SpeculativeContinuousBatcher(
                params, CFG, draft, CFG, batch=2, max_len=48,
                num_speculative=2, chunk=2, temperature=0.9, top_k=6,
                seed=3, pipeline=pipeline)
            return b.serve(prompts, budgets)

        assert run(True) == run(False)

    def test_distinct_draft_config(self, params):
        """The draft may have a different architecture (the production
        shape: a much smaller model) — caches sized per-config."""
        dcfg = CFG.scaled(n_layers=1, d_model=32, n_heads=2, d_ff=64)
        draft = T.init_params(jax.random.PRNGKey(5), dcfg)
        rng = np.random.RandomState(7)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=5))
                   for _ in range(4)]
        batcher = SpeculativeContinuousBatcher(
            params, CFG, draft, dcfg, batch=2, max_len=48,
            num_speculative=3, chunk=3)
        outs = batcher.serve(prompts, max_new_tokens=7)
        for i, p in enumerate(prompts):
            assert outs[i] == _reference(params, p, 7), f"request {i}"


class TestPipelinedServing:
    """Double-buffered dispatch: chunk N+1 is issued before chunk N's
    tokens are fetched. Outputs must be token-identical to the
    sequential loop in EVERY mode — the eos workloads force the
    catch-up path (a speculatively issued chunk crossing an
    unpredictable completion, whose garbage rows are discarded and
    whose admission lands late).

    Compile frugality: these tests deliberately REUSE the workloads and
    static shapes of the earlier equivalence tests (same RandomState
    seeds, batch/max_len/chunk/sampling combos), so the pipelined and
    sequential runs hit the already-compiled device programs and the
    solo-generate references hit generate()'s jit cache — the suite
    pays serve-loop wall time, not a second compile bill."""

    def test_greedy_pipelined_equals_sequential_and_reference(self,
                                                              params):
        # the test_token_identical_with_slot_reuse workload, verbatim
        rng = np.random.RandomState(0)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 3, 7, 4, 6, 3)]

        def run(pipeline):
            b = ContinuousBatcher(params, CFG, batch=3, max_len=32,
                                  chunk=4, pipeline=pipeline)
            return b.serve(prompts, max_new_tokens=6)

        pipelined, sequential = run(True), run(False)
        assert pipelined == sequential
        for i, p in enumerate(prompts):
            assert pipelined[i] == _reference(params, p, 6), i

    def test_greedy_eos_catchup_path(self, params):
        """eos completions are invisible to host budget bookkeeping, so
        the pipelined loop speculates across them and must catch up —
        discarding the freed rows' speculatively-decoded garbage and
        admitting late — without changing any output. (The
        test_eos_stops_a_row_early workload plus a third request so an
        admission rides the catch-up.)"""
        rng = np.random.RandomState(2)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 4, 5)]
        ref0 = _reference(params, prompts[0], 6)
        eos = ref0[2]

        def run(pipeline):
            b = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                                  eos_id=eos, chunk=2,
                                  pipeline=pipeline)
            return b.serve(prompts, max_new_tokens=6)

        pipelined = run(True)
        assert pipelined == run(False)
        for i, p in enumerate(prompts):
            ref = _reference(params, p, 6)
            cut = (ref.index(eos) + 1) if eos in ref else 6
            assert pipelined[i] == ref[:cut], i

    def test_sampled_pipelined_equals_sequential_with_eos(self, params):
        """Sampled serving under eos: admission timing CAN shift between
        the loops here, so equality hangs entirely on the per-request
        key streams. (Sampling params match
        test_sampled_serve_reproducible_by_seed — same compiled step
        program.)"""
        rng = np.random.RandomState(5)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=4))
                   for _ in range(5)]

        def run(pipeline, eos=None):
            b = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                                  chunk=3, temperature=0.8, top_k=50,
                                  top_p=0.9, seed=0, eos_id=eos,
                                  pipeline=pipeline)
            return b.serve(prompts, max_new_tokens=6)

        no_eos = run(True)
        assert no_eos == run(False)
        eos = no_eos[0][0]                   # a token that DOES occur
        assert run(True, eos=eos) == run(False, eos=eos)

    def test_sampled_output_independent_of_slot_count(self, params):
        """The per-request stream guarantee, stated directly: a sampled
        request's output is a function of (seed, request index, prompt)
        alone — re-serving the same workload through a different slot
        count (completely different admission timing) reproduces every
        output. The pre-pipelining shared-stream scheme could not do
        this."""
        rng = np.random.RandomState(5)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=4))
                   for _ in range(5)]

        def run(batch):
            b = ContinuousBatcher(params, CFG, batch=batch, max_len=32,
                                  chunk=3, temperature=0.8, top_k=50,
                                  top_p=0.9, seed=0)
            return b.serve(prompts, max_new_tokens=6)

        assert run(1) == run(2)

    def test_speculative_pipelined_equals_sequential(self, params):
        """Greedy speculative serving with eos mid-chunk (the spec
        test_token_identical workload shapes): catch-up discards a freed
        slot's speculatively-run ROUNDS, not just steps."""
        draft = T.init_params(jax.random.PRNGKey(99), CFG)
        rng = np.random.RandomState(3)
        prompts = [list(rng.randint(0, CFG.vocab_size,
                                    size=rng.randint(3, 9)))
                   for _ in range(5)]
        budgets = [int(b) for b in rng.randint(4, 14, size=5)]
        ref0 = _reference(params, prompts[0], budgets[0])
        eos = ref0[-1]

        def run(pipeline):
            b = SpeculativeContinuousBatcher(
                params, CFG, draft, CFG, batch=3, max_len=64,
                num_speculative=3, chunk=2, eos_id=eos,
                pipeline=pipeline)
            return b.serve(prompts, budgets)

        pipelined = run(True)
        assert pipelined == run(False)
        for i, (p, bud) in enumerate(zip(prompts, budgets)):
            ref = _reference(params, p, bud)
            cut = (ref.index(eos) + 1) if eos in ref else bud
            assert pipelined[i] == ref[:cut], i

    def test_shared_prefix_pipelined_equals_sequential(self, params):
        # the test_greedy_prefix_serving workload, verbatim (same
        # template/admission/step programs and cached references)
        rs = np.random.RandomState(7)
        prefix = [int(t) for t in rs.randint(0, CFG.vocab_size, size=9)]
        suffixes = [list(rs.randint(0, CFG.vocab_size,
                                    size=rs.randint(2, 6)))
                    for _ in range(5)]
        budgets = [int(b) for b in rs.randint(4, 9, size=5)]

        def run(pipeline):
            b = ContinuousBatcher(params, CFG, batch=2, max_len=48,
                                  chunk=3, shared_prefix=prefix,
                                  pipeline=pipeline)
            return b.serve(suffixes, budgets)

        pipelined = run(True)
        assert pipelined == run(False)
        full0 = jnp.asarray(prefix + suffixes[0], jnp.int32)[None]
        g = generate(params, full0, CFG, max_new_tokens=budgets[0],
                     rng=jax.random.PRNGKey(0), temperature=0.0)
        assert pipelined[0] == [
            int(t) for t in np.asarray(g.tokens[0, full0.shape[1]:])]

    def test_budget_only_workload_matches_sequential_steps(self, params):
        """With no eos, completions are budget-predictable, so the
        pipelined loop defers issuing across admission events and pays
        ZERO extra device steps — step utilization is identical to the
        sequential loop, not merely close."""
        rng = np.random.RandomState(3)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=4))
                   for _ in range(4)]
        budgets = [2, 7, 3, 5]

        def steps(pipeline):
            b = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                                  chunk=3, pipeline=pipeline)
            outs = b.serve(prompts, budgets)
            assert [len(o) for o in outs] == budgets
            return b.steps_executed

        assert steps(True) == steps(False)

    def test_phase_times_recorded(self, params):
        rng = np.random.RandomState(3)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=4))
                   for _ in range(3)]
        b = ContinuousBatcher(params, CFG, batch=2, max_len=32, chunk=3)
        b.serve(prompts, max_new_tokens=5)
        s = b.phase_times.summary()
        for phase in ("dispatch", "fetch", "admit"):
            assert s[phase]["count"] > 0, s
            assert s[phase]["total_s"] >= 0.0
        # every fetched chunk was first dispatched (the loop may drop at
        # most the final speculative chunk unfetched)
        assert 0 <= (b.phase_times.count("dispatch")
                     - b.phase_times.count("fetch")) <= 1


class TestBucketedAdmission:
    """Admission pads prompts to power-of-two length buckets and lands
    every slot freed in a chunk in one batched dispatch: at most ONE
    compiled program per bucket, however many distinct prompt lengths
    the workload carries."""

    def test_one_program_per_bucket(self, params, retrace_guard):
        """8 distinct prompt lengths spanning two buckets (<=16 and
        <=32) through repeated slot reuse: at most the two bucket
        programs may trace, and the per-length admit_row program must
        not trace at all."""
        rng = np.random.RandomState(30)
        lengths = [3, 4, 5, 7, 9, 17, 20, 23]
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in lengths]
        batcher = ContinuousBatcher(params, CFG, batch=2, max_len=48,
                                    chunk=3)
        outs = batcher.serve(prompts, max_new_tokens=4)
        retrace_guard.assert_max("admit_rows", 2)     # one per bucket
        retrace_guard.assert_max("admit_row", 0)      # legacy path idle
        # spot-check one short and one long (bucket-32) request against
        # solo generate; full-coverage exactness is pinned elsewhere
        assert outs[0] == _reference(params, prompts[0], 4)
        assert outs[6] == _reference(params, prompts[6], 4)
        assert all(len(o) == 4 for o in outs)

    def test_distinct_lengths_same_bucket_share_one_program(
            self, params, retrace_guard):
        """The core claim in isolation: lengths 3..10 all pad to one
        16-token bucket — at most one trace total."""
        rng = np.random.RandomState(31)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (3, 4, 5, 6, 7, 8, 9, 10)]
        batcher = ContinuousBatcher(params, CFG, batch=2, max_len=48,
                                    chunk=3)
        outs = batcher.serve(prompts, max_new_tokens=4)
        retrace_guard.assert_max("admit_rows", 1)
        assert outs[0] == _reference(params, prompts[0], 4)
        assert all(len(o) == 4 for o in outs)

    def test_batched_admission_multiple_slots_one_chunk(self, params):
        """Equal budgets retire every slot in the SAME chunk, so each
        admission wave lands multiple requests in one admit_rows
        dispatch — outputs stay per-request exact."""
        rng = np.random.RandomState(0)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 3, 7, 4, 6, 3)]
        batcher = ContinuousBatcher(params, CFG, batch=3, max_len=32,
                                    chunk=4)
        outs = batcher.serve(prompts, max_new_tokens=6)
        for i, p in enumerate(prompts):
            assert outs[i] == _reference(params, p, 6), i

    def test_legacy_admission_still_exact(self, params):
        """bucketed_admission=False keeps the batch-1 admit_row path
        working and exact."""
        rng = np.random.RandomState(0)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 3, 7, 4)]
        batcher = ContinuousBatcher(params, CFG, batch=3, max_len=32,
                                    chunk=4, bucketed_admission=False)
        outs = batcher.serve(prompts, max_new_tokens=6)
        for i, p in enumerate(prompts):
            assert outs[i] == _reference(params, p, 6), i

    def test_batch1_admission_pads_to_buckets_too(self, params,
                                                  retrace_guard):
        """The batch-1 admission retrace cap: with bucketed (batched)
        admission OFF, eight distinct prompt lengths in one 16-token
        bucket still compile at most ONE admit_row program — the old
        monolithic-prefill body retraced once per distinct length.
        Outputs stay per-request exact."""
        rng = np.random.RandomState(35)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (3, 4, 5, 6, 7, 8, 9, 10)]
        batcher = ContinuousBatcher(params, CFG, batch=2, max_len=48,
                                    chunk=3, bucketed_admission=False)
        outs = batcher.serve(prompts, max_new_tokens=4)
        retrace_guard.assert_max("admit_row", 1)
        retrace_guard.assert_max("admit_rows", 0)
        assert outs[0] == _reference(params, prompts[0], 4)
        assert outs[7] == _reference(params, prompts[7], 4)
        assert all(len(o) == 4 for o in outs)

    def test_ring_cache_falls_back_to_per_length_admission(
            self, params, retrace_guard):
        """Rolling caches cannot take padded prompts (wrapped writes
        would land padding on live ring rows): the batcher silently
        routes admission through admit_row and still serves correctly
        (pipelined == sequential under the ring too)."""
        rcfg = CFG.scaled(attn_window=8, kv_cache_capacity=8)
        rng = np.random.RandomState(34)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 3)]

        def run(pipeline):
            b = ContinuousBatcher(params, rcfg, batch=2, max_len=32,
                                  chunk=3, pipeline=pipeline)
            assert not b.bucketed_admission
            return b.serve(prompts, max_new_tokens=4)

        outs = run(True)
        retrace_guard.assert_max("admit_rows", 0)
        assert outs == run(False)
        for o in outs:
            assert len(o) == 4
            assert all(0 <= t < rcfg.vocab_size for t in o)

    def test_custom_admission_bucket_ladder(self, params):
        rng = np.random.RandomState(0)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 3, 7)]
        batcher = ContinuousBatcher(params, CFG, batch=3, max_len=32,
                                    chunk=4, admission_buckets=(8,))
        outs = batcher.serve(prompts, max_new_tokens=6)
        for i, p in enumerate(prompts):
            assert outs[i] == _reference(params, p, 6), i
        with pytest.raises(ValueError, match="admission_buckets"):
            ContinuousBatcher(params, CFG, batch=2, max_len=32,
                              admission_buckets=(0, 8))

    def test_speculative_bucketed_admission_one_program_per_bucket(
            self, params, retrace_guard):
        draft = T.init_params(jax.random.PRNGKey(99), CFG)
        rng = np.random.RandomState(3)
        prompts = [list(rng.randint(0, CFG.vocab_size,
                                    size=rng.randint(3, 9)))
                   for _ in range(6)]
        batcher = SpeculativeContinuousBatcher(
            params, CFG, draft, CFG, batch=3, max_len=64,
            num_speculative=3, chunk=2)
        outs = batcher.serve(prompts, max_new_tokens=5)
        retrace_guard.assert_max("spec_admit_rows", 1)
        retrace_guard.assert_max("spec_admit_row", 0)
        assert outs[0] == _reference(params, prompts[0], 5)
        assert all(len(o) == 5 for o in outs)


class TestClosedBatchEngineEquivalence:
    """The engine-refactor pin: ``serve()`` rebuilt as a thin wrapper
    over the open-loop :class:`ServeEngine` stays BIT-identical in
    outputs — and, for the single-token-per-step modes on budget-only
    workloads, identical in ``steps_executed`` — to the pre-refactor
    fixed-queue loop, across greedy / sampled / speculative /
    shared-prefix modes. The pre-refactor contract is the per-mode
    solo-generate references (PR 1's pins, all asserted above) plus
    pipelined==sequential equality; this class additionally pins that
    an OPEN-LOOP run (incremental submission from another thread, per-
    request rng streams doing the heavy lifting) produces the same
    tokens as the closed batch.

    Workloads/shapes deliberately REUSE the earlier tests' (same seeds,
    batch/max_len/chunk combos) so everything here hits already-
    compiled programs."""

    def _open_loop(self, batcher, prompts, budgets):
        outs: dict = {i: [] for i in range(len(prompts))}
        eng = ServeEngine(
            batcher, on_delta=lambda r, t: outs[r].extend(t),
            on_retired=lambda r, reason, n, final: outs[r].extend(final))
        th = threading.Thread(target=eng.run, daemon=True)
        th.start()
        for i, (p, b) in enumerate(zip(prompts, budgets)):
            eng.submit(i, p, b)
            if i == 0:
                time.sleep(0.05)      # a genuinely LIVE queue: later
                #                       submits land mid-serve
        eng.drain()
        th.join(timeout=300)
        assert not th.is_alive(), "engine did not drain"
        return [outs[i] for i in range(len(prompts))]

    def _pin(self, make, prompts, budgets, pin_steps=True):
        bp = make(True)
        outs_p = bp.serve(prompts, budgets)
        bs = make(False)
        outs_s = bs.serve(prompts, budgets)
        assert outs_p == outs_s
        if pin_steps:
            # budget-only workloads pipeline losslessly — the engine
            # must execute the exact chunk schedule of the sequential
            # (pre-refactor-equivalent) loop
            assert bp.steps_executed == bs.steps_executed
        assert self._open_loop(make(True), prompts, budgets) == outs_p
        return outs_p

    def test_greedy(self, params):
        rng = np.random.RandomState(0)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 3, 7, 4, 6, 3)]
        outs = self._pin(
            lambda pipeline: ContinuousBatcher(
                params, CFG, batch=3, max_len=32, chunk=4,
                pipeline=pipeline),
            prompts, [6] * 6)
        for i, p in enumerate(prompts):
            assert outs[i] == _reference(params, p, 6), i

    def test_sampled(self, params):
        rng = np.random.RandomState(5)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=4))
                   for _ in range(5)]
        self._pin(
            lambda pipeline: ContinuousBatcher(
                params, CFG, batch=2, max_len=32, chunk=3,
                temperature=0.8, top_k=50, top_p=0.9, seed=0,
                pipeline=pipeline),
            prompts, [6] * 5)

    def test_shared_prefix(self, params):
        rs = np.random.RandomState(7)
        prefix = [int(t) for t in rs.randint(0, CFG.vocab_size, size=9)]
        suffixes = [list(rs.randint(0, CFG.vocab_size,
                                    size=rs.randint(2, 6)))
                    for _ in range(5)]
        budgets = [int(b) for b in rs.randint(4, 9, size=5)]
        self._pin(
            lambda pipeline: ContinuousBatcher(
                params, CFG, batch=2, max_len=48, chunk=3,
                shared_prefix=prefix, pipeline=pipeline),
            suffixes, budgets)

    def test_speculative(self, params):
        draft = T.init_params(jax.random.PRNGKey(99), CFG)
        rng = np.random.RandomState(3)
        prompts = [list(rng.randint(0, CFG.vocab_size,
                                    size=rng.randint(3, 9)))
                   for _ in range(7)]
        budgets = [int(b) for b in rng.randint(4, 14, size=7)]
        outs = self._pin(
            lambda pipeline: SpeculativeContinuousBatcher(
                params, CFG, draft, CFG, batch=3, max_len=64,
                num_speculative=3, chunk=2, pipeline=pipeline),
            prompts, budgets,
            # speculative completions are acceptance-driven, not
            # host-predictable, so the chunk schedule (unlike tokens)
            # may legally differ pipelined-vs-sequential
            pin_steps=False)
        for i, (p, b) in enumerate(zip(prompts, budgets)):
            assert outs[i] == _reference(params, p, b), i


@pytest.mark.slow
class TestPipelinedServingSmoke:
    """End-to-end smoke: the pipelined batcher under a realistic mixed
    workload — many distinct prompt lengths across several buckets,
    per-request budgets, eos, sampled variants — on CPU."""

    def test_mixed_length_mixed_budget_smoke(self, params):
        rng = np.random.RandomState(40)
        n = 24
        prompts = [list(rng.randint(0, CFG.vocab_size,
                                    size=rng.randint(3, 40)))
                   for _ in range(n)]
        budgets = [int(b) for b in rng.randint(2, 12, size=n)]
        batcher = ContinuousBatcher(params, CFG, batch=4, max_len=64,
                                    chunk=4)
        outs = batcher.serve(prompts, budgets)
        for i, (p, b) in enumerate(zip(prompts, budgets)):
            assert outs[i] == _reference(params, p, b), i
        # admission compiled per bucket (16/32/64), not per length —
        # filtered to THIS batcher's batch-4 programs (the module-global
        # counter also holds other tests' batch-2/3 shapes)
        from tony_tpu.models.serve import TRACE_COUNTS
        admit_shapes = {k[1] for k in TRACE_COUNTS
                        if k[0] == "admit_rows" and k[1][0] == 4}
        assert len(admit_shapes) <= 3, admit_shapes

    def test_sampled_and_eos_smoke(self, params):
        rng = np.random.RandomState(41)
        prompts = [list(rng.randint(0, CFG.vocab_size,
                                    size=rng.randint(3, 20)))
                   for _ in range(12)]
        budgets = [int(b) for b in rng.randint(3, 10, size=12)]
        b1 = ContinuousBatcher(params, CFG, batch=3, max_len=48,
                               chunk=4, temperature=0.8, top_k=30,
                               seed=1)
        outs = b1.serve(prompts, budgets)
        eos = outs[0][0]
        b2 = ContinuousBatcher(params, CFG, batch=3, max_len=48,
                               chunk=4, temperature=0.8, top_k=30,
                               seed=1, eos_id=eos, pipeline=False)
        b3 = ContinuousBatcher(params, CFG, batch=3, max_len=48,
                               chunk=4, temperature=0.8, top_k=30,
                               seed=1, eos_id=eos)
        assert b3.serve(prompts, budgets) == b2.serve(prompts, budgets)
