"""Continuous batching: slot reuse, per-request exactness, eos handling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import transformer as T
from tony_tpu.models.decode import generate
from tony_tpu.models.serve import (ContinuousBatcher,
                                   SpeculativeContinuousBatcher)

CFG = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.PRNGKey(0), CFG)


def _reference(params, prompt, max_new):
    out = generate(params, jnp.asarray(prompt, jnp.int32)[None], CFG,
                   max_new_tokens=max_new, rng=jax.random.PRNGKey(0),
                   temperature=0.0)
    return [int(t) for t in np.asarray(out.tokens[0, len(prompt):])]


class TestContinuousBatching:
    def test_token_identical_with_slot_reuse(self, params):
        """6 requests of mixed lengths through 3 slots: every request's
        output equals its solo greedy generate — including requests
        admitted into a REUSED slot whose cache still holds the previous
        occupant's stale K/V beyond the frontier."""
        rng = np.random.RandomState(0)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 3, 7, 4, 6, 3)]
        batcher = ContinuousBatcher(params, CFG, batch=3, max_len=32,
                                    chunk=4)
        outs = batcher.serve(prompts, max_new_tokens=6)
        for i, p in enumerate(prompts):
            assert outs[i] == _reference(params, p, 6), f"request {i}"

    def test_quantized_cache_token_identical_to_quant_generate(self,
                                                               params):
        """int8 KV serving: the batcher with a quantized cache equals
        per-request generate under the SAME quantized config (quant-to-
        quant is deterministic — per-row math is batch-independent on
        CPU; quant-to-float agreement is approximate by design). Slot
        reuse included."""
        qcfg = CFG.scaled(kv_cache_dtype="int8")
        rng = np.random.RandomState(7)
        prompts = [list(rng.randint(0, qcfg.vocab_size, size=n))
                   for n in (5, 3, 6, 4)]
        batcher = ContinuousBatcher(params, qcfg, batch=2, max_len=32,
                                    chunk=4)
        outs = batcher.serve(prompts, max_new_tokens=6)
        for i, p in enumerate(prompts):
            want = generate(params, jnp.asarray(p, jnp.int32)[None],
                            qcfg, max_new_tokens=6,
                            rng=jax.random.PRNGKey(0), temperature=0.0)
            assert outs[i] == [int(t) for t in
                               np.asarray(want.tokens[0, len(p):])], \
                f"request {i}"

    def test_single_slot_serializes_correctly(self, params):
        """batch=1 degenerates to sequential serving — same outputs."""
        rng = np.random.RandomState(1)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (4, 6)]
        batcher = ContinuousBatcher(params, CFG, batch=1, max_len=32,
                                    chunk=3)
        outs = batcher.serve(prompts, max_new_tokens=5)
        for i, p in enumerate(prompts):
            assert outs[i] == _reference(params, p, 5)

    def test_eos_stops_a_row_early(self, params):
        """A request whose greedy chain hits eos stops there (eos token
        included), freeing the slot; others run to their budget."""
        rng = np.random.RandomState(2)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 4)]
        ref0 = _reference(params, prompts[0], 6)
        eos = ref0[2]            # third generated token of request 0
        batcher = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                                    eos_id=eos, chunk=2)
        outs = batcher.serve(prompts, max_new_tokens=6)
        assert outs[0] == ref0[:3]          # stopped AT the eos token
        ref1 = _reference(params, prompts[1], 6)
        cut = (ref1.index(eos) + 1) if eos in ref1 else 6
        assert outs[1] == ref1[:cut]

    def test_prompt_too_long_rejected(self, params):
        batcher = ContinuousBatcher(params, CFG, batch=1, max_len=16)
        with pytest.raises(ValueError, match="exceeds max_len"):
            batcher.serve([[1] * 14], max_new_tokens=8)

    def test_per_request_budgets(self, params):
        """Mixed generation budgets (the case continuous batching exists
        for): each request stops at ITS budget and slots recycle."""
        rng = np.random.RandomState(3)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=4))
                   for _ in range(4)]
        budgets = [2, 7, 3, 5]
        batcher = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                                    chunk=3)
        outs = batcher.serve(prompts, budgets)
        for i, (p, b) in enumerate(zip(prompts, budgets)):
            assert outs[i] == _reference(params, p, b), f"request {i}"
        assert batcher.steps_executed >= max(budgets)

    def test_idle_slots_do_not_march(self, params, monkeypatch):
        """Queue drained with a straggler still running: freed slots are
        reset EVERY chunk (not just once), so an idle slot's garbage
        frontier cannot walk toward the cache end. Asserted on the
        retire masks themselves (a final-state length check is vacuous
        — serve()'s last iteration resets all rows anyway)."""
        import tony_tpu.models.serve as S
        masks = []
        orig = S.retire_rows

        def spy(cache, mask):
            masks.append(np.asarray(mask))
            return orig(cache, mask)

        monkeypatch.setattr(S, "retire_rows", spy)
        rng = np.random.RandomState(4)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=4))
                   for _ in range(3)]
        batcher = ContinuousBatcher(params, CFG, batch=3, max_len=32,
                                    chunk=2)
        outs = batcher.serve(prompts, [2, 2, 12])
        for i, (p, b) in enumerate(zip(prompts, [2, 2, 12])):
            assert outs[i] == _reference(params, p, b)
        # rows 0 and 1 free after ~1 chunk; the straggler needs ~6 — the
        # idle rows must be re-reset on EVERY subsequent chunk
        both_idle = [m for m in masks if m[0] and m[1]]
        assert len(both_idle) >= 3, [list(m) for m in masks]

    def test_invalid_request_rejected_before_serving(self, params):
        """A bad request ANYWHERE in the list fails up front — no partial
        serve that would discard completed outputs mid-flight."""
        batcher = ContinuousBatcher(params, CFG, batch=1, max_len=16)
        with pytest.raises(ValueError, match="request 1"):
            batcher.serve([[1, 2], [1] * 14], max_new_tokens=8)
        with pytest.raises(ValueError, match="must be positive"):
            batcher.serve([[1, 2]], max_new_tokens=0)
        with pytest.raises(ValueError, match="empty prompt"):
            batcher.serve([[1, 2], []], max_new_tokens=4)


class TestSharedPrefix:
    """Shared-prefix caching: the prefix prefills once into a K/V
    template; admission copies it and runs only the request's suffix."""

    def _refs(self, params, prefix, suffixes, budgets):
        out = []
        for sfx, b in zip(suffixes, budgets):
            full = jnp.asarray(prefix + sfx, jnp.int32)[None]
            g = generate(params, full, CFG, max_new_tokens=b,
                         rng=jax.random.PRNGKey(0), temperature=0.0)
            out.append([int(t) for t in
                        np.asarray(g.tokens[0, full.shape[1]:])])
        return out

    def test_greedy_prefix_serving_token_identical(self, params):
        """Serving suffixes against a shared prefix equals per-request
        greedy decode of prefix+suffix — including slot reuse, where a
        new occupant's template copy overwrites the previous request's
        K/V."""
        rs = np.random.RandomState(7)
        prefix = [int(t) for t in rs.randint(0, CFG.vocab_size, size=9)]
        suffixes = [list(rs.randint(0, CFG.vocab_size,
                                    size=rs.randint(2, 6)))
                    for _ in range(5)]
        budgets = [int(b) for b in rs.randint(4, 9, size=5)]
        batcher = ContinuousBatcher(params, CFG, batch=2, max_len=48,
                                    chunk=3, shared_prefix=prefix)
        outs = batcher.serve(suffixes, budgets)
        assert outs == self._refs(params, prefix, suffixes, budgets)

    def test_speculative_prefix_serving_token_identical(self, params):
        """The speculative batcher's prefix admission fills BOTH models'
        caches from their own templates; greedy rounds stay token-exact."""
        draft = T.init_params(jax.random.PRNGKey(99), CFG)
        rs = np.random.RandomState(8)
        prefix = [int(t) for t in rs.randint(0, CFG.vocab_size, size=7)]
        suffixes = [list(rs.randint(0, CFG.vocab_size, size=3))
                    for _ in range(4)]
        budgets = [5, 7, 4, 6]
        batcher = SpeculativeContinuousBatcher(
            params, CFG, draft, CFG, batch=2, max_len=48,
            num_speculative=3, chunk=2, shared_prefix=prefix)
        outs = batcher.serve(suffixes, budgets)
        assert outs == self._refs(params, prefix, suffixes, budgets)

    def test_prefix_budget_validation(self, params):
        batcher = ContinuousBatcher(params, CFG, batch=1, max_len=16,
                                    shared_prefix=[1, 2, 3, 4])
        with pytest.raises(ValueError, match="shared prefix 4"):
            batcher.serve([[5] * 6], max_new_tokens=8)
        with pytest.raises(ValueError, match="non-empty"):
            ContinuousBatcher(params, CFG, batch=1, max_len=16,
                              shared_prefix=[])


class TestSampledServing:
    """temperature/top_k/top_p on the continuous batcher: valid tokens,
    seed-reproducible workloads, seed-sensitive outputs."""

    def test_sampled_serve_reproducible_by_seed(self, params):
        rng = np.random.RandomState(5)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=4))
                   for _ in range(5)]

        def run(seed):
            b = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                                  chunk=3, temperature=0.8, top_k=50,
                                  top_p=0.9, seed=seed)
            return b.serve(prompts, max_new_tokens=6)

        outs = run(0)
        for o in outs:
            assert len(o) == 6
            assert all(0 <= t < CFG.vocab_size for t in o)
        assert outs == run(0)          # same seed, same workload
        assert outs != run(1)          # overwhelmingly likely

    def test_greedy_default_unchanged_by_seed(self, params):
        rng = np.random.RandomState(6)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=4))
                   for _ in range(3)]
        a = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                              chunk=3, seed=0).serve(prompts, 5)
        b = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                              chunk=3, seed=7).serve(prompts, 5)
        assert a == b
        for i, p in enumerate(prompts):
            assert a[i] == _reference(params, p, 5)


class TestSpeculativeContinuousBatching:
    """Continuous batching composed with speculative decoding: every
    slot runs draft-propose/target-verify rounds at its own frontier
    and commits its own acceptance; slot reuse/retirement identical to
    the greedy batcher."""

    def test_token_identical_with_slot_reuse(self, params):
        """7 mixed-length requests with mixed budgets through 3 slots,
        self-draft and rejecting draft: every request equals its solo
        greedy generate, and the self-draft (full acceptance) finishes
        in strictly fewer speculative rounds."""
        draft = T.init_params(jax.random.PRNGKey(99), CFG)
        rng = np.random.RandomState(3)
        prompts = [list(rng.randint(0, CFG.vocab_size,
                                    size=rng.randint(3, 9)))
                   for _ in range(7)]
        budgets = [int(b) for b in rng.randint(4, 14, size=7)]
        rounds = {}
        for d, name in ((params, "self"), (draft, "rej")):
            batcher = SpeculativeContinuousBatcher(
                params, CFG, d, CFG, batch=3, max_len=64,
                num_speculative=3, chunk=2)
            outs = batcher.serve(prompts, budgets)
            for i, (p, b) in enumerate(zip(prompts, budgets)):
                assert outs[i] == _reference(params, p, b), (name, i)
            rounds[name] = batcher.rounds_executed
        assert rounds["self"] < rounds["rej"]

    def test_eos_frees_slot_early(self, params):
        """A request hitting eos mid-speculative-chunk stops there (eos
        included, surplus committed tokens discarded) and its slot is
        recycled."""
        rng = np.random.RandomState(2)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 4, 6)]
        ref0 = _reference(params, prompts[0], 8)
        eos = ref0[2]
        batcher = SpeculativeContinuousBatcher(
            params, CFG, params, CFG, batch=2, max_len=64,
            num_speculative=4, eos_id=eos, chunk=2)
        outs = batcher.serve(prompts, max_new_tokens=8)
        assert outs[0] == ref0[:3]
        for i in (1, 2):
            ref = _reference(params, prompts[i], 8)
            cut = (ref.index(eos) + 1) if eos in ref else 8
            assert outs[i] == ref[:cut]

    def test_bad_num_speculative_rejected(self, params):
        with pytest.raises(ValueError, match="num_speculative"):
            SpeculativeContinuousBatcher(params, CFG, params, CFG,
                                         batch=2, max_len=32,
                                         num_speculative=0)

    def test_vocab_mismatch_rejected(self, params):
        """A draft with a different vocabulary is silent corruption in
        greedy mode and a shape error in sampled mode — rejected up
        front, at the batcher AND at the generate-path entry points."""
        from tony_tpu.models.decode import (speculative_generate,
                                            speculative_generate_device)

        bad_cfg = CFG.scaled(vocab_size=CFG.vocab_size // 2)
        bad = T.init_params(jax.random.PRNGKey(1), bad_cfg)
        with pytest.raises(ValueError, match="vocab"):
            SpeculativeContinuousBatcher(params, CFG, bad, bad_cfg,
                                         batch=2, max_len=32)
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        with pytest.raises(ValueError, match="vocab"):
            speculative_generate_device(params, bad, prompt, CFG, bad_cfg,
                                        max_new_tokens=4,
                                        num_speculative=2)
        with pytest.raises(ValueError, match="vocab"):
            speculative_generate(params, bad, prompt, CFG, bad_cfg,
                                 max_new_tokens=4, num_speculative=2)

    @pytest.mark.slow
    def test_sampled_speculative_serving_matches_target_distribution(self):
        """Sampled speculative serving (rejection-sampling rounds inside
        the continuous batcher): each served request's tokens are
        distributed as direct target sampling, for a MISMATCHED draft —
        measured on the 2-token joint over many served requests, with a
        draft-only baseline proving the tolerance discriminates. Also
        pins seed-reproducibility of a whole served workload."""
        from tony_tpu.models.decode import generate as gen

        cfg = T.TransformerConfig(vocab_size=11, d_model=24, n_layers=2,
                                  n_heads=2, d_ff=48, max_seq=1024,
                                  dtype=jnp.float32,
                                  logits_dtype=jnp.float32, remat=False)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        draft = T.init_params(jax.random.PRNGKey(99), cfg)
        prompt = [3, 7, 1, 5]
        n_req, n = 192, 2

        def joint_serve(seed):
            b = SpeculativeContinuousBatcher(
                params, cfg, draft, cfg, batch=48, max_len=32,
                num_speculative=3, chunk=1, temperature=1.1, top_k=6,
                seed=seed)
            outs = b.serve([prompt] * n_req, n)
            c = np.zeros((cfg.vocab_size, cfg.vocab_size))
            for o in outs:
                c[o[0], o[1]] += 1
            return c

        counts = sum(joint_serve(s) for s in range(8))
        spec_p = counts / counts.sum()

        pm = jnp.asarray([prompt], jnp.int32).repeat(n_req, 0)

        def joint_gen(model, seed0):
            c = np.zeros((cfg.vocab_size, cfg.vocab_size))
            for i in range(8):
                a = np.asarray(gen(model, pm, cfg, max_new_tokens=n,
                                   rng=jax.random.PRNGKey(seed0 + i),
                                   temperature=1.1,
                                   top_k=6).tokens[:, -n:])
                for r in a:
                    c[r[0], r[1]] += 1
            return c / c.sum()

        ref_p = joint_gen(params, 40)
        ref2_p = joint_gen(params, 400)      # independent same-dist run
        draft_p = joint_gen(draft, 80)
        tv_spec = 0.5 * np.abs(spec_p - ref_p).sum()
        tv_noise = 0.5 * np.abs(ref2_p - ref_p).sum()
        tv_draft = 0.5 * np.abs(draft_p - ref_p).sum()
        # self-calibrated: within ~2x of same-distribution sampling
        # noise at this sample count (and far under the draft's gap)
        assert tv_spec < max(0.1, 2.0 * tv_noise), (tv_spec, tv_noise)
        assert tv_draft > 0.3, tv_draft

        # whole-workload reproducibility by seed
        b1 = SpeculativeContinuousBatcher(
            params, cfg, draft, cfg, batch=3, max_len=32,
            num_speculative=3, chunk=2, temperature=1.1, top_k=6, seed=7)
        o1 = b1.serve([prompt] * 5, 6)
        b2 = SpeculativeContinuousBatcher(
            params, cfg, draft, cfg, batch=3, max_len=32,
            num_speculative=3, chunk=2, temperature=1.1, top_k=6, seed=7)
        assert o1 == b2.serve([prompt] * 5, 6)

    def test_distinct_draft_config(self, params):
        """The draft may have a different architecture (the production
        shape: a much smaller model) — caches sized per-config."""
        dcfg = CFG.scaled(n_layers=1, d_model=32, n_heads=2, d_ff=64)
        draft = T.init_params(jax.random.PRNGKey(5), dcfg)
        rng = np.random.RandomState(7)
        prompts = [list(rng.randint(0, CFG.vocab_size, size=5))
                   for _ in range(4)]
        batcher = SpeculativeContinuousBatcher(
            params, CFG, draft, dcfg, batch=2, max_len=48,
            num_speculative=3, chunk=3)
        outs = batcher.serve(prompts, max_new_tokens=7)
        for i, p in enumerate(prompts):
            assert outs[i] == _reference(params, p, 7), f"request {i}"
