"""Docker-passthrough command construction (reference: TonyClient.java:
340-349 enables the YARN docker runtime; here the coordinator wraps the
executor command itself)."""

import shlex

import pytest

from tony_tpu.conf.config import TonyConfig
from tony_tpu.utils.docker import docker_wrap


def test_disabled_returns_command_unchanged():
    conf = TonyConfig({"tony.docker.enabled": "false"})
    assert docker_wrap("python x.py", conf, "/jobs/a") == "python x.py"


def test_enabled_wraps_with_mount_env_and_image():
    conf = TonyConfig({"tony.docker.enabled": "true",
                       "tony.docker.image": "ghcr.io/org/train:1.2"})
    cmd = docker_wrap("python -m tony_tpu.cluster.executor --am_address h:1",
                      conf, "/jobs/app_1",
                      env_keys=("JOB_NAME", "TASK_INDEX"),
                      task_id="worker:0", app_id="app_1")
    # Kill semantics: a TERM/INT trap docker-kills the named container
    # (backend kills signal the docker CLIENT, which alone would orphan it).
    trap_part, _, run_part = cmd.partition("; ")
    assert trap_part.startswith("trap ")
    assert "docker kill tony-app_1-worker-0" in trap_part
    assert run_part.endswith("& wait $!")
    argv = shlex.split(run_part[:-len("& wait $!")])
    assert argv[:2] == ["docker", "run"]
    assert "--network=host" in argv
    assert argv[argv.index("--name") + 1] == "tony-app_1-worker-0"
    assert "/jobs/app_1:/jobs/app_1" in argv
    assert "ghcr.io/org/train:1.2" in argv
    # env forwarded from the client process environment
    assert argv[argv.index("-e") + 1] == "JOB_NAME"
    assert "TASK_INDEX" in argv
    # the executor command survives quoting intact
    assert argv[-1] == "python -m tony_tpu.cluster.executor --am_address h:1"
    assert argv[-2] == "-c" and argv[-3] == "bash"


def test_enabled_without_image_raises():
    conf = TonyConfig({"tony.docker.enabled": "true"})
    with pytest.raises(ValueError, match="tony.docker.image"):
        docker_wrap("true", conf, "/jobs/a")


def test_coordinator_executor_command_honors_python_opts(tmp_path):
    """tony.task.executor.python-opts lands between the interpreter and -m
    (the jvm-opts analog, reference: TonySession.getTaskCommand:72)."""
    from tony_tpu.conf import keys as K
    from tony_tpu.cluster.coordinator import Coordinator

    conf = TonyConfig({K.TASK_EXECUTOR_PYTHON_OPTS_KEY: "-O -u",
                       "tony.worker.instances": "1"})
    co = Coordinator(conf, "app_test", str(tmp_path))
    try:
        cmd = co._executor_command("python train.py")
        assert " -O -u -m tony_tpu.cluster.executor " in cmd
    finally:
        co.rpc_server.stop()
