"""End-to-end metrics plane: a real local-backend job ships per-task
registry snapshots over heartbeats, the coordinator folds them into
METRICS_SNAPSHOT jhist events, and the history server exports them —
live Prometheus text while the job RUNS, JSON replay after it finishes
(the acceptance path of the metrics-plane issue)."""

import json
import os
import sys
import threading
import time
import urllib.request

import pytest

from tony_tpu.client.client import TonyClient
from tony_tpu.conf.config import TonyConfig
from tony_tpu.events import events as ev
from tony_tpu.history.server import HistoryServer
from tony_tpu.runtime import metrics as M

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
PY = sys.executable


def _get(port, path):
    with urllib.request.urlopen(
            f"http://localhost:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode("utf-8")


def _latest_snapshot_from_jhist(hist_dir):
    """(path, last METRICS_SNAPSHOT event) across every jhist/inprogress
    file under hist_dir, or (None, None)."""
    for path in sorted(ev.find_job_files(hist_dir), reverse=True):
        events = ev.parse_events(path)
        snaps = [e for e in events
                 if e.event_type == ev.METRICS_SNAPSHOT]
        if snaps:
            return path, snaps[-1]
    return None, None


@pytest.mark.e2e
def test_metrics_plane_end_to_end(tmp_path):
    hist = str(tmp_path / "tony-history")
    conf = TonyConfig({
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.location": hist,
        "tony.application.timeout": "60000",
        "tony.worker.instances": "1",
        "tony.task.heartbeat-interval-ms": "100",
        "tony.metrics.snapshot-interval-ms": "300",
    })
    client = TonyClient(
        conf, f"{PY} {os.path.join(FIXTURES, 'sleep_briefly.py')} 4")
    result = {}
    t = threading.Thread(target=lambda: result.update(code=client.run()))
    t.start()
    server = None
    try:
        # wait until the coordinator's .inprogress stream carries a
        # snapshot with the worker's heartbeat-shipped gauges
        intermediate = os.path.join(hist, "intermediate")
        deadline = time.monotonic() + 45
        snap = None
        while time.monotonic() < deadline and t.is_alive():
            if os.path.isdir(intermediate):
                _, snap = _latest_snapshot_from_jhist(intermediate)
                if snap and "worker:0" in snap.payload.get("tasks", {}):
                    break
                snap = None
            time.sleep(0.1)
        assert snap is not None, "no METRICS_SNAPSHOT with worker:0 " \
                                 "appeared while the job ran"

        # LIVE export: /metrics renders the running job's per-task series
        server = HistoryServer(TonyConfig({
            "tony.history.location": hist}), port=0)
        server.start()
        status, text = _get(server.port, "/metrics")
        assert status == 200
        app_id = client.app_id
        assert (f'tony_process_rss_bytes{{job="{app_id}",'
                f'task="worker:0"}}' in text)
        assert (f'tony_executor_uptime_seconds{{job="{app_id}",'
                f'task="worker:0"}}' in text)
        assert "# TYPE tony_process_rss_bytes gauge" in text
        assert 'tony_history_jobs{state="running"} 1' in text
        # valid exposition: numeric samples, no duplicate series
        samples = [ln for ln in text.splitlines()
                   if ln.strip() and not ln.startswith("#")]
        for ln in samples:
            float(ln.rpartition(" ")[2])
        keys = [ln.rpartition(" ")[0] for ln in samples]
        assert len(set(keys)) == len(keys)
    finally:
        t.join(timeout=90)
        if server is not None:
            server.stop()
    assert result.get("code") == 0

    # REPLAY: after the job finished, a fresh server reconstructs the
    # same series purely from METRICS_SNAPSHOT events in the jhist.
    jhist_path, final_snap = _latest_snapshot_from_jhist(hist)
    assert jhist_path is not None and jhist_path.endswith(".jhist")
    server2 = HistoryServer(TonyConfig({
        "tony.history.location": hist}), port=0)
    server2.start()
    try:
        status, body = _get(server2.port, f"/api/jobs/{client.app_id}/metrics")
        assert status == 200
        m = json.loads(body)
        assert m["snapshot_count"] >= 1
        # identical to what the jhist holds — the replay IS the jhist
        assert m["tasks"] == final_snap.payload["tasks"]
        worker = m["tasks"]["worker:0"]
        M.validate_wire(worker)
        gauges = {name: value for name, _, value in worker["g"]}
        assert gauges["tony_process_rss_bytes"] > 1 << 20
        assert gauges["tony_executor_uptime_seconds"] > 0
        assert "tony_process_cpu_seconds" in gauges
        # the executor's final beat shipped the child exit-code counter
        counters = {(name, tuple(sorted(labels.items()))): value
                    for name, labels, value in worker["c"]}
        assert counters[("tony_executor_child_exits_total",
                         (("code", "0"),))] == 1
        # the coordinator's own registry rode along as pseudo-task am:0
        assert "am:0" in m["tasks"]
        # finished job: no live series on /metrics anymore
        _, text = _get(server2.port, "/metrics")
        assert 'task="worker:0"' not in text
        assert 'tony_history_jobs{state="finished"} 1' in text
    finally:
        server2.stop()


def test_heartbeater_without_provider_sends_old_style(monkeypatch):
    """A Heartbeater with no snapshot provider (the pre-metrics shape)
    sends metrics-less beats — and a provider that RAISES costs a beat
    nothing (the snapshot collapses to \"\" instead of failing the
    ping). Liveness never depends on the piggyback."""
    from tony_tpu.cluster.executor import Heartbeater

    class FakeRpc:
        def __init__(self):
            self.calls = []

        def task_executor_heartbeat(self, task_id, metrics=""):
            self.calls.append((task_id, metrics))
            return ""

    rpc = FakeRpc()
    hb = Heartbeater(rpc, "worker:0", interval_s=0.01)
    assert hb._snapshot() == ""
    hb.snapshot_fn = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    assert hb._snapshot() == ""               # provider error → plain beat
    hb.snapshot_fn = lambda: '{"c":[],"g":[],"h":[],"m":{}}'
    assert hb._snapshot() == '{"c":[],"g":[],"h":[],"m":{}}'
