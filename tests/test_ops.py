"""Fused-op kernels vs their dense-jnp oracles (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.ops import (flash_attention, layer_norm, layer_norm_reference,
                          reference_attention, rms_norm, rms_norm_reference)
from tony_tpu.ops.attention import flash_attention_with_lse


def dense_o_lse(q, k, v, causal=True):
    """Dense (o, lse) oracle for the with-lse entry point."""
    import jax.numpy as jnp
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (d ** -0.5)
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    lse = jax.scipy.special.logsumexp(s, axis=-1)        # [B, H, Sq]
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, lse


@pytest.fixture(scope="module")
def qkv():
    r = np.random.RandomState(0)
    shape = (2, 64, 2, 32)   # small: interpret mode is slow
    return tuple(jnp.asarray(r.randn(*shape), jnp.float32) for _ in range(3))


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_dense(self, qkv, causal):
        q, k, v = qkv
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(o, ref, atol=2e-5)

    def test_gradients_match_dense(self, qkv):
        q, k, v = qkv
        g = jax.grad(lambda *a: flash_attention(
            *a, block_q=32, block_k=32).sum(), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: reference_attention(*a).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(got, want, atol=5e-5)

    def test_gradients_two_pass_long_seq(self, monkeypatch):
        # Force the partial-memory budget to zero so the two-pass dq/dkv
        # kernels (the huge-sequence fallback) stay covered.
        import tony_tpu.ops.attention as A
        monkeypatch.setattr(A, "_FUSED_PARTIALS_BYTES", 0)
        r = np.random.RandomState(2)
        q, k, v = (jnp.asarray(r.randn(1, 256, 2, 32), jnp.float32)
                   for _ in range(3))
        g = jax.grad(lambda *a: flash_attention(
            *a, block_q=32, block_k=32).sum(), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: reference_attention(*a).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(got, want, atol=5e-5)

    def test_gradients_bfloat16_within_tolerance(self, qkv):
        # the fused backward stores per-q-block dK/dV partials at input
        # precision (see _flash_backward_fused) — bf16 grads must stay
        # within bf16 rounding of the f32 dense oracle
        q, k, v = (x.astype(jnp.bfloat16) for x in qkv)
        g = jax.grad(lambda *a: flash_attention(
            *a, block_q=32, block_k=32).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: reference_attention(*a).sum(),
                      argnums=(0, 1, 2))(*qkv)
        for got, want in zip(g, gr):
            scale = float(jnp.abs(want).max())
            np.testing.assert_allclose(got.astype(jnp.float32), want,
                                       atol=0.02 * scale)

    def test_gradients_bfloat16_long_seq(self):
        # many fused dK/dV partials (nq = 16): the per-partial bf16
        # rounding must stay within the documented √nq·eps bound
        r = np.random.RandomState(3)
        q, k, v = (jnp.asarray(r.randn(1, 512, 2, 32), jnp.float32)
                   for _ in range(3))
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        g = jax.grad(lambda *a: flash_attention(
            *a, block_q=32, block_k=32).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(qb, kb, vb)
        gr = jax.grad(lambda *a: reference_attention(*a).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            scale = float(jnp.abs(want).max())
            np.testing.assert_allclose(got.astype(jnp.float32), want,
                                       atol=0.02 * scale)

    def test_unpadded_head_count(self, qkv):
        # batch·heads = 4 (not a multiple of 8): exercises the zero-head
        # padding path
        q, k, v = (x[:1] for x in qkv)   # [1, 64, 2, 32] → bh = 2
        o = flash_attention(q, k, v, block_q=32, block_k=32)
        np.testing.assert_allclose(
            o, reference_attention(q, k, v), atol=2e-5)
        g = jax.grad(lambda *a: flash_attention(
            *a, block_q=32, block_k=32).sum(), argnums=(0,))(q, k, v)
        gr = jax.grad(lambda *a: reference_attention(*a).sum(),
                      argnums=(0,))(q, k, v)
        np.testing.assert_allclose(g[0], gr[0], atol=5e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_with_lse_matches_dense(self, qkv, causal):
        q, k, v = qkv
        o, lse = flash_attention_with_lse(q, k, v, causal=causal,
                                          block_q=32, block_k=32)
        oref, lref = dense_o_lse(q, k, v, causal=causal)
        np.testing.assert_allclose(o, oref, atol=2e-5)
        np.testing.assert_allclose(lse, lref, atol=2e-5)

    def test_with_lse_gradients_include_dlse(self, qkv):
        # mixed loss touching BOTH outputs: d(lse) must flow through the
        # kernels' delta adjustment, not be silently dropped
        q, k, v = qkv

        def loss(f):
            def fn(q, k, v):
                o, lse = f(q, k, v)
                return (o ** 2).sum() + (jnp.sin(lse) * 1.7).sum()
            return fn
        g = jax.grad(loss(lambda *a: flash_attention_with_lse(
            *a, block_q=32, block_k=32)), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(dense_o_lse), argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(got, want, atol=5e-5)

    def test_with_lse_gradients_two_pass(self, monkeypatch):
        import tony_tpu.ops.attention as A
        monkeypatch.setattr(A, "_FUSED_PARTIALS_BYTES", 0)
        r = np.random.RandomState(5)
        q, k, v = (jnp.asarray(r.randn(1, 128, 2, 32), jnp.float32)
                   for _ in range(3))

        def loss(f):
            def fn(q, k, v):
                o, lse = f(q, k, v)
                return (o ** 2).sum() + (jnp.cos(lse) * 0.9).sum()
            return fn
        g = jax.grad(loss(lambda *a: flash_attention_with_lse(
            *a, block_q=32, block_k=32)), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(dense_o_lse), argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(got, want, atol=5e-5)

    def test_block_clamping_to_short_seq(self, qkv):
        q, k, v = qkv      # seq 64 < default blocks: must clamp, not raise
        o = flash_attention(q, k, v)
        np.testing.assert_allclose(o, reference_attention(q, k, v), atol=2e-5)

    def test_indivisible_seq_raises(self):
        q = jnp.zeros((1, 65, 2, 32))
        with pytest.raises(ValueError):
            flash_attention(q, q, q, block_q=32, block_k=32)


class TestFlashAttentionGQA:
    """GQA-native kernels: K/V with fewer heads than Q, consumed
    unexpanded (rep-band query layout + band-relative causal mask)."""

    @pytest.fixture(scope="class")
    def gqa(self):
        r = np.random.RandomState(5)
        q = jnp.asarray(r.randn(2, 64, 4, 32), jnp.float32)
        k = jnp.asarray(r.randn(2, 64, 2, 32), jnp.float32)
        v = jnp.asarray(r.randn(2, 64, 2, 32), jnp.float32)
        return q, k, v

    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_reference(self, gqa, causal):
        q, k, v = gqa
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(o, ref, atol=2e-5)

    def test_multiblock_band_mask(self, gqa):
        # several q-blocks per band: the band-relative causal mask must
        # reset at each replica band boundary
        q, k, v = gqa
        o = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(o, ref, atol=2e-5)

    def test_gradients_match_reference(self, gqa):
        q, k, v = gqa

        def f_flash(q, k, v):
            return flash_attention(q, k, v, causal=True, block_q=32,
                                   block_k=32).sum()

        def f_ref(q, k, v):
            return reference_attention(q, k, v, causal=True).sum()

        got = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", got, want):
            assert a.shape == b.shape        # dK/dV stay kv_heads-wide
            np.testing.assert_allclose(a, b, atol=3e-5,
                                       err_msg=f"d{name}")

    def test_gradients_two_pass(self, gqa, monkeypatch):
        from tony_tpu.ops import attention as A
        monkeypatch.setattr(A, "_FUSED_PARTIALS_BYTES", 0)
        q, k, v = gqa
        got = jax.grad(lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32).sum(),
            argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(lambda q, k, v: reference_attention(
            q, k, v, causal=True).sum(), argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", got, want):
            np.testing.assert_allclose(a, b, atol=3e-5, err_msg=f"d{name}")

    @pytest.mark.slow
    def test_full_tile_shapes_hit_kernel(self):
        """seq 256 / block 128: shapes that clear the _sub_tile guard, so
        this case exercises the GQA Pallas kernels on REAL TPU hardware
        too (the small-seq cases fall back to the dense arm there)."""
        from tony_tpu.ops import attention as A
        r = np.random.RandomState(9)
        q = jnp.asarray(r.randn(1, 256, 4, 32), jnp.float32)
        k = jnp.asarray(r.randn(1, 256, 2, 32), jnp.float32)
        v = jnp.asarray(r.randn(1, 256, 2, 32), jnp.float32)
        assert not A._sub_tile(q, 128)
        o = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
        np.testing.assert_allclose(
            o, reference_attention(q, k, v, causal=True), atol=2e-5)
        got = jax.grad(lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=128, block_k=128).sum(),
            argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(lambda q, k, v: reference_attention(
            q, k, v, causal=True).sum(), argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", got, want):
            np.testing.assert_allclose(a, b, atol=5e-5, err_msg=f"d{name}")

    def test_with_lse_matches_dense(self, gqa):
        q, k, v = gqa
        o, lse = flash_attention_with_lse(q, k, v, causal=True,
                                          block_q=32, block_k=32)
        kr = jnp.repeat(k, 2, axis=2)
        vr = jnp.repeat(v, 2, axis=2)
        ref_o, ref_lse = dense_o_lse(q, kr, vr, causal=True)
        np.testing.assert_allclose(o, ref_o, atol=2e-5)
        np.testing.assert_allclose(lse, ref_lse, atol=2e-5)

    def test_indivisible_heads_raises(self, gqa):
        q, k, v = gqa
        with pytest.raises(ValueError, match="divide"):
            flash_attention(q, k[:, :, :1].repeat(3, 2)[:, :, :3], v,
                            causal=True)


class TestNorms:
    @pytest.fixture(scope="class")
    def data(self):
        r = np.random.RandomState(1)
        x = jnp.asarray(r.randn(4, 16, 64), jnp.float32)
        w = jnp.asarray(1.0 + 0.1 * r.randn(64), jnp.float32)
        b = jnp.asarray(0.1 * r.randn(64), jnp.float32)
        return x, w, b

    def test_rms_forward(self, data):
        x, w, _ = data
        np.testing.assert_allclose(rms_norm(x, w), rms_norm_reference(x, w),
                                   atol=1e-6)

    def test_rms_gradients(self, data):
        x, w, _ = data
        loss = lambda f: lambda x, w: (f(x, w) ** 2).sum()
        g = jax.grad(loss(rms_norm), argnums=(0, 1))(x, w)
        gr = jax.grad(loss(rms_norm_reference), argnums=(0, 1))(x, w)
        np.testing.assert_allclose(g[0], gr[0], atol=1e-5)
        np.testing.assert_allclose(g[1], gr[1], atol=1e-4)

    def test_layer_norm_forward(self, data):
        x, w, b = data
        np.testing.assert_allclose(layer_norm(x, w, b),
                                   layer_norm_reference(x, w, b), atol=1e-6)

    def test_layer_norm_gradients(self, data):
        x, w, b = data
        loss = lambda f: lambda x, w, b: (f(x, w, b) ** 2).sum()
        g = jax.grad(loss(layer_norm), argnums=(0, 1, 2))(x, w, b)
        gr = jax.grad(loss(layer_norm_reference), argnums=(0, 1, 2))(x, w, b)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(got, want, atol=1e-4)

    def test_bfloat16_path(self, data):
        x, w, _ = data
        xb, wb = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
        out = rms_norm(xb, wb)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            out.astype(jnp.float32), rms_norm_reference(x, w), atol=0.05)


class TestFusedAdamW:
    """ops/optim.py vs the optax chain it replaces (interpret mode)."""

    def _setup(self):
        import optax
        from tony_tpu.ops.optim import FusedAdamW
        r = np.random.RandomState(3)
        # "big" and "proj" clear the >=65536-element kernel gate (2-D and
        # 3-D native-tile views respectively); the small/odd leaves
        # exercise the XLA fallback — BOTH paths feed the parity check
        params = {"big": jnp.asarray(r.randn(512, 128) * 0.1, jnp.float32),
                  "proj": jnp.asarray(r.randn(520, 8, 64) * 0.1,
                                      jnp.float32),
                  "w": jnp.asarray(r.randn(4, 128) * 0.1, jnp.float32),
                  "norm": jnp.asarray(np.ones(256), jnp.float32),
                  "odd": jnp.asarray(r.randn(5) * 0.1, jnp.float32)}
        from tony_tpu.ops import optim as _optim
        assert _optim._view_rows(params["big"].shape)[2] % 8 == 0
        assert _optim._leaf_view(params["proj"].shape) == (-1, 8, 64)
        sched = optax.warmup_cosine_decay_schedule(0.0, 1e-2, 3, 20)
        fused = FusedAdamW(sched, weight_decay=0.01, clip_norm=1.0)
        chain = optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.adamw(sched, weight_decay=0.01, mu_dtype=jnp.float32))
        return params, fused, chain, r

    def test_matches_optax_chain(self):
        import optax
        params, fused, chain, r = self._setup()
        fstate = fused.init(params)
        ostate = chain.init(params)
        fp = op = params
        apply_f = jax.jit(fused.fused_apply)
        for i in range(6):
            scale = 40.0 if i == 2 else 0.3   # step 2 triggers the clip
            grads = jax.tree.map(
                lambda p: jnp.asarray(
                    r.randn(*p.shape) * scale, jnp.float32), fp)
            fp, fstate, f_gnorm = apply_f(grads, fstate, fp)
            updates, ostate = chain.update(grads, ostate, op)
            op = optax.apply_updates(op, updates)
            o_gnorm = optax.global_norm(grads)
            np.testing.assert_allclose(float(f_gnorm), float(o_gnorm),
                                       rtol=1e-5)
            for (ka, a), (kb, b) in zip(
                    sorted(fp.items()), sorted(op.items())):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=2e-6,
                    err_msg=f"step {i} leaf {ka}")

    def test_train_step_protocol(self):
        """make_train_step consumes the fused_apply protocol end to end
        and the loss goes down."""
        from tony_tpu.models import transformer as T
        from tony_tpu.models.train import init_state, make_train_step
        from tony_tpu.ops.optim import FusedAdamW
        cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32, n_layers=1,
                                       d_model=128, n_heads=2, d_ff=256)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        opt = FusedAdamW(1e-2, weight_decay=0.0)
        state = init_state(params, opt)
        step = make_train_step(lambda p, b: T.lm_loss(p, b, cfg), opt)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                  cfg.vocab_size)
        batch = {"inputs": toks[:, :32], "targets": toks[:, 1:]}
        state, m0 = step(state, batch)
        for _ in range(4):
            state, m = step(state, batch)
        assert float(m["loss"]) < float(m0["loss"])
        assert bool(jnp.isfinite(m["grad_norm"]))
        assert int(state["opt_state"].count) == 5

    def test_bf16_params_keep_f32_moments(self):
        from tony_tpu.ops.optim import FusedAdamW
        params = {"w": jnp.ones((2, 128), jnp.bfloat16)}
        # lr must clear bf16's ulp near 1.0 (~0.008) to observe the move
        opt = FusedAdamW(0.1)
        state = opt.init(params)
        assert state.mu["w"].dtype == jnp.float32
        grads = {"w": jnp.full((2, 128), 0.5, jnp.bfloat16)}
        new_p, new_state, _ = jax.jit(opt.fused_apply)(grads, state, params)
        assert new_p["w"].dtype == jnp.bfloat16
        assert new_state.nu["w"].dtype == jnp.float32
        assert bool(jnp.all(new_p["w"] < params["w"]))   # moved downhill


class TestSlidingWindow:
    """Sliding-window (local) attention: query i attends positions
    (i-window, i]. The flash kernels triage out-of-window blocks exactly
    like above-diagonal ones (skip + DMA elision), so correctness must
    hold at every block/window alignment — window smaller than, equal
    to, larger than, and not a multiple of the block size."""

    @pytest.fixture(scope="class")
    def wqkv(self):
        r = np.random.RandomState(5)
        shape = (2, 128, 2, 32)
        return tuple(jnp.asarray(r.randn(*shape), jnp.float32)
                     for _ in range(3))

    @pytest.mark.parametrize("window", [1, 17, 32, 50, 96, 127, 128, 999])
    def test_forward_matches_dense(self, wqkv, window):
        q, k, v = wqkv
        o = flash_attention(q, k, v, causal=True, window=window,
                            block_q=32, block_k=32)
        ref = reference_attention(q, k, v, causal=True, window=window)
        np.testing.assert_allclose(o, ref, atol=2e-5)

    @pytest.mark.parametrize("window", [17, 50, 96])
    def test_gradients_match_dense(self, wqkv, window):
        q, k, v = wqkv
        g = jax.grad(lambda *a: flash_attention(
            *a, window=window, block_q=32, block_k=32).sum(),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: reference_attention(
            *a, window=window).sum(), argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(got, want, atol=5e-5)

    def test_gradients_two_pass(self, wqkv, monkeypatch):
        import tony_tpu.ops.attention as A
        monkeypatch.setattr(A, "_FUSED_PARTIALS_BYTES", 0)
        q, k, v = wqkv
        g = jax.grad(lambda *a: flash_attention(
            *a, window=50, block_q=32, block_k=32).sum(),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: reference_attention(
            *a, window=50).sum(), argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(got, want, atol=5e-5)

    def test_gqa_forward_matches_dense(self):
        r = np.random.RandomState(6)
        q = jnp.asarray(r.randn(2, 128, 4, 32), jnp.float32)
        k = jnp.asarray(r.randn(2, 128, 2, 32), jnp.float32)
        v = jnp.asarray(r.randn(2, 128, 2, 32), jnp.float32)
        o = flash_attention(q, k, v, causal=True, window=40,
                            block_q=32, block_k=32)
        ref = reference_attention(q, k, v, causal=True, window=40)
        np.testing.assert_allclose(o, ref, atol=2e-5)

    def test_with_lse_matches_dense(self, wqkv):
        from tony_tpu.ops.attention import _dense_with_lse
        q, k, v = wqkv
        o, lse = flash_attention_with_lse(q, k, v, causal=True, window=50,
                                          block_q=32, block_k=32)
        oref, lref = _dense_with_lse(q, k, v, causal=True, scale=None,
                                     window=50)
        np.testing.assert_allclose(o, oref, atol=2e-5)
        np.testing.assert_allclose(lse, lref, atol=2e-5)

    def test_out_of_window_kv_cannot_leak(self, wqkv):
        """The sharp masking check: corrupting K/V at position p must
        leave every query at position >= p+window BIT-IDENTICAL, and
        must change some query inside [p, p+window)."""
        q, k, v = wqkv
        w, p = 40, 30
        o1 = flash_attention(q, k, v, causal=True, window=w,
                             block_q=32, block_k=32)
        k2 = k.at[:, p].set(1e4)
        v2 = v.at[:, p].set(-1e4)
        o2 = flash_attention(q, k2, v2, causal=True, window=w,
                             block_q=32, block_k=32)
        np.testing.assert_array_equal(np.asarray(o1[:, p + w:]),
                                      np.asarray(o2[:, p + w:]))
        assert float(jnp.max(jnp.abs(o1[:, p:p + w] - o2[:, p:p + w]))) > 1

    def test_window_requires_causal(self, wqkv):
        q, k, v = wqkv
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=8,
                            block_q=32, block_k=32)
        with pytest.raises(ValueError, match=">= 1"):
            flash_attention(q, k, v, causal=True, window=0,
                            block_q=32, block_k=32)
