"""Seeded chaos smokes: randomized-but-reproducible gang-kill schedules.

Tier-1-safe fault injection over the REAL elastic stack: the schedule
(victim gangs, kill steps) is drawn from a seeded RNG — vary it with
``TONY_CHAOS_SEED`` — and logged in the failure message, so any red run
is replayable bit-for-bit. Uses the jax-free fake trainer: the smoke
exercises detection → shrink → resync → regrow orchestration, not model
math (tests/test_elastic.py pins the numerics)."""

import glob
import json
import os
import random
import sys

import pytest

from tony_tpu.client.client import TonyClient
from tony_tpu.conf.config import TonyConfig
from tony_tpu.events.events import find_job_files, parse_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAINER = os.path.join(REPO, "tests", "fixtures",
                       "fake_elastic_trainer.py")
PY = sys.executable


@pytest.mark.chaos
@pytest.mark.e2e
def test_seeded_gang_kill_schedule_survives(tmp_path):
    """3 single-host gangs, elastic on with regrow: kill a seeded-random
    non-chief gang at a seeded-random step (and, on half the seeds, a
    second gang later) — the job must still exit 0 without a session
    reset, and every worker must log its final step."""
    seed = int(os.environ.get("TONY_CHAOS_SEED", "20260804"))
    rng = random.Random(seed)
    steps = 14
    first_victim = rng.choice([1, 2])
    first_step = rng.randint(2, 6)
    second = rng.random() < 0.5
    second_victim = 3 - first_victim          # the other non-chief gang
    second_step = rng.randint(first_step + 4, steps - 3)
    schedule = {"seed": seed,
                "kills": [(f"worker:{first_victim}", first_step)]
                + ([(f"worker:{second_victim}", second_step)]
                   if second else [])}

    markers = {}
    clauses = []
    for victim, step in schedule["kills"]:
        m = tmp_path / f"kill-{victim.replace(':', '-')}.marker"
        markers[victim] = (m, step)
        clauses.append(f"{victim}@{m}")
    # every victim touches its own marker at its scheduled step (the
    # trainer's repeatable --kill clauses filter by task index)
    kill_flags = " ".join(
        f"--kill {m}:{s}:{v.split(':')[1]}"
        for v, (m, s) in markers.items())
    cmd = (f"{PY} {TRAINER} --steps {steps} "
           f"--ckpt {tmp_path / 'progress'} --ckpt_every 2 "
           f"--step_wait 0.2 {kill_flags}")
    conf = TonyConfig({
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.location": str(tmp_path / "hist"),
        "tony.application.timeout": "120000",
        "tony.worker.instances": "3",
        "tony.worker.slices": "3",
        "tony.task.heartbeat-interval-ms": "250",
        "tony.elastic.enabled": "true",
        "tony.elastic.regrow": "true",
        "tony.elastic.regrow-backoff-ms": "500",
    })
    client = TonyClient(conf, cmd, shell_env={
        "TEST_PREEMPT_TASKS": ";".join(clauses),
        "TONY_RESYNC_KILL_GRACE_S": "3",
    })
    rc = client.run()
    files = find_job_files(conf.get("tony.history.location"))
    types = [e.event_type for e in parse_events(files[0])] if files else []
    detail = (f"chaos schedule {schedule} → rc={rc}, events={types} — "
              f"reproduce with TONY_CHAOS_SEED={seed}")
    assert rc == 0, detail
    assert "SESSION_RESET" not in types, detail
    assert types.count("ELASTIC_SHRINK") == len(schedule["kills"]), detail
    log_dir = os.path.join(client.job_dir, "logs")
    # the chief is never detachable and its completion is the job verdict
    # — it must have run the whole schedule out
    chief = open(os.path.join(log_dir, "worker-0.stdout")).read()
    assert f"step {steps - 1}" in chief, detail + " (chief log)"
    # every victim's gang came back: a second trainer generation started
    # (the fake trainer has no collectives, so a regrown straggler may
    # legitimately be cut off when the chief's completion ends the job)
    for victim, _ in schedule["kills"]:
        body = open(os.path.join(
            log_dir, f"worker-{victim.split(':')[1]}.stdout")).read()
        assert body.count("starting at step") >= 2, (
            detail + f" ({victim} never relaunched)")
    # Flight recorder: every injected preempt-kill leaves a parseable
    # postmortem dump. The victims were SIGKILLed (they cannot dump), so
    # the COORDINATOR's ring is the incident artifact — one dump per
    # shrink, referenced from the ELASTIC_SHRINK jhist event, whose
    # final entries record the gang loss itself.
    events = parse_events(files[0])
    shrinks = [e for e in events if e.event_type == "ELASTIC_SHRINK"]
    assert len(shrinks) == len(schedule["kills"]), detail
    for shrink in shrinks:
        dump_path = shrink.payload.get("flight_dump")
        assert dump_path, detail + " (ELASTIC_SHRINK without flight_dump)"
        assert os.path.exists(dump_path), detail + f" ({dump_path} gone)"
        doc = json.load(open(dump_path))
        assert doc["reason"] == "elastic_shrink", doc["reason"]
        kinds = [e["kind"] for e in doc["events"]]
        # back-to-front: the dump marker, then the incident it records
        assert kinds[-1] == "flight_dump", kinds
        assert "gang_lost" in kinds, detail + f" (kinds={kinds})"
        lost_entry = next(e for e in reversed(doc["events"])
                          if e["kind"] == "gang_lost")
        victim = shrink.payload["lost"][0]
        assert victim in lost_entry["lost"], (lost_entry, shrink.payload)
    # dumps live under the job dir, named by the dumping process
    am_dumps = glob.glob(os.path.join(client.job_dir, "flight-am-0-*.json"))
    assert len(am_dumps) >= len(schedule["kills"]), (
        detail + f" (dumps={am_dumps})")


@pytest.mark.chaos
@pytest.mark.recovery
@pytest.mark.e2e
@pytest.mark.slow
def test_coordinator_kill_then_gang_preemption(tmp_path):
    """Interleaved faults: SIGKILL the coordinator early, then preempt a
    gang the RESTARTED coordinator only knows through journal adoption.
    The recovered session must absorb the loss through the normal
    elastic shrink → resync → regrow path — coordinator recovery and
    elastic recovery compose, neither resets the session."""
    from tony_tpu.cluster import journal as journal_mod
    steps = 30
    kill_marker = tmp_path / "kill-coordinator.marker"
    preempt_marker = tmp_path / "preempt-worker-2.marker"
    # Worker 0 touches the coordinator-kill marker at step 1; worker 2
    # touches its own preemption marker at step 10 — well after
    # re-adoption (~step 6 at this cadence). The job is long enough for
    # the adopted-reap hold + shrink + regrow to play out before the
    # chief's completion becomes the job verdict.
    cmd = (f"{PY} {TRAINER} --steps {steps} "
           f"--ckpt {tmp_path / 'progress'} --ckpt_every 2 "
           f"--step_wait 0.3 "
           f"--kill {kill_marker}:1:0 "
           f"--kill {preempt_marker}:10:2")
    conf = TonyConfig({
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.location": str(tmp_path / "hist"),
        "tony.application.timeout": "120000",
        "tony.worker.instances": "3",
        "tony.worker.slices": "3",
        "tony.task.heartbeat-interval-ms": "250",
        "tony.am.retry-count": "1",
        "tony.elastic.enabled": "true",
        "tony.elastic.regrow": "true",
        "tony.elastic.regrow-backoff-ms": "500",
    })
    client = TonyClient(conf, cmd, shell_env={
        "TEST_KILL_COORDINATOR": str(kill_marker),
        "TEST_PREEMPT_TASKS": f"worker:2@{preempt_marker}",
        "TONY_RESYNC_KILL_GRACE_S": "3",
    })
    rc = client.run()
    # events span both coordinator generations (the killed one's file
    # stays .inprogress forever; find_job_files matches both)
    files = find_job_files(conf.get("tony.history.location"))
    types = [e.event_type for f in files for e in parse_events(f)]
    detail = f"rc={rc}, job_dir={client.job_dir}, events={types}"
    assert rc == 0, detail
    assert os.path.exists(str(kill_marker) + ".fired"), detail
    assert "COORDINATOR_RESTART" in types, detail
    assert "ELASTIC_SHRINK" in types, detail
    assert "SESSION_RESET" not in types, detail
    # The chief ran the whole schedule out under BOTH faults. The
    # coordinator restart itself never touched it — exactly one
    # from-scratch generation; the later elastic resyncs legitimately
    # restart it FROM CHECKPOINT ("starting at step <n>0").
    chief = open(os.path.join(client.job_dir, "logs",
                              "worker-0.stdout")).read()
    assert f"step {steps - 1}" in chief, detail
    assert chief.count("starting at step 0 ") == 1, detail
    # the preempted gang came back through regrow: a second generation
    victim = open(os.path.join(client.job_dir, "logs",
                               "worker-2.stdout")).read()
    assert victim.count("starting at step") >= 2, detail
    # the journal folds both stories: two coordinator generations, and
    # the shrink/regrow records for the preempted gang
    records = journal_mod.replay(
        journal_mod.journal_path(client.job_dir))
    state = journal_mod.fold(records)
    kinds = [r["k"] for r in records]
    assert state.incarnation == 2, detail
    assert "elastic_shrink" in kinds, detail
    assert "regrow_activated" in kinds, detail
