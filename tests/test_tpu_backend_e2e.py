"""TPU-backend end-to-end against a fake gcloud (the MiniYARN trick).

The reference validates its launch commands as strings (TestTonyClient.
java:23-31) but then exercises the real executor path on MiniYARN; the
fake gcloud on PATH (tests/fake_gcloud.py) gives this backend the same
treatment: slices are directories, ssh runs commands as local processes
under per-worker fake $HOMEs, so staged executors REALLY run — importing
tony_tpu from the staged .tony-framework copy and registering with the
real coordinator over RPC."""

import os
import subprocess
import sys
import threading
import time

import pytest

from tony_tpu.client.client import TonyClient
from tony_tpu.conf.config import TonyConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FAKE_GCLOUD = os.path.join(REPO, "tests", "fake_gcloud.py")


@pytest.fixture
def fake_gcloud(tmp_path, monkeypatch):
    """Put a fake `gcloud` on PATH, rooted at tmp_path/fleet."""
    fleet = tmp_path / "fleet"
    fleet.mkdir()
    bindir = tmp_path / "bin"
    bindir.mkdir()
    gcloud = bindir / "gcloud"
    gcloud.write_text(
        f"#!/bin/bash\nexec {sys.executable} {FAKE_GCLOUD} \"$@\"\n")
    gcloud.chmod(0o755)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_GCLOUD_ROOT", str(fleet))
    monkeypatch.setenv("FAKE_NUM_WORKERS", "2")
    return str(fleet)


def tpu_conf(tmp_path, extra=None):
    base = {
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.location": str(tmp_path / "hist"),
        "tony.application.timeout": "90000",
        "tony.scheduler.backend": "tpu",
        "tony.tpu.project": "test-proj",
        "tony.tpu.zone": "us-test1-a",
        "tony.tpu.accelerator-type": "v5litepod",
        "tony.tpu.state-refresh-ms": "200",
        "tony.worker.instances": "2",
        "tony.worker.tpu.topology": "4x4",     # 16 chips / 8 per host = 2
        "tony.application.python-binary-path": sys.executable,
    }
    base.update(extra or {})
    return TonyConfig(base)


def calls(fleet):
    path = os.path.join(fleet, "calls.log")
    if not os.path.exists(path):
        return []
    return open(path).read().splitlines()


@pytest.mark.e2e
class TestTpuBackendE2E:
    def test_provision_stage_launch_succeed(self, fake_gcloud, tmp_path):
        """Full happy path: slice provisioned, job dir staged to every
        worker home, executors launched over fake ssh run the user command
        with cwd ~/tony-job, job SUCCEEDS."""
        proof = tmp_path / "proof"
        client = TonyClient(
            tpu_conf(tmp_path),
            f'bash -c "pwd >> {proof}-$JOB_NAME-$TASK_INDEX; '
            f'ls tony-final.xml >> {proof}-$JOB_NAME-$TASK_INDEX"')
        assert client.run() == 0

        ops = [c.split()[3] for c in calls(fake_gcloud)]
        assert ops.count("create") == 1
        assert "scp" in ops            # tarball staged
        assert "delete" in ops         # teardown releases the slice

        # every worker home got the full localized job dir
        slice_dirs = [d for d in os.listdir(fake_gcloud)
                      if d.startswith("tony-")]
        assert len(slice_dirs) == 0    # slice deleted at stop()
        # the user command itself proved cwd + staging (one file per task)
        for idx in (0, 1):
            body = open(f"{proof}-worker-{idx}").read()
            assert body.splitlines()[0].endswith("tony-job")
            assert "tony-final.xml" in body

    def test_multi_slice_two_gangs(self, fake_gcloud, tmp_path):
        """tony.worker.slices=2: TWO slices are provisioned and staged,
        each gang's executors run with in-slice --worker indices, and every
        task sees its gang identity (TONY_SLICE_ID / TONY_NUM_SLICES)."""
        proof = tmp_path / "gang"
        client = TonyClient(
            tpu_conf(tmp_path, {"tony.worker.instances": "4",
                                "tony.worker.slices": "2"}),
            f'bash -c "echo $TONY_SLICE_ID/$TONY_NUM_SLICES '
            f'> {proof}-$TASK_INDEX"')
        assert client.run() == 0
        ops = [c.split()[3] for c in calls(fake_gcloud)]
        assert ops.count("create") == 2          # one VM per gang
        creates = [c.split()[4] for c in calls(fake_gcloud)
                   if c.split()[3] == "create"]
        assert {n[-3:] for n in creates} == {"-s0", "-s1"}
        for idx, want in ((0, "0/2"), (1, "0/2"), (2, "1/2"), (3, "1/2")):
            assert open(f"{proof}-{idx}").read().strip() == want

    def test_staged_framework_is_importable(self, fake_gcloud, tmp_path):
        """Executors must run from the STAGED tony_tpu copy (no install on
        hosts): the user task prints tony_tpu.__file__ and it must resolve
        inside ~/tony-job/.tony-framework."""
        proof = tmp_path / "whereis"
        client = TonyClient(
            tpu_conf(tmp_path, {"tony.worker.instances": "1",
                                "tony.worker.tpu.topology": "2x4"}),
            f'bash -c "{sys.executable} -c '
            f"'import tony_tpu; print(tony_tpu.__file__)'"
            f' > {proof}"')
        assert client.run() == 0
        where = open(proof).read().strip()
        assert "tony-job/.tony-framework/tony_tpu" in where

    @staticmethod
    def _preemption_command(tmp_path, marker):
        """User command for preemption choreography: announce this task
        started (a sentinel the test waits on — ssh launch lines hit
        calls.log BEFORE the executor process runs, so polling those
        races task startup), then exit 0 on the retry attempt or hang."""
        return (f'bash -c "touch {tmp_path}/started-$JOB_NAME-$TASK_INDEX; '
                f'if [ -f {marker} ]; then exit 0; else sleep 60; fi"')

    @staticmethod
    def _wait_tasks_started(tmp_path, n, timeout_s=60):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            started = [f for f in os.listdir(tmp_path)
                       if f.startswith("started-")]
            if len(started) >= n:
                return
            time.sleep(0.2)
        raise AssertionError("first-generation tasks never started")

    @staticmethod
    def _preempt(fleet, slice_name):
        with open(os.path.join(fleet, slice_name, "state"), "w") as f:
            f.write("PREEMPTED")

    def test_preemption_reprovisions_and_restages(self, fake_gcloud,
                                                  tmp_path):
        """Slice goes PREEMPTED mid-run: the coordinator retries from the
        preemption budget and the backend deletes + recreates + RESTAGES
        the slice; the relaunched attempt succeeds."""
        marker = tmp_path / "attempt2.marker"
        client = TonyClient(tpu_conf(tmp_path),
                            self._preemption_command(tmp_path, marker))
        result = {}
        t = threading.Thread(target=lambda: result.update(
            code=client.run()))
        t.start()
        try:
            self._wait_tasks_started(tmp_path, 2)
            marker.write_text("go")
            slice_name = [d for d in os.listdir(fake_gcloud)
                          if d.startswith("tony-")][0]
            self._preempt(fake_gcloud, slice_name)
        finally:
            t.join(timeout=120)
        assert result.get("code") == 0
        ops = [c.split()[3] for c in calls(fake_gcloud)]
        assert ops.count("create") == 2      # reprovisioned
        assert ops.count("scp") == 2         # re-staged
        assert ops.count("delete") >= 2      # dead slice + final teardown

    def test_multi_slice_preemption_reprovisions_only_that_gang(
            self, fake_gcloud, tmp_path):
        """2 gangs; one goes PREEMPTED mid-run. The session retries, the
        dead gang is deleted + recreated + restaged, and the surviving
        gang's VM is NOT reprovisioned."""
        marker = tmp_path / "attempt2.marker"
        client = TonyClient(
            tpu_conf(tmp_path, {"tony.worker.instances": "4",
                                "tony.worker.slices": "2"}),
            self._preemption_command(tmp_path, marker))
        result = {}
        t = threading.Thread(target=lambda: result.update(
            code=client.run()))
        t.start()
        try:
            self._wait_tasks_started(tmp_path, 4)
            marker.write_text("go")
            victim = [d for d in os.listdir(fake_gcloud)
                      if d.endswith("-s1")][0]
            self._preempt(fake_gcloud, victim)
        finally:
            t.join(timeout=120)
        assert result.get("code") == 0

        def gang_ops(op, suffix):
            return sum(1 for c in calls(fake_gcloud)
                       if c.split()[3] == op
                       and (c.split()[4].endswith(suffix) if op != "scp"
                            else suffix in c.split()[5]))
        # gang s1: deleted, recreated, RE-STAGED; gang s0 untouched
        assert gang_ops("create", "-s1") == 2
        assert gang_ops("delete", "-s1") >= 1
        assert gang_ops("scp", "-s1") >= 2      # initial + restage
        assert gang_ops("create", "-s0") == 1

    def test_topology_instances_mismatch_rejected_at_submit(self, tmp_path):
        """VERDICT #6: instances=4 on a v5e 2x2 slice (1 host) must fail
        in the SUBMITTING process with an actionable message — before any
        coordinator launch, not as a late opaque ssh error."""
        conf = tpu_conf(tmp_path, {"tony.worker.instances": "4",
                                   "tony.worker.tpu.topology": "2x2"})
        client = TonyClient(conf, "true")
        with pytest.raises(ValueError, match="1 host"):
            client.stage()
        # nothing was staged or launched
        assert not os.path.exists(
            os.path.join(client.job_dir, "tony-final.xml"))

    def test_secret_via_file_never_in_ssh_argv(self, fake_gcloud, tmp_path):
        """Security on: executors must authenticate (job succeeds) while
        the secret travels as a chmod-600 staged file — absent from every
        gcloud argv (visible in ps) and from the stage tarball."""
        client = TonyClient(
            tpu_conf(tmp_path,
                     {"tony.application.security.enabled": "true"}),
            "true")
        assert client.run() == 0
        secret = client.secret
        assert secret
        for line in calls(fake_gcloud):
            assert secret not in line
        # the scp plan shipped the secret file + chmod'ed it
        joined = "\n".join(calls(fake_gcloud))
        assert ".tony-secret" in joined
        assert "chmod 600 ~/tony-job/.tony-secret" in joined

    def test_quota_exhausted_create_retries_with_backoff(
            self, fake_gcloud, tmp_path, monkeypatch):
        """The first two creates fail RESOURCE_EXHAUSTED (quota); the
        backend retries with backoff inside the SAME provisioning attempt
        and the job succeeds. No preemption budget is consumed — quota
        wait is not a lost slice."""
        monkeypatch.setenv("FAKE_FAIL_CREATE_N", "2")
        client = TonyClient(
            tpu_conf(tmp_path, {"tony.tpu.retry-backoff-ms": "50",
                                "tony.tpu.preemption-retries": "0"}),
            'bash -c "exit 0"')
        assert client.run() == 0
        ops = [c.split()[3] for c in calls(fake_gcloud)]
        assert ops.count("create") == 3        # 2 failures + 1 success

    def test_quota_budget_exhausted_fails_actionably(
            self, fake_gcloud, tmp_path, monkeypatch):
        monkeypatch.setenv("FAKE_FAIL_CREATE_N", "99")
        client = TonyClient(
            tpu_conf(tmp_path, {"tony.tpu.retry-backoff-ms": "20",
                                "tony.tpu.create-retries": "1"}),
            'bash -c "exit 0"')
        assert client.run() == 1
        ops = [c.split()[3] for c in calls(fake_gcloud)]
        assert ops.count("create") == 2        # initial + 1 retry

    def test_ssh_drop_mid_staging_restages_idempotently(
            self, fake_gcloud, tmp_path, monkeypatch):
        """The staging unpack drops once ('Connection reset by peer');
        the backend re-runs the WHOLE staging sequence (idempotent: rm -rf
        + untar, scp overwrites) and the job succeeds with a complete,
        uncorrupted job dir on every host."""
        monkeypatch.setenv("FAKE_FAIL_UNPACK_N", "1")
        proof = tmp_path / "proof"
        client = TonyClient(
            tpu_conf(tmp_path, {"tony.application.security.enabled":
                                "true"}),
            f'bash -c "ls tony-final.xml >> {proof}-$TASK_INDEX; '
            f'cat $PWD/.tony-secret >> {proof}-$TASK_INDEX"')
        assert client.run() == 0
        # the unpack ran twice (drop + re-stage) and the secret still
        # arrived AFTER the successful unpack
        unpacks = [c for c in calls(fake_gcloud)
                   if "tar -xzf" in c and c.split()[3] == "ssh"]
        assert len(unpacks) == 2
        for idx in (0, 1):
            body = open(f"{proof}-{idx}").read()
            assert "tony-final.xml" in body
            assert client.secret in body

    def test_describe_flakiness_does_not_fail_job(
            self, fake_gcloud, tmp_path, monkeypatch):
        """Transient describe failures map to state UNKNOWN — tasks keep
        running, nothing is treated as preempted, the job succeeds."""
        monkeypatch.setenv("FAKE_FAIL_DESCRIBE_N", "50")
        client = TonyClient(
            tpu_conf(tmp_path, {"tony.tpu.state-refresh-ms": "100",
                                "tony.tpu.preemption-retries": "0"}),
            'bash -c "sleep 2; exit 0"')
        assert client.run() == 0
        ops = [c.split()[3] for c in calls(fake_gcloud)]
        assert ops.count("describe") >= 2      # the poller really polled
