"""Metrics-plane unit tests: registry semantics, wire codec, Prometheus
rendering, the coordinator-side snapshot table, and the PhaseTimes bridge
(tony_tpu/runtime/metrics.py)."""

import json
import threading

import pytest

from tony_tpu.runtime import metrics as M
from tony_tpu.runtime.profiler import PhaseTimes


# ---------------------------------------------------------------------------
# Instruments
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_basics():
    reg = M.MetricsRegistry()
    c = reg.counter("reqs_total", help="requests")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    g = reg.gauge("depth")
    g.set(7)
    g.inc(-2)
    assert g.value == 5.0
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 5.0, 100.0):
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(105.65)
    # le semantics: value == bound counts in that bound's bucket
    assert h.cumulative() == [2, 3, 4, 5]


def test_get_or_create_returns_same_instrument():
    reg = M.MetricsRegistry()
    assert reg.counter("a") is reg.counter("a")
    assert reg.counter("a", phase="x") is reg.counter("a", phase="x")
    assert reg.counter("a", phase="x") is not reg.counter("a", phase="y")


def test_kind_conflict_rejected():
    reg = M.MetricsRegistry()
    reg.counter("x_total")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x_total")


def test_histogram_requires_buckets():
    reg = M.MetricsRegistry()
    with pytest.raises(ValueError, match="bucket"):
        reg.histogram("h", buckets=())


def test_concurrent_get_or_create_single_instrument():
    reg = M.MetricsRegistry()
    seen = []
    barrier = threading.Barrier(8)

    def worker():
        barrier.wait()
        c = reg.counter("shared_total")
        for _ in range(1000):
            c.inc()
        seen.append(c)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len({id(c) for c in seen}) == 1
    # per-instrument lock in inc(): concurrent writers lose no updates
    assert reg.counter("shared_total").value == 8000.0


# ---------------------------------------------------------------------------
# Wire codec
# ---------------------------------------------------------------------------

def _populated_registry():
    reg = M.MetricsRegistry()
    reg.counter("tok_total", help="tokens", task="worker:0").inc(42)
    reg.gauge("rss_bytes").set(1234.5)
    h = reg.histogram("step_seconds", buckets=(0.5, 1.0), phase="fit")
    h.observe(0.2)
    h.observe(3.0)
    return reg


def test_wire_round_trip_bit_exact():
    reg = _populated_registry()
    encoded = reg.to_wire_json()
    decoded = M.from_wire_json(encoded)
    assert decoded == reg.to_wire()
    assert json.dumps(decoded, separators=(",", ":")) == encoded


@pytest.mark.parametrize("bad", [
    "not json",
    '"a string"',
    "[]",
    '{"c": 7}',
    '{"c": [["only-two", {}]]}',
    '{"c": [["x", "not-labels", 1]]}',
    '{"c": [["x", {}, "not-a-number"]]}',
    '{"h": [["x", {}, 5]]}',
    '{"h": [["x", {}, {"b": [1], "n": [1], "s": 0, "c": 0}]]}',  # n != b+1
    '{"m": []}',
    # Prometheus-corruption vectors: anything passing validate_wire must
    # render cleanly, so illegal names/keys and non-finite values reject
    '{"c": [["bad name", {}, 1]]}',
    '{"c": [["x\\ny", {}, 1]]}',
    '{"c": [["x", {"bad-key": "v"}, 1]]}',
    '{"c": [["x", {"k": [1]}, 1]]}',
    '{"c": [["x", {}, NaN]]}',
    '{"g": [["x", {}, Infinity]]}',
    '{"h": [["x", {}, {"b": [2.0, 1.0], "n": [0, 0, 0], "s": 0, "c": 0}]]}',
    # missing "s" must be ValueError, never a KeyError escaping ingest
    '{"h": [["x", {}, {"b": [0.1], "n": [0, 0], "c": 0}]]}',
    '{"h": [["x", {}, {"b": [0.1], "n": [0, 0], "s": 0.0, "c": true}]]}',
    '{"h": [["x", {}, {"b": [0.1], "n": [0, 0], "s": 0.0, "c": -1}]]}',
    # meta values must be string sequences — series_from_wire indexes them
    '{"c": [["x", {}, 1]], "m": {"x": 5}}',
    '{"c": [["x", {}, 1]], "m": {"x": []}}',
    '{"c": [["x", {}, 1]], "m": {"x": [3, 4]}}',
])
def test_malformed_wire_rejected(bad):
    with pytest.raises(ValueError):
        M.from_wire_json(bad)


def test_snapshot_table_ingest_survives_garbage():
    table = M.SnapshotTable()
    good = M.MetricsRegistry()
    good.counter("x_total").inc(3)
    assert table.ingest("worker:0", good.to_wire_json())
    for garbage in ("}{", "null", '{"g": {}}', 17, None, b"bytes"):
        assert table.ingest("worker:0", garbage) is False
    assert table.rejected == 6
    assert table.get("worker:0")["c"] == [["x_total", {}, 3.0]]
    # histogram with well-typed SHAPE but poisoned elements must also
    # be rejected — these would crash the Prometheus renderer
    assert table.ingest("worker:0", json.dumps(
        {"c": [], "g": [],
         "h": [["x", {}, {"b": ["bad"], "n": [1, 2], "s": 0, "c": 0}]],
         "m": {}})) is False
    assert table.ingest("worker:0", json.dumps(
        {"c": [], "g": [],
         "h": [["x", {}, {"b": [1.0], "n": [1, "x"], "s": 0, "c": 0}]],
         "m": {}})) is False
    table.clear()
    assert table.tasks() == []


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------

def _parse_exposition(text):
    """Minimal format checker: returns ({name: type}, {series_line})."""
    types, series = {}, []
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = kind
        elif line.startswith("# HELP ") or not line.strip():
            continue
        else:
            series.append(line)
            name_labels, _, value = line.rpartition(" ")
            float(value)                       # every sample is numeric
    assert len(set(s.rpartition(" ")[0] for s in series)) == len(series), \
        "duplicate series in exposition"
    return types, series


def test_render_prometheus_valid_exposition():
    reg = _populated_registry()
    text = M.render_registry(reg, extra_labels={"job": "app_1"})
    types, series = _parse_exposition(text)
    assert types == {"tok_total": "counter", "rss_bytes": "gauge",
                     "step_seconds": "histogram"}
    assert "# HELP tok_total tokens" in text
    assert 'tok_total{job="app_1",task="worker:0"} 42' in text
    assert 'rss_bytes{job="app_1"} 1234.5' in text
    # histogram expands to cumulative buckets + sum + count
    assert 'step_seconds_bucket{job="app_1",le="0.5",phase="fit"} 1' in text
    assert 'step_seconds_bucket{job="app_1",le="1",phase="fit"} 1' in text
    assert 'step_seconds_bucket{job="app_1",le="+Inf",phase="fit"} 2' in text
    assert 'step_seconds_sum{job="app_1",phase="fit"} 3.2' in text
    assert 'step_seconds_count{job="app_1",phase="fit"} 2' in text


def test_render_prometheus_dedupes_and_escapes():
    entries = [
        ("counter", "c_total", {"t": 'a"b\n'}, 1.0, ""),
        ("counter", "c_total", {"t": 'a"b\n'}, 2.0, ""),   # dup: last wins
    ]
    text = M.render_prometheus(entries)
    assert text.count("c_total{") == 1
    assert 'c_total{t="a\\"b\\n"} 2' in text


def test_render_prometheus_empty():
    assert M.render_prometheus([]) == ""


# ---------------------------------------------------------------------------
# Bridges + default registry
# ---------------------------------------------------------------------------

def test_observe_phase_times_bridge_accumulates():
    reg = M.MetricsRegistry()
    pt = PhaseTimes()
    with pt.phase("fetch"):
        pass
    with pt.phase("fetch"):
        pass
    with pt.phase("admit"):
        pass
    M.observe_phase_times(pt, reg)
    assert reg.counter("tony_serve_phase_ops_total", phase="fetch").value == 2
    assert reg.counter("tony_serve_phase_ops_total", phase="admit").value == 1
    # a second serve() call's fold ADDS (monotonic counters)
    M.observe_phase_times(pt, reg)
    assert reg.counter("tony_serve_phase_ops_total", phase="fetch").value == 4
    assert reg.counter("tony_serve_phase_seconds_total",
                       phase="fetch").value >= 0.0


def test_sample_host_stats_populates_gauges():
    reg = M.MetricsRegistry()
    M.sample_host_stats(reg)
    wire = reg.to_wire()
    names = {name for name, _, _ in wire["g"]}
    assert "tony_process_uptime_seconds" in names
    # /proc exists on the CI image: rss + cpu should land too
    assert "tony_process_rss_bytes" in names
    assert "tony_process_cpu_seconds" in names
    rss = dict((n, v) for n, _, v in wire["g"])["tony_process_rss_bytes"]
    assert rss > 1 << 20                      # a python process is > 1 MiB
    # Linux-gated: the CI image has /proc/self/fd, so the open-fd gauge
    # must land — a python process always holds stdio at minimum
    import os
    if os.path.isdir("/proc/self/fd"):
        fds = dict((n, v) for n, _, v in wire["g"])["tony_task_open_fds"]
        assert fds >= 3


# ---------------------------------------------------------------------------
# histogram_quantile
# ---------------------------------------------------------------------------

def test_histogram_quantile_interpolates_and_handles_edges():
    import math
    h = M.Histogram("lat", {}, buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 1.5, 3.0):
        h.observe(v)
    # rank 2 of 4: one observation below the (1, 2] bucket, so the
    # rank sits halfway through it -> 1.5 (prometheus semantics)
    assert M.histogram_quantile(h, 0.5) == pytest.approx(1.5)
    assert M.histogram_quantile(h, 1.0) == pytest.approx(4.0)
    # first bucket interpolates from lower bound 0
    assert M.histogram_quantile(h, 0.25) == pytest.approx(1.0)
    # wire-dict input is equivalent to the live instrument
    wire = {"b": [1.0, 2.0, 4.0], "n": list(h._counts)}
    assert M.histogram_quantile(wire, 0.5) == \
        M.histogram_quantile(h, 0.5)
    # empty histogram -> NaN, never a crash
    empty = M.Histogram("e", {}, buckets=(1.0,))
    assert math.isnan(M.histogram_quantile(empty, 0.99))
    assert math.isnan(M.histogram_quantile({"b": [], "n": []}, 0.5))
    # a rank landing in the +Inf bucket clamps to the highest finite
    # bound (no interior to interpolate)
    inf = M.Histogram("i", {}, buckets=(1.0, 2.0))
    for v in (0.5, 10.0, 20.0):
        inf.observe(v)
    assert M.histogram_quantile(inf, 0.99) == 2.0
    # single-bucket histogram: everything interpolates inside [0, bound]
    one = M.Histogram("o", {}, buckets=(8.0,))
    for _ in range(4):
        one.observe(1.0)
    assert M.histogram_quantile(one, 0.5) == pytest.approx(4.0)
    with pytest.raises(ValueError):
        M.histogram_quantile(h, 1.5)
    with pytest.raises(ValueError):
        M.histogram_quantile(h, -0.1)


def test_default_registry_swap_restores():
    prev = M.set_default(M.NullRegistry())
    try:
        null = M.get_default()
        null.counter("anything").inc()
        null.histogram("h").observe(1.0)
        assert null.to_wire() == {"c": [], "g": [], "h": [], "m": {}}
    finally:
        M.set_default(prev)
    assert M.get_default() is prev


def test_serve_loop_observes_into_registry():
    """The continuous batcher's instrumentation lands admitted/retired/
    token counters and the PhaseTimes fold in the default registry."""
    jax = pytest.importorskip("jax")
    from tony_tpu.models import transformer as T
    from tony_tpu.models.serve import ContinuousBatcher

    cfg = T.PRESETS["tiny"]
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    reg = M.MetricsRegistry()
    prev = M.set_default(reg)
    try:
        b = ContinuousBatcher(params, cfg, batch=2, max_len=48, chunk=4)
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
        outs = b.serve(prompts, [6, 4, 5])
    finally:
        M.set_default(prev)
    assert [len(o) for o in outs] == [6, 4, 5]
    assert reg.counter("tony_serve_requests_admitted_total").value == 3
    assert reg.counter("tony_serve_requests_retired_total").value == 3
    assert reg.counter("tony_serve_tokens_total").value == 15
    assert reg.gauge("tony_serve_queue_depth").value == 0
    assert reg.counter("tony_serve_phase_ops_total", phase="fetch").value > 0
    assert reg.counter("tony_serve_phase_seconds_total",
                       phase="dispatch").value > 0


def test_train_step_observes_into_registry():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp
    from tony_tpu.models.train import (default_optimizer, init_state,
                                       make_train_step)

    # toy quadratic model: the test targets the step instrumentation,
    # not the transformer (whose own path test_serve/test_parallel cover)
    params = {"w": jnp.ones((4,), jnp.float32)}
    opt = default_optimizer(lr=1e-2)
    state = init_state(params, opt)
    step = make_train_step(
        lambda p, b: jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2), opt)
    batch = {"x": jnp.ones((2, 4), jnp.float32),
             "y": jnp.zeros((2,), jnp.float32)}
    reg = M.MetricsRegistry()
    prev = M.set_default(reg)
    try:
        for _ in range(3):
            state, m = step(state, batch)
        float(m["loss"])
    finally:
        M.set_default(prev)
    assert reg.counter("tony_train_steps_total").value == 3
    assert reg.counter("tony_train_examples_total").value == 6
    h = reg.histogram("tony_train_step_seconds")
    assert h.count == 3 and h.sum > 0
