"""Device-prefetched input pipeline + framework-owned train loop.

Pins the DevicePrefetcher contract (ordering, epochal determinism, error
propagation with original tracebacks, close-never-deadlocks, shape
consistency), the run_training driver (data-wait metric, periodic eval,
checkpoint+resume smoke on the local backend, KeyboardInterrupt leaves no
``tony-datafeed-*`` threads), the train-step retrace guard, and the
satellites (memoized ``data_parallel_rank``, short-tail handling across
the prefetch boundary)."""

import logging
import time
import traceback

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tony_tpu.io.prefetch import (DevicePrefetcher, PrefetchShapeError,
                                  reader_epochs, synchronous_batches)
from tony_tpu.models.loop import run_training
from tony_tpu.runtime import metrics as M


# ---------------------------------------------------------------------------
# DevicePrefetcher core contract
# ---------------------------------------------------------------------------

class TestDevicePrefetcher:

    def test_yields_all_batches_in_order(self):
        batches = [{"x": np.full((2, 3), i, np.float32)} for i in range(5)]
        with DevicePrefetcher(iter(batches)) as pf:
            out = list(pf)
        assert len(out) == 5
        for i, b in enumerate(out):
            assert isinstance(b["x"], jax.Array)   # device-resident
            np.testing.assert_array_equal(np.asarray(b["x"]), i)

    def test_epochal_source_cycles_and_is_deterministic(self):
        def source(epoch):
            rs = np.random.RandomState(epoch)
            for _ in range(3):
                yield rs.randint(0, 100, size=(4,)).astype(np.int32)

        def take(n):
            with DevicePrefetcher(source) as pf:
                return [np.asarray(next(pf)) for _ in range(n)]

        a, b = take(7), take(7)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)    # same stream both runs
        # batches 3..5 come from epoch 1 (a DIFFERENT reshuffle seed)
        expect_e1 = list(source(1))
        for got, want in zip(a[3:6], expect_e1):
            np.testing.assert_array_equal(got, want)

    def test_epochs_bound_ends_stream(self):
        def source(epoch):
            for i in range(3):
                yield np.full((2,), 10 * epoch + i, np.int32)

        with DevicePrefetcher(source, epochs=2) as pf:
            out = [int(np.asarray(b)[0]) for b in pf]
        assert out == [0, 1, 2, 10, 11, 12]

    def test_empty_epoch_raises_instead_of_spinning(self):
        with DevicePrefetcher(lambda epoch: iter(())) as pf:
            with pytest.raises(ValueError, match="no batches"):
                next(pf)

    def test_producer_error_surfaces_with_original_traceback(self):
        def _exploding_source(epoch):
            yield np.zeros((2,), np.float32)
            raise ValueError("decode exploded")

        with DevicePrefetcher(_exploding_source) as pf:
            next(pf)                                # the good batch
            with pytest.raises(ValueError, match="decode exploded") as ei:
                next(pf)
        frames = traceback.extract_tb(ei.value.__traceback__)
        assert any(f.name == "_exploding_source" for f in frames), (
            "producer traceback lost: " + str([f.name for f in frames]))

    def test_shape_change_raises_instead_of_retracing(self):
        batches = [np.zeros((4, 2), np.float32), np.zeros((4, 3), np.float32)]
        with DevicePrefetcher(iter(batches)) as pf:
            next(pf)
            with pytest.raises(PrefetchShapeError, match="retrace"):
                next(pf)

    def test_dtype_change_raises(self):
        batches = [np.zeros((4,), np.float32), np.zeros((4,), np.int32)]
        with DevicePrefetcher(iter(batches)) as pf:
            next(pf)
            with pytest.raises(PrefetchShapeError):
                next(pf)

    def test_close_during_full_queue_never_deadlocks(self):
        def gen():
            i = 0
            while True:
                yield np.full((2,), i, np.float32)
                i += 1

        pf = DevicePrefetcher(gen(), depth=1)
        deadline = time.monotonic() + 5
        while not pf._q.full() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pf._q.full(), "producer never filled the queue"
        t0 = time.monotonic()
        pf.close()
        assert time.monotonic() - t0 < 5
        assert not pf._thread.is_alive()
        assert pf._q is None               # parked batches released

    def test_no_tail_loss_when_producer_exits_inside_timeout(self):
        # Race pin: the producer parks its last batch + sentinel and DIES
        # inside the consumer's get() timeout window; the consumer must
        # drain what was parked, not conclude StopIteration early.
        import queue as queue_mod

        batches = [np.full((2,), i, np.float32) for i in range(3)]
        pf = DevicePrefetcher(iter(batches), depth=8)
        pf._thread.join(timeout=5)          # everything parked, thread dead
        assert not pf._thread.is_alive()
        real_get = pf._q.get
        state = {"raised": False}

        def flaky_get(block=True, timeout=None):   # one spurious Empty,
            if not state["raised"]:                # then the real queue
                state["raised"] = True
                raise queue_mod.Empty
            return real_get(block=block, timeout=timeout)

        pf._q.get = flaky_get
        out = [int(np.asarray(b)[0]) for b in pf]
        assert out == [0, 1, 2]
        pf.close()

    def test_synchronous_batches_same_contract(self):
        # the --prefetch_depth 0 contrast: same epochal cycling and
        # empty-epoch guard as the threaded path, assembly inline
        def source(epoch):
            for i in range(2):
                yield np.full((2,), 10 * epoch + i, np.float32)

        out = [int(np.asarray(b)[0])
               for b in synchronous_batches(source, epochs=2)]
        assert out == [0, 1, 10, 11]
        with pytest.raises(ValueError, match="no batches"):
            list(synchronous_batches(lambda epoch: iter(())))

    def test_sharded_assembly_matches_source(self):
        from tony_tpu.models.train import batch_sharding
        from tony_tpu.parallel import make_mesh

        mesh = make_mesh({"dp": -1})
        sharding = batch_sharding(mesh)
        src = np.arange(16, dtype=np.float32).reshape(8, 2)
        with DevicePrefetcher(iter([src]), sharding=sharding) as pf:
            got = next(pf)
        assert isinstance(got, jax.Array)
        assert got.sharding.is_equivalent_to(sharding, got.ndim)
        np.testing.assert_array_equal(np.asarray(got), src)


# ---------------------------------------------------------------------------
# run_training driver
# ---------------------------------------------------------------------------

def _counting_step(state, batch):
    return state + 1, {"loss": float(state + 1)}


class TestRunTraining:

    def test_data_wait_metric_eval_and_log_cadence(self):
        saved = M.set_default(M.MetricsRegistry())
        try:
            logged = []
            data = iter([np.zeros((2,))] * 10)
            state, metrics = run_training(
                _counting_step, 0, data, 6,
                eval_fn=lambda s: s, eval_every=2,
                log_every=2, log_fn=lambda st, m, b: logged.append(st))
            assert state == 6
            assert metrics["eval"] == 6          # eval ran after step 5
            assert logged == [0, 2, 4, 5]        # cadence + final step
            hist = M.get_default().histogram("tony_data_wait_seconds")
            assert hist.count == 6               # one observation per step
        finally:
            M.set_default(saved)

    def test_stops_cleanly_on_exhausted_data(self):
        state, _ = run_training(_counting_step, 0,
                                iter([np.zeros(2)] * 3), 10)
        assert state == 3

    def test_keyboardinterrupt_leaves_no_datafeed_threads(self):
        def gen():
            while True:
                yield np.zeros((2,), np.float32)

        def step_fn(state, batch):
            if state >= 2:
                raise KeyboardInterrupt
            return state + 1, {"loss": 0.0}

        pf = DevicePrefetcher(gen(), depth=2)
        with pytest.raises(KeyboardInterrupt):
            run_training(step_fn, 0, pf, 100)
        # the finally-close stopped the producer: nothing to leak
        assert not pf._thread.is_alive()
        assert pf._q is None
        import threading
        alive = [t.name for t in threading.enumerate()
                 if t.name.startswith("tony-datafeed-device")
                 and t.is_alive()]
        assert alive == []

    def test_checkpoint_resume_smoke_local_backend(self, tmp_path,
                                                   retrace_guard):
        """run_training end-to-end on the local backend: 5 steps with
        per-step checkpointing, then restore + resume to 8 — and exactly
        ONE compiled train step across the whole run (guard-pinned)."""
        import optax
        from tony_tpu.models.checkpoint import CheckpointManager
        from tony_tpu.models.train import init_state, make_train_step

        def loss_fn(params, batch):
            pred = batch["x"] @ params["w"]
            return jnp.mean((pred - batch["y"]) ** 2)

        def batches(seed):
            rs = np.random.RandomState(seed)
            while True:
                x = rs.randn(8, 4).astype(np.float32)
                yield {"x": x,
                       "y": (x @ np.ones((4, 2))).astype(np.float32) * 0.5}

        opt = optax.sgd(0.01, momentum=0.9)   # real (array) opt state
        params = {"w": jnp.ones((4, 2), jnp.float32)}
        step = make_train_step(loss_fn, opt)

        with CheckpointManager(str(tmp_path / "ckpt"),
                               save_interval_steps=1) as mgr:
            state, _ = run_training(step, init_state(params, opt),
                                    DevicePrefetcher(batches(0)), 5,
                                    checkpoint=mgr)
            assert int(state["step"]) == 5
            assert mgr.latest_step() == 5

        with CheckpointManager(str(tmp_path / "ckpt"),
                               save_interval_steps=1) as mgr2:
            state2 = mgr2.restore_or_init(lambda: init_state(params, opt))
            assert int(state2["step"]) == 5      # resumed, not restarted
            state2, metrics = run_training(step, state2,
                                           DevicePrefetcher(batches(1)), 8,
                                           start_step=5, checkpoint=mgr2)
            assert int(state2["step"]) == 8
            assert mgr2.latest_step() == 8
            assert np.isfinite(float(metrics["loss"]))
        retrace_guard.assert_max("train_step", 1)


# ---------------------------------------------------------------------------
# reader_epochs + short-tail behavior across the prefetch boundary
# ---------------------------------------------------------------------------

def _write_records(path, values, record_size, tail=b""):
    rows = b"".join(
        int(v).to_bytes(4, "little") * (record_size // 4) for v in values)
    path.write_bytes(rows + tail)
    return str(path)


class TestReaderEpochs:

    def test_deterministic_per_epoch_reshuffle(self, tmp_path):
        paths = [_write_records(tmp_path / "a.bin", range(20), 8),
                 _write_records(tmp_path / "b.bin", range(20, 40), 8)]
        epoch_fn, per_epoch = reader_epochs(
            paths, 4, np.int32, (2,), shuffle=True, seed=3,
            process_index=0, process_count=1)
        assert per_epoch == 10
        e0 = [b.copy() for b in epoch_fn(0)]
        e0_again = [b.copy() for b in epoch_fn(0)]
        e1 = [b.copy() for b in epoch_fn(1)]
        for x, y in zip(e0, e0_again):           # same epoch → same order
            np.testing.assert_array_equal(x, y)
        flat0 = np.concatenate(e0)[:, 0]
        flat1 = np.concatenate(e1)[:, 0]
        assert sorted(flat0) == sorted(flat1) == list(range(40))
        assert list(flat0) != list(flat1)        # epoch 1 reshuffled

    def test_short_tail_midstream_across_prefetch_boundary(self, tmp_path,
                                                           caplog):
        # f1 carries a short tail MID-STREAM; f2's full records must still
        # arrive through the prefetcher, and the batch count must agree
        # with full_records_in_split's size-derived budget.
        paths = [
            _write_records(tmp_path / "f0.bin", [0, 1, 2], 8),
            _write_records(tmp_path / "f1.bin", [3, 4], 8, tail=b"xyz"),
            _write_records(tmp_path / "f2.bin", [5, 6, 7], 8),
        ]
        epoch_fn, per_epoch = reader_epochs(
            paths, 2, np.int32, (2,), shuffle=False, seed=0,
            process_index=0, process_count=1)
        assert per_epoch == 4                    # 8 full records // 2
        with caplog.at_level(logging.WARNING, logger="tony_tpu.io.jax_feed"):
            with DevicePrefetcher(epoch_fn, epochs=1) as pf:
                out = [np.asarray(b) for b in pf]
        assert len(out) == 4
        assert list(np.concatenate(out)[:, 0]) == list(range(8))
        tails = [r for r in caplog.records if "short tail" in r.message]
        assert len(tails) == 1


# ---------------------------------------------------------------------------
# satellites: memoized dp-rank
# ---------------------------------------------------------------------------

def test_data_parallel_rank_memoized_per_mesh():
    from tony_tpu.models import train
    from tony_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": -1})
    train._data_parallel_rank_cached.cache_clear()
    r1 = train.data_parallel_rank(mesh)
    misses = train._data_parallel_rank_cached.cache_info().misses
    r2 = train.data_parallel_rank(mesh)
    info = train._data_parallel_rank_cached.cache_info()
    assert r1 == r2 == 0                         # single process
    assert info.misses == misses and info.hits >= 1   # second call cached
    # a different axes tuple is its own entry, not a stale hit
    assert train.data_parallel_rank(mesh, axes=("dp",)) == 0
    assert train._data_parallel_rank_cached.cache_info().misses == misses + 1
