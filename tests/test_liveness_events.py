"""Unit tests: heartbeat monitor + event log (mirrors TestEventHandler,
TestHistoryFileUtils, TestParserUtils in the reference)."""

import os
import threading
import time

from tony_tpu.cluster.liveness import HeartbeatMonitor
from tony_tpu.events.events import (EventHandler, JobMetadata, find_job_files,
                                    history_file_name,
                                    is_valid_history_file_name, parse_events)


def test_monitor_expires_silent_task():
    dead = []
    fired = threading.Event()

    def on_dead(tid):
        dead.append(tid)
        fired.set()

    m = HeartbeatMonitor(hb_interval_ms=50, max_missed=3, on_expired=on_dead)
    m.start()
    m.register("worker:0")
    m.register("worker:1")
    stop_pinger = threading.Event()

    def pinger():
        while not stop_pinger.wait(0.05):
            m.ping("worker:1")

    t = threading.Thread(target=pinger, daemon=True)
    t.start()
    assert fired.wait(timeout=3.0)
    time.sleep(0.3)   # give a wrongly-expiring worker:1 a chance to fire
    stop_pinger.set()
    m.stop()
    assert dead == ["worker:0"]   # fired once, only for the silent task


def test_monitor_unregister_prevents_expiry():
    dead = []
    m = HeartbeatMonitor(hb_interval_ms=50, max_missed=3,
                         on_expired=dead.append)
    m.start()
    m.register("worker:0")
    m.unregister("worker:0")      # completed normally
    time.sleep(0.5)
    m.stop()
    assert dead == []


def test_monitor_reset_forgets_tasks():
    dead = []
    m = HeartbeatMonitor(hb_interval_ms=50, max_missed=3,
                         on_expired=dead.append)
    m.start()
    m.register("worker:0")
    m.reset()                     # session retry
    time.sleep(0.5)
    m.stop()
    assert dead == []


def test_history_file_name_codec():
    name = history_file_name("app_1_2", 1000, "alice", completed_ms=2000,
                             status="SUCCEEDED")
    assert name == "app_1_2-1000-2000-alice-SUCCEEDED.jhist"
    md = JobMetadata.from_file_name(name)
    assert (md.app_id, md.started_ms, md.completed_ms, md.user, md.status) == \
        ("app_1_2", 1000, 2000, "alice", "SUCCEEDED")
    inprog = history_file_name("app_1_2", 1000, "alice", in_progress=True)
    assert inprog.endswith(".jhist.inprogress")
    assert JobMetadata.from_file_name(inprog).in_progress
    assert is_valid_history_file_name(name)
    assert not is_valid_history_file_name("random.txt")
    assert not is_valid_history_file_name("x-notanumber-user.jhist")


def test_history_file_name_hyphenated_user():
    """Regression: USER=john-doe (or a leading-digit user) must round-trip —
    the old regex rejected hyphens, making such jobs invisible to the
    history server."""
    for user in ("john-doe", "4dmin", "a-b-c"):
        name = history_file_name("application_1_2", 1000, user,
                                 completed_ms=2000, status="SUCCEEDED")
        md = JobMetadata.from_file_name(name)
        assert md is not None and md.user == user
        assert (md.app_id, md.started_ms, md.completed_ms, md.status) == \
            ("application_1_2", 1000, 2000, "SUCCEEDED")
        inprog = history_file_name("application_1_2", 1000, user,
                                   in_progress=True)
        md2 = JobMetadata.from_file_name(inprog)
        assert md2 is not None and md2.user == user and md2.in_progress


def test_history_file_name_digit_leading_user_inprogress():
    """Regression: USER=007-james in an in-progress name — the regex used to
    steal the leading digits as completed_ms; completion preceding start is
    impossible, so the parser must fold them back into the user."""
    started = 1_700_000_000_000
    name = history_file_name("application_1_2", started, "007-james",
                             in_progress=True)
    md = JobMetadata.from_file_name(name)
    assert md.user == "007-james" and md.completed_ms is None
    assert md.started_ms == started and md.in_progress


def test_event_handler_roundtrip(tmp_path):
    h = EventHandler(str(tmp_path), "app_9", "bob")
    h.start()
    h.emit("APPLICATION_INITED", app_id="app_9", num_tasks=2)
    h.emit("TASK_FINISHED", task="worker:0", exit_code=0)
    final = h.stop("SUCCEEDED")
    assert os.path.exists(final) and final.endswith(".jhist")
    assert not any(f.endswith(".inprogress") for f in os.listdir(tmp_path))
    events = parse_events(final)
    assert [e.event_type for e in events] == ["APPLICATION_INITED",
                                              "TASK_FINISHED"]
    assert events[0].payload["num_tasks"] == 2
    assert events[0].timestamp > 0
    assert find_job_files(str(tmp_path)) == [final]


def test_emit_after_stop_drops_with_warning(tmp_path, caplog):
    """emit() after stop() used to enqueue silently into a dead queue —
    the event vanished with no trace. It must now warn and drop, and the
    final file must not grow."""
    import logging
    h = EventHandler(str(tmp_path), "app_10", "bob")
    h.start()
    h.emit("APPLICATION_INITED", app_id="app_10")
    final = h.stop("SUCCEEDED")
    size = os.path.getsize(final)
    with caplog.at_level(logging.WARNING, logger="tony_tpu.events.events"):
        h.emit("TASK_FINISHED", task="worker:0", exit_code=0)
    assert any("after stop()" in r.message for r in caplog.records)
    assert os.path.getsize(final) == size
    assert [e.event_type for e in parse_events(final)] == [
        "APPLICATION_INITED"]


def test_stop_is_idempotent(tmp_path):
    h = EventHandler(str(tmp_path), "app_11", "bob")
    h.start()
    h.emit("APPLICATION_INITED", app_id="app_11")
    first = h.stop("SUCCEEDED")
    second = h.stop("FAILED")           # second verdict must not re-rename
    assert first == second == h.final_path
    assert os.path.exists(first)
    assert len(os.listdir(tmp_path)) == 1


def test_stop_retryable_after_failed_rename(tmp_path):
    """A transient storage error during stop()'s rename must not latch
    the handler as finished: emits stay refused, but a retried stop()
    re-attempts the move instead of returning a path that was never
    created."""
    h = EventHandler(str(tmp_path), "app_12", "bob")
    h.start()
    h.emit("APPLICATION_INITED", app_id="app_12")
    real_move = h._storage.move
    calls = []

    def flaky_move(src, dst):
        calls.append(dst)
        if len(calls) == 1:
            raise OSError("transient backend flake")
        return real_move(src, dst)

    h._storage.move = flaky_move
    try:
        import pytest
        with pytest.raises(OSError):
            h.stop("SUCCEEDED")
        assert h.final_path is None           # nothing reported as final
        h.emit("TASK_FINISHED", task="w:0")   # still refused (closed)
        final = h.stop("SUCCEEDED")           # retry re-attempts the move
    finally:
        h._storage.move = real_move
    assert os.path.exists(final) and final.endswith(".jhist")
    assert len(calls) == 2


def test_jhist_filename_codec_fuzz():
    """Fuzz the filename codec over hyphenated/digit-leading users and
    every completed/status/in-progress combination: round-trip
    history_file_name → from_file_name must reproduce the metadata, with
    the ONE documented ambiguity rule (a trailing all-digit token smaller
    than started_ms is part of the user, not a completed_ms — completion
    cannot precede start)."""
    import random
    rng = random.Random(0xC0DEC)
    letters = "abcdefghijklmnopqrstuvwxyz"
    statuses = [None, "SUCCEEDED", "FAILED", "KILLED", "RUNNING"]

    def rand_user():
        # segments joined by hyphens; digit-leading allowed, and at least
        # one letter somewhere (an ALL-digit user is inherently ambiguous
        # with completed_ms in this reference-inherited codec)
        segs = []
        for _ in range(rng.randint(1, 4)):
            seg = "".join(rng.choice(letters + "0123456789_")
                          for _ in range(rng.randint(1, 6)))
            segs.append(seg)
        user = "-".join(segs)
        if not any(ch in letters for ch in user):
            user += rng.choice(letters)
        return user

    for trial in range(500):
        app_id = f"application_{rng.randint(1, 10**13)}_{rng.randint(0, 9999):04d}"
        started = rng.randint(1_600_000_000_000, 1_900_000_000_000)
        completed = (started + rng.randint(0, 10**9)
                     if rng.random() < 0.5 else None)
        status = rng.choice(statuses)
        in_progress = completed is None and rng.random() < 0.5
        user = rand_user()
        name = history_file_name(app_id, started, user,
                                 completed_ms=completed, status=status,
                                 in_progress=in_progress)
        md = JobMetadata.from_file_name(name)
        assert md is not None, name
        assert (md.app_id, md.started_ms, md.user, md.completed_ms,
                md.status, md.in_progress) == \
            (app_id, started, user, completed, status, in_progress), name
        assert is_valid_history_file_name(name)


def test_jhist_codec_digit_leading_user_all_variants():
    """The documented disambiguation pins digit-leading users in every
    (completed, status, inprogress) shape — including the regression
    shapes of the original fix."""
    # (a PURELY numeric user like "7" is excluded: with a status token
    # and no completed_ms it is inherently ambiguous with completed_ms
    # in this reference-inherited codec — the documented limitation)
    for user in ("007-james", "99-44-x", "4dmin-2", "7x"):
        for completed in (None, 1_700_000_000_999):
            for status in (None, "SUCCEEDED"):
                name = history_file_name(
                    "application_1_2", 1_700_000_000_000, user,
                    completed_ms=completed, status=status)
                md = JobMetadata.from_file_name(name)
                assert md is not None, name
                assert (md.user, md.completed_ms, md.status) == \
                    (user, completed, status), name


def test_parse_skips_malformed_lines(tmp_path):
    p = tmp_path / "a-1-2-u-SUCCEEDED.jhist"
    p.write_text('{"event_type": "X", "payload": {}, "timestamp": 1}\n'
                 'garbage\n'
                 '{"event_type": "Y", "payload": {}, "timestamp": 2}\n')
    assert [e.event_type for e in parse_events(str(p))] == ["X", "Y"]


def test_background_threads_carry_tony_names(tmp_path):
    """TL003 behaviorally: the monitor's and event handler's threads are
    tony-* named daemons, so stacks/py-spy/flight dumps attribute them."""
    m = HeartbeatMonitor(hb_interval_ms=50, max_missed=3,
                         on_expired=lambda tid: None)
    m.start()
    try:
        assert m._thread.name == "tony-hb-monitor"
        assert m._thread.daemon
    finally:
        m.stop()
    h = EventHandler(str(tmp_path), "application_1_1", "alice")
    h.start()
    try:
        assert h._thread.name == "tony-event-handler"
        assert h._thread.daemon
    finally:
        h.stop("SUCCEEDED")
