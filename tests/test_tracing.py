"""Tracing plane + flight recorder units: span API and sampling, wire
codec validation, spool bridge, clock-offset estimation (pinned against
an injected skewed-clock beat), Chrome renderer invariants, flight
dumps (incl. channel torn-frame scoping), deterministic pipeline trace
ids, and the metric-series ↔ docs bijection."""

import json
import os
import socket
import threading
import time

import pytest

from tony_tpu.runtime import metrics as M
from tony_tpu.runtime import tracing as T


@pytest.fixture
def tracer():
    tr = T.Tracer(proc="test:0", sample_rate=1.0, ring_size=256)
    saved = T.set_tracer(tr)
    yield tr
    T.set_tracer(saved)


@pytest.fixture
def flight(tmp_path):
    fl = T.FlightRecorder(proc="test:0", ring_size=32,
                          dir_path=str(tmp_path))
    saved = T.set_flight(fl)
    yield fl
    T.set_flight(saved)


# ---------------------------------------------------------------------------
# Span API
# ---------------------------------------------------------------------------
class TestSpanAPI:
    def test_nesting_and_parent_links(self, tracer):
        with tracer.span("outer", k="v") as outer:
            with tracer.span("inner"):
                pass
        inner, out = tracer.drain()
        assert (inner["n"], out["n"]) == ("inner", "outer")
        assert inner["tid"] == out["tid"]
        assert inner["pid"] == out["sid"]
        assert out["pid"] == ""                   # root
        assert out["a"] == {"k": "v"}
        assert out["proc"] == "test:0"
        assert out["d"] >= inner["d"] >= 0

    def test_remote_ctx_joins_trace_and_head_sampling_wins(self):
        # rate 0: local roots never sample, but a REMOTE ctx means the
        # head already decided — the child must record
        tr = T.Tracer(proc="t", sample_rate=0.0)
        assert not tr.start_span("local-root").recording
        child = tr.start_span("remote-child",
                              ctx={"tid": "ab" * 16, "sid": "cd" * 8})
        assert child.recording
        child.end()
        (got,) = tr.drain()
        assert got["tid"] == "ab" * 16 and got["pid"] == "cd" * 8

    def test_coarse_bypasses_sampling(self):
        tr = T.Tracer(proc="t", sample_rate=0.0)
        with tr.span("job", coarse=True) as sp:
            assert sp.recording
        assert len(tr.drain()) == 1

    def test_disabled_tracer_is_all_noop(self):
        tr = T.Tracer(proc="t", enabled=False)
        with tr.span("a", coarse=True) as sp:
            assert not sp.recording
        tr.record_span("b", 0.5)
        assert tr.drain() == []

    def test_unsampled_parent_suppresses_children(self):
        tr = T.Tracer(proc="t", sample_rate=0.0)
        with tr.span("root") as root:
            assert not root.recording
            with tr.span("child") as child:
                assert not child.recording
        assert tr.drain() == []

    def test_unsampled_ambient_span_never_spawns_orphan_roots(self):
        """Head sampling is ONE decision per trace: a child opened
        inside an unsampled step must not re-roll the dice as its own
        root (at rate 0.5 that would double the sampled overhead and
        litter the trace with parentless orphans)."""
        tr = T.Tracer(proc="t", sample_rate=0.5)
        for _ in range(200):
            with tr.span("step"):
                with tr.span("child"):
                    pass
        spans = tr.drain(10_000)
        steps = [s for s in spans if s["n"] == "step"]
        children = [s for s in spans if s["n"] == "child"]
        assert len(children) == len(steps)
        assert all(c["pid"] for c in children)        # no orphan roots

    def test_ids_immune_to_user_seeding(self):
        """Training scripts seed the global RNG identically on every
        worker; trace/span ids must not collide because of it."""
        import random as _random
        _random.seed(42)
        a = (T.new_trace_id(), T.new_span_id())
        _random.seed(42)
        b = (T.new_trace_id(), T.new_span_id())
        assert a[0] != b[0] and a[1] != b[1]

    def test_end_is_idempotent(self, tracer):
        sp = tracer.start_span("once")
        sp.end()
        sp.end()
        assert len(tracer.drain()) == 1

    def test_record_span_explicit_ids(self, tracer):
        tracer.record_span("x", 0.25, trace_id="aa" * 16,
                           span_id="bb" * 8, parent_id="cc" * 8, k=1)
        (got,) = tracer.drain()
        assert (got["tid"], got["sid"], got["pid"]) == \
            ("aa" * 16, "bb" * 8, "cc" * 8)
        assert abs(got["d"] - 0.25) < 1e-9
        assert got["a"] == {"k": 1}

    def test_pending_overflow_drops_oldest_and_counts(self):
        saved = M.set_default(M.MetricsRegistry())
        try:
            tr = T.Tracer(proc="t", sample_rate=1.0, ring_size=16)
            for i in range(40):
                tr.record_span(f"s{i}", 0.0)
            pending = tr.drain(max_spans=1000)
            assert len(pending) == 16
            assert pending[0]["n"] == "s24"       # oldest dropped
            assert tr.dropped == 24
        finally:
            M.set_default(saved)

    def test_ring_keeps_recent_regardless_of_drain(self, tracer):
        tracer.record_span("keep", 0.0)
        tracer.drain()
        assert [s["n"] for s in tracer.recent()] == ["keep"]


# ---------------------------------------------------------------------------
# Wire codec
# ---------------------------------------------------------------------------
class TestWireCodec:
    def test_round_trip(self, tracer):
        with tracer.span("a", attr="x"):
            pass
        spans = tracer.drain()
        obj = T.parse_batch_json(T.encode_batch(spans))
        assert obj["s"] == spans

    @pytest.mark.parametrize("bad", [
        "not json",
        "[]",                                       # not an object
        '{"s": "nope"}',
        '{"s": [42]}',
        '{"s": [{}]}',                              # missing ids
        '{"s": [{"tid": "zz", "sid": "ab", "n": "x", "ts": 1, "d": 1}]}',
        '{"s": [{"tid": "ab", "sid": "ab", "n": "", "ts": 1, "d": 1}]}',
        '{"s": [{"tid": "ab", "sid": "ab", "n": "x", "ts": "t", "d": 1}]}',
        '{"s": [{"tid": "ab", "sid": "ab", "n": "x", "ts": 1, "d": 1,'
        ' "a": 5}]}',
        '{"s": [{"tid": "ab", "sid": "ab", "n": "x", "ts": 1, "d": 1,'
        ' "a": {"k": []}}]}',
        '{"s": [], "f": 7}',
        '{"s": [], "f": {"events": "x"}}',
    ])
    def test_malformed_batches_raise(self, bad):
        with pytest.raises(ValueError):
            T.parse_batch_json(bad)

    def test_flight_tail_rides_batch(self, flight, tracer):
        flight.record("boom", code=3)
        batch = T.encode_batch([], flight=flight.ship_tail("boom"))
        obj = T.parse_batch_json(batch)
        assert obj["f"]["events"][-1]["kind"] == "boom"


# ---------------------------------------------------------------------------
# Spool bridge (user process -> executor)
# ---------------------------------------------------------------------------
class TestSpool:
    def test_incremental_read(self, tmp_path):
        path = str(tmp_path / "spool.jsonl")
        tr = T.Tracer(proc="child", sample_rate=1.0, spool_path=path)
        reader = T.SpoolReader(path)
        with tr.span("one"):
            pass
        assert [s["n"] for s in reader.read_new()] == ["one"]
        assert reader.read_new() == []
        with tr.span("two"):
            pass
        with tr.span("three"):
            pass
        assert [s["n"] for s in reader.read_new()] == ["two", "three"]
        tr.close()

    def test_partial_trailing_line_waits(self, tmp_path):
        path = str(tmp_path / "spool.jsonl")
        full = json.dumps({"tid": "ab", "sid": "cd", "n": "x",
                           "ts": 1.0, "d": 0.1, "proc": "p", "a": {}})
        with open(path, "w") as f:
            f.write(full + "\n" + full[: len(full) // 2])
        reader = T.SpoolReader(path)
        assert len(reader.read_new()) == 1
        assert reader.read_new() == []            # half a line: wait
        with open(path, "a") as f:
            f.write(full[len(full) // 2:] + "\n")
        assert len(reader.read_new()) == 1        # completed now

    def test_malformed_lines_skipped(self, tmp_path):
        path = str(tmp_path / "spool.jsonl")
        good = json.dumps({"tid": "ab", "sid": "cd", "n": "ok",
                           "ts": 1.0, "d": 0.1, "proc": "p", "a": {}})
        with open(path, "w") as f:
            f.write("GARBAGE\n" + good + "\n{\"tid\": 1}\n")
        assert [s["n"] for s in T.SpoolReader(path).read_new()] == ["ok"]

    def test_missing_file_is_empty(self, tmp_path):
        assert T.SpoolReader(str(tmp_path / "absent")).read_new() == []

    def test_rotate_truncates_consumed_spool(self, tmp_path):
        """The spool FILE is bounded: once the reader has consumed
        everything, rotation truncates it to zero — and the writer's
        append-mode handle keeps working across the truncation."""
        path = str(tmp_path / "spool.jsonl")
        tr = T.Tracer(proc="child", sample_rate=1.0, spool_path=path)
        reader = T.SpoolReader(path)
        with tr.span("one"):
            pass
        assert len(reader.read_new()) == 1
        reader.maybe_rotate()
        assert os.path.getsize(path) == 0
        with tr.span("two"):                # same open writer handle
            pass
        assert [s["n"] for s in reader.read_new()] == ["two"]
        tr.close()

    def test_rotate_skips_runaway_backlog(self, tmp_path, monkeypatch):
        path = str(tmp_path / "spool.jsonl")
        monkeypatch.setattr(T.SpoolReader, "MAX_BACKLOG_BYTES", 64)
        good = json.dumps({"tid": "ab", "sid": "cd", "n": "old",
                           "ts": 1.0, "d": 0.1, "proc": "p", "a": {}})
        with open(path, "w") as f:
            for _ in range(50):
                f.write(good + "\n")
        reader = T.SpoolReader(path)
        reader.maybe_rotate()               # backlog > bound: skip + drop
        assert os.path.getsize(path) == 0
        assert reader.read_new() == []


# ---------------------------------------------------------------------------
# Clock offset (the satellite: skew visibility independent of tracing)
# ---------------------------------------------------------------------------
class TestClockOffset:
    def test_rtt_midpoint_estimate(self):
        # client clock 5 s BEHIND the server, 200 ms round trip: the
        # beat stamped t-5 arrives rtt/2 after send
        now = 1000.0
        sent_client_clock = now - 5.0 - 0.1      # send was rtt/2 ago
        off = T.clock_offset(sent_client_clock, 0.2, server_unix_time=now)
        assert abs(off - 5.0) < 1e-9

    def test_apply_offset_shifts_ts_only(self):
        spans = [{"tid": "ab", "sid": "cd", "n": "x", "ts": 10.0,
                  "d": 1.0, "proc": "p", "a": {}}]
        out = T.apply_offset(spans, 2.5)
        assert out[0]["ts"] == 12.5 and spans[0]["ts"] == 10.0
        assert T.apply_offset(spans, 0.0) is spans

    def test_coordinator_pins_injected_skewed_beat(self, tmp_path,
                                                   monkeypatch):
        """The coordinator's RTT-midpoint estimate lands on the metrics
        plane: a beat whose clock is injected 7 s behind (with a 400 ms
        measured RTT) must produce tony_clock_offset_seconds ≈ 7.2 —
        and the offset must be APPLIED to that task's exported span
        timestamps."""
        monkeypatch.chdir(tmp_path)
        from tony_tpu.cluster.coordinator import Coordinator, CoordinatorRpc
        from tony_tpu.conf.config import TonyConfig
        saved = M.set_default(M.MetricsRegistry())
        conf = TonyConfig({
            "tony.worker.instances": "1",
            "tony.history.location": str(tmp_path / "hist")})
        co = Coordinator(conf, "application_trace_skew", str(tmp_path))
        try:
            rpc = CoordinatorRpc(co)
            skew, rtt = 7.0, 0.4
            span = {"tid": "ab" * 16, "sid": "cd" * 8, "n": "w.step",
                    "ts": time.time() - skew, "d": 0.5, "proc": "worker:0",
                    "a": {}}
            rpc.task_executor_heartbeat(
                "worker:0", "", spans=T.encode_batch([span]),
                client_time=time.time() - skew - rtt / 2,
                client_rtt=rtt)
            est = co.clock_offsets["worker:0"]
            assert abs(est - skew) < 0.3, est
            gauge = M.get_default().gauge("tony_clock_offset_seconds",
                                          task="worker:0")
            assert abs(gauge.value - est) < 1e-9
            # offset applied at export: the emitted span ts is back on
            # the coordinator's clock
            co._emit_trace_events()
            emitted = [e for e in _drain_event_queue(co.events)
                       if e.event_type == "TRACE_SPAN"
                       and e.payload["task"] == "worker:0"]
            assert emitted, "no TRACE_SPAN emitted"
            got = emitted[-1].payload["spans"][0]
            assert abs(got["ts"] - (span["ts"] + est)) < 1e-6
            assert abs(emitted[-1].payload["offset_s"] - est) < 1e-6
        finally:
            co.rpc_server.stop(0)
            M.set_default(saved)

    def test_retried_beat_batch_deduped(self, tmp_path, monkeypatch):
        """A lost heartbeat ACK makes the sender RETRY the identical
        request; the batch id must stop the re-delivered span batch
        from being appended twice."""
        monkeypatch.chdir(tmp_path)
        from tony_tpu.cluster.coordinator import Coordinator, CoordinatorRpc
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({
            "tony.worker.instances": "1",
            "tony.history.location": str(tmp_path / "hist")})
        co = Coordinator(conf, "application_trace_dedup", str(tmp_path))
        try:
            rpc = CoordinatorRpc(co)
            span = {"tid": "ab" * 16, "sid": "cd" * 8, "n": "x",
                    "ts": 1.0, "d": 0.1, "proc": "worker:0", "a": {}}
            batch = T.encode_batch([span])
            rpc.task_executor_heartbeat("worker:0", "", spans=batch,
                                        client_time=time.time())
            rpc.task_executor_heartbeat("worker:0", "", spans=batch,
                                        client_time=time.time())
            with co._trace_lock:
                assert len(co._trace_pending) == 1
            # a NEW batch (fresh id) still lands
            rpc.task_executor_heartbeat("worker:0", "",
                                        spans=T.encode_batch([span]),
                                        client_time=time.time())
            with co._trace_lock:
                assert len(co._trace_pending) == 2
        finally:
            co.rpc_server.stop(0)

    def test_malformed_span_batch_never_costs_the_ping(self, tmp_path,
                                                       monkeypatch):
        monkeypatch.chdir(tmp_path)
        from tony_tpu.cluster.coordinator import Coordinator, CoordinatorRpc
        from tony_tpu.conf.config import TonyConfig
        conf = TonyConfig({
            "tony.worker.instances": "1",
            "tony.history.location": str(tmp_path / "hist")})
        co = Coordinator(conf, "application_trace_garbage", str(tmp_path))
        try:
            rpc = CoordinatorRpc(co)
            for garbage in ("NOT JSON", "[]", '{"s": [{}]}',
                            '{"s": [{"tid": 5}]}', "\x00\xff"):
                ack = rpc.task_executor_heartbeat(
                    "worker:0", "", spans=garbage,
                    client_time=time.time(), client_rtt=0.01)
                assert ack is not None             # the ping survived
            assert co.trace_rejects == 5
            with co._trace_lock:
                assert co._trace_pending == []
        finally:
            co.rpc_server.stop(0)


def _drain_event_queue(handler):
    """Peek the EventHandler's queued (not yet started) events."""
    out = []
    while not handler._queue.empty():
        e = handler._queue.get_nowait()
        if e is not None:
            out.append(e)
    return out


# ---------------------------------------------------------------------------
# Chrome renderer
# ---------------------------------------------------------------------------
class TestChromeRenderer:
    def test_invariants(self, tracer):
        with tracer.span("req", kind="serve"):
            with tracer.span("inner"):
                pass
        other = T.Tracer(proc="other:1", sample_rate=1.0)
        with other.span("peer"):
            pass
        spans = tracer.drain() + other.drain()
        chrome = json.loads(json.dumps(T.to_chrome(spans)))
        events = chrome["traceEvents"]
        assert chrome["displayTimeUnit"] == "ms"
        xs = [e for e in events if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"req", "inner", "peer"}
        for e in xs:
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
            assert e["ts"] >= 0 and e["dur"] >= 0
            assert e["args"]["trace_id"] and e["args"]["span_id"]
        # one pid per process, named by metadata
        names = {e["args"]["name"] for e in events
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert names == {"test:0", "other:1"}
        # two traces in test:0's process? no — req/inner share a trace,
        # peer is another process: distinct (pid, trace) tracks
        req = next(e for e in xs if e["name"] == "req")
        inner = next(e for e in xs if e["name"] == "inner")
        peer = next(e for e in xs if e["name"] == "peer")
        assert (req["pid"], req["tid"]) == (inner["pid"], inner["tid"])
        assert peer["pid"] != req["pid"]

    def test_empty(self):
        assert T.to_chrome([]) == {"traceEvents": [],
                                   "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def test_dump_final_entries_record_incident(self, tmp_path, tracer):
        fl = T.FlightRecorder(proc="w:0", ring_size=8,
                              dir_path=str(tmp_path))
        for i in range(20):
            fl.record("step", step=i)
        fl.record("gang_lost", error="peer died")
        path = fl.dump("gang_lost", step=19)
        doc = json.load(open(path))
        assert doc["proc"] == "w:0" and doc["reason"] == "gang_lost"
        kinds = [e["kind"] for e in doc["events"]]
        assert kinds[-1] == "flight_dump" and kinds[-2] == "gang_lost"
        assert len(doc["events"]) <= 8 + 1            # ring bound held
        assert isinstance(doc["spans"], list)

    def test_dump_includes_tracer_ring(self, tmp_path, tracer):
        with tracer.span("before-crash"):
            pass
        fl = T.FlightRecorder(proc="w:0", dir_path=str(tmp_path))
        doc = json.load(open(fl.dump("boom")))
        assert any(s["n"] == "before-crash" for s in doc["spans"])

    def test_dump_quota_is_per_reason(self, tmp_path, tracer):
        """Externally-triggerable dumps (protocol_error floods) must not
        starve a later genuine incident's dump."""
        fl = T.FlightRecorder(proc="w:0", dir_path=str(tmp_path))
        spam = [fl.dump("protocol_error")
                for _ in range(T.MAX_DUMPS_PER_REASON + 5)]
        assert sum(p is not None for p in spam) == T.MAX_DUMPS_PER_REASON
        # a DIFFERENT reason still dumps after the flood
        assert fl.dump("gang_lost") is not None

    def test_dump_process_backstop(self, tmp_path, tracer):
        fl = T.FlightRecorder(proc="w:0", dir_path=str(tmp_path))
        written = sum(fl.dump(f"reason{i}") is not None
                      for i in range(T.MAX_DUMPS_PER_PROCESS + 8))
        assert written == T.MAX_DUMPS_PER_PROCESS

    def test_record_never_raises_on_weird_values(self, flight):
        flight.record("odd", obj=object(), none=None, f=1.5)
        (entry,) = flight.tail(1)
        assert entry["kind"] == "odd" and entry["none"] is None
        assert entry["obj"].startswith("<object")

    def test_torn_channel_frame_dumps_scoped_to_offender(self, tmp_path,
                                                         tracer):
        """The chaos satellite's torn-frame leg in unit form: a garbage
        tensor frame makes the hub dump ONE postmortem naming the
        offending peer; a healthy channel on the same hub keeps
        delivering and triggers no dump."""
        import numpy as np

        from tony_tpu.channels.channel import (CH_MAGIC, CH_HELLO,
                                               CH_TENSOR, ChannelHub,
                                               ChannelSender)
        from tony_tpu.serving.protocol import encode_frame, send_frame
        saved = T.set_flight(T.FlightRecorder(proc="hub:0", ring_size=32,
                                              dir_path=str(tmp_path)))
        try:
            hub = ChannelHub(registry=M.MetricsRegistry())
            port = hub.start()
            recv = hub.receiver("good")
            sender = ChannelSender(f"127.0.0.1:{port}", "good",
                                   registry=M.MetricsRegistry())
            # offender: valid handshake, then a torn CH_TENSOR frame
            bad = socket.create_connection(("127.0.0.1", port))
            bad.sendall(CH_MAGIC)
            send_frame(bad, CH_HELLO, 0, b'{"v":1,"channel":"evil"}')
            deadline = time.monotonic() + 5
            while not any(f.startswith("flight-")
                          for f in os.listdir(str(tmp_path))) \
                    and time.monotonic() < deadline:
                # CH_TENSOR with garbage payload (undecodable header)
                try:
                    bad.sendall(encode_frame(CH_TENSOR, 0,
                                             b"\xff\xff\xff\xff"))
                except OSError:
                    break
                time.sleep(0.05)
            bad.close()
            # the healthy channel still works end to end
            sender.send(np.arange(4, dtype=np.float32), sync=True,
                        timeout=10)
            got = recv.recv(timeout=10)
            assert got.tolist() == [0.0, 1.0, 2.0, 3.0]
            dumps = [f for f in os.listdir(str(tmp_path))
                     if f.startswith("flight-")]
            assert dumps, "torn frame left no dump"
            doc = json.load(open(os.path.join(str(tmp_path), dumps[0])))
            assert doc["reason"] == "channel_protocol_error"
            assert any(e["kind"] == "channel_protocol_error"
                       for e in doc["events"])
            sender.close(drain=False)
            hub.stop()
        finally:
            T.set_flight(saved)


# ---------------------------------------------------------------------------
# Deterministic pipeline trace ids (in-process 2-stage harness)
# ---------------------------------------------------------------------------
class TestPipelineTracing:
    def test_stage_spans_share_deterministic_trace_id(self, tracer):
        import jax.numpy as jnp
        import numpy as np

        from tony_tpu.channels import open_local_pipeline
        from tony_tpu.parallel.pipeline import CrossSlicePipeline

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"])

        def loss_head(hp, out, tgt):
            return jnp.mean((out @ hp["wo"] - tgt) ** 2)

        rs = np.random.RandomState(0)
        dim, mb, m = 4, 2, 2
        links = open_local_pipeline(2, registry=M.MetricsRegistry())
        xs = jnp.asarray(rs.randn(m, mb, dim).astype(np.float32))
        tgts = jnp.asarray(rs.randn(m, mb, dim).astype(np.float32))
        params = [{"w": jnp.asarray(
            rs.randn(dim, dim).astype(np.float32))} for _ in range(2)]
        head = {"wo": jnp.asarray(rs.randn(dim, dim).astype(np.float32))}
        pipes = [CrossSlicePipeline(stage_fn, links[0]),
                 CrossSlicePipeline(stage_fn, links[1],
                                    loss_head=loss_head)]

        def run(stage):
            pipes[stage].value_and_grad(
                params[stage], num_microbatches=m,
                microbatches=xs if stage == 0 else None,
                head_params=head if stage == 1 else None,
                head_batches=tgts if stage == 1 else None)

        try:
            threads = [threading.Thread(target=run, args=(s,))
                       for s in (0, 1)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
        finally:
            for link in links:
                link.close()
        spans = tracer.drain(10_000)
        stage_spans = [s for s in spans if s["n"] == "pipeline.stage"]
        assert {s["a"]["stage"] for s in stage_spans} == {0, 1}
        tids = {s["tid"] for s in stage_spans}
        assert len(tids) == 1, tids            # one step, one trace id
        (tid,) = tids
        root_sid = T.deterministic_span_id(f"{tid}:root")
        assert all(s["pid"] == root_sid for s in stage_spans)
        roots = [s for s in spans if s["n"] == "pipeline.step"]
        assert len(roots) == 1 and roots[0]["sid"] == root_sid
        # microbatch spans tagged with matching channel seqs across the
        # act hop
        fwd = [s for s in spans if s["n"] == "pipeline.forward"]
        f0 = {s["a"]["mb"]: s["a"]["seq"] for s in fwd
              if s["a"]["stage"] == 0}
        f1 = {s["a"]["mb"]: s["a"]["seq"] for s in fwd
              if s["a"]["stage"] == 1}
        assert f0 and f0 == f1

    def test_deterministic_sample_agrees_across_parties(self):
        tid = T.deterministic_trace_id("job:step:5")
        assert T.deterministic_trace_id("job:step:5") == tid
        assert len(tid) == 32
        for rate in (0.0, 0.3, 1.0):
            a = T.deterministic_sample(tid, rate)
            b = T.deterministic_sample(tid, rate)
            assert a == b
        assert T.deterministic_sample(tid, 1.0)
        assert not T.deterministic_sample(tid, 0.0)
        # a fair split at 0.5 over many keys (loose bound)
        hits = sum(T.deterministic_sample(f"k{i}", 0.5)
                   for i in range(1000))
        assert 350 < hits < 650


# ---------------------------------------------------------------------------
# Metric-series / event-type ↔ docs bijections (the docs-enforcement
# satellite) — thin wrappers over tonylint's TL008 checker, which owns the
# one scanner implementation (tony_tpu/devtools/lint.py).
# ---------------------------------------------------------------------------
def test_metric_series_docs_bijection():
    """Every tony_* series registered anywhere under tony_tpu/ must have
    a row in docs/observability.md, and every documented series must be
    registered (the metrics-plane mirror of test_config's DEFAULTS-key
    enforcement) — a new series without an operator-facing description
    is a doc regression by construction. Enforced by tonylint TL008."""
    from tony_tpu.devtools import lint

    exact, _prefixes, _suffixes = lint.registered_series_names()
    assert exact, "series scan found nothing — the scanner regressed"
    # sanity: known series from several layers must be in the scan
    assert {"tony_serve_ttft_seconds", "tony_clock_offset_seconds",
            "tony_trace_spans_total",
            "tony_flight_dumps_total"} <= exact
    findings = lint.check_observability(facets=("metrics",))
    assert not findings, "\n".join(f.message for f in findings)


def test_event_types_docs_bijection():
    """Every declared jhist event type must have a row in
    docs/observability.md (and vice versa) — an event type without an
    operator-facing description is a doc regression by construction,
    exactly like an undocumented metric series. Enforced by tonylint
    TL008."""
    from tony_tpu.devtools import lint

    types = lint.declared_event_types()
    # sanity: the scanner still sees known types from several subsystems
    assert {"APPLICATION_INITED", "METRICS_SNAPSHOT", "TRACE_SPAN",
            "GOODPUT", "STRAGGLER_SUSPECTED",
            "COORDINATOR_RESTART"} <= types, types
    findings = lint.check_observability(facets=("events",))
    assert not findings, "\n".join(f.message for f in findings)
