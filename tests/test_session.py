"""Session state-machine tests (TonySession semantics, SURVEY.md §2.1/§3)."""

import json

from tony_tpu.cluster.session import (Session, SessionStatus, TaskStatus,
                                      next_session)
from tony_tpu.conf.config import TonyConfig


def make_conf(**extra):
    base = {"tony.worker.instances": "2", "tony.ps.instances": "1"}
    base.update(extra)
    return TonyConfig(base)


def test_task_layout_and_chief():
    s = Session(make_conf())
    assert {jt: len(ts) for jt, ts in s.tasks.items()} == {"worker": 2, "ps": 1}
    # no explicit chief type → worker:0 is chief
    assert s.is_chief("worker", 0)
    assert not s.is_chief("worker", 1)
    assert not s.is_chief("ps", 0)


def test_explicit_chief_type():
    s = Session(make_conf(**{"tony.chief.instances": "1"}))
    assert s.is_chief("chief", 0)
    assert not s.is_chief("worker", 0)


def test_gang_barrier_and_process_ids():
    s = Session(make_conf())
    assert s.register_task_spec("worker:1", "h1:1000") is None
    assert s.register_task_spec("ps:0", "h2:1000") is None
    payload = s.register_task_spec("worker:0", "h0:1000")
    assert payload is not None
    assert payload["num_processes"] == 3
    # chief (worker:0) is process 0 → hosts the jax.distributed coordinator
    assert s.process_id_of("worker:0") == 0
    assert payload["coordinator_address"] == "h0:1000"
    spec = json.loads(payload["cluster_spec"])
    assert spec == {"worker": ["h0:1000", "h1:1000"], "ps": ["h2:1000"]}
    # dense unique ids
    pids = sorted(t.process_id for t in s.all_tasks())
    assert pids == [0, 1, 2]
    # idempotent re-registration, stable ids
    again = s.register_task_spec("worker:1", "h1:1000")
    assert again == payload and s.process_id_of("worker:1") != 0


def test_completion_reduction_success():
    s = Session(make_conf())
    for tid in ("worker:0", "worker:1", "ps:0"):
        s.register_task_spec(tid, "h:1")
    s.on_task_completed("worker", 1, 0)
    assert not s.training_finished()          # worker:0 still running
    s.on_task_completed("worker", 0, 0)
    assert s.training_finished()              # ps untracked → not required
    assert s.status is SessionStatus.SUCCEEDED


def test_tracked_failure_fails_session():
    s = Session(make_conf())
    s.on_task_completed("worker", 1, 3)
    assert s.status is SessionStatus.FAILED
    assert "worker:1" in s.failure_message


def test_untracked_failure_ignored():
    s = Session(make_conf())
    s.on_task_completed("ps", 0, 1)
    assert s.status is SessionStatus.RUNNING


def test_chief_completion_short_circuits():
    s = Session(make_conf())
    s.on_task_completed("worker", 0, 0)       # chief succeeds
    assert s.status is SessionStatus.SUCCEEDED
    # worker:1 never finished — chief completion ends the job (reference :266-271)


def test_stale_session_events_ignored():
    s = Session(make_conf(), session_id=1)
    s.on_task_completed("worker", 0, 1, session_id=0)   # from previous attempt
    assert s.status is SessionStatus.RUNNING
    assert s.get_task("worker", 0).status is TaskStatus.NEW


def test_duplicate_completion_ignored():
    s = Session(make_conf())
    s.on_task_completed("worker", 1, 0)
    s.on_task_completed("worker", 1, 5)       # RPC result + process exit race
    assert s.get_task("worker", 1).exit_code == 0
    assert s.status is SessionStatus.RUNNING


def test_deemed_dead():
    s = Session(make_conf())
    s.on_task_deemed_dead("worker:1")
    assert s.status is SessionStatus.FAILED
    assert "heartbeat" in s.failure_message


def test_allocation_matching():
    s = Session(make_conf())
    t0 = s.next_allocation("worker")
    t1 = s.next_allocation("worker")
    assert (t0.index, t1.index) == (0, 1)
    assert s.next_allocation("worker") is None
    assert t0.status is TaskStatus.SCHEDULED
    assert t0.allocation_id != t1.allocation_id


def test_retry_session_versioning():
    s = Session(make_conf())
    s.on_task_completed("worker", 0, 1)
    s2 = next_session(s)
    assert s2.session_id == s.session_id + 1
    assert s2.status is SessionStatus.RUNNING
    assert all(t.status is TaskStatus.NEW for t in s2.all_tasks())


def test_mesh_spec_in_payload():
    s = Session(make_conf(**{"tony.application.mesh": "dp=2,tp=1"}))
    for tid in ("worker:0", "worker:1", "ps:0"):
        payload = s.register_task_spec(tid, "h:1")
    assert json.loads(payload["mesh_spec"]) == {
        "axes": {"dp": 2, "tp": 1}, "dcn_axes": {}}


def test_mesh_spec_multi_slice():
    """tony.{job}.slices=N ships slice metadata + DCN axes in mesh_spec."""
    s = Session(make_conf(**{
        "tony.worker.instances": "4",
        "tony.worker.slices": "2",
        "tony.application.mesh": "tp=-1",
        "tony.application.mesh.dcn": "dp=2",
    }))
    for tid in ("worker:0", "worker:1", "worker:2", "worker:3", "ps:0"):
        payload = s.register_task_spec(tid, "h:1")
    spec = json.loads(payload["mesh_spec"])
    assert spec["axes"] == {"tp": -1}
    assert spec["dcn_axes"] == {"dp": 2}
    # worker spans 2 slices of 2 hosts; ps (slices=1) carries no entry
    assert spec["slice_spec"] == {
        "worker": {"slices": 2, "hosts_per_slice": 2}}


def test_uptime_metrics_tracked_fraction():
    """North-star metric: tracked-task uptime fraction is computed from
    registration->completion windows (reference's Metric channel was always
    empty; TonyApplicationMaster.java:408-410)."""
    import time as _time
    from tony_tpu.conf.config import TonyConfig
    from tony_tpu.cluster.session import Session

    conf = TonyConfig({"tony.worker.instances": "2",
                       "tony.ps.instances": "1"})
    s = Session(conf)
    s.register_task_spec("worker:0", "h0:1")
    s.register_task_spec("worker:1", "h1:1")
    s.register_task_spec("ps:0", "h2:1")
    _time.sleep(0.05)
    s.on_task_completed("worker", 0, 0)
    s.on_task_completed("worker", 1, 0)
    m = s.uptime_metrics()
    assert set(m) == {"session_wall_s", "tracked_window_s", "task_uptime_s",
                      "tracked_uptime_fraction"}
    assert set(m["task_uptime_s"]) == {"worker:0", "worker:1", "ps:0"}
    assert m["task_uptime_s"]["worker:0"] > 0
    # Registered almost immediately after session start → fraction near 1;
    # ps is untracked and excluded from the fraction.
    assert 0.5 < m["tracked_uptime_fraction"] <= 1.0


def test_uptime_metrics_unregistered_task_is_zero():
    from tony_tpu.conf.config import TonyConfig
    from tony_tpu.cluster.session import Session

    s = Session(TonyConfig({"tony.worker.instances": "1"}))
    m = s.uptime_metrics()
    assert m["task_uptime_s"]["worker:0"] == 0.0
    assert m["tracked_uptime_fraction"] == 0.0


def test_uptime_fraction_omitted_when_no_tracked_tasks():
    """Single-node/notebook sessions schedule no tracked tasks; emitting
    a 0.0 fraction would render as a misleading '0.0%' uptime for a
    succeeded job — the metric must be absent instead."""
    from tony_tpu.conf.config import TonyConfig
    from tony_tpu.cluster.session import Session

    s = Session(TonyConfig({}))          # no job types at all
    m = s.uptime_metrics()
    assert "tracked_uptime_fraction" not in m
    assert m["task_uptime_s"] == {}

    # only-untracked job types behave the same
    s2 = Session(TonyConfig({"tony.ps.instances": "1"}))
    assert "tracked_uptime_fraction" not in s2.uptime_metrics()


def test_uptime_fraction_counts_never_registered_tracked_tasks():
    """A gang stuck at the barrier because one worker never came up is NOT
    100% uptime — the missing task zeroes into the denominator."""
    import time as _time
    from tony_tpu.conf.config import TonyConfig
    from tony_tpu.cluster.session import Session

    s = Session(TonyConfig({"tony.worker.instances": "2"}))
    s.register_task_spec("worker:0", "h0:1")   # worker:1 never registers
    _time.sleep(0.02)
    m = s.uptime_metrics()
    assert m["task_uptime_s"]["worker:1"] == 0.0
    assert m["tracked_uptime_fraction"] <= 0.51
