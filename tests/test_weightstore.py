"""Warm scale-up plane: content-addressed weight artifacts, the
chunked byte-blob lane, peer-to-peer pull against a live server, the
self-organizing fan-out, and the bench pins.

The load-bearing guarantees pinned here:

- the digest is a pure function of tree CONTENT (deterministic across
  processes; any flipped byte, renamed path, or dtype change moves it);
- a landing recomputes the digest and REFUSES mismatches — corruption
  is an error, never silently served weights;
- a ship-warmed replica's tokens are bit-identical to a
  storage-loaded one's, greedy AND sampled;
- a blob survives the channel's reconnect-with-seq-resume mid-transfer
  with zero duplicated and zero dropped bytes;
- warm fan-out reaches N replicas in O(log N) waves and a crashed
  seeder degrades to a storage load, never a wedged fleet.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp                                     # noqa: E402

from tony_tpu.channels.channel import (BLOB_CHUNK_MAGIC, ChannelError,
                                       ChannelHub, ChannelSender,
                                       _blob_frame)          # noqa: E402
from tony_tpu.models import transformer as T                 # noqa: E402
from tony_tpu.models.serve import ContinuousBatcher          # noqa: E402
from tony_tpu.runtime.metrics import MetricsRegistry         # noqa: E402
from tony_tpu.serving import blobcodec                       # noqa: E402
from tony_tpu.serving.protocol import ProtocolError          # noqa: E402
from tony_tpu.serving.server import ServingServer            # noqa: E402
from tony_tpu.serving.weightstore import (                   # noqa: E402
    WEIGHT_CHANNEL, WeightStore, dir_digest, flatten_tree,
    install_compile_cache, pack_compile_cache, pack_weights, peek_weights_meta,
    pull_weights, tree_digest, unflatten_tree, unpack_weights, warm_fanout,
    weights_rpc)

CFG = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.PRNGKey(0), CFG)


def _tree(seed=3):
    rng = np.random.RandomState(seed)
    return {"block": {"w": rng.randn(8, 16).astype(np.float32),
                      "b": rng.randn(16).astype(np.float32)},
            "head": [rng.randn(4).astype(np.float32),
                     rng.randint(0, 99, size=7).astype(np.int32)]}


# ---------------------------------------------------------------------------
# The content address
# ---------------------------------------------------------------------------
class TestTreeDigest:
    def test_flatten_round_trip(self):
        tree = _tree()
        flat = flatten_tree(tree)
        assert sorted(flat) == ["block/b", "block/w", "head/#0", "head/#1"]
        back = unflatten_tree(flat)
        assert isinstance(back["head"], list)
        np.testing.assert_array_equal(back["block"]["w"],
                                      tree["block"]["w"])
        np.testing.assert_array_equal(back["head"][1], tree["head"][1])

    def test_digest_is_content_only(self):
        d = tree_digest(_tree())
        assert len(d) == 64
        # dict order is irrelevant; an identically-valued rebuild agrees
        assert tree_digest(_tree()) == d
        # flat and nested forms agree (the wire ships flat)
        assert tree_digest(flatten_tree(_tree())) == d

    def test_digest_moves_on_any_change(self):
        base = tree_digest(_tree())
        flipped = _tree()
        flipped["block"]["w"][3, 7] += 1e-3
        assert tree_digest(flipped) != base
        renamed = _tree()
        renamed["block2"] = renamed.pop("block")
        assert tree_digest(renamed) != base
        recast = _tree()
        recast["block"]["b"] = recast["block"]["b"].astype(np.float64)
        assert tree_digest(recast) != base

    def test_digest_deterministic_across_processes(self):
        """The whole point of content addressing: two replicas that
        never spoke compute the SAME address for the same weights."""
        prog = (
            "import numpy as np, json, sys\n"
            "from tony_tpu.serving.weightstore import tree_digest\n"
            "rng = np.random.RandomState(3)\n"
            "tree = {'block': {'w': rng.randn(8, 16).astype(np.float32),"
            " 'b': rng.randn(16).astype(np.float32)},"
            " 'head': [rng.randn(4).astype(np.float32),"
            " rng.randint(0, 99, size=7).astype(np.int32)]}\n"
            "print(json.dumps(tree_digest(tree)))\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, "-c", prog], env=env,
                             capture_output=True, text=True, timeout=120,
                             cwd=os.path.join(os.path.dirname(__file__),
                                              os.pardir))
        assert out.returncode == 0, out.stderr
        assert json.loads(out.stdout.strip()) == tree_digest(_tree())


# ---------------------------------------------------------------------------
# The artifact: pack / land / refuse
# ---------------------------------------------------------------------------
class TestWeightArtifact:
    def test_round_trip_bit_identical(self):
        tree = _tree()
        blob = pack_weights(tree, version="v1")
        meta = peek_weights_meta(blob)
        assert meta["part"] == "weights" and meta["version"] == "v1"
        assert meta["digest"] == tree_digest(tree)
        landed_meta, landed = unpack_weights(blob)
        assert landed_meta["digest"] == meta["digest"]
        for path, a in flatten_tree(tree).items():
            b = flatten_tree(landed)[path]
            assert a.dtype == b.dtype
            assert a.tobytes() == b.tobytes()   # BIT identical

    def test_flipped_byte_refused(self):
        blob = bytearray(pack_weights(_tree()))
        blob[-10] ^= 0x40                       # one bit, deep in payload
        with pytest.raises(ProtocolError, match="REFUSED"):
            unpack_weights(bytes(blob))

    def test_quantized_ship_dequantizes_to_exact_shipped_version(self):
        """The quantize-on-wire guard: the digest names the AS-SERVED
        (dequantized) tree on both ends, so what lands is bit-identical
        to what the packer would itself serve — or the transfer is
        refused. A quantized artifact is its own version: distinct
        digest from the full-precision artifact."""
        rng = np.random.RandomState(5)
        tree = {"w": rng.randn(64, 64).astype(np.float32),
                "ids": rng.randint(0, 99, size=16).astype(np.int32)}
        q = pack_weights(tree, version="v1", quantize=True)
        full = pack_weights(tree, version="v1")
        assert len(q) < len(full) / 2           # int8 on the wire
        qmeta = peek_weights_meta(q)
        assert qmeta["quantized"] and qmeta["digest"] != \
            peek_weights_meta(full)["digest"]
        meta, landed = unpack_weights(q)        # digest gate passed
        assert tree_digest(landed) == meta["digest"]
        # landing the same quantized artifact twice is bit-stable
        _, landed2 = unpack_weights(q)
        for path, a in flatten_tree(landed).items():
            assert a.tobytes() == flatten_tree(landed2)[path].tobytes()

    def test_quantized_tamper_refused(self):
        blob = bytearray(pack_weights(_tree(), quantize=True))
        blob[-5] ^= 0x01
        with pytest.raises(ProtocolError, match="REFUSED"):
            unpack_weights(bytes(blob))

    def test_store_put_get_verifies(self):
        reg = MetricsRegistry()
        store = WeightStore(reg)
        blob = pack_weights(_tree())
        digest = store.put(blob)
        assert store.get(digest) == blob
        assert store.digests() == [digest]
        assert reg.counter("tony_weight_installs_total").value == 1
        bad = bytearray(blob)
        bad[-3] ^= 0x10
        with pytest.raises(ProtocolError, match="REFUSED"):
            store.put(bytes(bad))


# ---------------------------------------------------------------------------
# One codec, three lanes: adversarial blobs re-pinned for every kind
# ---------------------------------------------------------------------------
class TestBlobCodecKinds:
    def _mk(self, codec):
        return codec.pack({"x": 1}, {"a": np.arange(6, dtype=np.float32)})

    @pytest.mark.parametrize("codec", [blobcodec.KV_ROW,
                                       blobcodec.PREFIX_TEMPLATE,
                                       blobcodec.WEIGHTS],
                             ids=lambda c: c.kind)
    def test_truncated_rejected(self, codec):
        blob = self._mk(codec)
        with pytest.raises(ProtocolError, match="truncated"):
            codec.unpack(blob[:len(blob) - 4])

    @pytest.mark.parametrize("codec", [blobcodec.KV_ROW,
                                       blobcodec.PREFIX_TEMPLATE,
                                       blobcodec.WEIGHTS],
                             ids=lambda c: c.kind)
    def test_trailing_garbage_rejected(self, codec):
        with pytest.raises(ProtocolError, match="trailing"):
            codec.unpack(self._mk(codec) + b"xx")

    @pytest.mark.parametrize("packer,lane", [
        (blobcodec.WEIGHTS, blobcodec.KV_ROW),
        (blobcodec.KV_ROW, blobcodec.PREFIX_TEMPLATE),
        (blobcodec.PREFIX_TEMPLATE, blobcodec.WEIGHTS),
    ], ids=["weights-on-kv", "kv-on-template", "template-on-weights"])
    def test_mistagged_kind_rejected_on_every_lane(self, packer, lane):
        """A kv row can never land as weights (and every other
        pairing): the kind tag gates AFTER structural parse, so the
        error names the actual kind."""
        blob = self._mk(packer)
        with pytest.raises(ProtocolError,
                           match=f"does not belong on the {lane.kind!r}"):
            lane.unpack(blob)

    def test_untagged_legacy_meta_only_lands_on_kv_lane(self):
        legacy = blobcodec.pack_blob(
            {"x": 1}, {"a": np.arange(3, dtype=np.float32)})
        meta, bufs = blobcodec.KV_ROW.unpack(legacy)   # allow_untagged
        assert meta["x"] == 1 and "a" in bufs
        with pytest.raises(ProtocolError, match="does not belong"):
            blobcodec.WEIGHTS.unpack(legacy)

    def test_weights_blob_on_template_lane_keeps_template_error(self):
        """The pre-existing prefix pin survives the shared codec: a
        non-template blob on the template lane still reads 'not a
        prefix template'."""
        from tony_tpu.serving.kvship import unpack_template
        with pytest.raises(ProtocolError, match="not a prefix"):
            unpack_template(pack_weights(_tree()))


# ---------------------------------------------------------------------------
# The chunked resumable byte-blob lane
# ---------------------------------------------------------------------------
class TestChunkedBlobLane:
    def _hub(self):
        reg = MetricsRegistry()
        hub = ChannelHub(capacity=8, registry=reg)
        port = hub.start()
        return hub, port, reg

    def test_large_blob_chunks_and_lands_identical(self):
        hub, port, reg = self._hub()
        recv = hub.receiver("w")
        blob = np.random.RandomState(0).bytes(1 << 20)
        landed = {}

        def consume():
            landed["blob"] = recv.recv_bytes(timeout=30)

        t = threading.Thread(target=consume, daemon=True)
        try:
            s = ChannelSender(f"127.0.0.1:{port}", "w", window=8,
                              registry=reg)
            t.start()
            s.send_bytes(blob, sync=True, timeout=30,
                         chunk_bytes=64 * 1024)
            t.join(timeout=30)
            assert landed.get("blob") == blob
            s.close()
        finally:
            hub.stop()

    def test_magic_collision_escaped(self):
        """A payload that happens to START with the chunk magic must
        not be parsed as a manifest."""
        hub, port, reg = self._hub()
        recv = hub.receiver("w")
        blob = BLOB_CHUNK_MAGIC + b"i am not a manifest"
        try:
            s = ChannelSender(f"127.0.0.1:{port}", "w", window=4,
                              registry=reg)
            s.send_bytes(blob, sync=True, timeout=30)
            assert recv.recv_bytes(timeout=30) == blob
            s.close()
        finally:
            hub.stop()

    def test_short_poll_timeout_never_aborts_mid_blob(self):
        """The install-loop regression: a consumer polling with a
        250 ms timeout must land a blob whose chunks arrive SLOWER
        than that — the caller's timeout bounds only the wait for the
        blob to start; each chunk gets its own generous deadline."""
        hub, port, reg = self._hub()
        recv = hub.receiver("w")
        payloads = [b"a" * 100, b"b" * 100, b"c" * 77]
        blob_id = "feedfeedfeedfeed"
        landed = {}
        done = threading.Event()

        def consume():
            # the install-loop shape: short idle polls, forever
            while not done.is_set():
                try:
                    landed["blob"] = recv.recv_bytes(timeout=0.25)
                    return
                except ChannelError:
                    continue

        def trickle():
            s = ChannelSender(f"127.0.0.1:{port}", "w", window=8,
                              registry=reg)
            try:
                s.send(np.frombuffer(_blob_frame(
                    {"v": 2, "kind": "manifest", "chunks": 3,
                     "total": 277, "blob": blob_id}), np.uint8),
                    sync=True, timeout=30)
                for i, p in enumerate(payloads):
                    time.sleep(0.4)         # slower than the 0.25 poll
                    s.send(np.frombuffer(_blob_frame(
                        {"v": 2, "kind": "chunk", "blob": blob_id,
                         "i": i}, p), np.uint8), sync=True, timeout=30)
            finally:
                s.close(drain=False)

        ct = threading.Thread(target=consume, daemon=True)
        st = threading.Thread(target=trickle, daemon=True)
        try:
            ct.start()
            st.start()
            st.join(timeout=30)
            ct.join(timeout=30)
            done.set()
            assert landed.get("blob") == b"".join(payloads)
        finally:
            done.set()
            hub.stop()

    def test_aborted_reassembly_resyncs_discarding_stale_chunks(self):
        """A reassembly aborted mid-blob (dead seeder) leaves the
        already-queued stragglers on the lane; the NEXT recv_bytes
        identifies them by blob id and discards them instead of
        misparsing them as standalone blobs — the lane re-synchronizes
        and a fresh ship lands intact."""
        hub, port, reg = self._hub()
        recv = hub.receiver("w")
        stale_id = "deaddeaddeaddead"
        fresh = np.random.RandomState(7).bytes(300 * 1024)
        try:
            s = ChannelSender(f"127.0.0.1:{port}", "w", window=8,
                              registry=reg)
            # manifest promising 3 chunks, only one delivered: the
            # committed reassembly times out on chunk 1
            s.send(np.frombuffer(_blob_frame(
                {"v": 2, "kind": "manifest", "chunks": 3,
                 "total": 300, "blob": stale_id}), np.uint8),
                sync=True, timeout=30)
            s.send(np.frombuffer(_blob_frame(
                {"v": 2, "kind": "chunk", "blob": stale_id, "i": 0},
                b"x" * 100), np.uint8), sync=True, timeout=30)
            with pytest.raises(ChannelError):
                recv.recv_bytes(timeout=5, chunk_timeout=0.2)
            # the dead blob's stragglers arrive late, then a fresh blob
            s.send(np.frombuffer(_blob_frame(
                {"v": 2, "kind": "chunk", "blob": stale_id, "i": 1},
                b"y" * 100), np.uint8), sync=True, timeout=30)
            s.send(np.frombuffer(_blob_frame(
                {"v": 2, "kind": "chunk", "blob": stale_id, "i": 2},
                b"z" * 100), np.uint8), sync=True, timeout=30)
            s.send_bytes(fresh, sync=True, timeout=30,
                         chunk_bytes=64 * 1024)
            assert recv.recv_bytes(timeout=30) == fresh
            s.close(drain=False)
        finally:
            hub.stop()

    def test_new_manifest_mid_blob_restarts_reassembly(self):
        """A sender that gave up and re-shipped: a fresh manifest
        arriving mid-reassembly restarts on the new blob instead of
        erroring (or worse, splicing two blobs together)."""
        hub, port, reg = self._hub()
        recv = hub.receiver("w")
        fresh = np.random.RandomState(9).bytes(200 * 1024)
        try:
            s = ChannelSender(f"127.0.0.1:{port}", "w", window=8,
                              registry=reg)
            s.send(np.frombuffer(_blob_frame(
                {"v": 2, "kind": "manifest", "chunks": 2,
                 "total": 200, "blob": "0011223344556677"}), np.uint8),
                sync=True, timeout=30)
            s.send(np.frombuffer(_blob_frame(
                {"v": 2, "kind": "chunk", "blob": "0011223344556677",
                 "i": 0}, b"q" * 100), np.uint8), sync=True, timeout=30)
            s.send_bytes(fresh, sync=True, timeout=30,
                         chunk_bytes=64 * 1024)
            assert recv.recv_bytes(timeout=30) == fresh
            s.close(drain=False)
        finally:
            hub.stop()

    def test_disconnect_mid_blob_resumes_zero_dup_zero_drop(self):
        """Sever the socket repeatedly DURING a chunked transfer: the
        sender reconnects and resumes at the receiver's seq, and the
        landed bytes equal the shipped bytes exactly — a 30 GB ship
        that drops at 29 GB re-sends chunks, not the blob."""
        hub, port, reg = self._hub()
        recv = hub.receiver("w")
        # 24 chunks + manifest > hub capacity (8) + window (2): with no
        # consumer draining, the sender is GUARANTEED blocked mid-blob
        # when the severs land
        blob = np.random.RandomState(1).bytes(768 * 1024)
        landed = {}
        sent = {}

        def send():
            s = ChannelSender(f"127.0.0.1:{port}", "w", window=2,
                              registry=reg)
            try:
                s.send_bytes(blob, sync=True, timeout=60,
                             chunk_bytes=32 * 1024)
                sent["ok"] = True
            finally:
                s.close(drain=False)

        def consume():
            landed["blob"] = recv.recv_bytes(timeout=60)

        st = threading.Thread(target=send, daemon=True)
        try:
            st.start()
            time.sleep(0.2)                     # sender now wedged mid-blob
            assert st.is_alive()
            hub.disconnect_all()
            time.sleep(0.05)
            hub.disconnect_all()
            ct = threading.Thread(target=consume, daemon=True)
            ct.start()
            st.join(timeout=60)
            ct.join(timeout=60)
            assert sent.get("ok") and landed.get("blob") == blob
            assert reg.counter("tony_channel_reconnects_total",
                               channel="w").value >= 1
        finally:
            hub.stop()


# ---------------------------------------------------------------------------
# Self-organizing fan-out
# ---------------------------------------------------------------------------
class TestWarmFanout:
    def test_log2_waves_from_one_seed(self):
        shipped = []
        res = warm_fanout([f"t{i}" for i in range(8)],
                          lambda src, dst: shipped.append((src, dst)),
                          seeders=["seed"])
        assert not res["failed"] and not res["fallback"]
        assert len(res["warmed"]) == 8 and res["ships"] == 8
        # 1 -> 2 -> 4 -> 8 seeders: ceil(log2(8+1)) = 4 waves, not 8
        assert res["waves"] == 4

    def test_cold_start_mints_seed_then_fans_out(self):
        loads = []
        res = warm_fanout([f"t{i}" for i in range(8)],
                          lambda src, dst: None,
                          fallback=loads.append)
        assert loads == ["t0"]                  # ONE storage load
        assert res["waves"] == 4 and res["ships"] == 7
        assert res["fallback"] == ["t0"] and len(res["warmed"]) == 7

    def test_crashed_seeder_condemned_target_retries(self):
        calls = []

        def ship(src, dst):
            calls.append((src, dst))
            if src == "dead":
                raise RuntimeError("seeder crashed mid-ship")

        loads = []
        res = warm_fanout(["t0", "t1"], ship, seeders=["dead"],
                          fallback=loads.append)
        assert not res["failed"]
        assert loads == ["t0"]                  # fallback minted a seed
        assert ("dead", "t0") in calls          # the failed attempt
        assert sorted(res["warmed"] + res["fallback"]) == ["t0", "t1"]

    def test_no_fallback_reports_failed_without_wedging(self):
        res = warm_fanout(["t0", "t1"],
                          lambda s, d: (_ for _ in ()).throw(
                              RuntimeError("boom")),
                          seeders=["dead"])
        assert res["failed"] == ["t0", "t1"] and not res["warmed"]

    def test_failing_fallback_reports_failed_never_raises(self):
        """The chaos case the fleet controller ships: a storage load
        that ITSELF fails moves its target to ``failed`` (for the
        controller's release path) and the wave loop keeps warming —
        it never propagates out of _scale_up / rolling_upgrade."""
        attempts = []

        def fallback(dst):
            attempts.append(dst)
            if len(attempts) == 1:
                raise OSError("storage load failed")

        res = warm_fanout(["t0", "t1", "t2"], lambda src, dst: None,
                          fallback=fallback)
        assert res["failed"] == ["t0"]          # the failed load's target
        assert res["fallback"] == ["t1"]        # retry minted a seeder
        assert res["warmed"] == ["t2"]          # and fan-out resumed
        assert attempts == ["t0", "t1"]

    def test_fallback_always_failing_terminates(self):
        def fallback(dst):
            raise OSError("storage down")

        res = warm_fanout(["t0", "t1"], lambda src, dst: None,
                          fallback=fallback)
        assert res["failed"] == ["t0", "t1"]
        assert not res["warmed"] and not res["fallback"]


# ---------------------------------------------------------------------------
# Live server: advertise, pull, bit-identical serving
# ---------------------------------------------------------------------------
class TestLiveServerWarmBoot:
    def _prompts(self, seed, sizes):
        rng = np.random.RandomState(seed)
        return [[int(t) for t in rng.randint(0, CFG.vocab_size, size=n)]
                for n in sizes]

    def test_hello_advertises_and_pull_lands_verified(self, params):
        srv = ServingServer(
            ContinuousBatcher(params, CFG, batch=2, max_len=32, chunk=3),
            registry=MetricsRegistry())
        port = srv.start()
        addr = f"127.0.0.1:{port}"
        try:
            digest = srv.weights_digest
            assert isinstance(digest, str) and len(digest) == 64
            assert digest == tree_digest(params)
            listed = weights_rpc(addr, {"op": "list"})
            assert listed["ok"]
            hello = listed["_hello"]
            assert hello["weights_digest"] == digest
            assert digest in listed["resident"]
            meta, tree = pull_weights(addr, timeout_s=60)
            assert meta["digest"] == digest
            assert tree_digest(tree) == digest
        finally:
            srv.stop()

    def test_unknown_digest_fails_request_not_replica(self, params):
        srv = ServingServer(
            ContinuousBatcher(params, CFG, batch=2, max_len=32, chunk=3),
            registry=MetricsRegistry())
        port = srv.start()
        addr = f"127.0.0.1:{port}"
        try:
            res = weights_rpc(addr, {"op": "publish", "digest": "0" * 64,
                                     "target": "127.0.0.1:1"})
            assert not res["ok"]
            # the replica survived the bad request
            assert weights_rpc(addr, {"op": "list"})["ok"]
        finally:
            srv.stop()

    def test_ship_warmed_tokens_bit_identical_greedy_and_sampled(
            self, params):
        """THE acceptance gate: a replica serving pulled (ship-warmed)
        weights emits exactly the tokens a storage-loaded replica
        does, greedy AND sampled."""
        srv = ServingServer(
            ContinuousBatcher(params, CFG, batch=2, max_len=32, chunk=3),
            registry=MetricsRegistry())
        port = srv.start()
        try:
            _, pulled = pull_weights(f"127.0.0.1:{port}", timeout_s=60)
        finally:
            srv.stop()
        prompts = self._prompts(11, [4, 6, 3])
        for kw in ({},                               # greedy
                   {"temperature": 0.9, "top_k": 12, "top_p": 0.95,
                    "seed": 11}):                    # sampled
            want = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                                     chunk=3, **kw).serve(prompts, 6)
            got = ContinuousBatcher(pulled, CFG, batch=2, max_len=32,
                                    chunk=3, **kw).serve(prompts, 6)
            assert got == want, kw


# ---------------------------------------------------------------------------
# Lazy export: HELLO/STATS never pay (or pin) the params pack
# ---------------------------------------------------------------------------
class TestLazyExport:
    def test_resident_view_never_triggers_export(self):
        """The first client HELLO must not synchronously pack a
        multi-GB host copy of the params: resident_digests() (what
        HELLO/STATS advertise) never runs the exporter; digests()
        (the seed-intent list/publish path) runs it exactly once."""
        calls = []

        def exporter():
            calls.append(1)
            return pack_weights(_tree())

        store = WeightStore(MetricsRegistry(), exporter=exporter)
        assert store.resident_digests() == []
        assert store.resident_digests() == []
        assert not calls                        # advertising is free
        d = tree_digest(_tree())
        assert store.digests() == [d]           # seed intent: exports
        assert len(calls) == 1
        assert store.digests() == [d]           # ... exactly once
        assert len(calls) == 1
        assert store.resident_digests() == [d]

    def test_live_hello_and_resident_op_do_not_export(self, params):
        """End-to-end: a fresh server's HELLO advertises an EMPTY
        resident list (plus its precomputed weights_digest — the
        seedability signal); the 'resident' op stays non-exporting;
        the 'list' op is the moment the export runs."""
        srv = ServingServer(
            ContinuousBatcher(params, CFG, batch=2, max_len=32, chunk=3),
            registry=MetricsRegistry())
        port = srv.start()
        addr = f"127.0.0.1:{port}"
        try:
            digest = srv.weights_digest
            res = weights_rpc(addr, {"op": "resident"})
            assert res["ok"] and res["resident"] == []
            assert res["_hello"]["weights_resident"] == []
            assert res["_hello"]["weights_digest"] == digest
            listed = weights_rpc(addr, {"op": "list"})
            assert digest in listed["resident"]
            res2 = weights_rpc(addr, {"op": "resident"})
            assert digest in res2["resident"]
            assert digest in res2["_hello"]["weights_resident"]
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# The advertised pull-back address (cross-host warm boot)
# ---------------------------------------------------------------------------
class TestPullAdvertiseHost:
    def test_reachable_host_toward_loopback_is_loopback(self):
        from tony_tpu.serving.weightstore import _reachable_host
        assert _reachable_host("127.0.0.1:9") == "127.0.0.1"

    def test_reachable_host_falls_back_on_unresolvable_peer(self):
        from tony_tpu.serving.weightstore import _reachable_host
        assert _reachable_host("host.invalid:1",
                               default="203.0.113.1") == "203.0.113.1"

    @pytest.mark.parametrize("advertise,expect", [
        (None, "192.0.2.55"),           # derived from the seeder route
        ("203.0.113.7", "203.0.113.7"),  # explicit override wins
    ], ids=["derived", "explicit"])
    def test_pull_advertises_reachable_target(self, monkeypatch,
                                              advertise, expect):
        """The cross-host regression: pull_weights must advertise an
        address the SEEDER can reach — never a hard-coded loopback
        that would have a remote seeder ship the artifact to itself."""
        from tony_tpu.serving import weightstore as ws
        blob = pack_weights(_tree())
        digest = peek_weights_meta(blob)["digest"]
        captured = {}
        probed = []

        def fake_reachable(peer, default="127.0.0.1"):
            probed.append(peer)
            return "192.0.2.55"

        monkeypatch.setattr(ws, "_reachable_host", fake_reachable)

        def fake_rpc(addr, body, timeout_s=30.0):
            if body["op"] == "list":
                return {"ok": True, "resident": [digest], "_hello": {}}
            assert body["op"] == "publish"
            captured["target"] = body["target"]
            host, port = body["target"].rsplit(":", 1)

            def ship():
                s = ChannelSender(f"127.0.0.1:{port}", WEIGHT_CHANNEL,
                                  registry=MetricsRegistry())
                try:
                    s.send_bytes(blob, sync=True, timeout=30)
                finally:
                    s.close(drain=False)

            threading.Thread(target=ship, daemon=True).start()
            return {"ok": True, "digest": digest, "_hello": {}}

        monkeypatch.setattr(ws, "weights_rpc", fake_rpc)
        meta, tree = pull_weights("198.51.100.2:4242", timeout_s=30,
                                  advertise_host=advertise)
        assert meta["digest"] == digest
        assert tree_digest(tree) == digest
        assert captured["target"].rsplit(":", 1)[0] == expect
        # the route probe names the seeder; an explicit host skips it
        assert probed == ([] if advertise else ["198.51.100.2:4242"])


# ---------------------------------------------------------------------------
# Compiled-program artifacts
# ---------------------------------------------------------------------------
class TestCompileCache:
    def _seed_dir(self, tmp_path):
        src = tmp_path / "cache"
        (src / "sub").mkdir(parents=True)
        (src / "a.bin").write_bytes(b"\x01\x02xla")
        (src / "sub" / "b.bin").write_bytes(b"\x03" * 100)
        return str(src)

    def test_pack_install_round_trip(self, tmp_path):
        src = self._seed_dir(tmp_path)
        blob = pack_compile_cache(src, version="v1")
        dst = str(tmp_path / "landed")
        meta = install_compile_cache(blob, dst)
        assert meta["digest"] == dir_digest(src) == dir_digest(dst)
        assert open(os.path.join(dst, "sub", "b.bin"), "rb").read() \
            == b"\x03" * 100

    def test_flipped_byte_refused(self, tmp_path):
        """A corrupt transfer raises instead of being trusted as a
        trace cache (the landing is verified AFTER the write; nothing
        already resident is deleted)."""
        blob = bytearray(pack_compile_cache(self._seed_dir(tmp_path)))
        blob[-7] ^= 0x20
        with pytest.raises(ProtocolError, match="landed dirty"):
            install_compile_cache(bytes(blob), str(tmp_path / "landed"))

    def test_corrupt_blob_refused_at_put(self, tmp_path):
        """put() digest-verifies compile-cache artifacts too: a
        corrupt blob can never land resident (counted as an install)
        and be re-seeded peer-to-peer — corruption is caught at the
        store, not later at every target's install."""
        reg = MetricsRegistry()
        store = WeightStore(reg)
        good = pack_compile_cache(self._seed_dir(tmp_path))
        bad = bytearray(good)
        bad[-7] ^= 0x20
        with pytest.raises(ProtocolError, match="REFUSED"):
            store.put(bytes(bad))
        assert store.resident_digests() == []
        assert reg.counter("tony_weight_installs_total").value == 0
        digest = store.put(good)
        assert store.get(digest) == good        # a compile-cache hit
        assert reg.counter("tony_compile_cache_hits_total").value == 1


# ---------------------------------------------------------------------------
# Bench pins
# ---------------------------------------------------------------------------
class TestBenchArm:
    def test_weight_ship_arm_pins(self):
        import bench
        out = bench._weight_ship_arm()
        # ship-warmed replica ready >= 2x faster than cold start
        assert out["serving_scaleup_warm_vs_cold"] >= 2, out
        # one seed load + O(log N) fan-out beats N serial loads
        assert out["serving_upgrade_wall_vs_serial_loads"] > 1, out
        assert out["serving_warm_waves"] == 4, out      # 1 + log2(8)
        assert out["serving_warm_storage_loads"] == 1, out
        assert out["serving_scaleup_to_first_token_s"] > 0
