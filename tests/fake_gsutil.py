#!/usr/bin/env python3
"""Fake ``gsutil`` for tests: maps gs://bucket/path -> $FAKE_GCS_ROOT/bucket/
path on the local filesystem and implements the subset of verbs GcsStorage
uses (stat, ls, cat [-r], cp [-|src dst], mv, rm, rsync -r). The MiniDFS
analog — the real CLI's contract, no cloud."""

import os
import shutil
import sys


def to_local(uri: str) -> str:
    assert uri.startswith("gs://"), uri
    return os.path.join(os.environ["FAKE_GCS_ROOT"], uri[len("gs://"):])


def main(argv):
    # gsutil global flags before the verb (-q, -m)
    while argv and argv[0] in ("-q", "-m"):
        argv = argv[1:]
    verb, args = argv[0], argv[1:]

    # injected per-call latency: lets tests measure the parallel-prefetch
    # win over a slow link without a real network; the TIME log records
    # each call's [start, end] so tests can assert fetch OVERLAP directly
    # (wall-clock ratios flake under CI load; overlap doesn't)
    lat = os.environ.get("FAKE_GSUTIL_LATENCY_S")
    time_log = os.environ.get("FAKE_GSUTIL_TIME_LOG")
    import time
    t0 = time.time()
    if lat:
        time.sleep(float(lat))
    if time_log:
        with open(time_log, "a") as f:
            f.write(f"{verb} {t0:.4f} {time.time():.4f}\n")

    # auth observability for the credential-scoping tests: record which
    # identity each call ran under (CLOUDSDK_AUTH_ACCESS_TOKEN is how the
    # real gcloud suite receives an explicit access token)
    auth_log = os.environ.get("FAKE_GSUTIL_AUTH_LOG")
    if auth_log:
        with open(auth_log, "a") as f:
            tok = os.environ.get("CLOUDSDK_AUTH_ACCESS_TOKEN", "AMBIENT")
            target = next((a for a in args if a.startswith("gs://")), "-")
            f.write(f"{verb} {target} {tok}\n")

    if verb == "stat":
        return 0 if os.path.isfile(to_local(args[0])) else 1

    if verb == "ls":
        pat = args[0]
        recursive = pat.endswith("/**")
        base = to_local(pat[:-3] if recursive else pat.rstrip("/"))
        if not os.path.isdir(base):
            return 1
        prefix = pat[:-3].rstrip("/") if recursive else pat.rstrip("/")
        if recursive:
            found = False
            for root, _, files in os.walk(base):
                rel = os.path.relpath(root, base)
                for f in sorted(files):
                    p = f if rel == "." else f"{rel}/{f}"
                    print(f"{prefix}/{p}")
                    found = True
            return 0 if found else 1
        entries = sorted(os.listdir(base))
        if not entries:
            return 1
        for e in entries:
            full = os.path.join(base, e)
            print(f"{prefix}/{e}" + ("/" if os.path.isdir(full) else ""))
        return 0

    if verb == "cat":
        if args[0] == "-r":
            rng, path = args[1], args[2]
            with open(to_local(path), "rb") as f:
                if rng.startswith("-"):              # tail: last N bytes
                    n = int(rng[1:])
                    f.seek(0, os.SEEK_END)
                    f.seek(max(0, f.tell() - n))
                    sys.stdout.buffer.write(f.read())
                else:                                # inclusive a-b range
                    a, b = rng.split("-")
                    start = int(a)
                    f.seek(start)
                    if b:
                        sys.stdout.buffer.write(f.read(int(b) - start + 1))
                    else:                            # open-ended "a-"
                        sys.stdout.buffer.write(f.read())
            return 0
        with open(to_local(args[0]), "rb") as f:
            sys.stdout.buffer.write(f.read())
        return 0

    if verb == "du":
        p = to_local(args[0])
        if not os.path.isfile(p):
            return 1
        print(f"{os.path.getsize(p)}  {args[0]}")
        return 0

    if verb == "cp":
        src, dst = args[0], args[1]
        if src == "-":
            dest = to_local(dst)
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            with open(dest, "wb") as f:
                f.write(sys.stdin.buffer.read())
            return 0
        s = to_local(src) if src.startswith("gs://") else src
        d = to_local(dst) if dst.startswith("gs://") else dst
        if not os.path.isfile(s):
            return 1
        os.makedirs(os.path.dirname(d) or ".", exist_ok=True)
        shutil.copy2(s, d)
        return 0

    if verb == "mv":
        s, d = to_local(args[0]), to_local(args[1])
        if not os.path.exists(s):
            return 1
        os.makedirs(os.path.dirname(d), exist_ok=True)
        os.replace(s, d)
        return 0

    if verb == "rm":
        p = to_local(args[-1])
        if not os.path.exists(p):
            return 1
        os.remove(p)
        return 0

    if verb == "rsync":
        assert args[0] == "-r", args
        src, dst = args[1], args[2]
        s = to_local(src) if src.startswith("gs://") else src
        d = to_local(dst) if dst.startswith("gs://") else dst
        if not os.path.isdir(s):
            return 1
        shutil.copytree(s, d, dirs_exist_ok=True)
        return 0

    print(f"fake_gsutil: unknown verb {verb}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
