"""Goodput ledger + straggler detector: the interval accountant's
invariants (no gaps, no overlap — sum(categories) == wall), the
executor/user-process spool bridge, the heartbeat piggyback's
back-compat discipline, journal replay of coordinator-attributed
extras, and the two e2e acceptance pins: bit-exact ``/goodput`` replay
against the live coordinator's final GOODPUT event, and the chaos run
where exactly the artificially-slowed worker is flagged (then cleared
once the skew stops)."""

import json
import os
import sys
import threading
import time
import urllib.request

import pytest

from tony_tpu.client.client import TonyClient
from tony_tpu.cluster import journal as journal_mod
from tony_tpu.conf.config import TonyConfig
from tony_tpu.events import events as ev
from tony_tpu.history.server import HistoryServer
from tony_tpu.runtime import goodput as G
from tony_tpu.runtime import metrics as M

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAINER = os.path.join(REPO, "tests", "fixtures",
                       "fake_elastic_trainer.py")
PY = sys.executable


# ---------------------------------------------------------------------------
# Ledger core: the no-gaps / no-overlap invariant
# ---------------------------------------------------------------------------
class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def _ledger(clock):
    return G.GoodputLedger(clock=clock, wall_clock=clock)


def test_ledger_sum_equals_wall_with_nesting():
    clk = FakeClock()
    led = _ledger(clk)
    clk.tick(1.0)                        # base overhead
    with led.enter("provision"):
        clk.tick(2.0)
    with led.enter("step"):
        clk.tick(3.0)
        with led.enter("checkpoint"):    # nested: suspends step
            clk.tick(0.5)
        clk.tick(1.5)
    clk.tick(0.25)
    w = led.snapshot()
    assert w["cat"] == {"overhead": 1.25, "provision": 2.0,
                        "step": 4.5, "checkpoint": 0.5}
    assert sum(w["cat"].values()) == pytest.approx(w["now"] - w["t0"])
    assert w["cur"] == "overhead"
    # only the OUTER closed step counts toward the straggler accumulators
    assert w["sw"] == {"c": 1, "s": pytest.approx(4.5)}
    assert w["n"]["step"] == 1 and w["n"]["checkpoint"] == 1


def test_ledger_tolerates_out_of_order_exit():
    """A generator-held inner context finalized AFTER its parent exits
    must not corrupt the stack: the pop unwinds to the matching frame."""
    clk = FakeClock()
    led = _ledger(clk)
    led._push("step")
    clk.tick(1.0)
    led._push("checkpoint")
    clk.tick(1.0)
    led._pop("step")                     # outer popped first
    clk.tick(1.0)
    w = led.snapshot()
    assert sum(w["cat"].values()) == pytest.approx(w["now"] - w["t0"])
    assert w["cur"] == "overhead"


def test_ledger_rejects_unknown_category():
    led = _ledger(FakeClock())
    with pytest.raises(ValueError):
        led.enter("coffee")
    with pytest.raises(ValueError):
        led.add("coffee", 1.0)
    with pytest.raises(ValueError):
        G.GoodputLedger(base="coffee")


def test_ledger_mirrors_deltas_into_registry():
    clk = FakeClock()
    reg = M.MetricsRegistry()
    led = G.GoodputLedger(clock=clk, wall_clock=clk, registry=reg,
                          extra_categories=(G.USER_CATEGORY,))
    with led.enter("step"):
        clk.tick(2.0)
    with led.enter(G.USER_CATEGORY):     # internal: never exported
        clk.tick(1.0)
    led.snapshot()
    with led.enter("step"):
        clk.tick(3.0)
    led.snapshot()
    wire = reg.to_wire()
    totals = {(name, tuple(sorted(labels.items()))): value
              for name, labels, value in wire["c"]}
    key = ("tony_goodput_seconds_total", (("category", "step"),))
    assert totals[key] == pytest.approx(5.0)     # 2.0 then +3.0, not 2+5
    assert not any(lbls == (("category", G.USER_CATEGORY),)
                   for (_, lbls) in totals)


def test_ledger_spool_publish_roundtrip(tmp_path):
    spool = str(tmp_path / "spool.json")
    clk = FakeClock()
    led = G.GoodputLedger(clock=clk, wall_clock=clk, spool_path=spool)
    with led.enter("step"):
        clk.tick(1.0)
    led.publish()
    wire = G.from_wire_json(open(spool).read())
    assert wire is not None
    assert wire["cat"]["step"] == pytest.approx(1.0)
    assert not os.path.exists(spool + ".tmp")    # atomic publish


@pytest.mark.parametrize("payload", [
    "not json", "[]", '{"v": 99, "t0": 0, "now": 1}',
    '{"v": 1, "t0": 5, "now": 1}',               # now < t0
    '{"v": 1, "t0": 0, "now": 1, "cat": [1, 2]}',
    '{"v": 1, "t0": 0, "now": 1, "cat": {"step": -2}}',
    '{"v": 1, "t0": 0, "now": 1, "cat": {}, "sw": {"c": "x"}}',
])
def test_malformed_wires_are_dropped(payload):
    assert G.from_wire_json(payload) is None


def test_merge_wires_substitutes_child_and_credits_residual():
    host = {"v": 1, "t0": 0.0, "now": 10.0,
            "cat": {"provision": 1.0, "user": 8.0, "overhead": 1.0},
            "cur": "user", "n": {"provision": 1}, "sw": {"c": 0, "s": 0.0}}
    child = {"v": 1, "t0": 2.0, "now": 9.0,
             "cat": {"step": 5.0, "data_wait": 1.0, "overhead": 0.5},
             "cur": "step", "n": {"step": 10}, "sw": {"c": 10, "s": 5.0}}
    merged = G.merge_wires(host, child)
    assert "user" not in merged["cat"]
    # residual user wall the child hasn't accounted (8 - 6.5) -> overhead
    assert merged["cat"]["overhead"] == pytest.approx(1.0 + 0.5 + 1.5)
    assert sum(merged["cat"].values()) == pytest.approx(10.0)
    assert merged["sw"] == {"c": 10, "s": 5.0}
    assert merged["cur"] == "step"       # host was inside user -> child's
    # no child snapshot yet: the whole user wall is overhead
    alone = G.merge_wires(host, None)
    assert alone["cat"]["overhead"] == pytest.approx(9.0)
    assert alone["cur"] == "overhead"


def test_goodput_fraction_includes_extras_in_denominator():
    entry = {"t0": 0.0, "now": 8.0, "cat": {"step": 6.0, "overhead": 2.0},
             "extra": {"provision": 2.0}}
    assert G.goodput_fraction(entry) == pytest.approx(0.6)
    assert G.goodput_fraction({"t0": 0.0, "now": 0.0, "cat": {},
                               "extra": {}}) == 0.0


# ---------------------------------------------------------------------------
# Straggler detector: pure-logic windows
# ---------------------------------------------------------------------------
def _wire(c, s):
    return {"v": 1, "t0": 0.0, "now": 0.0, "cat": {}, "cur": "",
            "n": {}, "sw": {"c": c, "s": s}}


def test_straggler_flags_exactly_the_slow_task_then_clears():
    det = G.StragglerDetector(factor=2.0, windows=2, alpha=1.0)
    # window 0 seeds the per-task state; no verdicts possible yet
    det.observe({f"worker:{i}": _wire(0, 0.0) for i in range(3)})
    step = {0: 0.1, 1: 0.1, 2: 0.5}
    cum = {i: [0, 0.0] for i in range(3)}
    suspected_at = None
    for rnd in range(1, 5):
        wires = {}
        for i in range(3):
            cum[i][0] += 2
            cum[i][1] += 2 * step[i]
            wires[f"worker:{i}"] = _wire(*cum[i])
        sus, cleared = det.observe(wires)
        assert cleared == []
        if sus:
            assert suspected_at is None, "flagged twice without clearing"
            suspected_at = rnd
            assert [e["task"] for e in sus] == ["worker:2"]
            assert sus[0]["gang"] == "worker"
            assert sus[0]["ewma_s"] > 2.0 * sus[0]["median_s"]
    assert suspected_at == 2             # windows=2 consecutive strikes
    assert list(det.suspected) == ["worker:2"]
    # skew stops: with alpha=1 one healthy window clears the suspicion
    step[2] = 0.1
    for i in range(3):
        cum[i][0] += 2
        cum[i][1] += 2 * step[i]
    sus, cleared = det.observe(
        {f"worker:{i}": _wire(*cum[i]) for i in range(3)})
    assert sus == [] and cleared == ["worker:2"]
    assert det.suspected == {}


def test_straggler_gang_of_one_and_idle_windows_are_not_evidence():
    det = G.StragglerDetector(factor=2.0, windows=1, alpha=1.0)
    det.observe({"chief:0": _wire(0, 0.0)})
    sus, _ = det.observe({"chief:0": _wire(4, 40.0)})
    assert sus == []                     # no peers, no median, no verdict
    det2 = G.StragglerDetector(factor=2.0, windows=1, alpha=1.0)
    det2.observe({"worker:0": _wire(2, 0.2), "worker:1": _wire(2, 1.0)})
    # second window closes NO steps anywhere: strikes must not advance
    sus, cleared = det2.observe(
        {"worker:0": _wire(2, 0.2), "worker:1": _wire(2, 1.0)})
    assert sus == [] and cleared == []


# ---------------------------------------------------------------------------
# Heartbeat piggyback: back-compat at the Heartbeater layer
# ---------------------------------------------------------------------------
class _Ack:
    gcs_token = ""
    cluster_epoch = 0
    incarnation = 0


def test_heartbeater_goodput_piggyback_and_backcompat():
    from tony_tpu.cluster.executor import Heartbeater

    class NewRpc:
        def __init__(self):
            self.calls = []

        def task_executor_heartbeat(self, task_id, metrics="", spans="",
                                    client_unix_time=0.0, client_rtt=0.0,
                                    goodput=""):
            self.calls.append(goodput)
            return _Ack()

    rpc = NewRpc()
    hb = Heartbeater(rpc, "worker:0", interval_s=0.01,
                     goodput_fn=lambda: '{"v":1}')
    assert hb._rpc_takes_goodput
    hb._send_beat()
    assert rpc.calls == ['{"v":1}']
    # a RAISING provider costs nothing: the beat goes out ledger-less
    hb.goodput_fn = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    hb._send_beat()
    assert rpc.calls[-1] == ""

    class OldRpc:                        # pre-goodput RPC surface
        def __init__(self):
            self.calls = []

        def task_executor_heartbeat(self, task_id, metrics=""):
            self.calls.append((task_id, metrics))
            return ""

    old = OldRpc()
    hb2 = Heartbeater(old, "worker:0", interval_s=0.01,
                      goodput_fn=lambda: '{"v":1}')
    assert not hb2._rpc_takes_goodput
    hb2._send_beat()                     # must not pass goodput= at all
    assert old.calls == [("worker:0", "")]


# ---------------------------------------------------------------------------
# Journal: coordinator-attributed extras replay exactly once
# ---------------------------------------------------------------------------
def test_fold_accumulates_goodput_extras_and_reset_clears():
    records = [
        {"k": "goodput_extra", "task": "worker:0",
         "category": "provision", "seconds": 1.5},
        {"k": "goodput_extra", "task": "worker:0",
         "category": "provision", "seconds": 0.5},
        {"k": "goodput_extra", "task": "worker:1",
         "category": "recovery", "seconds": 2.0},
        {"k": "goodput_extra", "task": "worker:1"},           # malformed
        {"k": "goodput_extra", "task": "worker:1",
         "category": "recovery", "seconds": "not-a-number"},
    ]
    state = journal_mod.fold(records)
    assert state.goodput_extra == {
        "worker:0": {"provision": pytest.approx(2.0)},
        "worker:1": {"recovery": pytest.approx(2.0)}}
    state2 = journal_mod.fold(records + [
        {"k": "session_reset", "session_id": 1},
        {"k": "goodput_extra", "task": "worker:0",
         "category": "stage", "seconds": 0.25}])
    assert state2.goodput_extra == {
        "worker:0": {"stage": pytest.approx(0.25)}}


# ---------------------------------------------------------------------------
# E2E: live plane -> jhist -> bit-exact /goodput replay
# ---------------------------------------------------------------------------
def _get(port, path):
    with urllib.request.urlopen(
            f"http://localhost:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode("utf-8")


def _events_from_hist(hist_dir):
    out = []
    for path in sorted(ev.find_job_files(hist_dir)):
        out.extend(ev.parse_events(path))
    return out


@pytest.mark.e2e
def test_goodput_plane_end_to_end_and_replay_bit_exact(tmp_path):
    """A real local-backend training run: every task's replayed breakdown
    sums to its wall clock (no gaps, no overlap), the goodput fraction
    shows on /metrics and the job page, and /api/jobs/<id>/goodput
    replays the live coordinator's final GOODPUT event bit-exact."""
    hist = str(tmp_path / "hist")
    conf = TonyConfig({
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.location": hist,
        "tony.application.timeout": "90000",
        "tony.worker.instances": "2",
        "tony.task.heartbeat-interval-ms": "100",
        "tony.metrics.snapshot-interval-ms": "300",
    })
    cmd = (f"{PY} {TRAINER} --steps 10 --ckpt {tmp_path / 'progress'} "
           f"--ckpt_every 2 --step_wait 0.1 --tail_wait 0:1.5")
    client = TonyClient(conf, cmd)
    assert client.run() == 0

    events = _events_from_hist(hist)
    goodputs = [e for e in events if e.event_type == ev.GOODPUT]
    assert goodputs, "no GOODPUT events reached the jhist"
    final = goodputs[-1]
    tasks = final.payload["tasks"]
    assert set(tasks) >= {"worker:0", "worker:1"}
    for tid in ("worker:0", "worker:1"):
        entry = tasks[tid]
        wall = entry["now"] - entry["t0"]
        assert wall > 0
        # the acceptance pin: the carve-up is exhaustive and disjoint
        assert sum(entry["cat"].values()) == pytest.approx(wall, abs=0.02)
        assert entry["cat"]["step"] > 0.5        # 10 steps x 0.1s
        assert entry["sw"]["c"] == 10
        assert "extra" in entry
    frac = final.payload["fraction"]
    assert 0 < frac <= 1
    # the fraction gauge rode the coordinator's own registry (am:0) into
    # the same snapshot pass; worker wires carry the per-category counter
    snaps = [e for e in events if e.event_type == ev.METRICS_SNAPSHOT]
    assert snaps
    am_wire = json.dumps(snaps[-1].payload.get("tasks", {}).get("am:0", {}))
    assert "tony_goodput_fraction" in am_wire
    worker_wire = json.dumps(snaps[-1].payload["tasks"]["worker:0"])
    assert "tony_goodput_seconds_total" in worker_wire

    server = HistoryServer(TonyConfig({"tony.history.location": hist}),
                           port=0)
    server.start()
    try:
        status, body = _get(server.port,
                            f"/api/jobs/{client.app_id}/goodput")
        assert status == 200
        g = json.loads(body)
        # bit-exact: the replayed breakdown IS the final GOODPUT event
        assert g["tasks"] == final.payload["tasks"]
        assert g["fraction"] == final.payload["fraction"]
        assert g["window_count"] == len(goodputs)
        # the job page renders the goodput bar with its headline fraction
        status, page = _get(server.port, f"/jobs/{client.app_id}")
        assert status == 200
        assert f"Goodput {frac * 100.0:.1f}%" in page
        assert "Wall breakdown" in page
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# E2E chaos: one worker skewed -> exactly that task flagged, then cleared
# ---------------------------------------------------------------------------
@pytest.mark.e2e
def test_straggler_chaos_flags_exactly_the_slow_worker(tmp_path):
    """3-worker gang; worker 2 sleeps an extra 0.6s/step over a step
    window. The detector must flag worker:2 — and ONLY worker:2 — and
    clear it once the skew stops (both verdicts as jhist events)."""
    hist = str(tmp_path / "hist")
    conf = TonyConfig({
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.location": hist,
        "tony.application.timeout": "120000",
        "tony.worker.instances": "3",
        "tony.task.heartbeat-interval-ms": "250",
        "tony.metrics.snapshot-interval-ms": "1000",
        "tony.goodput.window-ms": "400",
        "tony.straggler.factor": "2.0",
        "tony.straggler.windows": "2",
    })
    cmd = (f"{PY} {TRAINER} --steps 44 --ckpt {tmp_path / 'progress'} "
           f"--ckpt_every 4 --step_wait 0.15 --slow 2:0.6:2:12 "
           f"--tail_wait 0:8")
    client = TonyClient(conf, cmd)
    assert client.run() == 0

    events = _events_from_hist(hist)
    sus = [e for e in events if e.event_type == ev.STRAGGLER_SUSPECTED]
    clr = [e for e in events if e.event_type == ev.STRAGGLER_CLEARED]
    assert sus, "the slowed worker was never flagged"
    assert {e.payload["task"] for e in sus} == {"worker:2"}, \
        [e.payload for e in sus]
    assert {e.payload["task"] for e in clr} == {"worker:2"}, \
        "suspicion never cleared after the skew stopped"
    evidence = sus[0].payload
    assert evidence["gang"] == "worker"
    assert evidence["ewma_s"] > evidence["factor"] * evidence["median_s"]
    # the counter rode the coordinator's registry into the jhist
    snaps = [e for e in events if e.event_type == ev.METRICS_SNAPSHOT]
    am_wire = json.dumps(snaps[-1].payload.get("tasks", {}).get("am:0", {}))
    assert "tony_straggler_suspected_total" in am_wire
