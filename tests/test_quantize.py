"""Weight-only int8 serving quantization: structure, numerics, and the
quant-to-quant exactness contract (same as the int8 KV cache's)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import transformer as T
from tony_tpu.models import decode as D
from tony_tpu.models.quantize import (QuantizedWeight, _quantize,
                                      quantize_weights_int8)

CFG = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def qparams(params):
    return quantize_weights_int8(params)


class TestQuantizeWeights:
    def test_structure(self, params, qparams):
        """Matmul weights become QuantizedWeight; embed, norms stay."""
        for name in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
            w = qparams["blocks"][name]
            assert isinstance(w, QuantizedWeight), name
            assert w.q.dtype == jnp.int8
            assert w.q.shape == params["blocks"][name].shape
        assert isinstance(qparams["lm_head"], QuantizedWeight)
        for name in ("attn_norm", "mlp_norm"):
            assert not isinstance(qparams["blocks"][name], QuantizedWeight)
        assert not isinstance(qparams["embed"], QuantizedWeight)
        assert not isinstance(qparams["final_norm"], QuantizedWeight)

    def test_moe_experts_not_quantized(self):
        cfg = CFG.scaled(num_experts=4)
        qp = quantize_weights_int8(T.init_params(jax.random.PRNGKey(1),
                                                 cfg))
        for name in ("router", "w_gate", "w_down"):
            assert not isinstance(qp["blocks"][name], QuantizedWeight)
        # attention weights still quantize
        assert isinstance(qp["blocks"]["wq"], QuantizedWeight)

    def test_per_channel_roundtrip_bound(self):
        w = jax.random.normal(jax.random.PRNGKey(2), (16, 4, 8),
                              jnp.float32)
        qw = _quantize(w, (0,))
        assert qw.scale.shape == (4, 8)
        deq = qw.q.astype(jnp.float32) * qw.scale
        # symmetric absmax: error <= per-channel absmax / 254
        bound = jnp.max(jnp.abs(w), axis=0) / 254.0
        assert bool(jnp.all(jnp.abs(deq - w) <= bound + 1e-7))

    def test_weinsum_fold_matches_dequantized(self):
        """The scale-outside-the-dot fold == einsum over the explicitly
        dequantized weight (same math, reassociated)."""
        w = jax.random.normal(jax.random.PRNGKey(3), (16, 4, 8),
                              jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(4), (2, 3, 16),
                              jnp.float32)
        qw = _quantize(w, (0,))
        got = D._weinsum("bsd,dhk->bshk", x, qw)
        want = jnp.einsum("bsd,dhk->bshk", x,
                          qw.q.astype(jnp.float32) * qw.scale)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4)
        # plain arrays pass straight through
        np.testing.assert_allclose(
            np.asarray(D._weinsum("bsd,dhk->bshk", x, w)),
            np.asarray(jnp.einsum("bsd,dhk->bshk", x, w)), atol=1e-6)

    def test_prefill_logits_track_float(self, params, qparams):
        prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 12), 0,
                                    CFG.vocab_size)
        lf, _ = D.prefill(params, prompt, CFG, 32)
        lq, _ = D.prefill(qparams, prompt, CFG, 32)
        rel = float(jnp.max(jnp.abs(lf - lq)) / jnp.max(jnp.abs(lf)))
        assert rel < 0.05, rel

    def test_serving_token_identical_to_generate(self, qparams):
        """Quant-to-quant: the batcher with quantized weights equals
        per-request generate with the same weights (deterministic)."""
        from tony_tpu.models.serve import ContinuousBatcher
        rs = np.random.RandomState(3)
        prompts = [list(rs.randint(0, CFG.vocab_size, size=n))
                   for n in (5, 7, 4)]
        b = ContinuousBatcher(qparams, CFG, batch=2, max_len=32, chunk=4)
        outs = b.serve(prompts, max_new_tokens=6)
        for i, p in enumerate(prompts):
            want = D.generate(qparams, jnp.asarray(p, jnp.int32)[None],
                              CFG, 6, jax.random.PRNGKey(0))
            assert outs[i] == [int(t) for t in
                               np.asarray(want.tokens[0, len(p):])], i

    def test_beam_and_speculative_equal_greedy(self, qparams):
        prompt = jax.random.randint(jax.random.PRNGKey(6), (2, 6), 0,
                                    CFG.vocab_size)
        g = D.generate(qparams, prompt, CFG, 10, jax.random.PRNGKey(0))
        bs = D.beam_search(qparams, prompt, CFG, 10, beam_width=1)
        np.testing.assert_array_equal(np.asarray(bs.tokens[:, 0]),
                                      np.asarray(g.tokens))
        sp = D.speculative_generate_device(qparams, qparams, prompt, CFG,
                                           CFG, max_new_tokens=10,
                                           num_speculative=3)
        np.testing.assert_array_equal(np.asarray(sp), np.asarray(g.tokens))

    def test_composes_with_int8_cache_and_window(self, qparams):
        cfg = CFG.scaled(kv_cache_dtype="int8", attn_window=24)
        prompt = jax.random.randint(jax.random.PRNGKey(7), (2, 30), 0,
                                    CFG.vocab_size)
        out = D.generate(qparams, prompt, cfg, 12, jax.random.PRNGKey(0))
        tk = np.asarray(out.tokens)
        assert tk.shape == (2, 42)
        assert (tk >= 0).all() and (tk < CFG.vocab_size).all()

    def test_tp_sharded_quant_decode_matches_unsharded(self, params,
                                                       qparams):
        """TP serving recipe: quantize AFTER shard_pytree — the int8
        weights/scales inherit the float weights' shardings — and
        sharded quantized decode is token-identical to unsharded
        quantized decode."""
        from tony_tpu.parallel.mesh import make_mesh
        from tony_tpu.parallel.sharding import shard_pytree
        mesh = make_mesh({"tp": 2, "dp": -1})
        sharded = shard_pytree(params, T.logical_axes(CFG), mesh)
        qs = quantize_weights_int8(sharded)
        # the quantized leaves carry the weight's tp sharding
        assert "tp" in str(qs["blocks"]["wq"].q.sharding.spec)
        assert "tp" in str(qs["blocks"]["wq"].scale.sharding.spec)
        prompt = jax.random.randint(jax.random.PRNGKey(8), (2, 8), 0,
                                    CFG.vocab_size)
        with jax.set_mesh(mesh):
            out_s = D.generate(qs, prompt, CFG, 10, jax.random.PRNGKey(0))
        out_u = D.generate(qparams, prompt, CFG, 10, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(out_s.tokens),
                                      np.asarray(out_u.tokens))
