"""Parallel gang launch + content-addressed staging cache.

Three layers under test, all hermetic via the fake gcloud (the MiniYARN
trick, tests/fake_gcloud.py — now with injected per-verb latency):

- the coordinator's launch fan-out (tony.launch.max-concurrent): bounded
  concurrency, serial fallback, launch failures funneled into
  record_completion instead of aborting the scheduling pass;
- the TPU backend's claim-or-wait gang logic under REAL concurrent
  callers (it always tolerated them; schedule_tasks finally provides
  some): waiter deadline expiry, provisioner failure waking co-gang
  waiters that re-claim, dead-gang reprovision racing a session retry;
- the content-stamp staging cache: a warm restart onto surviving slices
  ships ZERO tarballs (stamp-match path pinned), a content change falls
  back to the full re-stage, and the 4-gang cold-launch wall lands under
  2*D against a serial baseline of ~4*D (bench.py's launch arm, run at
  deterministic tier-1 delays here and realistic delays under `slow`).
"""

import os
import sys
import threading
import time
import types

import pytest

from tony_tpu.backend.base import CompletionEvent, LaunchSpec, SchedulerBackend
from tony_tpu.backend.tpu import (STAGE_DIGEST_FILE, TpuProvisioningError,
                                  TpuSliceBackend, compute_stage_digest)
from tony_tpu.cluster.coordinator import Coordinator
from tony_tpu.cluster.session import TaskStatus
from tony_tpu.conf.config import TonyConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FAKE_GCLOUD = os.path.join(REPO, "tests", "fake_gcloud.py")
sys.path.insert(0, REPO)          # for `import bench` (repo-root script)


@pytest.fixture
def fake_gcloud(tmp_path, monkeypatch):
    """Fake `gcloud` on PATH, rooted at tmp_path/fleet (2 hosts/slice)."""
    fleet = tmp_path / "fleet"
    fleet.mkdir()
    bindir = tmp_path / "bin"
    bindir.mkdir()
    gcloud = bindir / "gcloud"
    gcloud.write_text(
        f"#!/bin/bash\nexec {sys.executable} {FAKE_GCLOUD} \"$@\"\n")
    gcloud.chmod(0o755)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_GCLOUD_ROOT", str(fleet))
    monkeypatch.setenv("FAKE_NUM_WORKERS", "2")
    return str(fleet)


def make_backend(tmp_path, extra=None, instances=2, slices=1):
    base = {
        "tony.scheduler.backend": "tpu",
        "tony.tpu.project": "p", "tony.tpu.zone": "z",
        "tony.tpu.accelerator-type": "v5litepod",
        "tony.worker.instances": str(instances),
        "tony.worker.slices": str(slices),
    }
    base.update(extra or {})
    return TpuSliceBackend(TonyConfig(base), app_id="app1")


def make_job_dir(tmp_path, name="job"):
    job = tmp_path / name
    (job / "logs").mkdir(parents=True)
    (job / "tony-final.xml").write_text("<configuration></configuration>")
    return str(job)


def spec_for(i, job_dir):
    return LaunchSpec(task_id=f"worker:{i}", command="true", env={},
                      log_dir=os.path.join(job_dir, "logs"),
                      cwd=job_dir, tpu_topology="4x4")


def calls(fleet):
    path = os.path.join(fleet, "calls.log")
    if not os.path.exists(path):
        return []
    return open(path).read().splitlines()


def launch_concurrently(backend, specs):
    """Launch every spec from its own thread (what the coordinator's
    launch pool does) and collect per-thread exceptions."""
    errors = {}

    def one(s):
        try:
            backend.launch_task(s)
        except Exception as e:      # noqa: BLE001 - recorded for asserts
            errors[s.task_id] = e

    threads = [threading.Thread(target=one, args=(s,)) for s in specs]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    return errors


# ---------------------------------------------------------------------------
# Backend concurrency edge cases
# ---------------------------------------------------------------------------
class TestGangConcurrency:
    def test_await_gang_deadline_expiry(self, fake_gcloud, tmp_path):
        """A waiter whose provisioner never finishes must expire with the
        timeout error, not hang the launch thread forever."""
        b = make_backend(tmp_path, {"tony.tpu.provision-timeout-ms": "50",
                                    "tony.tpu.create-retries": "0",
                                    "tony.tpu.stage-retries": "0",
                                    "tony.tpu.retry-backoff-ms": "10"})
        gang = ("worker", 0)
        with b._lock:
            b._gangs[gang] = {"name": "stuck", "ready": threading.Event()}
        with pytest.raises(TpuProvisioningError, match="timed out"):
            b._await_gang(gang, 0.05)

    def test_provisioner_failure_wakes_waiters_then_reclaim(
            self, fake_gcloud, tmp_path, monkeypatch):
        """Both co-gang launchers fail when the provisioner's create dies
        (the waiter wakes on the retracted entry instead of its deadline);
        a retry RE-CLAIMS the gang with a fresh entry and succeeds."""
        monkeypatch.setenv("FAKE_FAIL_CREATE_N", "1")
        b = make_backend(tmp_path, {"tony.tpu.create-retries": "0"})
        job_dir = make_job_dir(tmp_path)
        specs = [spec_for(0, job_dir), spec_for(1, job_dir)]
        errors = launch_concurrently(b, specs)
        assert sorted(errors) == ["worker:0", "worker:1"]
        for e in errors.values():
            assert isinstance(e, TpuProvisioningError)
        assert ("worker", 0) not in b._gangs     # failed claim retracted

        # session retry: the gang is re-claimed fresh and provisions
        errors = launch_concurrently(b, specs)
        assert errors == {}
        assert b._gangs[("worker", 0)]["ready"].is_set()
        ops = [c.split()[3] for c in calls(fake_gcloud)]
        assert ops.count("create") == 2          # 1 failed + 1 succeeded
        b.stop()

    def test_dead_gang_reprovision_races_session_retry(
            self, fake_gcloud, tmp_path):
        """Two tasks of a DEAD gang relaunch concurrently (a session retry
        fanning out): exactly one claims the reprovision (one delete + one
        create), the other waits on the fresh entry, both launch. The
        surviving gang is untouched."""
        b = make_backend(tmp_path, instances=4, slices=2)
        job_dir = make_job_dir(tmp_path)
        specs = [spec_for(i, job_dir) for i in range(4)]
        assert launch_concurrently(b, specs) == {}

        # gang s1 dies: poison the cached state the way the poller would
        with b._lock:
            b._state_cache[("worker", 1)] = "PREEMPTED"
            b._state_ts[("worker", 1)] = float("inf")
            b._reported.update({"worker:2", "worker:3"})
        errors = launch_concurrently(b, [specs[2], specs[3]])
        assert errors == {}
        assert b._state_cache.get(("worker", 1)) != "PREEMPTED"
        assert b._gangs[("worker", 1)]["ready"].is_set()

        def gang_ops(op, suffix):
            return sum(1 for c in calls(fake_gcloud)
                       if c.split()[3] == op and c.split()[4].endswith(suffix))
        assert gang_ops("create", "-s1") == 2    # initial + ONE reprovision
        assert gang_ops("delete", "-s1") == 1
        assert gang_ops("create", "-s0") == 1    # survivor untouched
        # the relaunched tasks must not be instantly re-failed off the
        # stale PREEMPTED cache (their procs may legitimately have
        # EXITED 0 by now — only preempted events are the regression)
        assert not [e for e in b.poll_completed() if e.preempted]
        b.stop()

    def test_failed_delete_does_not_adopt_dead_slice(
            self, fake_gcloud, tmp_path, monkeypatch):
        """Reprovision path: when the delete of a DEAD slice fails, the
        create's ALREADY_EXISTS must surface as a provisioning error —
        adopting the slice we just classified as preempted would stage
        onto a dead VM with a misleading error."""
        b = make_backend(tmp_path, {"tony.tpu.create-retries": "0"})
        job_dir = make_job_dir(tmp_path)
        b.launch_task(spec_for(0, job_dir))
        with b._lock:
            b._state_cache[("worker", 0)] = "PREEMPTED"
            b._state_ts[("worker", 0)] = float("inf")
            b._reported.add("worker:0")
        monkeypatch.setenv("FAKE_FAIL_DELETE_N", "1")
        with pytest.raises(TpuProvisioningError, match="ALREADY_EXISTS"):
            b.launch_task(spec_for(0, job_dir))
        b.stop()


# ---------------------------------------------------------------------------
# Content-addressed staging
# ---------------------------------------------------------------------------
class TestStagingCache:
    def test_warm_restart_zero_tarball_ships(self, fake_gcloud, tmp_path):
        """The stamp-match path, pinned: a FRESH backend (coordinator
        restart / session retry re-staging a surviving slice) probes the
        content stamp, matches, and ships ZERO tarballs."""
        job_dir = make_job_dir(tmp_path)
        b1 = make_backend(tmp_path)
        assert launch_concurrently(
            b1, [spec_for(0, job_dir), spec_for(1, job_dir)]) == {}
        cold_scps = sum(1 for c in calls(fake_gcloud)
                        if c.split()[3] == "scp")
        assert cold_scps == 1                    # the tarball shipped once
        b1.kill_all()                            # fleet survives

        b2 = make_backend(tmp_path)              # fresh: empty _gangs
        assert launch_concurrently(
            b2, [spec_for(0, job_dir), spec_for(1, job_dir)]) == {}
        log = calls(fake_gcloud)
        warm_scps = sum(1 for c in log if c.split()[3] == "scp")
        assert warm_scps == cold_scps            # ZERO new ships
        assert any(STAGE_DIGEST_FILE in c and "ssh" == c.split()[3]
                   for c in log)                 # the probe really ran
        # and the executors really launched on the adopted slice
        assert set(b2._procs) == {"worker:0", "worker:1"}
        b2.stop()

    def test_digest_mismatch_falls_back_to_full_restage(
            self, fake_gcloud, tmp_path):
        """A content change between attempts fails the stamp probe and the
        full idempotent re-stage ships the new tree."""
        job_dir = make_job_dir(tmp_path)
        b1 = make_backend(tmp_path)
        b1.launch_task(spec_for(0, job_dir))
        scps_before = sum(1 for c in calls(fake_gcloud)
                          if c.split()[3] == "scp")
        b1.kill_all()

        with open(os.path.join(job_dir, "train.py"), "w") as f:
            f.write("print('v2')\n")
        b2 = make_backend(tmp_path)
        b2.launch_task(spec_for(0, job_dir))
        scps_after = sum(1 for c in calls(fake_gcloud)
                         if c.split()[3] == "scp")
        assert scps_after == scps_before + 1     # re-shipped
        b2.stop()

    def test_stage_digest_deterministic_and_content_only(self, tmp_path):
        """Identical content hashes identically across rebuilds (mtimes
        must not leak into it), volatile/secret entries are excluded, and
        any content change moves the digest."""
        job = tmp_path / "j"
        (job / "src").mkdir(parents=True)
        (job / "src" / "train.py").write_text("print(1)\n")
        (job / "tony-final.xml").write_text("<configuration/>")
        d1 = compute_stage_digest(str(job))
        # volatile coordinator files and secrets must not perturb it
        (job / "logs").mkdir()
        (job / "logs" / "worker-0.stdout").write_text("noise")
        (job / "coordinator.addr").write_text("host:123")
        (job / ".tony-secret").write_text("s3cret")
        (job / ".tony-tls.key").write_text("KEY")
        (job / ".tony-stage.tgz").write_text("tarball")
        os.utime(job / "src" / "train.py", (1, 1))   # mtime-only change
        assert compute_stage_digest(str(job)) == d1
        (job / "src" / "train.py").write_text("print(2)\n")
        d2 = compute_stage_digest(str(job))
        assert d2 != d1
        # the tarball ships modes, empty dirs, and symlinks too — a
        # chmod+x / added dir / retargeted link must move the digest or
        # the stamp cache would serve a stale tree
        os.chmod(job / "src" / "train.py", 0o755)
        d3 = compute_stage_digest(str(job))
        assert d3 != d2
        (job / "src" / "empty").mkdir()
        d4 = compute_stage_digest(str(job))
        assert d4 != d3
        os.symlink("src", job / "data")              # dir symlink
        assert compute_stage_digest(str(job)) != d4

    def test_tarball_excludes_tls_key_and_volatile_files(self, fake_gcloud,
                                                         tmp_path):
        """The stage tarball must never carry the TLS PRIVATE key, the
        auth secret, or per-run volatile files (their churn would also
        defeat the content stamp across coordinator attempts)."""
        import tarfile
        job_dir = make_job_dir(tmp_path)
        for name in (".tony-tls.key", ".tony-secret", ".gcs-token",
                     "coordinator.addr", "final-status.json"):
            with open(os.path.join(job_dir, name), "w") as f:
                f.write("x")
        with open(os.path.join(job_dir, ".tony-tls.crt"), "w") as f:
            f.write("public cert")
        b = make_backend(tmp_path)
        b._prepare_stage_artifacts(job_dir)
        names = tarfile.open(
            os.path.join(job_dir, ".tony-stage.tgz")).getnames()
        for banned in (".tony-tls.key", ".tony-secret", ".gcs-token",
                       "coordinator.addr", "final-status.json", "logs"):
            assert banned not in names
        assert ".tony-tls.crt" in names          # executors pin with it
        assert "tony-final.xml" in names


# ---------------------------------------------------------------------------
# Coordinator fan-out
# ---------------------------------------------------------------------------
class RecordingBackend(SchedulerBackend):
    """Stub that measures launch concurrency and can fail chosen tasks."""

    def __init__(self, launch_s=0.0, fail_tasks=()):
        self.launch_s = launch_s
        self.fail_tasks = set(fail_tasks)
        self.launched = []
        self.inflight = 0
        self.max_inflight = 0
        self._lock = threading.Lock()

    def launch_task(self, spec):
        with self._lock:
            self.inflight += 1
            self.max_inflight = max(self.max_inflight, self.inflight)
        try:
            time.sleep(self.launch_s)
            if spec.task_id in self.fail_tasks:
                raise TpuProvisioningError(f"no capacity for {spec.task_id}")
            with self._lock:
                self.launched.append(spec.task_id)
        finally:
            with self._lock:
                self.inflight -= 1

    def poll_completed(self):
        return []

    def kill_task(self, task_id):
        pass

    def kill_all(self):
        pass

    def stop(self):
        pass


def make_coordinator(tmp_path, extra=None):
    base = {"tony.worker.instances": "4",
            "tony.history.location": str(tmp_path / "hist")}
    base.update(extra or {})
    job_dir = tmp_path / "job"
    job_dir.mkdir(exist_ok=True)
    return Coordinator(TonyConfig(base), "app_fanout", str(job_dir))


class TestCoordinatorFanOut:
    def test_launches_overlap_up_to_pool_bound(self, tmp_path):
        co = make_coordinator(tmp_path)
        co.backend = RecordingBackend(launch_s=0.2)
        t0 = time.monotonic()
        co.schedule_tasks("true")
        submitted = time.monotonic() - t0
        co._drain_launches()
        assert submitted < 0.15          # returns before launches land
        assert co.backend.max_inflight >= 3
        assert sorted(co.backend.launched) == [f"worker:{i}"
                                               for i in range(4)]
        co.rpc_server.stop()

    def test_max_concurrent_one_is_serial(self, tmp_path):
        co = make_coordinator(tmp_path, {"tony.launch.max-concurrent": "1"})
        co.backend = RecordingBackend(launch_s=0.05)
        co.schedule_tasks("true")
        co._drain_launches()
        assert co.backend.max_inflight == 1
        assert len(co.backend.launched) == 4
        co.rpc_server.stop()

    def test_launch_failure_funnels_into_completion(self, tmp_path):
        """A failed provision fails the TASK through record_completion —
        co-scheduled launches still land, the session reduces to FAILED,
        and the backend's actionable error is preserved for stop()."""
        co = make_coordinator(tmp_path)
        # launch_s keeps every launch in flight when worker:2's failure
        # lands — launches not yet STARTED at that point are legitimately
        # skipped by their liveness check (the session is already doomed)
        co.backend = RecordingBackend(launch_s=0.1, fail_tasks={"worker:2"})
        co.schedule_tasks("true")
        co._drain_launches()
        failed = co.session.get_task("worker", 2)
        assert failed.status is TaskStatus.FAILED
        assert co.session.status.value == "FAILED"
        assert sorted(co.backend.launched) == ["worker:0", "worker:1",
                                               "worker:3"]
        assert any("no capacity" in e for e in co._launch_errors)
        co.rpc_server.stop()

    def test_relaunch_failure_funnels_not_raises(self, tmp_path):
        """A launch failure that triggers the in-session restart path and
        then fails AGAIN must keep funneling — consuming restart budget
        until the task is FAILED — not raise out of the launch thread and
        strand the task in SCHEDULED forever (job hang)."""
        co = make_coordinator(tmp_path, {"tony.task.restart-count": "2"})
        co.backend = RecordingBackend(launch_s=0.05,
                                      fail_tasks={"worker:2"})
        co.schedule_tasks("true")
        co._drain_launches(timeout=30)
        task = co.session.get_task("worker", 2)
        assert task.status is TaskStatus.FAILED
        assert task.restarts == 2                 # budget fully consumed
        assert co.session.status.value == "FAILED"
        co.rpc_server.stop()

    def test_identical_directory_resources_dedupe(self, tmp_path):
        """Satellite: two job types listing the SAME directory content
        under one basename must localize once, not raise the collision
        error (the dedup previously only handled files)."""
        for parent in ("a", "b"):
            d = tmp_path / parent / "assets" / "sub"
            d.mkdir(parents=True)
            (d / "vocab.txt").write_text("tokens")
            (tmp_path / parent / "assets" / "top.json").write_text("{}")
        co = make_coordinator(tmp_path)
        req_w = types.SimpleNamespace(
            job_type="worker", resources=str(tmp_path / "a" / "assets"))
        req_p = types.SimpleNamespace(
            job_type="ps", resources=str(tmp_path / "b" / "assets"))
        co._localize_resources(req_w)
        co._localize_resources(req_p)        # identical tree: no error
        assert (tmp_path / "job" / "assets" / "sub" / "vocab.txt").exists()

        (tmp_path / "b" / "assets" / "sub" / "vocab.txt").write_text("DIFF")
        with pytest.raises(ValueError, match="collides"):
            co._localize_resources(req_p)    # different tree: still loud

        # type clash (file vs dir under the same name) lands in dircmp's
        # common_funny — it must read as "different", not silently pass
        c = tmp_path / "c" / "assets"
        c.mkdir(parents=True)
        (c / "top.json").write_text("{}")
        (c / "sub").write_text("a FILE named like the dir")
        with pytest.raises(ValueError, match="collides"):
            co._localize_resources(types.SimpleNamespace(
                job_type="eval", resources=str(c)))
        co.rpc_server.stop()


# ---------------------------------------------------------------------------
# Startup observability acceptance: tony_startup_* per gang on the LIVE
# /metrics exposition and in the jhist replay of the finished job
# ---------------------------------------------------------------------------
@pytest.mark.e2e
def test_startup_metrics_live_and_in_jhist_replay(fake_gcloud, tmp_path):
    import json
    import urllib.request

    from tony_tpu.client.client import TonyClient
    from tony_tpu.history.server import HistoryServer

    hist = str(tmp_path / "hist")
    conf = TonyConfig({
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.location": hist,
        "tony.application.timeout": "90000",
        "tony.scheduler.backend": "tpu",
        "tony.tpu.project": "p", "tony.tpu.zone": "z",
        "tony.tpu.accelerator-type": "v5litepod",
        "tony.tpu.state-refresh-ms": "200",
        "tony.worker.instances": "4",
        "tony.worker.slices": "2",
        "tony.worker.tpu.topology": "4x4",
        "tony.metrics.snapshot-interval-ms": "200",
        "tony.application.python-binary-path": sys.executable,
    })
    client = TonyClient(conf, 'bash -c "sleep 6"')
    result = {}
    t = threading.Thread(target=lambda: result.update(code=client.run()))
    t.start()
    server = None

    def get(port, path):
        with urllib.request.urlopen(
                f"http://localhost:{port}{path}", timeout=10) as r:
            return r.read().decode("utf-8")

    try:
        server = HistoryServer(TonyConfig({"tony.history.location": hist}),
                               port=0)
        server.start()
        # LIVE: the per-gang bring-up gauges ride the coordinator registry
        # (pseudo-task am:0) into METRICS_SNAPSHOT and hence /metrics
        deadline = time.monotonic() + 45
        text = ""
        while time.monotonic() < deadline and t.is_alive():
            try:
                text = get(server.port, "/metrics")
            except OSError:
                text = ""
            if 'tony_startup_provision_seconds{gang="worker/s1"' in text:
                break
            time.sleep(0.3)
        for gang in ("worker/s0", "worker/s1"):
            assert f'tony_startup_provision_seconds{{gang="{gang}"' in text
            assert f'tony_startup_stage_seconds{{gang="{gang}"' in text
        assert "tony_startup_dispatch_seconds" in text
    finally:
        t.join(timeout=120)
        if server is not None:
            server.stop()
    assert result.get("code") == 0

    # REPLAY: a fresh server reconstructs the same gauges and the LAUNCH
    # timeline purely from the finished jhist
    server2 = HistoryServer(TonyConfig({"tony.history.location": hist}),
                            port=0)
    server2.start()
    try:
        m = json.loads(get(server2.port,
                           f"/api/jobs/{client.app_id}/metrics"))
        gauges = {(name, labels.get("gang")): value
                  for name, labels, value in m["tasks"]["am:0"]["g"]}
        for gang in ("worker/s0", "worker/s1"):
            assert gauges[("tony_startup_provision_seconds", gang)] >= 0
            assert gauges[("tony_startup_stage_seconds", gang)] >= 0
        events = json.loads(get(server2.port,
                                f"/api/jobs/{client.app_id}/events"))
        launches = [e for e in events if e["event_type"] == "LAUNCH"]
        phases = {(e["payload"]["gang"], e["payload"]["phase"])
                  for e in launches}
        for gang in ("worker/s0", "worker/s1"):
            assert {(gang, "provision"), (gang, "stage"),
                    (gang, "dispatch")} <= phases
        # cold run: the stage really shipped (no stale cache hit)
        assert all(not e["payload"].get("cached") for e in launches
                   if e["payload"]["phase"] == "stage")
        page = get(server2.port, f"/jobs/{client.app_id}")
        assert "Bring-up timeline" in page
    finally:
        server2.stop()


# ---------------------------------------------------------------------------
# Launch-wall benchmark (bench.py arm) — deterministic tier-1 variant and
# the latency-realistic slow variant
# ---------------------------------------------------------------------------
class TestLaunchWall:
    def test_cold_parallel_under_2d_warm_ships_nothing(self):
        """Acceptance: with per-gang delay D injected into the fake
        gcloud, a 4-gang cold launch lands under 2*D (serial baseline
        ~4*D) and the warm restart ships zero tarballs."""
        import bench
        d = 2.0
        res = bench._launch_arm(num_gangs=4, create_delay_s=d,
                                scp_delay_s=0.0)
        assert res["launch_cold_parallel_wall_s"] < 2 * d, res
        assert res["launch_cold_serial_wall_s"] > 3 * d, res
        assert res["launch_warm_stage_skip"] == 1, res
        assert res["launch_warm_wall_s"] < d, res

    @pytest.mark.slow
    def test_launch_wall_realistic_latency(self):
        """Latency-realistic variant: slower create AND a real scp cost,
        so the ratio reflects staging too."""
        import bench
        res = bench._launch_arm(num_gangs=4, create_delay_s=6.0,
                                scp_delay_s=2.0)
        assert res["launch_cold_wall_vs_serial"] > 2.0, res
        assert res["launch_cold_parallel_wall_s"] < 2 * 6.0 + 2.0, res
        assert res["launch_warm_stage_skip"] == 1, res
        assert res["launch_warm_vs_cold"] > 2.0, res
