"""Tests for the gateway→cluster TCP proxy (tony-proxy analog)."""

import socket
import socketserver
import threading

from tony_tpu.proxy import ProxyServer


class _Echo(socketserver.BaseRequestHandler):
    def handle(self):
        while True:
            data = self.request.recv(4096)
            if not data:
                return
            self.request.sendall(data.upper())


def _start_echo():
    server = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _Echo)
    server.daemon_threads = True
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, server.server_address[1]


def test_proxy_pumps_both_directions():
    echo, echo_port = _start_echo()
    proxy = ProxyServer("127.0.0.1", echo_port)
    port = proxy.start()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=5) as c:
            c.sendall(b"hello tony")
            assert c.recv(4096) == b"HELLO TONY"
            c.sendall(b"again")
            assert c.recv(4096) == b"AGAIN"
    finally:
        proxy.stop()
        echo.shutdown()


def test_proxy_concurrent_connections():
    echo, echo_port = _start_echo()
    proxy = ProxyServer("127.0.0.1", echo_port)
    port = proxy.start()
    errors = []

    def client(i):
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=5) as c:
                msg = f"msg-{i}".encode()
                c.sendall(msg)
                assert c.recv(4096) == msg.upper()
        except Exception as e:  # pragma: no cover - diagnostic
            errors.append(e)

    try:
        threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert not errors
    finally:
        proxy.stop()
        echo.shutdown()


def test_proxy_unreachable_upstream_closes_client():
    # Port 1 on localhost: connection refused — proxy must close the client
    # socket instead of hanging.
    proxy = ProxyServer("127.0.0.1", 1)
    port = proxy.start()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=5) as c:
            c.settimeout(5)
            assert c.recv(4096) == b""   # EOF
    finally:
        proxy.stop()


def test_proxy_stop_unbinds_port():
    proxy = ProxyServer("127.0.0.1", 9)
    port = proxy.start()
    proxy.stop()
    # Port is released: a fresh bind to it succeeds.
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("", port))
