"""End-to-end suite: real client → coordinator → executor subprocess trees.

The TPU-build analog of the reference's ``TestTonyE2E`` (reference: tony-core/
src/test/java/com/linkedin/tony/TestTonyE2E.java:69-273, 13 scenarios on an
in-process MiniYARN cluster). Here the fake cluster is the local subprocess
backend; every test submits through the real TonyClient and asserts on the
exit code, with the same chaos-env-hook coverage (HB miss, AM crash, worker
termination, skew)."""

import json
import time
import os
import subprocess
import sys

import pytest

from tony_tpu.client.client import TonyClient
from tony_tpu.conf.config import TonyConfig
from tony_tpu.events.events import find_job_files, parse_events

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
PY = sys.executable


def make_client(tmp_path, command, confs=None, shell_env=None, src_dir=None):
    base = {
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.location": str(tmp_path / "tony-history"),
        "tony.application.timeout": "60000",   # safety net for the suite
    }
    base.update(confs or {})
    conf = TonyConfig(base)
    return TonyClient(conf, command, src_dir=src_dir, shell_env=shell_env)


def fixture_cmd(name, *args):
    return " ".join([PY, os.path.join(FIXTURES, name), *args])


def test_stage_src_dir_containing_staging_dir(tmp_path):
    """Regression: --src_dir pointing at the tree that contains the staging
    root must not copytree the growing job dir into itself."""
    src = tmp_path / "project"
    src.mkdir()
    (src / "train.py").write_text("print('hi')\n")
    conf = TonyConfig({"tony.staging.dir": str(src / ".tony")})
    client = TonyClient(conf, "true", src_dir=str(src))
    client.stage()   # used to recurse until ENAMETOOLONG
    staged = os.path.join(client.job_dir, "project")
    assert os.path.exists(os.path.join(staged, "train.py"))
    assert not os.path.exists(os.path.join(staged, ".tony"))


def test_stage_src_dir_equal_to_staging_dir(tmp_path):
    """Harder regression: staging dir == src dir — the job dir is then a
    direct child of the copied tree and must itself be skipped."""
    src = tmp_path / "everything"
    src.mkdir()
    (src / "train.py").write_text("print('hi')\n")
    conf = TonyConfig({"tony.staging.dir": str(src)})
    client = TonyClient(conf, "true", src_dir=str(src))
    client.stage()
    staged = os.path.join(client.job_dir, "everything")
    assert os.path.exists(os.path.join(staged, "train.py"))
    assert not os.path.exists(os.path.join(staged, client.app_id))


@pytest.mark.e2e
class TestE2E:
    def test_single_worker_succeeds(self, tmp_path):
        client = make_client(tmp_path, fixture_cmd("exit_0.py"),
                             {"tony.worker.instances": "1"})
        assert client.run() == 0

    def test_worker_failure_fails_job(self, tmp_path):
        client = make_client(tmp_path, fixture_cmd("exit_1.py"),
                             {"tony.worker.instances": "1"})
        assert client.run() == 1

    def test_ps_worker_topology(self, tmp_path):
        """2 workers + 1 ps; ps sleeps forever and is untracked — the job
        must finish when workers do (reference: tracked-jobtype semantics)."""
        client = make_client(
            tmp_path,
            f'bash -c "if [ $JOB_NAME = ps ]; then {fixture_cmd("sleep_forever.py")};'
            f' else {fixture_cmd("exit_0.py")}; fi"',
            {"tony.worker.instances": "2", "tony.ps.instances": "1"})
        assert client.run() == 0

    def test_shell_env_propagation(self, tmp_path):
        client = make_client(tmp_path, fixture_cmd("check_env.py"),
                             {"tony.worker.instances": "1"},
                             shell_env={"TONY_TEST_SHELL_VAR": "hello"})
        assert client.run() == 0

    def test_jax_runtime_env(self, tmp_path):
        client = make_client(tmp_path, fixture_cmd("check_jax_env.py"),
                             {"tony.worker.instances": "2",
                              "tony.ps.instances": "1",
                              "tony.application.mesh": "dp=2"})
        assert client.run() == 0

    def test_multi_slice_env(self, tmp_path):
        """tony.worker.slices=2: every task learns its gang (TONY_SLICE_ID /
        TONY_NUM_SLICES) and the DCN mesh layout rides mesh_spec."""
        client = make_client(tmp_path, fixture_cmd("check_slice_env.py"),
                             {"tony.worker.instances": "4",
                              "tony.worker.slices": "2",
                              "tony.application.mesh": "tp=-1",
                              "tony.application.mesh.dcn": "dp=2"})
        assert client.run() == 0

    def test_pytorch_runtime_env(self, tmp_path):
        client = make_client(tmp_path, fixture_cmd("check_pytorch_env.py"),
                             {"tony.worker.instances": "2",
                              "tony.application.framework": "pytorch"})
        assert client.run() == 0

    def test_heartbeat_miss_fails_job(self, tmp_path):
        """Executor skips pings while the task sleeps → liveness expiry →
        job fails (reference: testTaskExecutorHeartbeatMiss)."""
        client = make_client(
            tmp_path, fixture_cmd("sleep_briefly.py", "10"),
            {"tony.worker.instances": "1",
             "tony.task.heartbeat-interval-ms": "100",
             "tony.task.max-missed-heartbeats": "3"},
            shell_env={"TEST_TASK_EXECUTOR_NUM_HB_MISS": "100"})
        assert client.run() == 1

    def test_am_crash_fails_job(self, tmp_path):
        """Coordinator suicide → no final status → client reports failure
        (reference: testAMCrashTonyShouldFail)."""
        client = make_client(tmp_path, fixture_cmd("exit_0.py"),
                             {"tony.worker.instances": "1"},
                             shell_env={"TEST_AM_CRASH": "true"})
        assert client.run() == 1

    def test_worker_termination_fails_job(self, tmp_path):
        """Chief registers → chaos kills worker:1 → gang failure
        (reference: testAMStopsJobAfterWorker0Killed)."""
        client = make_client(
            tmp_path,
            fixture_cmd("sleep_briefly.py", "15"),
            {"tony.worker.instances": "2"},
            shell_env={"TEST_WORKER_TERMINATION": "true"})
        assert client.run() == 1

    def test_session_retry_recovers(self, tmp_path):
        """First session fails (worker exits 1 once), retry succeeds: the
        fixture exits 1 iff a marker file does not exist yet, then creates it
        (reference: AM retry loop, TonyApplicationMaster.java:351-377)."""
        marker = tmp_path / "attempt.marker"
        cmd = (f'bash -c "if [ -f {marker} ]; then exit 0; '
               f'else touch {marker}; exit 1; fi"')
        client = make_client(tmp_path, cmd,
                             {"tony.worker.instances": "1",
                              "tony.am.retry-count": "1"})
        assert client.run() == 0

    def test_skew_chaos_still_succeeds(self, tmp_path):
        client = make_client(
            tmp_path, fixture_cmd("exit_0.py"),
            {"tony.worker.instances": "2"},
            shell_env={"TEST_TASK_EXECUTOR_SKEW": "worker#0#1500"})
        assert client.run() == 0

    def test_execution_timeout_kills_task(self, tmp_path):
        client = make_client(
            tmp_path, fixture_cmd("sleep_forever.py"),
            {"tony.worker.instances": "1",
             "tony.task.execution-timeout-ms": "1500"})
        assert client.run() == 1

    def test_history_events_written(self, tmp_path):
        client = make_client(tmp_path, fixture_cmd("exit_0.py"),
                             {"tony.worker.instances": "1"})
        assert client.run() == 0
        hist_dir = client.conf.get("tony.history.location")
        files = find_job_files(hist_dir)
        assert len(files) == 1 and files[0].endswith(".jhist")
        types = [e.event_type for e in parse_events(files[0])]
        assert types[0] == "APPLICATION_INITED"
        assert "TASK_REGISTERED" in types and "TASK_FINISHED" in types
        assert types[-1] == "APPLICATION_FINISHED"
        assert "SUCCEEDED" in os.path.basename(files[0])

    @pytest.mark.slow
    def test_distributed_jax_mnist_trains(self, tmp_path):
        """The minimum end-to-end slice (SURVEY.md §7.5): client →
        coordinator → 2 local workers → jax.distributed bootstrap over the
        gang barrier → data-parallel MNIST trains across both processes and
        exits 0. JAX_PLATFORMS=cpu + a clean PYTHONPATH keep the worker
        processes on the multi-process CPU backend."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "examples", "mnist", "mnist_distributed.py")
        client = make_client(
            tmp_path, f"{PY} {script} --steps 60 --batch_size 128",
            {"tony.worker.instances": "2",
             "tony.application.mesh": "dp=-1",
             "tony.application.timeout": "120000"},
            shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                       # 1 device per process (don't inherit the harness's
                       # 8-virtual-device XLA_FLAGS — 16 gloo ranks crawl)
                       "XLA_FLAGS": ""})
        assert client.run() == 0
        out = open(os.path.join(client.job_dir, "logs",
                                "worker-0.stdout")).read() + \
            open(os.path.join(client.job_dir, "logs", "worker-1.stdout")).read()
        assert "2 global devices" in out       # both processes federated
        assert "done:" in out

    @pytest.mark.slow
    @pytest.mark.parametrize("pp_schedule", ["gpipe", "1f1b"])
    def test_distributed_pipeline_parallel_lm_trains(self, tmp_path,
                                                     pp_schedule):
        """Pipeline parallelism across PROCESSES: 2 workers × 1 CPU device,
        mesh pp=2 — each process holds one stage of the flagship LM and
        activations hop stage→stage over the gloo collective backend (the
        same ppermute pattern that rides DCN between slices on real TPU).
        The batch is replicated over pp, so both processes must feed
        identical data (train.data_parallel_rank seeding). Both schedules
        drive the same CLI: gpipe differentiates through lm_loss, 1f1b
        routes through lm_value_and_grad via the value_and_grad hook."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "examples", "lm", "train_lm.py")
        client = make_client(
            tmp_path, f"{PY} {script} --steps 12 --batch_size 8 "
                      f"--seq_len 64 --preset tiny "
                      f"--pp_schedule {pp_schedule}",
            {"tony.worker.instances": "2",
             "tony.application.mesh": "pp=2,dp=-1",
             "tony.application.timeout": "180000"},
            shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                       "XLA_FLAGS": ""})
        assert client.run() == 0
        out = open(os.path.join(client.job_dir, "logs",
                                "worker-0.stdout")).read() + \
            open(os.path.join(client.job_dir, "logs", "worker-1.stdout")).read()
        assert "'pp': 2" in out       # train_lm prints the resolved mesh
        # schedule-specific: a silent fallback to the other schedule fails
        # (train_lm prints the RESOLVED branch, not the flag)
        assert f"pipeline schedule: {pp_schedule}" in out
        assert "done:" in out

    @pytest.mark.slow
    def test_serving_job_through_the_cluster(self, tmp_path):
        """Serving rides the SAME submission path as training: a
        single-worker job runs the continuous-batching example
        (examples/lm/serve_lm.py — speculative + sampled mode, the full
        serving stack) through client → coordinator → executor and exits
        0 with its served-request report in the task log. The reference
        has no serving path at all; this pins that the green-field one
        composes with the orchestration layer."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "examples", "lm", "serve_lm.py")
        client = make_client(
            tmp_path, f"{PY} {script} --preset tiny --draft_preset tiny "
                      f"--requests 5 --slots 2 --max_new_tokens 8 "
                      f"--temperature 0.8 --top_k 40",
            {"tony.worker.instances": "1",
             "tony.application.timeout": "180000"},
            shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                       "XLA_FLAGS": ""})
        assert client.run() == 0
        out = open(os.path.join(client.job_dir, "logs",
                                "worker-0.stdout")).read()
        assert "served 5 requests" in out
        assert "speculative sampled" in out
        assert "speculative rounds:" in out

    @pytest.mark.slow
    def test_serving_with_quantized_ring_cache_through_the_cluster(
            self, tmp_path):
        """The round-5 serving levers compose with the orchestration
        layer: a cluster-submitted serving job runs with the int8 KV
        cache, weight-only int8 matmuls, sliding-window attention, and
        the rolling ring cache all enabled (streams wrap past the
        32-row capacity; the ring's past-max_len ceiling lift is
        unit-tested in test_decode.py's TestRollingCache) and exits
        0."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "examples", "lm", "serve_lm.py")
        client = make_client(
            tmp_path, f"{PY} {script} --preset tiny --requests 4 "
                      f"--slots 2 --prompt_len 10 --max_new_tokens 40 "
                      f"--kv_cache_dtype int8 --quantize_weights "
                      f"--attn_window 24 --kv_cache_capacity 32",
            {"tony.worker.instances": "1",
             "tony.application.timeout": "180000"},
            shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                       "XLA_FLAGS": ""})
        assert client.run() == 0
        out = open(os.path.join(client.job_dir, "logs",
                                "worker-0.stdout")).read()
        assert "served 4 requests" in out
        assert "weight-only int8" in out

    def test_per_task_restart_within_session(self, tmp_path):
        """tony.task.restart-count: one worker fails once, is relaunched
        IN-SESSION (no whole-job reset — the reference kills the job and
        marks per-task restart TODO, TonyApplicationMaster.java:1158-1159),
        and the job succeeds. The jhist shows TASK_RESTARTED and no
        SESSION_RESET; uptime metrics carry the restart count."""
        client = make_client(
            tmp_path, fixture_cmd("fail_once.py"),
            {"tony.worker.instances": "2",
             "tony.task.restart-count": "1",
             "tony.am.retry-count": "0"})       # no session retries: the
        assert client.run() == 0                # restart must carry it
        hist_dir = client.conf.get("tony.history.location")
        files = find_job_files(hist_dir)
        events = list(parse_events(files[0]))
        types = [e.event_type for e in events]
        assert "TASK_RESTARTED" in types
        assert "SESSION_RESET" not in types
        restarted = [e for e in events if e.event_type == "TASK_RESTARTED"]
        assert restarted[0].payload["restarts"] == 1
        finished = [e for e in events
                    if e.event_type == "APPLICATION_FINISHED"][-1]
        assert finished.payload["metrics"]["task_restarts"] == {
            restarted[0].payload["task"]: 1}

    def test_per_task_restart_budget_exhausted_fails(self, tmp_path):
        """Failures beyond the restart budget still fail the session: the
        non-chief worker ALWAYS fails, so restart 1 is consumed and the
        second failure lands as a session failure (the chief sleeps so its
        verdict cannot pre-empt the sequence)."""
        client = make_client(
            tmp_path,
            f'bash -c "if [ $TASK_INDEX != 0 ]; then exit 1; '
            f'else {fixture_cmd("sleep_briefly.py", "10")}; fi"',
            {"tony.worker.instances": "2",
             "tony.task.restart-count": "1",
             "tony.am.retry-count": "0"})
        assert client.run() == 1
        hist_dir = client.conf.get("tony.history.location")
        files = find_job_files(hist_dir)
        types = [e.event_type for e in parse_events(files[0])]
        assert types.count("TASK_RESTARTED") == 1    # budget spent once

    def test_chief_failure_not_restarted(self, tmp_path):
        """The chief's exit is the job's verdict — never restarted."""
        client = make_client(
            tmp_path, fixture_cmd("fail_once.py"),
            {"tony.worker.instances": "1",      # worker:0 is implicit chief
             "tony.task.restart-count": "3",
             "tony.am.retry-count": "0"},
            shell_env={"FAIL_ONCE_INCLUDE_CHIEF": "1"})
        assert client.run() == 1

    def test_slice_preemption_retried_from_own_budget(self, tmp_path):
        """TEST_PREEMPT_SLICE kills the worker gang once and reports it
        preempted; with tony.am.retry-count=0 the job must STILL succeed —
        infrastructure preemption retries come from the separate
        tony.tpu.preemption-retries budget (SURVEY.md §7 hard part (d))."""
        client = make_client(
            tmp_path, fixture_cmd("sleep_briefly.py", "3"),
            {"tony.worker.instances": "1",
             "tony.am.retry-count": "0"},
            shell_env={"TEST_PREEMPT_SLICE": "worker"})
        assert client.run() == 0

    def test_preemption_budget_exhausted_fails(self, tmp_path):
        client = make_client(
            tmp_path, fixture_cmd("sleep_briefly.py", "3"),
            {"tony.worker.instances": "1",
             "tony.tpu.preemption-retries": "0"},
            shell_env={"TEST_PREEMPT_SLICE": "worker"})
        assert client.run() == 1

    def test_kill_reaps_user_processes(self, tmp_path):
        """The untracked ps task runs sleep_forever; when the workers finish
        and the coordinator tears the job down, the actual user process (a
        grandchild in its own session) must die too — not just its executor
        (regression: killpg only reached the executor's group)."""
        marker = f"tony-orphan-{os.getpid()}"
        client = make_client(
            tmp_path,
            f'bash -c "if [ $JOB_NAME = ps ]; then '
            f'{fixture_cmd("sleep_forever.py")} {marker}; '
            f'else {fixture_cmd("exit_0.py")}; fi"',
            {"tony.worker.instances": "1", "tony.ps.instances": "1"})
        assert client.run() == 0
        import time as _time
        for _ in range(50):   # PDEATHSIG/TERM-forwarding needs a beat
            alive = subprocess.run(["pgrep", "-f", marker],
                                   capture_output=True).returncode == 0
            if not alive:
                break
            _time.sleep(0.1)
        assert not alive, "user training process leaked after job teardown"

    def test_task_logs_written(self, tmp_path):
        client = make_client(
            tmp_path, 'bash -c "echo training-output-marker; exit 0"',
            {"tony.worker.instances": "1"})
        assert client.run() == 0
        log = os.path.join(client.job_dir, "logs", "worker-0.stdout")
        assert os.path.exists(log)
        assert "training-output-marker" in open(log).read()

    def test_security_enabled_job_succeeds(self, tmp_path):
        """With tony.application.security.enabled, the client mints a per-job
        secret, the coordinator enforces it on every RPC, and executors
        authenticate via their launch env — the job still runs end to end."""
        client = make_client(tmp_path, fixture_cmd("exit_0.py"),
                             {"tony.worker.instances": "2",
                              "tony.application.security.enabled": "true"})
        assert client.secret is not None
        assert client.run() == 0
        secret_file = os.path.join(client.job_dir, ".tony-secret")
        assert os.path.exists(secret_file)
        assert oct(os.stat(secret_file).st_mode & 0o777) == "0o600"
        with open(secret_file) as f:
            assert f.read() == client.secret

    def test_tls_job_succeeds_and_rejects_plaintext(self, tmp_path):
        """tony.tls.enabled: the coordinator serves gRPC over the per-job
        cert, executors pin their channels via the staged cert path, and
        the whole job succeeds; a plaintext probe against the live
        coordinator fails its handshake."""
        from tony_tpu.rpc.client import ApplicationRpcClient
        import threading
        client = make_client(tmp_path, fixture_cmd("sleep_briefly.py", "3"),
                             {"tony.worker.instances": "2",
                              "tony.application.security.enabled": "true",
                              "tony.tls.enabled": "true"})
        probe_result = {}

        def probe():
            # wait for the coordinator address, then poke it WITHOUT TLS
            for _ in range(100):
                addr_file = os.path.join(client.job_dir, "coordinator.addr")
                if os.path.exists(addr_file):
                    break
                time.sleep(0.1)
            else:
                probe_result["error"] = "no coordinator addr"
                return
            with open(addr_file) as f:
                addr = f.read().strip()
            c = ApplicationRpcClient(addr, max_retries=2,
                                     base_backoff_s=0.05, tls_cert=None)
            try:
                c.get_application_status()
                probe_result["plaintext_accepted"] = True
            except Exception:
                probe_result["plaintext_accepted"] = False
            finally:
                c.close()

        t = threading.Thread(target=probe)
        t.start()
        rc = client.run()
        t.join(timeout=30)
        assert rc == 0
        assert probe_result.get("plaintext_accepted") is False, probe_result
        key_file = os.path.join(client.job_dir, ".tony-tls.key")
        cert_file = os.path.join(client.job_dir, ".tony-tls.crt")
        assert os.path.exists(key_file) and os.path.exists(cert_file)
        assert oct(os.stat(key_file).st_mode & 0o777) == "0o600"

    def test_security_rejects_unauthenticated_probe(self, tmp_path):
        """An RPC probe without the token is refused while the job runs."""
        import grpc
        import threading
        from tony_tpu.rpc.client import ApplicationRpcClient

        client = make_client(tmp_path, fixture_cmd("sleep_briefly.py"),
                             {"tony.worker.instances": "1",
                              "tony.application.security.enabled": "true"})
        result = {}

        def run():
            result["code"] = client.run()

        t = threading.Thread(target=run)
        t.start()
        try:
            addr = None
            while addr is None and t.is_alive():
                addr = client._read_coordinator_addr()
            if addr:
                probe = ApplicationRpcClient(addr, secret=None, max_retries=2)
                with pytest.raises(grpc.RpcError) as ei:
                    probe.get_task_urls()
                assert ei.value.code() == grpc.StatusCode.UNAUTHENTICATED
                probe.close()
        finally:
            t.join(timeout=60)
        assert result.get("code") == 0

    def test_notebook_job_proxied(self, tmp_path):
        """Notebook flow: single notebook task gets $NOTEBOOK_PORT, registers
        its endpoint as the tracking URL, the client fires on_tracking_url,
        and a ProxyServer forwards a local port to it (reference:
        NotebookSubmitter.java:93-106 + tony-proxy)."""
        import urllib.request
        from tony_tpu.proxy import ProxyServer

        conf = TonyConfig({
            "tony.staging.dir": str(tmp_path / "staging"),
            "tony.history.location": str(tmp_path / "tony-history"),
            "tony.application.timeout": "60000",
            "tony.notebook.instances": "1",
        })
        fetched = {}

        def on_url(url):
            host, _, port = url.split("//")[-1].rstrip("/").rpartition(":")
            proxy = ProxyServer(host, int(port))
            local = proxy.start()
            # The tracking URL is registered before the user process binds
            # its server (same ordering as the reference) — retry the fetch
            # until the notebook is actually listening.
            deadline = time.monotonic() + 12
            while time.monotonic() < deadline:
                try:
                    with urllib.request.urlopen(
                            f"http://localhost:{local}/", timeout=5) as resp:
                        fetched["body"] = resp.read()
                    break
                except OSError:
                    time.sleep(0.3)
            proxy.stop()

        client = TonyClient(conf, fixture_cmd("notebook_server.py"),
                            on_tracking_url=on_url)
        assert client.run() == 0
        assert fetched.get("body") == b"notebook-ok"

    def test_notebook_cli_end_to_end(self, tmp_path):
        """Drive the REAL `tony notebook` CLI path: single-node mode means
        no executors ever run, so the coordinator itself must export
        $NOTEBOOK_PORT (pointing where the tracking URL / proxy points).
        Regression: only the executor set NOTEBOOK_PORT, so CLI notebooks
        got an empty port while the proxy pointed at tb_port."""
        import threading
        import urllib.request
        from tony_tpu.client import cli

        result = {}

        def run():
            result["code"] = cli.main([
                "notebook",
                "--executes", fixture_cmd("notebook_server.py"),
                "--conf", f"tony.staging.dir={tmp_path / 'staging'}",
                "--conf", f"tony.history.location={tmp_path / 'hist'}",
                "--conf", "tony.application.timeout=60000",
            ])

        t = threading.Thread(target=run)
        t.start()
        try:
            deadline = time.monotonic() + 30
            body = None
            while time.monotonic() < deadline and t.is_alive():
                proxy = cli._notebook_proxy
                if proxy is None:
                    time.sleep(0.2)
                    continue
                try:
                    with urllib.request.urlopen(
                            f"http://localhost:{proxy.local_port}/",
                            timeout=5) as resp:
                        body = resp.read()
                    break
                except OSError:
                    time.sleep(0.3)
            assert body == b"notebook-ok"
        finally:
            t.join(timeout=60)
            if cli._notebook_proxy is not None:
                cli._notebook_proxy.stop()
                cli._notebook_proxy = None
        assert result.get("code") == 0

    @pytest.mark.slow
    def test_distributed_pytorch_example_trains(self, tmp_path):
        """PyTorch runtime-adapter parity: 2 workers build a gloo process
        group from the exported RANK/WORLD/INIT_METHOD and train with manual
        all-reduce (the reference's mnist-pytorch recipe)."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "examples", "mnist-pytorch",
                              "mnist_distributed.py")
        client = make_client(
            tmp_path, f"{PY} {script} --steps 30",
            {"tony.worker.instances": "2",
             "tony.application.framework": "pytorch",
             "tony.application.timeout": "120000"},
            shell_env={"PYTHONPATH": repo})
        assert client.run() == 0
        out = open(os.path.join(client.job_dir, "logs",
                                "worker-0.stdout")).read()
        assert "process group up" in out
        assert "final loss" in out

    @pytest.mark.slow
    def test_lm_example_resumes_after_am_retry(self, tmp_path):
        """Checkpoint/resume across coordinator retries: a worker that
        crashes mid-training on attempt 0 resumes from its checkpoint on the
        retried session instead of restarting from step 0."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "examples", "lm", "train_lm.py")
        ckpt = tmp_path / "ckpt"
        # Crash the first session partway: die at checkpoint step >= 8 while
        # SESSION_ID is 0; the coordinator's retry rebuilds the session
        # (session_id+1) and the rerun must resume, not restart.
        crash_wrapper = tmp_path / "crashy.py"
        crash_wrapper.write_text(f"""
import os, runpy, sys
if int(os.environ.get("SESSION_ID", "0")) == 0:
    import tony_tpu.models.checkpoint as C
    orig = C.CheckpointManager.save
    def crashing_save(self, step, state, force=False):
        saved = orig(self, step, state, force=force)
        if step >= 8:
            self.wait_until_finished()
            os._exit(1)
        return saved
    C.CheckpointManager.save = crashing_save
sys.argv = ["train_lm.py", "--steps", "14", "--ckpt_dir", r"{ckpt}",
            "--ckpt_every", "2", "--batch_size", "2", "--seq_len", "32"]
runpy.run_path(r"{script}", run_name="__main__")
""")
        client = make_client(
            tmp_path, f"{PY} {crash_wrapper}",
            {"tony.worker.instances": "1",
             "tony.am.retry-count": "2",
             "tony.application.timeout": "180000"},
            shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                       "XLA_FLAGS": ""})
        assert client.run() == 0
        out = open(os.path.join(client.job_dir, "logs",
                                "worker-0.stdout")).read()
        assert "done:" in out
        # Resumed, not restarted: step 0 trained exactly once (session 1
        # would print "step 0" again if it had started from scratch).
        assert out.count("step 0 loss") == 1
        # And the retried session reached the end.
        assert "step 13" in out

    def test_single_node_job_runs_in_coordinator(self, tmp_path):
        """tony.application.single-node: the user command runs inside the
        coordinator (no task fleet) and its exit code is the job result
        (reference: doPreprocessingJob + single-node short-circuit)."""
        out_file = tmp_path / "single.txt"
        client = make_client(
            tmp_path,
            f'bash -c "echo ran-in-$PREPROCESSING_JOB > {out_file}"',
            {"tony.application.single-node": "true"})
        assert client.run() == 0
        assert out_file.read_text().strip() == "ran-in-true"
        # No executor logs: nothing was scheduled.
        logs = os.listdir(os.path.join(client.job_dir, "logs"))
        assert not any(n.startswith("worker") for n in logs)
        assert "am-preprocess.stdout" in logs

    def test_single_node_failure_fails_job(self, tmp_path):
        client = make_client(tmp_path, "false",
                             {"tony.application.single-node": "true"})
        assert client.run() == 1

    def test_preprocess_runs_before_workers(self, tmp_path):
        """tony.application.enable-preprocess: command runs once in the
        coordinator first, then again in each scheduled worker."""
        marker = tmp_path / "pre.txt"
        cmd = (f'bash -c "if [ \\"$PREPROCESSING_JOB\\" = true ]; then '
               f'echo pre > {marker}; else test -f {marker}; fi"')
        client = make_client(
            tmp_path, cmd,
            {"tony.worker.instances": "2",
             "tony.application.enable-preprocess": "true"})
        assert client.run() == 0
        assert marker.exists()

    def test_preprocess_failure_short_circuits(self, tmp_path):
        """A failing preprocess fails the job without scheduling workers."""
        client = make_client(
            tmp_path,
            'bash -c "if [ \\"$PREPROCESSING_JOB\\" = true ]; then exit 7; fi"',
            {"tony.worker.instances": "1",
             "tony.application.enable-preprocess": "true"})
        assert client.run() == 1
        logs = os.listdir(os.path.join(client.job_dir, "logs"))
        assert not any(n.startswith("worker") for n in logs)

    @pytest.mark.slow
    def test_distributed_resnet_dp_trains(self, tmp_path):
        """Progression config: ResNet DP across 2 processes (the 8w config
        at test scale — same code path, the instance count is config)."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "examples", "resnet", "train_resnet.py")
        client = make_client(
            tmp_path,
            f"{PY} {script} --steps 3 --batch_size 4 --image_size 32 "
            f"--num_classes 10 --lr 0.01",
            {"tony.worker.instances": "2",
             "tony.application.mesh": "dp=-1",
             "tony.application.timeout": "180000"},
            shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                       "XLA_FLAGS": ""})
        assert client.run() == 0
        out = open(os.path.join(client.job_dir, "logs",
                                "worker-0.stdout")).read()
        assert "devices=2" in out
        assert "done:" in out

    @pytest.mark.slow
    def test_distributed_bert_mlm_trains(self, tmp_path):
        """Progression config: BERT MLM pretraining, jax.distributed
        multi-host (2 processes at test scale of the 16w config)."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "examples", "bert", "pretrain_bert.py")
        client = make_client(
            tmp_path,
            f"{PY} {script} --steps 3 --batch_size 4 --seq_len 64",
            {"tony.worker.instances": "2",
             "tony.application.mesh": "dp=-1",
             "tony.application.timeout": "180000"},
            shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                       "XLA_FLAGS": ""})
        assert client.run() == 0
        out = open(os.path.join(client.job_dir, "logs",
                                "worker-0.stdout")).read()
        assert "2 global devices" in out
        assert "done:" in out

    @pytest.mark.slow
    @pytest.mark.parametrize("strategy", ["ring", "ulysses"])
    def test_distributed_context_parallel_lm_trains(self, tmp_path, strategy):
        """Long-context config: the LM trains with the sequence sharded over
        a 2-process cp mesh axis — ring attention's ppermute (or Ulysses'
        all-to-all) collectives run across real process boundaries, not
        just virtual devices."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "examples", "lm", "train_lm.py")
        client = make_client(
            tmp_path,
            f"{PY} {script} --steps 3 --batch_size 2 --seq_len 128 "
            f"--preset tiny --cp_strategy {strategy}",
            {"tony.worker.instances": "2",
             "tony.application.mesh": "cp=2",
             "tony.application.timeout": "180000"},
            shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                       "XLA_FLAGS": ""})
        assert client.run() == 0
        out = open(os.path.join(client.job_dir, "logs",
                                "worker-0.stdout")).read()
        assert "'cp': 2" in out
        assert "done:" in out

    def test_venv_unzipped_and_on_path(self, tmp_path):
        """A staged venv.zip is extracted once per host and its bin/ leads
        PATH in the user process (reference: TaskExecutor.java:96-105)."""
        import zipfile
        venv_zip = tmp_path / "venv.zip"
        with zipfile.ZipFile(venv_zip, "w") as zf:
            zf.writestr("bin/myvenvtool", "#!/bin/bash\necho venv-tool-ran\n")
        client = make_client(
            tmp_path, "myvenvtool",
            {"tony.worker.instances": "2",
             "tony.application.python-venv": str(venv_zip)})
        assert client.run() == 0
        out = open(os.path.join(client.job_dir, "logs",
                                "worker-0.stdout")).read()
        assert "venv-tool-ran" in out

    def test_job_type_resources_localized(self, tmp_path):
        """tony.<job>.resources files are copied into the job dir before
        launch (reference: ContainerLauncher.run:1090-1104)."""
        extra = tmp_path / "vocab.txt"
        extra.write_text("hello-vocab")
        client = make_client(
            tmp_path, 'bash -c "grep -q hello-vocab vocab.txt"',
            {"tony.worker.instances": "1",
             "tony.worker.resources": str(extra)})
        assert client.run() == 0

    def test_missing_resource_fails_job(self, tmp_path):
        client = make_client(
            tmp_path, "true",
            {"tony.worker.instances": "1",
             "tony.worker.resources": str(tmp_path / "nope.bin")})
        assert client.run() == 1

    def test_venv_with_symlinks_extracted_correctly(self, tmp_path):
        """A real pip venv zips bin/python as a symlink; extraction must
        recreate it as a link (ZipFile.extractall writes the target path as
        file CONTENT — the classic broken-venv failure)."""
        import stat
        import zipfile
        venv_zip = tmp_path / "venv.zip"
        with zipfile.ZipFile(venv_zip, "w") as zf:
            zf.writestr("bin/real-tool",
                        "#!/bin/bash\necho symlinked-venv-ok\n")
            link = zipfile.ZipInfo("bin/tool-link")
            link.external_attr = (stat.S_IFLNK | 0o777) << 16
            zf.writestr(link, "real-tool")
        client = make_client(
            tmp_path, "tool-link",
            {"tony.worker.instances": "1",
             "tony.application.python-venv": str(venv_zip)})
        assert client.run() == 0
        out = open(os.path.join(client.job_dir, "logs",
                                "worker-0.stdout")).read()
        assert "symlinked-venv-ok" in out

    def test_conflicting_resources_fail_loudly(self, tmp_path):
        """Two job types localizing DIFFERENT files under one basename must
        error, not silently serve the first file to both."""
        (tmp_path / "a").mkdir(); (tmp_path / "b").mkdir()
        (tmp_path / "a" / "config.json").write_text('{"for": "worker"}')
        (tmp_path / "b" / "config.json").write_text('{"for": "ps"}')
        client = make_client(
            tmp_path, "true",
            {"tony.worker.instances": "1",
             "tony.ps.instances": "1",
             "tony.worker.resources": str(tmp_path / "a" / "config.json"),
             "tony.ps.resources": str(tmp_path / "b" / "config.json")})
        assert client.run() == 1

    @pytest.mark.slow
    def test_distributed_tensorflow_example_trains(self, tmp_path):
        """Progression config: TF2 MultiWorkerMirroredStrategy consumes the
        exported TF_CONFIG across 2 workers (reference parity for the
        mnist-tensorflow example)."""
        pytest.importorskip("tensorflow")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "examples", "mnist-tensorflow",
                              "mnist_distributed.py")
        client = make_client(
            tmp_path, f"{PY} {script} --steps 20 --batch_size 32",
            {"tony.worker.instances": "2",
             "tony.application.framework": "tensorflow",
             "tony.application.timeout": "240000"},
            shell_env={"PYTHONPATH": repo, "CUDA_VISIBLE_DEVICES": "-1"})
        assert client.run() == 0
        out = open(os.path.join(client.job_dir, "logs",
                                "worker-0.stdout")).read()
        assert "'type': 'worker', 'index': 0" in out
        assert "final loss" in out

    @pytest.mark.slow
    def test_lm_trains_from_sharded_files(self, tmp_path):
        """Full data path: binary token shards → per-process byte-range
        splits (tony_tpu.io) → global sharded batches → train step, across
        2 workers."""
        import numpy as np
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "examples", "lm", "train_lm.py")
        data = tmp_path / "data"
        data.mkdir()
        rng = np.random.RandomState(0)
        files = []
        for i in range(3):
            p = data / f"shard{i}.bin"
            rng.randint(0, 1024, size=(40, 33)).astype(np.int32).tofile(p)
            files.append(str(p))
        client = make_client(
            tmp_path,
            f"{PY} {script} --steps 4 --batch_size 2 --seq_len 32 "
            f"--preset tiny --data_files {' '.join(files)}",
            {"tony.worker.instances": "2",
             "tony.application.mesh": "dp=-1",
             "tony.application.timeout": "180000"},
            shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                       "XLA_FLAGS": ""})
        assert client.run() == 0
        out = open(os.path.join(client.job_dir, "logs",
                                "worker-0.stdout")).read()
        assert "done:" in out

    def test_tony_kill_terminates_running_job(self, tmp_path):
        """`tony kill <job_dir>`: an out-of-band finishApplication while
        tasks run reduces the job to KILLED and tears everything down."""
        import threading
        from tony_tpu.client import cli

        client = make_client(tmp_path, fixture_cmd("sleep_forever.py"),
                             {"tony.worker.instances": "2",
                              "tony.application.security.enabled": "true"})
        result = {}
        t = threading.Thread(
            target=lambda: result.update(code=client.run()))
        t.start()
        try:
            deadline = time.monotonic() + 30
            while client._read_coordinator_addr() is None:
                assert time.monotonic() < deadline, "coordinator never up"
                time.sleep(0.2)
            # wait for the secret file (written at stage()) and kill
            assert cli.main(["kill", client.job_dir]) == 0
        finally:
            t.join(timeout=60)
        assert result.get("code") == 1
        final = client._read_final_status()
        assert final and final["status"] == "KILLED"

    def test_tony_kill_no_coordinator_errors(self, tmp_path):
        from tony_tpu.client import cli
        assert cli.main(["kill", str(tmp_path)]) == 1

    def test_cli_local_submit_end_to_end(self, tmp_path):
        """The `tony local` entry point itself (the ClusterSubmitter-analog
        coverage of TestClusterSubmitter.java:17-26, but against the real
        stack, not a stubbed client)."""
        from tony_tpu.client import cli
        rc = cli.main([
            "local", "--executes", fixture_cmd("exit_0.py"),
            "--conf", f"tony.staging.dir={tmp_path / 'staging'}",
            "--conf", f"tony.history.location={tmp_path / 'hist'}",
            "--conf", "tony.worker.instances=2",
            "--conf", "tony.application.timeout=60000",
        ])
        assert rc == 0
        rc = cli.main([
            "local", "--executes", fixture_cmd("exit_1.py"),
            "--conf", f"tony.staging.dir={tmp_path / 'staging'}",
            "--conf", f"tony.history.location={tmp_path / 'hist'}",
            "--conf", "tony.worker.instances=1",
            "--conf", "tony.application.timeout=60000",
        ])
        assert rc != 0                      # failure propagates as exit code

    def test_tony_status_running_and_finished(self, tmp_path, capsys):
        """`tony status <job_dir>`: live coordinator status + task URLs
        while running, final-status.json afterwards, error for unknown."""
        import threading
        from tony_tpu.client import cli

        client = make_client(tmp_path, fixture_cmd("sleep_forever.py"),
                             {"tony.worker.instances": "1",
                              "tony.application.security.enabled": "true"})
        result = {}
        t = threading.Thread(target=lambda: result.update(code=client.run()))
        t.start()
        try:
            deadline = time.monotonic() + 30
            while client._read_coordinator_addr() is None:
                assert time.monotonic() < deadline, "coordinator never up"
                time.sleep(0.2)
            assert cli.main(["status", client.job_dir]) == 0
            out = capsys.readouterr().out
            assert "status: RUNNING" in out
            assert cli.main(["kill", client.job_dir]) == 0
        finally:
            t.join(timeout=60)
        assert cli.main(["status", client.job_dir]) == 0
        out = capsys.readouterr().out
        assert "status: KILLED (finished)" in out
        assert cli.main(["status", str(tmp_path / "nope")]) == 1

    def test_tony_kill_stops_single_node_job(self, tmp_path):
        """Kill must also interrupt single-node/notebook jobs, which never
        reach the monitor loop (they block in the preprocess wait)."""
        import threading
        from tony_tpu.client import cli

        client = make_client(tmp_path, "sleep 300",
                             {"tony.application.single-node": "true"})
        result = {}
        t = threading.Thread(
            target=lambda: result.update(code=client.run()))
        t.start()
        try:
            deadline = time.monotonic() + 30
            while client._read_coordinator_addr() is None:
                assert time.monotonic() < deadline
                time.sleep(0.2)
            time.sleep(0.5)   # let the preprocess proc start
            assert cli.main(["kill", client.job_dir]) == 0
        finally:
            t.join(timeout=60)
        assert result.get("code") == 1
        final = client._read_final_status()
        assert final and final["status"] == "KILLED"

    def test_tony_kill_finished_job_reports_final(self, tmp_path):
        from tony_tpu.client import cli
        client = make_client(tmp_path, fixture_cmd("exit_0.py"),
                             {"tony.worker.instances": "1"})
        assert client.run() == 0
        # coordinator.addr remains, but the job is final: no-op success.
        assert cli.main(["kill", client.job_dir]) == 0


def test_zip_entry_escaping_to_prefix_sibling_rejected(tmp_path):
    """A zip entry resolving to a SIBLING dir that shares the dest's path
    prefix ('<dest>x/evil') must be rejected — a plain startswith() prefix
    check passes it."""
    import zipfile
    from tony_tpu.cluster.executor import TaskExecutor

    dest = tmp_path / "venv"
    dest.mkdir()
    sibling = tmp_path / "venvx"       # shares the '<dest>' string prefix
    sibling.mkdir()
    evil = tmp_path / "evil.zip"
    with zipfile.ZipFile(evil, "w") as zf:
        zf.writestr("../venvx/pwned", "boom")
    with pytest.raises(ValueError, match="escapes"):
        TaskExecutor._extract_zip_with_symlinks(str(evil), str(dest))
    assert not (sibling / "pwned").exists()


def test_cli_logs_command(tmp_path):
    """`tony logs <job_dir>` prints task logs (the `yarn logs` analog):
    all tasks, a single --task filter, and --tail."""
    import io
    from contextlib import redirect_stdout
    from tony_tpu.client import cli
    # the chief (worker:0) sleeps after echoing: its completion is the
    # session verdict and would otherwise race worker:1's output (the
    # teardown kill can land before worker:1 echoes)
    client = make_client(
        tmp_path,
        'bash -c "echo line-$TASK_INDEX-a; echo line-$TASK_INDEX-b; '
        'if [ $TASK_INDEX = 0 ]; then sleep 3; fi"',
        {"tony.worker.instances": "2"})
    assert client.run() == 0

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["logs", client.job_dir]) == 0
    out = buf.getvalue()
    assert "==== worker-0.stdout ====" in out and "line-0-a" in out
    assert "==== worker-1.stdout ====" in out and "line-1-b" in out
    assert "==== am.stderr ====" in out          # coordinator stream too

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["logs", client.job_dir, "--task", "worker:1",
                         "--tail", "1"]) == 0
    out = buf.getvalue()
    assert "worker-1.stdout" in out and "line-1-b" in out
    assert "worker-0" not in out and "line-1-a" not in out

    assert cli.main(["logs", client.job_dir, "--task", "nosuch:9"]) == 1
    assert cli.main(["logs", str(tmp_path / "nowhere")]) == 1


@pytest.mark.slow
def test_distributed_lm_trains_from_gs_data(tmp_path):
    """Training data read IN PLACE from gs:// through the storage seam
    (fake-gsutil substrate): 2 dp workers each stream their byte-range
    split of a remote token file via ranged reads — the reference's
    core data-path capability (HdfsAvroFileSplitReader.java:201 reads
    the cluster filesystem directly, no pre-copy)."""
    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # fake bucket: tokens.bin = 64 records of (seq+1)=65 int32 ids
    gcs_root = tmp_path / "gcs"
    (gcs_root / "bucket").mkdir(parents=True)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 128, size=(64, 65), dtype=np.int32)
    (gcs_root / "bucket" / "tokens.bin").write_bytes(tokens.tobytes())
    shim = tmp_path / "gsutil"
    shim.write_text(f"#!/bin/bash\nexec {PY} "
                    f"{os.path.join(FIXTURES, '..', 'fake_gsutil.py')} "
                    f"\"$@\"\n")
    shim.chmod(0o755)

    script = os.path.join(repo, "examples", "lm", "train_lm.py")
    client = make_client(
        tmp_path, f"{PY} {script} --steps 8 --batch_size 8 --seq_len 64 "
                  f"--preset tiny --data_files gs://bucket/tokens.bin",
        {"tony.worker.instances": "2",
         "tony.application.mesh": "dp=-1",
         "tony.application.timeout": "180000"},
        shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                   "XLA_FLAGS": "",
                   "TONY_GSUTIL": str(shim),
                   "FAKE_GCS_ROOT": str(gcs_root)})
    assert client.run() == 0
    out = open(os.path.join(client.job_dir, "logs",
                            "worker-0.stdout")).read()
    assert "done:" in out


@pytest.mark.slow
def test_gcs_service_account_scopes_every_gsutil_call(tmp_path):
    """tony.gcs.service-account (the delegation-token analog, reference
    TonyClient.java:509): the client mints an impersonation token via
    gcloud and EVERY gsutil invocation in the job — the client's staging
    push and the workers' gs:// data reads — runs under it, never under
    ambient host credentials."""
    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    gcs_root = tmp_path / "gcs"
    (gcs_root / "bucket").mkdir(parents=True)
    tokens = np.random.RandomState(0).randint(
        0, 128, size=(64, 65), dtype=np.int32)
    (gcs_root / "bucket" / "tokens.bin").write_bytes(tokens.tobytes())
    gsutil_shim = tmp_path / "gsutil"
    gsutil_shim.write_text(
        f"#!/bin/bash\nexec {PY} "
        f"{os.path.join(FIXTURES, '..', 'fake_gsutil.py')} \"$@\"\n")
    gsutil_shim.chmod(0o755)
    gcloud_shim = tmp_path / "gcloud"
    gcloud_shim.write_text(
        f"#!/bin/bash\nexec {PY} "
        f"{os.path.join(FIXTURES, '..', 'fake_gcloud.py')} \"$@\"\n")
    gcloud_shim.chmod(0o755)
    auth_log = tmp_path / "auth.log"

    # the client process itself stages through gs://, so the fake
    # substrate + token mint must be live in THIS process
    os.environ["FAKE_GCS_ROOT"] = str(gcs_root)
    (tmp_path / "gcloud-state").mkdir()
    os.environ["FAKE_GCLOUD_ROOT"] = str(tmp_path / "gcloud-state")
    os.environ["TONY_GSUTIL"] = str(gsutil_shim)
    os.environ["TONY_GCLOUD"] = str(gcloud_shim)
    os.environ["FAKE_GSUTIL_AUTH_LOG"] = str(auth_log)
    from tony_tpu.storage import register_storage
    try:
        script = os.path.join(repo, "examples", "lm", "train_lm.py")
        client = make_client(
            tmp_path,
            f"{PY} {script} --steps 30 --batch_size 8 --seq_len 64 "
            f"--preset tiny --data_files gs://bucket/tokens.bin",
            {"tony.worker.instances": "1",
             "tony.staging.dir": "gs://bucket/staging",
             "tony.gcs.service-account": "job-sa@proj.iam.gserviceaccount.com",
             # aggressive cadence so renewal happens DURING this short job
             "tony.gcs.token-renew-ms": "3000",
             "tony.application.mesh": "dp=-1",
             "tony.application.timeout": "180000"},
            shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                       "XLA_FLAGS": "",
                       "TONY_GSUTIL": str(gsutil_shim),
                       "FAKE_GCS_ROOT": str(gcs_root),
                       "FAKE_GSUTIL_AUTH_LOG": str(auth_log)})
        assert client.gcs_token.startswith(
            "fake-token-for-job-sa@proj.iam.gserviceaccount.com")
        assert client.run() == 0
        calls = auth_log.read_text().strip().splitlines()
        assert calls, "no gsutil calls recorded"
        # every call — staging rsync/cp from the client, ranged cat/du
        # from the worker's data feed — carried the scoped token
        ambient = [c for c in calls if c.endswith(" AMBIENT")]
        assert not ambient, f"gsutil ran under ambient creds: {ambient}"
        verbs = {c.split()[0] for c in calls}
        assert "rsync" in verbs or "cp" in verbs    # staging push
        assert "cat" in verbs and "du" in verbs     # ranged data reads
        # the token ROTATED mid-job (client re-mint → RPC push →
        # heartbeat fan-out → executor token-file republish → the
        # training process's storage calls pick the new one up)
        tokens_seen = {c.split()[-1] for c in calls}
        assert len(tokens_seen) >= 2, (
            f"expected a renewed token to reach gsutil calls, saw only "
            f"{tokens_seen}")
        # the token never landed in the bucket
        for root, _, files in os.walk(gcs_root):
            for fn in files:
                assert b"fake-token" not in open(
                    os.path.join(root, fn), "rb").read(), fn
    finally:
        for var in ("FAKE_GCS_ROOT", "FAKE_GCLOUD_ROOT", "TONY_GSUTIL",
                    "TONY_GCLOUD", "FAKE_GSUTIL_AUTH_LOG"):
            os.environ.pop(var, None)
        register_storage("gs", None)


def test_gcs_multi_identity_scopes_calls_per_bucket(tmp_path):
    """tony.gcs.service-account with bucket=sa pairs (the list-valued
    tony.other.namenodes analog, TonyConfigurationKeys.java:29): the job
    carries ONE token per identity and every gsutil call runs under the
    token mapped to ITS target bucket — data reads from one project's
    bucket, staging/history writes to another's, distinct identities."""
    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    gcs_root = tmp_path / "gcs"
    (gcs_root / "bkt-data").mkdir(parents=True)
    (gcs_root / "bkt-stage").mkdir(parents=True)
    tokens = np.random.RandomState(0).randint(
        0, 128, size=(64, 65), dtype=np.int32)
    (gcs_root / "bkt-data" / "tokens.bin").write_bytes(tokens.tobytes())
    gsutil_shim = tmp_path / "gsutil"
    gsutil_shim.write_text(
        f"#!/bin/bash\nexec {PY} "
        f"{os.path.join(FIXTURES, '..', 'fake_gsutil.py')} \"$@\"\n")
    gsutil_shim.chmod(0o755)
    gcloud_shim = tmp_path / "gcloud"
    gcloud_shim.write_text(
        f"#!/bin/bash\nexec {PY} "
        f"{os.path.join(FIXTURES, '..', 'fake_gcloud.py')} \"$@\"\n")
    gcloud_shim.chmod(0o755)
    auth_log = tmp_path / "auth.log"

    os.environ["FAKE_GCS_ROOT"] = str(gcs_root)
    (tmp_path / "gcloud-state").mkdir()
    os.environ["FAKE_GCLOUD_ROOT"] = str(tmp_path / "gcloud-state")
    os.environ["TONY_GSUTIL"] = str(gsutil_shim)
    os.environ["TONY_GCLOUD"] = str(gcloud_shim)
    os.environ["FAKE_GSUTIL_AUTH_LOG"] = str(auth_log)
    from tony_tpu.storage import register_storage
    try:
        script = os.path.join(repo, "examples", "lm", "train_lm.py")
        client = make_client(
            tmp_path,
            f"{PY} {script} --steps 10 --batch_size 8 --seq_len 64 "
            f"--preset tiny --data_files gs://bkt-data/tokens.bin",
            {"tony.worker.instances": "1",
             "tony.staging.dir": "gs://bkt-stage/staging",
             "tony.gcs.service-account":
                 "bkt-data=data-sa@proj.iam,bkt-stage=stage-sa@proj.iam",
             "tony.application.mesh": "dp=-1",
             "tony.application.timeout": "180000"},
            shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                       "XLA_FLAGS": "",
                       "TONY_GSUTIL": str(gsutil_shim),
                       "FAKE_GCS_ROOT": str(gcs_root),
                       "FAKE_GSUTIL_AUTH_LOG": str(auth_log)})
        import json as _json
        cred = _json.loads(client.gcs_token)
        assert cred["bkt-data"].startswith("fake-token-for-data-sa@")
        assert cred["bkt-stage"].startswith("fake-token-for-stage-sa@")
        assert client.run() == 0
        calls = [c.split() for c in
                 auth_log.read_text().strip().splitlines()]
        assert calls, "no gsutil calls recorded"
        data_calls = [c for c in calls
                      if c[1].startswith("gs://bkt-data")]
        stage_calls = [c for c in calls
                       if c[1].startswith("gs://bkt-stage")]
        assert data_calls and stage_calls
        # EVERY call carried the token of ITS bucket's identity
        for verb, target, tok in data_calls:
            assert tok.startswith("fake-token-for-data-sa@"), (
                verb, target, tok)
        for verb, target, tok in stage_calls:
            assert tok.startswith("fake-token-for-stage-sa@"), (
                verb, target, tok)
        ambient = [c for c in calls if c[-1] == "AMBIENT"]
        assert not ambient, f"gsutil ran under ambient creds: {ambient}"
    finally:
        for var in ("FAKE_GCS_ROOT", "FAKE_GCLOUD_ROOT", "TONY_GSUTIL",
                    "TONY_GCLOUD", "FAKE_GSUTIL_AUTH_LOG"):
            os.environ.pop(var, None)
        register_storage("gs", None)


@pytest.mark.slow
def test_distributed_moe_lm_trains(tmp_path):
    """Expert parallelism across PROCESSES: 2 workers x 1 CPU device,
    mesh ep=2 — each process holds half the experts and the gshard
    dispatch's resharding collectives ride the gloo backend, driven
    entirely from the example CLI (--num_experts)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "examples", "lm", "train_lm.py")
    client = make_client(
        tmp_path, f"{PY} {script} --steps 10 --batch_size 8 --seq_len 64 "
                  f"--preset tiny --num_experts 4",
        {"tony.worker.instances": "2",
         "tony.application.mesh": "ep=2,dp=-1",
         "tony.application.timeout": "180000"},
        shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                   "XLA_FLAGS": ""})
    assert client.run() == 0
    out = open(os.path.join(client.job_dir, "logs",
                            "worker-0.stdout")).read()
    assert "done:" in out
    # the ep axis must actually be live (a dense dp-only run would also
    # print "done:" — same guard as the pp e2e)
    assert "'ep': 2" in out


@pytest.mark.e2e
class TestPipelineE2E:
    """Cross-slice MPMD pipeline job: two stage GANGS (real executor
    subprocesses under the local backend) cooperate on one model over
    DCN tensor channels, each running its per-gang PROGRAM
    (tony.{job}.program), wired by the coordinator's channel registry.
    The trained losses and final params are pinned BIT-IDENTICAL to the
    in-slice 1F1B schedule (`pipeline_value_and_grad`) on the same
    params and batches — the tentpole's numerical acceptance."""

    STEPS, M, MB, DIM = 3, 4, 4, 8

    def _reference(self, trainer_mod):
        """In-process in-slice 1F1B training run on identical
        params/batches (pp=2 over two virtual CPU devices)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from tony_tpu.parallel.pipeline import pipeline_value_and_grad
        from jax.sharding import Mesh
        m, mb, dim = self.M, self.MB, self.DIM
        stacked = jax.tree.map(
            lambda *leaves: jnp.stack(leaves),
            trainer_mod.init_stage_params(0, dim),
            trainer_mod.init_stage_params(1, dim))
        head = trainer_mod.init_head_params(dim)
        mesh = Mesh(np.array(jax.devices()[:2]), ("pp",))
        losses = []
        for step in range(self.STEPS):
            x, tgt = trainer_mod.batch_for(step, m, mb, dim)
            loss, g_sp, g_hp, _ = pipeline_value_and_grad(
                trainer_mod.stage_fn, stacked,
                jnp.asarray(x.reshape(m * mb, dim)), head,
                jnp.asarray(tgt.reshape(m * mb, dim)), mesh,
                loss_head=trainer_mod.loss_head, num_microbatches=m)
            stacked = trainer_mod.sgd(stacked, g_sp, 0.1)
            head = trainer_mod.sgd(head, g_hp, 0.1)
            losses.append(float(loss))
        return stacked, head, losses

    def test_pipeline_job_bit_identical_to_in_slice(self, tmp_path):
        import importlib.util

        import numpy as np

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        trainer = os.path.join(repo, "examples", "lm", "train_pipeline.py")
        out = tmp_path / "pipe"
        prog = (f"{PY} {trainer} --steps {self.STEPS} "
                f"--microbatches {self.M} --mb_rows {self.MB} "
                f"--dim {self.DIM} --lr 0.1 --out {out}")
        client = make_client(
            tmp_path, f"{PY} -c 'raise SystemExit(7)'",   # must be unused
            {"tony.stage0.instances": "1",
             "tony.stage1.instances": "1",
             "tony.pipeline.stages": "stage0,stage1",
             # per-gang PROGRAMS override the job-wide command
             "tony.stage0.program": prog,
             "tony.stage1.program": prog,
             "tony.application.timeout": "150000"},
            shell_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
                       "XLA_FLAGS": ""})
        assert client.run() == 0

        spec = importlib.util.spec_from_file_location("train_pipeline",
                                                      trainer)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        ref_stacked, ref_head, ref_losses = self._reference(mod)

        got1 = np.load(f"{out}-stage1.npz")
        got0 = np.load(f"{out}-stage0.npz")
        assert np.array_equal(
            got1["losses"], np.asarray(ref_losses, np.float32)), (
                list(got1["losses"]), ref_losses)
        for stage, got in ((0, got0), (1, got1)):
            for k in ("w", "b"):
                assert np.array_equal(got[f"p_{k}"],
                                      np.asarray(ref_stacked[k][stage])), \
                    (stage, k)
        assert np.array_equal(got1["h_wo"], np.asarray(ref_head["wo"]))
        # the stage identity env must have reached both gangs
        log0 = open(os.path.join(client.job_dir, "logs",
                                 "stage0-0.stdout")).read()
        log1 = open(os.path.join(client.job_dir, "logs",
                                 "stage1-0.stdout")).read()
        assert "loss" not in log0        # stage 0 owns no loss head
        assert f"step {self.STEPS - 1} loss" in log1
