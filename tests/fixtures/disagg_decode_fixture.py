"""Decode-tier host for the disaggregated-serving e2e: TWO real
DecodeServers over one process — a greedy engine and a sampled one
(temperature/top_k/top_p/seed matching the driver's colocated
reference batchers) — so ONE extra process covers both token-identity
modes. Admissions arrive only as KV shipments on each server's channel
hub; the driver's routers BIND themselves as the delta sinks. Writes
{"greedy": port, "sampled": port} to --port_file (atomic) and serves
until --done_file appears."""

import argparse
import json
import os
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port_file", default=".decode-ports")
    ap.add_argument("--done_file", default=".disagg-done")
    ap.add_argument("--timeout_s", type=float, default=180.0)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from tony_tpu.models import transformer as T
    from tony_tpu.models.serve import ContinuousBatcher
    from tony_tpu.serving.disagg import DecodeServer

    cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    servers = {
        "greedy": DecodeServer(ContinuousBatcher(
            params, cfg, batch=2, max_len=48, chunk=3, seed=7)),
        "sampled": DecodeServer(ContinuousBatcher(
            params, cfg, batch=2, max_len=48, chunk=3, temperature=0.8,
            top_k=20, top_p=0.9, seed=7)),
    }
    ports = {name: s.start() for name, s in servers.items()}
    tmp = args.port_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ports, f)
    os.replace(tmp, args.port_file)
    print(f"decode tier serving on {ports}", flush=True)
    deadline = time.time() + args.timeout_s
    while not os.path.exists(args.done_file) and time.time() < deadline:
        time.sleep(0.1)
    for s in servers.values():
        s.stop(drain=True)
    print("decode tier done", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
