"""Asserts the multi-slice identity env (tony.{job}.slices > 1)."""
import json, os, sys

slice_id = int(os.environ["TONY_SLICE_ID"])
num_slices = int(os.environ["TONY_NUM_SLICES"])
idx = int(os.environ["TASK_INDEX"])
spec = json.loads(os.environ["TONY_MESH_SPEC"])
mine = spec["slice_spec"][os.environ["JOB_NAME"]]
assert num_slices == mine["slices"]
assert slice_id == idx // mine["hosts_per_slice"], (slice_id, idx, mine)
assert 0 <= slice_id < num_slices
assert spec["dcn_axes"] == {"dp": 2}, spec
# libtpu multi-slice contract rides along
assert os.environ["MEGASCALE_NUM_SLICES"] == os.environ["TONY_NUM_SLICES"]
assert os.environ["MEGASCALE_SLICE_ID"] == os.environ["TONY_SLICE_ID"]
assert os.environ["MEGASCALE_COORDINATOR_ADDRESS"]
sys.exit(0)
