"""Tiny serving host for the trace e2e: a real ContinuousBatcher behind
a ServingServer. Writes its bound port to --port_file (atomic) and
serves until --done_file appears, then drains and exits 0. Runs as the
"engine" job type's per-gang PROGRAM; its engine-side request spans
(engine.request / engine.queued / engine.first_token — the TTFT
decomposition) spool to the executor and ride heartbeats to the
coordinator."""

import argparse
import os
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port_file", default=".engine-port")
    ap.add_argument("--done_file", default=".client-done")
    ap.add_argument("--timeout_s", type=float, default=120.0)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from tony_tpu.models import transformer as T
    from tony_tpu.models.serve import ContinuousBatcher
    from tony_tpu.serving.server import ServingServer

    cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    batcher = ContinuousBatcher(params, cfg, batch=2, max_len=32, chunk=3)
    server = ServingServer(batcher, port=0)
    port = server.start()
    tmp = args.port_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(port))
    os.replace(tmp, args.port_file)
    print(f"engine serving on {port}", flush=True)
    deadline = time.time() + args.timeout_s
    while not os.path.exists(args.done_file) and time.time() < deadline:
        time.sleep(0.1)
    server.stop(drain=True)
    print("engine done", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
