"""Jax-free streaming client for the trace e2e: runs as the "driver"
job type's PROGRAM in a SEPARATE process from the engine, waits for the
engine's port file, streams one request, and touches --done_file. Its
client.request / client.ttft spans root the request's trace; the span
context rides the ADMIT frame, so the engine process's spans join the
SAME trace id — the cross-process causal chain the e2e asserts."""

import argparse
import os
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port_file", default=".engine-port")
    ap.add_argument("--done_file", default=".client-done")
    ap.add_argument("--timeout_s", type=float, default=90.0)
    args = ap.parse_args()

    from tony_tpu.serving.client import StreamingClient

    port = None
    deadline = time.time() + args.timeout_s
    while time.time() < deadline:
        if os.path.exists(args.port_file):
            try:
                port = int(open(args.port_file).read().strip())
                break
            except ValueError:
                pass                   # mid-write; retry
        time.sleep(0.1)
    if port is None:
        print("engine port never appeared", flush=True)
        return 1

    with StreamingClient("127.0.0.1", port) as client:
        rid = client.submit([1, 2, 3, 4], max_new_tokens=6)
        tokens, reason = client.result(rid, timeout=60.0)
    print(f"client streamed {len(tokens)} tokens ({reason})", flush=True)

    tmp = args.done_file + ".tmp"
    with open(tmp, "w") as f:
        f.write("done")
    os.replace(tmp, args.done_file)
    # give the spool one beat to ship before exiting (the final
    # heartbeat would carry leftovers anyway; this just keeps the
    # common path deterministic)
    time.sleep(0.3)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
