"""Asserts the JAX runtime adapter env (the TF_CONFIG-replacement payload)."""
import json, os, sys
for var in ("TONY_JAX_COORDINATOR_ADDRESS", "TONY_JAX_PROCESS_ID",
            "TONY_JAX_NUM_PROCESSES", "TONY_MESH_SPEC", "CLUSTER_SPEC",
            "JOB_NAME", "TASK_INDEX", "TASK_NUM", "SESSION_ID"):
    assert os.environ.get(var) not in (None, ""), f"missing {var}"
spec = json.loads(os.environ["CLUSTER_SPEC"])
nproc = int(os.environ["TONY_JAX_NUM_PROCESSES"])
assert sum(len(v) for v in spec.values()) == nproc, (spec, nproc)
pid = int(os.environ["TONY_JAX_PROCESS_ID"])
assert 0 <= pid < nproc
coord = os.environ["TONY_JAX_COORDINATOR_ADDRESS"]
assert coord in [h for v in spec.values() for h in v]
json.loads(os.environ["TONY_MESH_SPEC"])
sys.exit(0)
