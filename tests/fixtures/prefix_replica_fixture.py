"""One serving replica for the prefix-aware routing e2e: FOUR real
ServingServers in one process — (blind, aware) x (greedy, sampled) —
so the prefix-aware pass and the prefix-blind contrast pass each run
against engines whose per-request stream indices start at 0 (what
makes the sampled runs comparable request-for-request). The driver
installs the shared prefix on replica A's "aware" servers and warms
replica B's over the template-ship lane; the "blind" servers are never
touched. Writes {name: {"port": .., "prefix_port": ..}} to --port_file
(atomic JSON) and serves until --done_file appears, then drains and
exits 0. Model/config/seed pinned to match the driver's references
bit-for-bit."""

import argparse
import json
import os
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port_file", default=".replica-ports")
    ap.add_argument("--done_file", default=".prefix-done")
    ap.add_argument("--timeout_s", type=float, default=240.0)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from tony_tpu.models import transformer as T
    from tony_tpu.models.serve import ContinuousBatcher
    from tony_tpu.serving.server import ServingServer

    cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    sampled = dict(temperature=0.8, top_k=20, top_p=0.9)
    servers = {}
    for pass_name in ("blind", "aware"):
        for mode, kw in (("greedy", {}), ("sampled", sampled)):
            batcher = ContinuousBatcher(params, cfg, batch=2, max_len=64,
                                        chunk=3, seed=7, **kw)
            servers[f"{pass_name}_{mode}"] = ServingServer(batcher)
    ports = {name: {"port": s.start(), "prefix_port": s.prefix_port}
             for name, s in servers.items()}
    tmp = args.port_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ports, f)
    os.replace(tmp, args.port_file)
    print(f"prefix replica serving on {ports}", flush=True)
    deadline = time.time() + args.timeout_s
    while not os.path.exists(args.done_file) and time.time() < deadline:
        time.sleep(0.1)
    for s in servers.values():
        s.stop(drain=True)
    print("prefix replica done", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
