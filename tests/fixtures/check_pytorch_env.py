"""Asserts RANK/WORLD/INIT_METHOD (reference: exit_0_check_pytorchenv.py)."""
import os, sys
assert os.environ["INIT_METHOD"].startswith("tcp://"), os.environ.get("INIT_METHOD")
rank, world = int(os.environ["RANK"]), int(os.environ["WORLD"])
assert 0 <= rank < world
sys.exit(0)
