"""Asserts shell env propagated (reference fixture: exit_0_check_env.py)."""
import os, sys
assert os.environ.get("TONY_TEST_SHELL_VAR") == "hello", os.environ.get("TONY_TEST_SHELL_VAR")
sys.exit(0)
