"""Tiny REAL trainer for the elastic e2e suite: linear model, the full
framework stack (runtime bootstrap → mesh → elastic_epochs →
DevicePrefetcher → make_train_step → run_training → CheckpointManager),
compiling in well under a second so gang-loss recovery is testable in
tier-1 wall budgets.

Prints ``step <i> loss <v>`` EVERY step. Because the data source is
:func:`tony_tpu.io.prefetch.elastic_epochs` (world-size-invariant global
batches, stream aligned to the restored step), the loss at global step i
is a pure function of (init seed, data seed, i) — identical across world
sizes and across kill/resume boundaries — which is what the e2e pins.

Flags:
  --steps N --ckpt_dir D --ckpt_every K --global_batch B --dim F
  --data f1 [f2 ...]    binary int32 token files, rows of dim+1 ids
  --step_wait S         host sleep per step (makes the kill window real)
  --touch PATH --touch_at STEP --touch_index IDX
                        task IDX touches PATH when it STARTS step STEP —
                        the TEST_PREEMPT_TASKS marker handshake

Standalone (no cluster env) it runs single-process — the uninterrupted
baseline the e2e compares loss curves against.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import jax
import numpy as np
import optax

import tony_tpu.runtime as rt
from tony_tpu.io.prefetch import DevicePrefetcher, elastic_epochs
from tony_tpu.models.checkpoint import CheckpointManager
from tony_tpu.models.loop import GangLostError, run_training
from tony_tpu.models.train import batch_sharding, init_state, make_train_step


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--ckpt_dir", required=True)
    p.add_argument("--ckpt_every", type=int, default=2)
    p.add_argument("--global_batch", type=int, default=8)
    p.add_argument("--dim", type=int, default=4)
    p.add_argument("--data", nargs="+", required=True)
    p.add_argument("--step_wait", type=float, default=0.0)
    p.add_argument("--touch", default="")
    p.add_argument("--touch_at", type=int, default=-1)
    p.add_argument("--touch_index", type=int, default=1)
    args = p.parse_args()

    info = rt.initialize()
    mesh = rt.mesh()
    print(f"[{info.job_name}:{info.task_index}] epoch="
          f"{os.environ.get('TONY_CLUSTER_EPOCH', '0')} "
          f"procs={info.num_processes} devices={len(jax.devices())}",
          flush=True)

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"] + params["b"]
        return ((pred - batch["y"]) ** 2).mean()

    opt = optax.sgd(0.05)
    params = {"w": np.zeros((args.dim,), np.float32),
              "b": np.zeros((), np.float32)}
    # mesh=None: plain jit — the batch arrives as a GLOBAL sharded array
    # (DevicePrefetcher assembles it against batch_sharding below), so
    # jit runs SPMD via compute-follows-data without an ambient mesh.
    step_fn = make_train_step(loss_fn, opt)

    mgr = CheckpointManager(args.ckpt_dir,
                            save_interval_steps=args.ckpt_every)
    # Replicated-template init: restored arrays must come back as GLOBAL
    # (mesh-replicated) jax.Arrays, or jit refuses to mix them with the
    # globally-sharded batch in multi-process worlds. device_put of the
    # (identical-everywhere) init values onto the replicated sharding is
    # the standard multi-host recipe.
    rep = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec())
    state = mgr.restore_or_init(
        lambda: jax.tree.map(lambda x: jax.device_put(x, rep),
                             init_state(params, opt)))
    start_step = int(state["step"])
    print(f"starting at step {start_step}", flush=True)

    rows, per_epoch = elastic_epochs(
        args.data, args.global_batch, np.int32, (args.dim + 1,),
        shuffle=True, seed=7, start_step=start_step,
        process_index=info.process_id if info.is_distributed else 0,
        process_count=info.num_processes if info.is_distributed else 1)

    def batches():
        for r in rows:
            f = r.astype(np.float32) / 1024.0
            yield {"x": f[:, :args.dim], "y": f[:, args.dim]}

    sharding = batch_sharding(mesh, logical=("batch",))

    def step_hook(step: int) -> None:
        if (args.touch and step == args.touch_at
                and info.task_index == args.touch_index):
            open(args.touch, "w").close()
            print(f"touched kill marker at step {step}", flush=True)
        if args.step_wait:
            time.sleep(args.step_wait)

    def log_fn(step, metrics, batch):
        print(f"step {step} loss {float(metrics['loss']):.6f}", flush=True)

    try:
        with DevicePrefetcher(batches(), sharding=sharding, depth=2) as data:
            state, metrics = run_training(
                step_fn, state, data, args.steps, start_step=start_step,
                checkpoint=mgr, log_every=1, log_fn=log_fn,
                step_hook=step_hook)
    except GangLostError as e:
        # the elastic contract: distinguished exit, executor relaunches
        # us against the resized gang (checkpoints already flushed by
        # run_training's finally)
        print(f"gang lost: {e}", flush=True)
        return e.exit_code
    mgr.close()
    loss = float(metrics["loss"]) if metrics else float("nan")
    print(f"done: final loss {loss:.6f}", flush=True)
    return 0 if np.isfinite(loss) else 1


if __name__ == "__main__":
    sys.exit(main())
