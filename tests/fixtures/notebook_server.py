"""Fixture: a tiny HTTP server on $NOTEBOOK_PORT, exits after first request
or 15s — stands in for a jupyter process in the notebook-submitter E2E."""
import os
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

port = int(os.environ["NOTEBOOK_PORT"])
done = threading.Event()


class H(BaseHTTPRequestHandler):
    def do_GET(self):
        body = b"notebook-ok"
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        done.set()

    def log_message(self, *a):
        pass


server = HTTPServer(("", port), H)
threading.Thread(target=server.serve_forever, daemon=True).start()
done.wait(timeout=45)
server.shutdown()
