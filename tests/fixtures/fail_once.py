"""NON-CHIEF tasks (index > 0) exit 1 on their first run, 0 afterwards —
drives the in-session per-task restart e2e (worker:0 is the implicit
chief, whose exit is the job's verdict and is never restarted). The
marker lives in the cwd (the job dir), which restarted executors
share."""
import os
import sys

idx = os.environ.get("TASK_INDEX", "0")
if idx == "0" and os.environ.get("FAIL_ONCE_INCLUDE_CHIEF") != "1":
    # outlive the non-chief blip: chief completion is the job's verdict
    # (session chief short-circuit), so exiting before the restarted
    # workers finish would race the restart
    import time
    time.sleep(4)
    print("chief: succeeding")
    sys.exit(0)
marker = f".fail-once-{os.environ.get('JOB_NAME', 'x')}-{idx}"
if os.path.exists(marker):
    print("second run: succeeding")
    sys.exit(0)
open(marker, "w").close()
print("first run: failing once")
sys.exit(1)
