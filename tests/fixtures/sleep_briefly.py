import time, sys
time.sleep(float(sys.argv[1]) if len(sys.argv) > 1 else 2.0)
