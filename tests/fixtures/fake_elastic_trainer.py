"""jax-free stand-in trainer for orchestration-level elastic benchmarks
and chaos smokes: counts steps at a fixed host cadence, "checkpoints"
progress to an atomically-renamed file every K steps, resumes from it on
relaunch, and can touch a kill marker at a given step — so recovery wall
and replayed-step counts measure the ORCHESTRATION (detection, resync,
relaunch), not model compile time.

Prints ``step <i>`` per step; the same line set is what the bench arm
diffs to count replayed steps.

The step loop runs inside the process goodput ledger (``enter("step")``
around the step wait, ``enter("checkpoint")`` around the progress-file
write) and publishes through ``TONY_GOODPUT_SPOOL`` each step, so
executor heartbeats carry a real per-step breakdown — which is also what
the straggler chaos test drives: ``--slow index:seconds[:from:to]``
stretches one task's step wall so the coordinator's detector has an
honest skew signal to flag (and to watch recover once the window ends).
"""

import argparse
import os
import sys
import time

from tony_tpu.runtime import goodput as goodput_mod


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=16)
    p.add_argument("--ckpt", required=True,
                   help="progress-file stem (per-task suffix appended)")
    p.add_argument("--ckpt_every", type=int, default=2)
    p.add_argument("--step_wait", type=float, default=0.1)
    p.add_argument("--kill", action="append", default=[],
                   help="marker_path:step:task_index — task_index touches "
                        "marker_path when it STARTS that step (repeatable; "
                        "the TEST_PREEMPT_TASKS handshake)")
    p.add_argument("--tail_wait", default="",
                   help="task_index:seconds — that task sleeps extra before "
                        "'done' (make the chief finish LAST so its "
                        "completion verdict never truncates a sibling)")
    p.add_argument("--slow", default="",
                   help="task_index:seconds[:from_step:to_step] — that task "
                        "sleeps EXTRA per step (inside its step-wall "
                        "interval) over [from_step, to_step); omit the "
                        "range for every step. The straggler-chaos knob.")
    args = p.parse_args()

    idx = int(os.environ.get("TASK_INDEX", "0"))
    kills = []
    for clause in args.kill:
        marker, step, who = clause.rsplit(":", 2)
        if int(who) == idx:
            kills.append((int(step), marker))
    slow_s, slow_from, slow_to = 0.0, 0, 1 << 30
    if args.slow:
        parts = args.slow.split(":")
        if int(parts[0]) == idx:
            slow_s = float(parts[1])
            if len(parts) >= 4:
                slow_from, slow_to = int(parts[2]), int(parts[3])
    path = f"{args.ckpt}-{os.environ.get('JOB_NAME', 'worker')}-{idx}"
    start = 0
    if os.path.exists(path):
        start = int(open(path).read().strip() or 0)
    print(f"starting at step {start} "
          f"(epoch {os.environ.get('TONY_CLUSTER_EPOCH', '0')}, "
          f"session {os.environ.get('SESSION_ID', '0')})", flush=True)
    ledger = goodput_mod.get_ledger()
    for step in range(start, args.steps):
        for kill_step, marker in kills:
            if step == kill_step:
                open(marker, "w").close()
        with ledger.enter("step"):
            time.sleep(args.step_wait)
            if slow_s > 0 and slow_from <= step < slow_to:
                time.sleep(slow_s)
        print(f"step {step}", flush=True)
        if (step + 1) % args.ckpt_every == 0:
            with ledger.enter("checkpoint"):
                tmp = f"{path}.tmp"
                with open(tmp, "w") as f:
                    f.write(str(step + 1))
                os.replace(tmp, path)   # atomic: a kill never corrupts it
        # publish every step (not the ~1s throttle): chaos tests run
        # sub-second step waits and the detector needs fresh windows
        ledger.publish()
    if args.tail_wait:
        who, _, wait_s = args.tail_wait.partition(":")
        if int(who) == idx:
            time.sleep(float(wait_s))
    ledger.publish()
    print("done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
