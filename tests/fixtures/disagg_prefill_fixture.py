"""Prefill-tier host for the disaggregated-serving e2e: TWO real
PrefillServers (one paired with the decode fixture's greedy engine,
one with its sampled engine — separate servers so each pairing's
stream-index assignment starts at 0, which is what makes the sampled
run comparable to an in-driver colocated reference). Writes the bound
ports to --port_file as JSON (atomic) and serves until --done_file
appears. Model/config/seed are pinned to match the driver's reference
batchers bit-for-bit."""

import argparse
import json
import os
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port_file", default=".prefill-ports")
    ap.add_argument("--done_file", default=".disagg-done")
    ap.add_argument("--timeout_s", type=float, default=180.0)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from tony_tpu.models import transformer as T
    from tony_tpu.serving.disagg import PrefillServer

    cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    servers = {name: PrefillServer(params, cfg, max_len=48, max_batch=2,
                                   seed=7)
               for name in ("greedy", "sampled")}
    ports = {name: s.start() for name, s in servers.items()}
    tmp = args.port_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ports, f)
    os.replace(tmp, args.port_file)
    print(f"prefill tier serving on {ports}", flush=True)
    deadline = time.time() + args.timeout_s
    while not os.path.exists(args.done_file) and time.time() < deadline:
        time.sleep(0.1)
    for s in servers.values():
        s.stop()
    print("prefill tier done", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
