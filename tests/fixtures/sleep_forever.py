import time
time.sleep(3600)
