"""Disaggregated prefill/decode serving: KV wire-codec round trips for
every cache layout, landing exactness, in-process two-tier e2e
(greedy AND sampled token identity vs the colocated engine, trace
causality, metrics-plane visibility), decode-replica failover with
zero duplicated/dropped tokens, retrace pins for the shipping/landing
programs, and the deterministic bench-arm pins.

The two-REAL-process token-identity acceptance pin lives at the
bottom (fixture pair: tests/fixtures/disagg_{prefill,decode}_fixture).

Compile frugality: one tiny f32 config for everything except the
per-layout codec cases (which are single prefills, not serve loops).
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import transformer as T
from tony_tpu.models.decode import extract_kv_rows, generate, init_kv_cache
from tony_tpu.models.serve import (ContinuousBatcher,
                                   SpeculativeContinuousBatcher,
                                   land_kv_rows, prefill_ship_row,
                                   prefill_ship_rows)
from tony_tpu.runtime import metrics as M
from tony_tpu.runtime import tracing
from tony_tpu.serving import kvship
from tony_tpu.serving import protocol as P
from tony_tpu.serving.client import StreamingClient
from tony_tpu.serving.disagg import DecodeServer, PrefillServer
from tony_tpu.serving.router import ServingRouter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)          # for `import bench` (repo-root script)
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")

CFG = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.PRNGKey(0), CFG)


def _reference(params, prompt, max_new):
    out = generate(params, jnp.asarray(prompt, jnp.int32)[None], CFG,
                   max_new_tokens=max_new, rng=jax.random.PRNGKey(0),
                   temperature=0.0)
    return [int(t) for t in np.asarray(out.tokens[0, len(prompt):])]


def _prompts(seed, sizes, vocab=None):
    rng = np.random.RandomState(seed)
    return [[int(t) for t in rng.randint(0, vocab or CFG.vocab_size,
                                         size=n)]
            for n in sizes]


class _Stack:
    """One in-process disaggregated deployment: prefill + decode +
    router, with per-tier registries, torn down in reverse order."""

    def __init__(self, params, cfg, *, slots=2, max_len=48, chunk=3,
                 seed=0, temperature=0.0, top_k=0, top_p=0.0,
                 decode_batchers=None, max_batch=2,
                 prefill_cls=PrefillServer, **prefill_kw):
        self.regp, self.regd, self.regr = (M.MetricsRegistry(),
                                           M.MetricsRegistry(),
                                           M.MetricsRegistry())
        self.prefill = prefill_cls(params, cfg, max_len=max_len,
                                   max_batch=max_batch, seed=seed,
                                   registry=self.regp, **prefill_kw)
        if decode_batchers is None:
            decode_batchers = [ContinuousBatcher(
                params, cfg, batch=slots, max_len=max_len, chunk=chunk,
                seed=seed, temperature=temperature, top_k=top_k,
                top_p=top_p)]
        self.decodes = [DecodeServer(b, registry=self.regd)
                        for b in decode_batchers]
        self.router = ServingRouter(
            [f"127.0.0.1:{self.prefill.start()}"],
            decode_replicas=[f"127.0.0.1:{d.start()}"
                             for d in self.decodes],
            health_interval_s=0.2, registry=self.regr)
        self.port = self.router.start()

    def close(self):
        self.router.stop()
        self.prefill.stop()
        for d in self.decodes:
            d.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# KV wire codec: every cache layout round-trips through a real socket
# pair and place_rows-lands bit-identical
# ---------------------------------------------------------------------------
class TestKVWireCodec:
    LAYOUTS = {
        "f32": dict(),
        "bf16": dict(dtype=jnp.bfloat16),
        "int8": dict(kv_cache_dtype="int8"),
        "window": dict(attn_window=8),
        "ring": dict(attn_window=8, kv_cache_capacity=8),
    }

    def _ship_one(self, cfg, prompt):
        """Prefill one prompt for shipment exactly as the prefill tier
        does; returns (bufs, logits [V], length, width, mini)."""
        p = T.init_params(jax.random.PRNGKey(0), cfg)
        if cfg.kv_cache_capacity:
            lg, mini = prefill_ship_row(
                p, jnp.asarray(prompt, jnp.int32)[None], cfg)
            width = mini["k"].shape[2]
        else:
            toks = np.zeros((2, 16), np.int64)
            toks[0, :len(prompt)] = prompt
            lg, mini = prefill_ship_rows(
                p, jnp.asarray(toks, jnp.int32),
                jnp.asarray([len(prompt), 1], np.int32), cfg)
            width = len(prompt)
        bufs = extract_kv_rows(mini, [width])[0]
        return bufs, np.asarray(lg)[0], len(prompt), width, mini

    @pytest.mark.parametrize("layout", sorted(LAYOUTS))
    def test_socket_round_trip_lands_bit_identical(self, layout):
        """serialize -> ship through a REAL socket pair -> land into a
        fresh cache: the landed rows, frontier, logits, and rng key are
        bit-identical to the prefill-side originals, for every cache
        layout (bf16, int8+scales, sliding-window, ring)."""
        cfg = CFG.scaled(**self.LAYOUTS[layout])
        prompt = [3, 1, 4, 1, 5]
        bufs, lg, length, width, _ = self._ship_one(cfg, prompt)
        key = np.asarray(jax.random.fold_in(jax.random.PRNGKey(7), 3),
                         np.uint32)
        meta = kvship.pack_kv_meta(9, 4, length, key, rng_off=0)
        blob = kvship.pack_shipment(meta, dict(bufs, logits=lg))

        a, b = socket.socketpair()
        try:
            import threading
            got = {}
            t = threading.Thread(
                target=lambda: got.update(frame=P.recv_frame(
                    b, max_bytes=1 << 31)))
            t.start()                 # blob can exceed the socket buffer
            P.send_frame(a, P.TOKENS, 1, memoryview(blob))
            t.join(timeout=30)
            payload = got["frame"][2]
        finally:
            a.close()
            b.close()
        assert payload == blob

        meta2, bufs2 = kvship.unpack_shipment(payload)
        meta2 = kvship.parse_kv_meta(meta2)
        lg2 = bufs2.pop("logits")
        assert (meta2["rng"] == key).all() and meta2["length"] == length
        assert lg2.dtype == lg.dtype and (lg2 == lg).all()
        for n in bufs:
            assert bufs2[n].dtype == np.asarray(bufs[n]).dtype, n
            assert (bufs2[n] == np.asarray(bufs[n])).all(), n

        # place_rows-land into slot 1 of a fresh 3-slot cache
        batch, slot = 3, 1
        cache = init_kv_cache(cfg, batch, 32)
        cache = dict(cache, length=jnp.zeros((batch,), jnp.int32))
        logits = jnp.zeros((batch, cfg.vocab_size),
                           cfg.logits_storage_dtype)
        keys = jnp.zeros((batch, 2), jnp.uint32)
        rows = np.asarray([slot, batch, batch + 1], np.int32)
        s_b = bufs2["k"].shape[2]
        mini = {n: np.zeros((a2.shape[0], batch, s_b) + a2.shape[3:],
                            a2.dtype) for n, a2 in bufs2.items()}
        for n, a2 in bufs2.items():
            mini[n][:, 0:1] = a2
        lens = np.asarray([length, 0, 0], np.int32)
        lgs = np.zeros((batch, cfg.vocab_size), lg2.dtype)
        lgs[0] = lg2
        kmat = np.zeros((batch, 2), np.uint32)
        kmat[0] = meta2["rng"]
        cache, logits, keys = land_kv_rows(
            cache, logits, jnp.asarray(rows),
            {n: jnp.asarray(a2) for n, a2 in mini.items()},
            jnp.asarray(lens), jnp.asarray(lgs), keys,
            jnp.asarray(kmat))
        assert int(cache["length"][slot]) == length
        for n, a2 in bufs2.items():
            landed = np.asarray(cache[n][:, slot:slot + 1, :s_b])
            assert (landed == a2).all(), n
        assert (np.asarray(logits[slot]) == lg2).all()
        assert (np.asarray(keys[slot]) == meta2["rng"]).all()

    def test_int8_ships_quantized_half_the_bytes(self):
        """The int8 cache's shipment carries int8 values + f32 scales —
        NOT a dequantized bf16/f32 blow-up: k/v payload bytes are half
        the f32 layout's for the same prompt."""
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        q_bufs, _, _, _, _ = self._ship_one(
            CFG.scaled(kv_cache_dtype="int8"), prompt)
        f_bufs, _, _, _, _ = self._ship_one(CFG, prompt)
        assert q_bufs["k"].dtype == np.int8
        assert q_bufs["k_scale"].dtype == np.float32
        assert q_bufs["k"].nbytes * 4 == f_bufs["k"].nbytes
        kv_q = q_bufs["k"].nbytes + q_bufs["v"].nbytes
        kv_f = f_bufs["k"].nbytes + f_bufs["v"].nbytes
        scales = q_bufs["k_scale"].nbytes + q_bufs["v_scale"].nbytes
        assert kv_q + scales < 0.6 * kv_f, (kv_q, scales, kv_f)

    def test_linear_caches_ship_true_length_only(self):
        """A 5-token prompt in a 16 bucket ships 5 positions, not 16 —
        the unreachable padding tail stays home."""
        bufs, _, _, width, mini = self._ship_one(CFG, [3, 1, 4, 1, 5])
        assert width == 5 and bufs["k"].shape[2] == 5
        assert mini["k"].shape[2] == 16          # the compute ran padded

    def test_malformed_shipments_are_protocol_errors(self):
        with pytest.raises(P.ProtocolError, match="header"):
            kvship.unpack_shipment(b"\x01")
        with pytest.raises(P.ProtocolError, match="implausible"):
            kvship.unpack_shipment(b"\xff\xff\xff\xff" + b"x" * 32)
        blob = kvship.pack_shipment({"rid": 1}, {"k": np.zeros((2, 2))})
        with pytest.raises(P.ProtocolError, match="truncated"):
            kvship.unpack_shipment(blob[:-8])
        with pytest.raises(P.ProtocolError, match="trailing"):
            kvship.unpack_shipment(blob + b"xx")
        with pytest.raises(P.ProtocolError, match="rng"):
            kvship.parse_kv_meta({"rid": 1, "budget": 2, "length": 3,
                                  "rng": [1]})
        import struct
        head = json.dumps({"v": 1, "meta": {}, "bufs": [
            {"name": "k", "dtype": "nope", "shape": [1]}]}).encode()
        with pytest.raises(P.ProtocolError, match="dtype"):
            kvship.unpack_shipment(struct.pack("<I", len(head)) + head
                                   + b"\x00" * 8)
        # adversarial shape whose element count overflows int64 (and
        # would wrap a numpy-based product to 0, sneaking past the
        # bounds check into a reshape crash): caught as truncated
        head = json.dumps({"v": 1, "meta": {"rid": 1}, "bufs": [
            {"name": "k", "dtype": "float32",
             "shape": [1 << 32, 1 << 32]}]}).encode()
        with pytest.raises(P.ProtocolError, match="truncated"):
            kvship.unpack_shipment(struct.pack("<I", len(head)) + head)

    def test_malformed_decode_targets_rejected(self):
        """A decode target the channel sender could not dial (missing
        host, non-numeric or out-of-range port) must be rejected at
        parse time — downstream it would detonate on the prefill tier's
        worker thread."""
        ok = {"decode": "10.0.0.1:7072"}
        assert P.parse_decode_target(ok) == "10.0.0.1:7072"
        for bad in ("host:abc", "host:", ":7072", "nohost", "h:0",
                    "h:70000", "h:7.2", 7072, "", None):
            assert P.parse_decode_target({"decode": bad}) is None, bad


# ---------------------------------------------------------------------------
# In-process two-tier e2e: token identity, trace, metrics, exclusions
# ---------------------------------------------------------------------------
class TestDisaggE2E:
    def test_greedy_token_identity_and_metrics(self, params):
        prompts = _prompts(0, (5, 3, 7, 4))
        ref = ContinuousBatcher(params, CFG, batch=2, max_len=48,
                                chunk=3).serve(prompts, 6)
        with _Stack(params, CFG) as st:
            with StreamingClient("127.0.0.1", st.port) as c:
                rids = [c.submit(p, 6) for p in prompts]
                outs = [c.result(r, timeout=120) for r in rids]
            for i, (toks, reason) in enumerate(outs):
                assert toks == ref[i], i
                assert reason == "budget"
            # the handoff wall is on the metrics plane, both sides
            assert st.regp.histogram("tony_kv_ship_seconds").count == 4
            assert st.regp.counter("tony_kv_ship_bytes_total").value > 0
            assert st.regd.histogram("tony_kv_land_seconds").count == 4
            assert st.regr.counter(
                "tony_router_handoffs_total").value == 4
            assert st.regd.gauge("tony_decode_idle_slots").value == 2
            assert st.regp.gauge("tony_prefill_queue_depth").value == 0

    def test_sampled_token_identity(self, params):
        """Per-request rng stream state rides the shipment: sampled
        disaggregated output == the colocated engine's, bit-for-bit."""
        prompts = _prompts(1, (5, 3, 7, 4))
        kw = dict(batch=2, max_len=48, chunk=3, temperature=0.8,
                  top_k=20, top_p=0.9, seed=7)
        ref = ContinuousBatcher(params, CFG, **kw).serve(prompts, 6)
        batcher = ContinuousBatcher(params, CFG, **kw)
        with _Stack(params, CFG, seed=7,
                    decode_batchers=[batcher]) as st:
            with StreamingClient("127.0.0.1", st.port) as c:
                rids = [c.submit(p, 6) for p in prompts]
                outs = [c.result(r, timeout=120)[0] for r in rids]
        assert outs == ref

    def test_int8_and_ring_configs_serve_identically(self):
        """The quantized and rolling cache layouts serve disaggregated
        with outputs identical to their colocated engines — int8 ships
        quantized, rings ship the whole capacity buffer."""
        for extra in (dict(kv_cache_dtype="int8"),
                      dict(attn_window=8, kv_cache_capacity=8)):
            cfg = CFG.scaled(**extra)
            p = T.init_params(jax.random.PRNGKey(0), cfg)
            prompts = _prompts(6, (5, 3))
            ref = ContinuousBatcher(p, cfg, batch=2, max_len=32,
                                    chunk=3).serve(prompts, 4)
            batcher = ContinuousBatcher(p, cfg, batch=2, max_len=32,
                                        chunk=3)
            with _Stack(p, cfg, max_len=32,
                        decode_batchers=[batcher]) as st:
                with StreamingClient("127.0.0.1", st.port) as c:
                    rids = [c.submit(pr, 4) for pr in prompts]
                    outs = [c.result(r, timeout=120)[0] for r in rids]
            assert outs == ref, extra

    def test_kv_ship_span_joins_the_request_trace(self, params):
        """The TTFT decomposition stays causal across the gangs:
        client.request roots the trace; the prefill tier's
        engine.request (role=prefill) parents kv.ship; the decode
        tier's engine.request (prefilled=true) parents under THAT —
        one trace id end to end."""
        tr = tracing.Tracer(proc="test:disagg", sample_rate=1.0,
                            ring_size=512)
        saved = tracing.set_tracer(tr)
        try:
            with _Stack(params, CFG) as st:
                with StreamingClient("127.0.0.1", st.port) as c:
                    rid = c.submit(_prompts(2, (5,))[0], 4)
                    c.result(rid, timeout=120)
        finally:
            tracing.set_tracer(saved)
        spans = {s["sid"]: s for s in tr._ring}
        roots = [s for s in spans.values() if s["n"] == "client.request"]
        assert roots, sorted({s["n"] for s in spans.values()})
        tid = roots[0]["tid"]
        trace = [s for s in spans.values() if s["tid"] == tid]
        names = {s["n"] for s in trace}
        assert {"client.request", "router.place", "engine.request",
                "kv.ship", "engine.first_token"} <= names, names
        ship = next(s for s in trace if s["n"] == "kv.ship")
        pre_req = spans[ship["pid"]]
        assert pre_req["n"] == "engine.request"
        assert pre_req["a"].get("role") == "prefill"
        dec_reqs = [s for s in trace if s["n"] == "engine.request"
                    and s["a"].get("prefilled")]
        assert dec_reqs, names
        # the decode tier's leg parents under the prefill tier's
        # engine.request (whose context rode the shipment)
        assert dec_reqs[0]["pid"] == pre_req["sid"]

    def test_speculative_and_prefix_are_explicitly_excluded(self, params):
        spec = SpeculativeContinuousBatcher(
            params, CFG, T.init_params(jax.random.PRNGKey(1), CFG), CFG,
            batch=2, max_len=32)
        with pytest.raises(ValueError, match="draft-model cache"):
            DecodeServer(spec)
        pref = ContinuousBatcher(params, CFG, batch=2, max_len=32,
                                 shared_prefix=[1, 2])
        with pytest.raises(ValueError, match="colocated"):
            DecodeServer(pref)

    def test_decode_tier_refuses_prompts(self, params):
        dec = DecodeServer(ContinuousBatcher(params, CFG, batch=1,
                                             max_len=32),
                           registry=M.MetricsRegistry())
        port = dec.start()
        try:
            with StreamingClient("127.0.0.1", port) as c:
                assert c.hello["role"] == "decode"
                assert c.hello["channel_port"] == dec.hub.port
                rid = c.submit([1, 2, 3], 4)
                ev = c.next_event(rid, timeout=30)
                assert ev[0] == "error" and "prefill tier" in ev[1]
        finally:
            dec.stop()

    def test_router_rejects_role_mismatch(self, params):
        """Wiring a colocated engine where the disaggregated router
        expects a prefill tier fails loudly at start, not with silent
        mis-serving."""
        from tony_tpu.serving.server import ServingServer
        srv = ServingServer(ContinuousBatcher(params, CFG, batch=1,
                                              max_len=32),
                            registry=M.MetricsRegistry())
        port = srv.start()
        dec = DecodeServer(ContinuousBatcher(params, CFG, batch=1,
                                             max_len=32),
                           registry=M.MetricsRegistry())
        dport = dec.start()
        router = ServingRouter([f"127.0.0.1:{port}"],
                               decode_replicas=[f"127.0.0.1:{dport}"],
                               registry=M.MetricsRegistry())
        try:
            with pytest.raises(ConnectionError, match="role"):
                router.start()
        finally:
            router.stop()
            srv.stop()
            dec.stop()

    def test_land_and_ship_programs_compile_once_per_bucket(
            self, params, retrace_guard):
        """The decode tier's landing and the prefill tier's shipping
        run ONE compiled program per admission bucket — mixed prompt
        lengths inside a bucket share it (the bucketed-admission
        invariant, extended across the gang split)."""
        prompts = _prompts(3, (3, 5, 8, 10, 4, 6))
        ref = [
            _reference(params, p, 4) for p in prompts]
        with _Stack(params, CFG) as st:
            with StreamingClient("127.0.0.1", st.port) as c:
                rids = [c.submit(p, 4) for p in prompts]
                outs = [c.result(r, timeout=120)[0] for r in rids]
        assert outs == ref
        retrace_guard.assert_max("prefill_ship_rows", 1)
        retrace_guard.assert_max("land_kv_rows", 1)


# ---------------------------------------------------------------------------
# Failover: kill the decode replica mid-stream
# ---------------------------------------------------------------------------
class TestDisaggFailover:
    def test_decode_loss_no_dup_no_drop(self, params):
        """THE disaggregated failover pin: kill a decode replica
        mid-stream; every stream it carried completes with exactly the
        solo-reference token sequence — re-prefilled through the
        (surviving) prefill tier onto the surviving decode replica,
        streamed prefix folded into the prompt."""
        class SlowFetch(ContinuousBatcher):
            def _fetch(self, handle):
                time.sleep(0.05)          # keep streams mid-flight
                return super()._fetch(handle)

        batchers = [SlowFetch(params, CFG, batch=2, max_len=64, chunk=2)
                    for _ in range(2)]
        prompts = _prompts(4, (5, 5, 5, 5))
        budget = 24
        with _Stack(params, CFG, max_len=64,
                    decode_batchers=batchers) as st:
            with StreamingClient("127.0.0.1", st.port) as c:
                rids = [c.submit(p, budget) for p in prompts]
                got = {r: [] for r in rids}
                started = set()
                deadline = time.time() + 90
                while len(started) < len(rids) and time.time() < deadline:
                    for r in rids:
                        if r in started:
                            continue
                        try:
                            ev = c.next_event(r, timeout=0.05)
                        except Exception:
                            continue
                        assert ev[0] == "tokens", ev
                        got[r].extend(ev[1])
                        started.add(r)
                assert len(started) == len(rids), "streams never started"
                # both decode replicas carry streams (assignment
                # tiebreak spreads the pair placements)
                actives = [d.engine.stats()["active"]
                           for d in st.decodes]
                assert all(a > 0 for a in actives), actives
                st.decodes[0].kill()      # decode replica loss
                for i, r in enumerate(rids):
                    while True:
                        ev = c.next_event(r, timeout=90)
                        if ev[0] == "tokens":
                            got[r].extend(ev[1])
                        elif ev[0] == "retired":
                            break
                        else:
                            raise AssertionError(ev)
                for i, r in enumerate(rids):
                    assert got[r] == _reference(params, prompts[i],
                                                budget), i
            assert st.regr.counter(
                "tony_router_failovers_total").value >= 1
            assert st.regr.counter(
                "tony_router_handoffs_total").value >= len(rids)

    def test_kv_ship_failure_fails_over_not_errors(self, params):
        """A decode gang's CHANNEL endpoint dies before the router's
        reader notices the replica itself (its TONYS1 link stays up):
        the prefill tier's ship fails, marks the failure RETRYABLE, and
        the router re-places the session toward the surviving decode
        replica — the client sees its tokens, never the transport
        fault."""
        batchers = [ContinuousBatcher(params, CFG, batch=2, max_len=48,
                                      chunk=3) for _ in range(2)]
        with _Stack(params, CFG, decode_batchers=batchers,
                    ship_timeout_s=1.0) as st:
            # channel endpoint only — the serving link stays healthy,
            # so placement still points at this gang
            st.decodes[0].hub.stop()
            p = _prompts(11, (5,))[0]
            with StreamingClient("127.0.0.1", st.port) as c:
                toks, reason = c.result(c.submit(p, 6), timeout=60)
            assert toks == _reference(params, p, 6)
            assert reason == "budget"
            assert st.regr.counter(
                "tony_router_failovers_total").value >= 1
            # the failover also tombstoned the old rrid on the decode
            # gang the shipment could not (verifiably) reach: "ship
            # failed" may be a delivered frame whose ack timed out, and
            # without the tombstone a late adoption would burn a decode
            # slot streaming into a stale rrid
            deadline = time.time() + 15
            while (not st.decodes[0]._tombstones
                   and time.time() < deadline):
                time.sleep(0.01)
            assert st.decodes[0]._tombstones


# ---------------------------------------------------------------------------
# Cancel across the split: wherever the CANCEL catches a request —
# queued at the prefill tier, mid-wave, or racing its KV package to the
# decode tier — the client gets EXACTLY one terminal frame and the
# router forgets the session
# ---------------------------------------------------------------------------
class _GatedPrefill(PrefillServer):
    """Prefill tier whose waves block on a gate: pins requests in the
    'queued' and 'mid-wave' states long enough to cancel them there."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.gate = threading.Event()

    def _prefill_group(self, grp, bucket, entry=None):
        self.gate.wait(timeout=60)
        super()._prefill_group(grp, bucket, entry)


class _BoomWavePrefill(PrefillServer):
    """Prefill tier whose FIRST wave dies with an unexpected error,
    paused mid-wave long enough (``in_wave``/``resume``) for the test
    to cancel one of its items there; later waves serve normally.
    ``take_gate`` holds the worker back so both prompts land in ONE
    wave."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.take_gate = threading.Event()
        self.in_wave = threading.Event()
        self.resume = threading.Event()
        self._boomed = False

    def _take_wave(self):
        self.take_gate.wait(timeout=60)
        return super()._take_wave()

    def _prefill_group(self, grp, bucket, entry=None):
        if not self._boomed:
            self._boomed = True
            self.in_wave.set()
            self.resume.wait(timeout=60)
            raise RuntimeError("injected wave failure")
        super()._prefill_group(grp, bucket, entry)


def _package_blob(params, cfg, rid, budget, prompt=(3, 1, 4, 1, 5),
                  logits_len=None):
    """A valid KV shipment blob for ``prompt``, built exactly as the
    prefill tier builds one (padded prefill, true-length extract).
    ``logits_len`` substitutes a wrong-vocab logits vector (the
    mismatched-gang-config case)."""
    prompt = list(prompt)
    toks = np.zeros((2, 16), np.int64)
    toks[0, :len(prompt)] = prompt
    lg, mini = prefill_ship_rows(
        params, jnp.asarray(toks, jnp.int32),
        jnp.asarray([len(prompt), 1], np.int32), cfg)
    bufs = extract_kv_rows(mini, [len(prompt)])[0]
    key = np.asarray(jax.random.fold_in(jax.random.PRNGKey(0), 0),
                     np.uint32)
    meta = kvship.pack_kv_meta(rid, budget, len(prompt), key, rng_off=0)
    logits = (np.zeros((logits_len,), np.float32)
              if logits_len is not None else np.asarray(lg)[0])
    return kvship.pack_shipment(meta, dict(bufs, logits=logits))


class TestDisaggDrain:
    """Planned decode-replica drain: the live-operability twin of the
    failover pin. The router fences the replica, re-prefills each of
    its sessions through the prefill tier onto the survivor (streamed
    prefix folded in, rng stream + offset pinned), and the old
    placement streams until the new one ACKs — zero duplicated or
    dropped tokens, greedy and sampled."""

    def _run(self, params, *, seed=0, temperature=0.0, top_k=0,
             top_p=0.0, ref=None):
        class SlowFetch(ContinuousBatcher):
            def _fetch(self, handle):
                time.sleep(0.05)          # keep streams mid-flight
                return super()._fetch(handle)

        # batch=4: the surviving decode replica has idle slots, so
        # migrations ACK while the old placement still streams
        kw = dict(batch=4, max_len=64, chunk=2, seed=seed,
                  temperature=temperature, top_k=top_k, top_p=top_p)
        batchers = [SlowFetch(params, CFG, **kw) for _ in range(2)]
        prompts = _prompts(44, (5, 5, 4, 6))
        budget = 20
        if ref is None:
            ref = [_reference(params, p, budget) for p in prompts]
        else:
            ref = ref(kw, prompts, budget)
        with _Stack(params, CFG, max_len=64, seed=seed,
                    decode_batchers=batchers) as st:
            with StreamingClient("127.0.0.1", st.port) as c:
                rids = [c.submit(p, budget) for p in prompts]
                got = {r: [] for r in rids}
                started = set()
                deadline = time.time() + 90
                while len(started) < len(rids) and time.time() < deadline:
                    for r in rids:
                        if r in started:
                            continue
                        try:
                            ev = c.next_event(r, timeout=0.05)
                        except Exception:
                            continue
                        assert ev[0] == "tokens", ev
                        got[r].extend(ev[1])
                        started.add(r)
                assert len(started) == len(rids), "streams never started"
                reps = st.router.stats()["replicas"]
                decode = {a: v for a, v in reps.items()
                          if v["role"] == "decode"}
                assert all(v["assigned"] > 0 for v in decode.values())
                victim = max(decode, key=lambda a: decode[a]["assigned"])
                res = c.drain_replica(victim)
                assert res.get("drained"), res
                assert res["migrated"] >= 1, res
                for r in rids:
                    while True:
                        ev = c.next_event(r, timeout=90)
                        if ev[0] == "tokens":
                            got[r].extend(ev[1])
                        elif ev[0] == "retired":
                            break
                        else:
                            raise AssertionError(ev)
                for i, r in enumerate(rids):
                    assert got[r] == ref[i], \
                        f"stream {i}: dup/drop across decode drain"
                post = st.router.stats()["replicas"]
                assert post[victim]["draining"]
                assert post[victim]["assigned"] == 0
            # planned migration, not crash failover
            assert st.regr.counter(
                "tony_router_failovers_total").value == 0
            assert st.regr.counter(
                "tony_router_drains_total").value == 1

    def test_decode_drain_zero_dup_drop_greedy(self, params):
        self._run(params)

    def test_decode_drain_zero_dup_drop_sampled(self, params):
        self._run(params, seed=7, temperature=0.8, top_k=20, top_p=0.9,
                  ref=lambda kw, prompts, budget: ContinuousBatcher(
                      params, CFG, **kw).serve(prompts, budget))


class TestDisaggCancel:
    def test_cancel_queued_and_mid_wave_both_retire(self, params):
        """Cancel a prompt still QUEUED at the prefill tier and one
        already MID-WAVE: the queued one retires from the prefill
        tier's queue; the mid-wave one finishes its (sunk) prefill but
        must NOT ship — the shipper retires it. Both cancels end in a
        client-visible RETIRED and the router drops the sessions."""
        with _Stack(params, CFG, max_batch=1,
                    prefill_cls=_GatedPrefill) as st:
            with StreamingClient("127.0.0.1", st.port) as c:
                ra = c.submit(_prompts(7, (5,))[0], 6)
                deadline = time.time() + 30
                while (st.prefill.stats()["active"] != 1
                       and time.time() < deadline):
                    time.sleep(0.01)
                assert st.prefill.stats()["active"] == 1   # A mid-wave
                rb = c.submit(_prompts(8, (4,))[0], 6)
                while (st.prefill.stats()["queue_depth"] != 1
                       and time.time() < deadline):
                    time.sleep(0.01)
                c.cancel(rb)                # still queued at prefill
                toks, reason = c.result(rb, timeout=30)
                assert reason == "cancelled" and toks == []
                c.cancel(ra)                # mid-wave
                # the CANCEL must land tier-side before the gate opens,
                # or this degenerates into the (also covered) tombstone
                # race instead of the mid-wave pin
                while st.prefill._items and time.time() < deadline:
                    time.sleep(0.01)
                st.prefill.gate.set()
                toks, reason = c.result(ra, timeout=30)
                assert reason == "cancelled" and toks == []
                assert st.regp.counter(
                    "tony_prefill_requests_total").value == 0  # no ship
                # the stack still serves: a fresh request completes
                p = _prompts(9, (5,))[0]
                toks, reason = c.result(c.submit(p, 4), timeout=60)
                assert toks == _reference(params, p, 4)
                assert reason == "budget"
            assert not st.router._sessions and not st.router._by_rrid

    def test_wave_failure_settles_midwave_cancelled_item(self, params):
        """An unexpected wave failure must settle EVERY item of the
        wave with exactly one terminal frame — including one a
        mid-wave CANCEL already popped from the item table (its
        RETIRED was deferred to the shipper, which never ran): the
        survivor fails with the wave's ERROR, the cancelled one
        retires as cancelled, and the worker thread survives to serve
        the next admission."""
        from tony_tpu.serving.client import ServingConnectionError

        with _Stack(params, CFG, max_batch=2,
                    prefill_cls=_BoomWavePrefill) as st:
            with StreamingClient("127.0.0.1", st.port) as c:
                pa, pb = _prompts(11, (5, 5))
                ra = c.submit(pa, 4)
                rb = c.submit(pb, 4)
                deadline = time.time() + 30
                while (st.prefill.stats()["queue_depth"] != 2
                       and time.time() < deadline):
                    time.sleep(0.01)
                assert st.prefill.stats()["queue_depth"] == 2
                st.prefill.take_gate.set()         # wave [A, B] starts
                assert st.prefill.in_wave.wait(timeout=30)
                c.cancel(rb)                       # mid-wave: RETIRED
                #                                  # deferred to shipper
                while (len(st.prefill._items) > 1
                       and time.time() < deadline):
                    time.sleep(0.01)
                assert len(st.prefill._items) == 1  # B popped, A still in
                st.prefill.resume.set()            # the wave dies
                toks, reason = c.result(rb, timeout=30)
                assert reason == "cancelled" and toks == []
                with pytest.raises(ServingConnectionError):
                    c.result(ra, timeout=30)
                # the worker survived: a fresh request serves
                p = _prompts(12, (5,))[0]
                toks, reason = c.result(c.submit(p, 4), timeout=60)
                assert toks == _reference(params, p, 4)
                assert reason == "budget"
            assert not st.router._sessions and not st.router._by_rrid

    def test_tombstone_drop_and_bad_shipment_cost_only_themselves(
            self, params):
        """Decode-tier landing contract, pinned over a raw sink link:
        (1) a package whose rid was cancelled before arrival is dropped
        but still pushes the terminal RETIRED (the engine never saw the
        rid — nobody else will ever speak for it); (2) a malformed
        shipment is dropped without killing the landing thread; (3) a
        healthy package then lands and streams normally."""
        from tony_tpu.channels.channel import ChannelSender

        dec = DecodeServer(ContinuousBatcher(params, CFG, batch=1,
                                             max_len=32, chunk=2),
                           registry=M.MetricsRegistry())
        port = dec.start()
        sender = sock = None
        try:
            sock = socket.create_connection(("127.0.0.1", port),
                                            timeout=10)
            sock.sendall(P.MAGIC)
            assert P.recv_frame(sock)[0] == P.HELLO
            P.send_frame(sock, P.BIND, 0)      # we are the delta sink
            P.send_frame(sock, P.CANCEL, 7)    # tombstone rid 7
            deadline = time.time() + 15
            while 7 not in dec._tombstones and time.time() < deadline:
                time.sleep(0.01)
            assert 7 in dec._tombstones
            sender = ChannelSender(f"127.0.0.1:{dec.hub.port}", "kvship",
                                   registry=M.MetricsRegistry())
            sender.send_bytes(_package_blob(params, CFG, rid=7, budget=4),
                              sync=True, timeout=30)
            fr = P.recv_frame(sock)
            assert fr[0] == P.RETIRED and fr[1] == 7, fr
            assert P.unpack_json(fr[2])["reason"] == "cancelled"
            # a malformed shipment (overflowing declared shape) between
            # two good ones: dropped, lander survives
            head = json.dumps({"v": 1, "meta": {"rid": 9}, "bufs": [
                {"name": "k", "dtype": "float32",
                 "shape": [1 << 32, 1 << 32]}]}).encode("utf-8")
            import struct
            sender.send_bytes(struct.pack("<I", len(head)) + head,
                              sync=True, timeout=30)
            # a vocab-mismatched logits vector (prefill/decode gangs on
            # different configs): request-scoped ERROR, engine intact
            sender.send_bytes(_package_blob(params, CFG, rid=11, budget=3,
                                            logits_len=7),
                              sync=True, timeout=30)
            fr = P.recv_frame(sock)
            assert fr[0] == P.ERROR and fr[1] == 11, fr
            assert "logits" in P.unpack_json(fr[2])["message"]
            sender.send_bytes(_package_blob(params, CFG, rid=8, budget=3),
                              sync=True, timeout=30)
            got = []
            while True:
                fr = P.recv_frame(sock)
                assert fr is not None and fr[1] == 8, fr
                if fr[0] == P.TOKENS:
                    got.extend(P.unpack_tokens(fr[2]))
                elif fr[0] == P.RETIRED:
                    assert P.unpack_json(fr[2])["reason"] == "budget"
                    break
            assert len(got) == 3
        finally:
            if sender is not None:
                sender.close(drain=False)
            if sock is not None:
                sock.close()
            dec.stop()

    def test_cancel_racing_the_landing_still_cancels(self, params):
        """A CANCEL that interleaves INSIDE the landing — after the
        tombstone check, before the engine registered the rid (so its
        engine.cancel no-ops) — must still win: the post-submit
        tombstone re-check cancels the freshly admitted request instead
        of letting it stream its full budget to a client that asked for
        death."""
        from tony_tpu.channels.channel import ChannelSender

        dec = DecodeServer(ContinuousBatcher(params, CFG, batch=1,
                                             max_len=48, chunk=2),
                           registry=M.MetricsRegistry())
        port = dec.start()
        real_submit = dec.engine.submit_prefilled

        def racing_submit(rid, pkg, budget, trace_ctx=None, **kw):
            real_submit(rid, pkg, budget, trace_ctx=trace_ctx, **kw)
            # the CANCEL handler runs here "mid-submit": tombstone set,
            # its engine.cancel no-oped (rid not yet visible to it)
            with dec._lock:
                dec._tombstones[rid] = True

        dec.engine.submit_prefilled = racing_submit
        sender = sock = None
        try:
            sock = socket.create_connection(("127.0.0.1", port),
                                            timeout=10)
            sock.sendall(P.MAGIC)
            assert P.recv_frame(sock)[0] == P.HELLO
            P.send_frame(sock, P.BIND, 0)
            sender = ChannelSender(f"127.0.0.1:{dec.hub.port}", "kvship",
                                   registry=M.MetricsRegistry())
            sender.send_bytes(_package_blob(params, CFG, rid=5,
                                            budget=30),
                              sync=True, timeout=30)
            while True:
                fr = P.recv_frame(sock)
                assert fr is not None and fr[1] == 5, fr
                if fr[0] == P.RETIRED:
                    assert P.unpack_json(fr[2])["reason"] == "cancelled"
                    break
                assert fr[0] == P.TOKENS     # a first chunk may slip
            assert not dec._tombstones       # consumed, not leaked
        finally:
            if sender is not None:
                sender.close(drain=False)
            if sock is not None:
                sock.close()
            dec.stop()

    def test_sink_loss_frees_every_adopted_slot(self, params):
        """Losing the delta sink — whichever side notices first, a
        failed push or the reader's EOF — cancels every live adopted
        request so its slot frees for the router's re-placements,
        instead of generating into the void until budget exhausts."""
        from tony_tpu.channels.channel import ChannelSender

        dec = DecodeServer(ContinuousBatcher(params, CFG, batch=2,
                                             max_len=64, chunk=2),
                           registry=M.MetricsRegistry())
        port = dec.start()
        sender = None
        sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        try:
            sock.sendall(P.MAGIC)
            assert P.recv_frame(sock)[0] == P.HELLO
            P.send_frame(sock, P.BIND, 0)
            sender = ChannelSender(f"127.0.0.1:{dec.hub.port}", "kvship",
                                   registry=M.MetricsRegistry())
            sender.send_bytes(_package_blob(params, CFG, rid=3,
                                            budget=50),
                              sync=True, timeout=30)
            deadline = time.time() + 60
            while (dec.engine.stats()["active"] != 1
                   and time.time() < deadline):
                time.sleep(0.02)
            assert dec.engine.stats()["active"] == 1
            sock.close()                     # the sink dies mid-stream
            while (dec.engine.stats()["active"] != 0
                   and time.time() < deadline):
                time.sleep(0.02)
            assert dec.engine.stats()["active"] == 0
        finally:
            if sender is not None:
                sender.close(drain=False)
            dec.stop()


# ---------------------------------------------------------------------------
# Bench-arm pins (deterministic tier-1; latency-realistic @slow)
# ---------------------------------------------------------------------------
class TestDisaggBenchArm:
    def test_itl_p99_and_handoff_wall_pins(self):
        """The tentpole acceptance, deterministically: with equal
        injected prefill/decode floors on both topologies, decode ITL
        p99 under concurrent admissions is >= 2x better disaggregated
        than colocated at equal slot count, the outputs are
        token-identical (asserted inside the arm), and the KV handoff
        wall is visible on the metrics plane."""
        import bench

        res = bench._disagg_arm()
        assert res["serving_disagg_itl_p99_vs_colocated"] >= 2.0, res
        assert res["serving_disagg_handoff_wall_s"] > 0, res
        assert res["serving_disagg_handoffs"] >= 9, res
        # colocated p99 actually saw the admission stall (>= the decode
        # floor + a meaningful share of the prefill floor)
        assert res["serving_colocated_itl_p99_s"] >= \
            res["serving_disagg_fetch_floor_s"] \
            + 0.2 * res["serving_disagg_prefill_floor_s"], res


@pytest.mark.slow
class TestDisaggBenchRealistic:
    def test_itl_contrast_survives_wan_latency(self):
        """Latency-realistic variant: the client path rides a
        LatencyProxy WAN hop. ITL is push-cadence, not round-trip-bound
        — the p99 contrast must hold unchanged."""
        import bench

        res = bench._disagg_arm(one_way_s=0.02)
        assert res["serving_disagg_itl_p99_vs_colocated"] >= 2.0, res


# ---------------------------------------------------------------------------
# Two REAL processes: the end-to-end token-identity acceptance pin
# ---------------------------------------------------------------------------
@pytest.mark.e2e
def test_token_identity_across_two_real_processes(tmp_path, params):
    """Greedy AND sampled disaggregated serving, with the prefill tier
    and the decode tier in two separate real processes (the driver
    holds only the routers and the client): outputs are token-identical
    to in-driver colocated references. Everything that could diverge —
    params init, bucket ladder, prefill program, rng stream state —
    crosses a process boundary here."""
    pre_ports = tmp_path / "prefill-ports.json"
    dec_ports = tmp_path / "decode-ports.json"
    done = tmp_path / "done"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(FIXTURES, fixture),
         "--port_file", str(port_file), "--done_file", str(done)],
        env=env, cwd=str(tmp_path))
        for fixture, port_file in
        (("disagg_prefill_fixture.py", pre_ports),
         ("disagg_decode_fixture.py", dec_ports))]
    routers = []
    try:
        deadline = time.time() + 150
        while time.time() < deadline and not (
                pre_ports.exists() and dec_ports.exists()):
            assert all(p.poll() is None for p in procs), \
                "a tier process died before binding"
            time.sleep(0.2)
        assert pre_ports.exists() and dec_ports.exists(), \
            "tier port files never appeared"
        pports = json.loads(pre_ports.read_text())
        dports = json.loads(dec_ports.read_text())

        prompts = _prompts(5, (5, 3, 7, 4))
        refs = {
            "greedy": ContinuousBatcher(
                params, CFG, batch=2, max_len=48, chunk=3,
                seed=7).serve(prompts, 6),
            "sampled": ContinuousBatcher(
                params, CFG, batch=2, max_len=48, chunk=3,
                temperature=0.8, top_k=20, top_p=0.9,
                seed=7).serve(prompts, 6),
        }
        for mode in ("greedy", "sampled"):
            router = ServingRouter(
                [f"127.0.0.1:{pports[mode]}"],
                decode_replicas=[f"127.0.0.1:{dports[mode]}"],
                registry=M.MetricsRegistry())
            routers.append(router)
            with StreamingClient("127.0.0.1", router.start()) as c:
                rids = [c.submit(p, 6) for p in prompts]
                outs = [c.result(r, timeout=150)[0] for r in rids]
            assert outs == refs[mode], mode
    finally:
        done.write_text("done")
        for router in routers:
            router.stop()
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
    assert all(p.returncode == 0 for p in procs), \
        [p.returncode for p in procs]
