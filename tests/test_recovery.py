"""Coordinator crash recovery: journal replay, executor re-attach, and
the kill-coordinator-mid-train chaos e2e.

The tentpole pin: SIGKILL the coordinator mid-train, let the client
relaunch it on the SAME job dir, and require that the restarted
coordinator rebuilds the session from the journal and re-adopts the
running executors — every worker's user process runs start-to-finish
exactly once, the step transcript is bit-identical to an uninterrupted
run, and the journal's launch-record count proves zero re-provisions.
"""

import json
import os
import signal
import sys
import threading
import time

import pytest

from tony_tpu.client.client import TonyClient
from tony_tpu.cluster import journal as journal_mod
from tony_tpu.conf.config import TonyConfig
from tony_tpu.events.events import find_job_files, parse_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAINER = os.path.join(REPO, "tests", "fixtures",
                       "fake_elastic_trainer.py")
PY = sys.executable


def _run_job(workdir, steps, step_wait, workers, kill_flags="",
             extra_conf=None, shell_env=None, tail=2.0):
    # Chief (worker 0) finishes LAST: its completion is the job verdict
    # and would otherwise SIGTERM a sibling that is milliseconds behind,
    # truncating the transcript the bit-identity assertion diffs. A
    # single-worker job has no siblings to protect — pass tail=0 there.
    cmd = (f"{PY} {TRAINER} --steps {steps} "
           f"--ckpt {workdir / 'progress'} --ckpt_every 2 "
           f"--step_wait {step_wait}"
           + (f" --tail_wait 0:{tail}" if tail else "")
           + (f" {kill_flags}" if kill_flags else ""))
    conf = {
        "tony.staging.dir": str(workdir / "staging"),
        "tony.history.location": str(workdir / "hist"),
        "tony.application.timeout": "120000",
        "tony.worker.instances": str(workers),
        "tony.task.heartbeat-interval-ms": "250",
        "tony.metrics.snapshot-interval-ms": "1000",
    }
    conf.update(extra_conf or {})
    client = TonyClient(TonyConfig(conf), cmd, shell_env=shell_env or {})
    return client, client.run()


def _worker_steps(job_dir, index):
    """(ordered step lines, count of trainer generations) for a worker."""
    body = open(os.path.join(job_dir, "logs",
                             f"worker-{index}.stdout")).read()
    steps = [ln for ln in body.splitlines() if ln.startswith("step ")]
    return steps, body.count("starting at step")


@pytest.mark.recovery
@pytest.mark.e2e
def test_coordinator_kill_mid_train_recovers(tmp_path):
    """SIGKILL the coordinator at a marker step; the relaunched
    coordinator must recover the session from the journal and re-adopt
    both executors — zero relaunches, bit-identical step transcript."""
    workers = 2
    steps, step_wait = 18, 0.2

    # Uninterrupted reference run: its per-worker step transcript is the
    # bit-identity baseline for the chaos run. It runs CONCURRENTLY with
    # the chaos job — both are sleep-bound process trees on disjoint job
    # dirs and random RPC ports, so overlapping them halves the wall.
    base_dir = tmp_path / "baseline"
    base_dir.mkdir()
    base_out = {}

    def _baseline_job():
        c, r = _run_job(base_dir, steps, step_wait, workers)
        base_out["client"], base_out["rc"] = c, r

    base_thread = threading.Thread(target=_baseline_job)
    base_thread.start()

    # Chaos run: worker 0 touches the marker when it STARTS step 4; the
    # local backend SIGKILLs the coordinator on its next poll.
    chaos_dir = tmp_path / "chaos"
    chaos_dir.mkdir()
    marker = chaos_dir / "kill-coordinator.marker"
    client, rc = _run_job(
        chaos_dir, steps, step_wait, workers,
        kill_flags=f"--kill {marker}:4:0",
        extra_conf={"tony.am.retry-count": "1"},
        shell_env={"TEST_KILL_COORDINATOR": str(marker)})
    base_thread.join(timeout=120)
    assert not base_thread.is_alive(), "baseline job hung"
    assert base_out["rc"] == 0
    baseline = {i: _worker_steps(base_out["client"].job_dir, i)
                for i in range(workers)}
    detail = f"rc={rc}, job_dir={client.job_dir}"
    assert rc == 0, detail
    # the chaos hook actually fired (sentinel written before the SIGKILL)
    assert os.path.exists(str(marker) + ".fired"), detail

    # Every worker's user process ran start-to-finish exactly once, and
    # its ordered step transcript matches the uninterrupted runbit-for-bit.
    for i in range(workers):
        got_steps, generations = _worker_steps(client.job_dir, i)
        assert generations == 1, (
            f"worker {i} trainer restarted ({generations} generations) — "
            f"recovery must never touch the user process; {detail}")
        assert got_steps == baseline[i][0], (
            f"worker {i} step transcript diverged from the uninterrupted "
            f"run; {detail}")
        # the executor re-ran the registration handshake on seeing the
        # new incarnation
        err = open(os.path.join(client.job_dir, "logs",
                                f"worker-{i}.stderr")).read()
        assert "re-attached to restarted coordinator" in err, (
            f"worker {i} never re-attached; {detail}")

    # Journal: two coordinator generations, and exactly one launch record
    # per worker — the restarted coordinator provisioned NOTHING.
    records = journal_mod.replay(journal_mod.journal_path(client.job_dir))
    state = journal_mod.fold(records)
    assert state.incarnation == 2, detail
    launches = [r for r in records if r["k"] == "launch"]
    assert len(launches) == workers, (
        f"expected {workers} launch records (zero re-provisions), got "
        f"{[r['task_id'] for r in launches]}; {detail}")
    assert all(not t.completed or t.exit_code == 0
               for t in state.tasks.values()), detail

    # History: the restarted coordinator's jhist opens with
    # COORDINATOR_RESTART and contains zero TASK_SCHEDULED events — the
    # history-visible proof that recovery launched nothing. The killed
    # generation's (orphaned .inprogress) file holds the real launches.
    files = find_job_files(str(chaos_dir / "hist"))
    by_file = {f: parse_events(f) for f in files}
    restart_files = [f for f, evs in by_file.items()
                     if any(e.event_type == "COORDINATOR_RESTART"
                            for e in evs)]
    assert len(restart_files) == 1, (files, detail)
    restart_events = by_file[restart_files[0]]
    types = [e.event_type for e in restart_events]
    assert "TASK_SCHEDULED" not in types, (types, detail)
    restart = next(e for e in restart_events
                   if e.event_type == "COORDINATOR_RESTART")
    assert restart.payload["incarnation"] == 2, restart.payload
    assert sorted(restart.payload["adopted"]) == [
        f"worker:{i}" for i in range(workers)], restart.payload
    # the killed generation's file carries the original launches
    orphan = [evs for f, evs in by_file.items() if f not in restart_files]
    assert any(sum(1 for e in evs if e.event_type == "TASK_SCHEDULED")
               == workers for e in [None] for evs in orphan), detail

    # Observability: restart counter and recovery-wall gauge ride the
    # coordinator's own registry into the final METRICS_SNAPSHOT.
    snapshots = [e for e in restart_events
                 if e.event_type == "METRICS_SNAPSHOT"]
    assert snapshots, detail
    wire = json.dumps(snapshots[-1].payload)
    assert "tony_coordinator_restarts_total" in wire, detail
    assert "tony_coordinator_recovery_seconds" in wire, detail

    # Goodput ledger resume: the restarted coordinator journaled the
    # recovery wall ONCE per adopted task, and its final GOODPUT event
    # carries exactly the journal-folded extras — pre-crash attributions
    # are replayed, never re-journaled, so nothing double-counts.
    recov = [r for r in records if r["k"] == "goodput_extra"
             and r.get("category") == "recovery"]
    assert sorted(r["task"] for r in recov) == [
        f"worker:{i}" for i in range(workers)], (recov, detail)
    goodputs = [e for e in restart_events if e.event_type == "GOODPUT"]
    assert goodputs, detail
    final_tasks = goodputs[-1].payload["tasks"]
    for tid, cats in state.goodput_extra.items():
        got = final_tasks[tid]["extra"]
        for cat, secs in cats.items():
            assert got.get(cat, 0.0) == pytest.approx(secs, abs=1e-3), (
                tid, cat, got, state.goodput_extra, detail)
    assert all(final_tasks[f"worker:{i}"]["extra"]["recovery"] > 0
               for i in range(workers)), (final_tasks, detail)


@pytest.mark.recovery
def test_journal_disabled_runs_without_journal(tmp_path):
    """tony.coordinator.journal-enabled=false: no journal file, job still
    green (the feature must be fully optional)."""
    client, rc = _run_job(
        tmp_path, 4, 0.05, 1, tail=0,
        extra_conf={"tony.coordinator.journal-enabled": "false"})
    assert rc == 0
    assert not os.path.exists(journal_mod.journal_path(client.job_dir))


@pytest.mark.recovery
def test_journal_written_and_fsck_clean_after_success(tmp_path):
    """A green job leaves a clean, fsck-verifiable journal whose fold
    shows every task completed with exit 0."""
    client, rc = _run_job(tmp_path, 4, 0.05, 2)
    assert rc == 0
    path = journal_mod.journal_path(client.job_dir)
    records, torn, _ = journal_mod.scan(path)
    assert torn is None
    state = journal_mod.fold(records)
    assert state.incarnation == 1
    assert sorted(state.tasks) == ["worker:0", "worker:1"]
    assert all(t.completed and t.exit_code == 0
               for t in state.tasks.values())
    kinds = [r["k"] for r in records]
    assert kinds.count("launch") == 2
    assert kinds.count("task_registered") == 2
    assert kinds.count("completion") == 2


@pytest.mark.recovery
def test_stop_is_idempotent(tmp_path):
    """Second stop() (the double-SIGTERM path: the signal handler re-runs
    on the main thread while stop() is already executing) must not re-run
    teardown or overwrite the first call's verdict."""
    from tony_tpu.cluster.coordinator import Coordinator
    from tony_tpu.cluster.session import SessionStatus
    conf = TonyConfig({
        "tony.worker.instances": "1",
        "tony.history.location": str(tmp_path / "hist")})
    co = Coordinator(conf, "application_stop_idem", str(tmp_path))
    try:
        co.client_signalled_finish.set()     # don't wait out the grace
        co.failure_message = "killed by signal 15"
        rc1 = co.stop(SessionStatus.KILLED)
        final_path = tmp_path / "final-status.json"
        first = json.load(open(final_path))
        stamp = os.stat(final_path).st_mtime_ns
        # Re-entry with a DIFFERENT verdict: first caller won already.
        rc2 = co.stop(SessionStatus.SUCCEEDED)
        assert (rc1, rc2) == (1, 1)
        assert json.load(open(final_path)) == first
        assert os.stat(final_path).st_mtime_ns == stamp
        assert first["status"] == "KILLED"
    finally:
        co.rpc_server.stop(0)


@pytest.mark.recovery
@pytest.mark.e2e
def test_double_sigterm_single_teardown(tmp_path):
    """Two SIGTERMs in quick succession tear the job down exactly once:
    one final status, one 'application finished' log line."""
    cmd = f"{PY} -c 'import time; time.sleep(30)'"
    conf = TonyConfig({
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.location": str(tmp_path / "hist"),
        "tony.application.timeout": "60000",
        "tony.worker.instances": "1",
    })
    client = TonyClient(conf, cmd)
    rcs = []
    t = threading.Thread(target=lambda: rcs.append(client.run()))
    t.start()
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            am = getattr(client, "am_proc", None)
            if am is not None and os.path.exists(
                    os.path.join(client.job_dir, "coordinator.addr")):
                break
            time.sleep(0.1)
        else:
            pytest.fail("coordinator never came up")
        time.sleep(0.5)               # let the worker launch
        os.kill(client.am_proc.pid, signal.SIGTERM)
        time.sleep(0.3)
        try:
            os.kill(client.am_proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass                      # already fully down — also fine
    finally:
        t.join(timeout=60)
    assert not t.is_alive()
    assert rcs == [1]
    final = json.load(open(os.path.join(tmp_path, "staging",
                                        client.app_id, "final-status.json")))
    assert final["status"] == "KILLED"
    err = open(os.path.join(client.job_dir, "logs", "am.stderr")).read()
    assert err.count("application finished:") == 1, err[-2000:]
    assert err.count("received signal") >= 1, err[-2000:]


@pytest.mark.recovery
@pytest.mark.e2e
@pytest.mark.slow
def test_coordinator_kill_recovery_latency_realistic(tmp_path):
    """Production-cadence variant: 1s heartbeats, 3 workers, later kill —
    the re-attach window logic must hold at real heartbeat latencies, and
    the recovery wall must be recorded."""
    workers = 3
    marker = tmp_path / "kill-coordinator.marker"
    client, rc = _run_job(
        tmp_path, 30, 0.5, workers,
        kill_flags=f"--kill {marker}:8:0",
        extra_conf={
            "tony.am.retry-count": "1",
            "tony.task.heartbeat-interval-ms": "1000",
        },
        shell_env={"TEST_KILL_COORDINATOR": str(marker)})
    assert rc == 0
    assert os.path.exists(str(marker) + ".fired")
    for i in range(workers):
        _, generations = _worker_steps(client.job_dir, i)
        assert generations == 1
    state = journal_mod.fold(
        journal_mod.replay(journal_mod.journal_path(client.job_dir)))
    assert state.incarnation == 2


# ---------------------------------------------------------------------------
# Bench arm: recovery-vs-cold-restart ratio pin (jax-free fake trainer)
# ---------------------------------------------------------------------------
@pytest.mark.recovery
@pytest.mark.e2e
@pytest.mark.slow
def test_recovery_bench_arm_pins_ratio():
    """bench._recovery_arm drives the SAME coordinator SIGKILL through
    journal re-adoption and through the cold full-job restart. Pins:
    re-adoption replays ZERO steps, and the recovery wall beats the
    cold restart by >= 3x (asserted inside the arm; re-asserted here so
    the pin reads off the BENCH json keys)."""
    sys.path.insert(0, REPO)
    import bench
    res = bench._recovery_arm()
    assert res["coordinator_recovery_wall_s"] > 0
    assert res["recovery_steps_replayed"] == 0
    assert res["recovery_vs_cold_restart"] >= 3
    assert res["cold_restart_wall_s"] > res["coordinator_recovery_wall_s"]
