"""Parallel-layer tests on the virtual 8-device CPU mesh (conftest.py).

Covers the green-field strategies SURVEY.md §2.3 flags as absent from the
reference and first-class here: mesh construction/presets, logical sharding
rules, ring attention (CP), GPipe pipelining (PP), and MoE dispatch (EP).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tony_tpu.parallel import (
    logical_to_spec,
    make_mesh,
    moe_ffn,
    parse_mesh_string,
    pipeline_apply,
    ring_attention,
)


@pytest.fixture(scope="module")
def rng():
    return np.random.RandomState(42)


# ---------------------------------------------------------------------------
# mesh
# ---------------------------------------------------------------------------

class TestMesh:
    def test_explicit_axes(self):
        mesh = make_mesh({"dp": 2, "tp": 4})
        assert dict(mesh.shape) == {"dp": 2, "tp": 4}

    def test_inferred_axis(self):
        mesh = make_mesh({"dp": -1, "tp": 2})
        assert mesh.shape["dp"] == 4

    def test_default_is_pure_dp(self):
        mesh = make_mesh(None)
        assert dict(mesh.shape) == {"dp": 8}

    def test_canonical_axis_order(self):
        # minor-most (fastest ICI) axis must be tp regardless of dict order
        mesh = make_mesh({"tp": 2, "pp": 2, "dp": 2})
        assert mesh.axis_names == ("pp", "dp", "tp")

    def test_size_mismatch_raises(self):
        with pytest.raises(ValueError):
            make_mesh({"dp": 3})

    def test_two_inferred_raises(self):
        with pytest.raises(ValueError):
            make_mesh({"dp": -1, "tp": -1})

    def test_parse_mesh_string(self):
        assert parse_mesh_string("dp=2, tp=4") == {"dp": 2, "tp": 4}
        assert parse_mesh_string("") == {}
        with pytest.raises(ValueError):
            parse_mesh_string("dp")


class TestHybridMesh:
    """Multi-slice meshes: dcn axes across slices, ici axes within."""

    def test_dcn_major_ici_minor(self):
        from tony_tpu.parallel.mesh import make_hybrid_mesh
        mesh = make_hybrid_mesh({"tp": 2, "fsdp": 2}, {"dp": 2})
        assert mesh.axis_names == ("dp", "fsdp", "tp")   # dcn axis major
        assert dict(mesh.shape) == {"dp": 2, "fsdp": 2, "tp": 2}
        # contiguous device halves = the two slices (process ids are
        # slice-major, so this matches real multi-slice layout)
        import numpy as np
        devs = np.asarray(mesh.devices)
        first_slice = devs[0].ravel()
        assert [d.id for d in first_slice] == [0, 1, 2, 3]

    def test_ici_inference(self):
        from tony_tpu.parallel.mesh import make_hybrid_mesh
        mesh = make_hybrid_mesh({"tp": -1}, {"dp": 2})
        assert dict(mesh.shape) == {"dp": 2, "tp": 4}

    def test_no_dcn_falls_back_to_flat(self):
        from tony_tpu.parallel.mesh import make_hybrid_mesh
        mesh = make_hybrid_mesh({"dp": 8}, {})
        assert dict(mesh.shape) == {"dp": 8}

    def test_empty_ici_avoids_dcn_name_collision(self):
        # dcn dp + no tony.application.mesh is the documented common case
        from tony_tpu.parallel.mesh import make_hybrid_mesh
        mesh = make_hybrid_mesh({}, {"dp": 2})
        assert dict(mesh.shape) == {"dp": 2, "fsdp": 4}
        assert mesh.axis_names == ("dp", "fsdp")

    def test_errors(self):
        from tony_tpu.parallel.mesh import make_hybrid_mesh
        with pytest.raises(ValueError, match="explicit"):
            make_hybrid_mesh({"tp": 4}, {"dp": -1})
        with pytest.raises(ValueError, match="do not split"):
            make_hybrid_mesh({"tp": 4}, {"dp": 3})
        with pytest.raises(ValueError, match="both"):
            make_hybrid_mesh({"dp": 4}, {"dp": 2})

    def test_train_step_over_hybrid_mesh(self):
        """A dp-across-slices × tp-inside sharded step runs and is finite —
        the tony.{job}.slices=2 data path on the virtual backend."""
        import jax.numpy as jnp
        from tony_tpu.models import transformer as T
        from tony_tpu.models.train import (default_optimizer, init_state,
                                           make_train_step)
        from tony_tpu.parallel.mesh import make_hybrid_mesh
        from tony_tpu.parallel.sharding import shard_pytree
        mesh = make_hybrid_mesh({"tp": -1}, {"dp": 2})
        cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32)
        params = shard_pytree(T.init_params(jax.random.PRNGKey(0), cfg),
                              T.logical_axes(cfg), mesh)
        opt = default_optimizer(lr=1e-3)
        state = init_state(params, opt)
        step = make_train_step(lambda p, b: T.lm_loss(p, b, cfg, mesh),
                               opt, mesh)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 65), 0,
                                    cfg.vocab_size)
        batch = {"inputs": tokens[:, :64], "targets": tokens[:, 1:]}
        _, metrics = step(state, batch)
        assert bool(jnp.isfinite(metrics["loss"]))


# ---------------------------------------------------------------------------
# sharding rules
# ---------------------------------------------------------------------------

class TestShardingRules:
    def test_batch_maps_to_dp_fsdp(self):
        mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
        spec = logical_to_spec(("batch", "embed", "mlp"), mesh)
        # fsdp is consumed by batch, so embed (same array) must replicate —
        # a mesh axis may shard at most one dim of an array
        assert spec == P(("dp", "fsdp"), None, "tp")

    def test_params_get_fsdp_on_embed(self):
        mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
        assert logical_to_spec(("embed", "mlp"), mesh) == P("fsdp", "tp")

    def test_missing_axes_drop_to_replication(self):
        mesh = make_mesh({"dp": 8})
        spec = logical_to_spec(("batch", "embed", "mlp"), mesh)
        assert spec == P("dp", None, None)

    def test_unknown_logical_name_replicates(self):
        mesh = make_mesh({"dp": 8})
        assert logical_to_spec(("nonesuch",), mesh) == P(None)

    def test_pure_fsdp_activation_no_duplicate_axis(self):
        # regression: ("batch","embed") on {"fsdp": 8} must not produce
        # PartitionSpec("fsdp","fsdp") (DuplicateSpecError under jax)
        mesh = make_mesh({"fsdp": 8})
        spec = logical_to_spec(("batch", "embed"), mesh)
        assert spec == P("fsdp", None)
        from tony_tpu.parallel import logical_sharding
        logical_sharding(("batch", "embed"), mesh)  # must not raise

    def test_param_shardings_tuple_pytree(self):
        # regression: ((W_axes, b_axes), ...) containers must not be
        # swallowed as a single leaf (silent full replication)
        from tony_tpu.parallel import param_shardings
        mesh = make_mesh({"fsdp": 8})
        tree = (("embed", "mlp"), ("mlp",))
        got = param_shardings(tree, mesh)
        assert got[0].spec == P("fsdp", None)
        assert got[1].spec == P(None)


# ---------------------------------------------------------------------------
# ring attention (context parallelism)
# ---------------------------------------------------------------------------

def _dense_attention(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (d ** -0.5)
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)


class TestRingAttention:
    @pytest.fixture(scope="class")
    def qkv(self):
        r = np.random.RandomState(0)
        shape = (2, 32, 4, 16)
        return tuple(jnp.asarray(r.randn(*shape), jnp.float32)
                     for _ in range(3))

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, qkv, causal):
        q, k, v = qkv
        mesh = make_mesh({"dp": 2, "cp": 4})
        out = ring_attention(q, k, v, mesh, causal=causal)
        expect = _dense_attention(q, k, v, causal)
        np.testing.assert_allclose(out, expect, atol=2e-5)

    @pytest.mark.slow
    def test_gradients_match_dense(self, qkv):
        q, k, v = qkv
        mesh = make_mesh({"dp": 2, "cp": 4})
        g = jax.grad(lambda *a: ring_attention(*a, mesh).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: _dense_attention(*a, True).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(got, want, atol=2e-5)

    @pytest.fixture(scope="class")
    def qkv_gqa(self):
        r = np.random.RandomState(4)
        q = jnp.asarray(r.randn(2, 32, 4, 16), jnp.float32)
        k = jnp.asarray(r.randn(2, 32, 2, 16), jnp.float32)
        v = jnp.asarray(r.randn(2, 32, 2, 16), jnp.float32)
        return q, k, v

    def _dense_gqa(self, q, k, v, causal):
        return _dense_attention(q, jnp.repeat(k, 2, 2),
                                jnp.repeat(v, 2, 2), causal)

    @pytest.mark.parametrize("causal", [True, False])
    def test_gqa_rides_ring_unexpanded(self, qkv_gqa, causal):
        """GQA K/V rotate unexpanded (the ppermute payload is the ring's
        whole inter-chip cost) and expand locally per hop."""
        q, k, v = qkv_gqa
        mesh = make_mesh({"dp": 2, "cp": 4})
        out = ring_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(out, self._dense_gqa(q, k, v, causal),
                                   atol=2e-5)

    def test_gqa_flash_arm(self, qkv_gqa, monkeypatch):
        import importlib
        R = importlib.import_module("tony_tpu.parallel.ring_attention")
        monkeypatch.setattr(R, "_USE_FLASH_CHUNKS", True)
        q, k, v = qkv_gqa
        mesh = make_mesh({"dp": 2, "cp": 4})
        out = ring_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(out, self._dense_gqa(q, k, v, True),
                                   atol=2e-5)

    @pytest.mark.parametrize("kv_heads", [2, 1])
    def test_gqa_with_tp_sharded_heads(self, kv_heads):
        """GQA + a LIVE tp axis: q heads are tp-sharded, so kv heads must
        shard over the same axis (kv_heads % tp == 0) or expand — local
        j // rep pairing on replicated kv heads computes WRONG attention
        (regression for the mis-pairing bug)."""
        r = np.random.RandomState(6)
        q = jnp.asarray(r.randn(2, 32, 4, 16), jnp.float32)
        k = jnp.asarray(r.randn(2, 32, kv_heads, 16), jnp.float32)
        v = jnp.asarray(r.randn(2, 32, kv_heads, 16), jnp.float32)
        mesh = make_mesh({"dp": 2, "cp": 2, "tp": 2})
        out = ring_attention(q, k, v, mesh, causal=True)
        rep = 4 // kv_heads
        want = _dense_attention(q, jnp.repeat(k, rep, 2),
                                jnp.repeat(v, rep, 2), True)
        np.testing.assert_allclose(out, want, atol=2e-5)

    def test_gqa_indivisible_heads_raises(self, qkv_gqa):
        q, k, v = qkv_gqa
        mesh = make_mesh({"dp": 2, "cp": 4})
        with pytest.raises(ValueError, match="divide"):
            ring_attention(q, k[:, :, :1].repeat(3, 2)[:, :, :3], v, mesh)

    @pytest.mark.slow
    def test_gqa_gradients(self, qkv_gqa):
        q, k, v = qkv_gqa
        mesh = make_mesh({"dp": 2, "cp": 4})
        g = jax.grad(lambda *a: ring_attention(*a, mesh).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: self._dense_gqa(*a, True).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            assert got.shape == want.shape    # dK/dV stay kv_heads-wide
            np.testing.assert_allclose(got, want, atol=3e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_flash_chunk_arm_matches_dense(self, qkv, causal, monkeypatch):
        """The TPU arm (flash kernels per hop + logsumexp merge), forced on
        in interpret mode: values must match the dense oracle."""
        import importlib
        R = importlib.import_module("tony_tpu.parallel.ring_attention")
        monkeypatch.setattr(R, "_USE_FLASH_CHUNKS", True)
        q, k, v = qkv
        mesh = make_mesh({"dp": 2, "cp": 4})
        out = ring_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(out, _dense_attention(q, k, v, causal),
                                   atol=2e-5)

    @pytest.mark.slow
    def test_flash_chunk_arm_gradients(self, qkv, monkeypatch):
        """Backward through the flash arm: d(lse) flows through the merge
        into the chunk kernels (the transposed ring)."""
        import importlib
        R = importlib.import_module("tony_tpu.parallel.ring_attention")
        monkeypatch.setattr(R, "_USE_FLASH_CHUNKS", True)
        q, k, v = qkv
        mesh = make_mesh({"dp": 2, "cp": 4})
        g = jax.grad(lambda *a: (ring_attention(*a, mesh) ** 2).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: (_dense_attention(*a, True) ** 2).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(got, want, atol=5e-5)

    def test_no_cp_axis_fallback(self, qkv):
        q, k, v = qkv
        mesh = make_mesh({"dp": 2, "tp": 4})
        out = ring_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(out, _dense_attention(q, k, v, True),
                                   atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_no_cp_axis_flash_engine(self, qkv, causal, monkeypatch):
        import importlib
        R = importlib.import_module("tony_tpu.parallel.ring_attention")
        monkeypatch.setattr(R, "_USE_FLASH_CHUNKS", True)
        q, k, v = qkv
        mesh = make_mesh({"dp": 2, "tp": 4})
        out = ring_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(out, _dense_attention(q, k, v, causal),
                                   atol=2e-5)

    def test_heads_over_tp(self, qkv):
        q, k, v = qkv
        mesh = make_mesh({"cp": 4, "tp": 2})
        out = ring_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(out, _dense_attention(q, k, v, True),
                                   atol=2e-5)


# ---------------------------------------------------------------------------
# pipeline parallelism
# ---------------------------------------------------------------------------

class TestPipeline:
    @staticmethod
    def _stage(p, h):
        w, b = p
        return jnp.tanh(h @ w + b)

    @pytest.fixture(scope="class")
    def problem(self):
        r = np.random.RandomState(1)
        s, b, d = 4, 8, 16
        W = jnp.asarray(r.randn(s, d, d) * 0.1, jnp.float32)
        bias = jnp.asarray(r.randn(s, d) * 0.1, jnp.float32)
        x = jnp.asarray(r.randn(b, d), jnp.float32)
        h = x
        for i in range(s):
            h = jnp.tanh(h @ W[i] + bias[i])
        return W, bias, x, h

    def test_matches_sequential(self, problem):
        W, b, x, want = problem
        mesh = make_mesh({"pp": 4, "dp": 2})
        out = pipeline_apply(self._stage, (W, b), x, mesh, num_microbatches=4)
        np.testing.assert_allclose(out, want, atol=1e-6)

    @pytest.mark.slow
    def test_gradients_match_sequential(self, problem):
        W, b, x, _ = problem
        mesh = make_mesh({"pp": 4, "dp": 2})

        def ref_loss(W, b):
            h = x
            for i in range(W.shape[0]):
                h = self._stage((W[i], b[i]), h)
            return h.sum()

        got = jax.grad(lambda W, b: pipeline_apply(
            self._stage, (W, b), x, mesh, num_microbatches=4).sum(),
            argnums=(0, 1))(W, b)
        want = jax.grad(ref_loss, argnums=(0, 1))(W, b)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, atol=1e-5)

    def test_degenerate_no_pp_axis(self, problem):
        W, b, x, want = problem
        mesh = make_mesh({"dp": 8})
        out = pipeline_apply(self._stage, (W, b), x, mesh, num_microbatches=2)
        np.testing.assert_allclose(out, want, atol=1e-6)

    def test_indivisible_microbatches_raises(self, problem):
        W, b, x, _ = problem
        mesh = make_mesh({"pp": 4, "dp": 2})
        with pytest.raises(ValueError):
            pipeline_apply(self._stage, (W, b), x, mesh, num_microbatches=3)

    def test_indivisible_microbatches_raises_without_pp(self, problem):
        # regression: validation must not be skipped on the degenerate path
        W, b, x, _ = problem
        mesh = make_mesh({"dp": 8})
        with pytest.raises(ValueError):
            pipeline_apply(self._stage, (W, b), x, mesh, num_microbatches=3)

    def test_stage_count_mismatch_raises(self, problem):
        # regression: 4 stages over pp=2 silently dropped stages 1 and 3
        W, b, x, _ = problem
        mesh = make_mesh({"pp": 2, "dp": 4})
        with pytest.raises(ValueError, match="stacked stages"):
            pipeline_apply(self._stage, (W, b), x, mesh, num_microbatches=4)


# ---------------------------------------------------------------------------
# pipeline parallelism on the flagship transformer (forward routes through
# the GPipe schedule automatically when the mesh has a pp axis > 1)
# ---------------------------------------------------------------------------

class TestPipelineTransformer:
    @pytest.fixture(scope="class")
    def setup(self):
        from tony_tpu.models import transformer as T
        from tony_tpu.parallel import shard_pytree

        cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=True)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 65), 0,
                                    cfg.vocab_size)
        batch = {"inputs": tokens[:, :64], "targets": tokens[:, 1:65]}
        ref_loss = float(T.lm_loss(params, batch, cfg, None))
        return T, shard_pytree, cfg, params, batch, ref_loss

    def test_pp_loss_matches_unpipelined(self, setup):
        T, shard_pytree, cfg, params, batch, ref_loss = setup
        mesh = make_mesh({"pp": 2, "dp": 4})
        sp = shard_pytree(params, T.logical_axes(cfg), mesh)
        loss = jax.jit(lambda p, b: T.lm_loss(p, b, cfg, mesh))(sp, batch)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)

    def test_pp4_loss_matches_unpipelined(self, setup):
        # pp = n_layers/... : tiny has 2 layers, so scale to 4 for pp=4
        T, shard_pytree, cfg, params, batch, ref_loss = setup
        cfg4 = cfg.scaled(n_layers=4)
        params4 = T.init_params(jax.random.PRNGKey(3), cfg4)
        ref = float(T.lm_loss(params4, batch, cfg4, None))
        mesh = make_mesh({"pp": 4, "dp": 2})
        sp = shard_pytree(params4, T.logical_axes(cfg4), mesh)
        loss = jax.jit(lambda p, b: T.lm_loss(p, b, cfg4, mesh))(sp, batch)
        np.testing.assert_allclose(float(loss), ref, rtol=1e-5)

    @pytest.mark.slow
    def test_pp_gradients_match_unpipelined(self, setup):
        T, shard_pytree, cfg, params, batch, _ = setup
        mesh = make_mesh({"pp": 2, "dp": 4})
        sp = shard_pytree(params, T.logical_axes(cfg), mesh)
        g_ref = jax.grad(lambda p: T.lm_loss(p, batch, cfg, None))(params)
        g_pp = jax.jit(
            jax.grad(lambda p: T.lm_loss(p, batch, cfg, mesh)))(sp)
        flat_ref, _ = jax.tree_util.tree_flatten_with_path(g_ref)
        for (path, a), b in zip(flat_ref, jax.tree.leaves(g_pp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5, err_msg=str(path))

    def test_1f1b_loss_and_grads_match_unpipelined(self, setup):
        """The 1F1B schedule (explicit-vjp pipeline, O(pp) live
        activations) reproduces the unsharded model's loss AND full
        gradient pytree."""
        T, shard_pytree, cfg, params, batch, ref_loss = setup
        mesh = make_mesh({"pp": 2, "dp": 4})
        sp = shard_pytree(params, T.logical_axes(cfg), mesh)
        g_ref = jax.grad(lambda p: T.lm_loss(p, batch, cfg, None))(params)
        with jax.set_mesh(mesh):
            loss, g = jax.jit(lambda p, b: T.lm_value_and_grad(
                p, b, cfg, mesh))(sp, batch)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)
        flat_ref, _ = jax.tree_util.tree_flatten_with_path(g_ref)
        for (path, a), b in zip(flat_ref, jax.tree.leaves(g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5, err_msg=str(path))

    def test_1f1b_matches_gpipe_grads(self, setup):
        """Same mesh, same microbatching: the two schedules must agree on
        loss and gradients (they compute the same math in a different
        order)."""
        T, shard_pytree, cfg, params, batch, _ = setup
        mesh = make_mesh({"pp": 2, "dp": 4})
        sp = shard_pytree(params, T.logical_axes(cfg), mesh)
        with jax.set_mesh(mesh):
            l_gp, g_gp = jax.jit(jax.value_and_grad(
                lambda p: T.lm_loss(p, batch, cfg, mesh)))(sp)
            l_1f, g_1f = jax.jit(lambda p, b: T.lm_value_and_grad(
                p, b, cfg, mesh))(sp, batch)
        np.testing.assert_allclose(float(l_1f), float(l_gp), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g_gp), jax.tree.leaves(g_1f)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5)

    def test_1f1b_pp4_deep_schedule(self, setup):
        """pp=4 with M > S microbatches exercises warmup, steady 1F1B
        cadence, and cooldown on every stage."""
        T, shard_pytree, cfg, params, batch, _ = setup
        cfg4 = cfg.scaled(n_layers=4, pp_microbatches=8)
        params4 = T.init_params(jax.random.PRNGKey(3), cfg4)
        ref_loss, g_ref = jax.value_and_grad(
            lambda p: T.lm_loss(p, batch, cfg4, None))(params4)
        mesh = make_mesh({"pp": 4, "dp": 2})
        sp = shard_pytree(params4, T.logical_axes(cfg4), mesh)
        with jax.set_mesh(mesh):
            loss, g = jax.jit(lambda p, b: T.lm_value_and_grad(
                p, b, cfg4, mesh))(sp, batch)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        flat_ref, _ = jax.tree_util.tree_flatten_with_path(g_ref)
        for (path, a), b in zip(flat_ref, jax.tree.leaves(g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5, err_msg=str(path))

    def test_1f1b_tp_sharded_head(self, setup):
        """pp x tp: the loss head runs vocab-SHARDED inside the pipeline
        (distributed logsumexp + psum'd picked logit; activation
        cotangent psum'd over tp) and still reproduces the unsharded
        loss and gradients — the memory parity point with GPipe's
        propagated head sharding."""
        T, shard_pytree, cfg, params, batch, ref_loss = setup
        mesh = make_mesh({"pp": 2, "tp": 2, "dp": 2})
        sp = shard_pytree(params, T.logical_axes(cfg), mesh)
        g_ref = jax.grad(lambda p: T.lm_loss(p, batch, cfg, None))(params)
        with jax.set_mesh(mesh):
            loss, g = jax.jit(lambda p, b: T.lm_value_and_grad(
                p, b, cfg, mesh))(sp, batch)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)
        flat_ref, _ = jax.tree_util.tree_flatten_with_path(g_ref)
        for (path, a), b in zip(flat_ref, jax.tree.leaves(g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5, err_msg=str(path))

    def test_1f1b_degenerate_no_pp_axis(self, setup):
        """Without a pp axis the same entry point falls back to plain AD
        and still matches the reference."""
        T, shard_pytree, cfg, params, batch, ref_loss = setup
        mesh = make_mesh({"dp": 8})
        sp = shard_pytree(params, T.logical_axes(cfg), mesh)
        with jax.set_mesh(mesh):
            loss, g = jax.jit(lambda p, b: T.lm_value_and_grad(
                p, b, cfg, mesh))(sp, batch)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)
        g_ref = jax.grad(lambda p: T.lm_loss(p, batch, cfg, None))(params)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5)

    def test_1f1b_train_step_reduces_loss(self, setup):
        from tony_tpu.models.train import (default_optimizer, init_state,
                                           make_train_step)
        T, shard_pytree, cfg, params, batch, _ = setup
        mesh = make_mesh({"pp": 2, "dp": 4})
        sp = shard_pytree(jax.tree.map(jnp.copy, params),
                          T.logical_axes(cfg), mesh)
        opt = default_optimizer(lr=1e-3)
        state = init_state(sp, opt)
        step = make_train_step(
            None, opt, mesh,
            value_and_grad_fn=lambda p, b: T.lm_value_and_grad(
                p, b, cfg, mesh))
        state, m0 = step(state, batch)
        for _ in range(3):
            state, m = step(state, batch)
        assert float(m["loss"]) < float(m0["loss"])
        assert bool(jnp.isfinite(m["grad_norm"]))

    def test_1f1b_moe_replicated_experts_matches_gpipe(self, setup):
        """MoE x 1F1B with experts REPLICATED (no ep axis): the stage
        aux joins the loss inside each backward-tick vjp (one vjp covers
        the activation path and the aux path), so loss AND gradients
        match the GPipe schedule on the same mesh and microbatching."""
        T, shard_pytree, cfg, params, batch, _ = setup
        mcfg = cfg.scaled(num_experts=4)
        mparams = T.init_params(jax.random.PRNGKey(5), mcfg)
        mesh = make_mesh({"pp": 2, "dp": 4})
        sp = shard_pytree(mparams, T.logical_axes(mcfg), mesh)
        with jax.set_mesh(mesh):
            l_gp, g_gp = jax.jit(jax.value_and_grad(
                lambda p: T.lm_loss(p, batch, mcfg, mesh)))(sp, )
            l_1f, g_1f = jax.jit(lambda p, b: T.lm_value_and_grad(
                p, b, mcfg, mesh))(sp, batch)
        np.testing.assert_allclose(float(l_1f), float(l_gp), rtol=1e-6)
        flat_ref, _ = jax.tree_util.tree_flatten_with_path(g_gp)
        for (path, a), b in zip(flat_ref, jax.tree.leaves(g_1f)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5, err_msg=str(path))

    def test_1f1b_moe_with_tp_sharded_head_matches_gpipe(self, setup):
        """MoE x 1F1B on a pp x tp x dp mesh: the vocab-sharded head's
        psum reductions must not double-count the REPLICATED aux-path
        gradients (the aux seed pre-divides by the tp product) — loss
        and full gradients match GPipe on the same mesh (round-5 review
        caught an x-tp overcount here)."""
        T, shard_pytree, cfg, params, batch, _ = setup
        mcfg = cfg.scaled(num_experts=4)
        mparams = T.init_params(jax.random.PRNGKey(5), mcfg)
        mesh = make_mesh({"pp": 2, "tp": 2, "dp": 2})
        sp = shard_pytree(mparams, T.logical_axes(mcfg), mesh)
        with jax.set_mesh(mesh):
            l_gp, g_gp = jax.jit(jax.value_and_grad(
                lambda p: T.lm_loss(p, batch, mcfg, mesh)))(sp)
            l_1f, g_1f = jax.jit(lambda p, b: T.lm_value_and_grad(
                p, b, mcfg, mesh))(sp, batch)
        np.testing.assert_allclose(float(l_1f), float(l_gp), rtol=1e-6)
        flat_ref, _ = jax.tree_util.tree_flatten_with_path(g_gp)
        for (path, a), b in zip(flat_ref, jax.tree.leaves(g_1f)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5, err_msg=str(path))

    def test_1f1b_moe_ep_sharded_rejected(self, setup):
        """ep-SHARDED experts stay on GPipe: the explicit-collective
        dispatch's psum transposes are not exact under per-rank vjps."""
        T, shard_pytree, cfg, params, batch, _ = setup
        mcfg = cfg.scaled(num_experts=4, pp_schedule="1f1b")
        mesh = make_mesh({"pp": 2, "ep": 2, "dp": 2})
        with pytest.raises(NotImplementedError, match="REPLICATED"):
            T.lm_value_and_grad(T.init_params(jax.random.PRNGKey(9), mcfg),
                                batch, mcfg, mesh)

    @pytest.mark.slow
    def test_pp_train_step_reduces_loss(self, setup):
        from tony_tpu.models.train import (default_optimizer, init_state,
                                           make_train_step)
        T, shard_pytree, cfg, params, batch, _ = setup
        mesh = make_mesh({"pp": 2, "dp": 4})
        # copy: on the CPU backend device_put aliases the host buffers, and
        # the donating train step would delete the class-scoped params
        sp = shard_pytree(jax.tree.map(jnp.copy, params),
                          T.logical_axes(cfg), mesh)
        opt = default_optimizer(lr=1e-3)
        state = init_state(sp, opt)
        step = make_train_step(lambda p, b: T.lm_loss(p, b, cfg, mesh),
                               opt, mesh)
        state, m0 = step(state, batch)
        for _ in range(3):
            state, m = step(state, batch)
        assert float(m["loss"]) < float(m0["loss"])
        assert bool(jnp.isfinite(m["grad_norm"]))

    def test_pp_over_dcn(self, setup):
        # pp across the slice (DCN) axis — ppermute is point-to-point, the
        # one collective pattern that tolerates the slow cross-slice network
        from tony_tpu.parallel.mesh import make_hybrid_mesh
        T, shard_pytree, cfg, params, batch, ref_loss = setup
        hmesh = make_hybrid_mesh({"dp": -1}, {"pp": 2})
        assert dict(hmesh.shape) == {"pp": 2, "dp": 4}
        sp = shard_pytree(params, T.logical_axes(cfg), hmesh)
        loss = jax.jit(lambda p, b: T.lm_loss(p, b, cfg, hmesh))(sp, batch)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)

    def test_pp_indivisible_layers_raises(self, setup):
        T, shard_pytree, cfg, params, batch, _ = setup
        cfg3 = cfg.scaled(n_layers=3)
        params3 = T.init_params(jax.random.PRNGKey(4), cfg3)
        mesh = make_mesh({"pp": 2, "dp": 4})
        with pytest.raises(ValueError, match="pipeline stages"):
            T.lm_loss(params3, batch, cfg3, mesh)

    def test_pp_moe_loss_matches_unpipelined(self, setup):
        """MoE composes with pipeline parallelism: the stage body runs the
        explicit-collective dispatch (moe_ffn_manual) with experts sharded
        over an ep axis orthogonal to pp, and the aux loss rides the
        pipeline's side channel. Aux is a per-microbatch mean (nonlinear
        in the routing fractions), so the match is approximate at the
        microbatch level — tight here because routing is identical."""
        T, shard_pytree, cfg, params, batch, _ = setup
        mcfg = cfg.scaled(num_experts=4)
        mparams = T.init_params(jax.random.PRNGKey(5), mcfg)
        ref = float(T.lm_loss(mparams, batch, mcfg, None))
        mesh = make_mesh({"pp": 2, "ep": 2, "dp": 2})
        sp = shard_pytree(mparams, T.logical_axes(mcfg), mesh)
        loss = jax.jit(lambda p, b: T.lm_loss(p, b, mcfg, mesh))(sp, batch)
        np.testing.assert_allclose(float(loss), ref, rtol=2e-3)

    @pytest.mark.slow
    def test_pp_moe_trains(self, setup):
        from tony_tpu.models.train import (default_optimizer, init_state,
                                           make_train_step)
        T, shard_pytree, cfg, params, batch, _ = setup
        mcfg = cfg.scaled(num_experts=4)
        mesh = make_mesh({"pp": 2, "ep": 2, "dp": 2})
        sp = shard_pytree(T.init_params(jax.random.PRNGKey(6), mcfg),
                          T.logical_axes(mcfg), mesh)
        opt = default_optimizer(lr=1e-3)
        state = init_state(sp, opt)
        step = make_train_step(lambda p, b: T.lm_loss(p, b, mcfg, mesh),
                               opt, mesh)
        state, m0 = step(state, batch)
        for _ in range(3):
            state, m = step(state, batch)
        assert float(m["loss"]) < float(m0["loss"])
        assert bool(jnp.isfinite(m["grad_norm"]))

    def test_pp_moe_without_ep_axis_matches_unpipelined(self, setup):
        """MoE + pipeline on a mesh with NO ep axis: the stage body takes
        the GSPMD-constraint dispatch (moe_ffn) with expert weights
        replicated across pp ranks, relying on constrain's Manual-axes
        fallback inside shard_map — previously an untested configuration
        (round-4 advisor finding)."""
        T, shard_pytree, cfg, params, batch, _ = setup
        mcfg = cfg.scaled(num_experts=4)
        mparams = T.init_params(jax.random.PRNGKey(5), mcfg)
        ref = float(T.lm_loss(mparams, batch, mcfg, None))
        mesh = make_mesh({"pp": 2, "dp": 4})
        sp = shard_pytree(mparams, T.logical_axes(mcfg), mesh)
        loss = jax.jit(lambda p, b: T.lm_loss(p, b, mcfg, mesh))(sp, batch)
        np.testing.assert_allclose(float(loss), ref, rtol=2e-3)

    def test_pp_moe_indivisible_experts_raises(self, setup):
        T, shard_pytree, cfg, params, batch, _ = setup
        mcfg = cfg.scaled(num_experts=3)
        mparams = T.init_params(jax.random.PRNGKey(7), mcfg)
        mesh = make_mesh({"pp": 2, "ep": 2, "dp": 2})
        with pytest.raises(ValueError, match="num_experts"):
            T.lm_loss(mparams, batch, mcfg, mesh)

    def test_pp_with_gqa(self, setup):
        """Pipeline stages run the GQA-native attention path (kv heads <
        heads) — the two features must compose."""
        T, shard_pytree, cfg, params, batch, _ = setup
        gcfg = cfg.scaled(n_kv_heads=2)
        gparams = T.init_params(jax.random.PRNGKey(7), gcfg)
        ref = float(T.lm_loss(gparams, batch, gcfg, None))
        mesh = make_mesh({"pp": 2, "dp": 4})
        sp = shard_pytree(gparams, T.logical_axes(gcfg), mesh)
        loss = jax.jit(lambda p, b: T.lm_loss(p, b, gcfg, mesh))(sp, batch)
        np.testing.assert_allclose(float(loss), ref, rtol=1e-5)

    def test_pp_explicit_microbatches(self, setup):
        T, shard_pytree, cfg, params, batch, ref_loss = setup
        mesh = make_mesh({"pp": 2, "dp": 2, "tp": 2})
        cfg_m = cfg.scaled(pp_microbatches=8)
        sp = shard_pytree(params, T.logical_axes(cfg_m), mesh)
        loss = jax.jit(lambda p, b: T.lm_loss(p, b, cfg_m, mesh))(sp, batch)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)


# ---------------------------------------------------------------------------
# expert parallelism (MoE)
# ---------------------------------------------------------------------------

class TestMoE:
    @pytest.fixture(scope="class")
    def weights(self):
        r = np.random.RandomState(2)
        d, e, h = 8, 4, 32
        return (jnp.asarray(r.randn(d, e), jnp.float32),
                jnp.asarray(r.randn(e, d, h) * 0.1, jnp.float32),
                jnp.asarray(r.randn(e, h, d) * 0.1, jnp.float32))

    def test_matches_dense_reference(self, weights, rng):
        rw, w1, w2 = weights
        b, s, d = 2, 16, rw.shape[0]
        x = jnp.asarray(rng.randn(b, s, d), jnp.float32)
        # capacity_factor huge → nothing dropped → must equal per-token math
        out, metrics = moe_ffn(x, rw, w1, w2, top_k=2, capacity_factor=100.0)
        vals, idx = jax.lax.top_k(jax.nn.softmax(x @ rw, -1), 2)
        vals = vals / vals.sum(-1, keepdims=True)
        ref = np.zeros((b, s, d), np.float32)
        for bi in range(b):
            for si in range(s):
                for ki in range(2):
                    e = int(idx[bi, si, ki])
                    hid = jax.nn.gelu(x[bi, si] @ w1[e])
                    ref[bi, si] += float(vals[bi, si, ki]) * np.asarray(
                        hid @ w2[e])
        np.testing.assert_allclose(out, ref, atol=1e-5)
        assert float(metrics.dropped_fraction) == 0.0

    def test_capacity_drops_overflow(self, weights, rng):
        rw, w1, w2 = weights
        x = jnp.asarray(rng.randn(1, 32, rw.shape[0]), jnp.float32)
        # capacity_factor well below 1 forces drops on imbalanced routing
        _, metrics = moe_ffn(x, rw, w1, w2, top_k=1, capacity_factor=0.25)
        assert float(metrics.dropped_fraction) > 0.0

    def test_differentiable(self, weights, rng):
        rw, w1, w2 = weights
        x = jnp.asarray(rng.randn(2, 8, rw.shape[0]), jnp.float32)
        g = jax.grad(lambda x: moe_ffn(x, rw, w1, w2)[0].sum())(x)
        assert bool(jnp.isfinite(g).all())


class TestUlyssesAttention:
    @pytest.fixture(scope="class")
    def qkv(self):
        r = np.random.RandomState(1)
        shape = (2, 32, 4, 16)
        return tuple(jnp.asarray(r.randn(*shape), jnp.float32)
                     for _ in range(3))

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, qkv, causal):
        from tony_tpu.parallel import ulysses_attention
        q, k, v = qkv
        mesh = make_mesh({"dp": 2, "cp": 4})
        out = ulysses_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(out, _dense_attention(q, k, v, causal),
                                   atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_flash_engine_matches_dense(self, qkv, causal, monkeypatch):
        """The TPU arm: post-all-to-all [B, S_full, H/cp, D] chunks run the
        flash kernels (forced on, interpret mode)."""
        import importlib
        R = importlib.import_module("tony_tpu.parallel.ring_attention")
        monkeypatch.setattr(R, "_USE_FLASH_CHUNKS", True)
        from tony_tpu.parallel import ulysses_attention
        q, k, v = qkv
        mesh = make_mesh({"dp": 2, "cp": 4})
        out = ulysses_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(out, _dense_attention(q, k, v, causal),
                                   atol=2e-5)

    def test_flash_engine_rejects_untileable_seq(self, monkeypatch):
        """With the flash engine on, a full sequence that tiles no flash
        block must fail actionably, not silently go dense O(S²)."""
        import importlib
        R = importlib.import_module("tony_tpu.parallel.ring_attention")
        monkeypatch.setattr(R, "_USE_FLASH_CHUNKS", True)
        from tony_tpu.parallel import ulysses_attention
        r = np.random.RandomState(2)
        # S_full = 36: local 9 over cp=4, tiles no block (36 % 8 != 0)
        q, k, v = (jnp.asarray(r.randn(2, 36, 4, 16), jnp.float32)
                   for _ in range(3))
        mesh = make_mesh({"dp": 2, "cp": 4})
        with pytest.raises(ValueError, match="pad the per-device"):
            ulysses_attention(q, k, v, mesh, causal=True)

    def test_matches_ring(self, qkv):
        """Both context-parallel strategies compute the same attention."""
        from tony_tpu.parallel import ring_attention, ulysses_attention
        q, k, v = qkv
        mesh = make_mesh({"dp": 2, "cp": 4})
        np.testing.assert_allclose(
            ulysses_attention(q, k, v, mesh, causal=True),
            ring_attention(q, k, v, mesh, causal=True), atol=2e-5)

    @pytest.mark.slow
    def test_gradients_match_dense(self, qkv):
        from tony_tpu.parallel import ulysses_attention
        q, k, v = qkv
        mesh = make_mesh({"dp": 2, "cp": 4})
        g = jax.grad(lambda *a: ulysses_attention(*a, mesh).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: _dense_attention(*a, True).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(got, want, atol=2e-5)

    def test_no_cp_axis_fallback(self, qkv):
        from tony_tpu.parallel import ulysses_attention
        q, k, v = qkv
        mesh = make_mesh({"dp": 2, "tp": 4})
        np.testing.assert_allclose(
            ulysses_attention(q, k, v, mesh, causal=True),
            _dense_attention(q, k, v, True), atol=2e-5)

    @pytest.fixture(scope="class")
    def gqa_qkv(self):
        r = np.random.RandomState(7)
        q = jnp.asarray(r.randn(2, 32, 8, 16), jnp.float32)
        k = jnp.asarray(r.randn(2, 32, 4, 16), jnp.float32)   # 2 groups
        v = jnp.asarray(r.randn(2, 32, 4, 16), jnp.float32)
        return q, k, v

    def _gqa_dense(self, q, k, v, causal=True):
        rep = q.shape[2] // k.shape[2]
        return _dense_attention(q, jnp.repeat(k, rep, axis=2),
                                jnp.repeat(v, rep, axis=2), causal)

    @pytest.mark.parametrize("causal", [True, False])
    def test_gqa_unexpanded_matches_dense(self, gqa_qkv, causal,
                                          monkeypatch):
        """kv heads divide cp: K/V ride the all-to-alls UNEXPANDED — the
        local body must receive H_kv-wide K/V (the payload assertion) and
        still compute the grouped attention exactly."""
        import tony_tpu.parallel.ulysses as U
        q, k, v = gqa_qkv
        seen = []
        orig = U.ulysses_attention_local

        def spy(q, k, v, **kw):
            seen.append(k.shape)
            return orig(q, k, v, **kw)

        monkeypatch.setattr(U, "ulysses_attention_local", spy)
        mesh = make_mesh({"dp": 2, "cp": 4})
        out = U.ulysses_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(out, self._gqa_dense(q, k, v, causal),
                                   atol=2e-5)
        # local shard saw [B, S/cp, H_kv, D] — unexpanded (4 kv heads,
        # not 8): the inter-chip K/V payload is H/H_kv x smaller
        assert seen and seen[0][2] == 4, seen

    @pytest.mark.slow
    def test_gqa_unexpanded_grads_match_dense(self, gqa_qkv):
        from tony_tpu.parallel import ulysses_attention
        q, k, v = gqa_qkv
        mesh = make_mesh({"dp": 2, "cp": 4})
        g = jax.grad(lambda *a: ulysses_attention(*a, mesh).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: self._gqa_dense(*a).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(got, want, atol=2e-5)

    def test_gqa_indivisible_kv_expands(self, monkeypatch):
        """kv heads that cannot split over cp (2 % 4 != 0) expand to full
        width — correctness over the payload saving."""
        import tony_tpu.parallel.ulysses as U
        r = np.random.RandomState(8)
        q = jnp.asarray(r.randn(2, 32, 8, 16), jnp.float32)
        k = jnp.asarray(r.randn(2, 32, 2, 16), jnp.float32)
        v = jnp.asarray(r.randn(2, 32, 2, 16), jnp.float32)
        seen = []
        orig = U.ulysses_attention_local

        def spy(q, k, v, **kw):
            seen.append(k.shape)
            return orig(q, k, v, **kw)

        monkeypatch.setattr(U, "ulysses_attention_local", spy)
        mesh = make_mesh({"dp": 2, "cp": 4})
        out = U.ulysses_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(out, self._gqa_dense(q, k, v, True),
                                   atol=2e-5)
        assert seen and seen[0][2] == 8, seen    # expanded

    def test_gqa_unexpanded_matches_ring(self, gqa_qkv):
        """Both cp strategies agree on grouped-query attention with
        unexpanded K/V."""
        from tony_tpu.parallel import ring_attention, ulysses_attention
        q, k, v = gqa_qkv
        mesh = make_mesh({"dp": 2, "cp": 4})
        np.testing.assert_allclose(
            ulysses_attention(q, k, v, mesh, causal=True),
            ring_attention(q, k, v, mesh, causal=True), atol=2e-5)

    def test_indivisible_heads_rejected(self):
        from tony_tpu.parallel import ulysses_attention
        r = np.random.RandomState(2)
        q = k = v = jnp.asarray(r.randn(2, 24, 3, 8), jnp.float32)
        mesh = make_mesh({"cp": 8})      # 8 devices; 3 heads % 8 != 0
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, k, v, mesh, causal=True)


@pytest.mark.slow
def test_transformer_trains_with_ulysses_cp():
    """cp_strategy="ulysses" drives the model's attention through the
    all-to-all path end to end (loss finite, grads flow)."""
    from tony_tpu.models import transformer as T
    from tony_tpu.models.train import (default_optimizer, init_state,
                                       make_train_step)
    from tony_tpu.parallel import shard_pytree

    mesh = make_mesh({"dp": 2, "cp": 4})
    cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False,
                                   cp_strategy="ulysses")
    params = shard_pytree(T.init_params(jax.random.PRNGKey(0), cfg),
                          T.logical_axes(cfg), mesh)
    opt = default_optimizer(lr=1e-3)
    state = init_state(params, opt)
    step = make_train_step(lambda p, b: T.lm_loss(p, b, cfg, mesh), opt,
                           mesh)
    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 65), 0,
                             cfg.vocab_size)
    batch = {"inputs": tok[:, :64], "targets": tok[:, 1:]}
    state, metrics = step(state, batch)
    assert bool(jnp.isfinite(metrics["loss"]))


def test_ulysses_with_tp_head_sharding():
    """Heads shard over tp while sequence shards over cp — both strategies
    agree (the spec must not replicate heads across tp)."""
    from tony_tpu.parallel import ring_attention, ulysses_attention
    r = np.random.RandomState(3)
    q, k, v = (jnp.asarray(r.randn(2, 16, 4, 8), jnp.float32)
               for _ in range(3))
    mesh = make_mesh({"cp": 2, "tp": 2, "dp": 2})
    np.testing.assert_allclose(
        ulysses_attention(q, k, v, mesh, causal=True),
        ring_attention(q, k, v, mesh, causal=True), atol=2e-5)


def test_unknown_cp_strategy_rejected():
    from tony_tpu.models import transformer as T
    import pytest as _pytest
    cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32, cp_strategy="ulyses")
    tok = jnp.zeros((1, 16), jnp.int32)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    with _pytest.raises(ValueError, match="cp_strategy"):
        T.forward(params, tok, cfg)


class TestRematPolicy:
    """remat_policy: full recompute vs dots (save MXU outputs, recompute
    VPU) — same math, different memory/FLOP trade."""

    def test_policies_agree_and_bogus_rejected(self):
        from tony_tpu.models import transformer as T
        cfg_full = T.PRESETS["tiny"].scaled(dtype=jnp.float32)
        cfg_dots = cfg_full.scaled(remat_policy="dots")
        params = T.init_params(jax.random.PRNGKey(0), cfg_full)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                  cfg_full.vocab_size)
        batch = {"inputs": toks[:, :32], "targets": toks[:, 1:]}
        l_full = float(T.lm_loss(params, batch, cfg_full))
        l_dots = float(T.lm_loss(params, batch, cfg_dots))
        np.testing.assert_allclose(l_dots, l_full, rtol=1e-6)
        g_full = jax.grad(lambda p: T.lm_loss(p, batch, cfg_full))(params)
        g_dots = jax.grad(lambda p: T.lm_loss(p, batch, cfg_dots))(params)
        for a, b in zip(jax.tree.leaves(g_full), jax.tree.leaves(g_dots)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)
        # invalid policy fails at CONFIG time, even with remat off
        with pytest.raises(ValueError, match="remat_policy"):
            cfg_full.scaled(remat_policy="bogus")
        with pytest.raises(ValueError, match="remat_policy"):
            cfg_full.scaled(remat=False, remat_policy="bogus")
