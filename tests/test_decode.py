"""KV-cache decode tests: greedy equivalence with the full forward,
sampling shapes, cache semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import transformer as T
from tony_tpu.models.decode import (decode_step, generate, init_kv_cache,
                                    prefill)

CFG = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.PRNGKey(0), CFG)


def full_forward_greedy(params, prompt, steps, cfg=CFG):
    """Reference decode: re-run the full forward for every token."""
    tokens = prompt
    for _ in range(steps):
        logits, _ = T.forward(params, tokens, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return tokens


class TestDecode:
    def test_prefill_matches_forward_last_logits(self, params):
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0,
                                    CFG.vocab_size)
        logits_full, _ = T.forward(params, prompt, CFG)
        logits_pre, cache = prefill(params, prompt, CFG, max_len=16)
        np.testing.assert_allclose(np.asarray(logits_pre),
                                   np.asarray(logits_full[:, -1]),
                                   rtol=2e-4, atol=2e-4)
        assert int(cache["length"]) == 7

    def test_decode_step_matches_full_forward(self, params):
        """A cached step must produce the same logits as re-running the
        whole sequence through the training forward."""
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0,
                                    CFG.vocab_size)
        _, cache = prefill(params, prompt, CFG, max_len=12)
        nxt = jnp.array([3, 7])
        logits_cached, cache = decode_step(params, nxt, cache,
                                           cache["length"], CFG)
        extended = jnp.concatenate([prompt, nxt[:, None]], axis=1)
        logits_full, _ = T.forward(params, extended, CFG)
        np.testing.assert_allclose(np.asarray(logits_cached),
                                   np.asarray(logits_full[:, -1]),
                                   rtol=2e-4, atol=2e-4)
        assert int(cache["length"]) == 6

    @pytest.mark.slow
    def test_greedy_generate_equals_full_forward_loop(self, params):
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 4), 0,
                                    CFG.vocab_size)
        out = generate(params, prompt, CFG, max_new_tokens=6,
                       rng=jax.random.PRNGKey(0), temperature=0.0)
        expected = full_forward_greedy(params, prompt, 6)
        np.testing.assert_array_equal(np.asarray(out.tokens),
                                      np.asarray(expected))
        assert out.tokens.shape == (2, 10)
        assert out.logprobs.shape == (2, 6)
        assert bool((out.logprobs <= 0).all())

    def test_sampled_generate_shapes_and_validity(self, params):
        prompt = jax.random.randint(jax.random.PRNGKey(4), (3, 4), 0,
                                    CFG.vocab_size)
        out = generate(params, prompt, CFG, max_new_tokens=5,
                       rng=jax.random.PRNGKey(7), temperature=0.8, top_k=50)
        assert out.tokens.shape == (3, 9)
        gen = np.asarray(out.tokens[:, 4:])
        assert (gen >= 0).all() and (gen < CFG.vocab_size).all()
        # Different seeds give different samples (overwhelmingly likely).
        out2 = generate(params, prompt, CFG, max_new_tokens=5,
                        rng=jax.random.PRNGKey(8), temperature=0.8,
                        top_k=50)
        assert not np.array_equal(np.asarray(out.tokens),
                                  np.asarray(out2.tokens))

    def test_cache_shapes(self):
        cache = init_kv_cache(CFG, batch=2, max_len=32)
        assert cache["k"].shape == (CFG.n_layers, 2, 32, CFG.n_heads,
                                    CFG.head_dim)
        assert cache["k"].dtype == CFG.dtype

    @pytest.mark.slow
    def test_moe_greedy_generate_matches_full_forward(self):
        """MoE decode: cached generation equals the full-forward loop (high
        capacity factor so routing drops cannot differ between the S=1
        decode dispatch and the growing-S full forward)."""
        moe_cfg = CFG.scaled(num_experts=2, moe_capacity_factor=4.0)
        moe_params = T.init_params(jax.random.PRNGKey(5), moe_cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(6), (2, 4), 0,
                                    moe_cfg.vocab_size)
        out = generate(moe_params, prompt, moe_cfg, max_new_tokens=4,
                       rng=jax.random.PRNGKey(0), temperature=0.0)
        expected = full_forward_greedy(moe_params, prompt, 4, cfg=moe_cfg)
        np.testing.assert_array_equal(np.asarray(out.tokens),
                                      np.asarray(expected))

    def test_tp_sharded_decode_matches_unsharded(self, params):
        """Tensor-parallel serving: params sharded over tp (heads/mlp dims)
        decode token-identically via XLA sharding propagation."""
        from tony_tpu.parallel import make_mesh, shard_pytree
        prompt = jax.random.randint(jax.random.PRNGKey(9), (2, 8), 0,
                                    CFG.vocab_size)
        ref = generate(params, prompt, CFG, max_new_tokens=6,
                       rng=jax.random.PRNGKey(0), temperature=0.0)
        mesh = make_mesh({"tp": 4, "dp": 2})
        sharded = shard_pytree(params, T.logical_axes(CFG), mesh)
        with jax.set_mesh(mesh):
            out = generate(sharded, prompt, CFG, max_new_tokens=6,
                           rng=jax.random.PRNGKey(0), temperature=0.0)
        np.testing.assert_array_equal(np.asarray(ref.tokens),
                                      np.asarray(out.tokens))

    def test_extend_step_matches_sequential_decode(self, params):
        """A K-token chunked extend equals K sequential single steps."""
        prompt = jax.random.randint(jax.random.PRNGKey(10), (2, 5), 0,
                                    CFG.vocab_size)
        chunk = jax.random.randint(jax.random.PRNGKey(11), (2, 3), 0,
                                   CFG.vocab_size)
        from tony_tpu.models.decode import extend_step
        _, cache_a = prefill(params, prompt, CFG, max_len=12)
        logits_chunk, cache_a = extend_step(params, chunk, cache_a,
                                            cache_a["length"], CFG)
        _, cache_b = prefill(params, prompt, CFG, max_len=12)
        for i in range(3):
            logits_i, cache_b = decode_step(params, chunk[:, i], cache_b,
                                            cache_b["length"], CFG)
            np.testing.assert_allclose(np.asarray(logits_chunk[:, i]),
                                       np.asarray(logits_i),
                                       rtol=2e-4, atol=2e-4)
        assert int(cache_a["length"]) == int(cache_b["length"]) == 8

    @pytest.mark.slow
    @pytest.mark.parametrize("num_spec", [1, 3, 6])
    def test_speculative_equals_greedy(self, params, num_spec):
        """Speculative decoding with ANY draft model reproduces the target's
        greedy output exactly — here the draft IS the target (worst and best
        case acceptance paths both exercised across num_spec values)."""
        from tony_tpu.models.decode import speculative_generate
        prompt = jax.random.randint(jax.random.PRNGKey(12), (1, 5), 0,
                                    CFG.vocab_size)
        want = generate(params, prompt, CFG, max_new_tokens=9,
                        rng=jax.random.PRNGKey(0), temperature=0.0)
        got = speculative_generate(params, params, prompt, CFG, CFG,
                                   max_new_tokens=9,
                                   num_speculative=num_spec)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want.tokens))

    @pytest.mark.slow
    def test_speculative_with_distinct_draft(self, params):
        """A DIFFERENT (random) draft still yields the target's exact greedy
        output — only the speed, not the result, depends on the draft."""
        from tony_tpu.models.decode import speculative_generate
        draft_params = T.init_params(jax.random.PRNGKey(99), CFG)
        prompt = jax.random.randint(jax.random.PRNGKey(13), (1, 4), 0,
                                    CFG.vocab_size)
        want = generate(params, prompt, CFG, max_new_tokens=7,
                        rng=jax.random.PRNGKey(0), temperature=0.0)
        got = speculative_generate(params, draft_params, prompt, CFG, CFG,
                                   max_new_tokens=7, num_speculative=3)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want.tokens))

    @pytest.mark.slow
    @pytest.mark.parametrize("num_spec", [1, 3, 6])
    def test_speculative_device_equals_greedy(self, params, num_spec):
        """The DEVICE-side loop (one compiled while_loop program, no host
        round trips) is token-identical to the target's greedy generate —
        self-draft exercises the full-acceptance cache discipline."""
        from tony_tpu.models.decode import speculative_generate_device
        prompt = jax.random.randint(jax.random.PRNGKey(12), (1, 5), 0,
                                    CFG.vocab_size)
        want = generate(params, prompt, CFG, max_new_tokens=9,
                        rng=jax.random.PRNGKey(0), temperature=0.0)
        got = speculative_generate_device(params, params, prompt, CFG, CFG,
                                          max_new_tokens=9,
                                          num_speculative=num_spec)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want.tokens))

    @pytest.mark.slow
    def test_speculative_device_distinct_draft(self, params):
        """Rejections + corrections on device: a random draft still yields
        the target's exact greedy output (stale-entry overwrite path)."""
        from tony_tpu.models.decode import (speculative_generate,
                                            speculative_generate_device)
        draft_params = T.init_params(jax.random.PRNGKey(99), CFG)
        prompt = jax.random.randint(jax.random.PRNGKey(13), (1, 4), 0,
                                    CFG.vocab_size)
        want = generate(params, prompt, CFG, max_new_tokens=7,
                        rng=jax.random.PRNGKey(0), temperature=0.0)
        got = speculative_generate_device(params, draft_params, prompt,
                                          CFG, CFG, max_new_tokens=7,
                                          num_speculative=3)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want.tokens))
        host = speculative_generate(params, draft_params, prompt, CFG, CFG,
                                    max_new_tokens=7, num_speculative=3)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(host))


class TestGQA:
    """Grouped-query attention: n_kv_heads < n_heads."""
    GCFG = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False,
                                    n_kv_heads=2)    # 4 q heads, 2 kv heads

    def test_forward_equals_mha_with_repeated_kv_weights(self):
        """A GQA model must compute exactly what an MHA model with each
        K/V head repeated across its query group computes."""
        gparams = T.init_params(jax.random.PRNGKey(0), self.GCFG)
        mha_cfg = self.GCFG.scaled(n_kv_heads=None)
        rep = self.GCFG.n_heads // self.GCFG.kv_heads
        mparams = jax.tree.map(lambda x: x, gparams)
        mparams["blocks"] = dict(
            gparams["blocks"],
            wk=jnp.repeat(gparams["blocks"]["wk"], rep, axis=2),
            wv=jnp.repeat(gparams["blocks"]["wv"], rep, axis=2))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    self.GCFG.vocab_size)
        lg, _ = T.forward(gparams, tokens, self.GCFG)
        lm, _ = T.forward(mparams, tokens, mha_cfg)
        np.testing.assert_allclose(np.asarray(lg), np.asarray(lm),
                                   rtol=1e-5, atol=1e-5)

    def test_cache_stores_kv_heads_only(self):
        cache = init_kv_cache(self.GCFG, batch=2, max_len=32)
        assert cache["k"].shape == (self.GCFG.n_layers, 2, 32, 2,
                                    self.GCFG.head_dim)

    def test_greedy_generate_equals_full_forward(self):
        gparams = T.init_params(jax.random.PRNGKey(4), self.GCFG)
        prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 4), 0,
                                    self.GCFG.vocab_size)
        out = generate(gparams, prompt, self.GCFG, max_new_tokens=5,
                       rng=jax.random.PRNGKey(0), temperature=0.0)
        expected = full_forward_greedy(gparams, prompt, 5, cfg=self.GCFG)
        np.testing.assert_array_equal(np.asarray(out.tokens),
                                      np.asarray(expected))

    def test_indivisible_head_groups_rejected(self):
        # fails at CONSTRUCTION, not first trace
        with pytest.raises(ValueError, match="positive divisor"):
            T.PRESETS["tiny"].scaled(n_kv_heads=3)
        with pytest.raises(ValueError, match="positive divisor"):
            T.PRESETS["tiny"].scaled(n_kv_heads=0)
        with pytest.raises(ValueError, match="positive divisor"):
            T.PRESETS["tiny"].scaled(n_kv_heads=-2)

    def test_tp_sharded_gqa_decode(self):
        """GQA params shard on a tp mesh larger than n_kv_heads (K/V
        replicate — the Llama-style TP layout) and decode token-identically."""
        from tony_tpu.parallel import make_mesh, shard_pytree
        gparams = T.init_params(jax.random.PRNGKey(6), self.GCFG)
        prompt = jax.random.randint(jax.random.PRNGKey(7), (2, 8), 0,
                                    self.GCFG.vocab_size)
        ref = generate(gparams, prompt, self.GCFG, max_new_tokens=5,
                       rng=jax.random.PRNGKey(0), temperature=0.0)
        mesh = make_mesh({"tp": 4, "dp": 2})   # tp > n_kv_heads=2
        sharded = shard_pytree(gparams, T.logical_axes(self.GCFG), mesh)
        with jax.set_mesh(mesh):
            out = generate(sharded, prompt, self.GCFG, max_new_tokens=5,
                           rng=jax.random.PRNGKey(0), temperature=0.0)
        np.testing.assert_array_equal(np.asarray(ref.tokens),
                                      np.asarray(out.tokens))


@pytest.mark.slow
@pytest.mark.parametrize("batch,num_spec", [(4, 3), (3, 2)])
def test_speculative_device_batched_equals_greedy(batch, num_spec):
    """Batch > 1 speculation (min-commit: every round commits the
    smallest per-row acceptance uniformly, so the scalar cache frontier
    survives) stays token-identical to batched greedy — including rows
    whose acceptances diverge (distinct random draft forces rejections
    at different per-row lengths)."""
    from tony_tpu.models.decode import speculative_generate_device

    params = T.init_params(jax.random.PRNGKey(0), CFG)
    draft_params = T.init_params(jax.random.PRNGKey(99), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(21), (batch, 6), 0,
                                CFG.vocab_size)
    want = generate(params, prompt, CFG, max_new_tokens=9,
                    rng=jax.random.PRNGKey(0), temperature=0.0)
    for draft in (params, draft_params):    # self-draft + rejecting draft
        got = speculative_generate_device(params, draft, prompt, CFG, CFG,
                                          max_new_tokens=9,
                                          num_speculative=num_spec)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want.tokens))
