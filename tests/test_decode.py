"""KV-cache decode tests: greedy equivalence with the full forward,
sampling shapes, cache semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import transformer as T
from tony_tpu.models.decode import (decode_step, generate, init_kv_cache,
                                    prefill)

CFG = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.PRNGKey(0), CFG)


def full_forward_greedy(params, prompt, steps, cfg=CFG):
    """Reference decode: re-run the full forward for every token."""
    tokens = prompt
    for _ in range(steps):
        logits, _ = T.forward(params, tokens, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return tokens


class TestDecode:
    def test_prefill_matches_forward_last_logits(self, params):
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0,
                                    CFG.vocab_size)
        logits_full, _ = T.forward(params, prompt, CFG)
        logits_pre, cache = prefill(params, prompt, CFG, max_len=16)
        np.testing.assert_allclose(np.asarray(logits_pre),
                                   np.asarray(logits_full[:, -1]),
                                   rtol=2e-4, atol=2e-4)
        assert int(cache["length"]) == 7

    def test_decode_step_matches_full_forward(self, params):
        """A cached step must produce the same logits as re-running the
        whole sequence through the training forward."""
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0,
                                    CFG.vocab_size)
        _, cache = prefill(params, prompt, CFG, max_len=12)
        nxt = jnp.array([3, 7])
        logits_cached, cache = decode_step(params, nxt, cache,
                                           cache["length"], CFG)
        extended = jnp.concatenate([prompt, nxt[:, None]], axis=1)
        logits_full, _ = T.forward(params, extended, CFG)
        np.testing.assert_allclose(np.asarray(logits_cached),
                                   np.asarray(logits_full[:, -1]),
                                   rtol=2e-4, atol=2e-4)
        assert int(cache["length"]) == 6

    @pytest.mark.slow
    def test_greedy_generate_equals_full_forward_loop(self, params):
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 4), 0,
                                    CFG.vocab_size)
        out = generate(params, prompt, CFG, max_new_tokens=6,
                       rng=jax.random.PRNGKey(0), temperature=0.0)
        expected = full_forward_greedy(params, prompt, 6)
        np.testing.assert_array_equal(np.asarray(out.tokens),
                                      np.asarray(expected))
        assert out.tokens.shape == (2, 10)
        assert out.logprobs.shape == (2, 6)
        assert bool((out.logprobs <= 0).all())

    def test_sampled_generate_shapes_and_validity(self, params):
        prompt = jax.random.randint(jax.random.PRNGKey(4), (3, 4), 0,
                                    CFG.vocab_size)
        out = generate(params, prompt, CFG, max_new_tokens=5,
                       rng=jax.random.PRNGKey(7), temperature=0.8, top_k=50)
        assert out.tokens.shape == (3, 9)
        gen = np.asarray(out.tokens[:, 4:])
        assert (gen >= 0).all() and (gen < CFG.vocab_size).all()
        # Different seeds give different samples (overwhelmingly likely).
        out2 = generate(params, prompt, CFG, max_new_tokens=5,
                        rng=jax.random.PRNGKey(8), temperature=0.8,
                        top_k=50)
        assert not np.array_equal(np.asarray(out.tokens),
                                  np.asarray(out2.tokens))

    def test_nucleus_sampling_respects_the_nucleus(self, params):
        """Every top-p sample lies inside the nucleus a numpy reference
        computes from the same logits (smallest prefix of the
        temperature-scaled distribution reaching p, crossing token
        kept); a tiny p degenerates to greedy argmax."""
        from tony_tpu.models.decode import _sample

        logits = jax.random.normal(jax.random.PRNGKey(3),
                                   (4, CFG.vocab_size)) * 3.0
        temp, p = 0.7, 0.6
        scaled = np.asarray(logits, np.float64) / temp
        exp = np.exp(scaled - scaled.max(axis=-1, keepdims=True))
        probs = exp / exp.sum(axis=-1, keepdims=True)
        nuclei = []
        for row in probs:
            order = np.argsort(-row)
            cum = np.cumsum(row[order])
            keep = (cum - row[order]) < p
            nuclei.append(set(order[keep].tolist()))
        for seed in range(20):
            tok, logp = _sample(logits, jax.random.PRNGKey(seed),
                                temperature=temp, top_k=0, top_p=p)
            for r in range(4):
                assert int(tok[r]) in nuclei[r], (seed, r)
            assert np.all(np.isfinite(np.asarray(logp)))
        # p -> 0 keeps only the argmax (position 0 is always kept)
        tok, _ = _sample(logits, jax.random.PRNGKey(0), temperature=temp,
                         top_k=0, top_p=1e-9)
        np.testing.assert_array_equal(
            np.asarray(tok), np.asarray(jnp.argmax(logits, axis=-1)))

    def test_nucleus_generate_end_to_end(self, params):
        """top_p threads through generate(): valid tokens, and a tiny
        nucleus reproduces greedy decoding despite temperature > 0."""
        prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 4), 0,
                                    CFG.vocab_size)
        out = generate(params, prompt, CFG, max_new_tokens=5,
                       rng=jax.random.PRNGKey(7), temperature=0.9,
                       top_p=0.8)
        gen = np.asarray(out.tokens[:, 4:])
        assert (gen >= 0).all() and (gen < CFG.vocab_size).all()
        greedy = generate(params, prompt, CFG, max_new_tokens=5,
                          rng=jax.random.PRNGKey(7), temperature=0.0)
        tiny = generate(params, prompt, CFG, max_new_tokens=5,
                        rng=jax.random.PRNGKey(7), temperature=0.9,
                        top_p=1e-9)
        np.testing.assert_array_equal(np.asarray(tiny.tokens),
                                      np.asarray(greedy.tokens))

    def test_cache_shapes(self):
        cache = init_kv_cache(CFG, batch=2, max_len=32)
        assert cache["k"].shape == (CFG.n_layers, 2, 32, CFG.n_heads,
                                    CFG.head_dim)
        assert cache["k"].dtype == CFG.dtype

    def test_flash_safe_len_boundaries(self):
        """The TPU flash kernels' alignment rule prefill pads to: free up
        to 256, 256-multiples to 1024, 1024-multiples beyond."""
        from tony_tpu.models.decode import _flash_safe_len

        assert [_flash_safe_len(s) for s in (1, 100, 256)] == [1, 100, 256]
        assert [_flash_safe_len(s) for s in (257, 300, 512, 1000)] == \
            [512, 512, 512, 1024]
        assert [_flash_safe_len(s) for s in (1024, 1025, 1056, 2048,
                                             2049)] == \
            [1024, 2048, 2048, 2048, 3072]

    def test_prefill_padding_preserves_outputs(self, params, monkeypatch):
        """The prompt-padding path (TPU flash alignment; forced here on
        CPU through the _pad_prompts seam): padded prefill produces the
        same logits, cache K/V, and greedy continuations as unpadded —
        causal masking keeps real positions independent of the padding
        and only real rows reach the cache."""
        import tony_tpu.models.decode as D

        prompt = jax.random.randint(jax.random.PRNGKey(12), (2, 300), 0,
                                    CFG.vocab_size)
        lg_ref, cache_ref = prefill(params, prompt, CFG, max_len=310)
        monkeypatch.setattr(D, "_pad_prompts", lambda: True)
        assert D._flash_safe_len(300) == 512        # genuinely pads
        lg_pad, cache_pad = prefill(params, prompt, CFG, max_len=310)
        np.testing.assert_allclose(np.asarray(lg_pad),
                                   np.asarray(lg_ref), rtol=2e-4,
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(cache_pad["k"]),
                                   np.asarray(cache_ref["k"]),
                                   rtol=2e-4, atol=2e-4)
        assert int(cache_pad["length"]) == 300
        # greedy continuation off the padded-prefill cache matches the
        # unpadded one (eager decode_step calls — no jit cache aliasing
        # between the patched and unpatched traces)
        ca, cb = cache_pad, cache_ref
        la, lb = lg_pad, lg_ref
        for _ in range(3):
            ta = jnp.argmax(la, axis=-1)
            tb = jnp.argmax(lb, axis=-1)
            np.testing.assert_array_equal(np.asarray(ta), np.asarray(tb))
            la, ca = decode_step(params, ta, ca, ca["length"], CFG)
            lb, cb = decode_step(params, tb, cb, cb["length"], CFG)

    @pytest.mark.slow
    def test_moe_greedy_generate_matches_full_forward(self):
        """MoE decode: cached generation equals the full-forward loop (high
        capacity factor so routing drops cannot differ between the S=1
        decode dispatch and the growing-S full forward)."""
        moe_cfg = CFG.scaled(num_experts=2, moe_capacity_factor=4.0)
        moe_params = T.init_params(jax.random.PRNGKey(5), moe_cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(6), (2, 4), 0,
                                    moe_cfg.vocab_size)
        out = generate(moe_params, prompt, moe_cfg, max_new_tokens=4,
                       rng=jax.random.PRNGKey(0), temperature=0.0)
        expected = full_forward_greedy(moe_params, prompt, 4, cfg=moe_cfg)
        np.testing.assert_array_equal(np.asarray(out.tokens),
                                      np.asarray(expected))

    def test_tp_sharded_decode_matches_unsharded(self, params):
        """Tensor-parallel serving: params sharded over tp (heads/mlp dims)
        decode token-identically via XLA sharding propagation."""
        from tony_tpu.parallel import make_mesh, shard_pytree
        prompt = jax.random.randint(jax.random.PRNGKey(9), (2, 8), 0,
                                    CFG.vocab_size)
        ref = generate(params, prompt, CFG, max_new_tokens=6,
                       rng=jax.random.PRNGKey(0), temperature=0.0)
        mesh = make_mesh({"tp": 4, "dp": 2})
        sharded = shard_pytree(params, T.logical_axes(CFG), mesh)
        with jax.set_mesh(mesh):
            out = generate(sharded, prompt, CFG, max_new_tokens=6,
                           rng=jax.random.PRNGKey(0), temperature=0.0)
        np.testing.assert_array_equal(np.asarray(ref.tokens),
                                      np.asarray(out.tokens))

    def test_extend_step_matches_sequential_decode(self, params):
        """A K-token chunked extend equals K sequential single steps."""
        prompt = jax.random.randint(jax.random.PRNGKey(10), (2, 5), 0,
                                    CFG.vocab_size)
        chunk = jax.random.randint(jax.random.PRNGKey(11), (2, 3), 0,
                                   CFG.vocab_size)
        from tony_tpu.models.decode import extend_step
        _, cache_a = prefill(params, prompt, CFG, max_len=12)
        logits_chunk, cache_a = extend_step(params, chunk, cache_a,
                                            cache_a["length"], CFG)
        _, cache_b = prefill(params, prompt, CFG, max_len=12)
        for i in range(3):
            logits_i, cache_b = decode_step(params, chunk[:, i], cache_b,
                                            cache_b["length"], CFG)
            np.testing.assert_allclose(np.asarray(logits_chunk[:, i]),
                                       np.asarray(logits_i),
                                       rtol=2e-4, atol=2e-4)
        assert int(cache_a["length"]) == int(cache_b["length"]) == 8

    @pytest.mark.slow
    @pytest.mark.parametrize("num_spec", [1, 3, 6])
    def test_speculative_equals_greedy(self, params, num_spec):
        """Speculative decoding with ANY draft model reproduces the target's
        greedy output exactly — here the draft IS the target (worst and best
        case acceptance paths both exercised across num_spec values)."""
        from tony_tpu.models.decode import speculative_generate
        prompt = jax.random.randint(jax.random.PRNGKey(12), (1, 5), 0,
                                    CFG.vocab_size)
        want = generate(params, prompt, CFG, max_new_tokens=9,
                        rng=jax.random.PRNGKey(0), temperature=0.0)
        got = speculative_generate(params, params, prompt, CFG, CFG,
                                   max_new_tokens=9,
                                   num_speculative=num_spec)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want.tokens))

    @pytest.mark.slow
    def test_speculative_with_distinct_draft(self, params):
        """A DIFFERENT (random) draft still yields the target's exact greedy
        output — only the speed, not the result, depends on the draft."""
        from tony_tpu.models.decode import speculative_generate
        draft_params = T.init_params(jax.random.PRNGKey(99), CFG)
        prompt = jax.random.randint(jax.random.PRNGKey(13), (1, 4), 0,
                                    CFG.vocab_size)
        want = generate(params, prompt, CFG, max_new_tokens=7,
                        rng=jax.random.PRNGKey(0), temperature=0.0)
        got = speculative_generate(params, draft_params, prompt, CFG, CFG,
                                   max_new_tokens=7, num_speculative=3)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want.tokens))

    @pytest.mark.slow
    @pytest.mark.parametrize("num_spec", [1, 3, 6])
    def test_speculative_device_equals_greedy(self, params, num_spec):
        """The DEVICE-side loop (one compiled while_loop program, no host
        round trips) is token-identical to the target's greedy generate —
        self-draft exercises the full-acceptance cache discipline."""
        from tony_tpu.models.decode import speculative_generate_device
        prompt = jax.random.randint(jax.random.PRNGKey(12), (1, 5), 0,
                                    CFG.vocab_size)
        want = generate(params, prompt, CFG, max_new_tokens=9,
                        rng=jax.random.PRNGKey(0), temperature=0.0)
        got = speculative_generate_device(params, params, prompt, CFG, CFG,
                                          max_new_tokens=9,
                                          num_speculative=num_spec)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want.tokens))

    @pytest.mark.slow
    def test_speculative_device_distinct_draft(self, params):
        """Rejections + corrections on device: a random draft still yields
        the target's exact greedy output (stale-entry overwrite path)."""
        from tony_tpu.models.decode import (speculative_generate,
                                            speculative_generate_device)
        draft_params = T.init_params(jax.random.PRNGKey(99), CFG)
        prompt = jax.random.randint(jax.random.PRNGKey(13), (1, 4), 0,
                                    CFG.vocab_size)
        want = generate(params, prompt, CFG, max_new_tokens=7,
                        rng=jax.random.PRNGKey(0), temperature=0.0)
        got = speculative_generate_device(params, draft_params, prompt,
                                          CFG, CFG, max_new_tokens=7,
                                          num_speculative=3)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want.tokens))
        host = speculative_generate(params, draft_params, prompt, CFG, CFG,
                                    max_new_tokens=7, num_speculative=3)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(host))


class TestBlockwiseCachedAttention:
    """Length-aware decode attention: caches >= _BLOCKWISE_MIN_LEN take a
    block-wise online-softmax path whose executed cost follows the live
    length, not the padded max_len. It must agree with the dense einsum."""

    def _rand(self, key, b, max_len, kv, h, d, n_q):
        ks = jax.random.split(jax.random.PRNGKey(key), 3)
        q = jax.random.normal(ks[0], (b, n_q, h, d), jnp.float32)
        k_cache = jax.random.normal(ks[1], (b, max_len, kv, d), jnp.float32)
        v_cache = jax.random.normal(ks[2], (b, max_len, kv, d), jnp.float32)
        return q, k_cache, v_cache

    @pytest.mark.parametrize("q_start,n_q", [(0, 1), (5, 1), (255, 1),
                                             (256, 1), (300, 4), (635, 4)])
    def test_matches_dense(self, q_start, n_q):
        from tony_tpu.models import decode as D
        # max_len=640 is NOT a block multiple: the last slice start clamps
        # and the >= i*block mask must discard the re-read rows
        q, k_cache, v_cache = self._rand(q_start, 2, 640, 4, 4, 16, n_q)
        if q_start + n_q > 640:
            pytest.skip("positions exceed cache")
        got = D._cached_attention_blockwise(
            q, {"k": k_cache[None], "v": v_cache[None]}, 0,
            jnp.asarray(q_start))
        b, nq, h, d = q.shape
        kv = k_cache.shape[2]
        group = h // kv
        q_pos = q_start + jnp.arange(nq)
        k_pos = jnp.arange(640)
        mask = k_pos[None, :] <= q_pos[:, None]
        qg = q.reshape(b, nq, kv, group, d)
        scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k_cache) * d ** -0.5
        scores = jnp.where(mask[None, None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        want = jnp.einsum("bkgqs,bskd->bqkgd", probs,
                          v_cache).reshape(b, nq, h, d)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gqa_matches_dense(self):
        from tony_tpu.models import decode as D
        q, k_cache, v_cache = self._rand(7, 2, 768, 2, 8, 16, 3)  # group=4
        got = D._cached_attention_blockwise(
            q, {"k": k_cache[None], "v": v_cache[None]}, 0,
            jnp.asarray(500))
        b, nq, h, d = q.shape
        kv, group = 2, 4
        q_pos = 500 + jnp.arange(nq)
        mask = jnp.arange(768)[None, :] <= q_pos[:, None]
        qg = q.reshape(b, nq, kv, group, d)
        scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k_cache) * d ** -0.5
        scores = jnp.where(mask[None, None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        want = jnp.einsum("bkgqs,bskd->bqkgd", probs,
                          v_cache).reshape(b, nq, h, d)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_decode_step_long_cache_matches_full_forward(self, params):
        """End to end through the dispatch: a max_len >= 512 cache (block-
        wise path) still reproduces the training forward's logits."""
        prompt = jax.random.randint(jax.random.PRNGKey(30), (2, 5), 0,
                                    CFG.vocab_size)
        _, cache = prefill(params, prompt, CFG, max_len=600)
        nxt = jnp.array([3, 7])
        logits_cached, cache = decode_step(params, nxt, cache,
                                           cache["length"], CFG)
        extended = jnp.concatenate([prompt, nxt[:, None]], axis=1)
        logits_full, _ = T.forward(params, extended, CFG)
        np.testing.assert_allclose(np.asarray(logits_cached),
                                   np.asarray(logits_full[:, -1]),
                                   rtol=3e-4, atol=3e-4)

    @pytest.mark.slow
    def test_tp_sharded_long_cache_decode(self, params):
        """The fori_loop + dynamic_slice path must stay correct under tp
        sharding propagation (cache sharded on the KV-head axis)."""
        from tony_tpu.parallel import make_mesh, shard_pytree
        prompt = jax.random.randint(jax.random.PRNGKey(31), (2, 6), 0,
                                    CFG.vocab_size)
        _, cache_ref = prefill(params, prompt, CFG, max_len=600)
        nxt = jnp.array([1, 2])
        ref, _ = decode_step(params, nxt, cache_ref, cache_ref["length"],
                             CFG)
        mesh = make_mesh({"tp": 4, "dp": 2})
        sharded = shard_pytree(params, T.logical_axes(CFG), mesh)
        with jax.set_mesh(mesh):
            _, cache = prefill(sharded, prompt, CFG, max_len=600)
            got, _ = decode_step(sharded, nxt, cache, cache["length"], CFG)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=3e-4, atol=3e-4)


class TestGQA:
    """Grouped-query attention: n_kv_heads < n_heads."""
    GCFG = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False,
                                    n_kv_heads=2)    # 4 q heads, 2 kv heads

    def test_forward_equals_mha_with_repeated_kv_weights(self):
        """A GQA model must compute exactly what an MHA model with each
        K/V head repeated across its query group computes."""
        gparams = T.init_params(jax.random.PRNGKey(0), self.GCFG)
        mha_cfg = self.GCFG.scaled(n_kv_heads=None)
        rep = self.GCFG.n_heads // self.GCFG.kv_heads
        mparams = jax.tree.map(lambda x: x, gparams)
        mparams["blocks"] = dict(
            gparams["blocks"],
            wk=jnp.repeat(gparams["blocks"]["wk"], rep, axis=2),
            wv=jnp.repeat(gparams["blocks"]["wv"], rep, axis=2))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    self.GCFG.vocab_size)
        lg, _ = T.forward(gparams, tokens, self.GCFG)
        lm, _ = T.forward(mparams, tokens, mha_cfg)
        np.testing.assert_allclose(np.asarray(lg), np.asarray(lm),
                                   rtol=1e-5, atol=1e-5)

    def test_cache_stores_kv_heads_only(self):
        cache = init_kv_cache(self.GCFG, batch=2, max_len=32)
        assert cache["k"].shape == (self.GCFG.n_layers, 2, 32, 2,
                                    self.GCFG.head_dim)

    def test_greedy_generate_equals_full_forward(self):
        gparams = T.init_params(jax.random.PRNGKey(4), self.GCFG)
        prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 4), 0,
                                    self.GCFG.vocab_size)
        out = generate(gparams, prompt, self.GCFG, max_new_tokens=5,
                       rng=jax.random.PRNGKey(0), temperature=0.0)
        expected = full_forward_greedy(gparams, prompt, 5, cfg=self.GCFG)
        np.testing.assert_array_equal(np.asarray(out.tokens),
                                      np.asarray(expected))

    def test_indivisible_head_groups_rejected(self):
        # fails at CONSTRUCTION, not first trace
        with pytest.raises(ValueError, match="positive divisor"):
            T.PRESETS["tiny"].scaled(n_kv_heads=3)
        with pytest.raises(ValueError, match="positive divisor"):
            T.PRESETS["tiny"].scaled(n_kv_heads=0)
        with pytest.raises(ValueError, match="positive divisor"):
            T.PRESETS["tiny"].scaled(n_kv_heads=-2)

    def test_tp_sharded_gqa_decode(self):
        """GQA params shard on a tp mesh larger than n_kv_heads (K/V
        replicate — the Llama-style TP layout) and decode token-identically."""
        from tony_tpu.parallel import make_mesh, shard_pytree
        gparams = T.init_params(jax.random.PRNGKey(6), self.GCFG)
        prompt = jax.random.randint(jax.random.PRNGKey(7), (2, 8), 0,
                                    self.GCFG.vocab_size)
        ref = generate(gparams, prompt, self.GCFG, max_new_tokens=5,
                       rng=jax.random.PRNGKey(0), temperature=0.0)
        mesh = make_mesh({"tp": 4, "dp": 2})   # tp > n_kv_heads=2
        sharded = shard_pytree(gparams, T.logical_axes(self.GCFG), mesh)
        with jax.set_mesh(mesh):
            out = generate(sharded, prompt, self.GCFG, max_new_tokens=5,
                           rng=jax.random.PRNGKey(0), temperature=0.0)
        np.testing.assert_array_equal(np.asarray(ref.tokens),
                                      np.asarray(out.tokens))


@pytest.mark.slow
@pytest.mark.parametrize("batch,num_spec", [(4, 3), (3, 2)])
def test_speculative_device_batched_equals_greedy(batch, num_spec):
    """Batch > 1 speculation (per-row cache frontiers: every row commits
    its OWN acceptance each round; RoPE/mask/K-V writes take [B] position
    vectors) stays token-identical to batched greedy — including rows
    whose acceptances diverge (distinct random draft forces rejections
    at different per-row lengths)."""
    from tony_tpu.models.decode import speculative_generate_device

    params = T.init_params(jax.random.PRNGKey(0), CFG)
    draft_params = T.init_params(jax.random.PRNGKey(99), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(21), (batch, 6), 0,
                                CFG.vocab_size)
    want = generate(params, prompt, CFG, max_new_tokens=9,
                    rng=jax.random.PRNGKey(0), temperature=0.0)
    for draft in (params, draft_params):    # self-draft + rejecting draft
        got = speculative_generate_device(params, draft, prompt, CFG, CFG,
                                          max_new_tokens=9,
                                          num_speculative=num_spec)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want.tokens))


@pytest.mark.slow
def test_speculative_commit_policies_and_rounds():
    """Both commit schedules are token-identical to greedy; per-row
    commits never need MORE rounds than min-commit (self-draft makes the
    round counts deterministic; a rejecting draft makes them diverge)."""
    from tony_tpu.models.decode import speculative_generate_device

    params = T.init_params(jax.random.PRNGKey(0), CFG)
    draft_params = T.init_params(jax.random.PRNGKey(99), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(22), (3, 5), 0,
                                CFG.vocab_size)
    want = generate(params, prompt, CFG, max_new_tokens=8,
                    rng=jax.random.PRNGKey(0), temperature=0.0)
    for draft in (params, draft_params):
        toks_pr, rounds_pr = speculative_generate_device(
            params, draft, prompt, CFG, CFG, max_new_tokens=8,
            num_speculative=3, commit="per_row", return_rounds=True)
        toks_mc, rounds_mc = speculative_generate_device(
            params, draft, prompt, CFG, CFG, max_new_tokens=8,
            num_speculative=3, commit="min", return_rounds=True)
        np.testing.assert_array_equal(np.asarray(toks_pr),
                                      np.asarray(want.tokens))
        np.testing.assert_array_equal(np.asarray(toks_mc),
                                      np.asarray(want.tokens))
        assert int(rounds_pr) <= int(rounds_mc)
    with pytest.raises(ValueError, match="commit policy"):
        speculative_generate_device(params, params, prompt, CFG, CFG,
                                    max_new_tokens=8, num_speculative=3,
                                    commit="bogus")


@pytest.mark.slow
@pytest.mark.parametrize("window", [0, 5, 16])
def test_speculative_window_commit_equals_greedy(window):
    """The bounded-window commit (scatter-free per-row cache writes) is
    token-identical to greedy across window sizes — including window=5,
    the minimum legal slack for k=3, where any acceptance divergence
    immediately clamps. 0 = the 4*(k+1) default."""
    from tony_tpu.models.decode import speculative_generate_device

    params = T.init_params(jax.random.PRNGKey(0), CFG)
    draft_params = T.init_params(jax.random.PRNGKey(99), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(21), (4, 6), 0,
                                CFG.vocab_size)
    want = generate(params, prompt, CFG, max_new_tokens=9,
                    rng=jax.random.PRNGKey(0), temperature=0.0)
    for draft in (params, draft_params):    # self-draft + rejecting draft
        got = speculative_generate_device(params, draft, prompt, CFG, CFG,
                                          max_new_tokens=9,
                                          num_speculative=3,
                                          commit="window", window=window)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want.tokens))


@pytest.mark.slow
def test_speculative_window_commit_clamp_forced():
    """Window commit stays exact when the clamp provably BITES: one row's
    draft is perfect (its tokens' embeddings untouched) and the other's
    is sabotaged (draft embeddings corrupted exactly for the tokens its
    greedy trajectory visits — the rows' trajectories are disjoint for
    this seed, asserted), so per-row speculation diverges ~k positions
    per round while window=k+2 allows divergence 1. Also pins the
    heterogeneity itself via batch-1 round counts, so a model/seed drift
    that equalised acceptance would fail loudly instead of silently
    weakening the test."""
    from tony_tpu.models.decode import speculative_generate_device

    params = T.init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(0), (2, 6), 0,
                                CFG.vocab_size)
    n = 24
    want = generate(params, prompt, CFG, max_new_tokens=n,
                    rng=jax.random.PRNGKey(0), temperature=0.0)
    traj = np.asarray(want.tokens)
    set_a = set(traj[0].tolist())
    set_b = set(traj[1][prompt.shape[1]:].tolist())
    assert not (set_a & set_b), "seed drift: trajectories overlap"
    corrupt = jnp.asarray(sorted(set_b - set_a), jnp.int32)
    semi = dict(params, embed=params["embed"].at[corrupt].add(1.0))

    rounds_alone = []
    for r in range(2):
        _, rounds = speculative_generate_device(
            params, semi, prompt[r:r + 1], CFG, CFG, max_new_tokens=n,
            num_speculative=4, commit="per_row", return_rounds=True)
        rounds_alone.append(int(rounds))
    # row 0 speculates near-perfectly, row 1 barely — the batched run's
    # per-row frontiers MUST hit the window bound
    assert rounds_alone[0] < rounds_alone[1] // 2, rounds_alone

    for window in (6, 0):          # slack 1 (max clamping) and default
        got = speculative_generate_device(
            params, semi, prompt, CFG, CFG, max_new_tokens=n,
            num_speculative=4, commit="window", window=window)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want.tokens))

    with pytest.raises(ValueError, match="window"):
        speculative_generate_device(params, semi, prompt, CFG, CFG,
                                    max_new_tokens=n, num_speculative=4,
                                    commit="window", window=3)


class TestBeamSearch:
    BCFG = T.TransformerConfig(vocab_size=17, d_model=24, n_layers=2,
                               n_heads=2, d_ff=48, max_seq=256,
                               dtype=jnp.float32,
                               logits_dtype=jnp.float32, remat=False)

    @pytest.fixture(scope="class")
    def bparams(self):
        return T.init_params(jax.random.PRNGKey(2), self.BCFG)

    def _seq_logprob(self, params, row_tokens, prompt_len, n_tok):
        """Exact logprob of generated tokens via the full forward."""
        toks = jnp.asarray(row_tokens, jnp.int32)[None]
        logits, _ = T.forward(params, toks, self.BCFG)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        total = 0.0
        for i in range(n_tok):
            pos = prompt_len - 1 + i
            total += float(logp[0, pos, int(row_tokens[prompt_len + i])])
        return total

    def test_width_one_equals_greedy(self, bparams):
        from tony_tpu.models.decode import beam_search

        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0,
                                    self.BCFG.vocab_size)
        want = generate(bparams, prompt, self.BCFG, max_new_tokens=7,
                        rng=jax.random.PRNGKey(0), temperature=0.0)
        out = beam_search(bparams, prompt, self.BCFG, max_new_tokens=7,
                          beam_width=1)
        np.testing.assert_array_equal(np.asarray(out.tokens[:, 0]),
                                      np.asarray(want.tokens))

    def test_scores_are_exact_and_sorted(self, bparams):
        """Every returned beam's score equals the full-forward logprob of
        its tokens (the KV-cache path and per-step bookkeeping introduce
        no drift), and beams come back sorted, distinct."""
        from tony_tpu.models.decode import beam_search

        prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 4), 0,
                                    self.BCFG.vocab_size)
        out = beam_search(bparams, prompt, self.BCFG, max_new_tokens=6,
                          beam_width=4)
        toks = np.asarray(out.tokens)
        scores = np.asarray(out.scores)
        for r in range(2):
            assert (np.diff(scores[r]) <= 1e-6).all()
            seqs = {tuple(toks[r, wdx]) for wdx in range(4)}
            assert len(seqs) == 4
            for wdx in range(4):
                want = self._seq_logprob(bparams, toks[r, wdx], 4, 6)
                assert abs(want - scores[r, wdx]) < 1e-3, (r, wdx)

    def test_matches_cache_free_reference_beam(self, bparams):
        """Token-identical to a from-scratch beam search that re-runs the
        FULL forward on every prefix each step (no KV cache, no
        reordering) — the cache gather by parent index is the part this
        pins."""
        from tony_tpu.models.decode import beam_search

        cfg = self.BCFG
        prompt = jax.random.randint(jax.random.PRNGKey(5), (1, 4), 0,
                                    cfg.vocab_size)
        w, n = 3, 5

        # reference: python beam over full forwards
        beams = [(0.0, [int(t) for t in np.asarray(prompt[0])])]
        for _ in range(n):
            cand = []
            for score, seq in beams:
                logits, _ = T.forward(
                    bparams, jnp.asarray(seq, jnp.int32)[None], cfg)
                logp = np.asarray(jax.nn.log_softmax(
                    logits[0, -1].astype(jnp.float32)))
                for tok in range(cfg.vocab_size):
                    cand.append((score + float(logp[tok]), seq + [tok]))
            cand.sort(key=lambda x: -x[0])
            beams = cand[:w]

        out = beam_search(bparams, prompt, cfg, max_new_tokens=n,
                          beam_width=w)
        got = [tuple(np.asarray(out.tokens)[0, i]) for i in range(w)]
        want = [tuple(seq) for _, seq in beams]
        assert got == want, (got, want)
        for i in range(w):
            assert abs(float(out.scores[0, i]) - beams[i][0]) < 1e-3

    def test_eos_freezes_beams(self, bparams):
        """Beams that emit eos stop: score frozen, tokens padded with
        eos, length = tokens incl. eos; still exactly the logprob of the
        truncated sequence."""
        from tony_tpu.models.decode import beam_search

        prompt = jax.random.randint(jax.random.PRNGKey(6), (1, 4), 0,
                                    self.BCFG.vocab_size)
        # run once without eos to discover a token on the best path
        free = beam_search(bparams, prompt, self.BCFG, max_new_tokens=6,
                           beam_width=3)
        eos = int(np.asarray(free.tokens)[0, 0, 4 + 2])  # 3rd generated
        out = beam_search(bparams, prompt, self.BCFG, max_new_tokens=6,
                          beam_width=3, eos_id=eos)
        toks = np.asarray(out.tokens)
        for wdx in range(3):
            gen = toks[0, wdx, 4:]
            ln = int(out.lengths[0, wdx])
            if eos in gen.tolist():
                first = gen.tolist().index(eos)
                assert ln == first + 1
                assert (gen[first:] == eos).all()       # eos padding
            else:
                assert ln == 6
            want = self._seq_logprob(bparams, toks[0, wdx], 4, ln)
            assert abs(want - float(out.scores[0, wdx])) < 1e-3

    def test_bad_width_rejected(self, bparams):
        from tony_tpu.models.decode import beam_search

        prompt = jnp.asarray([[1, 2]], jnp.int32)
        with pytest.raises(ValueError, match="beam_width"):
            beam_search(bparams, prompt, self.BCFG, max_new_tokens=3,
                        beam_width=0)
        with pytest.raises(ValueError, match="max_new_tokens"):
            beam_search(bparams, prompt, self.BCFG, max_new_tokens=0,
                        beam_width=2)

    def test_tp_sharded_beams_match_unsharded(self):
        """Beam search under tensor parallelism: sharded params give the
        same beams/scores via XLA sharding propagation — the per-step
        cache gather by parent index must respect the propagated cache
        sharding."""
        from tony_tpu.models.decode import beam_search
        from tony_tpu.parallel import make_mesh, shard_pytree

        cfg = self.BCFG.scaled(vocab_size=16)   # tp-divisible lm_head
        params = T.init_params(jax.random.PRNGKey(2), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(8), (2, 5), 0,
                                    cfg.vocab_size)
        ref = beam_search(params, prompt, cfg, max_new_tokens=5,
                          beam_width=3)
        mesh = make_mesh({"tp": 2, "dp": 4})
        sharded = shard_pytree(params, T.logical_axes(cfg), mesh)
        with jax.set_mesh(mesh):
            out = beam_search(sharded, prompt, cfg,
                              max_new_tokens=5, beam_width=3)
        np.testing.assert_array_equal(np.asarray(ref.tokens),
                                      np.asarray(out.tokens))
        np.testing.assert_allclose(np.asarray(ref.scores),
                                   np.asarray(out.scores), atol=1e-4)


class TestSpeculativeSampling:
    """Rejection-sampling speculation (temperature > 0): committed
    tokens are distributed exactly as target-only sampling, for any
    draft."""

    SCFG = T.TransformerConfig(vocab_size=11, d_model=24, n_layers=2,
                               n_heads=2, d_ff=48, max_seq=1024,
                               dtype=jnp.float32,
                               logits_dtype=jnp.float32, remat=False)

    def test_requires_rng(self):
        from tony_tpu.models.decode import speculative_generate_device

        params = T.init_params(jax.random.PRNGKey(0), self.SCFG)
        prompt = jnp.asarray([[3, 7, 1, 5]], jnp.int32)
        with pytest.raises(ValueError, match="rng"):
            speculative_generate_device(params, params, prompt, self.SCFG,
                                        self.SCFG, max_new_tokens=4,
                                        num_speculative=2, temperature=0.8)

    def test_self_draft_accepts_everything(self):
        """With draft == target and no filters the accept ratio is
        exactly 1, so the round count is deterministic:
        ceil(max_new / (k+1))."""
        from tony_tpu.models.decode import speculative_generate_device

        params = T.init_params(jax.random.PRNGKey(0), self.SCFG)
        prompt = jnp.asarray([[3, 7, 1, 5]], jnp.int32).repeat(4, 0)
        _, rounds = speculative_generate_device(
            params, params, prompt, self.SCFG, self.SCFG,
            max_new_tokens=12, num_speculative=3, temperature=1.0,
            rng=jax.random.PRNGKey(5), return_rounds=True)
        assert int(rounds) == 3

    @pytest.mark.slow
    def test_matches_target_distribution_any_draft(self):
        """The core guarantee, measured: the 2-token joint distribution
        of speculative sampling with a MISMATCHED draft (a different
        random model) matches direct target sampling to sampling noise
        (TV ~ 0.05 at ~3k samples), while the draft's own distribution
        is far away (TV ~ 0.7) — so the tolerance has discriminating
        power. Run under the bounded-window commit with the minimum
        window so the clamped-pending path (accepted-token-at-the-cut)
        is exercised too."""
        from tony_tpu.models.decode import speculative_generate_device

        cfg = self.SCFG
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        draft = T.init_params(jax.random.PRNGKey(99), cfg)
        pm = jnp.asarray([[3, 7, 1, 5]], jnp.int32).repeat(512, 0)
        n = 2

        def joint(fn, seed0, batches=6):
            c = np.zeros((cfg.vocab_size, cfg.vocab_size))
            for i in range(batches):
                a = fn(jax.random.PRNGKey(seed0 + i))
                for r in a:
                    c[r[0], r[1]] += 1
            return c / c.sum()

        ref = joint(lambda key: np.asarray(generate(
            params, pm, cfg, max_new_tokens=n, rng=key, temperature=0.9,
            top_p=0.85).tokens[:, -n:]), 200)
        spec = joint(lambda key: np.asarray(speculative_generate_device(
            params, draft, pm, cfg, cfg, max_new_tokens=n,
            num_speculative=3, temperature=0.9, top_p=0.85,
            commit="window", window=5, rng=key)[:, -n:]), 100)
        draft_only = joint(lambda key: np.asarray(generate(
            draft, pm, cfg, max_new_tokens=n, rng=key, temperature=0.9,
            top_p=0.85).tokens[:, -n:]), 300)

        tv_spec = 0.5 * np.abs(spec - ref).sum()
        tv_draft = 0.5 * np.abs(draft_only - ref).sum()
        assert tv_spec < 0.1, tv_spec
        assert tv_draft > 0.3, tv_draft    # the test can tell them apart


class TestQuantizedKVCache:
    """int8 KV cache (cfg.kv_cache_dtype="int8"): k/v stored int8 with
    per-token, per-kv-head absmax scales in parallel [.., KV, 1] buffers.
    Exactness contract: the quantized ATTENTION math is deterministic, so
    everything downstream that compares quant-to-quant (serving vs
    generate, beam width-1 vs greedy, speculative vs greedy) stays
    token-identical on CPU; quant-to-float agreement is approximate
    (int8 rounding, ~1% relative on the attention output)."""

    QCFG = CFG.scaled(kv_cache_dtype="int8")

    def test_cache_layout(self):
        from tony_tpu.models import decode as D
        c = D.init_kv_cache(self.QCFG, 2, 64)
        kv, hd = self.QCFG.kv_heads, self.QCFG.head_dim
        assert c["k"].dtype == jnp.int8 and c["v"].dtype == jnp.int8
        assert c["k_scale"].shape == (self.QCFG.n_layers, 2, 64, kv, 1)
        assert c["k_scale"].dtype == jnp.float32

    def test_quantize_roundtrip_error_bounded(self):
        from tony_tpu.models import decode as D
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 7, 2, 32),
                              jnp.float32)
        q, s = D._kv_quantize(x)
        assert q.dtype == jnp.int8 and s.shape == (4, 7, 2, 1)
        err = jnp.abs(q.astype(jnp.float32) * s - x)
        # symmetric absmax: per-element error <= scale/2 = absmax/254
        bound = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 254.0
        assert bool(jnp.all(err <= bound + 1e-7))

    def _quant_bufs(self, key, b, max_len, kv, d):
        from tony_tpu.models import decode as D
        ks = jax.random.split(jax.random.PRNGKey(key), 2)
        k = jax.random.normal(ks[0], (1, b, max_len, kv, d), jnp.float32)
        v = jax.random.normal(ks[1], (1, b, max_len, kv, d), jnp.float32)
        kq, ksc = D._kv_quantize(k)
        vq, vsc = D._kv_quantize(v)
        return ({"k": kq, "v": vq, "k_scale": ksc, "v_scale": vsc},
                {"k": kq.astype(jnp.float32) * ksc,
                 "v": vq.astype(jnp.float32) * vsc})

    @pytest.mark.parametrize("max_len,q_start,n_q", [(192, 150, 1),
                                                     (1024, 700, 3)])
    def test_scale_fold_matches_dequantized(self, max_len, q_start, n_q):
        """The K scale applied on the scores and the V scale folded into
        p must equal attention over the explicitly dequantized cache
        (same math, reassociated) — covers the dense AND blockwise
        dispatch (max_len 1024 >= _BLOCKWISE_MIN_LEN)."""
        from tony_tpu.models import decode as D
        bufs_q, bufs_dq = self._quant_bufs(max_len, 2, max_len, 2, 32)
        q = jax.random.normal(jax.random.PRNGKey(1), (2, n_q, 8, 32),
                              jnp.float32)
        got = D._cached_attention(q, bufs_q, 0, jnp.asarray(q_start))
        want = D._cached_attention(q, bufs_dq, 0, jnp.asarray(q_start))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-2)

    def test_quantized_attention_close_to_float(self):
        """int8 rounding bounds the attention-output error (~1% rel)."""
        from tony_tpu.models import decode as D
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        k = jax.random.normal(ks[0], (1, 2, 192, 2, 32), jnp.float32)
        v = jax.random.normal(ks[1], (1, 2, 192, 2, 32), jnp.float32)
        q = jax.random.normal(ks[2], (2, 1, 4, 32), jnp.float32)
        kq, ksc = D._kv_quantize(k)
        vq, vsc = D._kv_quantize(v)
        of = D._cached_attention(q, {"k": k, "v": v}, 0, jnp.asarray(150))
        oq = D._cached_attention(
            q, {"k": kq, "v": vq, "k_scale": ksc, "v_scale": vsc}, 0,
            jnp.asarray(150))
        rel = float(jnp.max(jnp.abs(of - oq)) / jnp.max(jnp.abs(of)))
        assert rel < 0.05, rel

    def test_generate_runs_and_tracks_float(self, params):
        """Quantized greedy generate stays on the float model's rails:
        the FIRST token (sharpest signal, no drift) matches, and per-step
        model logprobs stay close while the streams agree."""
        prompt = jax.random.randint(jax.random.PRNGKey(40), (2, 8), 0,
                                    CFG.vocab_size)
        rng = jax.random.PRNGKey(0)
        out_f = generate(params, prompt, CFG, 24, rng)
        out_q = generate(params, prompt, self.QCFG, 24, rng)
        assert out_q.tokens.shape == out_f.tokens.shape
        assert bool(jnp.all(out_f.tokens[:, 8] == out_q.tokens[:, 8]))

    def test_extend_step_matches_sequential_quant(self, params):
        """Chunked verify == single steps under quantization (the
        property speculative decoding relies on). Cache CONTENTS are
        identical (per-token quantization is chunk-width-independent);
        logits agree to the same dot-rounding tolerance as the
        unquantized chunk-vs-sequential test above."""
        from tony_tpu.models import decode as D
        prompt = jax.random.randint(jax.random.PRNGKey(41), (1, 6), 0,
                                    CFG.vocab_size)
        toks = jax.random.randint(jax.random.PRNGKey(42), (1, 4), 0,
                                  CFG.vocab_size)
        _, c1 = D.prefill(params, prompt, self.QCFG, max_len=16)
        lg_chunk, c1 = D.extend_step(params, toks, c1, 6, self.QCFG)
        _, c2 = D.prefill(params, prompt, self.QCFG, max_len=16)
        for i in range(4):
            lg, c2 = D.decode_step(params, toks[:, i], c2, 6 + i,
                                   self.QCFG)
            np.testing.assert_allclose(np.asarray(lg_chunk[:, i]),
                                       np.asarray(lg), rtol=2e-4,
                                       atol=2e-4)
        # the chunk's DEQUANTIZED cache matches the sequential writes
        # (bit-equality only holds at layer 0 — deeper layers' K/V
        # inputs inherit shape-dependent dot rounding from the layers
        # below, which can move a value across a rounding boundary)
        for kn, sn in (("k", "k_scale"), ("v", "v_scale")):
            d1 = np.asarray(c1[kn], np.float32) * np.asarray(c1[sn])
            d2 = np.asarray(c2[kn], np.float32) * np.asarray(c2[sn])
            np.testing.assert_allclose(d1, d2, atol=1e-3)
        np.testing.assert_array_equal(np.asarray(c1["k"][0]),
                                      np.asarray(c2["k"][0]))

    def test_speculative_device_equals_greedy_quant(self, params):
        """Both caches quantized: the speculative program still equals
        quantized greedy generate token for token (CPU-exact)."""
        from tony_tpu.models.decode import speculative_generate_device
        prompt = jax.random.randint(jax.random.PRNGKey(43), (2, 5), 0,
                                    CFG.vocab_size)
        want = generate(params, prompt, self.QCFG, 12,
                        jax.random.PRNGKey(0)).tokens
        got = speculative_generate_device(
            params, params, prompt, self.QCFG, self.QCFG,
            max_new_tokens=12, num_speculative=3)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_beam_width_one_equals_greedy_quant(self, params):
        from tony_tpu.models.decode import beam_search
        prompt = jax.random.randint(jax.random.PRNGKey(44), (2, 6), 0,
                                    CFG.vocab_size)
        bs = beam_search(params, prompt, self.QCFG, 10, beam_width=1)
        g = generate(params, prompt, self.QCFG, 10, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(bs.tokens[:, 0]),
                                      np.asarray(g.tokens))


class TestSlidingWindowDecode:
    """attn_window threads from TransformerConfig through prefill,
    decode_step, and the blockwise cached-attention path: cached decode
    must equal the windowed training forward, and the blockwise loop's
    window-derived LOWER bound (the O(window) serving-cost lever) must
    not change results."""

    WCFG = CFG.scaled(attn_window=24)

    def test_config_validation(self):
        with pytest.raises(ValueError, match="attn_window"):
            CFG.scaled(attn_window=-1)

    def test_window_with_cp_mesh_rejected(self):
        from tony_tpu.parallel.mesh import make_mesh
        mesh = make_mesh({"cp": 2, "dp": -1})
        q = jnp.zeros((2, 8, 4, 8), jnp.float32)
        with pytest.raises(NotImplementedError, match="attn_window"):
            T._attention(q, q, q, mesh, "ring", 8)

    def test_windowed_generate_equals_windowed_forward(self, params):
        prompt = jax.random.randint(jax.random.PRNGKey(50), (2, 30), 0,
                                    CFG.vocab_size)
        out = generate(params, prompt, self.WCFG, 8,
                       jax.random.PRNGKey(0))
        want = full_forward_greedy(params, prompt, 8, cfg=self.WCFG)
        np.testing.assert_array_equal(np.asarray(out.tokens),
                                      np.asarray(want))
        # the window genuinely bites at these lengths: full attention
        # decodes differently
        out_full = generate(params, prompt, CFG, 8, jax.random.PRNGKey(0))
        assert bool((out.tokens != out_full.tokens).any())

    @pytest.mark.parametrize("q_start,n_q", [(700, 1), (700, 3), (120, 1)])
    def test_blockwise_window_matches_dense_formula(self, q_start, n_q):
        """q_start 700 with window 128 puts the loop's lower bound at
        block 2 — the skipped leading blocks must not change the result
        (and corrupting them must have no effect)."""
        from tony_tpu.models import decode as D
        w = 128
        ks = jax.random.split(jax.random.PRNGKey(60), 3)
        max_len, kv, h, d = 1024, 2, 4, 16
        q = jax.random.normal(ks[0], (2, n_q, h, d), jnp.float32)
        k_cache = jax.random.normal(ks[1], (2, max_len, kv, d), jnp.float32)
        v_cache = jax.random.normal(ks[2], (2, max_len, kv, d), jnp.float32)
        got = D._cached_attention_blockwise(
            q, {"k": k_cache[None], "v": v_cache[None]}, 0,
            jnp.asarray(q_start), attn_window=w)
        # dense masked oracle
        q_pos = q_start + jnp.arange(n_q)
        k_pos = jnp.arange(max_len)
        mask = ((k_pos[None, :] <= q_pos[:, None])
                & (q_pos[:, None] - k_pos[None, :] < w))
        group = h // kv
        qg = q.reshape(2, n_q, kv, group, d)
        s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k_cache) * d ** -0.5
        s = jnp.where(mask[None, None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        want = jnp.einsum("bkgqs,bskd->bqkgd", p,
                          v_cache).reshape(2, n_q, h, d)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        # out-of-window cache rows are never read: corrupt them
        if q_start - w > 0:
            kc = k_cache.at[:, :q_start - w].set(1e4)
            vc = v_cache.at[:, :q_start - w].set(-1e4)
            got2 = D._cached_attention_blockwise(
                q, {"k": kc[None], "v": vc[None]}, 0,
                jnp.asarray(q_start), attn_window=w)
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(got2))

    def test_window_composes_with_int8_cache(self, params):
        """attn_window + kv_cache_dtype="int8" together: windowed quant
        generate equals the same windowed quant full-forward chain only
        approximately (int8), so assert the serving-relevant exactness
        instead — blockwise quant windowed == dense-on-dequantized
        windowed."""
        from tony_tpu.models import decode as D
        w = 128
        ks = jax.random.split(jax.random.PRNGKey(61), 3)
        max_len, kv, h, d = 1024, 2, 4, 16
        q = jax.random.normal(ks[0], (2, 1, h, d), jnp.float32)
        k_c = jax.random.normal(ks[1], (2, max_len, kv, d), jnp.float32)
        v_c = jax.random.normal(ks[2], (2, max_len, kv, d), jnp.float32)
        kq, ksc = D._kv_quantize(k_c[None])
        vq, vsc = D._kv_quantize(v_c[None])
        got = D._cached_attention_blockwise(
            q, {"k": kq, "v": vq, "k_scale": ksc, "v_scale": vsc}, 0,
            jnp.asarray(700), attn_window=w)
        want = D._cached_attention_blockwise(
            q, {"k": kq.astype(jnp.float32) * ksc,
                "v": vq.astype(jnp.float32) * vsc}, 0,
            jnp.asarray(700), attn_window=w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-2)


class TestRollingCache:
    """Rolling (ring-buffer) KV cache: O(capacity) memory however long
    the stream runs. Requires a sliding window (full-causal queries need
    the history the ring overwrote); reads mask rows by their ring
    offset from each query's absolute position."""

    LCFG = CFG.scaled(attn_window=24)
    RCFG = LCFG.scaled(kv_cache_capacity=32)

    def test_validation(self):
        with pytest.raises(ValueError, match="attn_window"):
            CFG.scaled(kv_cache_capacity=32)
        with pytest.raises(ValueError, match="kv_cache_capacity"):
            CFG.scaled(attn_window=24, kv_cache_capacity=8)

    def test_cache_is_capacity_sized(self):
        c = init_kv_cache(self.RCFG, 2, 999)
        assert c["k"].shape[2] == 32

    def test_oversized_capacity_warns_o_capacity_cost(self):
        """_ring_cached_attention is dense over ALL capacity rows every
        step: capacity a large multiple of the window silently pays
        O(capacity) per token, not O(window) — init warns once. A
        capacity near the window (the intended regime) stays quiet."""
        import warnings

        big = CFG.scaled(attn_window=24, kv_cache_capacity=96)
        with pytest.warns(UserWarning, match=r"O\(capacity\)"):
            init_kv_cache(big, 1, 999)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            init_kv_cache(self.RCFG, 1, 999)     # 32 rows, window 24

    def test_ring_generate_equals_linear_windowed(self, params):
        """Same positions attended, same math: ring generate matches the
        linear windowed-cache generate (prompt shorter than capacity —
        no wraparound reordering of the softmax rows)."""
        prompt = jax.random.randint(jax.random.PRNGKey(70), (2, 20), 0,
                                    CFG.vocab_size)
        out_lin = generate(params, prompt, self.LCFG, 30,
                           jax.random.PRNGKey(0))
        out_ring = generate(params, prompt, self.RCFG, 30,
                            jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(out_lin.tokens),
                                      np.asarray(out_ring.tokens))

    def test_generation_far_past_capacity(self, params):
        """The headline property: generate 3x the ring capacity in one
        stream — the fixed 32-row cache serves a 96-token generation —
        and the stream stays in close agreement with the linear windowed
        reference (jit partitioning rounds differently; wraparound
        reorders softmax row order, so bit-equality is not the
        contract past capacity)."""
        prompt = jax.random.randint(jax.random.PRNGKey(71), (1, 10), 0,
                                    CFG.vocab_size)
        out = generate(params, prompt, self.RCFG, 96,
                       jax.random.PRNGKey(0))
        tk = np.asarray(out.tokens)
        assert tk.shape == (1, 106)
        assert (tk >= 0).all() and (tk < CFG.vocab_size).all()
        ref = generate(params, prompt, self.LCFG, 96,
                       jax.random.PRNGKey(0))
        agree = (tk == np.asarray(ref.tokens)).mean()
        assert agree > 0.8, agree

    def test_prompt_longer_than_capacity(self, params):
        """Prefill keeps only the last `capacity` prompt rows — all a
        windowed query can ever reach. First decode logits must match
        the linear windowed cache's exactly (same eager prefill math)."""
        from tony_tpu.models import decode as D
        prompt = jax.random.randint(jax.random.PRNGKey(72), (2, 45), 0,
                                    CFG.vocab_size)
        lg_r, c_r = D.prefill(params, prompt, self.RCFG, max_len=60)
        lg_l, c_l = D.prefill(params, prompt, self.LCFG, max_len=60)
        np.testing.assert_array_equal(np.asarray(lg_r), np.asarray(lg_l))
        nxt = jnp.argmax(lg_r, -1)
        s_r, _ = D.decode_step(params, nxt, c_r, c_r["length"], self.RCFG)
        s_l, _ = D.decode_step(params, nxt, c_l, c_l["length"], self.LCFG)
        np.testing.assert_allclose(np.asarray(s_r), np.asarray(s_l),
                                   rtol=2e-4, atol=2e-4)

    def test_batcher_slots_independent(self, params):
        """2-slot ring serving == each request through a 1-slot batcher
        (same jit partitioning on both sides — exact), including a
        request whose prompt exceeds the capacity and one that runs
        past max_len (the ring lifts the length ceiling)."""
        from tony_tpu.models.serve import ContinuousBatcher
        rs = np.random.RandomState(5)
        prompts = [list(rs.randint(0, CFG.vocab_size, size=n))
                   for n in (10, 45)]
        budgets = [60, 20]
        b2 = ContinuousBatcher(params, self.RCFG, batch=2, max_len=48,
                               chunk=4)
        outs = b2.serve(prompts, max_new_tokens=budgets)
        for i, p in enumerate(prompts):
            b1 = ContinuousBatcher(params, self.RCFG, batch=1,
                                   max_len=48, chunk=4)
            solo = b1.serve([p], max_new_tokens=[budgets[i]])
            assert outs[i] == solo[0], f"request {i}"

    def test_refusals(self, params):
        from tony_tpu.models import decode as D
        from tony_tpu.models.serve import (ContinuousBatcher,
                                           SpeculativeContinuousBatcher)
        prompt = jax.random.randint(jax.random.PRNGKey(73), (1, 8), 0,
                                    CFG.vocab_size)
        with pytest.raises(ValueError, match="linear KV cache"):
            D.beam_search(params, prompt, self.RCFG, 4)
        with pytest.raises(ValueError, match="linear KV cache"):
            D.speculative_generate_device(params, params, prompt,
                                          self.RCFG, self.RCFG,
                                          max_new_tokens=4)
        with pytest.raises(ValueError, match="linear KV cache"):
            ContinuousBatcher(params, self.RCFG, batch=1, max_len=32,
                              shared_prefix=[1, 2, 3])
        with pytest.raises(ValueError, match="linear KV"):
            SpeculativeContinuousBatcher(params, self.RCFG, params,
                                         self.RCFG, batch=1, max_len=32)

    def test_int8_ring_composes(self, params):
        cfg = self.RCFG.scaled(kv_cache_dtype="int8")
        prompt = jax.random.randint(jax.random.PRNGKey(74), (2, 12), 0,
                                    CFG.vocab_size)
        out = generate(params, prompt, cfg, 50, jax.random.PRNGKey(0))
        tk = np.asarray(out.tokens)
        assert tk.shape == (2, 62)
        assert (tk >= 0).all() and (tk < CFG.vocab_size).all()


class TestWindowCombinations:
    """Feature-combination coverage: sliding-window models (linear
    cache) through the chunked-verify, beam, and serving paths — the
    window mask must hold for K>1 chunk queries and per-row frontiers,
    not just single-step decode."""

    WCFG = CFG.scaled(attn_window=24)

    def test_speculative_equals_windowed_greedy(self, params):
        """Chunked verify under a window: the draft's chunk and the
        target's k+1-wide verify both mask by the window, so the device
        speculative program still reproduces windowed greedy exactly."""
        from tony_tpu.models.decode import speculative_generate_device
        prompt = jax.random.randint(jax.random.PRNGKey(80), (2, 30), 0,
                                    CFG.vocab_size)
        want = generate(params, prompt, self.WCFG, 16,
                        jax.random.PRNGKey(0)).tokens
        got = speculative_generate_device(
            params, params, prompt, self.WCFG, self.WCFG,
            max_new_tokens=16, num_speculative=3)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # non-vacuity: the window genuinely bites at this prompt — a
        # path that silently ignored attn_window would NOT match `want`
        full = generate(params, prompt, CFG, 16,
                        jax.random.PRNGKey(0)).tokens
        assert bool((want != full).any())

    def test_beam_width_one_equals_windowed_greedy(self, params):
        from tony_tpu.models.decode import beam_search
        prompt = jax.random.randint(jax.random.PRNGKey(81), (2, 28), 0,
                                    CFG.vocab_size)
        bs = beam_search(params, prompt, self.WCFG, 12, beam_width=1)
        g = generate(params, prompt, self.WCFG, 12, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(bs.tokens[:, 0]),
                                      np.asarray(g.tokens))
        # non-vacuity: windowed differs from full attention here
        full = generate(params, prompt, CFG, 12, jax.random.PRNGKey(0))
        assert bool((g.tokens != full.tokens).any())

    def test_serving_token_identical_under_window(self, params):
        """Continuous batching with a windowed model (linear cache):
        per-request outputs equal solo windowed generate, including a
        reused slot."""
        from tony_tpu.models.serve import ContinuousBatcher
        rs = np.random.RandomState(9)
        prompts = [list(rs.randint(0, CFG.vocab_size, size=n))
                   for n in (26, 30, 28)]
        b = ContinuousBatcher(params, self.WCFG, batch=2, max_len=48,
                              chunk=4)
        outs = b.serve(prompts, max_new_tokens=8)
        diverged = False
        for i, p in enumerate(prompts):
            pm = jnp.asarray(p, jnp.int32)[None]
            want = generate(params, pm, self.WCFG, 8, jax.random.PRNGKey(0))
            assert outs[i] == [int(t) for t in
                               np.asarray(want.tokens[0, len(p):])], i
            full = generate(params, pm, CFG, 8, jax.random.PRNGKey(0))
            diverged |= bool((want.tokens != full.tokens).any())
        # non-vacuity: at least one request's windowed output differs
        # from full attention
        assert diverged
