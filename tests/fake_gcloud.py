#!/usr/bin/env python3
"""Fake ``gcloud compute tpus tpu-vm`` for tests — the MiniYARN analog.

A "slice" is a directory under $FAKE_GCLOUD_ROOT; each host is a
``worker<i>/`` subdir used as that host's $HOME, and ``ssh --command``
runs the command as a LOCAL process with HOME pointed there. That makes
the TPU backend's full provision → stage → launch → preempt →
reprovision flow executable end-to-end on one machine: staged executors
really start, import tony_tpu from the staged framework copy, and talk
to the real coordinator over RPC.

Verbs: create / describe / delete / ssh [--worker=i|all] / scp.
State: ``$slice/state`` (tests flip it to PREEMPTED); host count comes
from $FAKE_NUM_WORKERS at create time. Every invocation is appended to
$FAKE_GCLOUD_ROOT/calls.log for assertions. One fake-ism: hosts share
this machine's /tmp, so the staging path /tmp/tony-stage.tgz is rewritten
to a per-worker location in both scp and ssh commands.

Deterministic preemption (the elastic suite's TPU-side kill switch):
  FAKE_PREEMPT_<GANG>=1|<marker>  flips the slice's state to PREEMPTED on
      its next describe (and SIGKILLs its host processes, like a real
      preemption) — <GANG> is the slice name uppercased with non-
      alphanumerics mapped to "_". A value other than "1" is a marker
      path: the flip waits until that file exists. One-shot per slice
      generation (delete + recreate rearms it). There is also an explicit
      verb: ``gcloud compute tpus tpu-vm preempt <name>`` flips the state
      immediately.

Scripted failures (the MiniYARN-style failure repertoire — file-backed
counters so they work across fake invocations):
  FAKE_FAIL_CREATE_N=k    first k creates exit 1 with RESOURCE_EXHAUSTED
  FAKE_FAIL_UNPACK_N=k    first k staging-unpack ssh commands drop
                          ("Connection reset by peer")
  FAKE_FAIL_DESCRIBE_N=k  first k describes exit 1 (API flakiness)
  FAKE_FAIL_DELETE_N=k    first k deletes exit 1 (slice left in place)

Injected latency (the launch-wall benchmark's knob — real slice creation
and scp staging take minutes; the fake sleeps instead):
  FAKE_DELAY_CREATE_S / FAKE_DELAY_SCP_S / FAKE_DELAY_SSH_S /
  FAKE_DELAY_DESCRIBE_S = seconds slept before executing that verb.

Coordinator kill (the crash-recovery suite's TPU-side fault):
  FAKE_KILL_COORDINATOR=1|<marker>  SIGKILLs the invoking coordinator —
      the fake's parent process, since the TPU backend shells out from
      inside the coordinator — on the next describe (the state poller's
      code path). A value other than "1" is a marker path the flip waits
      for. One-shot per job via a .kill-coordinator-fired sentinel under
      $FAKE_GCLOUD_ROOT, written+fsync'd BEFORE the kill (an in-memory
      latch would die with the process).

Like real gcloud, ``create`` of an existing slice fails ALREADY_EXISTS
(the backend adopts the surviving slice on that error — the warm-restart
path).
"""

import os
import shutil
import subprocess
import sys
import time


def inject_delay(verb: str) -> None:
    d = os.environ.get(f"FAKE_DELAY_{verb.upper()}_S")
    if d:
        time.sleep(float(d))


def root() -> str:
    return os.environ["FAKE_GCLOUD_ROOT"]


def scripted_failure(kind: str) -> bool:
    """Consume one scripted failure of ``kind`` if budget remains. The
    counter file initializes from $FAKE_FAIL_<KIND>_N on first use."""
    budget = os.environ.get(f"FAKE_FAIL_{kind}_N")
    if not budget:
        return False
    path = os.path.join(root(), f"fail_{kind.lower()}_left")
    left = int(open(path).read()) if os.path.exists(path) else int(budget)
    if left <= 0:
        return False
    with open(path, "w") as f:
        f.write(str(left - 1))
    return True


def log_call(argv):
    with open(os.path.join(root(), "calls.log"), "a") as f:
        f.write(" ".join(argv) + "\n")


def slice_dir(name: str) -> str:
    return os.path.join(root(), name)


def worker_home(name: str, i: int) -> str:
    home = os.path.join(slice_dir(name), f"worker{i}")
    os.makedirs(home, exist_ok=True)
    return home


def num_workers(name: str) -> int:
    try:
        with open(os.path.join(slice_dir(name), "num_workers")) as f:
            return int(f.read().strip())
    except OSError:
        return 1


def rewrite_tmp(cmd: str, home: str) -> str:
    # per-host /tmp emulation for the one path the backend uses there
    return cmd.replace("/tmp/tony-stage.tgz",
                       os.path.join(home, ".tony-stage.tgz"))


def preempt_slice(name: str) -> bool:
    """Flip ``name`` to PREEMPTED and SIGKILL its hosts' processes (a
    real preemption takes the VMs down, not just the API state). Returns
    False when the slice does not exist."""
    state_path = os.path.join(slice_dir(name), "state")
    if not os.path.exists(state_path):
        return False
    with open(state_path, "w") as f:
        f.write("PREEMPTED")
    # best-effort host kill: every process whose cwd/HOME is a worker dir
    subprocess.run(["pkill", "-9", "-f", slice_dir(name)],
                   capture_output=True)
    return True


def maybe_env_preempt(name: str) -> None:
    """FAKE_PREEMPT_<GANG>: one-shot marker-gated preemption, checked on
    describe (the state poller's code path, like the real API)."""
    key = "FAKE_PREEMPT_" + "".join(
        c if c.isalnum() else "_" for c in name).upper()
    val = os.environ.get(key)
    if not val:
        return
    fired = os.path.join(slice_dir(name), ".preempt-fired")
    if os.path.exists(fired):
        return
    if val != "1" and not os.path.exists(val):
        return      # marker-gated: wait for the trainer to reach the step
    if preempt_slice(name):
        open(fired, "w").close()


def maybe_kill_coordinator() -> None:
    """FAKE_KILL_COORDINATOR: one-shot marker-gated SIGKILL of the
    invoking coordinator process, checked on describe. Slice state and
    host processes are left untouched — exactly what a coordinator host
    crash looks like from the gang's point of view."""
    import signal
    val = os.environ.get("FAKE_KILL_COORDINATOR")
    if not val:
        return
    fired = os.path.join(root(), ".kill-coordinator-fired")
    if os.path.exists(fired):
        return
    if val != "1" and not os.path.exists(val):
        return      # marker-gated: wait for the trainer to reach the step
    fd = os.open(fired, os.O_CREAT | os.O_WRONLY, 0o644)
    os.fsync(fd)
    os.close(fd)
    os.kill(os.getppid(), signal.SIGKILL)


def main(argv):
    if argv[:2] == ["auth", "print-access-token"]:
        # per-job scoped identity mint (tony.gcs.service-account)
        log_call(argv)
        sa = ""
        for f in argv[2:]:
            if f.startswith("--impersonate-service-account="):
                sa = f.split("=", 1)[1]
        if not sa:
            print("ERROR: expected --impersonate-service-account",
                  file=sys.stderr)
            return 1
        # distinct token per mint so renewal tests can observe rotation
        counter = os.path.join(os.environ["FAKE_GCLOUD_ROOT"], ".mint-count")
        n = int(open(counter).read()) + 1 if os.path.exists(counter) else 1
        with open(counter, "w") as f:
            f.write(str(n))
        print(f"fake-token-for-{sa}#{n}")
        return 0
    assert argv[:3] == ["compute", "tpus", "tpu-vm"], argv
    verb, name = argv[3], argv[4]
    flags = argv[5:]
    log_call(argv)

    def flag(prefix):
        for f in flags:
            if f.startswith(prefix):
                return f[len(prefix):]
        return None

    if verb != "create":
        inject_delay(verb)

    if verb == "create":
        if scripted_failure("CREATE"):
            print("ERROR: (gcloud.compute.tpus.tpu-vm.create) "
                  "RESOURCE_EXHAUSTED: quota exceeded for "
                  "TPUV5sLitepodPerProjectPerZone", file=sys.stderr)
            return 1
        if os.path.isdir(slice_dir(name)):
            # fails FAST like the real API — only a SUCCESSFUL create
            # pays the provisioning wait, which is why the adopt path's
            # warm restart is cheap
            print(f"ERROR: (gcloud.compute.tpus.tpu-vm.create) "
                  f"ALREADY_EXISTS: node {name} already exists",
                  file=sys.stderr)
            return 1
        inject_delay(verb)
        d = slice_dir(name)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "state"), "w") as f:
            f.write("READY")
        with open(os.path.join(d, "num_workers"), "w") as f:
            f.write(os.environ.get("FAKE_NUM_WORKERS", "1"))
        return 0

    if verb == "preempt":
        # test-only verb: immediate deterministic preemption
        return 0 if preempt_slice(name) else 1

    if verb == "describe":
        if scripted_failure("DESCRIBE"):
            print("ERROR: backend error: please retry", file=sys.stderr)
            return 1
        maybe_env_preempt(name)
        maybe_kill_coordinator()
        state_path = os.path.join(slice_dir(name), "state")
        if not os.path.exists(state_path):
            print("NOT_FOUND", file=sys.stderr)
            return 1
        with open(state_path) as f:
            print('{"state": "%s"}' % f.read().strip())
        return 0

    if verb == "delete":
        if scripted_failure("DELETE"):
            print("ERROR: (gcloud.compute.tpus.tpu-vm.delete) "
                  "INTERNAL: please retry", file=sys.stderr)
            return 1
        if not os.path.isdir(slice_dir(name)):
            return 1
        shutil.rmtree(slice_dir(name))
        return 0

    if verb == "ssh":
        command = flag("--command=")
        worker = flag("--worker=") or "0"
        if not os.path.isdir(slice_dir(name)):
            print(f"ssh: slice {name} does not exist", file=sys.stderr)
            return 1
        # mid-staging connection drop: target the unpack command so the
        # failure lands between the tarball scp and the secret scp
        if "tar -xzf" in (command or "") and scripted_failure("UNPACK"):
            print("ssh: Connection reset by peer", file=sys.stderr)
            return 255
        idx_list = (range(num_workers(name)) if worker == "all"
                    else [int(worker)])
        for i in idx_list:
            home = worker_home(name, i)
            env = dict(os.environ)
            env["HOME"] = home
            rc = subprocess.run(
                ["bash", "-c", rewrite_tmp(command, home)],
                env=env, cwd=home).returncode
            if rc != 0:
                return rc
        return 0

    if verb == "scp":
        # argv: scp LOCAL NAME:REMOTE --worker=all ... (name var holds LOCAL)
        local = name
        target = argv[5]
        slice_name, _, remote = target.partition(":")
        if not os.path.isdir(slice_dir(slice_name)):
            print(f"scp: slice {slice_name} does not exist", file=sys.stderr)
            return 1
        for i in range(num_workers(slice_name)):
            home = worker_home(slice_name, i)
            dest = rewrite_tmp(remote, home)
            if dest.startswith("~/"):
                dest = os.path.join(home, dest[2:])
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            shutil.copy2(local, dest)
        return 0

    print(f"fake_gcloud: unknown verb {verb}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
