"""SLO-tiered serving: QoS classes end to end. Protocol robustness
(class parsing, BUSY wire round trip, classless-means-standard), the
engine's per-class admission + batch-row preemption with token-identical
resume (greedy AND sampled, colocated AND through the prefill/decode
split), client BUSY retry, router-level batch re-queue, the
interactive-pressure autoscale signal, configurable latency buckets,
and the 2x-overload bench-arm pins.

Compile frugality: the jax tests reuse test_serving's / test_disagg's
exact (batch, max_len, chunk) shapes, so this module warms the same
compiled programs those later modules reuse.
"""

import os
import queue as queue_mod
import socket
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.conf import keys as K
from tony_tpu.conf.config import TonyConfig
from tony_tpu.models import transformer as T
from tony_tpu.models.decode import generate
from tony_tpu.models.serve import ContinuousBatcher, EngineBusy, ServeEngine
from tony_tpu.runtime import metrics as M
from tony_tpu.serving import kvship
from tony_tpu.serving import protocol as P
from tony_tpu.serving.client import ServerBusy, StreamingClient
from tony_tpu.serving.disagg import DecodeServer, PrefillServer
from tony_tpu.serving.fleet import CapacityProvider, FleetController
from tony_tpu.serving.router import ServingRouter
from tony_tpu.serving.server import ServingServer
from tony_tpu.serving.simfleet import SimFleet, sim_token

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)          # for `import bench` (repo-root script)

CFG = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.PRNGKey(0), CFG)


def _reference(params, prompt, max_new):
    out = generate(params, jnp.asarray(prompt, jnp.int32)[None], CFG,
                   max_new_tokens=max_new, rng=jax.random.PRNGKey(0),
                   temperature=0.0)
    return [int(t) for t in np.asarray(out.tokens[0, len(prompt):])]


def _prompts(seed, sizes):
    rng = np.random.RandomState(seed)
    return [[int(t) for t in rng.randint(0, CFG.vocab_size, size=n)]
            for n in sizes]


class _SlowFetch(ContinuousBatcher):
    """Keeps streams genuinely mid-flight so admissions land on a full
    engine (the preemption / shed paths)."""

    def _fetch(self, handle):
        time.sleep(0.05)
        return super()._fetch(handle)


# ---------------------------------------------------------------------------
# protocol: class parsing, BUSY frame, kv-meta class field
# ---------------------------------------------------------------------------
class TestClassProtocol:
    def test_parse_class_absent_means_standard(self):
        assert P.parse_class({}) == "standard"
        assert P.parse_class({"prompt": [1]}) == "standard"

    def test_parse_class_accepts_every_tier(self):
        for c in P.QOS_CLASSES:
            assert P.parse_class({"class": c}) == c

    def test_parse_class_rejects_unknown_and_nonstring(self):
        with pytest.raises(ValueError, match="request class"):
            P.parse_class({"class": "gold"})
        with pytest.raises(ValueError, match="request class"):
            P.parse_class({"class": 3})

    def test_busy_frame_named_and_round_trips(self):
        assert P.FRAME_NAMES[P.BUSY] == "BUSY"
        a, b = socket.socketpair()
        try:
            P.send_frame(a, P.BUSY, 7,
                         P.pack_json({"retry_after_ms": 250}))
            ftype, rid, payload = P.recv_frame(b)
            assert (ftype, rid) == (P.BUSY, 7)
            assert P.unpack_json(payload)["retry_after_ms"] == 250
        finally:
            a.close()
            b.close()

    def test_kv_meta_class_round_trip(self):
        key = np.zeros((2,), np.uint32)
        meta = kvship.parse_kv_meta(kvship.pack_kv_meta(
            5, 8, 3, key, cls="interactive"))
        assert meta["class"] == "interactive"
        # default class is omitted from the wire (old peers see the
        # old meta), and the parse side normalizes it back in
        packed = kvship.pack_kv_meta(5, 8, 3, key)
        assert "class" not in packed
        assert kvship.parse_kv_meta(packed)["class"] == "standard"

    def test_kv_meta_malformed_class_rejected(self):
        key = np.zeros((2,), np.uint32)
        packed = kvship.pack_kv_meta(5, 8, 3, key, cls="interactive")
        packed["class"] = "platinum"
        with pytest.raises(P.ProtocolError, match="class"):
            kvship.parse_kv_meta(packed)


# ---------------------------------------------------------------------------
# configurable latency buckets (tony.metrics.latency-buckets)
# ---------------------------------------------------------------------------
class TestLatencyBuckets:
    def test_blank_spec_is_the_builtin_ladder(self):
        assert M.parse_latency_buckets("") == M.TIME_BUCKETS_S
        assert M.parse_latency_buckets("  ") == M.TIME_BUCKETS_S

    def test_custom_ladder_parses_and_wires_into_histograms(self):
        bounds = M.parse_latency_buckets("0.01, 0.05, 0.25, 1.0")
        assert bounds == (0.01, 0.05, 0.25, 1.0)
        reg = M.MetricsRegistry()
        h = reg.histogram("tony_test_qos_ladder", buckets=bounds)
        assert tuple(h.buckets) == bounds

    @pytest.mark.parametrize("spec", ["abc", "0.1,xyz", "0.5,0.25",
                                      "0.1,0.1", "-1,2", "0,1", "inf"])
    def test_malformed_specs_refused(self, spec):
        with pytest.raises(ValueError):
            M.parse_latency_buckets(spec)

    def test_bad_ladder_refused_at_config_load(self):
        with pytest.raises(ValueError, match="increasing"):
            TonyConfig.load(cli_overrides={
                K.METRICS_LATENCY_BUCKETS_KEY: "0.5,0.1"})
        conf = TonyConfig.load(cli_overrides={
            K.METRICS_LATENCY_BUCKETS_KEY: "0.1,0.5"})
        assert conf.get_latency_buckets() == (0.1, 0.5)

    def test_default_config_keeps_old_bounds(self):
        assert TonyConfig.load().get_latency_buckets() == M.TIME_BUCKETS_S


# ---------------------------------------------------------------------------
# engine: floors, shed, preemption with token-identical resume
# ---------------------------------------------------------------------------
class _Harness:
    """ServeEngine on a background thread with recorded deltas and
    retirement reasons (the final eos/budget delta arrives via
    on_retired — the atomic-final contract)."""

    def __init__(self, batcher, registry=None, **engine_kw):
        self.got: dict = {}
        self.retired: dict = {}

        def on_retired(rid, reason, n, final):
            self.got.setdefault(rid, []).extend(final)
            self.retired[rid] = (reason, n)

        self.engine = ServeEngine(
            batcher,
            on_delta=lambda rid, t: self.got.setdefault(rid, []).extend(t),
            on_retired=on_retired, registry=registry, **engine_kw)
        self.thread = threading.Thread(target=self.engine.run,
                                       daemon=True)
        self.thread.start()

    def wait_first_tokens(self, rids, timeout=60):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if all(self.got.get(r) for r in rids):
                return
            time.sleep(0.005)
        raise AssertionError(f"streams never started: "
                             f"{ {r: self.got.get(r) for r in rids} }")

    def finish(self, timeout=120):
        self.engine.drain()
        self.thread.join(timeout=timeout)
        assert not self.thread.is_alive(), "engine did not drain"


class TestEngineQoS:
    def test_floor_and_class_validation(self, params):
        b = ContinuousBatcher(params, CFG, batch=2, max_len=32, chunk=3)
        with pytest.raises(ValueError, match="exceed"):
            ServeEngine(b, class_floors={"interactive": 2, "batch": 1})
        with pytest.raises(ValueError, match="unknown QoS class"):
            ServeEngine(b, class_floors={"gold": 1})
        eng = ServeEngine(b)
        with pytest.raises(ValueError, match="unknown request class"):
            eng.submit(1, [1, 2], 4, request_class="gold")
        assert eng.stats()["class_floors"] == {
            c: 0 for c in P.QOS_CLASSES}

    def test_shed_past_queue_depth_interactive_exempt(self, params):
        """Past the bounded queue, standard/batch submits are refused
        with EngineBusy carrying the retry hint; interactive always
        queues (and preempts its way in). Everything that was accepted
        still finishes token-identically."""
        reg = M.MetricsRegistry()
        h = _Harness(_SlowFetch(params, CFG, batch=2, max_len=32,
                                chunk=3),
                     registry=reg, max_queue_depth=1, busy_retry_ms=123)
        prompts = _prompts(40, (4, 5, 4, 6))
        # long enough that neither slot-holder retires while the shed
        # probes and the interactive admission land
        budget = 20
        try:
            # stagger the fill: with depth 1, a submit racing the
            # loop's admission of the previous one would shed
            h.engine.submit(0, prompts[0], budget, request_class="batch")
            h.wait_first_tokens([0])
            h.engine.submit(1, prompts[1], budget, request_class="batch")
            h.wait_first_tokens([1])      # both slots held, queue empty
            h.engine.submit(2, prompts[2], budget, request_class="batch")
            with pytest.raises(EngineBusy) as ei:
                h.engine.submit(9, prompts[3], budget,
                                request_class="batch")
            assert ei.value.retry_after_ms == 123
            with pytest.raises(EngineBusy):
                h.engine.submit(9, prompts[3], budget,
                                request_class="standard")
            # interactive is exempt: it queues, then preempts a row
            h.engine.submit(3, prompts[3], 6,
                            request_class="interactive")
        finally:
            h.finish()
        for rid, prompt in ((0, prompts[0]), (1, prompts[1]),
                            (2, prompts[2])):
            assert h.got[rid] == _reference(params, prompt, budget), rid
        assert h.got[3] == _reference(params, prompts[3], 6)
        shed = {c: reg.counter("tony_serve_shed_total",
                               **{"class": c}).value
                for c in P.QOS_CLASSES}
        assert shed == {"interactive": 0, "standard": 1, "batch": 1}
        assert reg.counter("tony_serve_preemptions_total").value >= 1

    def test_preempt_resume_token_identity_greedy(self, params):
        """An interactive admission evicts a decoding batch row; the
        evicted stream is reincarnated via rng-offset re-prefill and
        must finish with EXACTLY the uninterrupted reference tokens —
        no terminal 'preempted' ever reaches the caller colocated."""
        reg = M.MetricsRegistry()
        h = _Harness(_SlowFetch(params, CFG, batch=2, max_len=32,
                                chunk=3), registry=reg)
        prompts = _prompts(41, (5, 4, 6))
        try:
            h.engine.submit(0, prompts[0], 12, request_class="batch")
            h.engine.submit(1, prompts[1], 12, request_class="batch")
            h.wait_first_tokens([0, 1])
            h.engine.submit(2, prompts[2], 6,
                            request_class="interactive")
        finally:
            h.finish()
        assert reg.counter("tony_serve_preemptions_total").value == 1
        assert h.got[0] == _reference(params, prompts[0], 12)
        assert h.got[1] == _reference(params, prompts[1], 12)
        assert h.got[2] == _reference(params, prompts[2], 6)
        assert {r for r, _ in h.retired.values()} == {"budget"}

    def test_preempt_resume_token_identity_sampled(self, params):
        """The sampled twin: the reincarnation's rng offset skips the
        emitted count, so the resumed sampled stream is bit-identical
        to the uninterrupted run."""
        kw = dict(batch=2, max_len=64, chunk=2, seed=7,
                  temperature=0.8, top_k=20, top_p=0.9)
        prompts = _prompts(42, (5, 4, 6))
        ref = ContinuousBatcher(params, CFG, **kw).serve(
            prompts, 12)
        reg = M.MetricsRegistry()
        h = _Harness(_SlowFetch(params, CFG, **kw), registry=reg)
        try:
            h.engine.submit(0, prompts[0], 12, request_class="batch")
            h.engine.submit(1, prompts[1], 12, request_class="batch")
            h.wait_first_tokens([0, 1])
            h.engine.submit(2, prompts[2], 12,
                            request_class="interactive")
        finally:
            h.finish()
        assert reg.counter("tony_serve_preemptions_total").value == 1
        for rid in (0, 1, 2):
            assert h.got[rid] == ref[rid], \
                f"stream {rid}: sampled dup/drop across preemption"


# ---------------------------------------------------------------------------
# serving server: the wire contract (classless e2e, malformed class,
# BUSY + client retry)
# ---------------------------------------------------------------------------
class TestServerWireQoS:
    def test_classless_admit_lands_standard_e2e(self, params):
        """An old client (no class field) must behave exactly as
        before: admitted, queued as ``standard`` (visible in the STATS
        per-class depths), served token-identically."""
        srv = ServingServer(_SlowFetch(params, CFG, batch=2, max_len=32,
                                       chunk=3),
                            registry=M.MetricsRegistry())
        port = srv.start()
        prompts = _prompts(43, (4, 5, 4))
        budget = 10
        try:
            with StreamingClient("127.0.0.1", port) as c:
                rids = [c.submit(p, budget) for p in prompts]
                deadline = time.time() + 30
                seen_standard = False
                while time.time() < deadline and not seen_standard:
                    depths = c.stats()["queue_depths"]
                    assert depths["interactive"] == 0
                    assert depths["batch"] == 0
                    seen_standard = depths["standard"] >= 1
                    time.sleep(0.01)
                assert seen_standard, "classless admit never queued as " \
                                      "standard"
                for i, r in enumerate(rids):
                    toks, reason = c.result(r)
                    assert toks == _reference(params, prompts[i], budget)
                    assert reason == "budget"
        finally:
            srv.stop()

    def test_malformed_class_is_request_scoped(self, params):
        srv = ServingServer(ContinuousBatcher(params, CFG, batch=2,
                                              max_len=32, chunk=3),
                            registry=M.MetricsRegistry())
        port = srv.start()
        try:
            with StreamingClient("127.0.0.1", port) as c:
                rid = c.submit([1, 2, 3], 4, request_class="gold")
                ev = c.next_event(rid, timeout=60)
                assert ev[0] == "error" and "request class" in ev[1]
                # the connection survives; a valid class still serves
                p = _prompts(44, (4,))[0]
                toks, _ = c.result(c.submit(p, 5,
                                            request_class="interactive"))
                assert toks == _reference(params, p, 5)
        finally:
            srv.stop()

    def test_busy_over_wire_then_client_retry_recovers(self, params):
        """A shed surfaces as ServerBusy carrying the server's hint
        when the retry budget is 0; with a budget the client re-admits
        transparently after backoff and the request completes once
        capacity frees."""
        srv = ServingServer(_SlowFetch(params, CFG, batch=2, max_len=32,
                                       chunk=3),
                            registry=M.MetricsRegistry(),
                            max_queue_depth=1, busy_retry_ms=40)
        port = srv.start()
        prompts = _prompts(45, (4, 5, 4, 6))
        budget = 20   # slot-holders must outlive the shed probe
        try:
            with StreamingClient("127.0.0.1", port) as c:
                # stagger the fill: with depth 1, a submit racing the
                # engine's admission of the previous one would shed
                rids = []
                for i, want in enumerate(((1, 0), (2, 0), (2, 1))):
                    rids.append(c.submit(prompts[i], budget,
                                         request_class="batch"))
                    deadline = time.time() + 30
                    while time.time() < deadline:
                        st = c.stats()
                        if (st["active"], st["queue_depth"]) == want:
                            break
                        time.sleep(0.01)
                    else:
                        pytest.fail(f"fill {i} never settled: {st}")
                with pytest.raises(ServerBusy) as ei:
                    c.result(c.submit(prompts[3], 6,
                                      request_class="batch"))
                assert ei.value.retry_after_ms == 40
                # with a retry budget the SAME submission self-heals
                toks, reason = c.result(
                    c.submit(prompts[3], 6, request_class="batch",
                             retries=8), timeout=120)
                assert toks == _reference(params, prompts[3], 6)
                assert reason == "budget"
                for i, r in enumerate(rids):
                    toks, _ = c.result(r, timeout=120)
                    assert toks == _reference(params, prompts[i],
                                              budget)
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# disaggregated: class rides the shipment; decode-tier preemption
# resumes through a fresh prefill, token-identically
# ---------------------------------------------------------------------------
class TestDisaggQoS:
    def _stack(self, params, decode_batcher, seed=0):
        regp, regd, regr = (M.MetricsRegistry(), M.MetricsRegistry(),
                            M.MetricsRegistry())
        pre = PrefillServer(params, CFG, max_len=64, max_batch=2,
                            seed=seed, registry=regp)
        dec = DecodeServer(decode_batcher, registry=regd)
        router = ServingRouter(
            [f"127.0.0.1:{pre.start()}"],
            decode_replicas=[f"127.0.0.1:{dec.start()}"],
            health_interval_s=0.2, registry=regr)
        return pre, dec, router, regr

    def _run_preempt(self, params, port, prompts, ref, budgets):
        got = {}
        with StreamingClient("127.0.0.1", port) as c:
            r0 = c.submit(prompts[0], budgets[0], request_class="batch")
            r1 = c.submit(prompts[1], budgets[1], request_class="batch")
            # both decode slots must be HELD by batch rows before the
            # interactive admission, or it would just take a free slot
            started = set()
            deadline = time.time() + 60
            while len(started) < 2 and time.time() < deadline:
                for r in (r0, r1):
                    if r in started:
                        continue
                    try:
                        ev = c.next_event(r, timeout=0.02)
                    except queue_mod.Empty:
                        continue
                    assert ev[0] == "tokens", ev
                    got.setdefault(r, []).extend(ev[1])
                    started.add(r)
            assert len(started) == 2, "batch streams never started"
            r2 = c.submit(prompts[2], budgets[2],
                          request_class="interactive")
            for r in (r0, r1, r2):
                while True:
                    ev = c.next_event(r, timeout=60)
                    if ev[0] == "tokens":
                        got.setdefault(r, []).extend(ev[1])
                    elif ev[0] == "retired":
                        assert ev[1] == "budget", (r, ev)
                        break
                    else:
                        raise AssertionError(ev)
        for i, r in enumerate((r0, r1, r2)):
            assert got[r] == ref[i], \
                f"stream {i}: dup/drop across decode-tier preemption"

    def test_decode_preemption_reprefills_identical_greedy(self, params):
        """Both decode slots hold batch rows; an interactive request
        arrives through the prefill tier (class rides the kv meta), the
        decode engine evicts a KV-adopted batch row as 'preempted', and
        the router re-places it through a FRESH prefill with the
        streamed prefix folded in — the resumed stream must equal the
        uninterrupted reference exactly."""
        dec_b = _SlowFetch(params, CFG, batch=2, max_len=64, chunk=2)
        prompts = _prompts(46, (5, 4, 6))
        budgets = (12, 12, 6)
        ref = [_reference(params, p, n)
               for p, n in zip(prompts, budgets)]
        pre, dec, router, regr = self._stack(params, dec_b)
        try:
            self._run_preempt(params, router.start(), prompts, ref,
                              budgets)
            assert regr.counter(
                "tony_router_preempt_requeues_total").value == 1
            assert regr.counter("tony_router_failovers_total").value == 0
        finally:
            router.stop()
            pre.stop()
            dec.stop()

    def test_decode_preemption_reprefills_identical_sampled(self, params):
        kw = dict(batch=2, max_len=64, chunk=2, seed=7,
                  temperature=0.8, top_k=20, top_p=0.9)
        prompts = _prompts(47, (5, 4, 6))
        ref = ContinuousBatcher(params, CFG, **kw).serve(prompts, 12)
        pre, dec, router, regr = self._stack(
            params, _SlowFetch(params, CFG, **kw), seed=7)
        try:
            self._run_preempt(params, router.start(), prompts, ref,
                              (12, 12, 12))
            assert regr.counter(
                "tony_router_preempt_requeues_total").value == 1
        finally:
            router.stop()
            pre.stop()
            dec.stop()

    def test_prefill_orders_waves_by_class_and_sheds(self, params):
        """A gated prefill accumulates a mixed queue; on release the
        wave takes interactive ahead of earlier-arrived batch work, and
        non-interactive admissions past the queue bound are refused
        with BUSY before any prefill compute is spent."""
        class Gated(PrefillServer):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self.gate = threading.Event()
                self.waves = []

            def _take_wave(self):
                self.gate.wait(timeout=60)
                wave = super()._take_wave()
                if wave:
                    self.waves.append([it.cls for it in wave])
                return wave

        regp = M.MetricsRegistry()
        pre = Gated(params, CFG, max_len=64, max_batch=2,
                    max_queue_depth=3, busy_retry_ms=77, registry=regp)
        dec = DecodeServer(ContinuousBatcher(params, CFG, batch=2,
                                             max_len=64, chunk=2),
                           registry=M.MetricsRegistry())
        router = ServingRouter(
            [f"127.0.0.1:{pre.start()}"],
            decode_replicas=[f"127.0.0.1:{dec.start()}"],
            health_interval_s=0.2, registry=M.MetricsRegistry())
        port = router.start()
        prompts = _prompts(48, (4, 5, 4, 5))
        budget = 4
        try:
            with StreamingClient("127.0.0.1", port) as c:
                rids = [c.submit(prompts[0], budget,
                                 request_class="batch"),
                        c.submit(prompts[1], budget,
                                 request_class="batch"),
                        c.submit(prompts[2], budget,
                                 request_class="interactive")]
                deadline = time.time() + 30
                while time.time() < deadline:
                    if pre.stats()["queue_depth"] == 3:
                        break
                    time.sleep(0.01)
                assert pre.stats()["queue_depths"] == {
                    "interactive": 1, "standard": 0, "batch": 2}
                # the bound is reached: a batch admit sheds BEFORE any
                # prefill compute is spent; interactive still queues
                with pytest.raises(ServerBusy) as ei:
                    c.result(c.submit(prompts[3], budget,
                                      request_class="batch"))
                assert ei.value.retry_after_ms == 77
                assert regp.counter("tony_serve_shed_total",
                                    **{"class": "batch"}).value == 1
                rids.append(c.submit(prompts[3], budget,
                                     request_class="interactive"))
                # submit() returns once the router has the request;
                # wait for the ADMIT to land in the prefill queue
                # before opening the gate, or wave 1 races it
                deadline = time.time() + 30
                while time.time() < deadline:
                    if pre.stats()["queue_depth"] == 4:
                        break
                    time.sleep(0.01)
                else:
                    pytest.fail(f"4th admit never queued: {pre.stats()}")
                pre.gate.set()
                for i, r in enumerate(rids):
                    toks, reason = c.result(r, timeout=120)
                    assert toks == _reference(params, prompts[i],
                                              budget), i
                    assert reason == "budget"
            # wave 1 (width 2) took BOTH interactive admissions ahead
            # of the earlier-arrived batch pair
            assert pre.waves[0] == ["interactive", "interactive"], \
                pre.waves
            assert [c for w in pre.waves for c in w].count("batch") == 2
        finally:
            router.stop()
            pre.stop()
            dec.stop()


# ---------------------------------------------------------------------------
# router over the simulated fleet: interactive placement, batch
# re-queue on BUSY, client retry, oracle continuity under preemption
# ---------------------------------------------------------------------------
@pytest.mark.fleet_sim
class TestRouterQoS:
    def _fill_direct(self, addr, n, budget, seed0):
        """Occupy a replica directly (bypassing the router) with batch
        streams: returns (client, seeds, results-dict, threads). Waits
        for each submission to be granted/queued before the next, so a
        bounded replica never sheds its own fill."""
        host, port = addr.split(":")
        c = StreamingClient(host, int(port))
        out, threads, seeds = {}, [], {}

        def pump(rid):
            toks = []
            for delta in c.deltas(rid, timeout=60):
                toks.extend(delta)
            out[rid] = toks

        for i in range(n):
            seed = seed0 + i
            rid = c.submit([seed, 1, 2], budget, request_class="batch")
            seeds[rid] = seed
            t = threading.Thread(target=pump, args=(rid,), daemon=True)
            t.start()
            threads.append(t)
            deadline = time.time() + 30
            while time.time() < deadline:
                st = c.stats()
                if st["active"] + st["queue_depth"] == i + 1 \
                        and st["queue_depth"] == max(
                            0, i + 1 - st["slots"]):
                    break
                time.sleep(0.005)
        return c, seeds, out, threads

    def test_interactive_lands_on_idle_slots(self):
        """With one replica saturated, an interactive admission is
        placed where idle reserved slots exist instead of by the
        generic load key."""
        fleet = SimFleet(2, itl_s=0.02, slots=2, health_interval_s=0.05,
                         registry=M.MetricsRegistry())
        try:
            port = fleet.start()
            a, b = fleet.addrs()
            c, seeds, out, threads = self._fill_direct(a, 2, 24, 500)
            try:
                deadline = time.time() + 30
                while time.time() < deadline:
                    reps = fleet.router.stats()["replicas"]
                    if reps[a]["reported_load"] >= 2:
                        break
                    time.sleep(0.01)
                with StreamingClient("127.0.0.1", port) as rc:
                    toks, reason = rc.result(rc.submit(
                        [900, 1, 2], 4, request_class="interactive"))
                assert toks == [sim_token(900, p) for p in range(4)]
                # it landed on the idle replica: the saturated one
                # (whose rows are batch, hence preemptable) was never
                # preempted
                assert fleet.replicas[a].preemptions == 0
                for t in threads:
                    t.join(timeout=60)
                for rid, seed in seeds.items():
                    assert out[rid] == [sim_token(seed, p)
                                        for p in range(24)]
            finally:
                c.close()
        finally:
            fleet.stop()

    def test_batch_requeue_cap_then_busy_interactive_preempts(self):
        """Every replica sheds batch work: the router re-places a shed
        batch session up to the cap (bouncing between replicas), then
        forwards the terminal BUSY with the hint intact. An interactive
        request submitted into the SAME overload preempts a batch row
        and completes fast — and every preempted direct stream still
        finishes with exactly the oracle tokens."""
        reg = M.MetricsRegistry()
        fleet = SimFleet(2, itl_s=0.02, slots=1, max_queue_depth=1,
                         busy_retry_ms=60, health_interval_s=0.05,
                         registry=reg)
        try:
            port = fleet.start()
            a, b = fleet.addrs()
            ca, seeds_a, out_a, th_a = self._fill_direct(a, 2, 24, 600)
            cb, seeds_b, out_b, th_b = self._fill_direct(b, 2, 24, 700)
            try:
                with StreamingClient("127.0.0.1", port) as rc:
                    with pytest.raises(ServerBusy) as ei:
                        rc.result(rc.submit([910, 1, 2], 4,
                                            request_class="batch"))
                    assert ei.value.retry_after_ms == 60
                    assert reg.counter(
                        "tony_router_busy_requeues_total").value == 3
                    toks, _ = rc.result(rc.submit(
                        [920, 1, 2], 4, request_class="interactive"))
                    assert toks == [sim_token(920, p) for p in range(4)]
                for t in th_a + th_b:
                    t.join(timeout=60)
                for seeds, out in ((seeds_a, out_a), (seeds_b, out_b)):
                    for rid, seed in seeds.items():
                        assert out[rid] == [sim_token(seed, p)
                                            for p in range(24)], \
                            "dup/drop across sim preemption"
                assert sum(r.preemptions
                           for r in fleet.replicas.values()) >= 1
            finally:
                ca.close()
                cb.close()
        finally:
            fleet.stop()

    def test_client_retry_self_heals_on_single_replica(self):
        """One replica, zero spare capacity: the router cannot re-queue
        (nowhere to exclude to), so the client's own retry budget is
        what heals the request once capacity frees."""
        fleet = SimFleet(1, itl_s=0.01, slots=1, max_queue_depth=1,
                         busy_retry_ms=30, health_interval_s=0.05,
                         registry=M.MetricsRegistry())
        try:
            port = fleet.start()
            (a,) = fleet.addrs()
            c, seeds, out, threads = self._fill_direct(a, 2, 10, 800)
            try:
                with StreamingClient("127.0.0.1", port) as rc:
                    toks, reason = rc.result(
                        rc.submit([930, 1, 2], 5, request_class="batch",
                                  retries=10), timeout=60)
                assert toks == [sim_token(930, p) for p in range(5)]
                assert reason == "budget"
                for t in threads:
                    t.join(timeout=60)
                for rid, seed in seeds.items():
                    assert out[rid] == [sim_token(seed, p)
                                        for p in range(10)]
            finally:
                c.close()
        finally:
            fleet.stop()

    def test_router_exports_per_class_series(self):
        reg = M.MetricsRegistry()
        fleet = SimFleet(1, itl_s=0.005, slots=4, health_interval_s=0.05,
                         registry=reg)
        try:
            port = fleet.start()
            with StreamingClient("127.0.0.1", port) as rc:
                toks, _ = rc.result(rc.submit(
                    [940, 1, 2], 4, request_class="interactive"))
            assert toks == [sim_token(940, p) for p in range(4)]
            ttft = reg.histogram("tony_serve_ttft_seconds",
                                 **{"class": "interactive"})
            itl = reg.histogram("tony_serve_intertoken_seconds",
                                **{"class": "interactive"})
            assert ttft.count == 1
            assert itl.count >= 1
            # untouched classes exist but stay empty
            assert reg.histogram("tony_serve_ttft_seconds",
                                 **{"class": "batch"}).count == 0
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# autoscale: interactive pressure pages capacity in; batch backlog
# alone never does
# ---------------------------------------------------------------------------
class _ClassedRouter:
    """stats()-only stand-in: a fixed 2-replica fleet whose per-replica
    reported_load/queue_depths are scripted per tick."""

    def __init__(self, script):
        self._script = list(script)
        self._i = 0
        self.added, self.removed, self.drained = [], [], []

    def stats(self):
        load, depths, active = self._script[min(
            self._i, len(self._script) - 1)]
        self._i += 1
        n = 2 + len(self.added) - len(self.removed)
        return {
            "active": active, "slots": 4 * n,
            "replicas": {f"r{i}": {"up": 1, "reported_load": load,
                                   "queue_depths": dict(depths),
                                   "assigned": active // max(n, 1),
                                   "draining": False}
                         for i in range(n)},
        }

    def add_replicas(self, addrs, role=None):
        self.added.extend(addrs)

    def remove_replica(self, addr):
        self.removed.append(addr)

    def drain(self, addr, timeout_s=None):
        self.drained.append(addr)
        return {"drained": True, "migrated": 0}


class _CountingProvider(CapacityProvider):
    def __init__(self):
        self.grown, self.released = 0, []

    def grow(self, n):
        addrs = [f"new{self.grown + i}" for i in range(n)]
        self.grown += n
        return addrs

    def release(self, addrs):
        self.released.extend(addrs)


class TestAutoscaleQoS:
    def test_batch_backlog_alone_never_scales_up(self):
        """48 batch requests queued per replica, slots busy — deliberate
        oversubscription, not SLO pressure: 20 ticks, zero actions."""
        script = [(52.0, {"interactive": 0, "standard": 0, "batch": 48},
                   8)] * 20
        router = _ClassedRouter(script)
        provider = _CountingProvider()
        ctrl = FleetController(router, provider, hysteresis_ticks=3,
                               cooldown_ticks=5,
                               up_queue_per_replica=6.0,
                               registry=M.MetricsRegistry())
        actions = [ctrl.tick() for _ in range(20)]
        assert set(actions) == {"hold"}, actions
        assert provider.grown == 0 and not router.drained

    def test_interactive_pressure_scales_up(self):
        """The SAME total backlog, but interactive: scale-up fires on
        the third consecutive tick, exactly the classless discipline."""
        script = [(52.0, {"interactive": 48, "standard": 0, "batch": 0},
                   8)] * 20
        router = _ClassedRouter(script)
        provider = _CountingProvider()
        reg = M.MetricsRegistry()
        ctrl = FleetController(router, provider, hysteresis_ticks=3,
                               cooldown_ticks=10,
                               up_queue_per_replica=6.0, registry=reg)
        actions = [ctrl.tick() for _ in range(12)]
        assert actions.count("up") == 1, actions
        assert actions.index("up") == 2
        assert reg.counter("tony_fleet_scale_ups_total").value == 1

    def test_classless_replicas_keep_aggregate_signal(self):
        """Replicas that never report queue_depths (old engines) fall
        back to reported_load — mixed fleets keep scaling."""
        script = [(8.0, {}, 8)] * 12
        router = _ClassedRouter(script)
        provider = _CountingProvider()
        ctrl = FleetController(router, provider, hysteresis_ticks=3,
                               cooldown_ticks=10,
                               up_queue_per_replica=6.0,
                               registry=M.MetricsRegistry())
        actions = [ctrl.tick() for _ in range(6)]
        assert actions.count("up") == 1, actions


# ---------------------------------------------------------------------------
# the bench-arm pins: 2x overload, classed vs classless
# ---------------------------------------------------------------------------
@pytest.mark.fleet_sim
class TestQosBenchArm:
    def test_qos_arm_pins(self):
        import bench
        out = bench._qos_arm()
        # interactive p99 TTFT holds under 2x overload while the
        # classless baseline blows through it
        assert out["serving_qos_interactive_ttft_p99_vs_classless"] \
            >= 2, out
        # every preemption eviction resumed with zero dup/drop tokens
        assert out["serving_qos_preempt_token_gap"] == 0, out
        assert out["serving_qos_preemptions"] >= 1, out

    @pytest.mark.slow
    def test_qos_arm_survives_wan_hop(self):
        import bench
        out = bench._qos_arm(one_way_s=0.02)
        assert out["serving_qos_interactive_ttft_p99_vs_classless"] \
            >= 2, out
        assert out["serving_qos_preempt_token_gap"] == 0, out
