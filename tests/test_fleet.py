"""Live fleet operations on the simulated fleet (SimFleet: a real
ServingRouter and real sockets in front of oracle-token replicas — no
model stack, so a hundred replicas fit in one process).

Pins, tier-1 scope:

- planned drain under live load: zero duplicated/dropped tokens
  (strict equality against the ``sim_token`` oracle) and EXACTLY one
  terminal frame per session, including a 3-at-once drain storm;
- drain edge cases: zero-session drain returns immediately; drain
  racing the target's crash falls back to crash-failover with the same
  zero dup/drop guarantee; client CANCEL mid-migration yields exactly
  one terminal frame;
- ``stop()`` racing a drain sweeps every session to a client-visible
  terminal and double-stop is idempotent;
- rolling weight upgrade: version-pinned migration tier to tier, token
  continuity per session, old tier retired;
- FleetController: no flapping on an oscillating load signal
  (hysteresis + cooldown), real scale-up/down against SimProvider;
- the bench arm's dup/drop gap == 0 and drain wall bounded.

The 100-replica storm (drain 30 at once + seeded chaos crashes, p99
placement latency bound off ``tony_router_place_seconds``) is @slow.
"""

import os
import queue
import random
import sys
import threading
import time

import pytest

from tony_tpu.runtime import metrics as M
from tony_tpu.runtime.metrics import MetricsRegistry
from tony_tpu.serving.client import StreamingClient
from tony_tpu.serving.fleet import CapacityProvider, FleetController
from tony_tpu.serving.simfleet import SimFleet, SimProvider, sim_token

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)          # for `import bench` (repo-root script)

pytestmark = pytest.mark.fleet_sim


def _oracle(seed, n):
    return [sim_token(seed, p) for p in range(n)]


def _pump(client, rid, out, timeout=60.0):
    """Collect every event for ``rid`` until its FIRST terminal frame,
    then linger briefly to catch any duplicate terminal (there must be
    none). Stores ``(tokens, terminals)``."""
    toks, terminals = [], []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            ev = client.next_event(rid, timeout=5.0)
        except queue.Empty:
            continue
        if ev[0] == "tokens":
            toks.extend(ev[1])
        else:
            terminals.append(ev)
            break
    # duplicate-terminal watch: nothing else may arrive for this rid
    try:
        terminals.append(client.next_event(rid, timeout=0.2))
    except queue.Empty:
        pass
    out[rid] = (toks, terminals)


def _launch_streams(client, n, max_new, out, prompt_len=4):
    seeds, threads = {}, []
    for i in range(n):
        seed = 1000 + 17 * i
        rid = client.submit([seed] + list(range(1, prompt_len)), max_new)
        seeds[rid] = seed
        t = threading.Thread(target=_pump, args=(client, rid, out),
                             daemon=True)
        t.start()
        threads.append(t)
    return seeds, threads


def _wait_spread(client, deadline_s=30.0):
    """Block until every replica holds at least one session (so drains
    migrate genuinely mid-flight streams)."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        reps = client.stats()["replicas"]
        if reps and all(r["assigned"] > 0 for r in reps.values()):
            return reps
        time.sleep(0.01)
    raise AssertionError("streams never spread across the fleet")


def _assert_exact(out, seeds, max_new, reason="budget"):
    for rid, (toks, terminals) in out.items():
        assert len(terminals) == 1, \
            f"rid {rid}: expected exactly one terminal, got {terminals}"
        assert terminals[0][0] == "retired" and terminals[0][1] == reason, \
            f"rid {rid}: unexpected terminal {terminals[0]}"
        assert toks == _oracle(seeds[rid], max_new), \
            f"rid {rid}: token dup/drop across migration"


class TestDrainUnderLoad:
    def test_drain_storm_zero_dup_drop(self):
        """Drain 3 of 8 replicas AT ONCE while 16 sessions stream:
        every session retires with the exact oracle token list and one
        terminal frame; the drained replicas end fenced and empty."""
        reg = MetricsRegistry()
        fleet = SimFleet(8, itl_s=0.002, slots=16, registry=reg)
        out = {}
        try:
            port = fleet.start()
            with StreamingClient("127.0.0.1", port) as client:
                seeds, threads = _launch_streams(client, 16, 80, out)
                reps = _wait_spread(client)
                victims = sorted(reps, key=lambda a: -reps[a]["assigned"])[:3]
                results = {}

                def do_drain(addr):
                    results[addr] = client.drain_replica(addr)

                drains = [threading.Thread(target=do_drain, args=(a,),
                                           daemon=True) for a in victims]
                for d in drains:
                    d.start()
                for d in drains:
                    d.join(timeout=60)
                for addr, res in results.items():
                    assert res.get("drained"), f"{addr}: {res}"
                for t in threads:
                    t.join(timeout=60)
                _assert_exact(out, seeds, 80)
                reps = client.stats()["replicas"]
                for addr in victims:
                    assert reps[addr]["draining"], addr
                    assert reps[addr]["assigned"] == 0, addr
            assert sum(r["migrated"] for r in results.values()) > 0
            assert reg.counter("tony_router_migrations_total").value > 0
            assert reg.counter("tony_router_drains_total").value == 3
        finally:
            fleet.stop()

    def test_zero_session_drain_immediate(self):
        fleet = SimFleet(2, registry=MetricsRegistry())
        try:
            fleet.start()
            addr = fleet.addrs()[0]
            t0 = time.monotonic()
            res = fleet.router.drain(addr)
            assert res["drained"] and res["migrated"] == 0
            assert time.monotonic() - t0 < 2.0
            # fence holds after the drain: new admissions avoid it
            assert fleet.router.stats()["replicas"][addr]["draining"]
            fleet.router.undrain(addr)
            assert not fleet.router.stats()["replicas"][addr]["draining"]
        finally:
            fleet.stop()

    def test_drain_racing_target_crash(self):
        """The drain target crashes mid-drain: its sessions fall back
        to crash-failover (rng-offset re-placement) and still retire
        with the exact oracle tokens and one terminal each."""
        reg = MetricsRegistry()
        fleet = SimFleet(4, itl_s=0.004, slots=16, registry=reg)
        out = {}
        try:
            port = fleet.start()
            with StreamingClient("127.0.0.1", port) as client:
                seeds, threads = _launch_streams(client, 8, 60, out)
                reps = _wait_spread(client)
                victim = max(reps, key=lambda a: reps[a]["assigned"])
                res_box = {}

                def do_drain():
                    res_box["res"] = client.drain_replica(victim)

                d = threading.Thread(target=do_drain, daemon=True)
                d.start()
                fleet.kill(victim)
                d.join(timeout=60)
                for t in threads:
                    t.join(timeout=60)
                _assert_exact(out, seeds, 60)
        finally:
            fleet.stop()

    def test_cancel_mid_migration_single_terminal(self):
        """Client CANCEL while a migration is in flight: exactly one
        terminal frame, no stray tokens after it."""
        fleet = SimFleet(3, itl_s=0.01, slots=8,
                         registry=MetricsRegistry())
        try:
            port = fleet.start()
            with StreamingClient("127.0.0.1", port) as client:
                rid = client.submit([4242, 1, 2, 3], 400)
                # wait for first tokens so the migration snapshots a
                # non-empty stream
                ev = client.next_event(rid, timeout=30)
                assert ev[0] == "tokens"
                client.migrate(rid)
                client.cancel(rid)
                terminals = []
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    try:
                        ev = client.next_event(rid, timeout=1.0)
                    except queue.Empty:
                        if terminals:
                            break
                        continue
                    if ev[0] != "tokens":
                        terminals.append(ev)
                assert len(terminals) == 1, terminals
                assert terminals[0][:2] == ("retired", "cancelled")
        finally:
            fleet.stop()

    def test_stop_racing_drain_sweeps_sessions(self):
        """router.stop() while a drain migrates live sessions: every
        session gets exactly one client-visible terminal, the drain
        call reports not-drained, and a second stop() is a no-op."""
        fleet = SimFleet(4, itl_s=0.01, slots=8,
                         registry=MetricsRegistry())
        out = {}
        try:
            port = fleet.start()
            with StreamingClient("127.0.0.1", port) as client:
                seeds, threads = _launch_streams(client, 8, 400, out)
                reps = _wait_spread(client)
                victim = max(reps, key=lambda a: reps[a]["assigned"])
                res_box = {}

                def do_drain():
                    try:
                        res_box["res"] = fleet.router.drain(victim)
                    except Exception as e:   # noqa: BLE001
                        res_box["err"] = e

                d = threading.Thread(target=do_drain, daemon=True)
                d.start()
                time.sleep(0.05)
                fleet.router.stop()
                fleet.router.stop()          # idempotent double-stop
                d.join(timeout=30)
                assert "err" not in res_box, res_box
                for t in threads:
                    t.join(timeout=30)
                for rid, (_, terminals) in out.items():
                    # exactly one protocol terminal; the client may
                    # additionally synthesize a transport-loss error
                    # once the router's listener goes away
                    assert terminals and terminals[0][0] == "error", \
                        (rid, terminals)
                    for extra in terminals[1:]:
                        assert extra == ("error",
                                         "connection closed by server"), \
                            (rid, terminals)
        finally:
            fleet.stop()


class TestRollingUpgrade:
    def test_upgrade_token_continuity(self):
        """Stand up a v2 tier, drain the v1 tier replica by replica:
        every in-flight session keeps its exact token stream, the old
        tier is retired, and new sessions land on v2."""
        reg = MetricsRegistry()
        fleet = SimFleet(2, itl_s=0.004, slots=16,
                         weights_version="v1", registry=reg)
        out = {}
        try:
            port = fleet.start()
            ctrl = FleetController(fleet.router, SimProvider(fleet),
                                   registry=reg)
            with StreamingClient("127.0.0.1", port) as client:
                seeds, threads = _launch_streams(client, 6, 80, out)
                _wait_spread(client)
                old = fleet.router.stats()["replicas"]
                new_addrs = [fleet.spawn(weights_version="v2")
                             for _ in range(2)]
                results = ctrl.rolling_upgrade(new_addrs)
                for addr, res in results.items():
                    assert res.get("drained"), (addr, res)
                for t in threads:
                    t.join(timeout=60)
                _assert_exact(out, seeds, 80)
                reps = client.stats()["replicas"]
                assert set(reps) == set(new_addrs)
                assert all(r["weights_version"] == "v2"
                           for r in reps.values())
                assert set(old).isdisjoint(reps)
                # a fresh session lands on the new tier and streams
                rid = client.submit([7, 1, 2, 3], 5)
                toks, reason = client.result(rid)
                assert reason == "budget" and toks == _oracle(7, 5)
            assert reg.counter("tony_fleet_upgrades_total").value == 1
        finally:
            fleet.stop()


class TestWarmScaleUp:
    def test_seeder_crash_mid_ship_falls_back_to_storage(self):
        """Chaos pin for the warm path: the upgrade's ONLY seeder is
        killed mid-ship. The fan-out condemns it, mints a fresh seeder
        off a storage load, warms the whole new tier anyway, and the
        upgrade completes with exact token streams — a crashed seeder
        degrades to the old cold path, never a wedged fleet."""
        from tony_tpu.serving.simfleet import SimWarmer

        reg = MetricsRegistry()
        fleet = SimFleet(2, itl_s=0.004, slots=16,
                         weights_version="v1", registry=reg)
        out = {}
        try:
            port = fleet.start()
            # the doomed seeder: warm, not routed, killed mid-ship
            doomed = fleet.spawn(weights_version="v2")
            warmer = SimWarmer(fleet, "v2", seeders=[doomed],
                               ship_s=0.3, load_s=0.05)
            ctrl = FleetController(fleet.router, SimProvider(fleet),
                                   registry=reg, warmer=warmer)
            with StreamingClient("127.0.0.1", port) as client:
                seeds, threads = _launch_streams(client, 4, 60, out)
                _wait_spread(client)
                new_addrs = [fleet.spawn(weights_version=None)
                             for _ in range(4)]
                # the first ship holds its ship_s floor for 0.3s; the
                # seeder dies 0.1s in — a genuine crash mid-transfer
                killer = threading.Timer(0.1, fleet.kill, args=(doomed,))
                killer.start()
                results = ctrl.rolling_upgrade(new_addrs)
                killer.join()
                for addr, res in results.items():
                    assert res.get("drained"), (addr, res)
                warm = ctrl.last_warm
                assert warm is not None and not warm["failed"], warm
                # the crash cost exactly one storage load, then the
                # minted seeder fanned out to the rest
                assert warmer.loads == 1
                assert len(warm["fallback"]) == 1
                assert len(warm["warmed"]) == 3
                for t in threads:
                    t.join(timeout=60)
                _assert_exact(out, seeds, 60)
                reps = client.stats()["replicas"]
                assert set(reps) == set(new_addrs)      # doomed never routed
                assert all(r["weights_version"] == "v2"
                           for r in reps.values())
                # the fleet is live: a fresh session streams to budget
                rid = client.submit([7, 1, 2, 3], 5)
                toks, reason = client.result(rid)
                assert reason == "budget" and toks == _oracle(7, 5)
        finally:
            fleet.stop()


class _ScriptedRouter:
    """stats()-only stand-in driving FleetController.tick()
    deterministically: each tick() observes the next scripted
    (load, active) pair over a fixed 4-replica, 64-slot fleet."""

    def __init__(self, script):
        self._script = list(script)
        self._i = 0
        self.added, self.removed, self.drained = [], [], []

    def stats(self):
        load, active = self._script[min(self._i,
                                        len(self._script) - 1)]
        self._i += 1
        n = 4 + len(self.added) - len(self.removed)
        return {
            "active": active, "slots": 16 * n,
            "replicas": {f"r{i}": {"up": 1, "reported_load": load,
                                   "assigned": active // max(n, 1),
                                   "draining": False}
                         for i in range(n)},
        }

    def add_replicas(self, addrs, role=None):
        self.added.extend(addrs)

    def remove_replica(self, addr):
        self.removed.append(addr)

    def drain(self, addr, timeout_s=None):
        self.drained.append(addr)
        return {"drained": True, "migrated": 0}


class _CountingProvider(CapacityProvider):
    def __init__(self):
        self.grown, self.released = 0, []

    def grow(self, n):
        addrs = [f"new{self.grown + i}" for i in range(n)]
        self.grown += n
        return addrs

    def release(self, addrs):
        self.released.extend(addrs)


class TestFleetController:
    def test_no_flap_on_oscillating_load(self):
        """Load that alternates above/below the scale-up threshold
        every tick must never trigger an action: the hysteresis
        counter resets on each dip."""
        script = [(8.0, 60) if i % 2 == 0 else (1.0, 30)
                  for i in range(40)]
        router = _ScriptedRouter(script)
        provider = _CountingProvider()
        ctrl = FleetController(router, provider, hysteresis_ticks=3,
                               cooldown_ticks=5,
                               registry=MetricsRegistry())
        actions = [ctrl.tick() for _ in range(40)]
        assert set(actions) == {"hold"}, actions
        assert provider.grown == 0 and not router.drained

    def test_sustained_pressure_scales_once_then_cools(self):
        """Sustained over-threshold load scales up exactly once, then
        the cooldown window absorbs the (still high) signal."""
        router = _ScriptedRouter([(8.0, 60)] * 20)
        provider = _CountingProvider()
        reg = MetricsRegistry()
        ctrl = FleetController(router, provider, hysteresis_ticks=3,
                               cooldown_ticks=10, registry=reg)
        actions = [ctrl.tick() for _ in range(12)]
        assert actions.count("up") == 1, actions
        assert actions.index("up") == 2      # 3rd consecutive tick
        assert provider.grown == 1
        assert reg.counter("tony_fleet_scale_ups_total").value == 1

    def test_sustained_idle_scales_down_via_drain(self):
        router = _ScriptedRouter([(0.5, 2)] * 10)
        provider = _CountingProvider()
        reg = MetricsRegistry()
        ctrl = FleetController(router, provider, min_replicas=1,
                               hysteresis_ticks=3, cooldown_ticks=10,
                               down_utilization=0.3, registry=reg)
        actions = [ctrl.tick() for _ in range(4)]
        assert actions.count("down") == 1, actions
        # scale-down path = drain THEN retire THEN release
        assert len(router.drained) == 1
        assert router.removed == router.drained
        assert provider.released == router.drained
        assert reg.counter("tony_fleet_scale_downs_total").value == 1

    def test_autoscale_against_simfleet(self):
        """Real loop: SimProvider spawns a sim replica on scale-up and
        reaps it on scale-down; the router picks both up live."""
        reg = MetricsRegistry()
        fleet = SimFleet(2, itl_s=0.005, slots=4, registry=reg)
        try:
            port = fleet.start()
            ctrl = FleetController(
                fleet.router, SimProvider(fleet), min_replicas=2,
                max_replicas=3, up_queue_per_replica=2.0,
                down_utilization=0.3, hysteresis_ticks=2,
                cooldown_ticks=2, drain_timeout_s=30, registry=reg)
            with StreamingClient("127.0.0.1", port) as client:
                rids = [client.submit([50 + i, 1], 300) for i in range(8)]
                # let STATS report the load
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    if ctrl._observe()[1] > 2.0:
                        break
                    time.sleep(0.05)
                actions = [ctrl.tick() for _ in range(3)]
                assert "up" in actions, actions
                assert len(fleet.router.stats()["replicas"]) == 3
                assert len(fleet.addrs()) == 3
                for rid in rids:
                    client.cancel(rid)
                # idle now: wait for STATS to catch up, then tick down
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    n, load, util = ctrl._observe()
                    if util < 0.3 and load < 2.0:
                        break
                    time.sleep(0.05)
                actions = []
                for _ in range(8):
                    actions.append(ctrl.tick())
                    if "down" in actions:
                        break
                assert "down" in actions, actions
                assert len(fleet.router.stats()["replicas"]) == 2
                assert len(fleet.addrs()) == 2
        finally:
            fleet.stop()


class TestBenchArm:
    def test_fleet_arm_pins(self):
        import bench
        out = bench._fleet_arm()
        assert out["serving_migration_token_gap"] == 0
        assert out["serving_drain_migrated"] >= 1
        # migration is re-prefill-on-survivor: the drain wall is
        # placement-bounded, never stream-length-bounded
        assert out["serving_drain_wall_s"] < 10.0


@pytest.mark.slow
class TestStorm:
    def test_100_replica_drain_storm_with_chaos(self):
        """100 replicas, 150 live sessions; drain 30 replicas at once
        while a seeded schedule crashes 5 more. Every session ends in
        exactly one terminal frame; sessions that retire on budget
        match the oracle exactly; p99 placement latency (from the
        ``tony_router_place_seconds`` buckets) stays bounded."""
        rng = random.Random(0xF1EE7)
        reg = MetricsRegistry()
        fleet = SimFleet(100, itl_s=0.005, slots=8,
                         health_interval_s=0.2, registry=reg)
        out = {}
        try:
            port = fleet.start()
            with StreamingClient("127.0.0.1", port) as client:
                seeds, threads = _launch_streams(client, 150, 60, out)
                # all sessions placed (spread need not be perfectly
                # even at this scale — placement keys lag STATS)
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    reps = client.stats()["replicas"]
                    if sum(r["assigned"] for r in reps.values()) >= 150:
                        break
                    time.sleep(0.02)
                reps = client.stats()["replicas"]
                by_load = sorted(reps, key=lambda a: -reps[a]["assigned"])
                victims = by_load[:30]
                crash = rng.sample(by_load[30:], 5)
                results = {}

                def do_drain(addr):
                    results[addr] = client.drain_replica(addr,
                                                         timeout_s=120)

                drains = [threading.Thread(target=do_drain, args=(a,),
                                           daemon=True)
                          for a in victims]
                for d in drains:
                    d.start()
                for addr in crash:
                    time.sleep(rng.uniform(0.0, 0.05))
                    fleet.kill(addr)
                for d in drains:
                    d.join(timeout=180)
                assert all(r.get("drained") for r in results.values()), \
                    {a: r for a, r in results.items()
                     if not r.get("drained")}
                for t in threads:
                    t.join(timeout=180)
                assert len(out) == 150
                budget_done = 0
                for rid, (toks, terminals) in out.items():
                    assert len(terminals) == 1, (rid, terminals)
                    kind = terminals[0][0]
                    assert kind in ("retired", "error"), terminals[0]
                    if kind == "retired" and terminals[0][1] == "budget":
                        budget_done += 1
                        assert toks == _oracle(seeds[rid], 60), \
                            f"rid {rid}: dup/drop under storm"
                # chaos may error a handful of sessions (both halves
                # dead mid-migration); the vast majority must complete
                assert budget_done >= 140, budget_done
            h = reg.histogram("tony_router_place_seconds")
            assert h.count >= 150
            p99 = M.histogram_quantile(h, 0.99)
            assert p99 <= 2.5, (p99, h.cumulative())
        finally:
            fleet.stop()
