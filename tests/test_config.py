"""Config-layer tests.

Mirrors the reference's config hygiene + parsing coverage:
- TestTonyConfigurationFields.java:15-63 (keys ⇄ defaults bijection)
- Utils.parseContainerRequests / parseMemoryString unit coverage (TestUtils.java)
"""

import os

import pytest

from tony_tpu.conf import keys as K
from tony_tpu.conf.config import (TonyConfig, parse_cli_confs,
                                  parse_memory_string, read_conf_file)


def test_keys_defaults_bijection():
    """Every static *_KEY constant has a default and vice versa (the
    TestTonyConfigurationFields analog). Enforced by tonylint TL008 —
    this wrapper keeps the check in tier-1 under its historical name."""
    from tony_tpu.devtools import lint

    declared, defaults = lint.config_key_constants()
    assert declared and defaults
    findings = [f for f in lint.check_observability(facets=("config",))
                if "out of sync" in f.message]
    assert not findings, "\n".join(f.message for f in findings)


def test_parse_memory_string():
    assert parse_memory_string("2g") == 2048
    assert parse_memory_string("2048m") == 2048
    assert parse_memory_string("2048") == 2048
    assert parse_memory_string("1t") == 1024 * 1024
    assert parse_memory_string("512M") == 512
    with pytest.raises(ValueError):
        parse_memory_string("lots")


def test_job_type_discovery():
    conf = TonyConfig({
        "tony.worker.instances": "4",
        "tony.ps.instances": "1",
        "tony.evaluator.instances": "1",
        "tony.application.name": "x",       # must not be treated as a job type
        "tony.task.instances": "9",         # reserved word, not a job type
    })
    assert conf.job_types() == ["evaluator", "ps", "worker"]


def test_task_requests_resources_and_priorities():
    conf = TonyConfig({
        "tony.worker.instances": "2",
        "tony.worker.memory": "4g",
        "tony.worker.vcores": "2",
        "tony.worker.tpus": "4",
        "tony.worker.tpu.topology": "2x2",
        "tony.worker.env": "A=1,B=2",
        "tony.ps.instances": "1",
    })
    reqs = conf.task_requests()
    assert set(reqs) == {"worker", "ps"}
    w = reqs["worker"]
    assert (w.instances, w.memory_mb, w.vcores, w.tpus, w.tpu_topology) == \
        (2, 4096, 2, 4, "2x2")
    assert w.env == {"A": "1", "B": "2"}
    assert reqs["ps"].memory_mb == 2048  # per-type default
    # unique priority per job type (Utils.java:330-336)
    assert reqs["worker"].priority != reqs["ps"].priority


def test_zero_instance_job_types_skipped():
    conf = TonyConfig({"tony.worker.instances": "0"})
    assert conf.task_requests() == {}


def test_multi_slice_topology_validation():
    base = {
        "tony.worker.slices": "2",
        "tony.worker.tpu.topology": "4x4",   # v5e: 16 chips = 2 hosts
        "tony.tpu.accelerator-type": "v5litepod",
    }
    ok = TonyConfig({**base, "tony.worker.instances": "4"})
    w = ok.task_requests()["worker"]
    assert (w.instances, w.slices) == (4, 2)

    bad = TonyConfig({**base, "tony.worker.instances": "2"})
    with pytest.raises(ValueError, match="tony.worker.instances=4"):
        bad.task_requests()


def test_slices_must_divide_instances():
    conf = TonyConfig({"tony.worker.instances": "3",
                       "tony.worker.slices": "2"})
    with pytest.raises(ValueError, match="not divisible"):
        conf.task_requests()
    conf = TonyConfig({"tony.worker.instances": "2",
                       "tony.worker.slices": "0"})
    with pytest.raises(ValueError, match="slices must be"):
        conf.task_requests()


def test_mesh_dcn_axes():
    conf = TonyConfig({"tony.application.mesh.dcn": "dp=2"})
    assert conf.mesh_dcn_axes() == {"dp": 2}
    assert TonyConfig().mesh_dcn_axes() == {}


def test_dcn_validated_at_parse_time():
    """Bad DCN configs fail the submit, not every task host later."""
    base = {"tony.worker.instances": "4", "tony.worker.slices": "2"}
    with pytest.raises(ValueError, match="explicit positive"):
        TonyConfig({**base, "tony.application.mesh.dcn": "dp=-1"}
                   ).task_requests()
    with pytest.raises(ValueError, match="must equal the slice count"):
        TonyConfig({**base, "tony.application.mesh.dcn": "dp=4"}
                   ).task_requests()
    with pytest.raises(ValueError, match="no job type"):
        TonyConfig({"tony.worker.instances": "2",
                    "tony.application.mesh.dcn": "dp=2"}).task_requests()
    # the matching config passes
    TonyConfig({**base, "tony.application.mesh.dcn": "dp=2"}).task_requests()


def test_untracked_job_types_default_ps():
    conf = TonyConfig()
    assert not conf.is_job_type_tracked("ps")
    assert conf.is_job_type_tracked("worker")
    conf.set(K.APPLICATION_UNTRACKED_KEY, "ps,evaluator")
    assert not conf.is_job_type_tracked("evaluator")


def test_layering_precedence(tmp_path):
    """defaults → conf file → CLI overrides → site (TonyClient.java:364-380)."""
    job = tmp_path / "tony.xml"
    job.write_text(
        "<configuration>"
        "<property><name>tony.application.name</name><value>from-job</value></property>"
        "<property><name>tony.worker.instances</name><value>2</value></property>"
        "<property><name>tony.am.retry-count</name><value>1</value></property>"
        "</configuration>")
    site_dir = tmp_path / "confdir"
    site_dir.mkdir()
    (site_dir / "tony-site.xml").write_text(
        "<configuration>"
        "<property><name>tony.am.retry-count</name><value>7</value></property>"
        "</configuration>")
    conf = TonyConfig.load(str(job),
                           cli_overrides={"tony.application.name": "from-cli"},
                           conf_dir=str(site_dir))
    assert conf.get("tony.application.name") == "from-cli"      # CLI beats job file
    assert conf.get_int("tony.am.retry-count") == 7             # site wins last
    assert conf.get_int("tony.worker.instances") == 2           # job file kept
    assert conf.get(K.APPLICATION_FRAMEWORK_KEY) == "jax"       # default kept


def test_xml_roundtrip_and_kv_files(tmp_path):
    conf = TonyConfig({"tony.worker.instances": "3", "tony.application.mesh": "dp=2,tp=4"})
    out = tmp_path / "tony-final.xml"
    conf.write_xml(str(out))
    back = TonyConfig(read_conf_file(str(out)), load_defaults=False)
    assert back.as_dict() == conf.as_dict()

    kv = tmp_path / "job.conf"
    kv.write_text("# comment\ntony.worker.instances = 5\n\ntony.ps.instances=1\n")
    d = read_conf_file(str(kv))
    assert d == {"tony.worker.instances": "5", "tony.ps.instances": "1"}


def test_mesh_axes_and_cli_confs():
    conf = TonyConfig({"tony.application.mesh": "dp=2, tp=2, sp=2"})
    assert conf.mesh_axes() == {"dp": 2, "tp": 2, "sp": 2}
    assert parse_cli_confs(["a=1", "b=x=y"]) == {"a": "1", "b": "x=y"}
    with pytest.raises(ValueError):
        parse_cli_confs(["nope"])


def test_site_via_env(tmp_path, monkeypatch):
    site_dir = tmp_path / "cd"
    site_dir.mkdir()
    (site_dir / "tony-site.xml").write_text(
        "<configuration><property><name>tony.scheduler.backend</name>"
        "<value>tpu</value></property></configuration>")
    monkeypatch.setenv("TONY_CONF_DIR", str(site_dir))
    conf = TonyConfig.load(None)
    assert conf.get(K.SCHEDULER_BACKEND_KEY) == "tpu"


def test_config_reference_doc_covers_every_key():
    """docs/configuration.md must document every static key (and every
    dynamic per-job-type suffix) — the doc-side half of the keys⇄defaults
    bijection (reference: TestTonyConfigurationFields). Enforced by
    tonylint TL008."""
    from tony_tpu.devtools import lint

    findings = [f for f in lint.check_observability(facets=("config",))
                if "out of sync" not in f.message]
    assert not findings, "\n".join(f.message for f in findings)
