"""Tests for the workflow-scheduler jobtype (tony-azkaban analog) and
version-info injection."""

import os
import sys

import pytest

from tony_tpu.conf.config import TonyConfig
from tony_tpu.workflow.jobtype import TonyJob, parse_properties


def _props(**extra):
    props = {
        "executes": "python train.py",
        "src_dir": "src",
        "tony.worker.instances": "2",
        "tony.application.framework": "jax",
        "worker_env.DATA_DIR": "/data",
        "worker_env.MODE": "prod",
        "unrelated.key": "ignored",
    }
    props.update(extra)
    return props


class TestTonyJob:
    def test_conf_file_contains_only_tony_keys(self, tmp_path):
        job = TonyJob(_props(), job_id="j1", working_dir=str(tmp_path))
        conf_file = job.write_conf()
        assert conf_file == str(tmp_path / "_tony-conf-j1" / "tony.xml")
        conf = TonyConfig.from_file(conf_file, load_defaults=False)
        assert conf.get("tony.worker.instances") == "2"
        assert conf.get("tony.application.framework") == "jax"
        assert "unrelated.key" not in conf
        assert "executes" not in conf

    def test_main_args_translation(self, tmp_path):
        job = TonyJob(_props(task_params="--epochs 3",
                             python_binary_path="python3",
                             python_venv="venv.zip"),
                      working_dir=str(tmp_path))
        args = job.main_args()
        assert args[0] == "submit"
        assert "--executes=python train.py" in args
        assert "--src_dir=src" in args
        assert "--task_params=--epochs 3" in args
        assert "--python_binary_path=python3" in args
        assert "--python_venv=venv.zip" in args
        # worker_env.* → repeated --shell_env k=v (reference:
        # TensorFlowJob.getMainArguments:101-105)
        envs = [a.split("=", 1)[1] for a in args
                if a.startswith("--shell_env=")]
        assert envs == ["DATA_DIR=/data", "MODE=prod"]

    def test_main_args_parse_through_cli(self, tmp_path):
        """The emitted args must survive the submission CLI's argparse —
        including values that start with a dash (--task_params=--verbose
        would be eaten as an option in two-token form)."""
        from tony_tpu.client.cli import build_parser
        job = TonyJob(_props(task_params="--verbose",
                             python_binary_path="python3.11"),
                      working_dir=str(tmp_path))
        parsed = build_parser().parse_args(job.main_args())
        assert parsed.executes == "python train.py"
        assert parsed.task_params == "--verbose"
        assert parsed.python_binary_path == "python3.11"
        assert parsed.shell_env == ["DATA_DIR=/data", "MODE=prod"]

    def test_missing_executes_raises(self, tmp_path):
        props = _props()
        del props["executes"]
        with pytest.raises(ValueError, match="executes"):
            TonyJob(props, working_dir=str(tmp_path)).main_args()

    def test_command_line_is_execable_argv(self, tmp_path):
        job = TonyJob(_props(), working_dir=str(tmp_path))
        argv = job.command_line()
        assert argv[0] == sys.executable
        assert argv[1:3] == ["-m", "tony_tpu.client.cli"]

    def test_properties_file_parsing(self, tmp_path):
        p = tmp_path / "job.properties"
        p.write_text("# a comment\n"
                     "executes=python t.py\n"
                     "tony.worker.instances = 3\n"
                     "\n"
                     "worker_env.X=1\n"
                     "malformed-line-no-equals\n")
        props = parse_properties(str(p))
        assert props == {"executes": "python t.py",
                         "tony.worker.instances": "3",
                         "worker_env.X": "1"}

    def test_end_to_end_submission(self, tmp_path):
        """The jobtype drives a real local submission to completion."""
        props = {
            "executes": "true",
            "tony.worker.instances": "1",
            "tony.staging.dir": str(tmp_path / "staging"),
            "tony.history.location": str(tmp_path / "hist"),
            "tony.application.timeout": "60000",
        }
        job = TonyJob(props, working_dir=str(tmp_path))
        assert job.run() == 0


class TestVersionInfo:
    def test_fields_resolved(self):
        from tony_tpu.utils.version import get_version_info
        info = get_version_info()
        assert set(info) == {"version", "revision", "branch", "user", "date"}
        assert info["version"] == "0.1.0"
        # Running inside the repo: revision resolves from git.
        assert len(info["revision"]) == 40

    def test_injected_into_conf(self):
        from tony_tpu.utils.version import inject_version_info
        conf = TonyConfig()
        inject_version_info(conf)
        assert conf.get("tony.version.version") == "0.1.0"
        assert conf.get("tony.version.revision") != "Unknown"
